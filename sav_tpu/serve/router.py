"""Wait-aware fleet router: admit, balance, drain, fail over.

One :class:`~sav_tpu.serve.engine.ServeEngine` is one process on one
chip group; the north star ("heavy traffic from millions of users")
needs horizontal capacity — N engine replicas behind a router that
spreads load by where it will actually finish soonest. This module is
that router, deliberately **stdlib-only** (no jax, no numpy — the
structural proof, like the batcher's, that routing cannot sync a device
value; the router typically runs in the pool's parent process, which
must never be hangable by backend import, the supervisor philosophy).

Routing policy — **projected dispatch wait**, not round robin: each
replica's live ``kind=serve`` heartbeat (sav_tpu/serve/telemetry.py)
carries its queue depth, in-flight batch count, and measured per-batch
step time; the router projects what a new request would wait at each
replica with the SAME arithmetic the PR-10 batcher uses for its
admission shed (:func:`projected_wait_s` — batches ahead x estimated
step), adds the requests it has itself routed there since the last
heartbeat (heartbeats are cadenced; the router's own outstanding count
fills the staleness gap), and picks the minimum. A fleet whose *best*
projected wait already blows the deadline sheds at admission
(:class:`~sav_tpu.serve.batcher.DeadlineInfeasibleError`) — the
batcher's "never serve a guaranteed miss" contract, lifted fleet-wide.

Replica lifecycle the router tracks (docs/serving.md "Fleet"):

- **active** — routable.
- **draining** — the leave-one-out straggler attribution
  (:func:`sav_tpu.obs.fleet._loo_scores`, the PR-7 machinery, here on
  windowed p99) flagged the replica: no NEW requests are routed to it,
  its in-flight work finishes normally, and it resumes the moment the
  attribution unflags it. The router never drains the last active
  replica — degraded capacity beats none.
- **down** — a transport failure (connection refused/reset: the
  process died mid-request) or heartbeat-silence suspicion
  (:func:`sav_tpu.obs.fleet.silence_suspects` — the same flag
  ``aggregate_serve``/``serve_status`` render) marks the replica dead.
  Requests in flight to it come back as transport errors and are
  REROUTED to a healthy replica while their deadline still stands —
  rerouted or honestly shed, never silently lost. Recovery is a fresh
  heartbeat newer than the down mark (the PR-9 supervisor restarts the
  process; its first beat folds it back in).

Distributed tracing (ISSUE 16, docs/serving.md "Distributed
tracing"): the router mints a globally unique trace id per admitted
request (``r<pid>-<seq>``) and stamps its own lifecycle with the same
stdlib :func:`~sav_tpu.serve.telemetry.stamp` machinery the replicas
use — ``submit -> admit -> route_selected -> connect -> sent -> reply
-> completed`` in the ROUTER's clock domain, one sub-span per
reroute/retry attempt, and honest terminal stamps for shed/failed.
The id rides the wire header (``meta["trace"]``); the replica's
``begin_trace`` adopts it, and the offline merge
(:func:`sav_tpu.obs.traceview.fleet_request_spans`) joins the two clock
domains into one contiguous router->replica->router chain per request.
Completed router traces land in a bounded
:class:`~sav_tpu.serve.telemetry.SpanRing` exported at close; live
per-stage windows feed ``kind=router`` heartbeats on the PR-7
substrate (``fleet/router.jsonl``).

savlint SAV118 (``router-hot-path-sync``) owns this module's hot
functions (``admit`` / ``route`` / ``note_result`` / ``_refresh_views``
/ ``drain`` / ``resume``), and SAV119 (``router-trace-hot-path-sync``)
owns the trace surface it grew (``_dispatch`` / ``_route_with_waits``
/ ``_observe_completion`` / ``router_beat``): a device sync anywhere
in the routing or tracing path would serialize every request in the
fleet behind one pipeline drain.
"""

from __future__ import annotations

import itertools
import json
import os
import queue as _queue_mod
import threading
import time
from typing import Any, Callable, Optional

from sav_tpu.obs.fleet import HeartbeatWriter, _loo_scores
from sav_tpu.serve.batcher import (
    DeadlineInfeasibleError,
    QueueFullError,
    ServeClosedError,
    ServeFuture,
)
from sav_tpu.serve.telemetry import (
    ROUTER_INTERVALS,
    RequestTrace,
    SlidingWindow,
    SpanRing,
    dominant_stage,
    intervals,
    stamp,
    write_request_trace,
)

ROUTER_SCHEMA = 1


def _round3(v: Optional[float]) -> Optional[float]:
    return round(v, 3) if isinstance(v, (int, float)) else None

#: Replica states (docs/serving.md "Fleet" state table).
ACTIVE = "active"
DRAINING = "draining"
DOWN = "down"


class ReplicaTransportError(RuntimeError):
    """The transport could not complete the exchange (connection
    refused/reset, torn reply): the replica process is gone or going.
    The router marks the replica down and REROUTES the request."""


class ReplicaShedError(QueueFullError):
    """The replica itself shed the request (its admission control
    rejected it). Retried elsewhere/later while the deadline stands."""


class RouterShedError(QueueFullError):
    """No replica could serve the request before its deadline — the
    router's honest shed (set on the future; never a silent drop)."""


def projected_wait_s(
    *,
    queued: int,
    inflight: int,
    fresh_outstanding: int,
    max_batch: int,
    est_step_s: float,
) -> float:
    """Projected dispatch wait at one replica, in the batcher's own
    arithmetic (sav_tpu/serve/batcher.py submit): the batches already
    drained-but-not-completed (``inflight``) plus the full batches the
    queue ahead would form — ``queued`` from the replica's last
    heartbeat plus ``fresh_outstanding``, the requests this router has
    sent since that heartbeat (cadenced beats are stale; the router's
    own ledger fills the gap) — each one estimated step. The ``+
    max_batch`` inside the ceiling counts the batch this request itself
    would ride, exactly like the batcher's ``(qsize + max_batch) //
    max_batch``."""
    max_batch = max(int(max_batch), 1)
    batches_ahead = max(int(inflight), 0) + (
        (max(int(queued), 0) + max(int(fresh_outstanding), 0) + max_batch)
        // max_batch
    )
    return batches_ahead * max(float(est_step_s), 0.0)


class _Replica:
    """Router-side live state for one replica (owner locks)."""

    __slots__ = (
        "rank", "state", "queued", "inflight", "est_step_s", "p99_ms",
        "last_beat_unix", "beats", "final", "pid", "sends", "routed",
        "completed", "failures", "down_since_unix", "down_reason",
        "drained_at_unix", "drain_auto", "dtype",
    )

    def __init__(self, rank: int):
        self.rank = rank
        self.state = ACTIVE
        self.queued = 0
        self.inflight = 0
        self.est_step_s: Optional[float] = None
        self.p99_ms: Optional[float] = None
        self.last_beat_unix: Optional[float] = None
        self.beats = 0
        self.final = False
        self.pid: Optional[int] = None
        # Weight-serving dtype stamp from the replica's heartbeats
        # (ISSUE 20): the shadow scorer keys its tolerance envelope on
        # the (primary, shadow) dtype pair.
        self.dtype: Optional[str] = None
        # In-flight sends: job id -> wall stamp (fresh_outstanding =
        # sends newer than the replica's last heartbeat).
        self.sends: dict = {}
        self.routed = 0
        self.completed = 0
        self.failures = 0
        self.down_since_unix: Optional[float] = None
        self.down_reason: Optional[str] = None
        self.drained_at_unix: Optional[float] = None
        self.drain_auto = False

    def fresh_outstanding(self) -> int:
        beat_t = self.last_beat_unix
        if beat_t is None:
            return len(self.sends)
        return sum(1 for t in self.sends.values() if t > beat_t)

    def view(self) -> dict:
        return {
            "rank": self.rank,
            "state": self.state,
            "queued": self.queued,
            "inflight": self.inflight,
            "outstanding": len(self.sends),
            "est_step_s": self.est_step_s,
            "p99_ms": self.p99_ms,
            "last_beat_unix": self.last_beat_unix,
            "beats": self.beats,
            "routed": self.routed,
            "completed": self.completed,
            "failures": self.failures,
            "down_reason": self.down_reason,
            "dtype": self.dtype,
        }


class _Job:
    __slots__ = (
        "jid", "payload", "meta", "deadline_t", "admit_t", "future",
        "trace", "attempts", "waits", "shadow",
    )

    def __init__(self, jid, payload, meta, deadline_t, admit_t, future):
        self.jid = jid
        self.payload = payload
        self.meta = meta
        self.deadline_t = deadline_t
        self.admit_t = admit_t
        self.future = future
        # Tracing: the per-request RequestTrace (router clock domain),
        # the per-attempt sub-span ledger, and the candidate projected
        # waits the first route decision saw (ms, keyed by rank).
        self.trace: Optional[RequestTrace] = None
        self.attempts: list = []
        self.waits: Optional[dict] = None
        # Shadow sampling mark (ISSUE 20): set at admit (deterministic
        # 1-in-N), mirrors the completed request to the shadow replica.
        self.shadow = False


_STOP = object()

# Dispatch workers poll their queue at this cadence so a torn-down
# router can never strand one (see Router._worker).
_WORKER_POLL_S = 1.0

#: Bound on queued shadow mirrors (ISSUE 20): a slow shadow replica
#: sheds its own sampled traffic (``shadow.shed``) instead of growing
#: an unbounded payload backlog in the router — shed-before-
#: primary-impact, the probe's contract on the router side.
SHADOW_QUEUE_DEPTH = 64

#: Wire timeout for one shadow mirror: generous (the shadow is off the
#: latency path), but bounded so a wedged shadow replica cannot pin the
#: shadow worker forever.
SHADOW_SEND_TIMEOUT_S = 10.0

#: Per-mirror request deadline (ms). The mirror is usually the ONLY
#: row in the otherwise-idle shadow replica's batcher, and inheriting a
#: live-traffic deadline would let the batcher hold it for seconds of
#: bucket-fill slack per sample — one mirror scored per drain instead
#: of dozens. A short deadline ships the batch-of-1 promptly; if the
#: shadow replica is genuinely busy the sample sheds (report-only),
#: never a live request.
SHADOW_MIRROR_DEADLINE_MS = 250.0


class Router:
    """Admission + load balancing over a serve replica fleet.

    Args:
      transport: the wire to the replicas —
        ``send(rank, payload, meta, timeout_s) -> dict`` (raising
        :class:`ReplicaTransportError` on a dead connection and
        :class:`ReplicaShedError` on a replica-side admission reject).
        :class:`sav_tpu.serve.fleet.TcpTransport` is the production
        implementation; tests inject fakes.
      views_fn: ``() -> {rank: view}`` — the per-replica live view
        (:func:`sav_tpu.serve.telemetry.router_views` reads it from the
        ``kind=serve`` heartbeat streams). Each view carries ``queued``
        / ``inflight`` / ``est_step_s`` / ``p99_ms`` /
        ``last_beat_unix`` / ``beats`` / ``final`` / ``suspect``.
      max_batch: the replicas' top bucket (the projection's batch unit).
      default_step_s: per-batch step estimate before the first heartbeat
        carries a measured one.
      default_deadline_s / max_inflight: admission knobs (the fleet
        twins of the batcher's ``default_deadline_s`` / ``max_queue``).
      refresh_secs: heartbeat-view refresh cadence (admission and the
        dispatch loop refresh at most this often).
      straggler_k / straggler_rel_floor / straggler_min_beats: the
        leave-one-out p99 drain gate (conservative by default — with a
        2-replica fleet the LOO baseline is a single value, so the
        relative floor alone separates "slower" from "straggling").
      ranks: the expected fleet roster — pre-seeds the routing table
        (active, no data) so replicas are routable from the first
        request, BEFORE their first heartbeat lands (a fresh fleet's
        beats are cadenced; waiting for them would funnel the whole
        warmup flood at whichever replica beat first). None = discover
        from heartbeats alone.
      workers: dispatch worker threads. ``0`` = synchronous mode —
        ``admit`` dispatches inline and blocks until the request
        completes or sheds (deterministic unit tests; single-threaded
        drivers).
      clock / wall_clock / sleep: injectable for fake-clock tests.
      log_dir: when set, ``close()`` writes the router summary to
        ``<log_dir>/fleet/router.json`` for ``serve_status``, exports
        the router span ring to
        ``<log_dir>/serve_traces/requests_router.trace.json.gz``, and
        (with ``heartbeat_secs > 0``) streams ``kind=router``
        heartbeats to ``<log_dir>/fleet/router.jsonl``.
      trace_depth: span-ring depth for completed/terminal request
        traces (the PR-11 bound — old spans roll off, admission never
        blocks on telemetry).
      heartbeat_secs: ``kind=router`` heartbeat cadence; ``0`` (the
        default) disables the heartbeat thread.
      window_s: sliding-window span for the live latency / per-stage
        attribution the heartbeats and mid-run ``summary()`` carry.
      perf: the overhead meter (``time.perf_counter``) — tracing cost
        is self-accounted exactly like the PR-11 engine telemetry and
        surfaced as ``router_overhead_ms`` per completed request.
      shadow_rank / shadow_frac: shadow agreement scoring (ISSUE 20,
        docs/quality.md): mirror a deterministic ``shadow_frac``
        sample of completed requests to replica ``shadow_rank``
        (excluded from normal routing) and score top-1 agreement +
        logit drift per (primary_dtype, shadow_dtype) pair.
        Report-only — scoring runs on a dedicated worker thread off
        the latency path and sheds before impacting live traffic.
    """

    _POLL_S = 0.02  # no-routable-replica retry cadence inside dispatch

    def __init__(
        self,
        transport,
        *,
        views_fn: Callable[[], dict],
        max_batch: int = 8,
        default_step_s: float = 0.05,
        default_deadline_s: float = 1.0,
        max_inflight: int = 256,
        refresh_secs: float = 0.5,
        suspect_factor: float = 3.0,
        straggler_k: float = 3.5,
        straggler_rel_floor: float = 1.0,
        straggler_min_beats: int = 3,
        ranks=None,
        workers: int = 8,
        clock: Callable[[], float] = time.monotonic,
        wall_clock: Callable[[], float] = time.time,
        sleep: Callable[[float], None] = time.sleep,
        log_dir: Optional[str] = None,
        trace_depth: int = 256,
        heartbeat_secs: float = 0.0,
        window_s: float = 30.0,
        perf: Callable[[], float] = time.perf_counter,
        shadow_rank: Optional[int] = None,
        shadow_frac: float = 0.05,
    ):
        if max_inflight < 1:
            raise ValueError(f"max_inflight must be >= 1, got {max_inflight}")
        if default_deadline_s <= 0:
            raise ValueError(
                f"default_deadline_s must be > 0, got {default_deadline_s}"
            )
        if shadow_rank is not None and not 0.0 < float(shadow_frac) <= 1.0:
            raise ValueError(
                f"shadow_frac must be in (0, 1], got {shadow_frac}"
            )
        self._transport = transport
        self._views_fn = views_fn
        self.max_batch = int(max_batch)
        self.default_step_s = float(default_step_s)
        self.default_deadline_s = float(default_deadline_s)
        self.max_inflight = int(max_inflight)
        self.refresh_secs = float(refresh_secs)
        self.suspect_factor = float(suspect_factor)
        self.straggler_k = float(straggler_k)
        self.straggler_rel_floor = float(straggler_rel_floor)
        self.straggler_min_beats = int(straggler_min_beats)
        self.log_dir = log_dir
        self._clock = clock
        self._wall = wall_clock
        self._sleep = sleep
        self._lock = threading.Lock()
        self._replicas: dict[int, _Replica] = {}
        self._closed = threading.Event()
        self._jid = 0
        self._inflight_total = 0
        self._last_refresh: Optional[float] = None
        self._t_start = clock()
        self._first_admit_t: Optional[float] = None
        self._last_complete_t: Optional[float] = None
        self._latencies_s: list = []
        self._completed = 0
        self._rejected = 0
        self._shed_admit = 0
        self._shed_deadline = 0
        self._rerouted = 0
        self._transport_failures = 0
        self._errors = 0
        self._down_flaps = 0
        # Tracing state (ISSUE 16): globally unique ids (r<pid>-<seq>),
        # a bounded span ring of terminal traces, live latency /
        # per-stage sliding windows, and the self-accounted overhead
        # meter behind router_overhead_ms.
        self._pid = os.getpid()
        self._trace_seq = itertools.count()
        self._perf = perf
        self.window_s = float(window_s)
        self._ring = SpanRing(depth=int(trace_depth))
        self._lat_window = SlidingWindow(self.window_s, clock=clock)
        self._stage_windows: dict[str, SlidingWindow] = {}
        self._overhead_s = 0.0
        self.heartbeat_secs = float(heartbeat_secs)
        self._hb_writer = None
        self._hb_thread = None
        self._roller = None
        self._last_roll = None
        if log_dir:
            self._hb_writer = HeartbeatWriter(
                log_dir, process_index=0, stream="router",
                clock=wall_clock,
            )
            if self.heartbeat_secs > 0:
                # The router owns the fleet's rollup ladder: one
                # single-writer Roller per run, ticked from the
                # heartbeat thread — never from request paths
                # (SAV125), never from replica processes (cursor is
                # single-writer).
                try:
                    from sav_tpu.obs.rollup import Roller

                    self._roller = Roller(log_dir)
                except Exception:
                    self._roller = None
        # Shadow agreement scoring (ISSUE 20, docs/quality.md): the
        # designated shadow rank is EXCLUDED from normal routing; a
        # deterministic 1-in-round(1/frac) sample of completed requests
        # is mirrored to it from a dedicated worker thread (report-only
        # — scoring never rides admit/route/_dispatch, SAV126), scored
        # per (primary_dtype, shadow_dtype) pair, and shed before it
        # could ever back-pressure live traffic (bounded queue).
        self.shadow_rank = int(shadow_rank) if shadow_rank is not None else None
        self.shadow_frac = float(shadow_frac)
        self._shadow_scorer = None
        self._shadow_queue: Any = None
        self._shadow_thread: Optional[threading.Thread] = None
        self._shadow_every = 0
        self._shadow_alerts = None
        if self.shadow_rank is not None:
            from sav_tpu.obs.quality import AgreementScorer

            self._shadow_scorer = AgreementScorer()
            self._shadow_every = max(1, round(1.0 / self.shadow_frac))
            self._shadow_queue = _queue_mod.Queue(maxsize=SHADOW_QUEUE_DEPTH)
            if self._hb_writer is not None:
                # Quality rules ONLY: the router beat carries w.p99_ms,
                # and arming the SLO/env rules here would double-fire
                # episodes the replicas already own.
                from sav_tpu.obs import alerts as alerts_mod

                self._shadow_alerts = alerts_mod.AlertEngine(
                    alerts_mod.quality_rules(),
                    log_dir=log_dir,
                    proc="router",
                    clock=wall_clock,
                )
        for rank in (ranks or ()):
            self._replicas[int(rank)] = _Replica(int(rank))
        self._refresh_views()  # seed the table before the first admit
        self._jobs: Any = _queue_mod.Queue()
        self._workers = []
        for i in range(int(workers)):
            t = threading.Thread(
                target=self._worker, name=f"router-dispatch-{i}", daemon=True
            )
            t.start()
            self._workers.append(t)
        if self._shadow_queue is not None:
            self._shadow_thread = threading.Thread(
                target=self._shadow_worker, name="router-shadow", daemon=True
            )
            self._shadow_thread.start()
        if self._hb_writer is not None and self.heartbeat_secs > 0:
            self._hb_thread = threading.Thread(
                target=self._hb_loop, name="router-heartbeat", daemon=True
            )
            self._hb_thread.start()

    # ----------------------------------------------------------- admission

    def admit(
        self,
        payload: Any,
        *,
        deadline_s: Optional[float] = None,
        meta: Optional[dict] = None,
    ) -> ServeFuture:
        """Admit one request into the fleet; returns its future.

        Sheds at admission (:class:`DeadlineInfeasibleError`) when even
        the BEST replica's projected dispatch wait blows the deadline —
        the batcher's guaranteed-miss contract, fleet-wide — and
        rejects (:class:`QueueFullError`) past ``max_inflight``. Both
        reject shapes subclass :class:`QueueFullError`, like the
        batcher's. Host bookkeeping only (savlint SAV118)."""
        if self._closed.is_set():
            raise ServeClosedError("router is closed")
        deadline_s = (
            float(deadline_s) if deadline_s is not None
            else self.default_deadline_s
        )
        if deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        t_entry = self._clock()  # the trace's "submit" instant
        self._maybe_refresh()
        # Capacity check, shed projection, and the inflight increment in
        # ONE critical section: a check in a separate lock acquisition
        # would let N concurrent admitters all pass at capacity-1 and
        # overshoot the bound by the caller thread count.
        with self._lock:
            if self._inflight_total >= self.max_inflight:
                self._rejected += 1
                raise QueueFullError(
                    f"router at capacity ({self.max_inflight} in flight); "
                    "shed load or raise max_inflight"
                )
            waits = [
                self._projected_wait(r)
                for r in self._replicas.values()
                if r.state == ACTIVE and r.rank != self.shadow_rank
            ]
            if waits and min(waits) > deadline_s:
                self._shed_admit += 1
                raise DeadlineInfeasibleError(
                    f"best projected dispatch wait {min(waits):.3f}s across "
                    f"{len(waits)} active replica(s) exceeds the "
                    f"{deadline_s:.3f}s deadline; shedding instead of "
                    "serving a guaranteed miss"
                )
            self._jid += 1
            now = self._clock()
            if self._first_admit_t is None:
                self._first_admit_t = now
            job = _Job(
                self._jid, payload, dict(meta or {}),
                now + deadline_s, now, ServeFuture(),
            )
            # Mint the fleet-global trace id and stamp submit/admit in
            # the router's clock domain; the id rides the wire header
            # (meta["trace"]) so the replica's begin_trace adopts it.
            t0 = self._perf()
            rid = f"r{self._pid}-{next(self._trace_seq)}"
            job.trace = RequestTrace(rid, deadline_s, t_entry)
            stamp(job.trace, "admit", now)
            job.meta["trace"] = rid
            if self._shadow_every and self._jid % self._shadow_every == 0:
                # Deterministic 1-in-N sampling (a counter, not a RNG —
                # reproducible benches): the PRIMARY exchange asks for
                # logits so the scorer can judge drift, not just top-1.
                # Integer bookkeeping only — the scoring itself happens
                # on the shadow worker thread (SAV118/SAV126).
                job.shadow = True
                job.meta["want_logits"] = True
            self._overhead_s += self._perf() - t0
            self._inflight_total += 1
        if self._workers:
            self._jobs.put(job)
            if self._closed.is_set():
                # close() can finish draining the queue and stopping
                # the workers between this thread's entry check and the
                # put above; the job would then sit in a queue nothing
                # will ever drain, stranding result() forever. Re-run
                # the fail pass (the batcher's PR-10 submit/close
                # TOCTOU fix, same shape) — any job still queued after
                # close must fail anyway.
                self._fail_queued_jobs()
        else:
            self._dispatch(job)  # synchronous mode: block until resolved
        return job.future

    def _projected_wait(self, replica: _Replica) -> float:
        est = replica.est_step_s
        if est is None:
            # No measured step yet (fresh replica / just restarted):
            # be OPTIMISTIC — assume the best measured step in the
            # fleet, so the unknown replica gets traffic and its
            # estimate gets measured. A pessimistic default would
            # repel traffic forever: no traffic, no measurement, no
            # recovery from the default (the fold-back deadlock).
            known = [
                r.est_step_s for r in self._replicas.values()
                if r.est_step_s is not None
            ]
            est = min(known) if known else self.default_step_s
        return projected_wait_s(
            queued=replica.queued,
            inflight=replica.inflight,
            fresh_outstanding=replica.fresh_outstanding(),
            max_batch=self.max_batch,
            est_step_s=est,
        )

    def route(self) -> Optional[int]:
        """The replica a new request should go to: minimum projected
        dispatch wait among ACTIVE replicas (ties break to the lowest
        rank — deterministic), or None when nothing is routable (all
        down/draining — the dispatch loop polls for recovery until the
        deadline). Host arithmetic only (SAV118)."""
        rank, _ = self._route_with_waits()
        return rank

    def _route_with_waits(self) -> tuple:
        """:meth:`route` plus the full candidate wait table the decision
        saw — ``(best_rank, {rank: projected_wait_s})`` — so the trace's
        ``route_selected`` span can carry WHY this replica won (the
        Tail-at-Scale attribution input). Same lock discipline and host
        arithmetic as route(); savlint SAV119 owns this body."""
        with self._lock:
            best = None
            best_wait = None
            waits: dict = {}
            for rank in sorted(self._replicas):
                replica = self._replicas[rank]
                if replica.state != ACTIVE or rank == self.shadow_rank:
                    # The shadow replica only sees mirrored traffic —
                    # routing live load at it would make its agreement
                    # window judge a double-loaded replica.
                    continue
                wait = self._projected_wait(replica)
                waits[rank] = wait
                if best_wait is None or wait < best_wait:
                    best, best_wait = rank, wait
            return best, waits

    # ------------------------------------------------------------ dispatch

    def _worker(self) -> None:
        # Bounded get (SAV123): close() posts one _STOP per worker, but a
        # close() that dies mid-teardown must not strand a worker blocked
        # forever — each timeout re-checks the closed flag and exits.
        while True:
            try:
                job = self._jobs.get(timeout=_WORKER_POLL_S)
            except _queue_mod.Empty:
                if self._closed.is_set():
                    return
                continue
            if job is _STOP:
                return
            self._dispatch(job)

    def _dispatch(self, job: _Job) -> None:
        """Route one admitted request until it completes, sheds, or the
        router closes: send to the best replica; a transport failure
        marks the replica down and REROUTES while the deadline stands
        (never silently lost); a replica-side shed retries as capacity
        frees; past the deadline the future fails with
        :class:`RouterShedError` — the honest shed. Stamps the trace
        lifecycle (route_selected/connect/sent/reply/completed plus one
        sub-span per attempt) along the way — host stamps only, savlint
        SAV119 owns this body."""
        trace = job.trace
        try:
            while True:
                if self._closed.is_set():
                    job.future.set_exception(
                        ServeClosedError("router closed with this request "
                                         "in flight")
                    )
                    self._observe_completion(job, rank=None, outcome="failed")
                    return
                # Keep the view fresh on the dispatch path too: under a
                # flood, admissions stop long before dispatch does, and
                # a router working a whole drain on its admission-time
                # view would never see queues build or replicas die.
                self._maybe_refresh()
                remaining = job.deadline_t - self._clock()
                if remaining <= 0:
                    with self._lock:
                        self._shed_deadline += 1
                    job.future.set_exception(RouterShedError(
                        "no replica could serve this request before its "
                        "deadline (rerouted/retried until the budget ran "
                        "out) — shed, not silently dropped"
                    ))
                    self._observe_completion(job, rank=None, outcome="shed")
                    return
                rank, waits = self._route_with_waits()
                if rank is None:
                    self._sleep(min(self._POLL_S, remaining))
                    self._maybe_refresh()
                    continue
                t_selected = self._clock()
                # First stamp wins in intervals() — a reroute's second
                # route_selected leaves the original span intact; the
                # per-attempt ledger carries the retries.
                stamp(trace, "route_selected", t_selected)
                if job.waits is None:
                    job.waits = {
                        int(r): round(w * 1e3, 3) for r, w in waits.items()
                    }
                attempt = {"rank": int(rank), "t_start": t_selected}
                job.attempts.append(attempt)
                with self._lock:
                    replica = self._replicas.get(rank)
                    if replica is None:
                        continue
                    replica.routed += 1
                    replica.sends[job.jid] = self._wall()
                # Transport stamp seam: a stamp-aware transport (the
                # production TcpTransport) stamps connect/sent at the
                # real socket instants; a plain transport degrades to
                # stamping both at the pre-send instant so the chain
                # stays contiguous (transport_send collapses to ~0 and
                # the whole exchange lands in replica_wait).
                stamp_fn = None
                if trace is not None:
                    if getattr(self._transport, "supports_stamps", False):
                        clock = self._clock
                        stamp_fn = lambda name, _t=trace: (  # noqa: E731
                            stamp(_t, name, clock())
                        )
                    else:
                        t_pre = self._clock()
                        stamp(trace, "connect", t_pre)
                        stamp(trace, "sent", t_pre)
                try:
                    if stamp_fn is not None:
                        result = self._transport.send(
                            rank, job.payload, job.meta, remaining,
                            stamp_fn=stamp_fn,
                        )
                    else:
                        result = self._transport.send(
                            rank, job.payload, job.meta, remaining
                        )
                except ReplicaShedError:
                    attempt["t_end"] = self._clock()
                    attempt["outcome"] = "replica_shed"
                    self.note_result(rank, job.jid, ok=False)
                    # The replica's own admission control is loaded:
                    # back off briefly and retry (here or elsewhere)
                    # while the deadline stands.
                    self._sleep(min(self._POLL_S, remaining))
                    self._maybe_refresh()
                    continue
                except ReplicaTransportError as e:
                    attempt["t_end"] = self._clock()
                    attempt["outcome"] = "transport_error"
                    self.note_result(rank, job.jid, ok=False)
                    with self._lock:
                        self._transport_failures += 1
                        self._rerouted += 1
                    self._mark_down(rank, reason=f"transport: {e}")
                    continue
                except Exception as e:  # noqa: BLE001 — replica app error
                    attempt["t_end"] = self._clock()
                    attempt["outcome"] = "error"
                    self.note_result(rank, job.jid, ok=False)
                    with self._lock:
                        self._errors += 1
                    job.future.set_exception(e)
                    self._observe_completion(job, rank=rank, outcome="failed")
                    return
                self.note_result(rank, job.jid, ok=True)
                now = self._clock()
                stamp(trace, "reply", now)
                attempt["t_end"] = now
                attempt["outcome"] = "ok"
                with self._lock:
                    self._completed += 1
                    self._latencies_s.append(now - job.admit_t)
                    self._last_complete_t = now
                job.future.set_result(result)
                stamp(trace, "completed", self._clock())
                if job.shadow and rank != self.shadow_rank:
                    # Hand the completed pair to the shadow worker: one
                    # bounded put_nowait — never a send, never scoring —
                    # on the dispatch path (SAV126). Full queue = the
                    # shadow sheds its own sample.
                    self._shadow_enqueue(job, rank, result)
                self._observe_completion(
                    job, rank=rank, outcome="completed",
                    latency_s=now - job.admit_t,
                )
                return
        finally:
            with self._lock:
                self._inflight_total = max(self._inflight_total - 1, 0)

    # ------------------------------------------------------------- shadow

    def _shadow_enqueue(self, job: _Job, rank: int, result: Any) -> None:
        """Bounded handoff to the shadow worker (dispatch path: one
        put_nowait, no scoring — SAV126). A full queue sheds the sample
        (``shadow.shed``) instead of back-pressuring live traffic."""
        if self._shadow_queue is None:
            return
        try:
            self._shadow_queue.put_nowait((job.payload, dict(job.meta),
                                           rank, result))
        except _queue_mod.Full:
            self._shadow_scorer.record_shed()

    def _shadow_worker(self) -> None:
        """Drain mirrored requests and score them — the ONE thread that
        talks to the shadow replica. Same bounded-poll shutdown shape
        as the dispatch workers (SAV123)."""
        while True:
            try:
                item = self._shadow_queue.get(timeout=_WORKER_POLL_S)
            except _queue_mod.Empty:
                if self._closed.is_set():
                    return
                continue
            if item is _STOP:
                return
            try:
                self._score_one(*item)
            except Exception:  # noqa: BLE001 — report-only by contract
                self._shadow_scorer.record_shed()

    def _score_one(self, payload, meta: dict, primary_rank: int,
                   primary_result: Any) -> None:
        """Mirror one sampled request to the shadow replica and fold
        the agreement verdict (shadow worker thread only)."""
        meta = dict(meta)
        meta["want_logits"] = True
        # The mirror must NOT adopt the primary's trace id: the shadow
        # exchange is observability traffic, and joining it to the live
        # request's span chain would double-count the request in the
        # fleet trace merge.
        meta.pop("trace", None)
        # Nor the live deadline: the mirror rides an idle batcher, and
        # a long deadline becomes pure bucket-fill slack per sample.
        meta["deadline_ms"] = SHADOW_MIRROR_DEADLINE_MS
        try:
            shadow_result = self._transport.send(
                self.shadow_rank, payload, meta, SHADOW_SEND_TIMEOUT_S
            )
        except Exception:  # noqa: BLE001 — shed, never propagate
            self._shadow_scorer.record_shed()
            return
        with self._lock:
            primary = self._replicas.get(primary_rank)
            shadow = self._replicas.get(self.shadow_rank)
            primary_dtype = primary.dtype if primary is not None else None
            shadow_dtype = shadow.dtype if shadow is not None else None
        if primary_dtype is None or shadow_dtype is None:
            # Early mirrors can outrun the first dtype-carrying
            # heartbeat view, and an unknown pair would be judged
            # against the tight same-dtype envelope — a false breach
            # on an int8 arm's first samples. Refresh once (worker
            # thread, off the hot path) before falling back to "?".
            self._refresh_views()
            with self._lock:
                primary = self._replicas.get(primary_rank)
                shadow = self._replicas.get(self.shadow_rank)
                if primary is not None and primary.dtype:
                    primary_dtype = primary.dtype
                if shadow is not None and shadow.dtype:
                    shadow_dtype = shadow.dtype
        p_res = primary_result if isinstance(primary_result, dict) else {}
        s_res = shadow_result if isinstance(shadow_result, dict) else {}
        self._shadow_scorer.score_shadow(
            primary_dtype or "?",
            shadow_dtype or "?",
            p_res.get("pred", -1),
            s_res.get("pred", -1),
            primary_logits=p_res.get("logits"),
            shadow_logits=s_res.get("logits"),
        )

    def _shadow_snapshot(self) -> Optional[dict]:
        if self._shadow_scorer is None:
            return None
        out = self._shadow_scorer.snapshot()
        out["rank"] = self.shadow_rank
        out["frac"] = self.shadow_frac
        with self._lock:
            primary_dtypes = sorted({
                r.dtype for rank, r in self._replicas.items()
                if r.dtype and rank != self.shadow_rank
            })
            shadow = self._replicas.get(self.shadow_rank)
            if shadow is not None and shadow.dtype:
                out["dtype"] = shadow.dtype
        if primary_dtypes:
            out["primary_dtypes"] = primary_dtypes
        return out

    def _quality_tick(self) -> None:
        """Evaluate the quality rules against the live shadow snapshot
        — heartbeat-thread cadence only, the SAV125/SAV126 sanctioned
        home for alert evaluation."""
        if self._shadow_alerts is None:
            return
        try:
            snapshot = self._shadow_scorer.snapshot()
            self._shadow_alerts.observe(
                {"shadow": snapshot}, now=self._wall()
            )
        except Exception:
            pass  # a broken rule must not stop heartbeating

    def note_result(self, rank: int, jid: int, *, ok: bool) -> None:
        """Completion bookkeeping for one send (host counters only,
        SAV118): the projection stops counting it as outstanding."""
        with self._lock:
            replica = self._replicas.get(rank)
            if replica is None:
                return
            replica.sends.pop(jid, None)
            if ok:
                replica.completed += 1
            else:
                replica.failures += 1

    # ------------------------------------------------------------- tracing

    def _observe_completion(
        self,
        job: _Job,
        *,
        rank: Optional[int],
        outcome: str,
        latency_s: Optional[float] = None,
    ) -> None:
        """Fold one TERMINAL request (completed/shed/failed) into the
        span ring and the live windows. Self-accounted against the
        overhead meter (router_overhead_ms) and host-only by contract —
        savlint SAV119 owns this body; it runs once per request on the
        dispatch path."""
        trace = job.trace
        if trace is None:
            return
        t0 = self._perf()
        now = self._clock()
        if outcome != "completed":
            # Honest terminal stamp: shed/failed traces end with their
            # real outcome, never a fake "completed".
            stamp(trace, outcome if outcome == "shed" else "failed", now)
        if latency_s is None:
            latency_s = now - job.admit_t
        overrun_s = latency_s - trace.deadline_s
        stages_s = intervals(trace.stamps, ROUTER_INTERVALS)
        record = {
            "rid": trace.rid,
            "deadline_ms": trace.deadline_s * 1e3,
            "latency_ms": latency_s * 1e3,
            "overrun_ms": overrun_s * 1e3,
            "hit": outcome == "completed" and overrun_s <= 0.0,
            "rank": rank,
            "outcome": outcome,
            "attempts": [
                {
                    "rank": a.get("rank"),
                    "outcome": a.get("outcome"),
                    "ms": (
                        round((a["t_end"] - a["t_start"]) * 1e3, 3)
                        if "t_end" in a else None
                    ),
                }
                for a in job.attempts
            ],
            "candidate_waits_ms": job.waits,
            "stamps": trace.stamps,
            "stages_ms": {k: v * 1e3 for k, v in stages_s.items()},
            "dominant_stage": dominant_stage(stages_s),
        }
        with self._lock:
            self._ring.append(record)
            if outcome == "completed":
                self._lat_window.observe(latency_s * 1e3, now=now)
                for name, dur_s in stages_s.items():
                    w = self._stage_windows.get(name)
                    if w is None:
                        w = self._stage_windows[name] = SlidingWindow(
                            self.window_s, clock=self._clock
                        )
                    w.observe(dur_s * 1e3, now=now)
            self._overhead_s += self._perf() - t0

    def _window_snapshot(self, now: Optional[float] = None) -> dict:
        """The live windowed view (owner must hold the lock): latency
        percentiles, throughput over the window, and per-stage latency
        SHARES — where the window's wall time went, the Tail-at-Scale
        attribution the heartbeats carry."""
        if now is None:
            now = self._clock()
        n = self._lat_window.count(now=now)
        total_ms = self._lat_window.total(now=now)
        stage_shares = {}
        if total_ms > 0:
            for name, w in sorted(self._stage_windows.items()):
                stage_ms = w.total(now=now)
                if stage_ms > 0:
                    stage_shares[name] = round(stage_ms / total_ms, 4)
        # Effective span: a run younger than the window must divide by
        # the time actually served, not the full window — otherwise a
        # 2-second flood reads as window_s worth of "throughput" and
        # mid-run disagrees with the close-time summary (the ISSUE-16
        # bugfix this snapshot exists for).
        eff = self.window_s
        if self._first_admit_t is not None:
            eff = min(self.window_s, max(now - self._first_admit_t, 1e-9))
        return {
            "window_s": self.window_s,
            "requests": n,
            "p50_ms": _round3(self._lat_window.percentile(50.0, now=now)),
            "p95_ms": _round3(self._lat_window.percentile(95.0, now=now)),
            "p99_ms": _round3(self._lat_window.percentile(99.0, now=now)),
            "throughput_rps": round(n / eff, 2) if n else 0.0,
            "stage_shares": stage_shares,
        }

    def live(self) -> dict:
        """The mid-run router view — counters + the windowed snapshot —
        the SAME numbers ``summary()`` reports at close (the ISSUE-16
        bugfix: serve_status mid-run and post-run must agree)."""
        with self._lock:
            now = self._clock()
            view_age = (
                now - self._last_refresh
                if self._last_refresh is not None else None
            )
            span = None
            if (
                self._first_admit_t is not None
                and self._last_complete_t is not None
            ):
                span = max(self._last_complete_t - self._first_admit_t, 1e-9)
            out = {
                "completed": self._completed,
                "throughput_rps": (
                    round(self._completed / span, 2) if span else None
                ),
                "rejected": self._rejected,
                "shed": self._shed_admit + self._shed_deadline,
                "rerouted": self._rerouted,
                "transport_failures": self._transport_failures,
                "errors": self._errors,
                "down_flaps": self._down_flaps,
                "inflight": self._inflight_total,
                "view_age_s": _round3(view_age),
                "router_overhead_ms": self._overhead_ms_locked(),
                "w": self._window_snapshot(now),
            }
        # Shadow agreement (ISSUE 20) rides every kind=router beat —
        # folded OUTSIDE the router lock (the scorer has its own).
        shadow = self._shadow_snapshot()
        if shadow is not None:
            out["shadow"] = shadow
        return out

    def _overhead_ms_locked(self) -> float:
        return round(
            self._overhead_s / max(self._completed, 1) * 1e3, 4
        )

    def router_beat(self) -> bool:
        """Append one ``kind=router`` heartbeat to ``fleet/router.jsonl``
        (the PR-7 substrate; bounded-lock, drop-never-block). The router
        is a first-class fleet citizen: serve_status/fleet_status render
        this stream next to the replicas'. SAV119 owns this body."""
        if self._hb_writer is None:
            return False
        return self._hb_writer.serve_beat(self.live(), kind="router")

    def _hb_loop(self) -> None:
        while not self._closed.wait(self.heartbeat_secs):
            self.router_beat()
            self._quality_tick()
            self._roll_tick()

    def _roll_tick(self, min_interval_s: float = 2.0) -> None:
        """Advance the fleet rollup ladder by the bytes appended since
        the last tick. Cadenced work, deliberately outside
        ``router_beat`` (SAV119 scope) and every request path
        (SAV125): O(new bytes) per tick, and a failed roll must never
        take the heartbeat with it. Ticks are rate-limited below the
        heartbeat cadence (the finest bucket is 10s — sub-second rolls
        only steal GIL slices from request threads); close() passes 0
        so the final fold always runs."""
        if self._roller is None:
            return
        now = self._clock()
        if (
            self._last_roll is not None
            and now - self._last_roll < min_interval_s
        ):
            return
        self._last_roll = now
        try:
            self._roller.roll_once()
        except Exception:
            pass

    # ----------------------------------------------------- replica states

    def _mark_down(self, rank: int, *, reason: str) -> None:
        with self._lock:
            replica = self._replicas.get(rank)
            if replica is None or replica.state == DOWN:
                return
            replica.state = DOWN
            replica.down_since_unix = self._wall()
            replica.down_reason = reason
            self._down_flaps += 1

    def drain(
        self, rank: int, *, reason: str = "manual", auto: bool = False
    ) -> bool:
        """Stop routing NEW requests to a replica; its in-flight work
        finishes normally (the futures resolve as results arrive). The
        straggler attribution calls this automatically (``auto`` — and
        only auto drains auto-RESUME when the attribution unflags; a
        manual drain stays until :meth:`resume`). Refuses to drain the
        last active replica. Host-only (SAV118)."""
        with self._lock:
            replica = self._replicas.get(rank)
            if replica is None or replica.state != ACTIVE:
                return False
            active = sum(
                1 for r in self._replicas.values() if r.state == ACTIVE
            )
            if active <= 1:
                return False  # degraded capacity beats none
            replica.state = DRAINING
            replica.drained_at_unix = self._wall()
            replica.down_reason = reason
            replica.drain_auto = bool(auto)
            return True

    def resume(self, rank: int) -> bool:
        """Fold a draining/down replica back into rotation (the
        recovery path calls this when a fresh heartbeat arrives)."""
        with self._lock:
            replica = self._replicas.get(rank)
            if replica is None or replica.state == ACTIVE:
                return False
            replica.state = ACTIVE
            replica.down_since_unix = None
            replica.down_reason = None
            replica.drained_at_unix = None
            replica.drain_auto = False
            return True

    # -------------------------------------------------------- view refresh

    def refresh(self) -> None:
        """Force a heartbeat-view refresh NOW (drivers polling for a
        replica's recovery — e.g. the chaos arm's fold-back probe —
        should not wait out the cadence)."""
        self._refresh_views()

    def _maybe_refresh(self) -> None:
        # Check-and-claim under the lock (SAV121): two dispatch workers
        # racing the lock-free check both used to decide "stale" and
        # refresh back-to-back — the claim makes one refresh per cadence.
        now = self._clock()
        with self._lock:
            if (
                self._last_refresh is not None
                and now - self._last_refresh < self.refresh_secs
            ):
                return
            self._last_refresh = now
        self._refresh_views()

    def _refresh_views(self) -> None:
        """Fold the live heartbeat views into the routing table: update
        each replica's queue/step estimates, mark heartbeat-silent
        replicas down (the silence_suspects flag), recover replicas
        whose beats resumed, and run the leave-one-out straggler gate
        on windowed p99 (drain flagged, resume unflagged). Host-only by
        contract — savlint SAV118 owns this body; every value read here
        is a parsed JSON line."""
        with self._lock:
            self._last_refresh = self._clock()
        try:
            views = self._views_fn() or {}
        except Exception:  # noqa: BLE001 — a torn read must not stop routing
            return
        with self._lock:
            for rank, view in views.items():
                rank = int(rank)
                replica = self._replicas.get(rank)
                if replica is None:
                    replica = self._replicas[rank] = _Replica(rank)
                queued = view.get("queued")
                inflight = view.get("inflight")
                replica.queued = int(queued) if queued is not None else 0
                replica.inflight = (
                    int(inflight) if inflight is not None else 0
                )
                est = view.get("est_step_s")
                if isinstance(est, (int, float)) and est > 0:
                    replica.est_step_s = float(est)
                p99 = view.get("p99_ms")
                replica.p99_ms = (
                    float(p99) if isinstance(p99, (int, float)) else None
                )
                beat_t = view.get("last_beat_unix")
                if isinstance(beat_t, (int, float)):
                    replica.last_beat_unix = float(beat_t)
                replica.beats = int(view.get("beats") or 0)
                replica.final = bool(view.get("final"))
                dtype = view.get("dtype")
                if dtype:
                    replica.dtype = str(dtype)
                pid = view.get("pid")
                if pid is not None:
                    if replica.pid is not None and replica.pid != pid:
                        # A new process took this rank (supervisor
                        # restart): the old outstanding ledger is dead
                        # weight against the fresh replica's projection.
                        replica.sends.clear()
                    replica.pid = pid
                # Dead suspicion / recovery. An orderly final record is
                # a close, not a death — down, but not suspicion-tagged.
                if view.get("suspect") or replica.final:
                    if replica.state != DOWN:
                        replica.state = DOWN
                        replica.down_since_unix = self._wall()
                        replica.down_reason = (
                            "final record" if replica.final
                            else "heartbeat-silent"
                        )
                        self._down_flaps += 1
                elif (
                    replica.state == DOWN
                    and replica.last_beat_unix is not None
                    and (
                        replica.down_since_unix is None
                        or replica.last_beat_unix > replica.down_since_unix
                    )
                ):
                    # Fresh beat after the down mark: the supervisor
                    # restarted it (or the silence healed) — fold it
                    # back in.
                    replica.state = ACTIVE
                    replica.down_since_unix = None
                    replica.down_reason = None
            # Straggler gate: LOO median+MAD on windowed p99 across the
            # replicas that have one (the sentinel machinery, PR-7's
            # fleet application — one robust-stats implementation).
            p99s = {
                rank: r.p99_ms
                for rank, r in self._replicas.items()
                if r.p99_ms is not None
                and r.beats >= self.straggler_min_beats
                and r.state in (ACTIVE, DRAINING)
            }
            flagged = set()
            if len(p99s) >= 2:
                scores = _loo_scores(
                    p99s, k=self.straggler_k,
                    rel_floor=self.straggler_rel_floor,
                )
                flagged = {
                    rank for rank, s in scores.items() if s["flagged"]
                }
        for rank in sorted(flagged):
            self.drain(rank, reason="straggler (LOO p99)", auto=True)
        with self._lock:
            unflag = [
                rank for rank, r in self._replicas.items()
                if r.state == DRAINING and r.drain_auto
                and rank not in flagged
            ]
        for rank in unflag:
            self.resume(rank)

    # ----------------------------------------------------------- lifecycle

    def close(self) -> None:
        """Stop admission, fail requests still queued for dispatch
        (:class:`ServeClosedError`), and join the workers. Requests a
        worker already sent complete normally. Idempotent."""
        if self._closed.is_set():
            return
        self._closed.set()
        # Fail everything still queued (workers check closed before
        # sending; the sentinel wakes them for shutdown).
        self._fail_queued_jobs()
        for _ in self._workers:
            self._jobs.put(_STOP)
        for t in self._workers:
            t.join(timeout=5.0)
        if self._shadow_thread is not None:
            # After the dispatch workers: nothing can enqueue mirrors
            # any more, so one _STOP drains whatever was sampled and the
            # final beat below carries the complete agreement picture.
            self._shadow_queue.put(_STOP)
            self._shadow_thread.join(timeout=SHADOW_SEND_TIMEOUT_S + 5.0)
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5.0)
        if self._hb_writer is not None:
            # One last beat with the final counters, then the stream's
            # orderly final record.
            self._hb_writer.serve_beat(self.live(), kind="router")
            self._hb_writer.close()
        if self._shadow_alerts is not None:
            # Judge the final snapshot, then resolve whatever is still
            # firing — exactly one resolved event per open episode (the
            # monotonic breach counter + this finalize is what makes a
            # planted fault exactly-once).
            self._quality_tick()
            try:
                self._shadow_alerts.finalize(self._wall())
            except Exception:
                pass
        # Fold the final beats into the rollup ladder so post-run
        # readers (console, headroom fold) see the whole run.
        self._roll_tick(min_interval_s=0.0)
        if self.log_dir:
            with self._lock:
                records = self._ring.records()
            if records:
                write_request_trace(
                    os.path.join(
                        self.log_dir, "serve_traces",
                        "requests_router.trace.json.gz",
                    ),
                    records,
                    ROUTER_INTERVALS,
                    process_name="Fleet Router",
                    extra_args=("rank", "outcome"),
                )
            self.write_summary()

    def _fail_queued_jobs(self) -> None:
        """Fail every queued job's future (close()'s pass; admit()
        re-runs it when its enqueue raced close). Worker shutdown
        sentinels drained in passing are re-enqueued — admit's re-run
        can execute after close() armed them, and swallowing one would
        leave a worker blocked forever on the queue."""
        stops = 0
        while True:
            try:
                job = self._jobs.get_nowait()
            except _queue_mod.Empty:
                break
            if job is _STOP:
                stops += 1
                continue
            job.future.set_exception(
                ServeClosedError("router closed before this request shipped")
            )
            with self._lock:
                self._inflight_total = max(self._inflight_total - 1, 0)
        for _ in range(stops):
            self._jobs.put(_STOP)

    @property
    def closed(self) -> bool:
        return self._closed.is_set()

    # ------------------------------------------------------------- reading

    def stats(self) -> dict:
        with self._lock:
            return {
                "completed": self._completed,
                "rejected": self._rejected,
                "shed_admit": self._shed_admit,
                "shed_deadline": self._shed_deadline,
                "rerouted": self._rerouted,
                "transport_failures": self._transport_failures,
                "errors": self._errors,
                "down_flaps": self._down_flaps,
                "router_overhead_ms": self._overhead_ms_locked(),
                "inflight": self._inflight_total,
                "replicas": {
                    str(rank): r.view()
                    for rank, r in sorted(self._replicas.items())
                },
            }

    def summary(self) -> dict:
        """The fleet-level serving headline: router-observed end-to-end
        latency percentiles (admit -> result), throughput over the
        serving span, and the shed/reroute accounting the chaos proof
        audits (completed + shed == admitted, nothing silently lost)."""
        from sav_tpu.serve.latency import percentile

        with self._lock:
            lat = sorted(self._latencies_s)
            span = None
            if (
                self._first_admit_t is not None
                and self._last_complete_t is not None
            ):
                span = max(self._last_complete_t - self._first_admit_t, 1e-9)
            shed = self._shed_admit + self._shed_deadline
            out = {
                "schema": ROUTER_SCHEMA,
                "completed": self._completed,
                "rejected": self._rejected,
                "shed": shed,
                "shed_admit": self._shed_admit,
                "shed_deadline": self._shed_deadline,
                "rerouted": self._rerouted,
                "transport_failures": self._transport_failures,
                "errors": self._errors,
                "down_flaps": self._down_flaps,
                "router_overhead_ms": self._overhead_ms_locked(),
                "traces": {
                    "ring": len(self._ring),
                    "appended": self._ring.appended,
                },
                "window": self._window_snapshot(),
                "latency_ms": {
                    "p50": round(percentile(lat, 50.0) * 1e3, 3) if lat else None,
                    "p95": round(percentile(lat, 95.0) * 1e3, 3) if lat else None,
                    "p99": round(percentile(lat, 99.0) * 1e3, 3) if lat else None,
                },
                "throughput_rps": (
                    round(self._completed / span, 2) if span else None
                ),
                "replicas": {
                    str(rank): r.view()
                    for rank, r in sorted(self._replicas.items())
                },
            }
        shadow = self._shadow_snapshot()
        if shadow is not None:
            out["shadow"] = shadow
        return out

    def write_summary(self) -> Optional[str]:
        """Persist the router summary to ``<log_dir>/fleet/router.json``
        (atomic; telemetry never raises) — ``serve_status`` renders it
        next to the per-replica heartbeat views."""
        if not self.log_dir:
            return None
        path = os.path.join(self.log_dir, "fleet", "router.json")
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(self.summary(), f, indent=2, default=str)
            os.replace(tmp, path)
            return path
        except OSError:
            return None


def read_router_summary(log_dir: str) -> Optional[dict]:
    """The persisted router summary (``fleet/router.json``), or None —
    the offline readers' (serve_status) side of :meth:`write_summary`."""
    try:
        with open(os.path.join(log_dir, "fleet", "router.json")) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    return doc if isinstance(doc, dict) else None
