"""Serving package: AOT-compiled, dynamically batched inference.

Re-exports are lazy (PEP 562 via :mod:`sav_tpu._lazy`, like the other
subpackages): :mod:`sav_tpu.serve.bucketing`, ``batcher`` and
``latency`` are stdlib-only — the batching policy and its tests run
without jax — while :mod:`sav_tpu.serve.engine` pulls in the model zoo
and a backend on first use. docs/serving.md is the subsystem guide.
"""

from __future__ import annotations

from sav_tpu._lazy import install_lazy_exports

_EXPORTS = {
    "BucketLadder": "sav_tpu.serve.bucketing",
    "default_ladder": "sav_tpu.serve.bucketing",
    "padding_waste": "sav_tpu.serve.bucketing",
    "DeadlineInfeasibleError": "sav_tpu.serve.batcher",
    "DynamicBatcher": "sav_tpu.serve.batcher",
    "FormedBatch": "sav_tpu.serve.batcher",
    "QueueFullError": "sav_tpu.serve.batcher",
    "ServeClosedError": "sav_tpu.serve.batcher",
    "ServeFuture": "sav_tpu.serve.batcher",
    "ServeRequest": "sav_tpu.serve.batcher",
    "LatencyLedger": "sav_tpu.serve.latency",
    "percentile": "sav_tpu.serve.latency",
    "ServeConfig": "sav_tpu.serve.engine",
    "ServeEngine": "sav_tpu.serve.engine",
    "build_infer_fn": "sav_tpu.serve.engine",
    "preprocess_request": "sav_tpu.serve.preprocess",
    "resize_bicubic_u8": "sav_tpu.serve.preprocess",
    "center_crop_window": "sav_tpu.serve.preprocess",
    # Telemetry (stdlib-only like the batcher: spans, windows, SLO,
    # serve heartbeats + their offline aggregation — docs/serving.md).
    "LiveWindow": "sav_tpu.serve.telemetry",
    "RequestTrace": "sav_tpu.serve.telemetry",
    "SLOTracker": "sav_tpu.serve.telemetry",
    "ServeTelemetry": "sav_tpu.serve.telemetry",
    "SlidingWindow": "sav_tpu.serve.telemetry",
    "SpanRing": "sav_tpu.serve.telemetry",
    "aggregate_serve": "sav_tpu.serve.telemetry",
    "export_chrome_trace": "sav_tpu.serve.telemetry",
    "router_views": "sav_tpu.serve.telemetry",
    "stamp": "sav_tpu.serve.telemetry",
    # Fleet (stdlib-only like the batcher: the pool's parent and the
    # router must never be hangable by backend import — docs/serving.md
    # "Fleet").
    "ReplicaPool": "sav_tpu.serve.fleet",
    "TcpTransport": "sav_tpu.serve.fleet",
    "read_endpoints": "sav_tpu.serve.fleet",
    "ReplicaShedError": "sav_tpu.serve.router",
    "ReplicaTransportError": "sav_tpu.serve.router",
    "Router": "sav_tpu.serve.router",
    "RouterShedError": "sav_tpu.serve.router",
    "projected_wait_s": "sav_tpu.serve.router",
}

__all__ = list(_EXPORTS)

__getattr__, __dir__ = install_lazy_exports(
    globals(),
    _EXPORTS,
    {"batcher", "bucketing", "engine", "fleet", "latency", "preprocess",
     "router", "telemetry"},
)
