"""Request-shaped inference preprocessing — uint8 end to end, no TF.

The training loader's eval path (``sav_tpu/data/pipeline.py``
``crop_resize``: aspect-preserving center crop padded by 32px, bicubic
resize, cast back to uint8) reimplemented on numpy for single requests:
a serving host must not drag TensorFlow (or a jit trace per odd input
size) into the request path. The wire format stays **uint8** the whole
way — the engine's compiled program normalizes on device with
:func:`sav_tpu.ops.preprocess.normalize_images`, exactly like training's
``device_preprocess`` path — so one request ships H*W*3 bytes, not 4x
that in f32.

Parity contract (tests/test_serve.py): on the same decoded image this
module's crop window is bit-identical to the TF path's integer
arithmetic, and the bicubic resample matches ``tf.image.resize(...,
BICUBIC)`` within one uint8 level (both use the Keys a=-0.5 kernel with
half-pixel centers; the residual is float-order noise at the truncating
uint8 cast).
"""

from __future__ import annotations

import numpy as np

CROP_PADDING = 32  # the eval path's aspect-preserving crop margin


def center_crop_window(height: int, width: int, image_size: int) -> tuple:
    """(y, x, crop) of the eval center-crop — the TF path's exact
    integer arithmetic (pipeline.py ``_center_crop_window``)."""
    ratio = image_size / (image_size + CROP_PADDING)
    crop = int(ratio * min(height, width))
    y = (height - crop + 1) // 2
    x = (width - crop + 1) // 2
    return y, x, crop


def _cubic_weights(in_size: int, out_size: int) -> tuple:
    """4-tap Keys cubic (a=-0.5) sample weights with half-pixel centers.

    Returns ``(indices [out, 4] int, weights [out, 4] f64)``. Boundary
    handling matches TF's keys-cubic kernel: an out-of-range tap's
    weight is zeroed and the remaining weights renormalized to sum 1
    (NOT accumulated onto the clamped edge pixel — that variant is ~7
    uint8 levels off at the borders on noise images).
    """
    a = -0.5
    scale = in_size / out_size
    out = np.arange(out_size, dtype=np.float64)
    in_coord = (out + 0.5) * scale - 0.5
    base = np.floor(in_coord).astype(np.int64)
    frac = in_coord - base
    # Tap offsets -1..2 around the base pixel.
    offsets = np.arange(-1, 3, dtype=np.int64)
    indices = base[:, None] + offsets[None, :]
    x = np.abs(frac[:, None] - offsets[None, :])
    weights = np.where(
        x <= 1.0,
        (a + 2.0) * x**3 - (a + 3.0) * x**2 + 1.0,
        np.where(
            x < 2.0,
            a * x**3 - 5.0 * a * x**2 + 8.0 * a * x - 4.0 * a,
            0.0,
        ),
    )
    valid = (indices >= 0) & (indices < in_size)
    weights = weights * valid
    weights /= weights.sum(axis=1, keepdims=True)
    return np.clip(indices, 0, in_size - 1), weights


def _resize_axis(image: np.ndarray, out_size: int, axis: int) -> np.ndarray:
    """Separable 1-D cubic resample of ``image`` along ``axis`` (f64)."""
    in_size = image.shape[axis]
    if in_size == out_size:
        return image
    indices, weights = _cubic_weights(in_size, out_size)
    moved = np.moveaxis(image, axis, 0)
    # [out, 4, ...] taps -> weighted sum over the tap axis.
    taps = moved[indices]
    out = np.einsum("ot,ot...->o...", weights, taps)
    return np.moveaxis(out, 0, axis)


def resize_bicubic_u8(image: np.ndarray, image_size: int) -> np.ndarray:
    """``tf.image.resize(..., BICUBIC)`` + clip + truncating uint8 cast,
    on numpy. Input uint8/float ``[H, W, C]``; output uint8
    ``[image_size, image_size, C]``."""
    out = _resize_axis(image.astype(np.float64), image_size, 0)
    out = _resize_axis(out, image_size, 1)
    # TF casts with tf.cast (truncation toward zero), not rounding.
    return np.clip(out, 0.0, 255.0).astype(np.uint8)


def preprocess_request(image: np.ndarray, image_size: int) -> np.ndarray:
    """Decoded uint8 ``[H, W, 3]`` image -> model-shaped uint8
    ``[image_size, image_size, 3]`` via the eval ``crop_resize`` recipe.

    The output is what :meth:`sav_tpu.serve.engine.ServeEngine.submit`
    expects; normalization happens inside the compiled program, so this
    function never leaves uint8.
    """
    image = np.asarray(image)
    if image.ndim != 3 or image.shape[-1] != 3:
        raise ValueError(
            f"expected a decoded [H, W, 3] image, got shape {image.shape}"
        )
    if image.dtype != np.uint8:
        raise ValueError(
            f"expected uint8 on the wire, got {image.dtype}; decode/clip "
            "to 0..255 uint8 first (the serving wire format is uint8 end "
            "to end — docs/serving.md)"
        )
    h, w = image.shape[0], image.shape[1]
    y, x, crop = center_crop_window(h, w, image_size)
    if crop < 1:
        raise ValueError(
            f"image {h}x{w} too small to crop for image_size {image_size}"
        )
    cropped = image[y : y + crop, x : x + crop]
    return resize_bicubic_u8(cropped, image_size)
