"""Serve telemetry — per-request spans, live windows, heartbeats, SLO burn.

PR 10's engine finalized a :class:`~sav_tpu.serve.latency.LatencyLedger`
at shutdown; mid-run a serve process was a black box — no live p99, no
per-request timeline, no way to tell *where* a deadline died. This
module is the serving twin of the training observability stack
(PRs 7–8), in the Dapper tradition of request-scoped spans. Four
pillars:

1. **Per-request lifecycle tracing.** Every admitted request carries a
   :class:`RequestTrace` stamped at each stage of its life::

       submit -> admit -> batch_formed -> placed -> dispatched
              -> executed -> depadded -> completed

   Stamps are host-clock appends only (:func:`stamp` — savlint SAV116
   pins the whole stamping surface sync-free; the batcher drain and the
   engine's device loop add ZERO device syncs for tracing). Completed
   traces land in a bounded :class:`SpanRing`; requests whose latency
   clears a robust median+MAD gate are dumped as **slow-request
   exemplars** with full span detail under ``<log_dir>/serve_traces/``,
   and the ring exports as chrome-trace events
   (:func:`export_chrome_trace`) that :mod:`sav_tpu.obs.traceview`
   parses (``request_spans``) — request timelines read through the same
   machinery as device profiles.

2. **Live windowed metrics.** :class:`SlidingWindow` is a fixed-window
   sorted-reservoir percentile sketch (stdlib-only, exact over the
   retained samples); :class:`LiveWindow` aggregates the serving
   headline over the trailing window — p50/p99, throughput, queue
   depth, occupancy, padding waste, shed/overrun counts — observable
   *while serving*. The :class:`~sav_tpu.serve.latency.LatencyLedger`
   feeds it from its existing observation path, so the ledger's final
   numbers stay bit-identical to the pre-window implementation
   (tests/test_serve_telemetry.py pins the on/off equality).

3. **Serve heartbeats.** A time-cadenced (serving has no step boundary)
   ``kind=serve`` stream on the PR-7
   :class:`~sav_tpu.obs.fleet.HeartbeatWriter` substrate
   (``fleet/proc_<i>.jsonl``): windowed p99, queue depth, inflight,
   occupancy, padding waste, shed/overrun counters, SLO burn state,
   HBM watermark. :func:`aggregate_serve` folds the streams into the
   per-replica view — queue depth, p99, occupancy per replica — that
   the ROADMAP item-3 fleet router load-balances on;
   ``tools/fleet_status.py`` / ``tools/serve_status.py`` render it.

4. **SLO accounting + anomaly triggers.** :class:`SLOTracker` scores
   every request against a declarative SLO (deadline-hit-rate target
   over short/long burn windows — the Google-SRE multiwindow
   burn-rate alerting shape), producing ``slo_hit_frac`` /
   ``burn_rate`` in heartbeats and the serve manifest (the regression
   sentinel gates ``slo_hit_frac``). The slow-request gate doubles as
   the anomaly trigger: a latency spike or queue-depth blowup arms a
   bounded :class:`~sav_tpu.obs.autoprof.AutoProfiler` capture
   (``serve_p99_spike`` / ``serve_queue_spike`` triggers, PR-7's
   budget/cooldown machinery) so the profile of a latency regression
   is captured the moment it happens.

Deliberately **stdlib-only** (no jax, no numpy): the offline readers
(``serve_status``, ``run_report --serve``, ``fleet_status``) must work
on rsynced logs from a laptop, and keeping jax unimportable here is the
structural proof that span stamping and window math cannot sync a
device value (tests pin the import surface).
"""

from __future__ import annotations

import gzip
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Callable, Optional

from sav_tpu.obs import alerts as alerts_mod
from sav_tpu.obs import rollup as rollup_mod
from sav_tpu.obs.fleet import (
    MAD_SCALE,
    _mad,
    _median,
    iter_manifests,
    read_heartbeats,
    silence_suspects,
)

SERVE_TELEMETRY_SCHEMA = 1

#: The span vocabulary, in lifecycle order (docs/serving.md).
STAGES = (
    "submit",       # engine.submit entry (request validated, host clock)
    "admit",        # batcher admission passed (queue + shed projection)
    "batch_formed", # the drain closed the batch this request rides in
    "placed",       # padded + sharded device_put ISSUED (feeder thread)
    "dispatched",   # device loop handed the batch to the executable
    "executed",     # device done (the loop's one per-batch sync returned)
    "depadded",     # this request's row sliced out of the padded batch
    "completed",    # future resolved; the submitter can read the result
)

#: Derived per-request intervals (seconds), keyed by the stage that
#: *ends* each one. "queue" spans admission to batch close — the
#: batcher wait; "device" spans dispatch to the post-execution sync.
INTERVALS = (
    ("admission", "submit", "admit"),
    ("queue", "admit", "batch_formed"),
    ("place", "batch_formed", "placed"),
    ("dispatch_wait", "placed", "dispatched"),
    ("device", "dispatched", "executed"),
    ("depad", "executed", "depadded"),
    ("deliver", "depadded", "completed"),
)

#: The fleet router's span vocabulary (ISSUE 16, docs/serving.md
#: "Distributed tracing"), in lifecycle order. The router stamps with
#: its OWN monotonic clock — replica stamps live in the replica's clock
#: domain and only meet these in the offline merge
#: (:func:`sav_tpu.obs.traceview.fleet_request_spans`), which estimates
#: the per-replica offset from the (sent, submit)/(completed, reply)
#: handshake pairs. Terminal stamps for requests that never complete
#: ("shed", "failed") ride the same list but end no interval.
ROUTER_STAGES = (
    "submit",         # router.admit entry (request validated, job built)
    "admit",          # admission passed (capacity + shed projection)
    "route_selected", # a dispatch worker picked a replica
    "connect",        # transport connection to the replica established
    "sent",           # request bytes handed to the replica socket
    "reply",          # the replica's reply line arrived
    "completed",      # future resolved; the submitter can read the result
)

#: The router's per-request intervals (its own clock domain only).
#: ``replica_wait`` is the opaque cross-process span the offline merge
#: decomposes into replica_queue/device/depad + transport halves.
ROUTER_INTERVALS = (
    ("admission", "submit", "admit"),
    ("router_queue", "admit", "route_selected"),
    ("route", "route_selected", "connect"),
    ("transport_send", "connect", "sent"),
    ("replica_wait", "sent", "reply"),
    ("deliver", "reply", "completed"),
)


class RequestTrace:
    """One request's span record: an append-only ``(stage, t)`` list.

    ``t`` values come from one injectable monotonic clock (the
    batcher's); stamping is the cheapest possible host operation so the
    admission/drain/device paths stay sync-free (SAV116).
    """

    __slots__ = ("rid", "deadline_s", "stamps")

    def __init__(self, rid: int, deadline_s: float, t_submit: float):
        self.rid = rid
        self.deadline_s = float(deadline_s)
        self.stamps = [("submit", float(t_submit))]


def stamp(trace: Optional[RequestTrace], stage: str, t: float) -> None:
    """Append one span stamp (no-op on untraced requests). Host-only by
    contract — savlint SAV116 owns this function's body: a device sync
    here would serialize the batcher drain behind a pipeline drain."""
    if trace is not None:
        trace.stamps.append((stage, t))


def intervals(stamps: list, defs: tuple = INTERVALS) -> dict:
    """Per-interval seconds from a stamp list (missing stages skipped).
    ``defs`` selects the vocabulary — the replica's :data:`INTERVALS`
    by default, :data:`ROUTER_INTERVALS` for router traces."""
    at = {}
    for name, t in stamps:
        at.setdefault(name, float(t))
    out = {}
    for name, start, end in defs:
        if start in at and end in at:
            out[name] = at[end] - at[start]
    return out


def dominant_stage(stages_s: dict) -> Optional[str]:
    """The interval that ate the most wall time — 'queue vs device' for
    a slow-request post-mortem."""
    if not stages_s:
        return None
    return max(stages_s, key=lambda k: stages_s[k])


class SpanRing:
    """Bounded ring of the last N completed request traces (plain
    dicts, export-ready). Thread-safety is the owner's job — the engine
    appends from its single device loop."""

    def __init__(self, depth: int = 256):
        if depth < 1:
            raise ValueError(f"ring depth must be >= 1, got {depth}")
        self._ring: deque = deque(maxlen=depth)
        self.appended = 0

    def append(self, record: dict) -> None:
        self._ring.append(record)
        self.appended += 1

    def records(self) -> list:
        return list(self._ring)

    def __len__(self) -> int:
        return len(self._ring)


def trace_record(
    trace: RequestTrace,
    *,
    latency_s: float,
    overrun_s: float,
    bucket: int,
    batch_n: int,
) -> dict:
    """Fold one completed trace into the ring/export record shape.

    Values stay UNROUNDED here — this runs on the device loop for every
    completed request, and cosmetic rounding is deferred to the write
    paths (exemplar dump, chrome export), which are rare/bounded.
    """
    stages_s = intervals(trace.stamps)
    return {
        "rid": trace.rid,
        "deadline_ms": trace.deadline_s * 1e3,
        "latency_ms": latency_s * 1e3,
        "overrun_ms": overrun_s * 1e3,
        "hit": overrun_s <= 0.0,
        "bucket": bucket,
        "batch_n": batch_n,
        "stamps": trace.stamps,
        "stages_ms": {k: v * 1e3 for k, v in stages_s.items()},
        "dominant_stage": dominant_stage(stages_s),
    }


# -------------------------------------------------------- chrome export


def export_chrome_trace(
    records: list,
    defs: tuple = INTERVALS,
    *,
    process_name: str = "Serve Requests",
    extra_args: tuple = (),
) -> dict:
    """The span ring as chrome-trace events (one row per request,
    one "X" event per interval) — the format
    :func:`sav_tpu.obs.traceview.load_trace` /
    ``traceview.request_spans`` read, so ``tools/trace_report.py``
    renders request timelines with the device-profile machinery.
    ``defs`` picks the interval vocabulary; ``extra_args`` names record
    keys copied into each event's args verbatim (the router export
    carries ``rank``/``outcome`` so the offline merge can join the
    replica's trace)."""
    events = [
        {
            "ph": "M", "pid": 1, "name": "process_name",
            "args": {"name": process_name},
        }
    ]
    for rec in records:
        at = {}
        for stage, t in rec.get("stamps", []):
            at.setdefault(stage, float(t))
        rid = rec.get("rid", 0)
        for name, start, end in defs:
            if start not in at or end not in at:
                continue
            args = {
                "request": rid,
                "bucket": rec.get("bucket"),
                "deadline_ms": (
                    round(rec["deadline_ms"], 3)
                    if isinstance(rec.get("deadline_ms"), (int, float))
                    else None
                ),
                "overrun_ms": (
                    round(rec["overrun_ms"], 3)
                    if isinstance(rec.get("overrun_ms"), (int, float))
                    else None
                ),
            }
            for key in extra_args:
                if key in rec:
                    args[key] = rec[key]
            events.append({
                "ph": "X",
                "pid": 1,
                "tid": rid,
                "name": name,
                "ts": round(at[start] * 1e6, 1),
                "dur": round((at[end] - at[start]) * 1e6, 1),
                "args": args,
            })
    return {"traceEvents": events}


def write_request_trace(
    path: str,
    records: list,
    defs: tuple = INTERVALS,
    *,
    process_name: str = "Serve Requests",
    extra_args: tuple = (),
) -> Optional[str]:
    """Persist the ring as ``*.trace.json.gz`` (telemetry: returns None
    instead of raising on I/O failure)."""
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with gzip.open(tmp, "wt") as f:
            json.dump(
                export_chrome_trace(
                    records, defs,
                    process_name=process_name, extra_args=extra_args,
                ),
                f,
            )
        os.replace(tmp, path)
        return path
    except OSError:
        return None


# ------------------------------------------------------- sliding windows


class SlidingWindow:
    """Fixed-window sorted-reservoir percentile sketch (stdlib-only).

    Holds the last ``window_s`` seconds of ``(t, value)`` samples,
    bounded by ``max_samples`` (oldest evicted first — under cap the
    percentiles are EXACT over the window; over cap they are exact over
    the newest ``max_samples``, a bounded-staleness approximation the
    tolerance tests pin). Not thread-safe; owners lock.
    """

    def __init__(
        self,
        window_s: float = 30.0,
        *,
        max_samples: int = 4096,
        clock: Callable[[], float] = time.monotonic,
    ):
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        if max_samples < 1:
            raise ValueError(f"max_samples must be >= 1, got {max_samples}")
        self.window_s = float(window_s)
        self._max = int(max_samples)
        self._clock = clock
        self._samples: deque = deque()

    def _evict(self, now: float) -> None:
        horizon = now - self.window_s
        while self._samples and self._samples[0][0] < horizon:
            self._samples.popleft()
        while len(self._samples) > self._max:
            self._samples.popleft()

    def observe(self, value: float, now: Optional[float] = None) -> None:
        now = self._clock() if now is None else now
        self._samples.append((now, float(value)))
        self._evict(now)

    def values(self, now: Optional[float] = None) -> list:
        self._evict(self._clock() if now is None else now)
        return [v for _, v in self._samples]

    def count(self, now: Optional[float] = None) -> int:
        self._evict(self._clock() if now is None else now)
        return len(self._samples)

    def total(self, now: Optional[float] = None) -> float:
        self._evict(self._clock() if now is None else now)
        return sum(v for _, v in self._samples)

    def percentile(self, q: float, now: Optional[float] = None):
        """Windowed percentile, or None on an empty window — the
        graceful-degrade contract: a live query before the first
        completed batch must never raise."""
        values = sorted(self.values(now))
        if not values:
            return None
        from sav_tpu.serve.latency import percentile as _pct

        return _pct(values, q)


class LiveWindow:
    """The live serving headline over a trailing window.

    Fed by :meth:`~sav_tpu.serve.latency.LatencyLedger.observe_batch`
    (one call per shipped batch — same observation path as the final
    summary, which is what keeps the two views consistent) and by the
    shed path. ``snapshot()`` is safe at ANY point in the run: before
    the first completed batch every percentile is None and every rate
    zero, never an exception.
    """

    def __init__(
        self,
        window_s: float = 30.0,
        *,
        max_samples: int = 4096,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.window_s = float(window_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._latency = SlidingWindow(
            window_s, max_samples=max_samples, clock=clock
        )
        self._queue = SlidingWindow(
            window_s, max_samples=max_samples, clock=clock
        )
        # Per-batch (t, (real_rows, padded_rows)) for occupancy/waste.
        self._rows: deque = deque()
        self._overruns = SlidingWindow(
            window_s, max_samples=max_samples, clock=clock
        )
        self._shed = SlidingWindow(
            window_s, max_samples=max_samples, clock=clock
        )
        self._step_s = SlidingWindow(
            window_s, max_samples=max_samples, clock=clock
        )

    def observe_window(
        self,
        *,
        latencies_s: list,
        overruns_s: list,
        bucket: int,
        queue_depth: int,
        step_s: float,
        now: Optional[float] = None,
    ) -> None:
        """One shipped batch into the window (host floats only —
        savlint SAV116 owns this body)."""
        now = self._clock() if now is None else now
        with self._lock:
            for v in latencies_s:
                self._latency.observe(float(v), now)
            for v in overruns_s:
                if v > 0.0:
                    self._overruns.observe(float(v), now)
            self._queue.observe(int(queue_depth), now)
            self._step_s.observe(float(step_s), now)
            self._rows.append((now, (len(latencies_s), int(bucket))))
            horizon = now - self.window_s
            while self._rows and self._rows[0][0] < horizon:
                self._rows.popleft()

    def observe_shed(self, n: int = 1, now: Optional[float] = None) -> None:
        now = self._clock() if now is None else now
        with self._lock:
            for _ in range(int(n)):
                self._shed.observe(1.0, now)

    def latency_values(self, now: Optional[float] = None) -> list:
        with self._lock:
            return self._latency.values(now)

    def queue_values(self, now: Optional[float] = None) -> list:
        with self._lock:
            return self._queue.values(now)

    def snapshot(self, now: Optional[float] = None) -> dict:
        now = self._clock() if now is None else now
        with self._lock:
            lat = sorted(self._latency.values(now))
            horizon = now - self.window_s
            while self._rows and self._rows[0][0] < horizon:
                self._rows.popleft()
            # Request counts and throughput come from the per-batch row
            # entries (one per batch, time-evicted only) — EXACT over
            # the window. The latency reservoir is additionally capped
            # at max_samples, so len(lat) saturates under high load
            # (4096/30s ≈ 137 rps at defaults) and must only feed the
            # percentiles, where bounded staleness is the documented
            # approximation.
            real = sum(r for _, (r, _) in self._rows)
            padded = sum(b for _, (_, b) in self._rows)
            queue_vals = self._queue.values(now)
            # Elapsed window: the full window once data is older than
            # it, else the observed span (a 2s-old window must not
            # report a 30s-diluted rate).
            span = self.window_s
            if lat or self._rows:
                oldest = min(
                    [t for t, _ in self._rows]
                    or [now - self.window_s]
                )
                span = min(self.window_s, max(now - oldest, 1e-9))
            out = {
                "window_s": self.window_s,
                "requests": real,
                "batches": len(self._rows),
                "throughput_rps": (
                    round(real / span, 2) if real else 0.0
                ),
                "queue_depth_last": (
                    int(queue_vals[-1]) if queue_vals else 0
                ),
                "queue_depth_avg": (
                    round(sum(queue_vals) / len(queue_vals), 2)
                    if queue_vals else 0.0
                ),
                "queue_depth_max": (
                    int(max(queue_vals)) if queue_vals else 0
                ),
                "occupancy": (
                    round(real / padded, 4) if padded else None
                ),
                "padding_waste_frac": (
                    round(1.0 - real / padded, 4) if padded else None
                ),
                "overruns": self._overruns.count(now),
                "shed": self._shed.count(now),
                "step_s_avg": (
                    round(
                        self._step_s.total(now) / self._step_s.count(now), 5
                    )
                    if self._step_s.count(now) else None
                ),
            }
            if lat:
                from sav_tpu.serve.latency import percentile as _pct

                out["p50_ms"] = round(_pct(lat, 50.0) * 1e3, 3)
                out["p95_ms"] = round(_pct(lat, 95.0) * 1e3, 3)
                out["p99_ms"] = round(_pct(lat, 99.0) * 1e3, 3)
            else:
                out["p50_ms"] = out["p95_ms"] = out["p99_ms"] = None
            return out


# -------------------------------------------------------------- SLO


class _RateWindow:
    """Windowed (misses, total) counts — the SLO burn windows need only
    rates, so one ``(t, misses, n)`` entry per observed BATCH keeps the
    per-request hot-path cost at zero appends. Owner locks."""

    def __init__(self, window_s: float):
        self.window_s = float(window_s)
        self._entries: deque = deque()
        self._misses = 0
        self._n = 0

    def observe(self, misses: int, n: int, now: float) -> None:
        self._entries.append((now, misses, n))
        self._misses += misses
        self._n += n
        self._evict(now)

    def _evict(self, now: float) -> None:
        horizon = now - self.window_s
        while self._entries and self._entries[0][0] < horizon:
            _, misses, n = self._entries.popleft()
            self._misses -= misses
            self._n -= n

    def counts(self, now: float) -> tuple:
        self._evict(now)
        return self._misses, self._n


class SLOTracker:
    """Deadline-hit-rate SLO with Google-SRE multiwindow burn rates.

    ``target`` is the hit-rate objective (0.99 = at most 1% of requests
    may miss their deadline); the **error budget** is ``1 - target``.
    The burn rate of a window is ``miss_frac / budget`` — 1.0 means the
    budget burns exactly at the sustainable rate, N means the budget
    exhausts N times too fast. Alerting uses the standard two-window
    AND (a short window for responsiveness, a long one so a single
    blip cannot page): ``burning`` iff BOTH windows exceed
    ``burn_threshold``. Shed requests count as misses — a request the
    admission controller turned away did not hit its deadline.
    """

    def __init__(
        self,
        *,
        target: float = 0.99,
        fast_window_s: float = 60.0,
        slow_window_s: float = 600.0,
        burn_threshold: float = 2.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if not 0.0 < target < 1.0:
            raise ValueError(f"slo target must be in (0, 1), got {target}")
        if fast_window_s >= slow_window_s:
            raise ValueError(
                f"fast window ({fast_window_s}s) must be shorter than the "
                f"slow window ({slow_window_s}s)"
            )
        self.target = float(target)
        self.burn_threshold = float(burn_threshold)
        self._clock = clock
        self._lock = threading.Lock()
        self._fast = _RateWindow(fast_window_s)
        self._slow = _RateWindow(slow_window_s)
        self.requests = 0
        self.misses = 0

    def observe_outcomes(
        self, misses: int, n: int, now: Optional[float] = None
    ) -> None:
        """Fold one batch's outcomes in — ONE lock + append per batch,
        which is what keeps SLO accounting off the per-request cost."""
        if n <= 0:
            return
        now = self._clock() if now is None else now
        with self._lock:
            self.requests += n
            self.misses += misses
            self._fast.observe(misses, n, now)
            self._slow.observe(misses, n, now)

    def observe_request(
        self, hit: bool, now: Optional[float] = None
    ) -> None:
        self.observe_outcomes(int(not hit), 1, now)

    def _burn(self, window: _RateWindow, now: float) -> Optional[float]:
        misses, n = window.counts(now)
        if not n:
            return None
        return round((misses / n) / (1.0 - self.target), 4)

    def state(self, now: Optional[float] = None) -> dict:
        now = self._clock() if now is None else now
        with self._lock:
            fast = self._burn(self._fast, now)
            slow = self._burn(self._slow, now)
            return {
                "target": self.target,
                "requests": self.requests,
                "misses": self.misses,
                "hit_frac": (
                    round(1.0 - self.misses / self.requests, 6)
                    if self.requests else None
                ),
                "burn_fast": fast,
                "burn_slow": slow,
                # The headline burn number: the long window (short-blip
                # noise stays in burn_fast).
                "burn_rate": slow,
                "burning": bool(
                    fast is not None and slow is not None
                    and fast > self.burn_threshold
                    and slow > self.burn_threshold
                ),
                "burn_threshold": self.burn_threshold,
            }


# -------------------------------------------------------- the orchestrator


class ServeTelemetry:
    """The engine's request-scoped + fleet-scoped observability layer.

    Owns the span ring, the live window, the SLO tracker, the
    slow-request exemplar gate, the serve heartbeat thread, and the
    anomaly hooks into a bounded :class:`AutoProfiler`. Everything on
    the serving hot path (``begin_trace`` / ``stamp`` /
    ``observe_completed`` / ``observe_shed`` / ``serve_beat``) is
    host-only — savlint SAV116 statically pins it, and ``stats()``'s
    ``overhead_s`` gauge makes the cost assertable.
    """

    # Batches between robust-gate recomputations (latency + queue
    # anomaly gates): median+MAD over the window costs two sorts, which
    # must not be a per-batch tax. A slow-moving gate refreshed every
    # few batches detects the same spikes (a spike is 10-100x the
    # median; the gate drifts by percents between refreshes).
    GATE_REFRESH = 8

    def __init__(
        self,
        log_dir: Optional[str] = None,
        *,
        dtype: Optional[str] = None,
        trace_ring: int = 256,
        exemplar_max: int = 8,
        exemplar_sigma: float = 4.0,
        exemplar_min_history: int = 16,
        window_s: float = 30.0,
        heartbeat_secs: float = 5.0,
        slo_target: float = 0.99,
        slo_fast_window_s: float = 60.0,
        slo_slow_window_s: float = 600.0,
        slo_burn_threshold: float = 2.0,
        clock: Callable[[], float] = time.monotonic,
        wall_clock: Callable[[], float] = time.time,
        perf: Callable[[], float] = time.perf_counter,
        writer=None,
        autoprof=None,
        queue_stats_fn: Optional[Callable[[], dict]] = None,
        hbm_fn: Optional[Callable[[], Optional[dict]]] = None,
        quality_fn: Optional[Callable[[], Optional[dict]]] = None,
        max_batch: Optional[int] = None,
        alerts="auto",
    ):
        self.log_dir = log_dir
        # Weight-serving dtype stamp ("bf16" | "f32" | "int8" — ISSUE
        # 17): rides every heartbeat so fleet_status/serve_status can
        # tell a quantized replica from a bf16 one without reading its
        # manifest. None = unstamped (pre-quant callers).
        self.dtype = dtype
        self.clock = clock
        self._wall = wall_clock
        self._perf = perf
        self.ring = SpanRing(trace_ring)
        self.window = LiveWindow(window_s, clock=clock)
        self.slo = SLOTracker(
            target=slo_target,
            fast_window_s=slo_fast_window_s,
            slow_window_s=slo_slow_window_s,
            burn_threshold=slo_burn_threshold,
            clock=clock,
        )
        self.exemplar_max = int(exemplar_max)
        self.exemplar_sigma = float(exemplar_sigma)
        self.exemplar_min_history = int(exemplar_min_history)
        self.heartbeat_secs = float(heartbeat_secs)
        self.writer = writer
        self.autoprof = autoprof
        # Measured capacity (ISSUE 19): the ladder's top rung over the
        # windowed per-batch step — rows/s this replica can actually
        # sustain, published as ``capacity_rps`` in every beat. None
        # (pre-fleet callers) publishes nothing.
        self.max_batch = int(max_batch) if max_batch else None
        # Declarative alert rules (ISSUE 19): "auto" arms the built-in
        # SLO burn rule (parity-gated against SLOTracker) plus any
        # operator rules from the SAV_ALERT_RULES env seam (a JSON file
        # path — replicas inherit the parent's env through the pool, so
        # a fleet arms without flag plumbing). Pass an AlertEngine to
        # own the rule set outright, or None to disarm. Evaluation runs
        # at heartbeat cadence only (savlint SAV125).
        if alerts == "auto":
            alerts = None
            if writer is not None:
                rules = alerts_mod.default_rules(slo_burn_threshold)
                # Quality rules (ISSUE 20) arm ALONGSIDE the default
                # set, never inside it — default_rules() stays exactly
                # the SLO rule (pinned by test_alerts). Beats without
                # quality fields evaluate them False, so pre-quality
                # replicas pay nothing.
                rules = rules + alerts_mod.quality_rules()
                source = os.environ.get("SAV_ALERT_RULES")
                if source:
                    rules = rules + alerts_mod.load_rules(source)
                alerts = alerts_mod.AlertEngine(
                    rules,
                    log_dir=log_dir,
                    proc=getattr(writer, "process_index", None),
                    clock=wall_clock,
                )
        self.alerts = alerts
        self._queue_stats_fn = queue_stats_fn
        self._hbm_fn = hbm_fn
        # Quality snapshot seam (ISSUE 20): digest drift gates + probe
        # state folded at beat cadence by the engine's
        # quality_snapshot — rides every kind=serve beat under
        # ``quality`` (schema stays v2; readers are forward-compatible).
        self._quality_fn = quality_fn
        self._lock = threading.Lock()
        self._rid = itertools.count(1)
        self._batches = 0
        self._completed = 0
        self._shed = 0
        # Cached robust gates (latency + queue), refreshed every
        # GATE_REFRESH batches: the median+MAD of a trailing window
        # moves slowly, and recomputing it (two sorts) on EVERY batch
        # is the kind of per-batch tax the <2%-overhead contract
        # exists to keep out of the device loop.
        self._lat_gate: Optional[float] = None
        self._queue_gate: Optional[float] = None
        self._gates_at = -10**9
        self._gate_window_n = 0
        self._exemplars: list = []
        self._heartbeats = 0
        self._overhead_s = 0.0
        self._t_start: Optional[float] = None
        self._stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._closed = False

    # ----------------------------------------------------------- tracing

    def begin_trace(self, deadline_s: float, *, rid=None) -> RequestTrace:
        """Open one request's span record (engine ``submit`` entry —
        host clock only, SAV116). Request ids come from a lock-free
        counter (itertools.count — the submit path must not contend
        with the device loop's telemetry lock) unless the caller
        propagates one: a fleet request arrives with the ROUTER's
        globally unique ``r<pid>-<seq>`` id in the wire header, and
        adopting it is what joins this replica's spans to the router's
        in the offline merge (ISSUE 16 — replica-local serving, with no
        id to adopt, mints exactly as before)."""
        return RequestTrace(
            next(self._rid) if rid is None else rid,
            deadline_s, self.clock(),
        )

    def observe_completed(
        self,
        formed,
        *,
        latencies_s: list,
        overruns_s: list,
        step_s: float,
    ) -> None:
        """One completed batch from the device loop: ring, SLO, the
        slow-exemplar gate, and the anomaly triggers. Host bookkeeping
        only (SAV116) — the bounded exemplar dump is the single file
        write this path can take, capped at ``exemplar_max`` per run.
        """
        t0 = self._perf()
        now = self.clock()
        # Robust gates over the live window (the ledger fed it before
        # this call; median+MAD keeps a spike from raising its own
        # bar). Refreshed every GATE_REFRESH batches, not every batch —
        # the gate moves slowly and the two sorts it costs belong off
        # the per-batch path.
        window_n = 0
        if self._batches - self._gates_at >= self.GATE_REFRESH:
            lat_values = self.window.latency_values(now)
            window_n = len(lat_values)
            if window_n >= self.exemplar_min_history:
                med = _median(lat_values)
                mad = _mad(lat_values, med)
                self._lat_gate = med + self.exemplar_sigma * max(
                    MAD_SCALE * mad, 0.05 * abs(med), 1e-9
                )
            queue_vals = self.window.queue_values(now)
            if len(queue_vals) >= self.exemplar_min_history:
                qmed = _median(queue_vals)
                qmad = _mad(queue_vals, qmed)
                self._queue_gate = qmed + self.exemplar_sigma * max(
                    MAD_SCALE * qmad, 0.25 * abs(qmed), 1.0
                )
            self._gates_at = self._batches
            self._gate_window_n = window_n
        gate = self._lat_gate
        self.slo.observe_outcomes(
            sum(1 for v in overruns_s if v > 0.0), len(overruns_s), now
        )
        spiked = False
        records = []
        for request, latency_s, overrun_s in zip(
            formed.requests, latencies_s, overruns_s
        ):
            trace = getattr(request, "trace", None)
            if trace is None:
                continue
            rec = trace_record(
                trace,
                latency_s=latency_s,
                overrun_s=overrun_s,
                bucket=formed.bucket,
                batch_n=len(formed.requests),
            )
            slow = gate is not None and latency_s > gate
            rec["slow"] = slow
            spiked = spiked or slow
            records.append(rec)
            if slow:
                self._dump_exemplar(rec, gate, self._gate_window_n)
        with self._lock:
            for rec in records:
                self.ring.append(rec)
            self._batches += 1
            self._completed += len(latencies_s)
            batches = self._batches
        # Queue-depth anomaly: the current depth against the cached
        # robust gate (a backlog building faster than the drain can eat
        # it is the overload signature shedding is about to follow).
        queue_spiked = (
            self._queue_gate is not None
            and formed.queue_depth > self._queue_gate
        )
        if self.autoprof is not None:
            if spiked:
                self.autoprof.request("serve_p99_spike", batches)
            elif queue_spiked:
                self.autoprof.request("serve_queue_spike", batches)
            # Drive the capture window in batch units (serving's only
            # repeating boundary): starts an armed capture, stops a
            # finished one — PR-7's state machine unchanged.
            self.autoprof.on_step(batches)
        with self._lock:
            self._overhead_s += self._perf() - t0

    def observe_shed(self, n: int = 1) -> None:
        """Admission rejects (queue full / deadline infeasible): SLO
        misses (a shed request did not hit its deadline). The window's
        shed count is fed by the ledger's ``observe_rejected`` forward
        — one window-observation path, no double counting."""
        self.slo.observe_outcomes(int(n), int(n), self.clock())
        with self._lock:
            self._shed += int(n)

    # ---------------------------------------------------------- exemplars

    def _dump_exemplar(self, rec: dict, gate_s: float, window_n: int):
        """Write one slow-request bundle (bounded: ``exemplar_max``)."""
        with self._lock:
            if (
                self.log_dir is None
                or len(self._exemplars) >= self.exemplar_max
            ):
                return
            seq = len(self._exemplars)
            # pid-stamped: seq and rid both restart per process, so
            # replicas/restarts sharing a log dir must not reclaim each
            # other's bundle names — earlier runs' exemplars stay on
            # disk (docs/serving.md's contract).
            path = os.path.join(
                self.log_dir, "serve_traces",
                f"slow_{seq:04d}_req{rec['rid']}_p{os.getpid()}.json",
            )
            self._exemplars.append(path)
        bundle = dict(rec)
        # Cosmetic rounding happens HERE (bounded writes), not on the
        # per-request trace_record path.
        for key in ("deadline_ms", "latency_ms", "overrun_ms"):
            bundle[key] = round(bundle[key], 3)
        bundle["stamps"] = [(s, round(t, 6)) for s, t in bundle["stamps"]]
        bundle["stages_ms"] = {
            k: round(v, 3) for k, v in bundle["stages_ms"].items()
        }
        bundle["schema"] = SERVE_TELEMETRY_SCHEMA
        bundle["kind"] = "slow_exemplar"
        bundle["t_unix"] = round(self._wall(), 3)
        bundle["gate"] = {
            "sigma": self.exemplar_sigma,
            "threshold_ms": round(gate_s * 1e3, 3),
            "window_n": window_n,
        }
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(bundle, f, indent=2)
            os.replace(tmp, path)
        except OSError:
            with self._lock:
                self._exemplars.remove(path)

    # ---------------------------------------------------------- heartbeats

    def start(self) -> None:
        """Open the serving window and start the heartbeat thread."""
        self._t_start = self.clock()
        if self.writer is not None and self.heartbeat_secs > 0:
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop,
                name="serve-heartbeat",
                daemon=True,
            )
            self._hb_thread.start()

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_secs):
            self.serve_beat()

    def serve_beat(self) -> None:
        """Emit one ``kind=serve`` heartbeat line (host-only, SAV116:
        every value is already host-side — window floats, batcher
        counters, the HBM stats counter read)."""
        if self.writer is None:
            return
        t0 = self._perf()
        now = self.clock()
        # One consistent counter snapshot (SAV121): the heartbeat thread
        # reads what request threads write, and a beat catching requests
        # N with batches from N+1 is a torn line in the fleet record.
        with self._lock:
            completed = self._completed
            batches = self._batches
            shed = self._shed
            exemplars = len(self._exemplars)
        record: dict = {
            "up_s": (
                round(now - self._t_start, 3)
                if self._t_start is not None else None
            ),
            "requests": completed,
            "batches": batches,
            "shed": shed,
            "w": self.window.snapshot(now),
            "slo": self.slo.state(now),
            "exemplars": exemplars,
        }
        if self.dtype is not None:
            record["dtype"] = self.dtype
        # Measured capacity: top ladder rung / windowed per-batch step
        # (rows per second at full batches). Published only once the
        # window has a measured step — capacity is a measurement, not a
        # spec sheet (absent beats are skipped by the fold, not zeroed).
        step = record["w"].get("step_s_avg")
        if (
            self.max_batch
            and isinstance(step, (int, float))
            and step > 0
        ):
            record["capacity_rps"] = round(self.max_batch / step, 2)
        if self._queue_stats_fn is not None:
            try:
                qs = self._queue_stats_fn() or {}
                record["queued"] = qs.get("queued")
                record["inflight"] = qs.get("inflight")
                record["rejected"] = qs.get("rejected")
            except Exception:
                pass
        if self._hbm_fn is not None:
            try:
                hbm = self._hbm_fn()
                if hbm:
                    record.update(hbm)
            except Exception:
                pass
        if self._quality_fn is not None:
            # Quality fields (ISSUE 20): digest drift gates + probe
            # fingerprint state, folded by the engine at THIS beat
            # cadence (never per request — SAV126). Inserted before
            # alerts.observe so the quality rules see them on the same
            # beat; the close() path reuses this, so the FINAL beat of
            # a stopping replica carries its last probe verdict — a
            # mismatch is on disk even if the replica dies right after.
            try:
                quality = self._quality_fn()
                if quality and (
                    quality.get("n") or quality.get("probe_runs")
                ):
                    record["quality"] = quality
            except Exception:
                pass
        if self.autoprof is not None:
            record["captures"] = len(self.autoprof.captures)
        if self.alerts is not None:
            # Rule evaluation rides the beat cadence (the ONE sanctioned
            # home — savlint SAV125 keeps it out of the request paths);
            # active rule names stamp the line so a beat stream alone
            # shows what was firing when.
            try:
                self.alerts.observe(record, now=self._wall())
                active = self.alerts.active()
                if active:
                    record["alerts"] = active
            except Exception:
                pass  # a broken rule must not stop heartbeating
        appended = self.writer.serve_beat(record)
        with self._lock:
            # Count only beats actually appended — a dropped (lock
            # timeout) or post-close beat must not make the bench
            # line's heartbeat count exceed the lines on disk.
            if appended:
                self._heartbeats += 1
            self._overhead_s += self._perf() - t0

    # ------------------------------------------------------------ shutdown

    def close(self, outcome: str = "ok") -> dict:
        """Stop the heartbeat thread, emit one final beat, persist the
        span ring, and return the summary the engine stamps into the
        manifest. Idempotent."""
        if self._closed:
            return self.summary()
        self._closed = True
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5.0)
        if self.writer is not None:
            self.serve_beat()
            self.writer.close(outcome)
        if self.alerts is not None:
            # An episode cannot outlive its emitter: the final beat
            # above was its last chance to resolve on data; whatever is
            # still firing resolves here (exactly one resolved event
            # per open episode — the once-per-episode contract).
            try:
                self.alerts.finalize(self._wall())
            except Exception:
                pass
        if self.autoprof is not None:
            try:
                self.autoprof.finalize(self._batches)
            except Exception:
                pass
        if self.log_dir is not None and len(self.ring):
            # Replica-namespaced like the heartbeat streams
            # (proc_<i>.jsonl): N replicas sharing a log dir must not
            # overwrite each other's ring. A RESTART of the same
            # replica does overwrite — the ring is "the last N
            # requests of replica i", newest state wins.
            proc = (
                getattr(self.writer, "process_index", 0)
                if self.writer is not None else 0
            )
            write_request_trace(
                os.path.join(
                    self.log_dir, "serve_traces",
                    f"requests_proc{proc}.trace.json.gz",
                ),
                self.ring.records(),
            )
        return self.summary()

    def summary(self) -> dict:
        with self._lock:
            out = {
                "schema": SERVE_TELEMETRY_SCHEMA,
                "requests": self._completed,
                "batches": self._batches,
                "shed": self._shed,
                "traced": self.ring.appended,
                "ring": len(self.ring),
                "exemplars": list(self._exemplars),
                "heartbeats": self._heartbeats,
                "overhead_s": round(self._overhead_s, 6),
            }
        out["slo"] = self.slo.state()
        out["window"] = self.window.snapshot()
        if self.autoprof is not None:
            out["autoprof"] = self.autoprof.stats()
        if self.alerts is not None:
            out["alerts"] = self.alerts.state()
        return out

    def stats(self) -> dict:
        """Flat gauge view (the <2% overhead guard reads overhead_s)."""
        with self._lock:
            return {
                "requests": float(self._completed),
                "batches": float(self._batches),
                "shed": float(self._shed),
                "exemplars": float(len(self._exemplars)),
                "heartbeats": float(self._heartbeats),
                "overhead_s": self._overhead_s,
            }


# -------------------------------------------------------- offline readers


def _serve_streams(
    log_dir: str, *, tail_bytes: Optional[int] = None
) -> tuple:
    """``(streams, finals)``: per-process ``kind=serve`` beats plus a
    per-process "closed" flag — the ONE filtering body behind
    :func:`read_serve_beats`, :func:`aggregate_serve` and the router's
    live view. ``finals[proc]`` is True only when the newest ``final``
    record is at least as new as the newest serve beat: the streams are
    append-only across restarts, so a final from a PREVIOUS process
    generation (a graceful stop before a pool restart) must not mark
    the freshly-beating replica as closed — that would down every
    replica of a reused log dir forever (same recency rule for the
    suspicion's "an orderly close is not a death" exemption)."""
    streams: dict = {}
    finals: dict = {}
    for proc, records in read_heartbeats(
        log_dir, tail_bytes=tail_bytes
    ).items():
        serve = [r for r in records if r.get("kind") == "serve"]
        if not serve:
            continue
        streams[proc] = serve
        last_final = max(
            (
                float(r.get("t", 0.0)) for r in records
                if r.get("kind") == "final"
            ),
            default=None,
        )
        finals[proc] = (
            last_final is not None
            and last_final >= float(serve[-1].get("t", 0.0))
        )
    return streams, finals


def read_serve_beats(log_dir: str) -> dict:
    """Per-process ``kind=serve`` heartbeat records from the fleet
    streams (``fleet/proc_*.jsonl`` — same files, same torn-tail
    discipline as training heartbeats)."""
    return _serve_streams(log_dir)[0]


def aggregate_serve(
    log_dir: str,
    *,
    max_timeline: int = 120,
    now: Optional[float] = None,
    suspect_factor: float = 3.0,
    tail_bytes: Optional[int] = None,
) -> dict:
    """Fold the serve heartbeat streams into the per-replica fleet view.

    This is the ROADMAP item-3 router input: per replica, the latest
    windowed p99 / queue depth / inflight / occupancy, plus SLO burn
    state — recomputable offline from artifacts alone (stdlib-only).

    Dead-replica suspicion rides the same summary (the flag
    ``aggregate_fleet`` has carried for training streams since PR 7,
    via the shared :func:`sav_tpu.obs.fleet.silence_suspects` body): a
    replica silent for more than ``suspect_factor`` x the fleet median
    beat interval, with no final record, is listed in ``suspects`` and
    flagged ``suspect`` in its view — a SIGKILLed replica shows up as
    "replica 1 stopped heartbeating", not by vanishing from
    ``serve_status``. The fleet router routes on EXACTLY this flag
    (:func:`router_views`). ``now`` defaults to the newest heartbeat
    across the fleet (offline semantics — wall clock would flag every
    replica of a finished run); the live router passes the wall clock
    (and a ``tail_bytes`` bound, so refreshing the view every half
    second never re-parses a long run's full history).
    """
    streams, finals = _serve_streams(log_dir, tail_bytes=tail_bytes)
    summary: dict = {
        "schema": SERVE_TELEMETRY_SCHEMA,
        "log_dir": log_dir,
        "replicas": {},
    }
    if not streams:
        return summary
    if now is None:
        now = max(
            float(b.get("t", 0.0)) for beats in streams.values()
            for b in beats
        )
    suspects = silence_suspects(
        {
            proc: [float(b.get("t", 0.0)) for b in beats]
            for proc, beats in streams.items()
        },
        finals,
        now=float(now),
        suspect_factor=suspect_factor,
    )
    suspect_procs = {s["proc"] for s in suspects}
    timeline = []
    for proc, beats in streams.items():
        last = beats[-1]
        w = last.get("w") or {}
        slo = last.get("slo") or {}
        p99s = [
            (b.get("w") or {}).get("p99_ms")
            for b in beats
            if isinstance((b.get("w") or {}).get("p99_ms"), (int, float))
        ]
        view = {
            "beats": len(beats),
            "first_unix": beats[0].get("t"),
            "last_unix": last.get("t"),
            "dtype": last.get("dtype"),
            "up_s": last.get("up_s"),
            "requests": last.get("requests"),
            "shed": last.get("shed"),
            "queued": last.get("queued"),
            "inflight": last.get("inflight"),
            "p99_ms": w.get("p99_ms"),
            "throughput_rps": w.get("throughput_rps"),
            "capacity_rps": last.get("capacity_rps"),
            "alerts": last.get("alerts") or [],
            "step_s_avg": w.get("step_s_avg"),
            "queue_depth": w.get("queue_depth_last"),
            "occupancy": w.get("occupancy"),
            "padding_waste_frac": w.get("padding_waste_frac"),
            "median_p99_ms": (
                round(_median(p99s), 3) if p99s else None
            ),
            "slo_hit_frac": slo.get("hit_frac"),
            "burn_rate": slo.get("burn_rate"),
            "burning": slo.get("burning"),
            "exemplars": last.get("exemplars"),
            "captures": last.get("captures"),
            "hbm_peak_bytes": last.get("hbm_peak_bytes"),
            # Quality fields (ISSUE 20): the last beat's digest gates +
            # probe verdict — absent on pre-quality streams (readers
            # skip, never zero-fill).
            "quality": last.get("quality"),
            "pid": last.get("pid"),
            "final": bool(finals.get(proc)),
            "suspect": proc in suspect_procs,
        }
        summary["replicas"][str(proc)] = view
        for b in beats:
            bw = b.get("w") or {}
            timeline.append({
                "t": b.get("t"),
                "proc": proc,
                "p99_ms": bw.get("p99_ms"),
                "queue": bw.get("queue_depth_last"),
                "rps": bw.get("throughput_rps"),
            })
    timeline.sort(key=lambda e: (e.get("t") or 0.0, e.get("proc") or 0))
    if len(timeline) > max_timeline:
        stride = -(-len(timeline) // max_timeline)
        timeline = timeline[::stride] + timeline[-1:]
    summary["timeline"] = timeline
    replicas = summary["replicas"].values()
    rps = [
        v["throughput_rps"] for v in replicas
        if isinstance(v.get("throughput_rps"), (int, float))
    ]
    p99 = [
        v["p99_ms"] for v in replicas
        if isinstance(v.get("p99_ms"), (int, float))
    ]
    summary["suspects"] = suspects
    summary["fleet"] = {
        "replicas": len(summary["replicas"]),
        "throughput_rps": round(sum(rps), 2) if rps else None,
        "worst_p99_ms": max(p99) if p99 else None,
        "burning": sorted(
            int(p) for p, v in summary["replicas"].items() if v.get("burning")
        ),
        "suspects": sorted(s["proc"] for s in suspects),
        "alerts": sorted({
            name for v in replicas for name in (v.get("alerts") or [])
        }),
    }
    # Fleet probe verdict (ISSUE 20): the WORST replica's probe_ok_frac
    # — one corrupt replica must not hide behind healthy peers. Skipped
    # (not zero-filled) when no replica ran a probe.
    probe_ok = [
        (v.get("quality") or {}).get("probe_ok_frac") for v in replicas
    ]
    probe_ok = [p for p in probe_ok if isinstance(p, (int, float))]
    if probe_ok:
        summary["fleet"]["probe_ok_frac"] = round(min(probe_ok), 6)
    _fold_capacity(summary, log_dir)
    return summary


#: Projection horizon for the headroom fold: one fast SLO window ahead
#: — far enough that a building ramp shows, near enough that the
#: Theil–Sen slope over the finest rollup tier is still predictive.
HEADROOM_HORIZON_S = 60.0


def _fold_capacity(summary: dict, log_dir: str) -> None:
    """The ISSUE-19 capacity/headroom fold on ``summary["fleet"]``:

    - ``capacity_rps``: sum of the replicas' measured ``capacity_rps``
      stamps (absent stamps are SKIPPED, not zero-filled — capacity is
      a measurement; a fleet with no measured replica has no capacity
      number and therefore no headroom number, the sentinel's
      skip-don't-fabricate rule).
    - ``projected_rps``: robust-slope projection of fleet throughput
      over the finest rollup tier (:func:`sav_tpu.obs.rollup
      .project_load` — Theil–Sen, so one straggling bucket cannot bend
      the forecast), falling back to the beat timeline when nothing has
      been rolled yet.
    - ``headroom_frac``: ``(capacity - projected) / capacity``, clamped
      to [-1, 1] — the ROADMAP item-3 autoscaler/weighted-routing
      input, sentinel-gated as ``fleet_headroom_frac``.
    """
    fleet = summary["fleet"]
    replicas = summary["replicas"].values()
    capacity = [
        v["capacity_rps"] for v in replicas
        if isinstance(v.get("capacity_rps"), (int, float))
    ]
    if not capacity or sum(capacity) <= 0:
        return
    fleet["capacity_rps"] = round(sum(capacity), 2)
    points = []
    try:
        res, lines = rollup_mod.finest_rollup(log_dir)
        if res is not None:
            points = [
                (t, v)
                for t, v in rollup_mod.series(lines, "throughput_rps")
            ]
    except Exception:
        points = []
    if not points:
        # Nothing rolled yet: the beat timeline carries per-replica rps
        # at beat cadence; sum per timestamp bucket (1s) as a stand-in.
        per_t: dict = {}
        for entry in summary.get("timeline") or []:
            t, v = entry.get("t"), entry.get("rps")
            if isinstance(t, (int, float)) and isinstance(v, (int, float)):
                per_t[int(t)] = per_t.get(int(t), 0.0) + float(v)
        points = sorted(per_t.items())
    projection = rollup_mod.project_load(
        points, horizon_s=HEADROOM_HORIZON_S
    )
    if projection is None:
        return
    fleet["load_rps"] = projection["now_rps"]
    fleet["load_slope_rps_per_s"] = projection["slope_rps_per_s"]
    fleet["projected_rps"] = projection["projected_rps"]
    raw = (fleet["capacity_rps"] - projection["projected_rps"]) / (
        fleet["capacity_rps"]
    )
    fleet["headroom_frac"] = round(max(min(raw, 1.0), -1.0), 4)


#: Default per-stream read bound for the LIVE router view: enough for
#: hours of beats at the default cadence, constant-cost per refresh.
ROUTER_VIEW_TAIL_BYTES = 256 * 1024


def router_views(
    log_dir: str,
    *,
    now: Optional[float] = None,
    suspect_factor: float = 3.0,
    tail_bytes: Optional[int] = ROUTER_VIEW_TAIL_BYTES,
) -> dict:
    """The fleet router's live per-replica view (``Router.views_fn``):
    queue depth / inflight / measured per-batch step / windowed p99 /
    beat recency / dead suspicion, read from the same ``kind=serve``
    heartbeat streams ``aggregate_serve`` folds offline — the router
    balances on the numbers the offline tools render, by construction.
    ``now`` defaults to the wall clock (live semantics: a replica that
    stopped beating IS suspect, unlike the offline default). Reads are
    tail-bounded by default: a long-lived router refreshes up to every
    half second, and re-parsing the full history each time would grow
    routing cost with run age (``tail_bytes=None`` = full read)."""
    now = time.time() if now is None else float(now)
    summary = aggregate_serve(
        log_dir, now=now, suspect_factor=suspect_factor, max_timeline=1,
        tail_bytes=tail_bytes,
    )
    views = {}
    for proc, v in (summary.get("replicas") or {}).items():
        step = v.get("step_s_avg")
        views[int(proc)] = {
            "queued": v.get("queued"),
            "inflight": v.get("inflight"),
            "est_step_s": (
                float(step) if isinstance(step, (int, float)) else None
            ),
            "p99_ms": v.get("p99_ms"),
            "last_beat_unix": v.get("last_unix"),
            "beats": v.get("beats"),
            "final": v.get("final"),
            "suspect": v.get("suspect"),
            # Replica dtype stamp (ISSUE 20): the router's shadow
            # scorer keys its tolerance envelope on the (primary,
            # shadow) dtype pair it reads from here.
            "dtype": v.get("dtype"),
            "pid": v.get("pid"),
        }
    return views


def find_exemplars(log_dir: str) -> list:
    """The slow-request exemplar index under ``serve_traces/`` (newest
    last; torn/unreadable bundles skipped)."""
    root = os.path.join(log_dir, "serve_traces")
    out = []
    if not os.path.isdir(root):
        return out
    for name in sorted(os.listdir(root)):
        if not (name.startswith("slow_") and name.endswith(".json")):
            continue
        path = os.path.join(root, name)
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        doc["path"] = path
        out.append(doc)
    return out


def find_serve_manifests(log_dir: str) -> list:
    """Finalized-or-live ``kind=serve`` manifests in a log dir (the
    PR-10 artifact the telemetry layer grew around)."""
    out = []
    for path, doc in iter_manifests(log_dir):
        if doc.get("kind") == "serve":
            doc["path"] = path
            out.append(doc)
    return out
