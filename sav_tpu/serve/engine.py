"""AOT-compiled serving engine: bucketed dynamic batching over warm
executables.

The inference product the training stack feeds (ROADMAP item 2). One
engine owns:

- **A bucket ladder of AOT executables.** Startup lowers + compiles one
  inference executable per (model, bucket batch size) — request time
  never traces or compiles. With ``compilation_cache_dir`` set the
  compiles round-trip the persistent XLA cache
  (:mod:`sav_tpu.utils.compile_cache`): a restart re-reads them from
  disk in milliseconds, and :attr:`startup_report` counts cache hits vs
  from-scratch compiles so the warm path is assertable, not assumed.
- **A deadline-aware dynamic batcher** (:mod:`sav_tpu.serve.batcher`):
  bounded admission, batches formed into the largest bucket that fills
  before the earliest admitted deadline's slack expires, short batches
  padded to the bucket with a validity mask.
- **Host->device overlap**: batch N+1 is padded and placed on device by
  a :class:`~sav_tpu.data.feeder.DeviceFeeder` worker while the device
  executes batch N — the training input path's double-buffering rebased
  onto serving (place of N+1 strictly overlaps execution of N;
  tests/test_serve.py pins the ordering the same way
  tests/test_feeder.py does).
- **A latency ledger + run manifest**: p50/p95/p99 latency, throughput,
  queue depth, bucket occupancy, and padding waste finalize into a
  :class:`~sav_tpu.obs.manifest.RunManifest` so
  ``tools/regression_sentinel.py`` gates serving perf exactly like
  training perf (docs/serving.md).

Params restore **params-only** from any training checkpoint
(:meth:`sav_tpu.train.checkpoint.Checkpointer.restore_params_only` —
opt_state is never read, so serving HBM never holds optimizer moments),
and the model builds under the same tuned attention dispatch as
training (``attention_tune_cache`` winners apply at serving shapes too).

The wire format is uint8 end to end: requests carry
``[image_size, image_size, 3]`` uint8 rows
(:func:`sav_tpu.serve.preprocess.preprocess_request` shapes raw decoded
images), and the compiled program normalizes on device with the same op
the training ``device_preprocess`` path uses.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from sav_tpu.serve.batcher import (
    DynamicBatcher,
    FormedBatch,
    QueueFullError,
    ServeClosedError,
)
from sav_tpu.serve.bucketing import BucketLadder, default_ladder
from sav_tpu.serve.latency import LatencyLedger
from sav_tpu.serve.telemetry import ServeTelemetry, stamp


@dataclasses.dataclass
class ServeConfig:
    """Serving configuration (the inference twin of TrainConfig)."""

    model_name: str = "deit_s_patch16"
    num_classes: int = 1000
    image_size: int = 224
    compute_dtype: str = "bfloat16"
    # None = the measured three-way auto dispatch (sav_tpu/ops/attention.py);
    # the attn_tune cache's winners apply at serving shapes too.
    attention_backend: Optional[str] = None
    attention_tune_cache: Optional[str] = None
    model_overrides: Optional[dict] = None
    # Batch-size rungs, one AOT executable each. None = powers of two up
    # to max_batch (sav_tpu/serve/bucketing.py).
    buckets: Optional[list] = None
    max_batch: int = 8
    # Admission bound: submits past this many queued requests are
    # rejected (QueueFullError) instead of growing the latency tail.
    max_queue: int = 256
    # Default per-request latency budget; the batcher ships a batch no
    # later than deadline - est_step(bucket) (docs/serving.md).
    deadline_ms: float = 100.0
    # Placed batches buffered beyond the one executing (DeviceFeeder
    # depth — host->device transfer of batch N+1 overlaps execution of N).
    feed_depth: int = 2
    # Training checkpoint to serve (params-only restore; opt_state is
    # never materialized). None = fresh init (benches, smoke tests).
    checkpoint_dir: Optional[str] = None
    # Serve int8 quantized weights (docs/quantization.md): the float
    # (checkpoint-format) param tree converts through
    # sav_tpu.ops.quant.quantize_params into int8 kernels + per-channel
    # f32 scales, and every projection/FFN/head dot runs the int8 MXU
    # pipe (the attention core stays in compute_dtype). Param HBM is
    # ~half the bf16 arm's (startup_report["quant"] proves it); logits
    # track the bf16 arm within the pinned tolerance
    # (tests/test_quant.py parity gates). Works with any float source —
    # a --quant QAT checkpoint (matching train/serve numerics) or a
    # plain bf16 one (post-training quantization).
    quant_weights: bool = False
    # Declarative sharding layout (sav_tpu/parallel/layout.py): a
    # built-in name ('tpN' | '2dXxY' | ...) or a tools/mesh_tune.py
    # preset path. The engine then builds its mesh from the layout and
    # SHARDS THE SERVING PARAMS by the layout's specs — one big model
    # spans chips via TP instead of replicating (the ROADMAP item-3
    # prerequisite). None keeps the single-device default (replicate
    # engines for more chips).
    layout_preset: Optional[str] = None
    # Persistent XLA compile cache: a warm second start compiles nothing
    # from scratch (startup_report["compiled_from_scratch"] == 0).
    compilation_cache_dir: Optional[str] = None
    # Sink for the serving run manifest (None disables).
    log_dir: Optional[str] = None
    seed: int = 0
    # ---- serve telemetry (sav_tpu/serve/telemetry.py; docs/serving.md).
    # Per-request span tracing + live windowed metrics + SLO accounting
    # are in-memory even without a log_dir; heartbeats / slow-request
    # exemplars / anomaly captures need log_dir to land anywhere.
    telemetry: bool = True
    # Trailing window for the live p50/p99/throughput/queue view.
    telemetry_window_s: float = 30.0
    # Serve heartbeat cadence (kind=serve lines in fleet/proc_<i>.jsonl;
    # 0 disables the thread).
    heartbeat_secs: float = 5.0
    # Golden-probe cadence (sav_tpu/serve/quality.py; docs/quality.md):
    # every probe_every_s seconds an idle engine runs the checked-in
    # probe batch through the normal admission path and fingerprints
    # the logits. 0 disables the probe thread. Probes shed themselves
    # whenever live work is queued or in flight — they never evict a
    # live request.
    probe_every_s: float = 0.0
    # Completed request traces kept in the span ring.
    trace_ring: int = 256
    # Slow-request exemplar bundles dumped per run (serve_traces/).
    slow_exemplars: int = 8
    # Slow gate: latency beyond median + slow_sigma scaled MADs of the
    # live window flags a request as a slow exemplar (and arms the
    # anomaly profiler).
    slow_sigma: float = 4.0
    # SLO: deadline-hit-rate objective + Google-SRE two-window burn
    # alerting (docs/serving.md "SLO knobs").
    slo_target: float = 0.99
    slo_fast_window_s: float = 60.0
    slo_slow_window_s: float = 600.0
    slo_burn_threshold: float = 2.0
    # Anomaly-triggered bounded profiling (PR-7 AutoProfiler budget
    # machinery; trace window counted in completed batches).
    autoprof: bool = True
    autoprof_batches: int = 4
    autoprof_max: int = 2

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)

    @classmethod
    def from_json(cls, text: str) -> "ServeConfig":
        return cls(**json.loads(text))

    def ladder(self) -> BucketLadder:
        return BucketLadder(
            self.buckets if self.buckets else default_ladder(self.max_batch)
        )


def build_infer_fn(model, compute_dtype) -> Callable:
    """The serving step: uint8 batch -> masked f32 logits.

    Shared by :class:`ServeEngine` and the zoo ``--serve`` check
    (tools/zoo_tpu_check.py) so "servable" means exactly one program
    shape. Normalization runs on device
    (:func:`sav_tpu.ops.preprocess.normalize_images` — the same op the
    training ``device_preprocess`` path uses, so serve and train see
    identical numerics from the same uint8 wire bytes); padded rows are
    zeroed by the validity mask so the contract "padding never leaks
    into results" is visible in the program itself.
    """
    from sav_tpu.ops import preprocess as pp

    def infer(params, batch_stats, batch):
        images = batch["images"]
        if images.dtype != jnp.uint8:
            raise ValueError(
                f"serving wire format is uint8, got {images.dtype}; "
                "preprocess_request() keeps requests uint8 end to end"
            )
        x = pp.normalize_images(images, compute_dtype)
        variables = {"params": params}
        if batch_stats:
            variables["batch_stats"] = batch_stats
        logits = model.apply(variables, x, is_training=False)
        return logits.astype(jnp.float32) * batch["valid"][:, None]

    return infer


def _count_cache_entries(cache_dir: Optional[str]) -> Optional[int]:
    """Executable entries in the persistent compile cache (None when
    disabled) — the before/after delta across the AOT loop is the
    from-scratch compile count. jax writes a ``*-cache`` payload plus a
    ``*-atime`` access stamp per entry; only the payloads are entries
    (and the stamps are REWRITTEN on cache hits, so counting them would
    book a warm start as a recompile)."""
    if not cache_dir or not os.path.isdir(cache_dir):
        return 0 if cache_dir else None
    total = 0
    for _, _, files in os.walk(cache_dir):
        total += sum(1 for f in files if not f.endswith("-atime"))
    return total


class ServeEngine:
    """One model, one bucket ladder of warm executables, one batcher.

    Lifecycle: construction does all the heavy lifting (params restore,
    per-bucket AOT compile + warmup — :attr:`startup_report`);
    :meth:`start` opens admission and spins up the serving threads;
    :meth:`submit` returns a future per request; :meth:`stop` drains
    in-flight batches, fails still-queued requests, and finalizes the
    manifest. Context manager = start/stop.

    Test seams: ``place_hook`` fires on the feeder thread after batch
    placement is issued, ``execute_hook`` on the device loop before
    execution — the overlap-ordering proof instruments both (the
    tests/test_feeder.py technique).
    """

    def __init__(
        self,
        config: ServeConfig,
        *,
        model=None,
        params=None,
        batch_stats=None,
        mesh=None,
        manifest=None,
        place_hook: Optional[Callable[[FormedBatch], None]] = None,
        execute_hook: Optional[Callable[[FormedBatch], None]] = None,
        autoprof=None,
    ):
        self.config = config
        self.ladder = config.ladder()
        self.place_hook = place_hook
        self.execute_hook = execute_hook
        cache_before = _count_cache_entries(config.compilation_cache_dir)
        if config.compilation_cache_dir:
            from sav_tpu.utils.compile_cache import enable_persistent_cache

            # min_compile_time 0: jax's ~1s default floor is tuned for
            # training (don't litter the cache with trivial programs),
            # but a serving restart wants EVERY bucket executable back
            # from disk — a warm start must compile nothing from scratch.
            enable_persistent_cache(
                config.compilation_cache_dir, min_compile_time_secs=0.0
            )
        if config.attention_tune_cache:
            from sav_tpu.ops.attn_tuning import set_cache_path

            set_cache_path(config.attention_tune_cache)
        from sav_tpu.parallel.layout import (
            BoundLayout,
            layout_from_mesh,
            resolve_layout,
        )

        explicit_layout = resolve_layout(config.layout_preset)
        if explicit_layout is not None and -1 in dict(
            explicit_layout.mesh_axes
        ).values():
            # Serving pins wildcard axes to 1: a built-in name like
            # 'tp2' carries data=-1, and absorbing the host's spare
            # chips onto the data axis would both break the bucket
            # ladder's shard-divisibility (bucket 1 % data) and
            # contradict the serving default — one engine claims
            # exactly the chips its TP degree needs, replicate engines
            # for more. A preset that WANTS a data axis sizes it
            # explicitly.
            import dataclasses as _dc

            explicit_layout = _dc.replace(
                explicit_layout,
                mesh_axes=tuple(
                    (a, 1 if s == -1 else s)
                    for a, s in explicit_layout.mesh_axes
                ),
            )
        if mesh is None:
            if explicit_layout is not None:
                # Layout-stated mesh over exactly the chips it sizes: a
                # TP/2D layout spans chips with sharded params instead
                # of replicating.
                mesh = explicit_layout.create_mesh()
            else:
                # Serving default: one device per engine (replicate
                # engines for more chips). A multi-device mesh is
                # accepted when every bucket divides its batch axes
                # (validated below).
                from sav_tpu.parallel.mesh import create_mesh

                mesh = create_mesh({"data": 1}, devices=jax.devices()[:1])
        self.mesh = mesh
        self.layout = (
            explicit_layout if explicit_layout is not None
            else layout_from_mesh(mesh)
        )
        self._blayout = BoundLayout(self.layout, mesh)
        from sav_tpu.parallel.mesh import batch_axes

        baxes = batch_axes(mesh)
        shards = int(np.prod([mesh.shape[a] for a in baxes])) if baxes else 1
        bad = [b for b in self.ladder.buckets if b % shards]
        if bad:
            raise ValueError(
                f"buckets {bad} do not divide the mesh batch axes "
                f"({dict((a, mesh.shape[a]) for a in baxes)}); every "
                "bucket must shard evenly — adjust the ladder or serve "
                "on a single-device mesh"
            )
        self._batch_sharding = self._blayout.batch_sharding()
        self.compute_dtype = (
            jnp.bfloat16 if config.compute_dtype == "bfloat16" else jnp.float32
        )
        # The dtype stamp telemetry/heartbeats/status tools render: what
        # the *weights* are served in (docs/quantization.md).
        self.serve_dtype = (
            "int8" if config.quant_weights
            else ("bf16" if config.compute_dtype == "bfloat16" else "f32")
        )
        t0 = time.perf_counter()
        self._restore_model = None
        if model is None:
            from sav_tpu.models import create_model

            model_kwargs = dict(
                num_classes=config.num_classes,
                dtype=self.compute_dtype,
                backend=config.attention_backend,
                # 2D-TP layouts pin between-block activations (the same
                # seam the trainer threads; 1D propagates from params).
                layout=(
                    self._blayout if self.layout.tp_feature_axis else None
                ),
                **(config.model_overrides or {}),
            )
            model = create_model(
                config.model_name,
                quant="int8_serve" if config.quant_weights else None,
                **model_kwargs,
            )
            if config.quant_weights:
                # The restore twin: the same architecture in float form.
                # Its param tree is what training checkpoints (and
                # passed-in trees) hold; the int8 serving tree is derived
                # from it by quantize_params below.
                self._restore_model = create_model(
                    config.model_name, quant=None, **model_kwargs
                )
        elif config.quant_weights:
            raise ValueError(
                "quant_weights=True builds its own int8_serve/float model "
                "pair from the registry; pass model=None (an externally "
                "built int8_serve model can be served directly — its "
                "params are already quantized, so quant_weights adds "
                "nothing)"
            )
        self.model = model
        if self._restore_model is None:
            self._restore_model = model
        self._params, self._batch_stats, params_source = self._load_params(
            params, batch_stats
        )
        noise_scale = os.environ.get("SAV_CHAOS_NOISE_WEIGHTS")
        if noise_scale:
            # Chaos seam (docs/quality.md "Chaos"): deterministically
            # corrupt the FLOAT tree before any quantization, so a
            # planted-fault replica misbehaves identically on every
            # arm — the shadow-agreement / probe-mismatch detection
            # tests and the r20 battery plant faults through this.
            from sav_tpu.serve.quality import noise_params

            self._params = noise_params(self._params, float(noise_scale))
        self._quant_report: Optional[dict] = None
        if config.quant_weights:
            self._params, self._quant_report = self._quantize_params_tree(
                self._params
            )
        # The serving program additionally returns per-row output
        # digests (top-1 / margin / entropy) computed in-graph — they
        # ride the existing result fetch, so quality telemetry costs
        # zero extra device syncs on the request path (SAV126;
        # docs/quality.md).
        from sav_tpu.serve.quality import digested_infer_fn

        self._infer = jax.jit(
            digested_infer_fn(build_infer_fn(model, self.compute_dtype))
        )
        # ---- AOT: one executable per bucket, warmed from the cache ----
        compile_t0 = time.perf_counter()
        cache_pre_aot = _count_cache_entries(config.compilation_cache_dir)
        self._executables: dict = {}
        for bucket in self.ladder.buckets:
            lowered = self._infer.lower(
                self._params, self._batch_stats, self._abstract_batch(bucket)
            )
            self._executables[bucket] = lowered.compile()
        compile_s = time.perf_counter() - compile_t0
        cache_after = _count_cache_entries(config.compilation_cache_dir)
        # Per-bucket executable HBM estimate (ride-along fix: the report
        # used to say nothing about how much device memory each rung
        # costs, so a ladder that barely fit was invisible until the
        # allocator said otherwise). XLA's own memory_analysis when the
        # backend provides one; an analytic floor (params + wire input +
        # f32 logits) otherwise — the source is recorded so a reader
        # knows which number they are trusting.
        self._param_bytes = sum(
            int(leaf.size) * jnp.dtype(leaf.dtype).itemsize
            for leaf in jax.tree.leaves((self._params, self._batch_stats))
        )
        bucket_hbm: dict = {}
        hbm_source = "analytic"
        s = config.image_size
        for bucket in self.ladder.buckets:
            est = None
            try:
                ma = self._executables[bucket].memory_analysis()
                est = int(
                    getattr(ma, "argument_size_in_bytes", 0)
                    + getattr(ma, "output_size_in_bytes", 0)
                    + getattr(ma, "temp_size_in_bytes", 0)
                    + getattr(ma, "generated_code_size_in_bytes", 0)
                )
            except Exception:
                est = None
            if est:
                hbm_source = "memory_analysis"
            else:
                est = (
                    self._param_bytes
                    + bucket * s * s * 3
                    + bucket * config.num_classes * 4
                )
            bucket_hbm[str(bucket)] = est
        # Warmup: one execution per bucket seeds the batcher's per-bucket
        # step-time estimates (and faults in any lazy backend state).
        self._step_est: dict = {}
        warmup_t0 = time.perf_counter()
        for bucket in self.ladder.buckets:
            placed = self._place_host_batch(
                np.zeros(
                    (bucket, config.image_size, config.image_size, 3),
                    np.uint8,
                ),
                np.ones((bucket,), np.float32),
            )
            t = time.perf_counter()
            jax.block_until_ready(
                self._executables[bucket](
                    self._params, self._batch_stats, placed
                )
            )
            self._step_est[bucket] = time.perf_counter() - t
        scratch = (
            cache_after - cache_pre_aot
            if (cache_after is not None and cache_pre_aot is not None)
            else None
        )
        self.startup_report = {
            "model": config.model_name,
            "layout": self.layout.name,
            "buckets": list(self.ladder.buckets),
            "params_source": params_source,
            "dtype": self.serve_dtype,
            "param_bytes": self._param_bytes,
            "bucket_hbm_bytes": bucket_hbm,
            "bucket_hbm_source": hbm_source,
            "startup_s": round(time.perf_counter() - t0, 3),
            "compile_s": round(compile_s, 3),
            "warmup_s": round(time.perf_counter() - warmup_t0, 3),
            "warmup_step_s": {
                str(b): round(s, 5) for b, s in self._step_est.items()
            },
            "cache_entries_before": cache_before,
            "cache_entries_after": cache_after,
            # The warm-start proof: from-scratch compiles this startup
            # (persistent-cache writes during the AOT loop) vs hits.
            "compiled_from_scratch": scratch,
            "cache_hits": (
                len(self.ladder.buckets) - scratch
                if scratch is not None else None
            ),
        }
        if self._quant_report is not None:
            # The HBM-density proof: int8 serving bytes vs what the same
            # tree would weigh in bf16 (docs/quantization.md).
            self.startup_report["quant"] = self._quant_report
        self.manifest = manifest
        if self.manifest is None and config.log_dir:
            from sav_tpu.obs.manifest import RunManifest

            self.manifest = RunManifest(
                os.path.join(
                    config.log_dir,
                    f"manifest-serve-{time.strftime('%Y%m%d-%H%M%S')}"
                    f"-{os.getpid()}.json",
                ),
                kind="serve",
                config=dataclasses.asdict(config),
            )
            self.manifest.begin()
        if self.manifest is not None:
            self.manifest.note("serve_startup", self.startup_report)
            # Same provenance note the trainer stamps: "which layout was
            # this serving" reads from notes.layout alone.
            self.manifest.note("layout", self.layout.describe(self.mesh))
            if self._quant_report is not None:
                # notes.quant: "which arm was this" reads from here alone
                # (regression_sentinel keys int8 records off it).
                self.manifest.note(
                    "quant", dict(self._quant_report, weights="int8")
                )
        # ---- quality: digest windows + golden-probe ledger -------------
        # Always constructed (the digests ride every executable), even
        # without telemetry — tests and embedders can read
        # quality_snapshot() directly. Stdlib-side folds only
        # (sav_tpu/obs/quality.py); the probe thread spins up in
        # start() when probe_every_s > 0.
        from sav_tpu.obs.quality import ProbeLedger, QualityTracker

        self._quality = QualityTracker()
        self._probe_ledger = ProbeLedger()
        self._probe = None
        # ---- telemetry: spans + live windows + heartbeats + SLO --------
        self._telemetry: Optional[ServeTelemetry] = None
        self._watermark = None
        if config.telemetry:
            writer = None
            if config.log_dir and config.heartbeat_secs > 0:
                from sav_tpu.obs.fleet import (
                    HeartbeatWriter,
                    resolve_identity,
                )

                proc, procs = resolve_identity()
                writer = HeartbeatWriter(
                    config.log_dir,
                    process_index=proc,
                    process_count=procs,
                )
            if autoprof is None and config.autoprof and config.log_dir:
                from sav_tpu.obs.autoprof import AutoProfiler
                from sav_tpu.obs.fleet import resolve_identity

                autoprof = AutoProfiler(
                    config.log_dir,
                    trace_steps=config.autoprof_batches,
                    max_captures=config.autoprof_max,
                    process_index=resolve_identity()[0],
                    manifest=self.manifest,
                )
            from sav_tpu.obs.memdump import HbmWatermark

            self._watermark = HbmWatermark()

            def _hbm() -> Optional[dict]:
                self._watermark.observe()
                if not self._watermark.samples:
                    return None
                return {
                    "hbm_bytes_in_use": self._watermark.in_use_bytes,
                    "hbm_peak_bytes": self._watermark.peak_bytes,
                }

            self._telemetry = ServeTelemetry(
                config.log_dir,
                dtype=self.serve_dtype,
                trace_ring=config.trace_ring,
                exemplar_max=config.slow_exemplars,
                exemplar_sigma=config.slow_sigma,
                window_s=config.telemetry_window_s,
                heartbeat_secs=config.heartbeat_secs,
                slo_target=config.slo_target,
                slo_fast_window_s=config.slo_fast_window_s,
                slo_slow_window_s=config.slo_slow_window_s,
                slo_burn_threshold=config.slo_burn_threshold,
                writer=writer,
                autoprof=autoprof,
                queue_stats_fn=lambda: (
                    self._batcher.stats() if self._batcher else {}
                ),
                hbm_fn=_hbm,
                # Quality fields on every kind=serve beat (ISSUE 20):
                # digest drift gates + probe fingerprint state, folded
                # at beat cadence — never per request.
                quality_fn=self.quality_snapshot,
                # Measured capacity stamp (ISSUE 19): the ladder's top
                # rung over the windowed step — beats publish
                # capacity_rps, the fleet fold sums it into headroom.
                max_batch=self.ladder.max_batch,
            )
        self.ledger = LatencyLedger(
            window=(
                self._telemetry.window
                if self._telemetry is not None else None
            )
        )
        self._batcher: Optional[DynamicBatcher] = None
        self._feeder = None
        self._device_thread: Optional[threading.Thread] = None
        self._started = False
        self._stopped = False
        self._errors = 0

    # ------------------------------------------------------------ startup

    def _load_params(self, params, batch_stats) -> tuple:
        """(params, batch_stats, source): passed-in, params-only
        checkpoint restore, or fresh init — placed by the layout's param
        specs (replicated under the default DP layout; TP/2D layouts
        shard the serving weights over the mesh).

        Always the FLOAT (checkpoint-format) tree, built against
        ``self._restore_model`` — under ``quant_weights`` the caller
        converts it to the int8 serving tree afterwards
        (:meth:`_quantize_params_tree`), so every params source
        (checkpoint / passed / fresh init) quantizes identically."""
        if params is not None:
            def place(tree):
                if not tree:
                    return tree
                return jax.tree.map(
                    jax.device_put, tree, self._blayout.param_shardings(tree)
                )

            return place(params), place(batch_stats or {}), "passed"
        abstract = self._abstract_state()
        if self.config.checkpoint_dir:
            from sav_tpu.train.checkpoint import Checkpointer

            ckpt = Checkpointer(self.config.checkpoint_dir, read_only=True)
            try:
                restored = ckpt.restore_params_only(abstract)
            finally:
                ckpt.close()
            if restored is None:
                raise FileNotFoundError(
                    "no checkpoint found in "
                    f"{self.config.checkpoint_dir!r}"
                )
            return (
                restored["params"],
                restored.get("batch_stats") or {},
                f"checkpoint:{self.config.checkpoint_dir}",
            )
        # Fresh init (benches/smoke): jitted, materialized on the mesh
        # directly under the layout's shardings.
        rng = jax.random.PRNGKey(self.config.seed)
        s = self.config.image_size

        def init_fn(rng):
            dummy = jnp.zeros((1, s, s, 3), self.compute_dtype)
            variables = dict(
                self._restore_model.init(
                    {"params": rng}, dummy, is_training=False
                )
            )
            return {
                "params": variables.pop("params"),
                "batch_stats": variables.pop("batch_stats", {}),
            }

        out_shardings = self._blayout.param_shardings(
            jax.eval_shape(init_fn, rng)
        )
        built = jax.jit(init_fn, out_shardings=out_shardings)(rng)
        return built["params"], built["batch_stats"], "init"

    def _abstract_state(self) -> dict:
        """Abstract ``{"params", "batch_stats", "step"}`` template for the
        params-only restore (shapes from a traced init — no weights are
        materialized to build it), each leaf carrying its layout
        sharding so the restore materializes sharded."""
        rng = jax.random.PRNGKey(0)
        s = self.config.image_size

        def init_fn(rng):
            dummy = jnp.zeros((1, s, s, 3), self.compute_dtype)
            return dict(
                self._restore_model.init(
                    {"params": rng}, dummy, is_training=False
                )
            )

        shapes = jax.eval_shape(init_fn, rng)
        template = {
            "params": shapes["params"],
            "batch_stats": shapes.get("batch_stats", {}),
            "step": jax.ShapeDtypeStruct((), jnp.int32),
        }
        shardings = self._blayout.param_shardings(template)
        return jax.tree.map(
            lambda sds, sh: jax.ShapeDtypeStruct(
                sds.shape, sds.dtype, sharding=sh
            ),
            template,
            shardings,
        )

    def _quantize_params_tree(self, float_params) -> tuple:
        """Float tree → the int8+scales serving tree, jitted with the
        layout's ``out_shardings`` so the int8 kernels materialize
        sharded exactly like their float twins (same tree paths — the
        SpecLayout rules key on names); the tiny ``scale`` leaves match
        no rule and replicate. Returns ``(quantized, report)`` where the
        report is the HBM-density proof: serving bytes vs the bf16
        weight of the same float tree."""
        from sav_tpu.ops.quant import quantize_params

        s = self.config.image_size

        def init_fn(rng):
            dummy = jnp.zeros((1, s, s, 3), self.compute_dtype)
            return dict(
                self.model.init({"params": rng}, dummy, is_training=False)
            )["params"]

        template = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
        shardings = self._blayout.param_shardings(template)
        quantized = jax.jit(
            lambda p: quantize_params(p, template), out_shardings=shardings
        )(float_params)
        bf16_equiv = sum(
            int(leaf.size) * 2 for leaf in jax.tree.leaves(float_params)
        )
        serving = sum(
            int(leaf.size) * jnp.dtype(leaf.dtype).itemsize
            for leaf in jax.tree.leaves(quantized)
        )
        report = {
            "weights_dtype": "int8",
            "param_bytes_serving": int(serving),
            "param_bytes_bf16_equiv": int(bf16_equiv),
            "param_bytes_ratio": round(serving / max(bf16_equiv, 1), 4),
        }
        return quantized, report

    def _abstract_batch(self, bucket: int) -> dict:
        s = self.config.image_size
        return {
            "images": jax.ShapeDtypeStruct(
                (bucket, s, s, 3), jnp.uint8, sharding=self._batch_sharding
            ),
            "valid": jax.ShapeDtypeStruct(
                (bucket,), jnp.float32, sharding=self._batch_sharding
            ),
        }

    # ------------------------------------------------------------ serving

    def start(self) -> "ServeEngine":
        if self._started:
            raise RuntimeError("engine already started")
        from sav_tpu.data.feeder import DeviceFeeder

        self._batcher = DynamicBatcher(
            self.ladder,
            step_time_fn=self._estimate_step,
            max_queue=self.config.max_queue,
            default_deadline_s=self.config.deadline_ms / 1e3,
        )
        self._feeder = DeviceFeeder(
            self._formed_batches(),
            self._place_formed,
            depth=self.config.feed_depth,
            name="serve-feeder",
        )
        self._device_thread = threading.Thread(
            target=self._device_loop, name="serve-device-loop", daemon=True
        )
        self._started = True
        self.ledger.start()
        if self._telemetry is not None:
            self._telemetry.start()
        self._device_thread.start()
        if self.config.probe_every_s > 0:
            from sav_tpu.serve.quality import ProbeRunner

            self._probe = ProbeRunner(
                self,
                self._probe_ledger,
                every_s=self.config.probe_every_s,
                log_dir=self.config.log_dir,
            ).start()
        return self

    def _estimate_step(self, bucket: int) -> float:
        """Per-bucket device seconds: warmup-seeded, EMA-updated from
        real batches (single writer: the device loop)."""
        return self._step_est.get(bucket, 0.0)

    def _formed_batches(self):
        """Batcher drain as the feeder's source iterator (runs on the
        feeder worker thread — the drain wait and the device_put of the
        next batch both overlap the device loop's execution)."""
        while True:
            formed = self._batcher.next_batch()
            if formed is None:
                return
            yield formed

    def _place_host_batch(self, images: np.ndarray, valid: np.ndarray) -> dict:
        return {
            "images": jax.device_put(images, self._batch_sharding),
            "valid": jax.device_put(valid, self._batch_sharding),
        }

    def _place_formed(self, formed: FormedBatch):
        """Pad to the bucket + issue the sharded device_put (feeder
        worker thread — this is the host->device stage that overlaps
        batch N's execution)."""
        try:
            s = self.config.image_size
            n = len(formed.requests)
            images = np.zeros((formed.bucket, s, s, 3), np.uint8)
            for i, request in enumerate(formed.requests):
                images[i] = request.payload
            valid = np.zeros((formed.bucket,), np.float32)
            valid[:n] = 1.0
            placed = self._place_host_batch(images, valid)
            if self._telemetry is not None:
                t_placed = self._telemetry.clock()
                for request in formed.requests:
                    stamp(request.trace, "placed", t_placed)
            if self.place_hook is not None:
                self.place_hook(formed)
            return formed, placed
        except BaseException as e:
            # A failed placement must not strand its submitters on
            # never-resolving futures; fail them, then let the feeder
            # propagate the error to the device loop.
            self._batcher.mark_completed()
            for request in formed.requests:
                if not request.future.done():
                    request.future.set_exception(e)
            raise

    def _device_loop(self):
        """Consume placed batches, execute, distribute results. The ONE
        device sync per batch (``np.asarray`` on the logits) lives here —
        after execution, outside the batcher drain (savlint SAV115)."""
        try:
            for formed, placed in self._feeder:
                t0 = time.perf_counter()
                try:
                    if self._telemetry is not None:
                        t_dispatch = self._telemetry.clock()
                        for request in formed.requests:
                            stamp(request.trace, "dispatched", t_dispatch)
                    if self.execute_hook is not None:
                        # After the dispatched stamp: a hook that holds
                        # the batch "on device" (the overlap/anomaly
                        # tests) books as device time, not dispatch wait.
                        self.execute_hook(formed)
                    out = self._executables[formed.bucket](
                        self._params, self._batch_stats, placed
                    )
                    # One fetch for the whole output tree: the logits
                    # plus the in-graph digest leaves land in the same
                    # transfer the logits alone used to (SAV126's
                    # zero-extra-syncs contract).
                    host = jax.device_get(out)
                    if self._telemetry is not None:
                        t_exec = self._telemetry.clock()
                        for request in formed.requests:
                            stamp(request.trace, "executed", t_exec)
                    self._complete(formed, host, t0)
                except Exception as e:  # noqa: BLE001 — fail batch, serve on
                    self._errors += 1
                    self._batcher.mark_completed()
                    for request in formed.requests:
                        if not request.future.done():
                            request.future.set_exception(e)
        except Exception:  # noqa: BLE001 — feeder/placement died
            # _place_formed already failed the in-flight batch's futures;
            # close() fails everything still queued, so no submitter is
            # left blocked on a future nothing will resolve.
            self._errors += 1
            if self._batcher is not None:
                self._batcher.close()

    def _complete(self, formed: FormedBatch, host: dict, t0: float):
        self._batcher.mark_completed()
        done_t = time.perf_counter()
        step_s = done_t - t0
        logits = host["logits"]
        # EMA keeps the batcher's dispatch-by estimate tracking the
        # hardware (warmup seeds it; single writer: this thread).
        prev = self._step_est.get(formed.bucket, step_s)
        self._step_est[formed.bucket] = 0.8 * prev + 0.2 * step_s
        now = time.monotonic()
        telemetry = self._telemetry
        latencies, overruns = [], []
        for i, request in enumerate(formed.requests):
            if telemetry is not None:
                stamp(request.trace, "depadded", telemetry.clock())
            request.future.set_result(logits[i])
            if telemetry is not None:
                stamp(request.trace, "completed", telemetry.clock())
            latencies.append(now - request.enqueue_t)
            overruns.append(now - request.deadline_t)
        n = len(formed.requests)
        # Digest rows into the quality window: host values, bounded
        # deque appends only — the gate math waits for the beat thread
        # (obs/quality.py; SAV126).
        self._quality.observe_digests(
            host["top1"][:n].tolist(),
            host["margin"][:n].tolist(),
            host["entropy"][:n].tolist(),
            num_classes=self.config.num_classes,
        )
        self.ledger.observe_batch(
            bucket=formed.bucket,
            latencies_s=latencies,
            overruns_s=overruns,
            queue_depth=formed.queue_depth,
            step_s=step_s,
        )
        if telemetry is not None:
            # Ring + SLO + the slow-exemplar/anomaly gates — host
            # bookkeeping on the window the ledger just fed (SAV116).
            telemetry.observe_completed(
                formed,
                latencies_s=latencies,
                overruns_s=overruns,
                step_s=step_s,
            )

    def submit(
        self,
        image: np.ndarray,
        *,
        deadline_ms: Optional[float] = None,
        trace_id=None,
    ):
        """Admit one preprocessed uint8 request; returns its future.

        ``image`` must be ``[image_size, image_size, 3]`` uint8 (use
        :func:`sav_tpu.serve.preprocess.preprocess_request` /
        :meth:`submit_raw` for raw decoded images). Raises
        :class:`~sav_tpu.serve.batcher.QueueFullError` on an admission
        reject (counted on the ledger).

        ``trace_id`` (ISSUE 16): a router-propagated fleet trace id —
        ``begin_trace`` ADOPTS it instead of minting a replica-local
        one, so this replica's spans join the fleet-wide trace.
        Replica-local serving (no id) is unchanged.
        """
        if not self._started or self._stopped:
            raise ServeClosedError("engine is not serving (start() first)")
        image = np.asarray(image)  # savlint: disable=SAV115 -- request validation on the submitted HOST image; no device value is in reach here
        s = self.config.image_size
        if image.shape != (s, s, 3) or image.dtype != np.uint8:
            raise ValueError(
                f"expected a [{s}, {s}, 3] uint8 request, got "
                f"{image.shape} {image.dtype}; run preprocess_request() "
                "(or submit_raw) first"
            )
        deadline_s = (
            deadline_ms / 1e3 if deadline_ms is not None
            else self.config.deadline_ms / 1e3
        )
        trace = (
            self._telemetry.begin_trace(deadline_s, rid=trace_id)
            if self._telemetry is not None else None
        )
        try:
            return self._batcher.submit(
                image,
                deadline_s=deadline_s,
                trace=trace,
            )
        except QueueFullError:
            self.ledger.observe_rejected()
            if self._telemetry is not None:
                self._telemetry.observe_shed()
            raise

    def submit_raw(
        self, image: np.ndarray, *, deadline_ms: Optional[float] = None
    ):
        """``submit`` for raw decoded images: center-crop + bicubic
        resize on the host (uint8 in, uint8 out), then admit."""
        from sav_tpu.serve.preprocess import preprocess_request

        return self.submit(
            preprocess_request(image, self.config.image_size),
            deadline_ms=deadline_ms,
        )

    # ----------------------------------------------------------- shutdown

    def drain(self, timeout_s: float = 30.0, *, poll_s: float = 0.02) -> bool:
        """Wait until every ACCEPTED request has resolved (queue empty,
        no drained batch still on the device loop) — the graceful half
        of leaving a fleet: a replica told to go away (SIGTERM from the
        pool, a weight swap) stops ADMITTING first (its server closes
        the listener), drains here, then :meth:`stop`s — nothing it
        accepted is failed by its own shutdown. Returns True when fully
        drained, False on timeout (stop() then fails the stragglers
        loudly). Host-side polling only — no device sync beyond the
        device loop's own."""
        if self._batcher is None:
            return True
        deadline = time.monotonic() + float(timeout_s)
        while self._batcher.pending() > 0:
            if time.monotonic() >= deadline:
                return False
            time.sleep(poll_s)
        return True

    def stop(
        self,
        timeout_s: float = 30.0,
        *,
        error: Optional[BaseException] = None,
    ) -> dict:
        """Drain in-flight batches, fail queued requests, finalize the
        manifest. Returns the final serving summary. Idempotent.

        ``error`` is the exception the caller is unwinding on (the
        context manager passes it through): the manifest then finalizes
        with that exception's outcome, NOT ``ok`` — a run whose driver
        died mid-serve must never enter the sentinel history as a
        healthy serving baseline built from the few requests that
        happened to finish (finalize is first-wins, so a later error
        finalize by the caller would be a no-op).
        """
        if self._stopped:
            return self.ledger.summary()
        self._stopped = True
        if self._probe is not None:
            # Before the batcher closes: the probe thread must not be
            # mid-submit when admission shuts, and its ledger state must
            # be final before telemetry's close() emits the final
            # quality beat (the leave-the-failing-fingerprint-on-disk
            # contract, docs/quality.md).
            self._probe.close()
        if self._batcher is not None:
            self._batcher.close()
        if self._device_thread is not None:
            self._device_thread.join(timeout=timeout_s)
        if self._feeder is not None:
            self._feeder.close()
        summary = self.ledger.summary()
        if error is not None:
            from sav_tpu.obs.manifest import classify_exception

            outcome, detail = classify_exception(error), repr(error)
        elif self._errors:
            outcome, detail = "error", f"{self._errors} batch(es) failed"
        else:
            outcome, detail = "ok", None
        tele_summary = None
        if self._telemetry is not None:
            if self._watermark is not None:
                try:
                    self._watermark.finalize()
                except Exception:
                    pass
            tele_summary = self._telemetry.close(outcome)
        if self.manifest is not None:
            metrics = self.ledger.flat_metrics()
            if self.config.quant_weights:
                # Flat marker so run records are filterable by arm even
                # when the notes were stripped (sentinel isolation).
                metrics["serve/quant_weights"] = 1.0
            if self.startup_report.get("compiled_from_scratch") is not None:
                metrics["serve/compiled_from_scratch"] = float(
                    self.startup_report["compiled_from_scratch"]
                )
            self.manifest.note("serve_summary", summary)
            if tele_summary is not None:
                slo = tele_summary.get("slo") or {}
                # SLO facts flow manifest -> normalize_run_record ->
                # sentinel (slo_hit_frac higher-better); absent on
                # zero-request runs — skipped, never zero-filled.
                if isinstance(slo.get("hit_frac"), (int, float)):
                    metrics["serve/slo_hit_frac"] = float(slo["hit_frac"])
                if isinstance(slo.get("burn_rate"), (int, float)):
                    metrics["serve/burn_rate"] = float(slo["burn_rate"])
                metrics["serve/shed"] = float(tele_summary.get("shed", 0))
                self.manifest.note("serve_telemetry", {
                    "slo": slo,
                    "window": tele_summary.get("window"),
                    "exemplars": tele_summary.get("exemplars"),
                    "heartbeats": tele_summary.get("heartbeats"),
                    "traced": tele_summary.get("traced"),
                    "overhead_s": tele_summary.get("overhead_s"),
                    "autoprof": tele_summary.get("autoprof"),
                })
                if tele_summary.get("alerts"):
                    # notes.alerts: which rules fired and how many
                    # episodes — "what paged during this run" reads
                    # from the manifest alone (ISSUE 19).
                    self.manifest.note(
                        "alerts", tele_summary["alerts"]
                    )
            qsnap = self.quality_snapshot()
            if qsnap.get("n") or qsnap.get("probe_runs"):
                # notes.quality + the sentinel-facing probe metric:
                # "what did this run predict and did the probe hold"
                # reads from the manifest alone. probe_ok_frac is
                # absent when no probe ran — skipped, never
                # zero-filled (the attention_core_frac contract).
                self.manifest.note("quality", qsnap)
                if isinstance(qsnap.get("probe_ok_frac"), (int, float)):
                    metrics["serve/probe_ok_frac"] = float(
                        qsnap["probe_ok_frac"]
                    )
            if (
                self._watermark is not None
                and self._watermark.source is not None
            ):
                # source "device-stats" on accelerators; finalize()'s
                # "live-arrays" backfill keeps the field present on CPU.
                metrics["serve/hbm_peak_bytes"] = float(
                    self._watermark.peak_bytes
                )
            self.manifest.finalize(outcome, error=detail, metrics=metrics)
        return summary

    def __enter__(self) -> "ServeEngine":
        return self.start() if not self._started else self

    def __exit__(self, exc_type, exc, tb):
        self.stop(error=exc)
        return False

    def quality_snapshot(self) -> dict:
        """The quality fields one heartbeat (and the manifest's
        ``notes.quality``) carries: digest drift gates + probe ledger
        state. Host bookkeeping only — named for savlint SAV126's
        audit set, which proves no device sync ever hides in here."""
        out = self._quality.snapshot()
        out.update(self._probe_ledger.snapshot())
        return out

    def stats(self) -> dict:
        out = {"ledger": self.ledger.summary(), "errors": self._errors}
        qsnap = self.quality_snapshot()
        if qsnap.get("n") or qsnap.get("probe_runs"):
            out["quality"] = qsnap
        if self._batcher is not None:
            out["batcher"] = self._batcher.stats()
        if self._feeder is not None:
            out["feeder"] = self._feeder.stats()
        if self._telemetry is not None:
            # The live mid-run view: windowed percentiles (None before
            # the first completed batch — never an exception) + SLO burn.
            out["live"] = self.ledger.live()
            out["slo"] = self._telemetry.slo.state()
            out["telemetry"] = self._telemetry.stats()
        return out
