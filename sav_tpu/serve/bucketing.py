"""Batch-size bucket ladder for the AOT-compiled serving engine.

Serving traffic arrives one request at a time, but the device wants big
static shapes: XLA compiles one executable per input shape, and a fresh
shape at request time would pay a full compile mid-traffic. The ladder is
the contract between the two worlds — a small fixed set of batch sizes
(default: powers of two), one AOT-compiled executable each, every dynamic
batch padded up to the smallest bucket that holds it. Padding is wasted
compute; the ladder's geometry bounds it (a power-of-two ladder wastes
<50% worst-case, and the latency ledger reports the *measured* waste so
the bound is checked, not assumed — docs/serving.md).

Stdlib-only: the batcher and its tests drive this without jax.
"""

from __future__ import annotations

from typing import Sequence


def default_ladder(max_batch: int) -> list:
    """Powers of two up to and including ``max_batch``.

    ``max_batch`` itself is always a rung (even when not a power of two)
    so configured capacity is reachable.
    """
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    rungs = []
    b = 1
    while b < max_batch:
        rungs.append(b)
        b *= 2
    rungs.append(max_batch)
    return rungs


class BucketLadder:
    """Sorted, validated batch-size rungs with the two lookups serving
    needs: the smallest bucket holding ``n`` requests (for padding) and
    the largest bucket a hot queue can fill outright (for draining)."""

    def __init__(self, buckets: Sequence[int]):
        rungs = sorted(set(int(b) for b in buckets))
        if not rungs:
            raise ValueError("bucket ladder must have at least one rung")
        if rungs[0] < 1:
            raise ValueError(f"bucket sizes must be >= 1, got {rungs[0]}")
        self.buckets = tuple(rungs)

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, n: int) -> int:
        """Smallest bucket >= ``n`` (the padding target for a batch of
        ``n`` real requests). ``n`` above the top rung is a caller bug —
        the batcher never forms more than ``max_batch``."""
        if n < 1:
            raise ValueError(f"need at least one request, got {n}")
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(
            f"batch of {n} exceeds the top bucket {self.max_batch}"
        )

    def largest_fillable(self, n: int) -> int:
        """Largest bucket <= ``n`` — what a queue holding ``n`` requests
        can fill without padding; the smallest rung when even that does
        not fill."""
        filled = self.buckets[0]
        for b in self.buckets:
            if b <= n:
                filled = b
        return filled


def padding_waste(n_real: int, bucket: int) -> float:
    """Fraction of the bucket's rows that are padding."""
    if bucket < n_real:
        raise ValueError(f"bucket {bucket} smaller than batch {n_real}")
    return (bucket - n_real) / bucket
