"""Deadline-aware dynamic batcher: bounded queue -> bucketed batches.

The serving engine's admission + batch-forming layer. Requests enter a
bounded FIFO (admission control: a full queue rejects loudly instead of
growing an unbounded latency tail, and a request whose deadline the
projected queue wait already blows is SHED at submit —
:class:`DeadlineInfeasibleError` — rather than served as a guaranteed
miss); a drain loop groups them into the **largest ladder bucket that
fills before the earliest admitted deadline's slack expires**:

- Hot queue: the drain grabs everything already waiting, up to the top
  bucket — full batches, zero added latency, maximum throughput.
- Trickle traffic: the drain *waits* for more requests, but only while
  the earliest deadline in the forming batch still leaves room for the
  batch's own device step — at ``deadline - est_step(bucket)`` it ships
  whatever it has, padded up to the current bucket.

The deadline guarantee this policy pins (tests/test_serve.py): a batch
is dispatched no later than ``earliest_deadline - est_step(bucket)``, so
a request finishes past its deadline by at most the *actual* device step
time of its bucket — one bucket step, never an unbounded queue wait.
``est_step`` comes from the engine's measured per-bucket warmup times
(EMA-updated as traffic flows), so the estimate tracks the hardware.

Results travel on :class:`ServeFuture` — a minimal set-once future the
engine completes from its device loop (one result set per request; the
completion path, not this module, owns the single per-batch device
sync). Stdlib-only, injectable clock; the jax half lives in
:mod:`sav_tpu.serve.engine`.

savlint SAV115 owns this module's hot functions (``submit`` /
``next_batch``): a ``device_get`` or implicit ``float(device_scalar)``
in the admission/drain path would serialize every request behind a
pipeline drain — the serving twin of SAV101's training-loop contract.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any, Callable, Optional

from sav_tpu.serve.bucketing import BucketLadder
from sav_tpu.serve.telemetry import stamp


class QueueFullError(RuntimeError):
    """Admission rejected: the bounded request queue is at capacity."""


class DeadlineInfeasibleError(QueueFullError):
    """Admission rejected: the projected queue+dispatch wait already
    exceeds the request's deadline — serving it would burn a device
    step on a guaranteed miss. Subclasses :class:`QueueFullError` so
    load-shedding callers handle both reject shapes in one place."""


class ServeClosedError(RuntimeError):
    """The engine was stopped with this request still pending."""


class ServeFuture:
    """Set-once result slot the submitter blocks on.

    ``result(timeout)`` returns the engine's per-request output (host
    numpy row) or re-raises the engine-side failure; a timeout raises
    ``TimeoutError`` without consuming the slot.
    """

    def __init__(self):
        self._done = threading.Event()
        self._value: Any = None
        self._error: Optional[BaseException] = None

    def set_result(self, value: Any) -> None:
        self._value = value
        self._done.set()

    def set_exception(self, error: BaseException) -> None:
        self._error = error
        self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> Any:
        if not self._done.wait(timeout):
            raise TimeoutError("serve request still pending")
        if self._error is not None:
            raise self._error
        return self._value


@dataclasses.dataclass
class ServeRequest:
    payload: Any  # preprocessed host input (uint8 [H, W, 3] row)
    deadline_s: float  # latency budget from submit time
    enqueue_t: float
    future: ServeFuture
    # Per-request span record (sav_tpu/serve/telemetry.py RequestTrace);
    # None when telemetry is off. Stamps are host-clock appends only —
    # the drain's tracing cost is one list append per stage (SAV116).
    trace: Any = None

    @property
    def deadline_t(self) -> float:
        return self.enqueue_t + self.deadline_s


@dataclasses.dataclass
class FormedBatch:
    """One drained batch: the real requests (<= bucket), the bucket they
    pad to, and drain-time telemetry for the latency ledger."""

    requests: list
    bucket: int
    queue_depth: int
    formed_t: float


class DynamicBatcher:
    """Bounded request queue + deadline-aware bucket drain.

    Args:
      ladder: the engine's compiled bucket ladder.
      step_time_fn: bucket -> estimated device seconds for one batch of
        that bucket (the engine's measured warmup/EMA estimate). The
        drain subtracts it from the earliest deadline to find the
        latest safe dispatch time.
      max_queue: admission bound; ``submit`` past it raises
        :class:`QueueFullError`.
      default_deadline_s: budget for requests submitted without one.
      clock: injectable monotonic clock (deterministic tests).
    """

    _POLL_S = 0.05  # close()-responsiveness bound for blocking waits

    def __init__(
        self,
        ladder: BucketLadder,
        *,
        step_time_fn: Callable[[int], float],
        max_queue: int = 256,
        default_deadline_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if default_deadline_s <= 0:
            raise ValueError(
                f"default_deadline_s must be > 0, got {default_deadline_s}"
            )
        self.ladder = ladder
        self._step_time_fn = step_time_fn
        self._default_deadline_s = default_deadline_s
        self._clock = clock
        self._queue: queue.Queue = queue.Queue(maxsize=max_queue)
        self._closed = threading.Event()
        # Counters: submit-side writes guarded by _lock (multi-writer);
        # the drain thread only reads them for telemetry.
        self._lock = threading.Lock()
        self._submitted = 0
        self._rejected = 0
        self._shed_infeasible = 0
        # Batches drained but not yet completed (the engine calls
        # mark_completed once results are distributed): the admission
        # projection counts them as wait ahead of a new arrival.
        self._inflight = 0

    # ---------------------------------------------------------- admission

    def submit(
        self,
        payload: Any,
        *,
        deadline_s: Optional[float] = None,
        trace: Any = None,
    ) -> ServeFuture:
        """Admit one request; returns the future its result arrives on.

        Raises :class:`QueueFullError` when the bounded queue is at
        capacity (the caller sheds load — an unbounded queue would turn
        overload into an unbounded latency tail for *every* request) and
        :class:`ServeClosedError` after ``close()``. ``trace`` is the
        request's span record (telemetry): admission success stamps
        ``admit`` on it — a host-clock append, nothing more (SAV116).
        """
        if self._closed.is_set():
            raise ServeClosedError("batcher is closed")
        future = ServeFuture()
        now = self._clock()
        request = ServeRequest(
            payload=payload,
            deadline_s=(
                deadline_s if deadline_s is not None
                else self._default_deadline_s
            ),
            enqueue_t=now,
            future=future,
            trace=trace,
        )
        if request.deadline_s <= 0:
            raise ValueError(
                f"deadline_s must be > 0, got {request.deadline_s}"
            )
        # Deadline-infeasibility shed: project the dispatch wait — the
        # batches already drained-but-not-completed plus the full
        # batches queued ahead of this request, each one top-bucket step
        # (conservative on bucket size, optimistic that the executing
        # batch is nearly done — the two roughly cancel). If even the
        # DISPATCH would land past the deadline, the request is a
        # guaranteed miss and admitting it would burn a device step on
        # dead work while delaying every request behind it. Rejecting
        # here is what keeps the served population's overrun bounded by
        # one bucket step under overload, not just under light load.
        max_batch = self.ladder.max_batch
        est = max(float(self._step_time_fn(max_batch)), 0.0)
        if est > 0.0:
            with self._lock:
                inflight = self._inflight
            batches_ahead = inflight + (
                (self._queue.qsize() + max_batch) // max_batch
            )
            if batches_ahead * est > request.deadline_s:
                with self._lock:
                    self._rejected += 1
                    self._shed_infeasible += 1
                raise DeadlineInfeasibleError(
                    f"projected dispatch wait {batches_ahead * est:.3f}s "
                    f"({batches_ahead} batches ahead at ~{est:.3f}s) "
                    f"exceeds the {request.deadline_s:.3f}s deadline; "
                    "shedding instead of serving a guaranteed miss"
                )
        # Stamp admit BEFORE the put: once the request is queued, the
        # drain thread can pop it and stamp batch_formed immediately —
        # an admit stamped after the put could postdate batch_formed,
        # yielding a negative derived "queue" interval. A stamp on a
        # request the put then rejects is harmless (the trace dies with
        # the raised exception, never reaching the ring).
        stamp(trace, "admit", self._clock())
        try:
            self._queue.put_nowait(request)
        except queue.Full:
            with self._lock:
                self._rejected += 1
            raise QueueFullError(
                f"request queue at capacity ({self._queue.maxsize}); "
                "shed load or raise max_queue"
            ) from None
        if self._closed.is_set():
            # close() can finish its fail-the-queue pass between this
            # thread's entry check and the put above; the request would
            # then sit in a queue nothing will ever drain, stranding
            # result() forever. Re-running the fail pass covers it (any
            # request still queued after close must fail anyway).
            self._fail_queued()
            raise ServeClosedError("batcher closed during submit")
        with self._lock:
            self._submitted += 1
        return future

    # -------------------------------------------------------------- drain

    def _get(self, timeout: float):
        """One bounded queue read; None on timeout."""
        try:
            return self._queue.get(timeout=timeout)
        except queue.Empty:
            return None

    def next_batch(self) -> Optional[FormedBatch]:
        """Block until a batch is ready under the deadline policy; None
        once closed and fully drained (the engine's device loop exits).

        Called from exactly one drain thread (the engine's feeder
        iterator); concurrent drains would interleave FIFO order.
        """
        # Wait for the first request, staying responsive to close().
        first = None
        while first is None:
            if self._closed.is_set() and self._queue.empty():
                return None
            first = self._get(self._POLL_S)
        batch = [first]
        earliest_deadline = first.deadline_t
        max_batch = self.ladder.max_batch
        while True:
            # Grab everything already waiting — the hot-queue fast path.
            while len(batch) < max_batch:
                try:
                    request = self._queue.get_nowait()
                except queue.Empty:
                    break
                batch.append(request)
                earliest_deadline = min(earliest_deadline, request.deadline_t)
            if len(batch) >= max_batch:
                break
            # Latest safe dispatch: the earliest admitted deadline minus
            # the current bucket's estimated step. Waiting for a larger
            # bucket only ever *shrinks* this bound (step_time_fn is
            # nondecreasing in bucket), so the guarantee survives growth.
            bucket = self.ladder.bucket_for(len(batch))
            dispatch_by = earliest_deadline - max(
                float(self._step_time_fn(bucket)), 0.0
            )
            now = self._clock()
            if now >= dispatch_by or self._closed.is_set():
                break
            request = self._get(min(dispatch_by - now, self._POLL_S))
            if request is not None:
                batch.append(request)
                earliest_deadline = min(earliest_deadline, request.deadline_t)
        with self._lock:
            self._inflight += 1
        formed_t = self._clock()
        for request in batch:
            stamp(request.trace, "batch_formed", formed_t)
        return FormedBatch(
            requests=batch,
            bucket=self.ladder.bucket_for(len(batch)),
            queue_depth=self._queue.qsize(),
            formed_t=formed_t,
        )

    def mark_completed(self) -> None:
        """One drained batch finished (results distributed OR failed) —
        the engine's completion/error paths call this so the admission
        projection stops counting it as wait ahead of new arrivals."""
        with self._lock:
            self._inflight = max(self._inflight - 1, 0)

    def pending(self) -> int:
        """Requests not yet resolved: queued + drained-but-uncompleted
        batches (the latter in batch units — nonzero means the device
        loop still owns work). The engine's ``drain()`` polls this to
        zero before a graceful stop, so a replica leaving the fleet
        (SIGTERM, weight swap) finishes what it accepted instead of
        failing it — the fleet's accepted-never-silently-lost contract
        (docs/serving.md)."""
        with self._lock:
            return self._queue.qsize() + self._inflight

    # ----------------------------------------------------------- shutdown

    def close(self) -> None:
        """Stop admission and fail queued-but-unshipped requests.

        Requests already drained into a batch complete normally (the
        device loop owns them); everything still queued gets
        :class:`ServeClosedError` on its future. Idempotent.
        """
        self._closed.set()
        self._fail_queued()

    def _fail_queued(self) -> None:
        """Fail every queued request's future (close()'s pass; submit()
        re-runs it when its enqueue raced close)."""
        while True:
            try:
                request = self._queue.get_nowait()
            except queue.Empty:
                break
            request.future.set_exception(
                ServeClosedError("engine stopped before this request shipped")
            )

    @property
    def closed(self) -> bool:
        return self._closed.is_set()

    def stats(self) -> dict:
        with self._lock:
            return {
                "submitted": self._submitted,
                "rejected": self._rejected,
                "shed_infeasible": self._shed_infeasible,
                "inflight": self._inflight,
                "queued": self._queue.qsize(),
            }
