"""Device-side prediction-quality primitives: in-graph output digests,
the golden-probe batch + fingerprint, and the deterministic
weight-perturbation chaos seam.

The scalar folds (windows, drift gates, ledgers) live stdlib-side in
``sav_tpu.obs.quality`` — this module is the only quality code allowed
to touch jax/numpy, and none of it runs on the request hot path:

- :func:`output_digests` is TRACED into the serving executable — the
  digests ride the batch's existing result fetch as three extra tiny
  output leaves (B ints + 2B floats), so quality telemetry adds zero
  device syncs to the request path (savlint SAV126).
- :class:`ProbeRunner` runs on its own low-cadence thread and submits
  through the NORMAL admission path, but only when the engine is fully
  idle — a probe sheds itself before it would ever queue behind (or
  evict) a live request.
- :func:`fingerprint_logits` is a blake2b over the exact float32 logit
  bytes: bit-stable under a fixed executable, so a matching fingerprint
  across a restart/swap proves weight integrity (and a per-dtype
  reference keeps int8 and bf16 replicas from judging each other's
  bits). This is the determinism primitive ROADMAP item 5's promotion
  cache needs.

See docs/quality.md.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

# Golden probe shape: small on purpose (one bucket-1..4 batch); the
# probe is a weight-integrity check, not a benchmark.
PROBE_ROWS = 4
_PROBE_TAG = b"sav_tpu golden probe v1"


def output_digests(logits, valid):
    """Per-row digest leaves, computed IN-GRAPH next to the logits:
    top-1 class index, top-1 margin (best minus runner-up), and
    predictive entropy (nats). Padded rows are masked to zero by the
    same validity mask that already zeroes their logits."""
    top1 = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    # Runner-up via a masked second reduce, not lax.top_k: top_k
    # lowers to a sort and this subgraph is re-compiled into EVERY
    # bucket executable of every engine — two max-reduces keep the
    # per-bucket compile cost flat. Masking exactly the argmax slot
    # (not every tied maximum) preserves top_k's tie semantics: all
    # logits equal gives margin 0, never -inf.
    num_classes = logits.shape[-1]
    best = jnp.max(logits, axis=-1)
    is_top1 = (
        jax.lax.broadcasted_iota(jnp.int32, logits.shape, len(logits.shape) - 1)
        == top1[..., None]
    )
    second = jnp.max(
        jnp.where(is_top1, jnp.finfo(logits.dtype).min, logits), axis=-1
    )
    if num_classes < 2:  # degenerate single-class head: no runner-up
        second = best
    margin = (best - second) * valid
    logp = jax.nn.log_softmax(logits, axis=-1)
    entropy = -jnp.sum(jnp.exp(logp) * logp, axis=-1) * valid
    return {
        "top1": (top1 * valid.astype(jnp.int32)),
        "margin": margin.astype(jnp.float32),
        "entropy": entropy.astype(jnp.float32),
    }


def digested_infer_fn(infer_fn: Callable) -> Callable:
    """Wrap a ``build_infer_fn`` program so the compiled executable
    returns ``{"logits", "top1", "margin", "entropy"}`` — the digests
    are folded into the same program (and the same single result fetch)
    rather than computed host-side per request."""

    def infer(params, batch_stats, batch):
        logits = infer_fn(params, batch_stats, batch)
        out = {"logits": logits}
        out.update(output_digests(logits, batch["valid"]))
        return out

    return infer


# --------------------------------------------------------------- probe


def make_probe_batch(image_size: int, rows: int = PROBE_ROWS) -> tuple:
    """(images, probe_id): a content-addressed deterministic uint8 probe
    batch. The bytes are a blake2b stream keyed only by the request
    shape, so every replica of every fleet regenerates the identical
    batch — and ``probe_id`` (the digest OF those bytes) names it, so a
    reference fingerprint can never be compared against logits from a
    different probe."""
    need = rows * image_size * image_size * 3
    chunks = []
    counter = 0
    while sum(len(c) for c in chunks) < need:
        h = hashlib.blake2b(
            _PROBE_TAG + f":{image_size}:{rows}:{counter}".encode(),
            digest_size=64,
        )
        chunks.append(h.digest())
        counter += 1
    raw = b"".join(chunks)[:need]
    images = np.frombuffer(raw, np.uint8).reshape(
        rows, image_size, image_size, 3
    )
    probe_id = hashlib.blake2b(raw, digest_size=8).hexdigest()
    return images, probe_id


def fingerprint_logits(rows) -> str:
    """blake2b over the exact float32 logit bytes of the probe rows —
    bit-stable under a fixed executable + weights."""
    h = hashlib.blake2b(digest_size=16)
    for row in rows:
        h.update(np.ascontiguousarray(np.asarray(row, np.float32)).tobytes())
    return h.hexdigest()


def _reference_path(log_dir: str) -> str:
    return os.path.join(log_dir, "fleet", "probe_reference.json")


def load_reference(log_dir: Optional[str]) -> dict:
    if not log_dir:
        return {}
    try:
        with open(_reference_path(log_dir)) as f:
            return json.load(f) or {}
    except (OSError, ValueError):
        return {}


def store_reference(log_dir: Optional[str], key: str, fingerprint: str) -> None:
    """First-writer-wins per ``probe_id:dtype`` key (identical-weight
    replicas write identical values, so the race is benign); atomic
    tmp+rename so a torn write never corrupts the reference."""
    if not log_dir:
        return
    path = _reference_path(log_dir)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    doc = load_reference(log_dir)
    if key in doc:
        return
    doc[key] = fingerprint
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
    os.replace(tmp, path)


class ProbeRunner:
    """Low-cadence golden-probe thread.

    Submits the probe batch through the engine's NORMAL admission path
    (``engine.submit`` — so the probe exercises the same batcher,
    feeder, executable, and depad the live traffic does), but only when
    the engine is fully idle: any queued or in-flight live work sheds
    the probe instead (``probe_shed`` on the ledger) — probe traffic
    never evicts or delays a live request, pinned by test_quality's
    shed-first test.

    Outcomes land on the stdlib :class:`~sav_tpu.obs.quality.ProbeLedger`
    the heartbeat ``quality_fn`` snapshots; the expected fingerprint is
    persisted per ``probe_id:dtype`` under ``log_dir`` so a restarted
    replica (warm compile cache, same weights) must reproduce its
    predecessor's bits exactly.
    """

    def __init__(
        self,
        engine,
        ledger,
        *,
        every_s: float,
        log_dir: Optional[str] = None,
    ):
        self._engine = engine
        self._ledger = ledger
        self._every_s = max(0.05, float(every_s))
        self._log_dir = log_dir
        self._images, self.probe_id = make_probe_batch(
            engine.config.image_size
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "ProbeRunner":
        self._thread = threading.Thread(
            target=self._loop, name="serve-probe", daemon=True
        )
        self._thread.start()
        return self

    def close(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    def _loop(self) -> None:
        while not self._stop.wait(self._every_s):
            try:
                self.observe_probe()
            except Exception:
                # The probe is observability: a failed probe run must
                # never take the serving loop down with it.
                self._ledger.record_shed()

    # -------------------------------------------------------------- one run

    def _idle(self) -> bool:
        batcher = getattr(self._engine, "_batcher", None)
        if batcher is None:
            return False
        stats = batcher.stats()
        return not stats.get("queued") and not stats.get("inflight")

    def observe_probe(self) -> Optional[bool]:
        """One probe run: None when shed (engine busy/closed), else
        whether the fingerprint matched the reference. Named for
        savlint SAV126's audit set — this function may block on device
        results precisely because it never runs on the hot path."""
        if not self._idle():
            self._ledger.record_shed()
            return None
        try:
            futures = [
                self._engine.submit(row, deadline_ms=10_000)
                for row in self._images
            ]
        except Exception:
            self._ledger.record_shed()
            return None
        rows = [f.result(timeout=30.0) for f in futures]
        fingerprint = fingerprint_logits(rows)
        key = f"{self.probe_id}:{self._engine.serve_dtype}"
        reference = load_reference(self._log_dir)
        expected = reference.get(key)
        if expected is None:
            # First run under this (probe, dtype): the observed bits
            # BECOME the reference every later run/restart must match.
            store_reference(self._log_dir, key, fingerprint)
            expected = load_reference(self._log_dir).get(key, fingerprint)
        return self._ledger.record(
            fingerprint=fingerprint, expected=expected, probe_id=self.probe_id
        )


# ---------------------------------------------------------- chaos seam


def noise_params(params, scale: float, seed: int = 0):
    """Deterministically perturb every float leaf of a param tree
    (relative to its own std) — the SAV_CHAOS_NOISE_WEIGHTS seam: a
    planted corrupt replica for the shadow-agreement and
    probe-mismatch detection tests (docs/quality.md "Chaos")."""
    rng = np.random.default_rng(int(seed))
    scale = float(scale)

    def perturb(leaf):
        arr = np.asarray(leaf)
        if not np.issubdtype(arr.dtype, np.floating):
            return leaf
        std = float(arr.std()) or 1.0
        noise = rng.standard_normal(arr.shape).astype(arr.dtype)
        return jnp.asarray(arr + scale * std * noise)

    return jax.tree.map(perturb, params)
