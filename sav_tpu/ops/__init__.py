from sav_tpu.ops.attention import (
    AttentionDispatch,
    clear_dispatch_log,
    dot_product_attention,
    resolve_attention_backend,
    snapshot_dispatch_log,
    xla_attention,
    xla_attention_fast,
)
from sav_tpu.ops.flash_attention import flash_attention, flash_botnet_attention
from sav_tpu.ops.fused_attention import fused_attention, fused_eligible
from sav_tpu.ops.relative import relative_logits_2d
from sav_tpu.ops.rotary import fixed_positional_embedding, apply_rotary_pos_emb

__all__ = [
    "AttentionDispatch",
    "clear_dispatch_log",
    "dot_product_attention",
    "resolve_attention_backend",
    "snapshot_dispatch_log",
    "xla_attention",
    "xla_attention_fast",
    "flash_attention",
    "flash_botnet_attention",
    "fused_attention",
    "fused_eligible",
    "relative_logits_2d",
    "fixed_positional_embedding",
    "apply_rotary_pos_emb",
]
