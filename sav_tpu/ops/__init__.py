from sav_tpu.ops.attention import (
    dot_product_attention,
    xla_attention,
    xla_attention_fast,
)
from sav_tpu.ops.flash_attention import flash_attention, flash_botnet_attention
from sav_tpu.ops.relative import relative_logits_2d
from sav_tpu.ops.rotary import fixed_positional_embedding, apply_rotary_pos_emb

__all__ = [
    "dot_product_attention",
    "xla_attention",
    "xla_attention_fast",
    "flash_attention",
    "flash_botnet_attention",
    "relative_logits_2d",
    "fixed_positional_embedding",
    "apply_rotary_pos_emb",
]
