"""Fixed sinusoidal and rotary position embeddings.

Working rebuild of the reference's broken rotary path
(/root/reference/models/layers/position_embed.py:8-45 — undefined ``self.dim``,
malformed ``10e4 ** intervals / dim`` frequency formula; SURVEY.md §2.9 #12).
Frequencies here follow the standard RoPE formulation
``inv_freq_i = 10000 ** (-2i / dim)``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def fixed_positional_embedding(seq_len: int, dim: int, dtype=jnp.float32):
    """Sinusoidal (sin, cos) tables of shape ``[seq_len, dim]`` each.

    Each frequency is repeated twice along the feature axis so the tables
    align with :func:`rotate_every_two` pairing.
    """
    if dim % 2 != 0:
        raise ValueError(f"rotary dim must be even, got {dim}")
    inv_freq = 1.0 / (10000 ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    t = jnp.arange(seq_len, dtype=jnp.float32)
    freqs = jnp.einsum("i,j->ij", t, inv_freq)  # [L, dim/2]
    freqs = jnp.repeat(freqs, 2, axis=-1)  # [L, dim]
    return jnp.sin(freqs).astype(dtype), jnp.cos(freqs).astype(dtype)


def rotate_every_two(x: jax.Array) -> jax.Array:
    """``(x0, x1, x2, x3, ...) -> (-x1, x0, -x3, x2, ...)`` along the last axis."""
    x1 = x[..., 0::2]
    x2 = x[..., 1::2]
    out = jnp.stack([-x2, x1], axis=-1)
    return out.reshape(x.shape)


def apply_rotary_pos_emb(x: jax.Array, sincos) -> jax.Array:
    """Apply RoPE to ``x: [..., seq_len, dim]`` (or ``[..., seq_len, heads, dim]``).

    ``sincos``: pair of ``[seq_len, dim]`` tables from
    :func:`fixed_positional_embedding`.
    """
    sin, cos = sincos
    if x.ndim == 4:  # [B, L, H, D] — broadcast over heads
        sin = sin[None, :, None, :]
        cos = cos[None, :, None, :]
    sin = sin.astype(x.dtype)
    cos = cos.astype(x.dtype)
    return x * cos + rotate_every_two(x) * sin
