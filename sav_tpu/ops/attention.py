"""Scaled dot-product attention cores with a pluggable TPU backend.

Functional equivalent of the einsum pipeline inside the reference's
``AttentionBlock`` (/root/reference/models/layers/attentions/attention.py:39-57):
``logits = einsum('...qhd,...khd->...hqk', q, k); softmax; einsum('...hqk,...khd->...qhd')``.

Layout convention everywhere in this framework: ``[batch..., length, heads, head_dim]``
(the natural output of ``nn.DenseGeneral`` head-splitting), matching the
reference. The Pallas path transposes to ``[B*H, L, D]`` internally.

``backend``:
  - ``'xla'``    — jnp/einsum path, plain autodiff backward. Measured faster
                   than the hand-written bf16-residual VJP on v5e (PERF.md
                   §5: the custom_vjp boundary blocks XLA fusions worth more
                   than the residual-traffic saving); the hand VJP remains
                   available as :func:`xla_attention_fast` for
                   memory-constrained cases (bf16 residual halves the saved
                   probabilities' HBM footprint).
  - ``'fused'``  — single-pass fused short-sequence kernel
                   (:mod:`sav_tpu.ops.fused_attention`): the whole KV
                   sequence in one VMEM block, plain softmax (no online
                   carry), single fused backward. Raises when the shape
                   exceeds the single-block VMEM budget. Deterministic only.
  - ``'pallas'`` — blockwise online-softmax flash kernel
                   (:mod:`sav_tpu.ops.flash_attention`) for shapes beyond
                   the single block. Deterministic only (attention dropout
                   falls back to XLA).
  - ``'auto'``   — three-way measured dispatch on TPU (else xla), resolved
                   per traced shape by :func:`resolve_attention_backend`:

                   * dense fp32 logits past the HBM budget → ``pallas``
                     (the flash kernel's O(L·D) memory is the only way the
                     shape runs at all);
                   * short band (KV fits one VMEM block,
                     ``fused_attention.fused_eligible``) → the measured
                     winner from the ``tools/attn_tune.py`` cache
                     (:mod:`sav_tpu.ops.attn_tuning`) — ``fused`` only
                     where a sweep + ``ab_step`` gate confirmed the win on
                     chip, else XLA (the PERF.md §5 measured winner);
                   * middle band → ``xla`` (L² fits HBM comfortably and
                     XLA keeps the MXU busy).

                   Every resolution is recorded in a trace-time dispatch
                   log (:func:`snapshot_dispatch_log`) that ``bench.py``
                   stamps into its JSON line and run manifest, so perf
                   history is attributable to the dispatch decision.
"""

from __future__ import annotations

import dataclasses
import functools
import threading
from typing import Optional

import jax
import jax.numpy as jnp

from sav_tpu.ops import attn_tuning
from sav_tpu.ops import flash_attention as _flash
from sav_tpu.ops import fused_attention as _fused


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:  # pragma: no cover - no backend at all
        return False


# 'auto' flips to the flash kernel when materializing the [B, H, Lq, Lk]
# fp32 logits (fwd + bwd residual ≈ 3 copies) would eat this much HBM —
# beyond it the XLA path thrashes or OOMs while flash stays O(L·D).
_AUTO_PALLAS_LOGITS_BYTES = 2 << 30

# DEPRECATED process-wide fallback for the XLA path's softmax dtype, used
# only when a caller passes ``logits_dtype=None`` to the bare functional
# core. Every framework path resolves the dtype explicitly instead: the
# attention *blocks* carry a ``logits_dtype`` attribute (None = the block's
# compute dtype — the reference's semantics) threaded from
# ``TrainConfig.attention_logits_dtype`` through ``create_model``, so no
# jitted model path reads this module state. f32 is the safe raw-op
# default; bf16 halves the dominant HBM traffic of the [B, H, L, L]
# logits/probability tensors (PERF.md §5) at ~2⁻⁸ relative logit precision.
_DEFAULT_LOGITS_DTYPE = jnp.float32


def set_default_logits_dtype(dtype) -> None:
    """DEPRECATED: set the process-wide softmax dtype fallback.

    Only affects direct :func:`xla_attention` / :func:`dot_product_attention`
    calls that pass ``logits_dtype=None``. Model blocks resolve their dtype
    from their own ``logits_dtype``/``dtype`` attributes and never consult
    this. Prefer passing ``logits_dtype`` explicitly.
    """
    global _DEFAULT_LOGITS_DTYPE
    _DEFAULT_LOGITS_DTYPE = jnp.dtype(dtype).type


def _dense_logits_bytes(batch: int, heads: int, q_len: int, kv_len: int) -> int:
    """HBM bytes of the dense attention's fp32 [B, H, Lq, Lk] working set
    (logits + probabilities + saved bwd residual ≈ 3 copies) — the single
    source of the ``auto`` rule's long-band accounting."""
    return 3 * 4 * batch * heads * q_len * kv_len


def xla_attention(
    query: jax.Array,
    key: jax.Array,
    value: jax.Array,
    bias: Optional[jax.Array] = None,
    *,
    scale: Optional[float] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    deterministic: bool = True,
    logits_dtype=None,
) -> jax.Array:
    """Reference attention core in pure XLA ops.

    Args:
      query: ``[..., q_len, heads, head_dim]``.
      key, value: ``[..., kv_len, heads, head_dim]``.
      bias: optional logits bias broadcastable to ``[..., heads, q_len, kv_len]``.
      scale: logit scale; defaults to ``head_dim ** -0.5`` (attention.py:39).
      logits_dtype: dtype for softmax math; None = the process default
        (:func:`set_default_logits_dtype`, f32 unless configured). fp32
        keeps bf16 runs stable; bf16 halves the L² HBM traffic.

    Returns:
      ``[..., q_len, heads, head_dim]`` in the query dtype.
    """
    if scale is None:
        scale = query.shape[-1] ** -0.5
    if logits_dtype is None:
        logits_dtype = _DEFAULT_LOGITS_DTYPE
    # Canonicalize: config-layer callers pass strings ('bfloat16').
    logits_dtype = jnp.dtype(logits_dtype)
    probs = _softmax_probs(query, key, bias, scale, logits_dtype)
    if dropout_rate > 0.0 and not deterministic:
        if dropout_rng is None:
            raise ValueError("dropout_rng required for non-deterministic attention dropout")
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = probs * keep.astype(probs.dtype) / (1.0 - dropout_rate)
    probs = probs.astype(value.dtype)
    return jnp.einsum("...hqk,...khd->...qhd", probs, value)


def _softmax_probs(q, k, bias, scale, logits_dtype):
    """Shared scaled-QK softmax — the single source of forward numerics for
    both the autodiff reference path and the fast-VJP path."""
    qs = q * jnp.asarray(scale, dtype=q.dtype)
    logits = jnp.einsum(
        "...qhd,...khd->...hqk", qs, k, preferred_element_type=logits_dtype
    )
    if bias is not None:
        logits = logits + bias.astype(logits_dtype)
    return jax.nn.softmax(logits, axis=-1)


def _fast_fwd_impl(q, k, v, bias, scale):
    probs = _softmax_probs(q, k, bias, scale, jnp.float32).astype(v.dtype)
    out = jnp.einsum("...hqk,...khd->...qhd", probs, v)
    return out, probs


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _fast_attention(q, k, v, bias, scale):
    return _fast_fwd_impl(q, k, v, bias, scale)[0]


def _fast_attention_fwd(q, k, v, bias, scale):
    out, probs = _fast_fwd_impl(q, k, v, bias, scale)
    # bias rides the residuals only to carry its (static) shape to the
    # backward; the unused value is dead-code-eliminated there.
    return out, (q, k, v, probs, bias)


def _fast_attention_bwd(scale, residuals, g):
    q, k, v, probs, bias = residuals
    bias_shape = None if bias is None else bias.shape
    # dV: both operands in the storage dtype — rides the MXU at bf16 rate.
    dv = jnp.einsum("...hqk,...qhd->...khd", probs, g)
    dp = jnp.einsum("...qhd,...khd->...hqk", g, v, preferred_element_type=jnp.float32)
    p32 = probs.astype(jnp.float32)
    # d(softmax): dS = P ⊙ (dP − Σ_k P·dP). Elementwise in f32; P itself is
    # the bf16 residual, whose quantization (~2⁻⁸ relative) is the price for
    # halving residual HBM traffic vs autodiff's saved f32 probabilities.
    ds = p32 * (dp - jnp.sum(p32 * dp, axis=-1, keepdims=True))
    ds_lo = ds.astype(q.dtype)  # bf16 operands → bf16-rate matmuls below
    dq = jnp.einsum("...hqk,...khd->...qhd", ds_lo, k) * jnp.asarray(
        scale, dtype=q.dtype
    )
    dk = jnp.einsum("...hqk,...qhd->...khd", ds_lo, q) * jnp.asarray(
        scale, dtype=q.dtype
    )
    if bias_shape is None:
        dbias = None
    else:
        # Sum dS over the dims the bias broadcast along. Broadcasting aligns
        # shapes from the RIGHT: reduce any leading dims the bias lacks, plus
        # right-aligned size-1 bias dims that dS expanded.
        offset = ds.ndim - len(bias_shape)
        reduce_axes = tuple(range(offset)) + tuple(
            offset + i
            for i, (b_dim, s_dim) in enumerate(zip(bias_shape, ds.shape[offset:]))
            if b_dim == 1 and s_dim != 1
        )
        dbias = jnp.sum(ds, axis=reduce_axes, keepdims=True) if reduce_axes else ds
        # custom_vjp cotangents must match the primal's dtype.
        dbias = dbias.reshape(bias_shape).astype(bias.dtype)
    return dq, dk, dv, dbias


_fast_attention.defvjp(_fast_attention_fwd, _fast_attention_bwd)


def xla_attention_fast(
    query: jax.Array,
    key: jax.Array,
    value: jax.Array,
    bias: Optional[jax.Array] = None,
    *,
    scale: Optional[float] = None,
) -> jax.Array:
    """``xla_attention`` with a hand-written VJP tuned for TPU training.

    Forward numerics are identical to :func:`xla_attention` (f32 softmax,
    probabilities cast to the value dtype before PV). The backward differs
    from autodiff in two deliberate ways, both measured dominant in the
    DeiT-S profile (PERF.md §1):

    - the softmax residual is stored in the value dtype (bf16 in training)
      instead of f32 — half the save/restore HBM traffic;
    - every backward matmul (dV, dP, dQ, dK) takes low-precision operands
      with f32 accumulation, instead of the f32-operand dots autodiff emits
      (f32 matmuls run at ~1/4 MXU rate on v5e).

    Gradient error vs the f32 chain is bounded by bf16 probability
    quantization (~2⁻⁸ relative) — below bf16 training noise; verified
    against :func:`xla_attention` autodiff in tests/test_flash_attention.py.
    No attention-dropout support (training dropout uses the plain path).

    Precondition: q/k/v leading batch dims must match (no cross-operand
    batch broadcasting — the hand VJP does not sum cotangents over
    broadcast batch dims the way autodiff's transpose does; a mismatch
    fails at trace time under grad). A bias may still broadcast freely
    against the logits. Use :func:`xla_attention` for broadcast batches.
    """
    if scale is None:
        scale = query.shape[-1] ** -0.5
    return _fast_attention(query, key, value, bias, scale)


@dataclasses.dataclass(frozen=True)
class AttentionDispatch:
    """One resolved dispatch decision (static-shape, trace-time)."""

    backend: str  # 'xla' | 'fused' | 'pallas'
    reason: str  # human-readable why
    source: str  # 'requested' | 'threshold' | 'tuned' | 'default'
    block_config: Optional[dict] = None  # kernel block kwargs, if any

    def as_note(self) -> dict:
        return dataclasses.asdict(self)


# Trace-time dispatch provenance, keyed by (shape, requested backend) so
# bench.py / fit() can stamp *which* backend + block config each traced
# attention shape resolved to. Host-side and append-once-per-trace — the
# jitted hot path never touches it (savlint-clean by construction).
_DISPATCH_LOG: dict = {}
_DISPATCH_LOCK = threading.Lock()


def clear_dispatch_log() -> None:
    with _DISPATCH_LOCK:
        _DISPATCH_LOG.clear()


def snapshot_dispatch_log() -> list:
    """Resolved decisions since the last clear, one dict per unique
    (shape, requested) pair — the provenance record bench.py stamps into
    its JSON line and run manifest."""
    with _DISPATCH_LOCK:
        return [dict(v) for v in _DISPATCH_LOG.values()]


def _log_dispatch(shape, kv_len, requested, dispatch: AttentionDispatch) -> None:
    # kv_len is part of the identity: cross-attention sites share a query
    # shape with self-attention ones but can resolve differently.
    key = (shape, kv_len, requested)
    with _DISPATCH_LOCK:
        if key not in _DISPATCH_LOG:
            _DISPATCH_LOG[key] = {
                "shape": list(shape),
                "kv_len": kv_len,
                "requested": requested or "auto",
                **dispatch.as_note(),
            }


def resolve_attention_backend(
    batch: int,
    q_len: int,
    kv_len: int,
    heads: int,
    dim: int,
    *,
    dtype="bfloat16",
    requested: Optional[str] = None,
    kernels_ok: bool = True,
    on_tpu: Optional[bool] = None,
) -> AttentionDispatch:
    """The three-way ``auto`` rule on static shapes (see module docstring).

    ``kernels_ok`` is the caller's eligibility for the Pallas paths (4-D
    inputs, deterministic); ``on_tpu`` defaults to the live backend. Every
    threshold here is test-pinned (tests/test_attn_dispatch.py). Explicit
    ``requested`` backends pass through, picking up any tuned block config
    for the shape.
    """
    if on_tpu is None:
        on_tpu = _on_tpu()
    entry = attn_tuning.lookup(batch, q_len, kv_len, heads, dim, dtype)
    tuned_cfg = attn_tuning.block_config(entry)
    if requested and requested != "auto":
        cfg = tuned_cfg if (entry and entry["backend"] == requested) else None
        return AttentionDispatch(
            backend=requested, reason="explicit backend", source="requested",
            block_config=cfg,
        )
    if not kernels_ok or not on_tpu:
        return AttentionDispatch(
            backend="xla",
            reason=(
                "kernel-ineligible call (dropout or non-4-D inputs)"
                if not kernels_ok
                else "non-TPU backend"
            ),
            source="threshold",
        )
    itemsize = jnp.dtype(dtype).itemsize
    dense_bytes = _dense_logits_bytes(batch, heads, q_len, kv_len)
    if dense_bytes > _AUTO_PALLAS_LOGITS_BYTES:
        cfg = tuned_cfg if (entry and entry["backend"] == "pallas") else None
        return AttentionDispatch(
            backend="pallas",
            reason=(
                f"dense fp32 logits ≈{dense_bytes >> 20} MiB exceed the "
                f"{_AUTO_PALLAS_LOGITS_BYTES >> 30} GiB HBM budget"
            ),
            source="threshold",
            block_config=cfg,
        )
    short = _fused.fused_eligible(q_len, kv_len, dim, itemsize=itemsize)
    if entry:
        # A measured winner from the tune cache. Fused is additionally
        # gated on the VMEM band (a fused verdict at an over-budget shape
        # is stale/foreign — ignore it); xla and pallas verdicts apply at
        # any shape the sweep measured.
        winner = entry["backend"]
        if winner == "fused" and not short:
            winner = None
        if winner:
            return AttentionDispatch(
                backend=winner,
                reason=(
                    f"measured {winner} win "
                    f"({entry.get('source', 'tune cache')})"
                ),
                source="tuned",
                block_config=tuned_cfg if winner != "xla" else None,
            )
    return AttentionDispatch(
        backend="xla",
        reason=(
            "short band, no measured fused win yet (promotion is gated on "
            "the attn_tune + ab_step battery)"
            if short
            else "middle band: dense logits fit HBM, XLA keeps the MXU busy"
        ),
        source="default",
    )


def dot_product_attention(
    query: jax.Array,
    key: jax.Array,
    value: jax.Array,
    bias: Optional[jax.Array] = None,
    *,
    scale: Optional[float] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    deterministic: bool = True,
    backend: Optional[str] = None,
    logits_dtype=None,
) -> jax.Array:
    """Backend-dispatched attention. See module docstring.

    ``logits_dtype`` sets the XLA path's softmax dtype (None = the
    deprecated process-wide default, f32 unless configured). The Pallas
    kernels always accumulate their softmax in f32 on-chip and ignore it.
    """
    requested = backend
    backend = backend or "auto"
    if backend not in ("auto", "xla", "pallas", "fused"):
        raise ValueError(f"unknown attention backend: {backend!r}")

    has_dropout = dropout_rate > 0.0 and not deterministic
    kernels_ok = (
        not has_dropout
        and query.ndim == 4  # [B, L, H, D] — the kernels' one layout
        and key.ndim == 4
        and (bias is None or bias.ndim == 4)
    )
    if kernels_ok:
        b, lq, h, d = query.shape
        dispatch = resolve_attention_backend(
            b, lq, key.shape[1], h, d,
            dtype=query.dtype, requested=requested, kernels_ok=True,
        )
        _log_dispatch(tuple(query.shape), key.shape[1], requested, dispatch)
        backend = dispatch.backend
        cfg = dispatch.block_config or {}
    else:
        if backend in ("pallas", "fused"):
            raise ValueError(
                f"{backend} attention backend requires 4-D [B, L, H, D] "
                "inputs and deterministic mode (attention dropout runs on "
                "the XLA path)"
            )
        backend, cfg = "xla", {}
    if backend == "fused":
        # Shape ineligibility (kv_len over the single-block VMEM budget)
        # raises inside fused_attention with the budget numbers.
        kw = {k: cfg[k] for k in ("block_q", "block_b") if k in cfg}
        return _fused.fused_attention(query, key, value, bias, scale=scale, **kw)
    if backend == "pallas":
        kw = {k: cfg[k] for k in ("block_q", "block_kv") if k in cfg}
        return _flash.flash_attention(query, key, value, bias, scale=scale, **kw)
    return xla_attention(
        query,
        key,
        value,
        bias,
        scale=scale,
        dropout_rate=dropout_rate,
        dropout_rng=dropout_rng,
        deterministic=deterministic,
        logits_dtype=logits_dtype,
    )
