"""Scaled dot-product attention cores with a pluggable TPU backend.

Functional equivalent of the einsum pipeline inside the reference's
``AttentionBlock`` (/root/reference/models/layers/attentions/attention.py:39-57):
``logits = einsum('...qhd,...khd->...hqk', q, k); softmax; einsum('...hqk,...khd->...qhd')``.

Layout convention everywhere in this framework: ``[batch..., length, heads, head_dim]``
(the natural output of ``nn.DenseGeneral`` head-splitting), matching the
reference. The Pallas path transposes to ``[B*H, L, D]`` internally.

``backend``:
  - ``'xla'``    — jnp/einsum path, plain autodiff backward. Measured faster
                   than the hand-written bf16-residual VJP on v5e (PERF.md
                   §5: the custom_vjp boundary blocks XLA fusions worth more
                   than the residual-traffic saving); the hand VJP remains
                   available as :func:`xla_attention_fast` for
                   memory-constrained cases (bf16 residual halves the saved
                   probabilities' HBM footprint).
  - ``'pallas'`` — fused Pallas TPU flash-attention kernel
                   (:mod:`sav_tpu.ops.flash_attention`). Deterministic only
                   (attention dropout falls back to XLA).
  - ``'auto'``   — measured-crossover dispatch on TPU (else xla). Benchmarked
                   on v5e (PERF.md): at the model zoo's short sequences
                   (197–785 tokens) XLA's batched-matmul attention beats
                   every flash kernel — including the tuned stock one — by
                   ~2×, because the L² logits easily fit HBM and the MXU
                   stays busy; the fused kernel's win is *memory*: it keeps
                   O(L²) out of HBM entirely, which is what long-context /
                   ring-attention shapes need. ``auto`` therefore picks
                   pallas only when the dense fp32 logits would be
                   HBM-prohibitive and xla otherwise.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from sav_tpu.ops import flash_attention as _flash


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:  # pragma: no cover - no backend at all
        return False


# 'auto' flips to the fused kernel when materializing the [B, H, Lq, Lk]
# fp32 logits (fwd + bwd residual ≈ 3 copies) would eat this much HBM —
# beyond it the XLA path thrashes or OOMs while flash stays O(L·D).
_AUTO_PALLAS_LOGITS_BYTES = 2 << 30

# DEPRECATED process-wide fallback for the XLA path's softmax dtype, used
# only when a caller passes ``logits_dtype=None`` to the bare functional
# core. Every framework path resolves the dtype explicitly instead: the
# attention *blocks* carry a ``logits_dtype`` attribute (None = the block's
# compute dtype — the reference's semantics) threaded from
# ``TrainConfig.attention_logits_dtype`` through ``create_model``, so no
# jitted model path reads this module state. f32 is the safe raw-op
# default; bf16 halves the dominant HBM traffic of the [B, H, L, L]
# logits/probability tensors (PERF.md §5) at ~2⁻⁸ relative logit precision.
_DEFAULT_LOGITS_DTYPE = jnp.float32


def set_default_logits_dtype(dtype) -> None:
    """DEPRECATED: set the process-wide softmax dtype fallback.

    Only affects direct :func:`xla_attention` / :func:`dot_product_attention`
    calls that pass ``logits_dtype=None``. Model blocks resolve their dtype
    from their own ``logits_dtype``/``dtype`` attributes and never consult
    this. Prefer passing ``logits_dtype`` explicitly.
    """
    global _DEFAULT_LOGITS_DTYPE
    _DEFAULT_LOGITS_DTYPE = jnp.dtype(dtype).type


def _dense_logits_bytes(query, key) -> int:
    b, lq, h, _ = query.shape
    return 3 * 4 * b * h * lq * key.shape[1]


def xla_attention(
    query: jax.Array,
    key: jax.Array,
    value: jax.Array,
    bias: Optional[jax.Array] = None,
    *,
    scale: Optional[float] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    deterministic: bool = True,
    logits_dtype=None,
) -> jax.Array:
    """Reference attention core in pure XLA ops.

    Args:
      query: ``[..., q_len, heads, head_dim]``.
      key, value: ``[..., kv_len, heads, head_dim]``.
      bias: optional logits bias broadcastable to ``[..., heads, q_len, kv_len]``.
      scale: logit scale; defaults to ``head_dim ** -0.5`` (attention.py:39).
      logits_dtype: dtype for softmax math; None = the process default
        (:func:`set_default_logits_dtype`, f32 unless configured). fp32
        keeps bf16 runs stable; bf16 halves the L² HBM traffic.

    Returns:
      ``[..., q_len, heads, head_dim]`` in the query dtype.
    """
    if scale is None:
        scale = query.shape[-1] ** -0.5
    if logits_dtype is None:
        logits_dtype = _DEFAULT_LOGITS_DTYPE
    # Canonicalize: config-layer callers pass strings ('bfloat16').
    logits_dtype = jnp.dtype(logits_dtype)
    probs = _softmax_probs(query, key, bias, scale, logits_dtype)
    if dropout_rate > 0.0 and not deterministic:
        if dropout_rng is None:
            raise ValueError("dropout_rng required for non-deterministic attention dropout")
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = probs * keep.astype(probs.dtype) / (1.0 - dropout_rate)
    probs = probs.astype(value.dtype)
    return jnp.einsum("...hqk,...khd->...qhd", probs, value)


def _softmax_probs(q, k, bias, scale, logits_dtype):
    """Shared scaled-QK softmax — the single source of forward numerics for
    both the autodiff reference path and the fast-VJP path."""
    qs = q * jnp.asarray(scale, dtype=q.dtype)
    logits = jnp.einsum(
        "...qhd,...khd->...hqk", qs, k, preferred_element_type=logits_dtype
    )
    if bias is not None:
        logits = logits + bias.astype(logits_dtype)
    return jax.nn.softmax(logits, axis=-1)


def _fast_fwd_impl(q, k, v, bias, scale):
    probs = _softmax_probs(q, k, bias, scale, jnp.float32).astype(v.dtype)
    out = jnp.einsum("...hqk,...khd->...qhd", probs, v)
    return out, probs


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _fast_attention(q, k, v, bias, scale):
    return _fast_fwd_impl(q, k, v, bias, scale)[0]


def _fast_attention_fwd(q, k, v, bias, scale):
    out, probs = _fast_fwd_impl(q, k, v, bias, scale)
    # bias rides the residuals only to carry its (static) shape to the
    # backward; the unused value is dead-code-eliminated there.
    return out, (q, k, v, probs, bias)


def _fast_attention_bwd(scale, residuals, g):
    q, k, v, probs, bias = residuals
    bias_shape = None if bias is None else bias.shape
    # dV: both operands in the storage dtype — rides the MXU at bf16 rate.
    dv = jnp.einsum("...hqk,...qhd->...khd", probs, g)
    dp = jnp.einsum("...qhd,...khd->...hqk", g, v, preferred_element_type=jnp.float32)
    p32 = probs.astype(jnp.float32)
    # d(softmax): dS = P ⊙ (dP − Σ_k P·dP). Elementwise in f32; P itself is
    # the bf16 residual, whose quantization (~2⁻⁸ relative) is the price for
    # halving residual HBM traffic vs autodiff's saved f32 probabilities.
    ds = p32 * (dp - jnp.sum(p32 * dp, axis=-1, keepdims=True))
    ds_lo = ds.astype(q.dtype)  # bf16 operands → bf16-rate matmuls below
    dq = jnp.einsum("...hqk,...khd->...qhd", ds_lo, k) * jnp.asarray(
        scale, dtype=q.dtype
    )
    dk = jnp.einsum("...hqk,...qhd->...khd", ds_lo, q) * jnp.asarray(
        scale, dtype=q.dtype
    )
    if bias_shape is None:
        dbias = None
    else:
        # Sum dS over the dims the bias broadcast along. Broadcasting aligns
        # shapes from the RIGHT: reduce any leading dims the bias lacks, plus
        # right-aligned size-1 bias dims that dS expanded.
        offset = ds.ndim - len(bias_shape)
        reduce_axes = tuple(range(offset)) + tuple(
            offset + i
            for i, (b_dim, s_dim) in enumerate(zip(bias_shape, ds.shape[offset:]))
            if b_dim == 1 and s_dim != 1
        )
        dbias = jnp.sum(ds, axis=reduce_axes, keepdims=True) if reduce_axes else ds
        # custom_vjp cotangents must match the primal's dtype.
        dbias = dbias.reshape(bias_shape).astype(bias.dtype)
    return dq, dk, dv, dbias


_fast_attention.defvjp(_fast_attention_fwd, _fast_attention_bwd)


def xla_attention_fast(
    query: jax.Array,
    key: jax.Array,
    value: jax.Array,
    bias: Optional[jax.Array] = None,
    *,
    scale: Optional[float] = None,
) -> jax.Array:
    """``xla_attention`` with a hand-written VJP tuned for TPU training.

    Forward numerics are identical to :func:`xla_attention` (f32 softmax,
    probabilities cast to the value dtype before PV). The backward differs
    from autodiff in two deliberate ways, both measured dominant in the
    DeiT-S profile (PERF.md §1):

    - the softmax residual is stored in the value dtype (bf16 in training)
      instead of f32 — half the save/restore HBM traffic;
    - every backward matmul (dV, dP, dQ, dK) takes low-precision operands
      with f32 accumulation, instead of the f32-operand dots autodiff emits
      (f32 matmuls run at ~1/4 MXU rate on v5e).

    Gradient error vs the f32 chain is bounded by bf16 probability
    quantization (~2⁻⁸ relative) — below bf16 training noise; verified
    against :func:`xla_attention` autodiff in tests/test_flash_attention.py.
    No attention-dropout support (training dropout uses the plain path).

    Precondition: q/k/v leading batch dims must match (no cross-operand
    batch broadcasting — the hand VJP does not sum cotangents over
    broadcast batch dims the way autodiff's transpose does; a mismatch
    fails at trace time under grad). A bias may still broadcast freely
    against the logits. Use :func:`xla_attention` for broadcast batches.
    """
    if scale is None:
        scale = query.shape[-1] ** -0.5
    return _fast_attention(query, key, value, bias, scale)


def dot_product_attention(
    query: jax.Array,
    key: jax.Array,
    value: jax.Array,
    bias: Optional[jax.Array] = None,
    *,
    scale: Optional[float] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    deterministic: bool = True,
    backend: Optional[str] = None,
    logits_dtype=None,
) -> jax.Array:
    """Backend-dispatched attention. See module docstring.

    ``logits_dtype`` sets the XLA path's softmax dtype (None = the
    deprecated process-wide default, f32 unless configured). The Pallas
    flash kernel always accumulates its running softmax in f32 on-chip and
    ignores it.
    """
    backend = backend or "auto"
    if backend not in ("auto", "xla", "pallas"):
        raise ValueError(f"unknown attention backend: {backend!r}")

    has_dropout = dropout_rate > 0.0 and not deterministic
    pallas_ok = (
        not has_dropout
        and query.ndim == 4  # [B, L, H, D] — flash path handles the common case
        and key.ndim == 4
        and (bias is None or bias.ndim == 4)
    )
    if backend == "auto":
        big = pallas_ok and (
            _dense_logits_bytes(query, key) > _AUTO_PALLAS_LOGITS_BYTES
        )
        backend = "pallas" if (big and _on_tpu()) else "xla"
    if backend == "pallas":
        if not pallas_ok:
            raise ValueError(
                "pallas attention backend requires 4-D [B, L, H, D] inputs and "
                "deterministic mode (attention dropout runs on the XLA path)"
            )
        return _flash.flash_attention(query, key, value, bias, scale=scale)
    return xla_attention(
        query,
        key,
        value,
        bias,
        scale=scale,
        dropout_rate=dropout_rate,
        dropout_rng=dropout_rng,
        deterministic=deterministic,
        logits_dtype=logits_dtype,
    )
