"""Scaled dot-product attention cores with a pluggable TPU backend.

Functional equivalent of the einsum pipeline inside the reference's
``AttentionBlock`` (/root/reference/models/layers/attentions/attention.py:39-57):
``logits = einsum('...qhd,...khd->...hqk', q, k); softmax; einsum('...hqk,...khd->...qhd')``.

Layout convention everywhere in this framework: ``[batch..., length, heads, head_dim]``
(the natural output of ``nn.DenseGeneral`` head-splitting), matching the
reference. The Pallas path transposes to ``[B*H, L, D]`` internally.

``backend``:
  - ``'xla'``    — pure jnp/einsum; the numerics reference. Supports bias,
                   attention dropout, arbitrary leading batch dims.
  - ``'pallas'`` — fused Pallas TPU flash-attention kernel
                   (:mod:`sav_tpu.ops.flash_attention`). Deterministic only
                   (attention dropout falls back to XLA).
  - ``'auto'``   — measured-crossover dispatch on TPU (else xla). Benchmarked
                   on v5e (PERF.md): at the model zoo's short sequences
                   (197–785 tokens) XLA's batched-matmul attention beats
                   every flash kernel — including the tuned stock one — by
                   ~2×, because the L² logits easily fit HBM and the MXU
                   stays busy; the fused kernel's win is *memory*: it keeps
                   O(L²) out of HBM entirely, which is what long-context /
                   ring-attention shapes need. ``auto`` therefore picks
                   pallas only when the dense fp32 logits would be
                   HBM-prohibitive and xla otherwise.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from sav_tpu.ops import flash_attention as _flash


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:  # pragma: no cover - no backend at all
        return False


# 'auto' flips to the fused kernel when materializing the [B, H, Lq, Lk]
# fp32 logits (fwd + bwd residual ≈ 3 copies) would eat this much HBM —
# beyond it the XLA path thrashes or OOMs while flash stays O(L·D).
_AUTO_PALLAS_LOGITS_BYTES = 2 << 30


def _dense_logits_bytes(query, key) -> int:
    b, lq, h, _ = query.shape
    return 3 * 4 * b * h * lq * key.shape[1]


def xla_attention(
    query: jax.Array,
    key: jax.Array,
    value: jax.Array,
    bias: Optional[jax.Array] = None,
    *,
    scale: Optional[float] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    deterministic: bool = True,
    logits_dtype=jnp.float32,
) -> jax.Array:
    """Reference attention core in pure XLA ops.

    Args:
      query: ``[..., q_len, heads, head_dim]``.
      key, value: ``[..., kv_len, heads, head_dim]``.
      bias: optional logits bias broadcastable to ``[..., heads, q_len, kv_len]``.
      scale: logit scale; defaults to ``head_dim ** -0.5`` (attention.py:39).
      logits_dtype: dtype for softmax math; fp32 keeps bf16 runs stable.

    Returns:
      ``[..., q_len, heads, head_dim]`` in the query dtype.
    """
    if scale is None:
        scale = query.shape[-1] ** -0.5
    q = query * jnp.asarray(scale, dtype=query.dtype)
    logits = jnp.einsum("...qhd,...khd->...hqk", q, key, preferred_element_type=logits_dtype)
    if bias is not None:
        logits = logits + bias.astype(logits_dtype)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_rate > 0.0 and not deterministic:
        if dropout_rng is None:
            raise ValueError("dropout_rng required for non-deterministic attention dropout")
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = probs * keep.astype(probs.dtype) / (1.0 - dropout_rate)
    probs = probs.astype(value.dtype)
    return jnp.einsum("...hqk,...khd->...qhd", probs, value)


def dot_product_attention(
    query: jax.Array,
    key: jax.Array,
    value: jax.Array,
    bias: Optional[jax.Array] = None,
    *,
    scale: Optional[float] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    deterministic: bool = True,
    backend: Optional[str] = None,
) -> jax.Array:
    """Backend-dispatched attention. See module docstring."""
    backend = backend or "auto"
    if backend not in ("auto", "xla", "pallas"):
        raise ValueError(f"unknown attention backend: {backend!r}")

    has_dropout = dropout_rate > 0.0 and not deterministic
    pallas_ok = (
        not has_dropout
        and query.ndim == 4  # [B, L, H, D] — flash path handles the common case
        and key.ndim == 4
        and (bias is None or bias.ndim == 4)
    )
    if backend == "auto":
        big = pallas_ok and (
            _dense_logits_bytes(query, key) > _AUTO_PALLAS_LOGITS_BYTES
        )
        backend = "pallas" if (big and _on_tpu()) else "xla"
    if backend == "pallas":
        if not pallas_ok:
            raise ValueError(
                "pallas attention backend requires 4-D [B, L, H, D] inputs and "
                "deterministic mode (attention dropout runs on the XLA path)"
            )
        return _flash.flash_attention(query, key, value, bias, scale=scale)
    return xla_attention(
        query,
        key,
        value,
        bias,
        scale=scale,
        dropout_rate=dropout_rate,
        dropout_rng=dropout_rng,
        deterministic=deterministic,
    )
