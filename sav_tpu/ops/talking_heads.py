"""Fused talking-heads attention (CaiT trunk) — Pallas TPU kernel.

Talking-heads attention (reference: /root/reference/models/layers/attentions/
talking_heads.py:5-14 applied at attention.py:44-52) mixes attention *logits*
across heads before the softmax and mixes the *probabilities* after it:

    s'_i = Σ_h W_pre[h, i] · s_h        (pre-softmax head mix)
    p_i  = softmax(s'_i)
    p'_i = Σ_h W_post[h, i] · p_h       (post-softmax head mix)
    out_i = p'_i · V_i

The head coupling breaks the per-head independence the generic flash kernel
relies on, so this kernel keeps **all heads of one batch element in a single
grid cell** and mixes them in VMEM. CaiT's talking-heads trunk runs at short
sequence lengths by design (196 tokens for the named CaiT configs), so the
whole K/V fits one block and the softmax is exact row-wise — no online
accumulation needed. The ``[B, H, L, L]`` logits therefore never exist in
HBM on the forward pass; the backward is an XLA flash-style recompute (the
head mixing makes the blocked backward a 4-way coupled system; dense
recompute at ≤1k tokens is cheap and keeps numerics identical to autodiff).

The ``[H, H]`` mixing matrices ride in SMEM and are read as scalars.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float("-inf")

# Soft cap on the kernel's VMEM working set. The dominant terms per grid
# cell are the per-head logits+probs tiles (2 · H · block_q · kv_len_p · 4 B
# live at once) plus the whole K/V (2 · H · kv_len_p · dim_p · 2 B); the
# budget leaves headroom under the ~16 MB/core VMEM.
VMEM_BUDGET_BYTES = 10 << 20
_DEFAULT_BLOCK_Q = 256


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def fused_eligible(heads: int, kv_len: int, dim: int,
                   block_q: int = _DEFAULT_BLOCK_Q) -> bool:
    """Whether the all-heads-in-cell kernel fits the VMEM budget.

    Used by the ``'auto'`` dispatch so ineligible shapes (many heads ×
    long kv) fall back to XLA instead of failing Mosaic VMEM allocation."""
    kv_len_p = _round_up(kv_len, 128)
    dim_p = _round_up(dim, 128)
    block_q = min(block_q, _round_up(kv_len, 16))
    logits = 2 * heads * block_q * kv_len_p * 4
    kv = 2 * heads * kv_len_p * dim_p * 2
    qo = 2 * heads * block_q * dim_p * 2
    return logits + kv + qo <= VMEM_BUDGET_BYTES


def _th_kernel(q_ref, k_ref, v_ref, wpre_ref, wpost_ref, o_ref, *,
               heads: int, scale: float, kv_len: int, kv_len_p: int):
    """One grid cell = all heads of one batch element × one q block."""
    logits = []
    for h in range(heads):
        s = jax.lax.dot_general(
            q_ref[0, h], k_ref[0, h], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        logits.append(s * scale)

    col = jax.lax.broadcasted_iota(jnp.int32, logits[0].shape, 1)
    probs = []
    for i in range(heads):
        # Pre-softmax mix. Padded kv columns hold Σ_h w·0 = 0 garbage —
        # masked to −inf *after* the mix, exactly where the reference's
        # dense mask would sit.
        mixed = logits[0] * wpre_ref[0, i]
        for h in range(1, heads):
            mixed += logits[h] * wpre_ref[h, i]
        if kv_len != kv_len_p:
            mixed = jnp.where(col < kv_len, mixed, _NEG_INF)
        m = jnp.max(mixed, axis=-1, keepdims=True)
        p = jnp.exp(mixed - m)
        probs.append(p / jnp.sum(p, axis=-1, keepdims=True))

    for i in range(heads):
        post = probs[0] * wpost_ref[0, i]
        for h in range(1, heads):
            post += probs[h] * wpost_ref[h, i]
        v = v_ref[0, i]
        o_ref[0, i] = jax.lax.dot_general(
            post.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(o_ref.dtype)


def _th_forward(q, k, v, w_pre, w_post, scale, block_q, interpret):
    """q/k/v ``[B, L, H, D]``; w_pre/w_post ``[H, H]`` float32."""
    batch, q_len, heads, dim = q.shape
    kv_len = k.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def to_bhld(x):
        return jnp.transpose(x, (0, 2, 1, 3))  # [B, H, L, D]

    dim_p = _round_up(dim, 128)
    block_q = min(block_q, _round_up(q_len, 16))
    q_len_p = _round_up(q_len, block_q)
    kv_len_p = _round_up(kv_len, 128)

    def pad4(x, lp):
        return jnp.pad(
            x, ((0, 0), (0, 0), (0, lp - x.shape[2]), (0, dim_p - x.shape[3]))
        )

    qf = pad4(to_bhld(q), q_len_p)
    kf = pad4(to_bhld(k), kv_len_p)
    vf = pad4(to_bhld(v), kv_len_p)

    kernel = functools.partial(
        _th_kernel,
        heads=heads,
        scale=scale,
        kv_len=kv_len,
        kv_len_p=kv_len_p,
    )
    out = pl.pallas_call(
        kernel,
        grid=(batch, q_len_p // block_q),
        in_specs=[
            pl.BlockSpec(
                (1, heads, block_q, dim_p), lambda b, i: (b, 0, i, 0)
            ),
            pl.BlockSpec(
                (1, heads, kv_len_p, dim_p), lambda b, i: (b, 0, 0, 0)
            ),
            pl.BlockSpec(
                (1, heads, kv_len_p, dim_p), lambda b, i: (b, 0, 0, 0)
            ),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, heads, block_q, dim_p), lambda b, i: (b, 0, i, 0)
        ),
        out_shape=jax.ShapeDtypeStruct(
            (batch, heads, q_len_p, dim_p), q.dtype
        ),
        interpret=interpret,
    )(qf, kf, vf, w_pre.astype(jnp.float32), w_post.astype(jnp.float32))
    out = out[:, :, :q_len, :dim]
    return jnp.transpose(out, (0, 2, 1, 3))


def _th_dense_reference(q, k, v, w_pre, w_post, scale):
    """Dense XLA talking-heads attention (backward recompute + numerics
    cross-check). Mirrors sav_tpu.models.layers.attention.talking_heads_attention."""
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q * jnp.asarray(scale, q.dtype), k,
        preferred_element_type=jnp.float32,
    )
    s = jnp.einsum("hi,bhqk->biqk", w_pre.astype(jnp.float32), s)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.einsum("hi,bhqk->biqk", w_post.astype(jnp.float32), p)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    ).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _th(q, k, v, w_pre, w_post, scale, block_q, interpret):
    return _th_forward(q, k, v, w_pre, w_post, scale, block_q, interpret)


def _th_fwd(q, k, v, w_pre, w_post, scale, block_q, interpret):
    out = _th_forward(q, k, v, w_pre, w_post, scale, block_q, interpret)
    return out, (q, k, v, w_pre, w_post)


def _th_bwd(scale, block_q, interpret, residuals, g):
    q, k, v, w_pre, w_post = residuals
    _, vjp = jax.vjp(
        lambda q, k, v, wp, wq: _th_dense_reference(q, k, v, wp, wq, scale),
        q, k, v, w_pre, w_post,
    )
    return vjp(g)


_th.defvjp(_th_fwd, _th_bwd)


def flash_talking_heads_attention(
    query: jax.Array,
    key: jax.Array,
    value: jax.Array,
    w_pre: jax.Array,
    w_post: jax.Array,
    *,
    scale: Optional[float] = None,
    block_q: int = 256,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused talking-heads attention. See module docstring.

    Args:
      query/key/value: ``[B, L, H, D]``.
      w_pre / w_post: ``[H, H]`` learned head-mixing matrices
        (``mixed_i = Σ_h W[h, i] · head_h``, the reference's einsum
        ``'h i, b h ... -> b i ...'``).
      scale: logit scale, default ``D ** -0.5``.

    Raises:
      ValueError: shape beyond the VMEM budget (whole-K/V-in-VMEM design;
        talking-heads models run short trunks — use the XLA path otherwise).
    """
    if query.ndim != 4:
        raise ValueError(f"expected [B, L, H, D] inputs, got {query.shape}")
    _, kv_len, heads, dim = key.shape
    if not fused_eligible(heads, kv_len, dim, block_q):
        raise ValueError(
            f"fused talking-heads holds all heads' K/V and logits in VMEM; "
            f"heads={heads}, kv_len={kv_len}, dim={dim} exceeds the "
            f"{VMEM_BUDGET_BYTES >> 20} MB budget — use the XLA path"
        )
    if scale is None:
        scale = query.shape[-1] ** -0.5
    return _th(query, key, value, w_pre, w_post, float(scale), block_q, interpret)
