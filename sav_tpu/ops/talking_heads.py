"""Fused talking-heads attention (CaiT trunk) — Pallas TPU kernel.

Talking-heads attention (reference: /root/reference/models/layers/attentions/
talking_heads.py:5-14 applied at attention.py:44-52) mixes attention *logits*
across heads before the softmax and mixes the *probabilities* after it:

    s'_i = Σ_h W_pre[h, i] · s_h        (pre-softmax head mix)
    p_i  = softmax(s'_i)
    p'_i = Σ_h W_post[h, i] · p_h       (post-softmax head mix)
    out_i = p'_i · V_i

The head coupling breaks the per-head independence the generic flash kernel
relies on, so this kernel keeps **all heads of one batch element in a single
grid cell** and mixes them in VMEM. CaiT's talking-heads trunk runs at short
sequence lengths by design (196 tokens for the named CaiT configs), so the
whole K/V fits one block and the softmax is exact row-wise — no online
accumulation needed. The ``[B, H, L, L]`` logits never exist in HBM in
either direction: the backward is also a blocked Pallas kernel
(:func:`_th_bwd_kernel`) that recomputes S/P/P' in VMEM and resolves the
4-way head-mix coupling with elementwise tile reductions for the ``[H, H]``
gradients (no extra matmuls). Shapes beyond its VMEM budget
(:func:`fused_bwd_eligible`) fall back to a dense XLA recompute with
autodiff-identical numerics.

The ``[H, H]`` mixing matrices ride in SMEM and are read as scalars.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float("-inf")

# Soft cap on the kernel's VMEM working set. The dominant terms per grid
# cell are the per-head logits+probs tiles (2 · H · block_q · kv_len_p · 4 B
# live at once) plus the whole K/V (2 · H · kv_len_p · dim_p · 2 B); the
# budget leaves headroom under the ~16 MB/core VMEM.
VMEM_BUDGET_BYTES = 10 << 20
_DEFAULT_BLOCK_Q = 256


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def fused_eligible(heads: int, kv_len: int, dim: int,
                   block_q: int = _DEFAULT_BLOCK_Q) -> bool:
    """Whether the all-heads-in-cell kernel fits the VMEM budget.

    Used by the ``'auto'`` dispatch so ineligible shapes (many heads ×
    long kv) fall back to XLA instead of failing Mosaic VMEM allocation."""
    kv_len_p = _round_up(kv_len, 128)
    dim_p = _round_up(dim, 128)
    block_q = min(block_q, _round_up(kv_len, 16))
    logits = 2 * heads * block_q * kv_len_p * 4
    kv = 2 * heads * kv_len_p * dim_p * 2
    qo = 2 * heads * block_q * dim_p * 2
    return logits + kv + qo <= VMEM_BUDGET_BYTES


def _th_kernel(q_ref, k_ref, v_ref, wpre_ref, wpost_ref, o_ref, *,
               heads: int, scale: float, kv_len: int, kv_len_p: int):
    """One grid cell = all heads of one batch element × one q block."""
    logits = []
    for h in range(heads):
        s = jax.lax.dot_general(
            q_ref[0, h], k_ref[0, h], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        logits.append(s * scale)

    col = jax.lax.broadcasted_iota(jnp.int32, logits[0].shape, 1)
    probs = []
    for i in range(heads):
        # Pre-softmax mix. Padded kv columns hold Σ_h w·0 = 0 garbage —
        # masked to −inf *after* the mix, exactly where the reference's
        # dense mask would sit.
        mixed = logits[0] * wpre_ref[0, i]
        for h in range(1, heads):
            mixed += logits[h] * wpre_ref[h, i]
        if kv_len != kv_len_p:
            mixed = jnp.where(col < kv_len, mixed, _NEG_INF)
        m = jnp.max(mixed, axis=-1, keepdims=True)
        p = jnp.exp(mixed - m)
        probs.append(p / jnp.sum(p, axis=-1, keepdims=True))

    for i in range(heads):
        post = probs[0] * wpost_ref[0, i]
        for h in range(1, heads):
            post += probs[h] * wpost_ref[h, i]
        v = v_ref[0, i]
        o_ref[0, i] = jax.lax.dot_general(
            post.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(o_ref.dtype)


def _th_forward(q, k, v, w_pre, w_post, scale, block_q, interpret):
    """q/k/v ``[B, L, H, D]``; w_pre/w_post ``[H, H]`` float32."""
    batch, q_len, heads, dim = q.shape
    kv_len = k.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def to_bhld(x):
        return jnp.transpose(x, (0, 2, 1, 3))  # [B, H, L, D]

    dim_p = _round_up(dim, 128)
    block_q = min(block_q, _round_up(q_len, 16))
    q_len_p = _round_up(q_len, block_q)
    kv_len_p = _round_up(kv_len, 128)

    def pad4(x, lp):
        return jnp.pad(
            x, ((0, 0), (0, 0), (0, lp - x.shape[2]), (0, dim_p - x.shape[3]))
        )

    qf = pad4(to_bhld(q), q_len_p)
    kf = pad4(to_bhld(k), kv_len_p)
    vf = pad4(to_bhld(v), kv_len_p)

    kernel = functools.partial(
        _th_kernel,
        heads=heads,
        scale=scale,
        kv_len=kv_len,
        kv_len_p=kv_len_p,
    )
    out = pl.pallas_call(
        kernel,
        grid=(batch, q_len_p // block_q),
        in_specs=[
            pl.BlockSpec(
                (1, heads, block_q, dim_p), lambda b, i: (b, 0, i, 0)
            ),
            pl.BlockSpec(
                (1, heads, kv_len_p, dim_p), lambda b, i: (b, 0, 0, 0)
            ),
            pl.BlockSpec(
                (1, heads, kv_len_p, dim_p), lambda b, i: (b, 0, 0, 0)
            ),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, heads, block_q, dim_p), lambda b, i: (b, 0, i, 0)
        ),
        out_shape=jax.ShapeDtypeStruct(
            (batch, heads, q_len_p, dim_p), q.dtype
        ),
        interpret=interpret,
    )(qf, kf, vf, w_pre.astype(jnp.float32), w_post.astype(jnp.float32))
    out = out[:, :, :q_len, :dim]
    return jnp.transpose(out, (0, 2, 1, 3))


def fused_bwd_eligible(heads: int, q_len: int, kv_len: int, dim: int,
                       block_q: int = _DEFAULT_BLOCK_Q) -> bool:
    """Whether the blocked backward's larger VMEM working set fits.

    The backward keeps ~6 per-head f32 logit-sized tiles live at once
    (S, P, P', dP', dS', dS) plus Q/K/V/dO and the dk/dv accumulators —
    stricter than the forward's 2. ``block_q`` is capped by ``q_len``
    exactly as :func:`_th_backward` caps it, so the estimate tracks the
    kernel's real tile size (a single-query class-attention call is far
    cheaper than a square trunk call). Used by the backward dispatch so
    shapes beyond the budget recompute on the XLA path instead."""
    kv_len_p = _round_up(kv_len, 128)
    dim_p = _round_up(dim, 128)
    block_q = min(block_q, _round_up(q_len, 16))
    logit_tiles = 6 * heads * block_q * kv_len_p * 4
    qkv = 4 * heads * kv_len_p * dim_p * 2
    accum = 2 * heads * kv_len_p * dim_p * 4
    return logit_tiles + qkv + accum <= VMEM_BUDGET_BYTES


def _th_bwd_kernel(q_ref, k_ref, v_ref, g_ref, wpre_ref, wpost_ref,
                   dq_ref, dk_ref, dv_ref, dwpre_ref, dwpost_ref, *,
                   heads: int, scale: float, kv_len: int, kv_len_p: int):
    """Blocked talking-heads backward; one cell = all heads of one batch
    element × one q block. No ``[B, H, L, L]`` tensor ever reaches HBM.

    Recomputes S/P/P' flash-style from the q/k residuals, then:

      dP'_i = dO_i·V_iᵀ                 dV_i += P'_iᵀ·dO_i
      dWpost[h,i] += ⟨P_h, dP'_i⟩       dP_h = Σ_i Wpost[h,i]·dP'_i
      dS'_i = P_i ⊙ (dP_i − rowsum(P_i⊙dP_i))
      dWpre[h,i] += ⟨S_h, dS'_i⟩        dS_h = Σ_i Wpre[h,i]·dS'_i
      dQ_h = scale·dS_h·K_h             dK_h += scale·dS_hᵀ·Q_h

    The ⟨·,·⟩ head-mix gradients are elementwise VPU reductions (no
    matmul), and every matmul runs storage-dtype-in / f32-accumulate on
    the MXU. dk/dv/dW accumulate in their output blocks across the
    (sequential, innermost) q-block grid axis.

    Mosaic cannot store rank-0 values to VMEM, so the per-(h, i) scalar
    mix-weight gradients are scattered into an ``[H, H]`` register tile
    via iota masks and written with one full-block store per cell."""
    qi = pl.program_id(1)
    mix_rows = jax.lax.broadcasted_iota(jnp.int32, (heads, heads), 0)
    mix_cols = jax.lax.broadcasted_iota(jnp.int32, (heads, heads), 1)

    def at_cell(h, i, val):
        # rank-0 `val` broadcast into the (h, i) slot of an [H, H] tile.
        return jnp.where((mix_rows == h) & (mix_cols == i), val, 0.0)

    @pl.when(qi == 0)
    def _init():
        dk_ref[...] = jnp.zeros_like(dk_ref)
        dv_ref[...] = jnp.zeros_like(dv_ref)
        dwpre_ref[...] = jnp.zeros_like(dwpre_ref)
        dwpost_ref[...] = jnp.zeros_like(dwpost_ref)

    col = None
    # Recompute per-head raw logits (padded kv columns give exact 0 —
    # K is zero-padded — matching the forward's pre-mix values).
    s = []
    for h in range(heads):
        sh = jax.lax.dot_general(
            q_ref[0, h], k_ref[0, h], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        s.append(sh)
    if kv_len != kv_len_p:
        col = jax.lax.broadcasted_iota(jnp.int32, s[0].shape, 1)

    probs = []
    for i in range(heads):
        mixed = s[0] * wpre_ref[0, i]
        for h in range(1, heads):
            mixed += s[h] * wpre_ref[h, i]
        if col is not None:
            mixed = jnp.where(col < kv_len, mixed, _NEG_INF)
        m = jnp.max(mixed, axis=-1, keepdims=True)
        p = jnp.exp(mixed - m)
        probs.append(p / jnp.sum(p, axis=-1, keepdims=True))

    # dP' and dV per output head; dWpost from direct tile reductions.
    dpost = []
    dwpost_acc = jnp.zeros((heads, heads), jnp.float32)
    for i in range(heads):
        g = g_ref[0, i]
        vi = v_ref[0, i]
        dpi = jax.lax.dot_general(  # dO_i · V_iᵀ : [bq, Lkv]
            g, vi, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dpost.append(dpi)
        post = probs[0] * wpost_ref[0, i]
        for h in range(1, heads):
            post += probs[h] * wpost_ref[h, i]
        dv_ref[0, i] += jax.lax.dot_general(  # P'_iᵀ · dO_i : [Lkv, D]
            post.astype(g.dtype), g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        for h in range(heads):
            dwpost_acc += at_cell(h, i, jnp.sum(probs[h] * dpi))
    dwpost_ref[0] += dwpost_acc

    # Softmax backward per head, then the pre-mix couplings.
    ds_mixed = []
    dwpre_acc = jnp.zeros((heads, heads), jnp.float32)
    for i in range(heads):
        dp = dpost[0] * wpost_ref[i, 0]
        for j in range(1, heads):
            dp += dpost[j] * wpost_ref[i, j]
        pi = probs[i]
        ds = pi * (dp - jnp.sum(pi * dp, axis=-1, keepdims=True))
        ds_mixed.append(ds)
        for h in range(heads):
            dwpre_acc += at_cell(h, i, jnp.sum(s[h] * ds))
    dwpre_ref[0] += dwpre_acc

    for h in range(heads):
        dsh = ds_mixed[0] * wpre_ref[h, 0]
        for i in range(1, heads):
            dsh += ds_mixed[i] * wpre_ref[h, i]
        dsh_lo = dsh.astype(k_ref.dtype)
        dq_ref[0, h] = (
            jax.lax.dot_general(
                dsh_lo, k_ref[0, h], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
        ).astype(dq_ref.dtype)
        dk_ref[0, h] += (
            jax.lax.dot_general(
                dsh_lo, q_ref[0, h], (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
        )


def _th_backward(q, k, v, w_pre, w_post, g, scale, block_q, interpret):
    """Pallas-call wrapper for the blocked backward. Layouts as forward."""
    batch, q_len, heads, dim = q.shape
    kv_len = k.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def to_bhld(x):
        return jnp.transpose(x, (0, 2, 1, 3))

    dim_p = _round_up(dim, 128)
    block_q = min(block_q, _round_up(q_len, 16))
    q_len_p = _round_up(q_len, block_q)
    kv_len_p = _round_up(kv_len, 128)

    def pad4(x, lp):
        return jnp.pad(
            x, ((0, 0), (0, 0), (0, lp - x.shape[2]), (0, dim_p - x.shape[3]))
        )

    qf = pad4(to_bhld(q), q_len_p)
    kf = pad4(to_bhld(k), kv_len_p)
    vf = pad4(to_bhld(v), kv_len_p)
    # Zero-padded cotangent rows make the padded q rows contribute exact
    # zeros to dk/dv/dW (their dP' and dS' rows vanish).
    gf = pad4(to_bhld(g.astype(q.dtype)), q_len_p)

    num_q_blocks = q_len_p // block_q
    kernel = functools.partial(
        _th_bwd_kernel,
        heads=heads,
        scale=scale,
        kv_len=kv_len,
        kv_len_p=kv_len_p,
    )
    whole = lambda b, i: (b, 0, 0, 0)
    dq, dk, dv, dwpre, dwpost = pl.pallas_call(
        kernel,
        grid=(batch, num_q_blocks),
        in_specs=[
            pl.BlockSpec((1, heads, block_q, dim_p), lambda b, i: (b, 0, i, 0)),
            pl.BlockSpec((1, heads, kv_len_p, dim_p), whole),
            pl.BlockSpec((1, heads, kv_len_p, dim_p), whole),
            pl.BlockSpec((1, heads, block_q, dim_p), lambda b, i: (b, 0, i, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, heads, block_q, dim_p), lambda b, i: (b, 0, i, 0)),
            pl.BlockSpec((1, heads, kv_len_p, dim_p), whole),
            pl.BlockSpec((1, heads, kv_len_p, dim_p), whole),
            pl.BlockSpec((1, heads, heads), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, heads, heads), lambda b, i: (b, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch, heads, q_len_p, dim_p), q.dtype),
            jax.ShapeDtypeStruct((batch, heads, kv_len_p, dim_p), jnp.float32),
            jax.ShapeDtypeStruct((batch, heads, kv_len_p, dim_p), jnp.float32),
            jax.ShapeDtypeStruct((batch, heads, heads), jnp.float32),
            jax.ShapeDtypeStruct((batch, heads, heads), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, gf, w_pre.astype(jnp.float32), w_post.astype(jnp.float32))

    def from_bhld(x, l):
        return jnp.transpose(x[:, :, :l, :dim], (0, 2, 1, 3))

    dq = from_bhld(dq, q_len)
    dk = from_bhld(dk, kv_len).astype(k.dtype)
    dv = from_bhld(dv, kv_len).astype(v.dtype)
    dwpre = jnp.sum(dwpre, axis=0).astype(w_pre.dtype)
    dwpost = jnp.sum(dwpost, axis=0).astype(w_post.dtype)
    return dq, dk, dv, dwpre, dwpost


def _th_dense_reference(q, k, v, w_pre, w_post, scale):
    """Dense XLA talking-heads attention (backward recompute + numerics
    cross-check). Mirrors sav_tpu.models.layers.attention.talking_heads_attention."""
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q * jnp.asarray(scale, q.dtype), k,
        preferred_element_type=jnp.float32,
    )
    s = jnp.einsum("hi,bhqk->biqk", w_pre.astype(jnp.float32), s)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.einsum("hi,bhqk->biqk", w_post.astype(jnp.float32), p)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    ).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _th(q, k, v, w_pre, w_post, scale, block_q, interpret):
    return _th_forward(q, k, v, w_pre, w_post, scale, block_q, interpret)


def _th_fwd(q, k, v, w_pre, w_post, scale, block_q, interpret):
    out = _th_forward(q, k, v, w_pre, w_post, scale, block_q, interpret)
    return out, (q, k, v, w_pre, w_post)


def _th_bwd(scale, block_q, interpret, residuals, g):
    q, k, v, w_pre, w_post = residuals
    heads, dim = q.shape[2], q.shape[3]
    if fused_bwd_eligible(heads, q.shape[1], k.shape[1], dim, block_q):
        return _th_backward(q, k, v, w_pre, w_post, g, scale, block_q, interpret)
    # Shapes beyond the backward's VMEM budget: dense XLA recompute
    # (numerics identical to autodiff; the [B,H,L,L] cost returns, but
    # only where the blocked kernel cannot run).
    _, vjp = jax.vjp(
        lambda q, k, v, wp, wq: _th_dense_reference(q, k, v, wp, wq, scale),
        q, k, v, w_pre, w_post,
    )
    return vjp(g)


_th.defvjp(_th_fwd, _th_bwd)


def flash_talking_heads_attention(
    query: jax.Array,
    key: jax.Array,
    value: jax.Array,
    w_pre: jax.Array,
    w_post: jax.Array,
    *,
    scale: Optional[float] = None,
    block_q: int = 256,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused talking-heads attention. See module docstring.

    Args:
      query/key/value: ``[B, L, H, D]``.
      w_pre / w_post: ``[H, H]`` learned head-mixing matrices
        (``mixed_i = Σ_h W[h, i] · head_h``, the reference's einsum
        ``'h i, b h ... -> b i ...'``).
      scale: logit scale, default ``D ** -0.5``.

    Raises:
      ValueError: shape beyond the VMEM budget (whole-K/V-in-VMEM design;
        talking-heads models run short trunks — use the XLA path otherwise).
    """
    if query.ndim != 4:
        raise ValueError(f"expected [B, L, H, D] inputs, got {query.shape}")
    _, kv_len, heads, dim = key.shape
    if not fused_eligible(heads, kv_len, dim, block_q):
        raise ValueError(
            f"fused talking-heads holds all heads' K/V and logits in VMEM; "
            f"heads={heads}, kv_len={kv_len}, dim={dim} exceeds the "
            f"{VMEM_BUDGET_BYTES >> 20} MB budget — use the XLA path"
        )
    if scale is None:
        scale = query.shape[-1] ** -0.5
    return _th(query, key, value, w_pre, w_post, float(scale), block_q, interpret)
