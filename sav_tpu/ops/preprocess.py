"""Device-side batch preprocessing: normalize + CutMix/MixUp under jit.

TPU-first alternative to finishing batches on the host
(sav_tpu/data/mix.py + the pipeline's normalize stage): the host ships
post-augment **uint8** images — 4x fewer host->device bytes than f32,
2x fewer than late-bf16 — and the jitted train step normalizes and mixes
on device, where both are bandwidth-trivial fused elementwise work. The
host also sheds its normalize/mix arithmetic (it is the scarce resource
on TPU machines; SURVEY.md §7).

Semantics mirror the host path op-for-op so the two are interchangeable
(tests assert it): mixes act on 0..255 values *before* normalization
(convex combinations and box-masks commute with the per-channel affine
normalize — sav_tpu/data/mix.py docstring), MixUp draws one
Beta(alpha, alpha) ratio per example against the roll-by-1 partner
(reference input_pipeline.py:169-178 attaches per-example ratios),
CutMix boxes are per-example with kept-area label ratios
(:166-168, 248-282), and the combined policy runs MixUp on the first
half / CutMix on the second (``my_mixup_cutmix``, :328-350). The only
deliberate difference is the RNG stream: ``jax.random`` from the step
seed instead of TF's — distributions are identical, so training
statistics match while batches become replayable from (seed, step).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from sav_tpu.data.constants import MEAN_RGB, STDDEV_RGB


def normalize_images(images: jax.Array, dtype=jnp.float32) -> jax.Array:
    """(x - MEAN_RGB) / STDDEV_RGB on 0..255 input, cast to ``dtype``.

    Matches the host `_normalize` (pipeline.py) exactly; accepts uint8 or
    float input. Statistics are applied in f32 before the storage cast so
    uint8 and pre-floated inputs produce identical values.
    """
    x = images.astype(jnp.float32)
    mean = jnp.asarray(MEAN_RGB, jnp.float32).reshape(1, 1, 1, 3)
    std = jnp.asarray(STDDEV_RGB, jnp.float32).reshape(1, 1, 1, 3)
    return ((x - mean) / std).astype(dtype)


def mixup(
    rng: jax.Array, images: jax.Array, labels: jax.Array, alpha: float = 0.2
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """images <- r*x + (1-r)*roll(x), r ~ Beta(alpha, alpha) per example.

    Returns (mixed_images, mix_labels, ratio); images are 0..255 floats.
    """
    n = images.shape[0]
    x = images.astype(jnp.float32)
    ratio = jax.random.beta(rng, alpha, alpha, (n,))
    r = ratio[:, None, None, None]
    mixed = r * x + (1.0 - r) * jnp.roll(x, 1, axis=0)
    return mixed, jnp.roll(labels, 1, axis=0), ratio


def _cutmix_mask(
    rng: jax.Array, n: int, height: int, width: int
) -> Tuple[jax.Array, jax.Array]:
    """Per-example keep-mask ``[n, h, w, 1]`` + kept-area ratio ``[n]``.

    Box side fraction sqrt(1 - lam), lam ~ Beta(1,1) = U(0,1) — the
    reference's ``cutmix_padding`` distribution; mirrors
    sav_tpu/data/mix.py:_cutmix_mask including its center/clip geometry.
    """
    k_lam, k_cy, k_cx = jax.random.split(rng, 3)
    lam = jax.random.uniform(k_lam, (n,))
    cut = jnp.sqrt(1.0 - lam)
    cut_h = (cut * height).astype(jnp.int32)
    cut_w = (cut * width).astype(jnp.int32)
    cy = jax.random.randint(k_cy, (n,), 0, height)
    cx = jax.random.randint(k_cx, (n,), 0, width)
    y0 = jnp.clip(cy - cut_h // 2, 0, height)[:, None, None, None]
    y1 = jnp.clip(cy + cut_h // 2, 0, height)[:, None, None, None]
    x0 = jnp.clip(cx - cut_w // 2, 0, width)[:, None, None, None]
    x1 = jnp.clip(cx + cut_w // 2, 0, width)[:, None, None, None]
    rows = jax.lax.broadcasted_iota(jnp.int32, (1, height, 1, 1), 1)
    cols = jax.lax.broadcasted_iota(jnp.int32, (1, 1, width, 1), 2)
    inside = (rows >= y0) & (rows < y1) & (cols >= x0) & (cols < x1)
    keep = 1.0 - inside.astype(jnp.float32)
    ratio = jnp.mean(keep, axis=(1, 2, 3))
    return keep, ratio


def cutmix(
    rng: jax.Array, images: jax.Array, labels: jax.Array, alpha: float = 1.0
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Paste a random box from the rolled partner; label ratio = kept area."""
    del alpha  # Beta(1, 1) like the reference's cutmix_padding
    n, h, w = images.shape[0], images.shape[1], images.shape[2]
    x = images.astype(jnp.float32)
    keep, ratio = _cutmix_mask(rng, n, h, w)
    mixed = keep * x + (1.0 - keep) * jnp.roll(x, 1, axis=0)
    return mixed, jnp.roll(labels, 1, axis=0), ratio


def mixup_and_cutmix(
    rng: jax.Array,
    images: jax.Array,
    labels: jax.Array,
    *,
    mixup_alpha: float = 0.2,
    cutmix_alpha: float = 1.0,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """MixUp on the first half of the batch, CutMix on the second
    (roll-partners inside each half), like the host combined policy."""
    k_mu, k_cm = jax.random.split(rng)
    half = images.shape[0] // 2
    mu_x, mu_l, mu_r = mixup(k_mu, images[:half], labels[:half], mixup_alpha)
    cm_x, cm_l, cm_r = cutmix(k_cm, images[half:], labels[half:], cutmix_alpha)
    return (
        jnp.concatenate([mu_x, cm_x], axis=0),
        jnp.concatenate([mu_l, cm_l], axis=0),
        jnp.concatenate([mu_r, cm_r], axis=0),
    )


def apply_mixes(
    rng: jax.Array, images: jax.Array, labels: jax.Array, spec
) -> Tuple[jax.Array, Optional[jax.Array], Optional[jax.Array]]:
    """Apply the mixes an :class:`AugmentSpec` selects (device analogue of
    sav_tpu/data/mix.py:apply_mixes). Returns
    ``(images_0_255, mix_labels | None, ratio | None)``.
    """
    if spec is None:
        return images.astype(jnp.float32), None, None
    if spec.cutmix and spec.mixup:
        x, ml, r = mixup_and_cutmix(
            rng,
            images,
            labels,
            mixup_alpha=spec.mixup_alpha,
            cutmix_alpha=spec.cutmix_alpha,
        )
        return x, ml, r
    if spec.mixup:
        return mixup(rng, images, labels, spec.mixup_alpha)
    if spec.cutmix:
        return cutmix(rng, images, labels, spec.cutmix_alpha)
    return images.astype(jnp.float32), None, None
