"""Fused Pallas TPU flash attention.

The TPU execution backend for every attention family in the layer zoo
(SURVEY.md §2.1, BASELINE.json north star): a blockwise online-softmax kernel
that streams K/V tiles through VMEM, keeps the running ``(max, sum, acc)``
statistics in scratch, and never materializes the ``[B, H, Lq, Lk]`` logits
in HBM. An optional additive bias input carries 2-D relative-position logits
(BoTNet) or masks through the fused softmax.

Differentiation: ``flash_attention`` is a ``jax.custom_vjp``. Without a
bias, the backward is fully blocked Pallas too: the forward saves only the
per-row logsumexp (broadcast across one 128-lane tile, the TPU-friendly
layout), and two kernels recompute probabilities tile-by-tile to produce
dq (kv-innermost grid) and dk/dv (q-innermost grid) — the ``[B, H, Lq,
Lk]`` probability matrix never exists in HBM in either direction. With an
additive bias that requires a gradient, the backward falls back to an XLA
flash-style recompute (the dbias reduction needs the dense ``ds``).

Numerics: logits/softmax/accumulation in float32 regardless of input dtype;
the P·V matmul runs in the value dtype on the MXU (bf16 in, f32 accumulate).
Cross-checked against :func:`sav_tpu.ops.attention.xla_attention` in
``tests/test_flash_attention.py``.

On non-TPU backends the kernel runs in Pallas interpreter mode, so the same
code path is testable on the CPU mesh.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = float("-inf")


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _pick_block_b(bh: int, *, force_one: bool = False) -> int:
    """Batch·head slices per grid cell. Grid-cell issue overhead on TPU is
    ~µs-scale, so short-sequence shapes (few kv blocks per cell) want several
    bh slices batched into one cell; 8 × block 256 stays well inside VMEM."""
    if force_one:
        return 1
    for bb in (8, 4, 2):
        if bh % bb == 0:
            return bb
    return 1


def _online_softmax_step(s, v, o_ref, m_scr, l_scr, acc_scr, ki,
                         num_kv_blocks, bi, lse_ref=None):
    """Shared flash epilogue for one batch·head slice ``bi``: fold this
    block's logits ``s`` into the running (max, sum, acc) statistics; write
    the normalized output (and, when ``lse_ref`` is given, the per-row
    logsumexp the blocked backward needs) on the last kv block."""
    m_prev = m_scr[bi, :, 0:1]
    l_prev = l_scr[bi, :, 0:1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)
    l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
    m_scr[bi] = jnp.broadcast_to(m_new, m_scr.shape[1:])
    l_scr[bi] = jnp.broadcast_to(l_new, l_scr.shape[1:])
    pv = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc_scr[bi] = acc_scr[bi] * alpha + pv

    @pl.when(ki == num_kv_blocks - 1)
    def _finalize():
        o_ref[bi] = (acc_scr[bi] / l_scr[bi, :, 0:1]).astype(o_ref.dtype)
        if lse_ref is not None:
            # Combined logsumexp, broadcast across the lane tile so the
            # backward reads it with no relayout.
            lse_ref[bi] = m_scr[bi] + jnp.log(l_scr[bi])


def _kernel(
    q_ref,
    k_ref,
    v_ref,
    *rest,
    has_bias: bool,
    with_lse: bool,
    scale: float,
    kv_len: int,
    block_b: int,
    block_kv: int,
    num_kv_blocks: int,
):
    """Online-softmax flash kernel;
    ``rest`` = ([bias_ref], o_ref, [lse_ref], m, l, acc).

    The leading grid axis carries ``block_b`` batch·head slices per cell
    (unrolled loop below): TPU grid-cell issue overhead is ~µs-scale, so at
    small sequence lengths a [B·H, 1, 1]-cell grid is overhead-bound — the
    dominant cost at DeiT shapes, measured on v5e."""
    bias_ref = rest[0] if has_bias else None
    rest = rest[1 if has_bias else 0 :]
    if with_lse:
        o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
        lse_ref = None
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    for bi in range(block_b):
        q = q_ref[bi]  # [block_q, d]
        k = k_ref[bi]  # [block_kv, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * scale
        if has_bias:
            s = s + bias_ref[bi].astype(jnp.float32)
        if kv_len % block_kv != 0:
            col = ki * block_kv + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(col < kv_len, s, _NEG_INF)

        _online_softmax_step(s, v_ref[bi], o_ref, m_scr, l_scr, acc_scr, ki,
                             num_kv_blocks, bi, lse_ref=lse_ref)


def _flash_forward(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    bias: Optional[jax.Array],
    scale: float,
    block_q: int,
    block_kv: int,
    interpret: Optional[bool],
    with_lse: bool = False,
):
    """Run the kernel. Layout in/out: ``[B, L, H, D]``.

    With ``with_lse`` also returns the per-row logsumexp as
    ``[B·H, padded_q_len, 128]`` f32 (value broadcast across the lane dim) —
    the residual the blocked backward consumes as-is.
    """
    batch, q_len, heads, dim = q.shape
    kv_len = k.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # [B, L, H, D] -> [B*H, L, D]
    def to_bhld(x):
        b, l, h, d = x.shape
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, l, d)

    qf, kf, vf = to_bhld(q), to_bhld(k), to_bhld(v)

    dim_p = _round_up(dim, 128)
    block_q = min(block_q, _round_up(q_len, 16))
    block_kv = min(block_kv, _round_up(kv_len, 16))
    q_len_p = _round_up(q_len, block_q)
    kv_len_p = _round_up(kv_len, block_kv)

    def pad3(x, lp):
        return jnp.pad(x, ((0, 0), (0, lp - x.shape[1]), (0, dim_p - x.shape[2])))

    qf, kf, vf = pad3(qf, q_len_p), pad3(kf, kv_len_p), pad3(vf, kv_len_p)

    shared_bias = False
    if bias is not None:
        bias = jnp.broadcast_to(bias, bias.shape[:-2] + (q_len, kv_len))
        bb, bh = bias.shape[0], bias.shape[1]
        if (bb, bh) not in ((batch, heads), (1, 1)):
            bias = jnp.broadcast_to(bias, (batch, heads) + bias.shape[-2:])
            bb, bh = batch, heads
        shared_bias = bb * bh == 1

    block_b = _pick_block_b(batch * heads, force_one=shared_bias)
    num_q_blocks = q_len_p // block_q
    num_kv_blocks = kv_len_p // block_kv
    grid = (batch * heads // block_b, num_q_blocks, num_kv_blocks)

    in_specs = [
        pl.BlockSpec((block_b, block_q, dim_p), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((block_b, block_kv, dim_p), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((block_b, block_kv, dim_p), lambda b, i, j: (b, j, 0)),
    ]
    args = [qf, kf, vf]
    if bias is not None:
        biasf = bias.reshape(-1, q_len, kv_len)
        biasf = jnp.pad(
            biasf, ((0, 0), (0, q_len_p - q_len), (0, kv_len_p - kv_len))
        )
        if shared_bias:
            bias_index = lambda b, i, j: (0, i, j)
        else:
            bias_index = lambda b, i, j: (b, i, j)
        in_specs.append(pl.BlockSpec((block_b, block_q, block_kv), bias_index))
        args.append(biasf)

    kernel = functools.partial(
        _kernel,
        has_bias=bias is not None,
        with_lse=with_lse,
        scale=scale,
        kv_len=kv_len,
        block_b=block_b,
        block_kv=block_kv,
        num_kv_blocks=num_kv_blocks,
    )

    out_specs = [
        pl.BlockSpec((block_b, block_q, dim_p), lambda b, i, j: (b, i, 0))
    ]
    out_shape = [jax.ShapeDtypeStruct((batch * heads, q_len_p, dim_p), q.dtype)]
    if with_lse:
        out_specs.append(
            pl.BlockSpec((block_b, block_q, 128), lambda b, i, j: (b, i, 0))
        )
        out_shape.append(
            jax.ShapeDtypeStruct((batch * heads, q_len_p, 128), jnp.float32)
        )

    outs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_b, block_q, 128), jnp.float32),
            pltpu.VMEM((block_b, block_q, 128), jnp.float32),
            pltpu.VMEM((block_b, block_q, dim_p), jnp.float32),
        ],
        interpret=interpret,
    )(*args)

    out = outs[0][:, :q_len, :dim]
    out = out.reshape(batch, heads, q_len, dim)
    out = jnp.transpose(out, (0, 2, 1, 3))
    if with_lse:
        return out, outs[1]
    return out


# ---------------------------------------------------------------------------
# Blocked Pallas backward (no-bias path). Standard flash backward with the
# normalized-probability formulation: the forward saves lse = m + log(l),
# so p = exp(s − lse) is already normalized, and with
# delta_i = Σ_d dO_id · O_id the gradients are
#   ds = p ⊙ (dO·Vᵀ − delta),  dq = scale·ds·K,  dk = scale·dsᵀ·Q,
#   dv = pᵀ·dO.
# dq uses a kv-innermost grid (accumulator indexed by q block); dk/dv use a
# q-innermost grid (accumulators indexed by kv block). All matmuls run
# bf16-in/f32-accumulate on the MXU — feeding fp32 operands to the MXU would
# run it at a fraction of peak for no accuracy gain (same policy as the XLA
# recompute path below).
# ---------------------------------------------------------------------------


def _lanes(x: jax.Array, n: int) -> jax.Array:
    """Expand a [rows, 128] lane-broadcast tile to ``n`` lanes."""
    if n == 128:
        return x
    if n % 128 == 0:
        return jnp.tile(x, (1, n // 128))
    return jnp.broadcast_to(x[:, 0:1], (x.shape[0], n))


class _BwdGeom(NamedTuple):
    """Shared padded operands + geometry for the blocked backward drivers."""

    qf: jax.Array
    kf: jax.Array
    vf: jax.Array
    dof: jax.Array
    delta: jax.Array
    batch: int
    heads: int
    q_len: int
    kv_len: int
    dim: int
    dim_p: int
    block_q: int
    block_kv: int
    q_len_p: int
    kv_len_p: int

    def unprep(self, x: jax.Array, l: int) -> jax.Array:
        """Padded ``[B·H, L_p, D_p]`` → ``[B, L, H, D]``."""
        x = x[:, :l, : self.dim].reshape(self.batch, self.heads, l, self.dim)
        return jnp.transpose(x, (0, 2, 1, 3))


def lse_padded_layout(lse: jax.Array, q_len: int, block_q: int) -> jax.Array:
    """``[B, H, Lq]`` f32 logsumexp → the ``[B·H, q_len_p, 128]`` broadcast
    residual layout the blocked backward kernels read. Uses the same block
    clamping as :func:`_bwd_prep`, so external callers (e.g. the flash-mode
    ring backward) stay in sync with the drivers' padding geometry."""
    block_q = min(block_q, _round_up(q_len, 16))
    q_len_p = _round_up(q_len, block_q)
    b, h, lq = lse.shape
    flat = lse.reshape(b * h, lq)
    flat = jnp.pad(flat, ((0, 0), (0, q_len_p - lq)))
    return jnp.broadcast_to(flat[:, :, None], flat.shape + (128,))


def _bwd_prep(q, k, v, out, g, block_q, block_kv) -> _BwdGeom:
    """``[B, L, H, D]`` operands → the padded ``[B·H, L_p, D_p]`` layout both
    blocked backward drivers consume, plus ``delta_i = Σ_d dO·O`` broadcast
    across one lane tile (same layout as lse, so kernels read both with no
    relayout). Single source for block clamping and padding geometry."""
    batch, q_len, heads, dim = q.shape
    kv_len = k.shape[1]
    dim_p = _round_up(dim, 128)
    block_q = min(block_q, _round_up(q_len, 16))
    block_kv = min(block_kv, _round_up(kv_len, 16))
    q_len_p = _round_up(q_len, block_q)
    kv_len_p = _round_up(kv_len, block_kv)

    def to_bhld(x):
        b, l, h, d = x.shape
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, l, d)

    def pad3(x, lp):
        return jnp.pad(x, ((0, 0), (0, lp - x.shape[1]), (0, dim_p - x.shape[2])))

    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    delta = jnp.transpose(delta, (0, 2, 1)).reshape(batch * heads, q_len)
    delta = jnp.pad(delta, ((0, 0), (0, q_len_p - q_len)))
    delta = jnp.broadcast_to(delta[:, :, None], delta.shape + (128,))
    return _BwdGeom(
        qf=pad3(to_bhld(q), q_len_p),
        kf=pad3(to_bhld(k), kv_len_p),
        vf=pad3(to_bhld(v), kv_len_p),
        dof=pad3(to_bhld(g), q_len_p),
        delta=delta,
        batch=batch,
        heads=heads,
        q_len=q_len,
        kv_len=kv_len,
        dim=dim,
        dim_p=dim_p,
        block_q=block_q,
        block_kv=block_kv,
        q_len_p=q_len_p,
        kv_len_p=kv_len_p,
    )


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc, *, scale: float, q_len: int, kv_len: int,
                   block_b: int, block_q: int, block_kv: int,
                   num_kv_blocks: int):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    for bi in range(block_b):
        q, k, v, do = q_ref[bi], k_ref[bi], v_ref[bi], do_ref[bi]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        p = jnp.exp(s - _lanes(lse_ref[bi], s.shape[1]))
        if kv_len % block_kv != 0:
            col = ki * block_kv + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1
            )
            p = jnp.where(col < kv_len, p, 0.0)
        if q_len % block_q != 0:
            # Padded (zero) q rows carry a finite lse ≈ log(kv_len), so p is
            # finite garbage, not NaN; their dq rows are sliced off outside.
            # Zero them anyway so the padded rows cost nothing downstream and
            # the invariant "p == 0 outside the real block" holds in both
            # backward kernels.
            row = pl.program_id(1) * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0
            )
            p = jnp.where(row < q_len, p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - _lanes(delta_ref[bi], s.shape[1]))
        dq_acc[bi] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(ki == num_kv_blocks - 1)
    def _write():
        dq_ref[...] = dq_acc[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
                    dv_ref, dk_acc, dv_acc, *, scale: float, q_len: int,
                    block_b: int, block_q: int, num_q_blocks: int):
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    for bi in range(block_b):
        q, k, v, do = q_ref[bi], k_ref[bi], v_ref[bi], do_ref[bi]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [block_q, block_kv]
        p = jnp.exp(s - _lanes(lse_ref[bi], s.shape[1]))
        if q_len % block_q != 0:
            # Padded q rows must not contribute to the dk/dv sums.
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0
            )
            p = jnp.where(row < q_len, p, 0.0)
        dv_acc[bi] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - _lanes(delta_ref[bi], s.shape[1]))
        dk_acc[bi] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(qi == num_q_blocks - 1)
    def _write():
        dk_ref[...] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc[...].astype(dv_ref.dtype)


def _flash_backward_pallas(q, k, v, out, lse, g, scale, block_q, block_kv,
                           interpret):
    """Blocked backward; q/k/v/out/g are ``[B, L, H, D]``, lse is the padded
    ``[B·H, q_len_p, 128]`` forward residual."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    geom = _bwd_prep(q, k, v, out, g, block_q, block_kv)
    qf, kf, vf, dof, delta = geom.qf, geom.kf, geom.vf, geom.dof, geom.delta
    q_len, kv_len = geom.q_len, geom.kv_len
    dim_p, block_q, block_kv = geom.dim_p, geom.block_q, geom.block_kv
    q_len_p, kv_len_p = geom.q_len_p, geom.kv_len_p

    num_q_blocks = q_len_p // block_q
    num_kv_blocks = kv_len_p // block_kv
    bh = geom.batch * geom.heads
    block_b = _pick_block_b(bh)

    qspec = pl.BlockSpec((block_b, block_q, dim_p), lambda b, i, j: (b, i, 0))
    kspec = pl.BlockSpec((block_b, block_kv, dim_p), lambda b, i, j: (b, j, 0))
    rowq = pl.BlockSpec((block_b, block_q, 128), lambda b, i, j: (b, i, 0))

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel,
            scale=scale,
            q_len=q_len,
            kv_len=kv_len,
            block_b=block_b,
            block_q=block_q,
            block_kv=block_kv,
            num_kv_blocks=num_kv_blocks,
        ),
        grid=(bh // block_b, num_q_blocks, num_kv_blocks),
        in_specs=[qspec, kspec, kspec, qspec, rowq, rowq],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((bh, q_len_p, dim_p), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_b, block_q, dim_p), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    # q-innermost grid for dk/dv: block index 1 is the kv block, index 2
    # sweeps q blocks into the accumulators.
    qspec2 = pl.BlockSpec((block_b, block_q, dim_p), lambda b, j, i: (b, i, 0))
    kspec2 = pl.BlockSpec((block_b, block_kv, dim_p), lambda b, j, i: (b, j, 0))
    rowq2 = pl.BlockSpec((block_b, block_q, 128), lambda b, j, i: (b, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel,
            scale=scale,
            q_len=q_len,
            block_b=block_b,
            block_q=block_q,
            num_q_blocks=num_q_blocks,
        ),
        grid=(bh // block_b, num_kv_blocks, num_q_blocks),
        in_specs=[qspec2, kspec2, kspec2, qspec2, rowq2, rowq2],
        out_specs=[kspec2, kspec2],
        out_shape=[
            jax.ShapeDtypeStruct((bh, kv_len_p, dim_p), k.dtype),
            jax.ShapeDtypeStruct((bh, kv_len_p, dim_p), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_b, block_kv, dim_p), jnp.float32),
            pltpu.VMEM((block_b, block_kv, dim_p), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    return geom.unprep(dq, q_len), geom.unprep(dk, kv_len), geom.unprep(dv, kv_len)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, bias, scale, block_q, block_kv, interpret):
    return _flash_forward(q, k, v, bias, scale, block_q, block_kv, interpret)


def _flash_fwd(q, k, v, bias, scale, block_q, block_kv, interpret):
    if bias is None:
        out, lse = _flash_forward(
            q, k, v, bias, scale, block_q, block_kv, interpret, with_lse=True
        )
        return out, (q, k, v, bias, out, lse)
    out = _flash_forward(q, k, v, bias, scale, block_q, block_kv, interpret)
    return out, (q, k, v, bias, None, None)


def _flash_bwd(scale, block_q, block_kv, interpret, residuals, g):
    """Backward dispatch: blocked Pallas kernels when there is no bias;
    XLA flash-style recompute when a dbias is needed (the dense ``ds`` is
    unavoidable for the bias gradient)."""
    q, k, v, bias, out, lse = residuals
    if bias is None:
        dq, dk, dv = _flash_backward_pallas(
            q, k, v, out, lse, g, scale, block_q, block_kv, interpret
        )
        return dq, dk, dv, None
    del block_q, block_kv, interpret
    return _dense_recompute_bwd(q, k, v, bias, g, scale)


def _dense_recompute_bwd(q, k, v, bias, g, scale):
    """XLA flash-style recompute backward for the biased path — shared by
    this kernel and the fused short-sequence kernel
    (:mod:`sav_tpu.ops.fused_attention`): a dense dbias is O(L²) by
    construction, so the recompute materializes nothing the caller's bias
    gradient doesn't already require."""
    mm_dtype = q.dtype
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    p = jax.nn.softmax(s, axis=-1)  # [B, H, Lq, Lk] fp32
    p_mm = p.astype(mm_dtype)
    g_mm = g.astype(mm_dtype)
    dv = jnp.einsum("bhqk,bqhd->bkhd", p_mm, g_mm, preferred_element_type=jnp.float32)
    dp = jnp.einsum(
        "bqhd,bkhd->bhqk", g_mm, v.astype(mm_dtype),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - jnp.sum(p * dp, axis=-1, keepdims=True))  # fp32
    ds_mm = ds.astype(mm_dtype)
    dq = jnp.einsum(
        "bhqk,bkhd->bqhd", ds_mm, k.astype(mm_dtype),
        preferred_element_type=jnp.float32,
    ) * scale
    dk = jnp.einsum(
        "bhqk,bqhd->bkhd", ds_mm, q.astype(mm_dtype),
        preferred_element_type=jnp.float32,
    ) * scale
    if bias is not None:
        dbias = ds
        # Un-broadcast to the original bias shape.
        for axis in range(dbias.ndim):
            if bias.shape[axis] == 1 and dbias.shape[axis] != 1:
                dbias = jnp.sum(dbias, axis=axis, keepdims=True)
        dbias = dbias.astype(bias.dtype)
    else:
        dbias = None
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype), dbias


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    query: jax.Array,
    key: jax.Array,
    value: jax.Array,
    bias: Optional[jax.Array] = None,
    *,
    scale: Optional[float] = None,
    block_q: int = 256,
    block_kv: int = 256,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused flash attention.

    Args:
      query: ``[B, q_len, heads, head_dim]``.
      key, value: ``[B, kv_len, heads, head_dim]``.
      bias: optional additive logits bias, broadcastable to
        ``[B, heads, q_len, kv_len]`` (e.g. BoTNet relative-position logits).
      scale: logit scale, default ``head_dim ** -0.5``.
      block_q / block_kv: VMEM tile sizes (clamped for short sequences).
        Default 256: the v5e block sweep (now tools/attn_tune.py, PERF.md §5)
        measured 256/256 ~1.6x faster than 128/128 at model-zoo shapes.
      interpret: force Pallas interpreter mode; default = auto (on for non-TPU).

    Returns:
      ``[B, q_len, heads, head_dim]`` in the query dtype.
    """
    if query.ndim != 4:
        raise ValueError(f"expected [B, L, H, D] inputs, got {query.shape}")
    if scale is None:
        scale = query.shape[-1] ** -0.5
    if bias is not None and bias.ndim != 4:
        raise ValueError(f"bias must be 4-D broadcastable, got {bias.shape}")
    return _flash(query, key, value, bias, float(scale), block_q, block_kv, interpret)

# ---------------------------------------------------------------------------
# BoTNet 2-D relative-position flash attention (SURVEY.md §7 "hard parts"):
# the rel_h + rel_w logits are folded into the flash inner loop instead of
# materializing the [B, heads, L, L] bias in HBM. The learned tables enter
# as *compact* per-axis logits [B, heads, L, 2W-1] (a small XLA einsum);
# the kernel expands them to the block's [block_q, block_kv] bias with iota
# index arithmetic and 2W-1 + 2H-1 unrolled masked adds — no gathers.
# ---------------------------------------------------------------------------


def _rel_selection_mats(ki, block_kv, wp, hp, width):
    """Iota-built 0/1 selection matrices for one kv block:
    ``S_w[r, c] = (kw(ki·block_kv + c) == r)`` (and ``kh`` for S_h), so
    ``bias_blk = rw_abs_blk @ S_w + rh_abs_blk @ S_h`` — two small MXU
    matmuls instead of a gather. Shared by the forward and both backward
    kernels (the backward's ``d_rw = dS @ S_wᵀ`` is the exact transpose)."""

    def selection(rows, key_coord):
        col = ki * block_kv + jax.lax.broadcasted_iota(
            jnp.int32, (rows, block_kv), 1
        )
        row = jax.lax.broadcasted_iota(jnp.int32, (rows, block_kv), 0)
        return (key_coord(col) == row).astype(jnp.float32)

    sel_w = selection(wp, lambda c: c % width)
    sel_h = selection(hp, lambda c: c // width)
    return sel_w, sel_h


def _rel_bias_block(rw, rh, sel_w, sel_h):
    bias = jax.lax.dot_general(
        rw, sel_w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    return bias + jax.lax.dot_general(
        rh, sel_h, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _rel_kernel(
    q_ref,
    k_ref,
    v_ref,
    rw_ref,
    rh_ref,
    o_ref,
    *rest,
    scale: float,
    kv_len: int,
    block_kv: int,
    num_kv_blocks: int,
    width: int,
    with_lse: bool,
):
    if with_lse:
        lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        (m_scr, l_scr, acc_scr), lse_ref = rest, None
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q = q_ref[0]
    k = k_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    s = s * scale

    # Expand the absolute per-axis logits to this block's bias:
    #   bias[q, k] = rw_abs[q, kw(k)] + rh_abs[q, kh(k)].
    # Padded rows of rw/rh are zero and padded selection rows never match,
    # so padding contributes nothing; padded kv columns are masked below.
    rw = rw_ref[0]  # [block_q, pad(W)] f32
    rh = rh_ref[0]  # [block_q, pad(H)] f32
    sel_w, sel_h = _rel_selection_mats(
        ki, block_kv, rw.shape[1], rh.shape[1], width
    )
    s = s + _rel_bias_block(rw, rh, sel_w, sel_h)

    if num_kv_blocks * block_kv != kv_len:
        kcol = ki * block_kv + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(kcol < kv_len, s, _NEG_INF)

    _online_softmax_step(s, v_ref[0], o_ref, m_scr, l_scr, acc_scr, ki,
                         num_kv_blocks, 0, lse_ref=lse_ref)


def _rel_forward(q, k, v, rw_abs, rh_abs, height, width, scale, block_q,
                 block_kv, interpret, with_lse=False):
    """q/k/v ``[B, L, H, D]``; rw_abs/rh_abs ``[B, heads, L, W / H]`` f32
    absolute per-axis relative-position logits. ``with_lse`` additionally
    returns the ``[B·H, padded_q_len, 128]`` per-row logsumexp residual the
    blocked backward consumes."""
    batch, q_len, heads, dim = q.shape
    kv_len = k.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def to_bhld(x):
        b, l, h, d = x.shape
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, l, d)

    qf, kf, vf = to_bhld(q), to_bhld(k), to_bhld(v)
    dim_p = _round_up(dim, 128)
    block_q = min(block_q, _round_up(q_len, 16))
    block_kv = min(block_kv, _round_up(kv_len, 16))
    q_len_p = _round_up(q_len, block_q)
    kv_len_p = _round_up(kv_len, block_kv)

    def pad3(x, lp):
        return jnp.pad(x, ((0, 0), (0, lp - x.shape[1]), (0, dim_p - x.shape[2])))

    qf, kf, vf = pad3(qf, q_len_p), pad3(kf, kv_len_p), pad3(vf, kv_len_p)

    def prep_compact(c):
        bb, hh, ll, rr = c.shape
        cf = c.reshape(bb * hh, ll, rr).astype(jnp.float32)
        return jnp.pad(
            cf, ((0, 0), (0, q_len_p - ll), (0, _round_up(rr, 128) - rr))
        )

    rwf, rhf = prep_compact(rw_abs), prep_compact(rh_abs)

    num_q_blocks = q_len_p // block_q
    num_kv_blocks = kv_len_p // block_kv
    grid = (batch * heads, num_q_blocks, num_kv_blocks)
    kernel = functools.partial(
        _rel_kernel,
        scale=scale,
        kv_len=kv_len,
        block_kv=block_kv,
        num_kv_blocks=num_kv_blocks,
        width=width,
        with_lse=with_lse,
    )
    out_specs = [
        pl.BlockSpec((1, block_q, dim_p), lambda b, i, j: (b, i, 0))
    ]
    out_shape = [
        jax.ShapeDtypeStruct((batch * heads, q_len_p, dim_p), q.dtype)
    ]
    if with_lse:
        out_specs.append(
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0))
        )
        out_shape.append(
            jax.ShapeDtypeStruct((batch * heads, q_len_p, 128), jnp.float32)
        )
    outs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, dim_p), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_kv, dim_p), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_kv, dim_p), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec(
                (1, block_q, rwf.shape[-1]), lambda b, i, j: (b, i, 0)
            ),
            pl.BlockSpec(
                (1, block_q, rhf.shape[-1]), lambda b, i, j: (b, i, 0)
            ),
        ],
        out_specs=out_specs if with_lse else out_specs[0],
        out_shape=out_shape if with_lse else out_shape[0],
        scratch_shapes=[
            pltpu.VMEM((1, block_q, 128), jnp.float32),
            pltpu.VMEM((1, block_q, 128), jnp.float32),
            pltpu.VMEM((1, block_q, dim_p), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, rwf, rhf)
    out_raw = outs[0] if with_lse else outs
    out = out_raw[:, :q_len, :dim].reshape(batch, heads, q_len, dim)
    out = jnp.transpose(out, (0, 2, 1, 3))
    if with_lse:
        return out, outs[1]
    return out


def compact_to_absolute(cw: jax.Array, ch: jax.Array, height: int,
                        width: int) -> tuple[jax.Array, jax.Array]:
    """Relative-indexed per-axis logits → absolute-indexed.

    ``cw``: ``[B, heads, L, 2W-1]`` (``cw[..., q, r] = q_vec · rel_w[r]``) →
    ``rw_abs [B, heads, L, W]`` with ``rw_abs[..., q, kw] = cw[..., q,
    kw - qw + W - 1]`` — the pad-reshape-slice ``rel_to_abs`` trick, applied
    once in XLA so the kernel only does matmul expansion. Same for ``ch``
    along the height axis.
    """
    from sav_tpu.ops.relative import rel_to_abs

    b, h, l, _ = cw.shape
    rw = rel_to_abs(cw.reshape(b, h, height, width, 2 * width - 1))
    rw_abs = rw.reshape(b, h, l, width)
    ch_t = jnp.swapaxes(ch.reshape(b, h, height, width, 2 * height - 1), 2, 3)
    rh = rel_to_abs(ch_t)  # [b, h, W, H, H] = [b, n, y, x, X]
    rh_abs = jnp.transpose(rh, (0, 1, 3, 2, 4)).reshape(b, h, l, height)
    return rw_abs, rh_abs


def expand_relative_bias(rw_abs: jax.Array, rh_abs: jax.Array, height: int,
                         width: int) -> jax.Array:
    """Absolute per-axis logits → full ``[B, heads, L, L]`` bias.

    ``bias[q, kh·W + kw] = rh_abs[q, kh] + rw_abs[q, kw]`` — a broadcast
    sum, so its autodiff transpose is the reduction the backward needs.
    """
    b, h, l, _ = rw_abs.shape
    bias = rh_abs[..., :, None] + rw_abs[..., None, :]  # [b, h, L, H, W]
    return bias.reshape(b, h, l, l)


def _rel_recompute_ds(q, k, v, do, rw, rh, lse_row, delta_row, ki, qi, *,
                      scale, q_len, kv_len, block_q, block_kv, width):
    """Shared backward recompute for one (q block, kv block) pair: rebuild
    the biased logits, normalize against the forward lse, mask padded
    rows/cols, and return ``(p, ds)``. Single source of recompute semantics
    for both backward kernels (dq and dk/dv)."""
    sel_w, sel_h = _rel_selection_mats(
        ki, block_kv, rw.shape[1], rh.shape[1], width
    )
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    s = s + _rel_bias_block(rw, rh, sel_w, sel_h)
    p = jnp.exp(s - _lanes(lse_row, s.shape[1]))
    if kv_len % block_kv != 0:
        col = ki * block_kv + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        p = jnp.where(col < kv_len, p, 0.0)
    if q_len % block_q != 0:
        row = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        p = jnp.where(row < q_len, p, 0.0)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - _lanes(delta_row, s.shape[1]))
    return p, ds, sel_w, sel_h


def _rel_bwd_dq_kernel(q_ref, k_ref, v_ref, rw_ref, rh_ref, do_ref, lse_ref,
                       delta_ref, dq_ref, drw_ref, drh_ref, dq_acc, drw_acc,
                       drh_acc, *, scale: float, q_len: int, kv_len: int,
                       block_q: int, block_kv: int, num_kv_blocks: int,
                       width: int):
    """dq + per-axis relative-logit gradients, kv-innermost grid.

    dS w.r.t. the bias factors through the selection matmuls:
    ``d_rw = dS @ S_wᵀ`` — the row-sum of dS over key columns sharing a
    width coordinate (and S_h for height). Accumulated per q block, so the
    dense ``[B,H,L,L]`` bias gradient never exists in HBM."""
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)
        drw_acc[...] = jnp.zeros_like(drw_acc)
        drh_acc[...] = jnp.zeros_like(drh_acc)

    q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    _, ds, sel_w, sel_h = _rel_recompute_ds(
        q, k, v, do, rw_ref[0], rh_ref[0], lse_ref[0], delta_ref[0],
        ki, pl.program_id(1), scale=scale, q_len=q_len, kv_len=kv_len,
        block_q=block_q, block_kv=block_kv, width=width,
    )
    dq_acc[0] += jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    drw_acc[0] += jax.lax.dot_general(
        ds, sel_w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    drh_acc[0] += jax.lax.dot_general(
        ds, sel_h, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(ki == num_kv_blocks - 1)
    def _write():
        dq_ref[...] = dq_acc[...].astype(dq_ref.dtype)
        drw_ref[...] = drw_acc[...]
        drh_ref[...] = drh_acc[...]


def _rel_bwd_dkv_kernel(q_ref, k_ref, v_ref, rw_ref, rh_ref, do_ref, lse_ref,
                        delta_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                        scale: float, q_len: int, kv_len: int, block_q: int,
                        block_kv: int, num_q_blocks: int, width: int):
    """dk/dv, q-innermost grid; kv block index is grid axis 1."""
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    p, ds, _, _ = _rel_recompute_ds(
        q, k, v, do, rw_ref[0], rh_ref[0], lse_ref[0], delta_ref[0],
        ki, qi, scale=scale, q_len=q_len, kv_len=kv_len,
        block_q=block_q, block_kv=block_kv, width=width,
    )
    dv_acc[0] += jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dk_acc[0] += jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale

    @pl.when(qi == num_q_blocks - 1)
    def _write():
        dk_ref[...] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc[...].astype(dv_ref.dtype)


def _rel_backward_pallas(q, k, v, rw_abs, rh_abs, out, lse, g, height, width,
                         scale, block_q, block_kv, interpret):
    """Blocked backward for the fused rel-pos kernel. Mirrors
    ``_flash_backward_pallas`` with the bias rebuilt in-kernel and its
    gradient reduced to the compact per-axis ``[B, H, L, W]/[B, H, L, H]``
    tables — ``[B,H,L,L]`` never materializes in either direction."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    geom = _bwd_prep(q, k, v, out, g, block_q, block_kv)
    qf, kf, vf, dof, delta = geom.qf, geom.kf, geom.vf, geom.dof, geom.delta
    q_len, kv_len = geom.q_len, geom.kv_len
    dim_p, block_q, block_kv = geom.dim_p, geom.block_q, geom.block_kv
    q_len_p, kv_len_p = geom.q_len_p, geom.kv_len_p
    batch, heads = geom.batch, geom.heads

    def prep_compact(c):
        bb, hh, ll, rr = c.shape
        cf = c.reshape(bb * hh, ll, rr).astype(jnp.float32)
        return jnp.pad(
            cf, ((0, 0), (0, q_len_p - ll), (0, _round_up(rr, 128) - rr))
        )

    rwf, rhf = prep_compact(rw_abs), prep_compact(rh_abs)
    wp, hp = rwf.shape[-1], rhf.shape[-1]

    num_q_blocks = q_len_p // block_q
    num_kv_blocks = kv_len_p // block_kv
    bh = batch * heads

    qspec = pl.BlockSpec((1, block_q, dim_p), lambda b, i, j: (b, i, 0))
    kspec = pl.BlockSpec((1, block_kv, dim_p), lambda b, i, j: (b, j, 0))
    rwspec = pl.BlockSpec((1, block_q, wp), lambda b, i, j: (b, i, 0))
    rhspec = pl.BlockSpec((1, block_q, hp), lambda b, i, j: (b, i, 0))
    rowq = pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0))

    dq, drw, drh = pl.pallas_call(
        functools.partial(
            _rel_bwd_dq_kernel,
            scale=scale,
            q_len=q_len,
            kv_len=kv_len,
            block_q=block_q,
            block_kv=block_kv,
            num_kv_blocks=num_kv_blocks,
            width=width,
        ),
        grid=(bh, num_q_blocks, num_kv_blocks),
        in_specs=[qspec, kspec, kspec, rwspec, rhspec, qspec, rowq, rowq],
        out_specs=[qspec, rwspec, rhspec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, q_len_p, dim_p), q.dtype),
            jax.ShapeDtypeStruct((bh, q_len_p, wp), jnp.float32),
            jax.ShapeDtypeStruct((bh, q_len_p, hp), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, block_q, dim_p), jnp.float32),
            pltpu.VMEM((1, block_q, wp), jnp.float32),
            pltpu.VMEM((1, block_q, hp), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, rwf, rhf, dof, lse, delta)

    qspec2 = pl.BlockSpec((1, block_q, dim_p), lambda b, j, i: (b, i, 0))
    kspec2 = pl.BlockSpec((1, block_kv, dim_p), lambda b, j, i: (b, j, 0))
    rwspec2 = pl.BlockSpec((1, block_q, wp), lambda b, j, i: (b, i, 0))
    rhspec2 = pl.BlockSpec((1, block_q, hp), lambda b, j, i: (b, i, 0))
    rowq2 = pl.BlockSpec((1, block_q, 128), lambda b, j, i: (b, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _rel_bwd_dkv_kernel,
            scale=scale,
            q_len=q_len,
            kv_len=kv_len,
            block_q=block_q,
            block_kv=block_kv,
            num_q_blocks=num_q_blocks,
            width=width,
        ),
        grid=(bh, num_kv_blocks, num_q_blocks),
        in_specs=[qspec2, kspec2, kspec2, rwspec2, rhspec2, qspec2, rowq2,
                  rowq2],
        out_specs=[kspec2, kspec2],
        out_shape=[
            jax.ShapeDtypeStruct((bh, kv_len_p, dim_p), k.dtype),
            jax.ShapeDtypeStruct((bh, kv_len_p, dim_p), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, block_kv, dim_p), jnp.float32),
            pltpu.VMEM((1, block_kv, dim_p), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, rwf, rhf, dof, lse, delta)

    def from_compact(x, rr, ref):
        return x[:, :q_len, :rr].reshape(batch, heads, q_len, rr).astype(
            ref.dtype
        )

    return (
        geom.unprep(dq, q_len),
        geom.unprep(dk, kv_len),
        geom.unprep(dv, kv_len),
        from_compact(drw, rw_abs.shape[-1], rw_abs),
        from_compact(drh, rh_abs.shape[-1], rh_abs),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash_rel(q, k, v, rw_abs, rh_abs, height, width, scale, block_q,
               block_kv, interpret):
    return _rel_forward(
        q, k, v, rw_abs, rh_abs, height, width, scale, block_q, block_kv,
        interpret,
    )


def _flash_rel_fwd(q, k, v, rw_abs, rh_abs, height, width, scale, block_q,
                   block_kv, interpret):
    out, lse = _rel_forward(
        q, k, v, rw_abs, rh_abs, height, width, scale, block_q, block_kv,
        interpret, with_lse=True,
    )
    return out, (q, k, v, rw_abs, rh_abs, out, lse)


def _flash_rel_bwd(height, width, scale, block_q, block_kv, interpret,
                   residuals, g):
    q, k, v, rw_abs, rh_abs, out, lse = residuals
    return _rel_backward_pallas(
        q, k, v, rw_abs, rh_abs, out, lse, g, height, width, scale, block_q,
        block_kv, interpret,
    )


_flash_rel.defvjp(_flash_rel_fwd, _flash_rel_bwd)


def flash_botnet_attention(
    query: jax.Array,
    key: jax.Array,
    value: jax.Array,
    rel_k_h: jax.Array,
    rel_k_w: jax.Array,
    height: int,
    width: int,
    *,
    scale: Optional[float] = None,
    block_q: int = 256,
    block_kv: int = 256,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused BoTNet attention: 2-D relative logits inside the flash kernel.

    Args:
      query/key/value: ``[B, L, heads, D]`` with ``L == height * width``.
      rel_k_h: learned ``[2·height−1, D]`` height-relative table.
      rel_k_w: learned ``[2·width−1, D]`` width-relative table.
      scale: content-logit scale, default ``D ** -0.5``; the relative logits
        use the same scaled query (botnet.py:187-192 semantics).

    Returns:
      ``[B, L, heads, D]`` in the query dtype. Differentiable w.r.t. all
      five tensor inputs; the backward is fully blocked Pallas (dq + compact
      per-axis bias gradients in one kernel, dk/dv in another) — the dense
      ``[B,H,L,L]`` bias/probability tensors exist in neither direction.
    """
    b, l, heads, d = query.shape
    if l != height * width:
        raise ValueError(f"L={l} != height*width={height * width}")
    if scale is None:
        scale = d ** -0.5
    qs = (query * jnp.asarray(scale, query.dtype)).astype(jnp.float32)
    cw = jnp.einsum("blhd,rd->bhlr", qs, rel_k_w.astype(jnp.float32))
    ch = jnp.einsum("blhd,rd->bhlr", qs, rel_k_h.astype(jnp.float32))
    rw_abs, rh_abs = compact_to_absolute(cw, ch, height, width)
    return _flash_rel(
        query, key, value, rw_abs, rh_abs, height, width, float(scale),
        block_q, block_kv, interpret,
    )
