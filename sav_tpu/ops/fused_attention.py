"""Single-pass fused attention for sequences that fit one KV block in VMEM.

The model zoo's headline shapes (DeiT/ViT L=197, CaiT L=197, TNT outer
L=785) are exactly where PERF.md §5 measured the online-softmax flash
kernel *losing* to XLA: with L_kv inside a single VMEM block the
multi-pass (max, sum, acc) carry, the per-kv-block grid cells, and the
cross-block finalize are pure overhead. This kernel keeps the flash
*memory* shape — the ``[B, H, Lq, Lk]`` logits/probabilities never exist
in HBM in either direction, which is the 67-of-112 ms HBM tax the dense
XLA path pays at DeiT-S/16 — but computes each ``block_b`` batch·head
slice in ONE grid cell: QK → scale/bias → plain softmax (the whole row is
resident, no running max/sum) → PV, bf16-in/f32-accumulate.

Differentiation: ``fused_attention`` is a ``jax.custom_vjp``. Without a
bias the backward is a SINGLE fused Pallas kernel per (bh slice, q block):
the forward saves only the per-row logsumexp, the backward recomputes the
probabilities from it in VMEM and emits dq directly plus dk/dv through
VMEM accumulators swept over q blocks — no dense logits rematerialized in
HBM. With a bias that requires a gradient the backward falls back to the
XLA flash-style recompute shared with :mod:`sav_tpu.ops.flash_attention`
(the dense ``ds`` is unavoidable for a dense dbias).

Block configs (``block_q``, ``block_b``) default to the static heuristics
below; the measured per-shape winners come from ``tools/attn_tune.py``'s
cache via the ``auto`` dispatcher (:mod:`sav_tpu.ops.attn_tuning`).

On non-TPU backends the kernels run in Pallas interpreter mode, so the
same code path is testable on the CPU mesh (tests/test_fused_attention.py
cross-checks fwd + grads against ``xla_attention``).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from sav_tpu.ops.flash_attention import (
    _bwd_prep,
    _dense_recompute_bwd,
    _lanes,
    _round_up,
)

_NEG_INF = float("-inf")

# Default q tile; clamped to round_up(q_len, 16) for short sequences
# (mirrors flash_attention's clamping so padding geometry is shared).
DEFAULT_BLOCK_Q = 256

# Per-grid-cell VMEM working-set budget for eligibility/auto block_b
# selection. v5e-class cores have ~16 MiB of VMEM; Mosaic rejected flash
# configs already at ~half of it (the block_b 16/32 failures, PERF.md §5),
# so the estimator budgets conservatively — 8 MiB — and the dispatcher's
# "fits one KV block" band is defined as: some (block_q, block_b=1)
# config's *backward* working set (the larger of the two passes) fits.
FUSED_VMEM_BUDGET = 8 * 2**20


def _kv_pad(kv_len: int) -> int:
    """The single KV block width: the whole (padded) key/value sequence."""
    return _round_up(kv_len, 16)


def fused_vmem_bytes(
    q_len: int,
    kv_len: int,
    dim: int,
    *,
    block_q: int = DEFAULT_BLOCK_Q,
    block_b: int = 1,
    itemsize: int = 2,
) -> int:
    """Estimated per-grid-cell VMEM working set of the fused *backward*
    (the larger pass — q/k/v/do in, dq out, f32 dk/dv accumulators, and the
    f32 logits-tile temporaries the unrolled block_b loop keeps live).
    Intentionally conservative: real Mosaic allocation is the arbiter on
    chip (tools/attn_tune.py records its failures as infeasible)."""
    dim_p = _round_up(dim, 128)
    block_q = min(block_q, _round_up(q_len, 16))
    kv_p = _kv_pad(kv_len)
    tensors = block_b * (block_q + 2 * kv_p) * dim_p * itemsize  # q, k, v
    tensors += block_b * block_q * dim_p * itemsize  # do
    tensors += block_b * block_q * dim_p * itemsize  # dq out
    tensors += 2 * block_b * kv_p * dim_p * 4  # dk/dv f32 accumulators
    tensors += 2 * block_b * block_q * 128 * 4  # lse + delta rows
    tensors += 3 * block_b * block_q * kv_p * 4  # s/p/ds f32 temporaries
    return tensors


def fused_eligible(
    q_len: int,
    kv_len: int,
    dim: int,
    *,
    block_q: int = DEFAULT_BLOCK_Q,
    itemsize: int = 2,
    budget: int = FUSED_VMEM_BUDGET,
) -> bool:
    """True when the whole KV sequence fits one VMEM block under the
    budget at block_b=1 (larger block_b only shrinks under the budget by
    never being auto-picked)."""
    return (
        fused_vmem_bytes(
            q_len, kv_len, dim, block_q=block_q, block_b=1, itemsize=itemsize
        )
        <= budget
    )


def _pick_block_b(
    bh: int,
    q_len: int,
    kv_len: int,
    dim: int,
    *,
    block_q: int,
    itemsize: int,
    divisor_of: Optional[int] = None,
) -> int:
    """Largest of (8, 4, 2, 1) dividing bh (and ``divisor_of``, when a
    batch- or head-shared bias needs grid cells that don't straddle batch
    boundaries) whose working set stays under the VMEM budget. Several bh
    slices per grid cell amortize the ~µs grid-cell issue overhead that
    dominates short-L shapes (PERF.md §2)."""
    for bb in (8, 4, 2):
        if bh % bb != 0:
            continue
        if divisor_of is not None and divisor_of % bb != 0:
            continue
        if (
            fused_vmem_bytes(
                q_len, kv_len, dim,
                block_q=block_q, block_b=bb, itemsize=itemsize,
            )
            <= FUSED_VMEM_BUDGET
        ):
            return bb
    return 1


def _fused_kernel(
    q_ref,
    k_ref,
    v_ref,
    *rest,
    has_bias: bool,
    bias_per_slice: bool,
    with_lse: bool,
    scale: float,
    kv_len: int,
    kv_p: int,
    block_b: int,
):
    """One grid cell = ``block_b`` batch·head slices × one q block × the
    WHOLE kv sequence: plain (single-pass) softmax, no online statistics,
    no scratch carry, no finalize pass. ``bias_per_slice`` distinguishes a
    bias block carrying one row per bh slice from a single shared row
    (batch-shared / fully shared biases — see ``_prep_bias``)."""
    bias_ref = rest[0] if has_bias else None
    rest = rest[1 if has_bias else 0 :]
    if with_lse:
        o_ref, lse_ref = rest
    else:
        (o_ref,), lse_ref = rest, None

    for bi in range(block_b):
        q = q_ref[bi]  # [block_q, dim_p]
        k = k_ref[bi]  # [kv_p, dim_p]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * scale
        if has_bias:
            s = s + bias_ref[bi if bias_per_slice else 0].astype(jnp.float32)
        if kv_p != kv_len:
            col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(col < kv_len, s, _NEG_INF)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        acc = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[bi], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        o_ref[bi] = (acc / l).astype(o_ref.dtype)
        if lse_ref is not None:
            # Broadcast across one 128-lane tile — the layout the blocked
            # backward reads with no relayout (same as flash_attention).
            lse_ref[bi] = jnp.broadcast_to(
                m + jnp.log(l), lse_ref.shape[1:]
            )


def _fused_forward(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    bias: Optional[jax.Array],
    scale: float,
    block_q: int,
    block_b: Optional[int],
    interpret: Optional[bool],
    with_lse: bool = False,
):
    """Layout in/out ``[B, L, H, D]``; internally ``[B·H, L, D]`` padded to
    the shared flash geometry (dim→128 lanes, q→block_q, kv→one block)."""
    batch, q_len, heads, dim = q.shape
    kv_len = k.shape[1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def to_bhld(x):
        b, l, h, d = x.shape
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, l, d)

    dim_p = _round_up(dim, 128)
    block_q = min(block_q, _round_up(q_len, 16))
    q_len_p = _round_up(q_len, block_q)
    kv_p = _kv_pad(kv_len)

    def pad3(x, lp):
        return jnp.pad(x, ((0, 0), (0, lp - x.shape[1]), (0, dim_p - x.shape[2])))

    qf = pad3(to_bhld(q), q_len_p)
    kf = pad3(to_bhld(k), kv_p)
    vf = pad3(to_bhld(v), kv_p)

    # Bias broadcast pattern. A bias is stored (and padded) at its OWN
    # broadcast rank — (1,1), (1,H), (B,1) biases are never materialized
    # to the full [B, H, Lq, Lk] (that tensor is the HBM tax this kernel
    # exists to avoid); the grid reads the compact form through an index
    # map instead. The head-ful patterns need grid cells that never
    # straddle a batch boundary, i.e. block_b | heads.
    bias_mode = None
    if bias is not None:
        bias = jnp.broadcast_to(bias, bias.shape[:-2] + (q_len, kv_len))
        shape2 = (bias.shape[0], bias.shape[1])
        # Order matters for the degenerate batch==1 / heads==1 cases: the
        # fully-shared and fully-indexed patterns subsume them, so the
        # modular modes below only ever see batch > 1 AND heads > 1.
        if shape2 == (1, 1):
            bias_mode = "single"
        elif shape2 == (batch, heads):
            bias_mode = "per_slice"
        elif shape2 == (1, heads):
            bias_mode = "per_head"
        elif shape2 == (batch, 1):
            bias_mode = "per_batch"
        else:
            bias = jnp.broadcast_to(bias, (batch, heads) + bias.shape[-2:])
            bias_mode = "per_slice"

    bh = batch * heads
    # The modular modes read the compact bias through index arithmetic that
    # only works when grid cells never straddle a batch boundary.
    needs_head_divisor = bias_mode in ("per_head", "per_batch")
    if block_b is None:
        block_b = _pick_block_b(
            bh, q_len, kv_len, dim,
            block_q=block_q, itemsize=q.dtype.itemsize,
            divisor_of=heads if needs_head_divisor else None,
        )
    elif bh % block_b != 0 or (needs_head_divisor and heads % block_b != 0):
        block_b = 1
    num_q_blocks = q_len_p // block_q
    grid = (bh // block_b, num_q_blocks)

    in_specs = [
        pl.BlockSpec((block_b, block_q, dim_p), lambda b, i: (b, i, 0)),
        pl.BlockSpec((block_b, kv_p, dim_p), lambda b, i: (b, 0, 0)),
        pl.BlockSpec((block_b, kv_p, dim_p), lambda b, i: (b, 0, 0)),
    ]
    args = [qf, kf, vf]
    bias_per_slice = bias_mode in ("per_slice", "per_head")
    if bias is not None:
        # The bias is padded at its OWN broadcast rank — (1,1)/(1,H)/(B,1)
        # stay compact; the full [B·H, Lq, Lk] only exists when the caller
        # materialized it (the HBM tax this kernel exists to avoid).
        biasf = bias.reshape(-1, q_len, kv_len)
        biasf = jnp.pad(
            biasf, ((0, 0), (0, q_len_p - q_len), (0, kv_p - kv_len))
        )
        groups = heads // block_b  # cells per batch element (modular modes)
        if bias_mode == "per_slice":
            bias_spec = pl.BlockSpec(
                (block_b, block_q, kv_p), lambda b, i: (b, i, 0)
            )
        elif bias_mode == "per_head":
            # One bias row per head; cell b starts at head
            # (b·block_b) mod heads, i.e. row-block b mod groups.
            bias_spec = pl.BlockSpec(
                (block_b, block_q, kv_p), lambda b, i: (b % groups, i, 0)
            )
        elif bias_mode == "per_batch":
            # One shared row per batch element: cell b sits in batch
            # (b·block_b) // heads = b // groups.
            bias_spec = pl.BlockSpec(
                (1, block_q, kv_p), lambda b, i: (b // groups, i, 0)
            )
        else:  # 'single': one row for everyone, any block_b
            bias_spec = pl.BlockSpec(
                (1, block_q, kv_p), lambda b, i: (0, i, 0)
            )
        in_specs.append(bias_spec)
        args.append(biasf)

    kernel = functools.partial(
        _fused_kernel,
        has_bias=bias is not None,
        bias_per_slice=bias_per_slice,
        with_lse=with_lse,
        scale=scale,
        kv_len=kv_len,
        kv_p=kv_p,
        block_b=block_b,
    )
    out_specs = [
        pl.BlockSpec((block_b, block_q, dim_p), lambda b, i: (b, i, 0))
    ]
    out_shape = [jax.ShapeDtypeStruct((bh, q_len_p, dim_p), q.dtype)]
    if with_lse:
        out_specs.append(
            pl.BlockSpec((block_b, block_q, 128), lambda b, i: (b, i, 0))
        )
        out_shape.append(
            jax.ShapeDtypeStruct((bh, q_len_p, 128), jnp.float32)
        )

    outs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(*args)

    out = outs[0][:, :q_len, :dim]
    out = out.reshape(batch, heads, q_len, dim)
    out = jnp.transpose(out, (0, 2, 1, 3))
    if with_lse:
        return out, outs[1]
    return out


def _fused_bwd_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                      scale: float, q_len: int, kv_len: int, kv_p: int,
                      block_b: int, block_q: int, num_q_blocks: int):
    """SINGLE fused backward: with the whole kv sequence resident, each
    grid cell recomputes its probability tile from the lse residual and
    emits dq directly (no kv-block sweep to accumulate over) while dk/dv
    accumulate across q blocks in VMEM scratch — one kernel, not the dq +
    dk/dv pair the multi-block flash backward needs."""
    qi = pl.program_id(1)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    for bi in range(block_b):
        q, k, v, do = q_ref[bi], k_ref[bi], v_ref[bi], do_ref[bi]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        p = jnp.exp(s - _lanes(lse_ref[bi], s.shape[1]))
        if kv_p != kv_len:
            col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            p = jnp.where(col < kv_len, p, 0.0)
        if q_len % block_q != 0:
            # Padded q rows carry a finite lse, so p is finite garbage —
            # zero it so the padded rows contribute nothing to dk/dv.
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0
            )
            p = jnp.where(row < q_len, p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - _lanes(delta_ref[bi], s.shape[1]))
        dq_ref[bi] = (
            jax.lax.dot_general(
                ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        ).astype(dq_ref.dtype)
        dv_acc[bi] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_acc[bi] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(qi == num_q_blocks - 1)
    def _write():
        dk_ref[...] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc[...].astype(dv_ref.dtype)


def _fused_backward(q, k, v, out, lse, g, scale, block_q, block_b,
                    interpret):
    """q/k/v/out/g ``[B, L, H, D]``; lse is the padded ``[B·H, q_len_p,
    128]`` forward residual."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    kv_p = _kv_pad(k.shape[1])
    geom = _bwd_prep(q, k, v, out, g, block_q, kv_p)
    q_len, kv_len = geom.q_len, geom.kv_len
    block_q, dim_p = geom.block_q, geom.dim_p
    num_q_blocks = geom.q_len_p // block_q
    bh = geom.batch * geom.heads
    if block_b is None:
        block_b = _pick_block_b(
            bh, q_len, kv_len, geom.dim,
            block_q=block_q, itemsize=q.dtype.itemsize,
        )
    elif bh % block_b != 0:
        block_b = 1

    qspec = pl.BlockSpec((block_b, block_q, dim_p), lambda b, i: (b, i, 0))
    kspec = pl.BlockSpec((block_b, kv_p, dim_p), lambda b, i: (b, 0, 0))
    rowq = pl.BlockSpec((block_b, block_q, 128), lambda b, i: (b, i, 0))

    dq, dk, dv = pl.pallas_call(
        functools.partial(
            _fused_bwd_kernel,
            scale=scale,
            q_len=q_len,
            kv_len=kv_len,
            kv_p=kv_p,
            block_b=block_b,
            block_q=block_q,
            num_q_blocks=num_q_blocks,
        ),
        grid=(bh // block_b, num_q_blocks),
        in_specs=[qspec, kspec, kspec, qspec, rowq, rowq],
        out_specs=[qspec, kspec, kspec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, geom.q_len_p, dim_p), q.dtype),
            jax.ShapeDtypeStruct((bh, kv_p, dim_p), k.dtype),
            jax.ShapeDtypeStruct((bh, kv_p, dim_p), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_b, kv_p, dim_p), jnp.float32),
            pltpu.VMEM((block_b, kv_p, dim_p), jnp.float32),
        ],
        interpret=interpret,
    )(geom.qf, geom.kf, geom.vf, geom.dof, lse, geom.delta)

    return (
        geom.unprep(dq, q_len),
        geom.unprep(dk, kv_len),
        geom.unprep(dv, kv_len),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _fused(q, k, v, bias, scale, block_q, block_b, interpret):
    return _fused_forward(q, k, v, bias, scale, block_q, block_b, interpret)


def _fused_fwd(q, k, v, bias, scale, block_q, block_b, interpret):
    if bias is None:
        out, lse = _fused_forward(
            q, k, v, bias, scale, block_q, block_b, interpret, with_lse=True
        )
        return out, (q, k, v, bias, out, lse)
    out = _fused_forward(q, k, v, bias, scale, block_q, block_b, interpret)
    return out, (q, k, v, bias, None, None)


def _fused_vjp_bwd(scale, block_q, block_b, interpret, residuals, g):
    """No bias → the single fused Pallas backward. A bias gradient needs
    the dense ``ds`` (its own size is O(L²) by construction), so that path
    shares flash_attention's XLA recompute."""
    q, k, v, bias, out, lse = residuals
    if bias is None:
        dq, dk, dv = _fused_backward(
            q, k, v, out, lse, g, scale, block_q, block_b, interpret
        )
        return dq, dk, dv, None
    return _dense_recompute_bwd(q, k, v, bias, g, scale)


_fused.defvjp(_fused_fwd, _fused_vjp_bwd)


def fused_attention(
    query: jax.Array,
    key: jax.Array,
    value: jax.Array,
    bias: Optional[jax.Array] = None,
    *,
    scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_b: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused single-pass short-sequence attention.

    Args:
      query: ``[B, q_len, heads, head_dim]``.
      key, value: ``[B, kv_len, heads, head_dim]``. The whole (padded) kv
        sequence must fit one VMEM block (:func:`fused_eligible`).
      bias: optional additive logits bias broadcastable to
        ``[B, heads, q_len, kv_len]``.
      scale: logit scale, default ``head_dim ** -0.5``.
      block_q: q tile (clamped for short sequences). Per-shape measured
        winners come from the ``tools/attn_tune.py`` cache via the ``auto``
        dispatcher.
      block_b: batch·head slices per grid cell; None = largest of
        (8, 4, 2, 1) under the VMEM budget.
      interpret: force Pallas interpreter mode; default = auto (on for
        non-TPU backends).

    Returns:
      ``[B, q_len, heads, head_dim]`` in the query dtype.
    """
    if query.ndim != 4 or key.ndim != 4 or value.ndim != 4:
        raise ValueError(
            f"fused attention expects [B, L, H, D] inputs, got "
            f"{query.shape}/{key.shape}/{value.shape}"
        )
    if bias is not None and bias.ndim != 4:
        raise ValueError(f"bias must be 4-D broadcastable, got {bias.shape}")
    q_len, kv_len = query.shape[1], key.shape[1]
    dim = query.shape[-1]
    if not fused_eligible(
        q_len, kv_len, dim, block_q=block_q, itemsize=query.dtype.itemsize
    ):
        raise ValueError(
            f"kv_len={kv_len} (dim={dim}) does not fit the fused kernel's "
            f"single-KV-block VMEM budget ({FUSED_VMEM_BUDGET} bytes, "
            f"estimate {fused_vmem_bytes(q_len, kv_len, dim, block_q=block_q, itemsize=query.dtype.itemsize)}); "
            "use the flash kernel (backend='pallas') or XLA"
        )
    if scale is None:
        scale = query.shape[-1] ** -0.5
    return _fused(
        query, key, value, bias, float(scale), block_q, block_b, interpret
    )
