"""Shape→config cache for the attention dispatcher.

``tools/attn_tune.py`` sweeps (block_q, block_kv, block_b) per shape
across the xla / fused / flash backends on the live chip and emits a
JSON cache; this module is the *consumer* side: the ``auto`` dispatcher
(:func:`sav_tpu.ops.attention.resolve_attention_backend`) looks the
traced shape up here to pick the measured-winner backend and block
config instead of a hand-picked one.

Promotion is evidence-gated by construction: without a measured cache
entry the short-sequence band stays on XLA (the PERF.md §5 measured
winner), and a fused/flash entry only exists where the autotuner +
``tools/ab_step.py`` + the regression sentinel confirmed the win on
chip. The checked-in default cache (``attn_tune_cache.json`` next to
this module) carries the PERF.md §5 measurements; point
``SAV_ATTN_TUNE_CACHE`` / :func:`set_cache_path` /
``TrainConfig.attention_tune_cache`` at a fresh sweep to override.

Everything here runs at TRACE time only (the lookup is keyed on static
shapes) — no host work ever lands in the jitted hot path, and the file
is read once per (path, mtime) per process.

Cache schema (version 1)::

    {
      "version": 1,
      "device": "TPU v5e (axon relay)",
      "entries": {
        "<key>": {"backend": "xla"|"fused"|"pallas",
                   "block_q": int|null, "block_kv": int|null,
                   "block_b": int|null,
                   "fwd_ms": float|null, "fwd_bwd_ms": float|null,
                   "source": "<tool / PERF.md section>"}
      },
      "infeasible": {
        "<key>": [{"backend": ..., "block_q": ..., "block_kv": ...,
                    "block_b": ..., "error": "<Mosaic message>"}]
      }
    }

Keys come from :func:`shape_key`; a lookup tries the exact batch first,
then the batch-wildcard key (``B*``) so one measured model-zoo shape
covers every batch size that shares its sequence geometry.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Optional

import jax.numpy as jnp

CACHE_VERSION = 1
ENV_VAR = "SAV_ATTN_TUNE_CACHE"
DEFAULT_CACHE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "attn_tune_cache.json"
)

_BACKENDS = ("xla", "fused", "pallas")

_lock = threading.Lock()
_cache_path_override: Optional[str] = None
# (path, mtime) -> parsed cache dict; misses/IO errors memoize as {}.
_loaded: dict = {}


def shape_key(
    batch, q_len: int, kv_len: int, heads: int, dim: int, dtype="bfloat16"
) -> str:
    """Canonical cache key. ``batch`` may be ``'*'`` for the wildcard."""
    dt = jnp.dtype(dtype).name
    return f"B{batch}.Lq{q_len}.Lkv{kv_len}.H{heads}.D{dim}.{dt}"


def set_cache_path(path: Optional[str]) -> None:
    """Process-wide cache-path override (trace-time state only; wired from
    ``TrainConfig.attention_tune_cache`` / ``bench.py --attn-tune-cache``).
    ``None`` restores the env-var / default resolution."""
    global _cache_path_override
    with _lock:
        _cache_path_override = path


def get_cache_path() -> str:
    with _lock:
        if _cache_path_override is not None:
            return _cache_path_override
    return os.environ.get(ENV_VAR, DEFAULT_CACHE_PATH)


def load_cache(path: Optional[str] = None) -> dict:
    """Parsed cache (``{}`` when the file is missing/invalid — a broken
    cache degrades to the static dispatch rule, never to a crash)."""
    path = path or get_cache_path()
    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError:
        return {}
    key = (path, mtime)
    with _lock:
        if key in _loaded:
            return _loaded[key]
    try:
        with open(path) as f:
            cache = json.load(f)
        if not isinstance(cache, dict) or cache.get("version") != CACHE_VERSION:
            cache = {}
    except (OSError, ValueError):
        cache = {}
    with _lock:
        _loaded.clear()  # one live file per process is plenty
        _loaded[key] = cache
    return cache


def lookup(
    batch: int,
    q_len: int,
    kv_len: int,
    heads: int,
    dim: int,
    dtype="bfloat16",
    *,
    path: Optional[str] = None,
) -> Optional[dict]:
    """Measured entry for a shape (exact batch, then batch-wildcard);
    ``None`` when the shape has never been swept. Entries with an unknown
    backend name are ignored rather than dispatched on."""
    entries = load_cache(path).get("entries", {})
    for b in (batch, "*"):
        entry = entries.get(shape_key(b, q_len, kv_len, heads, dim, dtype))
        if isinstance(entry, dict) and entry.get("backend") in _BACKENDS:
            return entry
    return None


def block_config(entry: Optional[dict]) -> Optional[dict]:
    """The (block_q, block_kv, block_b) triple of a cache entry, with
    Nones dropped — the kwargs shape the kernels accept."""
    if not entry:
        return None
    cfg = {
        k: entry[k]
        for k in ("block_q", "block_kv", "block_b")
        if entry.get(k) is not None
    }
    return cfg or None


def write_cache(
    path: str,
    entries: dict,
    infeasible: Optional[dict] = None,
    *,
    device: Optional[str] = None,
    merge: bool = False,
) -> dict:
    """Write (or merge into) a cache file; returns the written dict.
    ``merge=True`` folds the new entries/infeasible records over an
    existing file's, so per-shape sweeps accumulate into one table."""
    cache = {"version": CACHE_VERSION, "entries": {}, "infeasible": {}}
    if merge and os.path.exists(path):
        old = load_cache(path)
        cache["entries"].update(old.get("entries", {}))
        cache["infeasible"].update(old.get("infeasible", {}))
        if old.get("device"):
            cache["device"] = old["device"]
    if device:
        cache["device"] = device
    cache["entries"].update(entries)
    for k, v in (infeasible or {}).items():
        cache["infeasible"].setdefault(k, [])
        cache["infeasible"][k].extend(v)
    tmp = f"{path}.tmp.{os.getpid()}"
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(tmp, "w") as f:
        json.dump(cache, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return cache
