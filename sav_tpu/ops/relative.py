"""2-D relative position logits for BoTNet-style attention.

Functional, fixed rebuild of the reference's ``RelativeLogits`` machinery
(/root/reference/models/botnet.py:70-141): per-axis 1-D relative logits from
learned ``(2L-1, d)`` tables, converted relative→absolute with the
pad-reshape-slice trick, combined as ``rel_h + rel_w``. The reference's
output einsum bug (botnet.py:194, SURVEY.md §2.9 #3) does not apply here —
this op only produces the logits bias; attention consumes it via the shared
``dot_product_attention`` cores (XLA or Pallas, where it enters the fused
softmax as a bias term).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rel_to_abs(x: jax.Array) -> jax.Array:
    """Convert relative-indexed logits ``[..., L, 2L-1]`` to absolute ``[..., L, L]``.

    ``out[..., i, j] == x[..., i, j - i + L - 1]`` — the classic pad/reshape/slice
    trick (no gathers, TPU-friendly).
    """
    *lead, length, rel = x.shape
    if rel != 2 * length - 1:
        raise ValueError(f"expected last dim {2 * length - 1}, got {rel}")
    pad = [(0, 0)] * len(lead)
    x = jnp.pad(x, pad + [(0, 0), (0, 1)])  # [..., L, 2L]
    x = x.reshape(*lead, length * 2 * length)
    x = jnp.pad(x, pad + [(0, length - 1)])  # [..., 2L² + L - 1]
    x = x.reshape(*lead, length + 1, 2 * length - 1)
    return x[..., :length, length - 1 :]


def _relative_logits_1d(q: jax.Array, rel_k: jax.Array) -> jax.Array:
    """``q: [B, h, X, Y, d]``, ``rel_k: [2Y-1, d]`` → ``[B, h, X, Y, Y]``."""
    logits = jnp.einsum("bhxyd,md->bhxym", q, rel_k, preferred_element_type=jnp.float32)
    return rel_to_abs(logits)


def relative_logits_2d(q: jax.Array, rel_k_h: jax.Array, rel_k_w: jax.Array) -> jax.Array:
    """Full 2-D relative position logits.

    Args:
      q: queries on the feature-map grid, ``[B, heads, H, W, d]``.
      rel_k_h: ``[2H-1, d]`` learned height-relative embedding table.
      rel_k_w: ``[2W-1, d]`` learned width-relative embedding table.

    Returns:
      ``[B, heads, H, W, H, W]`` float32 logits where entry
      ``[b, n, x, y, X, Y] = q[b,n,x,y]·rel_k_h[X-x+H-1] + q[b,n,x,y]·rel_k_w[Y-y+W-1]``.
    """
    b, h, height, width, _ = q.shape
    # Width logits: independent of the key row → broadcast over X.
    rel_w = _relative_logits_1d(q, rel_k_w)  # [B, h, H, W, W] = [b,n,x,y,Y]
    rel_w = jnp.broadcast_to(rel_w[:, :, :, :, None, :], (b, h, height, width, height, width))
    # Height logits: transpose the grid, compute along H, transpose back.
    q_t = jnp.swapaxes(q, 2, 3)  # [B, h, W, H, d]
    rel_h = _relative_logits_1d(q_t, rel_k_h)  # [B, h, W, H, H] = [b,n,y,x,X]
    rel_h = jnp.transpose(rel_h, (0, 1, 3, 2, 4))  # [b,n,x,y,X]
    rel_h = jnp.broadcast_to(rel_h[:, :, :, :, :, None], (b, h, height, width, height, width))
    return rel_w + rel_h
