"""Int8 quantized matmuls: AQT-style training dot + quantized-weights
serving (ISSUE 17, ROADMAP item 1).

The projection/FFN dots are ~31% of step time and already sit near the
bf16 matmul roofline (PERF.md §2/§5), so the next integer-factor win is
a narrower dtype. This module is the single source of int8 truth:

- **Per-channel symmetric scales from the contracting dimension.**
  ``quantize_channelwise(a, axes)`` computes ``s = amax(|a|, axes)/127``
  per output channel and ``q = clip(round(a/s), ±127)`` — symmetric
  (no zero-point), so the int8×int8 product needs no cross terms
  (Jacob et al. 2018 §2.3 simplification for symmetric weights).
- **int8×int8→int32 accumulation.** Every quantized contraction runs
  ``jax.lax.dot_general(..., preferred_element_type=jnp.int32)`` — the
  MXU's native int8 pipe — and dequantizes on exit by the scalar
  product of the two per-channel scales.
- **Straight-through estimator + stochastic rounding** (training arm).
  :func:`int8_ste_dot` is a ``custom_vjp``: the forward runs the
  quantized dot, the backward re-derives both gradient dots as int8
  contractions with the *gradient* tensor quantized by stochastic
  rounding (``floor(g/s + u)``, ``u ~ U[0,1)`` — unbiased, the AQT
  recipe that keeps SGD's expected update intact; Abdolrashidi et al.
  2021 §3.2). The rng rides the trainer's existing ``fold_in`` recipe
  as a ``"quant"`` rng stream — no ad-hoc ``PRNGKey`` construction
  anywhere (SAV110).
- **``quantize_params``** converts a trained bf16/f32 param tree into
  the int8+scales serving tree (kernels → int8 + per-channel ``scale``
  leaf, everything else cast to the serving template's dtype). The
  serving modules (mode ``"int8_serve"``) declare the int8 ``kernel``
  under the *same tree path* as the float one, so SpecLayout sharding
  rules and checkpoint naming carry over unchanged; the new ``scale``
  leaf is tiny and replicates under the layout's default spec.

Contraction convention (matches ``flax.linen.DenseGeneral``): ``x``
contracts its **trailing** ``n`` axes against the **leading** ``n``
axes of ``w`` — every projection/FFN dot in the model zoo fits this
shape, which keeps both transposed gradient dots expressible as plain
leading/trailing contractions (docs/quantization.md).

Attention QK/AV stays bf16 by design: PERF §5 shows those dots are not
matmul-roofline-bound, so int8 there buys noise, not time.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

# Symmetric int8: [-127, 127]. -128 is unused so the range is symmetric
# and negation never overflows (the Jacob et al. restricted-range
# convention).
INT8_AMAX = 127.0


def _f32(a):
    return a.astype(jnp.float32)


def quantize_channelwise(a, contract_axes: Sequence[int]):
    """Symmetric per-channel int8 quantization.

    ``contract_axes`` are the axes about to be contracted away: the
    scale reduces over exactly those axes (keepdims), giving one scale
    per *surviving* channel. Returns ``(q int8, scale f32)`` with
    ``a ≈ q * scale``. All-zero channels get scale 1.0 (q is 0 there
    anyway), so dequantization never divides by or multiplies with 0/0.
    """
    axes = tuple(int(ax) for ax in contract_axes)
    a = _f32(a)
    amax = jnp.max(jnp.abs(a), axis=axes, keepdims=True)
    scale = jnp.where(amax > 0.0, amax / INT8_AMAX, 1.0)
    q = jnp.clip(jnp.round(a / scale), -INT8_AMAX, INT8_AMAX).astype(jnp.int8)
    return q, scale


def quantize_stochastic(a, contract_axes: Sequence[int], key):
    """:func:`quantize_channelwise` with stochastic rounding:
    ``floor(a/s + u)``, ``u ~ U[0,1)`` — ``E[q*s] = a``, the unbiased
    rounding the gradient tensor needs (round-to-nearest gradients bias
    small updates toward zero; AQT §3.2)."""
    axes = tuple(int(ax) for ax in contract_axes)
    a = _f32(a)
    amax = jnp.max(jnp.abs(a), axis=axes, keepdims=True)
    scale = jnp.where(amax > 0.0, amax / INT8_AMAX, 1.0)
    noise = jax.random.uniform(key, a.shape, jnp.float32)
    q = jnp.clip(jnp.floor(a / scale + noise), -INT8_AMAX, INT8_AMAX)
    return q.astype(jnp.int8), scale


def _contract_dims(x_ndim: int, w_ndim: int, n: int):
    """dot_general dims: trailing ``n`` axes of x vs leading ``n`` of w."""
    del w_ndim
    return (
        (tuple(range(x_ndim - n, x_ndim)), tuple(range(n))),
        ((), ()),
    )


def _int8_contract(qx, sx, qw, sw, dims, out_scale_shape_x, out_scale_shape_w):
    """One int8×int8→int32 contraction + per-channel dequantize."""
    acc = jax.lax.dot_general(
        qx, qw, dims, preferred_element_type=jnp.int32
    )
    return (
        _f32(acc)
        * sx.reshape(out_scale_shape_x)
        * sw.reshape(out_scale_shape_w)
    )


def as_key_data(key):
    """Raw uint32 key data from either key flavor (typed or legacy) —
    the custom_vjp carries raw bits so its residues stay plain arrays."""
    if jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
        return jax.random.key_data(key)
    return key


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def int8_ste_dot(x, w, key, n_contract):
    """Quantized dot with an STE backward (the QAT training dot).

    ``x`` contracts its trailing ``n_contract`` axes against the
    leading ``n_contract`` axes of ``w``; ``key`` is raw uint32 key
    data (:func:`as_key_data`) consumed only by the backward's
    stochastic rounding. Forward: per-channel int8 quantize both
    operands, int32-accumulate, dequantize. Backward: both transposed
    gradient dots run int8 too, with the incoming cotangent
    stochastically rounded — weights/activations round-to-nearest.
    """
    out, _ = _ste_fwd(x, w, key, n_contract)
    return out


def _ste_fwd(x, w, key, n):
    nb = x.ndim - n  # x free (batch-ish) axes
    nf = w.ndim - n  # w free (feature) axes
    qx, sx = quantize_channelwise(x, range(nb, x.ndim))
    qw, sw = quantize_channelwise(w, range(n))
    out = _int8_contract(
        qx, sx, qw, sw,
        _contract_dims(x.ndim, w.ndim, n),
        x.shape[:nb] + (1,) * nf,
        w.shape[n:],
    ).astype(jnp.result_type(x, w))
    return out, (x, w, key)


def _ste_bwd(n, res, g):
    x, w, key = res
    nb = x.ndim - n
    nf = w.ndim - n
    k_dx, k_dw = jax.random.split(key)
    # dx = g ·_F w  (contract the nf feature axes of both) — the
    # cotangent is the noisy operand: stochastic rounding keeps it
    # unbiased; the weight re-quantizes round-to-nearest per in-channel.
    qg, sg = quantize_stochastic(g, range(nb, g.ndim), k_dx)
    qwt, swt = quantize_channelwise(w, range(n, w.ndim))
    dx = _int8_contract(
        qg, sg, qwt, swt,
        (
            (tuple(range(nb, g.ndim)), tuple(range(n, w.ndim))),
            ((), ()),
        ),
        g.shape[:nb] + (1,) * n,
        w.shape[:n],
    ).astype(x.dtype)
    # dw = x ·_B g  (contract the nb batch axes of both).
    qxt, sxt = quantize_channelwise(x, range(nb))
    qg2, sg2 = quantize_stochastic(g, range(nb), k_dw)
    dw = _int8_contract(
        qxt, sxt, qg2, sg2,
        ((tuple(range(nb)), tuple(range(nb))), ((), ())),
        x.shape[nb:] + (1,) * nf,
        g.shape[nb:],
    ).astype(w.dtype)
    # The key is integer data: its cotangent is the empty float0 zero.
    dkey = np.zeros(np.shape(key), jax.dtypes.float0)
    return dx, dw, dkey


int8_ste_dot.defvjp(_ste_fwd, _ste_bwd)


def int8_serve_dot(x, q_kernel, scale, n_contract: int):
    """The serving-side dot: pre-quantized int8 weights + per-channel
    ``scale`` (shape = the kernel's feature dims), activations
    quantized dynamically per row. Returns f32 (caller casts + biases).
    """
    n = int(n_contract)
    nb = x.ndim - n
    nf = q_kernel.ndim - n
    qx, sx = quantize_channelwise(x, range(nb, x.ndim))
    return _int8_contract(
        qx, sx, q_kernel, jnp.asarray(scale, jnp.float32),
        _contract_dims(x.ndim, q_kernel.ndim, n),
        x.shape[:nb] + (1,) * nf,
        np.shape(scale),
    )


# --------------------------------------------------------------- modules


def _canonical_tuple(v) -> tuple:
    return tuple(v) if isinstance(v, (tuple, list)) else (v,)


def quant_rng_data(module: nn.Module):
    """The module-side half of the SAV110-clean rng recipe: the trainer
    threads one ``"quant"`` stream per step (its existing ``fold_in``
    ladder), ``make_rng`` folds in the module path so every quantized
    dot rounds with independent bits. Outside training (init, eval,
    serving) there is no stream and no backward — a zeros key keeps the
    forward trace identical without minting an ad-hoc seed."""
    if not module.is_initializing() and module.has_rng("quant"):
        return as_key_data(module.make_rng("quant"))
    return jnp.zeros((2,), jnp.uint32)


class QuantDenseGeneral(nn.Module):
    """Drop-in quantized twin of ``nn.DenseGeneral`` (and, with scalar
    ``features``/``axis=-1``, of ``nn.Dense``).

    mode="int8" (QAT): declares the *same* float ``kernel``/``bias``
    params at the same tree paths and with the same init numerics as
    the flax layer it replaces — a quant-arm checkpoint is
    byte-compatible with the bf16 arm — but routes the contraction
    through :func:`int8_ste_dot`.

    mode="int8_serve": declares ``kernel`` as int8 (same path/shape —
    SpecLayout rules keyed on the name still apply) plus a per-channel
    f32 ``scale`` leaf shaped like the feature dims; the pair is
    produced from a trained float tree by :func:`quantize_params`.

    ``axis`` must name the trailing axes of the input (what every
    call-site in the zoo does) — that restriction is what keeps both
    STE gradient dots expressible as int8 contractions.
    """

    features: Union[int, Sequence[int]]
    mode: str = "int8"
    axis: Union[int, Sequence[int]] = -1
    use_bias: bool = True
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32
    kernel_init: Callable = nn.initializers.lecun_normal()
    bias_init: Callable = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, x):
        features = _canonical_tuple(self.features)
        axis = tuple(sorted(a % x.ndim for a in _canonical_tuple(self.axis)))
        n = len(axis)
        if axis != tuple(range(x.ndim - n, x.ndim)):
            raise ValueError(
                f"QuantDenseGeneral contracts trailing axes only; got "
                f"axis={axis} for ndim={x.ndim}"
            )
        kshape = tuple(x.shape[a] for a in axis) + features
        if self.mode == "int8_serve":
            q_kernel = self.param(
                "kernel", nn.initializers.zeros_init(), kshape, jnp.int8
            )
            scale = self.param(
                "scale", nn.initializers.ones_init(), features, jnp.float32
            )
            y = int8_serve_dot(
                _f32(x) if self.dtype is None else x.astype(self.dtype),
                q_kernel, scale, n,
            )
        elif self.mode == "int8":
            def kernel_init_wrap(rng, shape, dtype=self.param_dtype):
                # flax DenseGeneral's init contract: draw at the
                # flattened 2-D fan shape, then fold — identical bytes
                # to the layer this replaces.
                flat = (
                    int(np.prod(shape[:n])), int(np.prod(shape[n:]))
                )
                return jnp.reshape(self.kernel_init(rng, flat, dtype), shape)

            kernel = self.param(
                "kernel", kernel_init_wrap, kshape, self.param_dtype
            )
            x, kernel = nn.dtypes.promote_dtype(x, kernel, dtype=self.dtype)
            y = int8_ste_dot(x, kernel, quant_rng_data(self), n)
        else:
            raise ValueError(f"unknown quant mode {self.mode!r}")
        if self.use_bias:
            bias = self.param(
                "bias", self.bias_init, features, self.param_dtype
            )
            y = y + bias.astype(y.dtype)
        if self.dtype is not None:
            y = y.astype(self.dtype)
        return y


class QuantDense(QuantDenseGeneral):
    """``nn.Dense`` twin: scalar features, one contracted axis."""


# ------------------------------------------------------ tree conversion


def is_quantized_template(t) -> bool:
    """True for a module dict declaring the int8 kernel/scale pair."""
    return (
        isinstance(t, dict)
        and "kernel" in t
        and "scale" in t
        and getattr(t["kernel"], "dtype", None) == jnp.int8
    )


def quantize_params(params, template):
    """Trained float param tree → int8+scales serving tree.

    ``template`` is the abstract (``jax.eval_shape``) param tree of the
    same model built in ``mode="int8_serve"`` — wherever it declares an
    int8 ``kernel`` with a sibling ``scale``, the float kernel is
    quantized per-channel over its leading contracting axes
    (``kernel.ndim - scale.ndim`` of them); every other leaf is cast to
    the template's dtype. Jit-friendly: close over ``template`` (it is
    a ShapeDtypeStruct tree, not hashable as an argument).
    """

    def walk(p, t):
        if isinstance(t, dict):
            if is_quantized_template(t):
                n = p["kernel"].ndim - t["scale"].ndim
                q, s = quantize_channelwise(p["kernel"], range(n))
                out = {"kernel": q, "scale": s.reshape(t["scale"].shape)}
                for k, tv in t.items():
                    if k not in ("kernel", "scale"):
                        out[k] = walk(p[k], tv)
                return out
            return {k: walk(p[k], t[k]) for k in t}
        return p if p.dtype == t.dtype else p.astype(t.dtype)

    return walk(params, template)
