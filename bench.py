#!/usr/bin/env python
"""Headline benchmark: DeiT-S/16 ImageNet-shape training throughput per chip.

Measures the full jitted train step (forward + backward + AdamW update,
bf16 compute, label smoothing) — the BASELINE.json north-star metric
(target ≥8,000 img/s/chip). Prints exactly one JSON line:

    {"metric": ..., "value": N, "unit": "img/s/chip", "vs_baseline": N, ...}

``value`` is the best-window throughput (the shared/tunneled benchmark chip
shows >5x transient slowdowns; the minimum step time is the honest
hardware-capability number) and ``median_img_per_sec_per_chip`` is the
median window — both reported so the methodology is transparent
(ADVICE r1). ``mfu`` is model-FLOPs utilization from the compiled step's
XLA cost analysis against the chip's peak bf16 FLOP/s. ``goodput`` is the
run's wall-time ledger (sav_tpu.obs.goodput, docs/observability.md):
compile / step / input-wait buckets plus the per-window stall anomalies
that make the >5x transient slowdowns visible in the recorded JSON.

Feeds (``--feed``):
  synthetic — one device-resident batch, re-stepped (pure device number)
  pipeline  — the real tf.data path (JPEG bytes → crops → RandAugment →
              CutMix/MixUp) over an in-memory source, feeding the real
              train step; also reports the host pipeline's own img/s
  savrec    — the native SavRecord mmap loader feeding the train step

Fed loops run through the async double-buffered device feeder by default
(sav_tpu/data/feeder.py — host fetch + device_put of batch N+1 overlap
step N, exactly like Trainer.fit); ``--no-async-feed`` serializes them
for A/B. ``transfer_bytes_per_batch`` makes the wire format visible:
``--device-preprocess`` ships uint8 (≈½ the late-bf16 bytes, ¼ of f32).
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

BASELINE_IMG_PER_SEC_PER_CHIP = 8000.0

# Relay probing lives in sav_tpu.utils.backend_probe (shared with
# train.py --backend-wait; round-3's lost headline number motivated the
# bounded wait, round-5's wedged-grant episode moved it into the library).
# Imported inside main() so --help never pays the sav_tpu import.

def _make_trainer(model_name, batch_size, backend, image_size,
                  device_preprocess=False, augment=None):
    from sav_tpu.train import TrainConfig, Trainer

    config = TrainConfig(
        model_name=model_name,
        num_classes=1000,
        image_size=image_size,
        compute_dtype="bfloat16",
        attention_backend=None if backend == "auto" else backend,
        global_batch_size=batch_size,
        transpose_images=False,
        clip_grad_norm=1.0,
        device_preprocess=device_preprocess,
        seed=0,
        **({"augment": augment} if augment is not None else {}),
    )
    return Trainer(config)


def _feed_iterator(feed, batch_size, image_size, tmpdir, device_preprocess=False):
    """Host-side batch stream for the fed modes."""
    import numpy as np

    if feed == "pipeline":
        from sav_tpu.data.pipeline import Split, load

        rng = np.random.default_rng(0)
        n = max(4 * batch_size, 2048)
        images = rng.integers(0, 256, (n, image_size, image_size, 3), np.uint8)
        labels = rng.integers(0, 1000, (n,), np.int64)
        return load(
            Split.TRAIN,
            source=(images, labels),
            is_training=True,
            batch_dims=[batch_size],
            image_size=image_size,
            augment_name="cutmix_mixup_randaugment_405",
            # uint8 (device_preprocess) quarters host->device bytes vs
            # f32; otherwise late bf16 halves them.
            bfloat16=True,
            device_preprocess=device_preprocess,
            seed=0,
            process_index=0,
            process_count=1,
        )
    if feed == "savrec":
        import os

        from sav_tpu.data.records import (
            SavRecDataset,
            savrec_train_iterator,
            write_savrec,
        )

        rng = np.random.default_rng(0)
        n = max(4 * batch_size, 2048)
        path = os.path.join(tmpdir, "bench.savrec")
        if not os.path.exists(path):
            write_savrec(
                path,
                rng.integers(0, 256, (n, image_size, image_size, 3), np.uint8),
                rng.integers(0, 1000, (n,), np.int32),
            )
        ds = SavRecDataset(path)
        return savrec_train_iterator(
            ds, batch_size=batch_size, seed=0,
            normalize=not device_preprocess,
            bfloat16=not device_preprocess,
        )
    raise ValueError(feed)


def _record_window(recorder, step, loss_val, result):
    """One bench window through the flight recorder's gates (shared by the
    synthetic and fed loops): pair the window with its context, run the
    nonfinite/spike detection on the already-synced loss, and stash the
    first incident pointer + trigger into the result dict."""
    if recorder is None:
        return
    recorder.on_step(step)
    trig = recorder.note_metrics(step, {"loss": loss_val})
    if trig:
        inc = recorder.dump_incident(trig, step)
        if inc:
            result.setdefault("incident", inc)
            result.setdefault("incident_trigger", trig)


def run(model_name, batch_size, steps, backend, image_size, reps, feed,
        device_preprocess=False, async_feed=True, compilation_cache_dir=None,
        peak_flops=None, record=False, record_dir=None, attn_tune_cache=None,
        trace=False):
    import jax

    from sav_tpu.data import synthetic_data_iterator
    from sav_tpu.ops.attention import (
        clear_dispatch_log,
        snapshot_dispatch_log,
    )
    from sav_tpu.obs.costs import (
        publish_cost_gauges,
        resolve_peak_flops,
        train_step_cost,
    )
    from sav_tpu.obs.goodput import GoodputLedger

    if compilation_cache_dir:
        # Before any compile: repeat benches of the same program then read
        # XLA binaries from disk instead of re-paying the relay compile
        # (sav_tpu/utils/compile_cache.py; PERF.md §12's 493 s TNT trace).
        from sav_tpu.utils.compile_cache import enable_persistent_cache

        enable_persistent_cache(compilation_cache_dir)
    if attn_tune_cache:
        # Point the 'auto' dispatcher at a measured shape→config table
        # (tools/attn_tune.py output) instead of the checked-in default.
        from sav_tpu.ops.attn_tuning import set_cache_path

        set_cache_path(attn_tune_cache)
    # Attention-dispatch provenance: the resolver logs every traced
    # attention shape's (backend, block config, reason) at trace time;
    # cleared here so the stamped record covers exactly this bench's
    # compile (A/B runs and the sentinel can then attribute a number to
    # the dispatch decision that produced it).
    clear_dispatch_log()

    # Wall-time ledger over the whole measurement (docs/observability.md):
    # compile vs step vs input-wait decomposition plus per-window stall
    # anomalies — on the relayed bench chip the >5x transient slowdowns
    # are exactly what separates `value` (best window) from the median.
    ledger = GoodputLedger()

    # Keep both A/B arms doing the same work: the savrec path never mixes
    # on the host, so its device_preprocess trainer must not mix either;
    # the tf.data feed mixes on both sides (host mixes vs device mixes),
    # with the trainer's recipe pinned to the iterator's hard-coded
    # augment_name rather than whatever TrainConfig defaults to.
    trainer = _make_trainer(
        model_name, batch_size, backend, image_size, device_preprocess,
        augment="none" if feed == "savrec" else "cutmix_mixup_randaugment_405",
    )
    state = trainer.init_state()
    rng = jax.random.PRNGKey(0)
    result: dict = {}
    recorder = None
    if record:
        # Flight recorder at *window* granularity (off by default — bench
        # measures the hot loop and must not instrument inside it): a
        # pre-window state snapshot + the window's loss through the
        # nonfinite/spike gates. A NaN'd bench then carries an incident
        # pointer in its JSON line instead of just a wrong-looking number
        # (docs/incident_replay.md). Window entries are step-sparse, so
        # bundles honestly come out replayable: false.
        from sav_tpu.obs.recorder import FlightRecorder

        recorder = FlightRecorder.from_config(
            trainer.config, record_dir or "runs/bench",
            depth=max(reps, 2), keep_batches=max(reps, 2), snapshot_every=1,
        )
    # Roofline accounting (sav_tpu/obs/costs.py): the synthetic branch
    # upgrades this analytic estimate with the AOT executable's exact XLA
    # cost analysis; the fed branches keep the analytic fallback (their
    # step compiles through the jit dispatch cache).
    peak, peak_source = resolve_peak_flops(peak_flops)
    cost = train_step_cost(
        state.params, batch_size=batch_size, image_size=image_size,
        n_devices=len(jax.devices()),
    )

    if feed == "synthetic":
        batch = next(
            synthetic_data_iterator(
                batch_size=batch_size,
                image_size=image_size,
                num_classes=1000,
                learnable=False,
            )
        )
        sharded = trainer.shard_batch(batch)

        # One AOT compile: the measurement loop runs the same executable the
        # cost analysis comes from (AOT .compile() does not populate the jit
        # dispatch cache, so mixing AOT + jit would compile twice).
        with ledger.measure("compile"):
            step = trainer.compile_train_step(state, sharded, rng)
        cost = train_step_cost(
            state.params, batch_size=batch_size, image_size=image_size,
            compiled=step, n_devices=len(jax.devices()),
        )

        # Warmup. Sync via device_get of the loss value — on relayed/remote
        # platforms block_until_ready alone can return before execution
        # completes.
        with ledger.measure("step"):
            for _ in range(2):
                state, metrics = step(state, sharded, rng)
            float(jax.device_get(metrics["loss"]))

        windows = []
        for rep in range(reps):
            if recorder is not None:
                recorder.snapshot(rep * steps, jax.device_get(state))
                recorder.observe_batch(batch)
            t0 = time.perf_counter()
            for _ in range(steps):
                state, metrics = step(state, sharded, rng)
            loss_val = float(jax.device_get(metrics["loss"]))
            elapsed = time.perf_counter() - t0
            ledger.note_window(steps, elapsed, step=(rep + 1) * steps)
            windows.append(elapsed / steps)
            _record_window(recorder, (rep + 1) * steps, loss_val, result)

        if trace:
            # One EXTRA profiled window after the measured ones (profiler
            # overhead must not pollute `value`), machine-read on the
            # spot (sav_tpu/obs/traceview.py): the compiled step's HLO
            # metadata attributes device time onto the cost model's
            # component keys, and the measured attention-core fraction
            # rides the JSON line + manifest so the regression sentinel
            # gates on WHERE the time went, not just how much
            # (docs/profiling.md).
            from sav_tpu.obs import traceview
            from sav_tpu.utils import profiler as _prof

            # `value` is fully measured by now: a capture failure
            # (unwritable dir, profiler already active, a crash in the
            # extra window) must degrade to a bench WITHOUT trace
            # fields, never destroy the measurement (see except below).
            # Fresh per-run subdirectory: runs/bench/trace accumulates
            # captures across invocations, and an empty capture (the
            # failure the `if traces:` guard exists for) must read as
            # "no trace", never as a PRIOR run's trace summarized under
            # THIS run's op index and stamped into its sentinel record.
            trace_dir = os.path.join(
                record_dir or "runs/bench", "trace",
                f"{time.strftime('%Y%m%d-%H%M%S')}-{os.getpid()}",
            )
            try:
                op_index = traceview.parse_hlo_op_index(step.as_text())
                jax.block_until_ready(state)
                _prof.start_trace(trace_dir)
                try:
                    for _ in range(steps):
                        state, metrics = step(state, sharded, rng)
                    float(jax.device_get(metrics["loss"]))
                finally:
                    _prof.stop_trace()
                traces = traceview.find_traces(trace_dir)
                if traces:
                    traceview.save_op_index(
                        os.path.join(
                            os.path.dirname(traces[-1]), "op_index.json"
                        ),
                        op_index,
                    )
                    summary = traceview.summarize(
                        traces[-1], op_index=op_index,
                        predicted=cost.attribution, steps=steps,
                    )
                    # Same artifact contract as autoprof captures: the
                    # full summary next to the trace, so run_report
                    # --trace and trace_report discover it offline.
                    try:
                        with open(
                            os.path.join(
                                os.path.dirname(traces[-1]),
                                "trace_summary.json",
                            ),
                            "w",
                        ) as f:
                            json.dump(summary, f, indent=2)
                    except OSError:
                        pass
                    acf = summary.get("attention_core_frac")
                    result["trace"] = {
                        "path": traces[-1],
                        "per_step_ms": summary.get("per_step_ms"),
                        "idle_frac": summary.get("idle_frac"),
                        "indexed_frac": summary.get("indexed_frac"),
                        "components_frac": summary.get(
                            "components_frac"
                        ),
                        "disagrees": (
                            summary.get("vs_predicted") or {}
                        ).get("disagrees", []),
                    }
                    if acf is not None:
                        result["attention_core_frac"] = round(acf, 4)
            except Exception as e:
                result["trace_error"] = repr(e)[:300]
    else:
        import tempfile

        tmpdir = tempfile.mkdtemp(prefix="sav_bench_")
        # Host-only pipeline rate (how fast the input side alone can go).
        it = _feed_iterator(feed, batch_size, image_size, tmpdir, device_preprocess)
        for _ in range(2):
            next(it)  # warm caches / tf.data autotune
        t0 = time.perf_counter()
        host_steps = max(steps // 2, 5)
        with ledger.measure("input_wait"):
            for _ in range(host_steps):
                next(it)
        host_rate = batch_size * host_steps / (time.perf_counter() - t0)
        result["host_pipeline_img_per_sec"] = round(host_rate, 1)

        # End-to-end: pipeline feeding the real train step.
        it = _feed_iterator(feed, batch_size, image_size, tmpdir, device_preprocess)
        first = next(it)
        with ledger.measure("compile"):
            state, metrics = trainer.train_step(state, first, rng)
            float(jax.device_get(metrics["loss"]))
        # Host->device transfer cost for one batch, measured *after* device
        # compute has run: on some rigs (the relayed bench chip) transfer
        # bandwidth degrades sharply once a program has executed, and this
        # is what dominates the fed number there — report it so end-to-end
        # decomposes into host / transfer / device-step. Best of 3 (the
        # chip shows transient stalls), synced via device_get of a
        # reduction over the placed bytes (block_until_ready alone can ack
        # early on relayed platforms — see the synthetic branch).
        import jax.numpy as jnp

        # jit caches on the callable object: define the reduction once and
        # run one untimed warm-up so the timed reps measure transfer, not a
        # fresh trace+compile per rep (ADVICE r3).
        _sum_placed = jax.jit(lambda b: jnp.sum(b.astype(jnp.float32)))
        jax.device_get(_sum_placed(trainer.shard_batch(first)["images"]))
        transfer_s = float("inf")
        with ledger.measure("h2d"):
            for _ in range(3):
                t0 = time.perf_counter()
                placed = trainer.shard_batch(first)
                jax.device_get(_sum_placed(placed["images"]))
                transfer_s = min(transfer_s, time.perf_counter() - t0)
        nbytes = sum(
            getattr(v, "nbytes", 0) for v in first.values()
        )
        result["transfer_ms_per_batch"] = round(transfer_s * 1e3, 1)
        result["transfer_mb_per_s"] = round(nbytes / transfer_s / 1e6, 1)
        # Bytes on the wire per batch: uint8 (--device-preprocess) must
        # come out ≈½ the late-bf16 path's, ¼ of f32 — the lever PERF §7
        # measured directly in fed throughput.
        result["transfer_bytes_per_batch"] = nbytes
        # The measured loop pipelines via the async device feeder (the
        # production fit() path): a background thread fetches + places
        # batch N+1 while the device runs step N. --no-async-feed
        # restores the serial fetch → put → step loop for A/B.
        feeder = None
        if async_feed:
            from sav_tpu.data.feeder import DeviceFeeder

            feeder = DeviceFeeder(
                it, trainer.shard_batch, depth=2, name="bench-feeder"
            )

            def next_placed():
                return next(feeder)
        else:
            def next_placed():
                return trainer.shard_batch(next(it))
        windows = []
        try:
            for rep in range(reps):
                if recorder is not None:
                    recorder.snapshot(rep * steps, jax.device_get(state))
                t0 = time.perf_counter()
                for _ in range(steps):
                    state, metrics = trainer.train_step_placed(
                        state, next_placed(), rng
                    )
                loss_val = float(jax.device_get(metrics["loss"]))
                elapsed = time.perf_counter() - t0
                _record_window(
                    recorder, (rep + 1) * steps, loss_val, result
                )
                # Fed windows interleave host fetch + transfer + device
                # step; the ledger books them as 'step' (end-to-end
                # goodput), with the host-only and transfer shares
                # reported separately above.
                ledger.note_window(steps, elapsed, step=(rep + 1) * steps)
                windows.append(elapsed / steps)
        finally:
            if feeder is not None:
                for k, v in feeder.stats().items():
                    ledger.set_gauge(f"feeder/{k}", v)
                feeder.close()

    if recorder is not None:
        for k, v in recorder.stats().items():
            ledger.set_gauge(f"recorder/{k}", v)
    n_chips = len(jax.devices())
    best = min(windows)
    # Cost-model attribution + roofline (docs/perf_accounting.md):
    # cost_analysis FLOPs are per-device → MFU is per chip. Fed-mode MFU
    # is end-to-end (the windows interleave host fetch + transfer with
    # device compute) — lower by construction than the synthetic number.
    publish_cost_gauges(
        ledger, cost, peak_flops=peak, peak_source=peak_source
    )
    result["step_flops_per_device"] = cost.flops
    result["cost_source"] = cost.source
    result["flops_attribution"] = {
        k: round(v, 4) for k, v in cost.attribution.items()
    }
    if cost.flops and peak:
        ledger.set_gauge("flops_per_s", cost.flops / best)
        ledger.set_gauge("mfu", cost.flops / best / peak)
        result["mfu"] = round(cost.flops / best / peak, 4)
        result["peak_flops_source"] = peak_source
        if peak_source != "cpu-fake":
            # The img/s/chip this hardware could do at 100% of its
            # *theoretical* peak — the physical ceiling of the benchmark
            # chip. FLOPs are per-device and the batch is sharded, so
            # the per-chip image share is batch/n_devices. The BASELINE
            # north star (8,000 img/s/chip) was set for a TPU v4 part;
            # when this bound is below the north star, no code on this
            # chip can reach it and vs_baseline must be read against
            # the bound. Suppressed under the CPU fake peak — a bound
            # computed from a made-up number would only mislead.
            per_chip_images = batch_size / n_chips
            result["peak_bound_img_per_sec_per_chip"] = round(
                peak * per_chip_images / cost.flops, 1
            )
    # The resolved attention dispatch (backend + block config per traced
    # shape) — stamped into the JSON line and the run manifest so perf
    # history is attributable to the dispatch decision, not just the
    # requested flag (tools/regression_sentinel.py reads the manifests).
    result["attention_dispatch"] = snapshot_dispatch_log()
    result.update(
        best_step_ms=round(best * 1e3, 2),
        median_img_per_sec_per_chip=round(
            batch_size / statistics.median(windows) / n_chips, 1
        ),
        goodput=ledger.summary(),
    )
    # Flat metric view for the run manifest (main() pops this before
    # printing — underscore-prefixed keys never reach the output JSON).
    result["_manifest_metrics"] = {
        "value": round(batch_size / best / n_chips, 1),
        **ledger.flat_metrics(),
        **(
            {"attention_core_frac": result["attention_core_frac"]}
            if "attention_core_frac" in result else {}
        ),
    }
    return batch_size / best / n_chips, n_chips, result


def _abort_backend_unreachable(args, manifest, probe_log):
    """The BENCH_r05 fix: when the relay probe gives up, the run still
    ends with ONE parseable stdout JSON line — ``outcome:
    "backend_unreachable"``, the probe timeline, and a pointer to the
    finalized manifest — instead of prose-only stderr that records as
    ``"parsed": null``. The stderr message and exit 3 keep the
    backend_probe abort contract wrapper scripts key on.
    """
    from sav_tpu.obs.fleet import write_probe_timeline
    from sav_tpu.utils.backend_probe import unreachable_message

    message = unreachable_message("bench", args.backend_wait)
    probe = {
        "deadline_s": args.backend_wait,
        "attempts": len(probe_log),
        "probes": probe_log,
    }
    manifest.finalize(
        "backend_unreachable", error=message, exit_code=3,
        notes={"backend_probe": probe},
    )
    # The same timeline in the fleet artifact layout (stdlib-only write,
    # never raises): a post-mortem then distinguishes "backend never
    # came up" (probe lines, no proc_*.jsonl heartbeats) from "backend
    # died mid-run" (heartbeats that stop) in ONE directory —
    # docs/fleet.md.
    probe_path = write_probe_timeline(
        os.path.dirname(manifest.path) or ".", probe_log,
        deadline_s=args.backend_wait, tag="bench",
    )
    print(message, file=sys.stderr)
    print(json.dumps({
        "metric": f"{args.model} train img/s/chip (bs={args.batch_size})",
        "value": None,
        "unit": "img/s/chip",
        "outcome": "backend_unreachable",
        "backend_probe": probe,
        "probe_timeline": probe_path,
        "manifest": manifest.path,
    }))
    return 3


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--model", default="deit_s_patch16")
    parser.add_argument("--batch-size", type=int, default=256)
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument(
        "--backend",
        default="xla",
        choices=["xla", "fused", "pallas", "auto"],
        help="attention backend: xla (dense), fused (single-pass "
        "short-sequence kernel), pallas (online-softmax flash), or the "
        "three-way measured auto dispatch (short band consults the "
        "attn_tune cache; long band is flash — see PERF.md). The resolved "
        "decision is stamped into the JSON line as attention_dispatch",
    )
    parser.add_argument(
        "--feed",
        default="synthetic",
        choices=["synthetic", "pipeline", "savrec"],
        help="synthetic = device-resident batch; pipeline/savrec = real "
        "input paths feeding the train step",
    )
    parser.add_argument(
        "--reps", type=int, default=4,
        help="timed windows; best and median are both reported",
    )
    parser.add_argument(
        "--device-preprocess", action="store_true",
        help="fed modes ship post-augment uint8 (4x fewer bytes than f32) "
        "and the jitted step normalizes + mixes on device "
        "(TrainConfig.device_preprocess)",
    )
    parser.add_argument(
        "--no-async-feed", action="store_true",
        help="serialize the fed loop (fetch -> device_put -> step on one "
        "thread) instead of the default async double-buffered feeder "
        "(sav_tpu/data/feeder.py) -- the A/B arm for overlap wins",
    )
    parser.add_argument(
        "--compilation-cache-dir", default=None,
        help="persistent XLA compilation cache directory "
        "(jax_compilation_cache_dir): repeat benches skip the relay "
        "compile (493s for TNT, PERF.md §12)",
    )
    parser.add_argument(
        "--backend-wait", type=float, default=600.0,
        help="seconds to poll for the accelerator relay before giving up "
        "(0 disables; a transient outage then degrades to a late number "
        "instead of a missing one)",
    )
    parser.add_argument(
        "--peak-flops", type=float, default=None,
        help="per-chip peak FLOP/s override for MFU/roofline accounting "
        "(docs/perf_accounting.md); default: the device-kind table, with "
        "a deterministic fake peak on CPU (labeled cpu-fake)",
    )
    parser.add_argument(
        "--attn-tune-cache", default=None,
        help="tools/attn_tune.py shape→config cache for the 'auto' "
        "dispatcher (default: SAV_ATTN_TUNE_CACHE env var, then the "
        "checked-in sav_tpu/ops/attn_tune_cache.json)",
    )
    parser.add_argument(
        "--record", action="store_true",
        help="flight recorder at window granularity (off by default so "
        "the measured loop stays uninstrumented): pre-window state "
        "snapshots + the window losses through the nonfinite/spike "
        "gates; a NaN'd bench then carries an 'incident' bundle pointer "
        "in its JSON line and finalizes outcome: nonfinite "
        "(docs/incident_replay.md)",
    )
    parser.add_argument(
        "--trace", action="store_true",
        help="capture one extra profiled window AFTER the measured ones "
        "and machine-read it (sav_tpu/obs/traceview.py): per-layer-group "
        "device-time attribution vs the cost model, with the measured "
        "attention-core fraction in the JSON line + manifest so the "
        "regression sentinel gates on where time went (synthetic feed "
        "only — the fed loops have no AOT executable to index)",
    )
    parser.add_argument(
        "--manifest", default=None,
        help="run-manifest path (sav_tpu/obs/manifest.py): written at "
        "start, finalized with a machine-readable outcome on every exit "
        "path — including the backend-unreachable abort. Default: a "
        "per-run runs/bench/manifest-<stamp>-<pid>.json, so successive "
        "benches accumulate history instead of overwriting one file "
        "(the sentinel's directory expansion globs manifest*.json)",
    )
    args = parser.parse_args(argv)
    if args.manifest is None:
        args.manifest = os.path.join(
            "runs", "bench",
            f"manifest-{time.strftime('%Y%m%d-%H%M%S')}-{os.getpid()}.json",
        )
    if args.trace and args.feed != "synthetic":
        parser.error(
            "--trace needs the synthetic feed: attribution reads the AOT "
            "executable's HLO metadata, and only the synthetic loop runs "
            "one"
        )
    if args.device_preprocess and args.feed == "synthetic":
        parser.error(
            "--device-preprocess measures the fed paths (uint8 transfer + "
            "on-device finishing); the synthetic feed ships device-resident "
            "f32 batches, so the combination would mislabel the metric"
        )
    from sav_tpu.obs.manifest import RunManifest, classify_exception

    manifest = RunManifest(args.manifest, kind="bench", argv=sys.argv[1:])
    manifest.begin()
    if args.backend_wait > 0 and "pytest" not in sys.modules:
        from sav_tpu.utils.backend_probe import wait_for_backend

        probe_log: list = []
        platform = wait_for_backend(
            args.backend_wait, tag="bench", probe_log=probe_log
        )
        if platform is None:
            return _abort_backend_unreachable(args, manifest, probe_log)

    try:
        value, n_chips, extra = run(
            args.model, args.batch_size, args.steps, args.backend,
            args.image_size, reps=args.reps, feed=args.feed,
            device_preprocess=args.device_preprocess,
            async_feed=not args.no_async_feed,
            compilation_cache_dir=args.compilation_cache_dir,
            peak_flops=args.peak_flops,
            record=args.record,
            record_dir=os.path.dirname(args.manifest) or "runs/bench",
            attn_tune_cache=args.attn_tune_cache,
            trace=args.trace,
        )
    except BaseException as e:
        # Every exit path stays parseable: classify (oom/error/...), put
        # the outcome in the manifest AND on stdout, then re-raise for
        # the traceback + nonzero rc (the BENCH_r03 failure mode recorded
        # rc=1 with parsed: null — now the last stdout line explains).
        outcome = classify_exception(e)
        manifest.finalize(outcome, error=repr(e), exit_code=1)
        print(json.dumps({
            "outcome": outcome,
            "error": repr(e)[:500],
            "manifest": manifest.path,
        }))
        raise
    feed_desc = args.feed + (
        " uint8+device-preprocess" if args.device_preprocess else ""
    )
    if args.feed != "synthetic" and args.no_async_feed:
        feed_desc += " serial"
    # Heavy imports stay function-local so --help never pays for them; the
    # relay probe itself runs in a subprocess (sav_tpu.utils.backend_probe,
    # stdlib-only module behind lazy package re-exports).
    import jax

    manifest_metrics = extra.pop("_manifest_metrics", {})
    # A recorded NONFINITE incident demotes the outcome: the regression
    # sentinel must never score a diverged run's throughput as a
    # measurement. A finite loss_spike incident keeps outcome ok — the
    # timing numbers are still real measurements — but the bundle pointer
    # rides the JSON line and manifest either way.
    outcome = (
        "nonfinite" if extra.get("incident_trigger") == "nonfinite"
        else "ok"
    )
    out = {
        "metric": f"{args.model} train img/s/chip (bs={args.batch_size}, "
        f"bf16, {args.backend} attention, {feed_desc} feed, {n_chips} chip, "
        f"best of {args.reps}x{args.steps}-step windows)",
        "value": round(value, 1),
        "unit": "img/s/chip",
        "vs_baseline": round(value / BASELINE_IMG_PER_SEC_PER_CHIP, 4),
        # Makes a silent CPU fallback visible in the recorded JSON — the
        # number is only comparable to the baseline on a real accelerator.
        "platform": jax.devices()[0].platform,
        "outcome": outcome,
        "manifest": manifest.path,
    }
    out.update(extra)
    notes = {"metric": out["metric"], "platform": out["platform"]}
    if extra.get("attention_dispatch"):
        notes["attention_dispatch"] = extra["attention_dispatch"]
    if extra.get("trace"):
        notes["trace"] = extra["trace"]
    if extra.get("incident"):
        notes["incident"] = extra["incident"]
    manifest.finalize(
        outcome, exit_code=0, metrics=manifest_metrics, notes=notes,
    )
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
