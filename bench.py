#!/usr/bin/env python
"""Headline benchmark: DeiT-S/16 ImageNet-shape training throughput per chip.

Measures the full jitted train step (forward + backward + AdamW update,
bf16 compute, label smoothing) on synthetic 224² batches — the
BASELINE.json north-star metric (target ≥8,000 img/s/chip). Prints exactly
one JSON line:

    {"metric": ..., "value": N, "unit": "img/s/chip", "vs_baseline": N}

``vs_baseline`` is value / 8000 (the driver-set north star; the reference
itself published no numbers — BASELINE.md).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

BASELINE_IMG_PER_SEC_PER_CHIP = 8000.0


def run(model_name: str, batch_size: int, steps: int, backend, image_size: int,
        reps: int = 4):
    import jax
    import numpy as np

    from sav_tpu.data import synthetic_data_iterator
    from sav_tpu.train import TrainConfig, Trainer

    config = TrainConfig(
        model_name=model_name,
        num_classes=1000,
        image_size=image_size,
        compute_dtype="bfloat16",
        attention_backend=backend,
        global_batch_size=batch_size,
        transpose_images=False,
        clip_grad_norm=1.0,
        seed=0,
    )
    trainer = Trainer(config)
    state = trainer.init_state()
    batch = next(
        synthetic_data_iterator(
            batch_size=batch_size,
            image_size=image_size,
            num_classes=1000,
            learnable=False,
        )
    )
    sharded = trainer.shard_batch(batch)
    rng = jax.random.PRNGKey(0)

    # Warmup/compile (2 steps: first compiles, second confirms steady state).
    # Sync via device_get of the loss value — on relayed/remote platforms
    # block_until_ready alone can return before execution completes.
    for _ in range(2):
        state, metrics = trainer._train_step(state, sharded, rng)
    float(jax.device_get(metrics["loss"]))

    # Best of ``reps`` timed windows: the benchmark chip is shared/tunneled
    # and single windows show >5x transient slowdowns from contention; the
    # minimum step time is the honest hardware-capability number.
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = trainer._train_step(state, sharded, rng)
        float(jax.device_get(metrics["loss"]))
        best = min(best, (time.perf_counter() - t0) / steps)

    n_chips = len(jax.devices())
    img_per_sec = batch_size / best
    return img_per_sec / n_chips, n_chips, best


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--model", default="deit_s_patch16")
    parser.add_argument("--batch-size", type=int, default=256)
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument(
        "--backend",
        default="xla",
        choices=["xla", "pallas", "auto"],
        help="attention backend (XLA fuses best at 197-token DeiT shapes today)",
    )
    parser.add_argument(
        "--reps", type=int, default=4,
        help="timed windows; the best one is reported (shared-chip noise)",
    )
    args = parser.parse_args(argv)

    value, n_chips, step_s = run(
        args.model, args.batch_size, args.steps, args.backend, args.image_size,
        reps=args.reps,
    )
    print(
        json.dumps(
            {
                "metric": f"{args.model} train img/s/chip (bs={args.batch_size}, "
                f"bf16, {args.backend} attention, {n_chips} chip, "
                f"best of {args.reps}x{args.steps}-step windows)",
                "value": round(value, 1),
                "unit": "img/s/chip",
                "vs_baseline": round(value / BASELINE_IMG_PER_SEC_PER_CHIP, 4),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
