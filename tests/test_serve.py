"""Serving engine (sav_tpu/serve/) — ISSUE 10.

Unit tier (stdlib-only, no jax): the bucket ladder, the latency ledger's
percentiles/waste accounting, and the DynamicBatcher's deadline policy
under an injectable fake clock — the drain waits while the earliest
admitted deadline still has slack for the bucket's step, ships promptly
once it does not, and never dispatches later than
``earliest_deadline - est_step(bucket)`` (the invariant that bounds any
overrun to at most one bucket's actual step time).

Engine tier (tiny ViT on CPU): end-to-end serving correctness (results
match the model, padded rows masked to zero), the overlap-ordering
proof that batch N+1 is PLACED while batch N executes (the
tests/test_feeder.py technique, through the engine's instrumented
hooks), the dynamic-batching throughput proof against the batch-size-1
ladder, params-only checkpoint restore (both optimizer layouts, EMA,
opt_state never requested), the serving manifest -> sentinel loop
(fixture-pinned both directions), the uint8 wire-format parity against
the training loader's eval preprocessing, the zoo ``--serve`` check for
all seven families, and the warm-compile-cache restart proof (second
serve_bench process compiles 0 executables from scratch).
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from sav_tpu.serve.batcher import (
    DynamicBatcher,
    QueueFullError,
    ServeClosedError,
    ServeFuture,
)
from sav_tpu.serve.bucketing import BucketLadder, default_ladder, padding_waste
from sav_tpu.serve.latency import LatencyLedger, percentile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(__file__), "sentinel_fixtures")


# ------------------------------------------------------------- unit tier


def test_bucket_ladder_lookups():
    ladder = BucketLadder([4, 1, 8, 2])
    assert ladder.buckets == (1, 2, 4, 8)
    assert ladder.max_batch == 8
    assert ladder.bucket_for(1) == 1
    assert ladder.bucket_for(3) == 4
    assert ladder.bucket_for(8) == 8
    assert ladder.largest_fillable(7) == 4
    assert ladder.largest_fillable(1) == 1
    with pytest.raises(ValueError, match="exceeds the top bucket"):
        ladder.bucket_for(9)
    with pytest.raises(ValueError, match="at least one request"):
        ladder.bucket_for(0)
    with pytest.raises(ValueError, match="at least one rung"):
        BucketLadder([])
    with pytest.raises(ValueError, match=">= 1"):
        BucketLadder([0, 2])


def test_default_ladder_is_pow2_and_reaches_max():
    assert default_ladder(8) == [1, 2, 4, 8]
    assert default_ladder(1) == [1]
    # A non-power-of-two max is still a rung: configured capacity is
    # reachable.
    assert default_ladder(6) == [1, 2, 4, 6]
    assert padding_waste(3, 4) == 0.25
    assert padding_waste(4, 4) == 0.0
    with pytest.raises(ValueError):
        padding_waste(5, 4)


def test_percentile_interpolation():
    series = sorted([10.0, 20.0, 30.0, 40.0])
    assert percentile(series, 50.0) == 25.0
    assert percentile(series, 0.0) == 10.0
    assert percentile(series, 100.0) == 40.0
    assert percentile([7.0], 99.0) == 7.0
    with pytest.raises(ValueError):
        percentile([], 50.0)
    with pytest.raises(ValueError):
        percentile([1.0], 101.0)


def test_latency_ledger_summary_accounting():
    t = [0.0]
    ledger = LatencyLedger(clock=lambda: t[0])
    ledger.start()
    t[0] = 1.0
    ledger.observe_batch(
        bucket=4, latencies_s=[0.010, 0.020, 0.030],
        overruns_s=[-0.05, -0.04, 0.002], queue_depth=5, step_s=0.008,
    )
    t[0] = 2.0
    ledger.observe_batch(
        bucket=1, latencies_s=[0.040], overruns_s=[-0.1],
        queue_depth=0, step_s=0.004,
    )
    ledger.observe_rejected(2)
    s = ledger.summary()
    assert s["requests"] == 4
    assert s["batches"] == 2
    assert s["rejected"] == 2
    # 4 real rows over 4+1=5 padded rows -> 1/5 waste.
    assert s["padding_waste_frac"] == 0.2
    assert s["bucket_occupancy"]["4"] == {"batches": 1, "fill": 0.75}
    assert s["queue_depth_max"] == 5
    assert s["deadline_overruns"] == 1
    assert s["deadline_overrun_max_ms"] == 2.0
    assert s["latency_ms"]["p50"] == 25.0
    assert s["wall_s"] == 2.0  # start() .. last observe
    assert s["throughput_rps"] == 2.0
    flat = ledger.flat_metrics()
    assert flat["serve/p99_latency_ms"] == s["latency_ms"]["p99"]
    assert flat["serve/throughput_rps"] == 2.0


class FakeClock:
    """Manually advanced monotonic clock for deterministic drain tests."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _drain_in_thread(batcher):
    out = {}

    def drain():
        out["formed"] = batcher.next_batch()

    thread = threading.Thread(target=drain, daemon=True)
    thread.start()
    return thread, out


def test_batcher_hot_queue_fills_largest_bucket_immediately():
    clock = FakeClock()
    batcher = DynamicBatcher(
        BucketLadder([1, 2, 4]), step_time_fn=lambda b: 0.01,
        default_deadline_s=1.0, clock=clock,
    )
    for _ in range(6):
        batcher.submit("x")
    formed = batcher.next_batch()
    # 6 waiting -> grab the top bucket's worth outright, no deadline wait.
    assert formed.bucket == 4
    assert len(formed.requests) == 4
    assert formed.queue_depth == 2
    batcher.close()


def test_batcher_waits_while_slack_remains_then_ships_partial():
    clock = FakeClock()
    batcher = DynamicBatcher(
        BucketLadder([1, 2, 4]), step_time_fn=lambda b: 0.2,
        default_deadline_s=10.0, clock=clock,
    )
    batcher.submit("lonely")
    thread, out = _drain_in_thread(batcher)
    # Fake time is frozen with 9.8s of slack: the drain must NOT ship.
    thread.join(timeout=0.4)
    assert thread.is_alive(), "shipped a partial batch with slack remaining"
    # Advance past deadline - est_step: ships promptly, padded bucket 1.
    clock.advance(9.85)
    thread.join(timeout=2.0)
    assert not thread.is_alive()
    formed = out["formed"]
    assert formed.bucket == 1
    assert len(formed.requests) == 1
    batcher.close()


def test_batcher_deadline_dispatch_bound_pinned():
    """The overrun bound: every batch is dispatched no later than
    ``earliest_deadline - est_step(bucket)`` (+ the drain's poll
    granularity in fake time: one poll wakes per real POLL_S, and the
    test advances fake time in sub-slack steps). Completion therefore
    exceeds the earliest deadline by at most the bucket's ACTUAL step
    time — the 'one bucket step' guarantee docs/serving.md states."""
    clock = FakeClock()
    est = {1: 0.1, 2: 0.15, 4: 0.2}
    batcher = DynamicBatcher(
        BucketLadder([1, 2, 4]), step_time_fn=lambda b: est[b],
        default_deadline_s=5.0, clock=clock,
    )
    batcher.submit("a")
    clock.advance(1.0)
    batcher.submit("b", deadline_s=2.5)  # earliest absolute deadline: 3.5
    thread, out = _drain_in_thread(batcher)
    thread.join(timeout=0.4)
    assert thread.is_alive()  # slack remains at t=1.0
    # Jump near the bound (still slack), then step fake time across it;
    # the drain must ship at the first poll where
    # now >= earliest_deadline - est_step(bucket_for(2)) = 3.35.
    clock.advance(2.25)  # t = 3.25, 0.1 of slack left
    thread.join(timeout=0.3)
    assert thread.is_alive(), "shipped with slack remaining"
    while thread.is_alive() and clock.t < 10.0:
        clock.advance(0.05)
        thread.join(timeout=0.15)
    formed = out["formed"]
    assert formed is not None
    assert formed.bucket == 2
    earliest = min(r.deadline_t for r in formed.requests)
    assert earliest == pytest.approx(3.5)
    # Dispatched at-or-after the bound was crossed, within one fake step
    # of it — never later (the pinned guarantee), never earlier than the
    # slack allowed (the previous test).
    bound = earliest - est[2]
    assert bound <= formed.formed_t <= bound + 0.1 + 1e-9
    batcher.close()


def test_batcher_bounded_queue_rejects_and_counts():
    batcher = DynamicBatcher(
        BucketLadder([1, 2]), step_time_fn=lambda b: 0.0, max_queue=2,
    )
    batcher.submit("a")
    batcher.submit("b")
    with pytest.raises(QueueFullError, match="capacity"):
        batcher.submit("c")
    assert batcher.stats() == {
        "submitted": 2, "rejected": 1, "shed_infeasible": 0,
        "inflight": 0, "queued": 2,
    }
    batcher.close()


def test_batcher_sheds_deadline_infeasible_at_admission():
    """The overload half of the deadline guarantee: a request whose
    projected dispatch wait (in-flight + queued-ahead batches, one
    top-bucket step each) already exceeds its deadline is shed at
    submit — serving it would be a guaranteed miss. Projection math
    pinned: max bucket 2, est 0.1s/batch, deadline 0.25s admits 4
    queued (ceil(k/2)*0.1 <= 0.25) and sheds the 5th."""
    from sav_tpu.serve.batcher import DeadlineInfeasibleError

    clock = FakeClock()
    batcher = DynamicBatcher(
        BucketLadder([1, 2]), step_time_fn=lambda b: 0.1,
        default_deadline_s=0.25, clock=clock,
    )
    for tag in ("a", "b", "c", "d"):
        batcher.submit(tag)  # batches ahead: 1,1,2,2 -> <= 0.2s wait
    with pytest.raises(DeadlineInfeasibleError, match="shedding"):
        batcher.submit("e")  # 3 batches ahead -> 0.3s > 0.25s deadline
    # A roomier per-request deadline is still admitted.
    batcher.submit("e", deadline_s=1.0)
    # In-flight batches count as wait ahead too: drain one batch (NOT
    # completed) and the projection for a fresh 0.25s request now sees
    # inflight=1 + its own batch.
    formed = batcher.next_batch()
    assert formed.bucket == 2
    assert batcher.stats()["inflight"] == 1
    with pytest.raises(DeadlineInfeasibleError):
        batcher.submit("f", deadline_s=0.25)  # (1+2)*0.1 > 0.25
    batcher.mark_completed()
    assert batcher.stats()["inflight"] == 0
    stats = batcher.stats()
    assert stats["shed_infeasible"] == 2
    assert stats["rejected"] == 2
    # A deadline shorter than ONE bucket step is unserveable by
    # construction: always shed, even on an idle batcher.
    idle = DynamicBatcher(
        BucketLadder([1]), step_time_fn=lambda b: 0.2,
    )
    with pytest.raises(DeadlineInfeasibleError):
        idle.submit("x", deadline_s=0.1)
    idle.close()
    batcher.close()


def test_batcher_close_fails_queued_and_stops_admission():
    batcher = DynamicBatcher(
        BucketLadder([4]), step_time_fn=lambda b: 0.0,
    )
    future = batcher.submit("a")
    batcher.close()
    with pytest.raises(ServeClosedError):
        future.result(timeout=1.0)
    with pytest.raises(ServeClosedError):
        batcher.submit("b")
    # Drained-and-closed: next_batch reports the end of the stream.
    assert batcher.next_batch() is None


def test_future_result_timeout_and_set_once():
    future = ServeFuture()
    with pytest.raises(TimeoutError):
        future.result(timeout=0.05)
    future.set_result(41)
    assert future.result(timeout=0.1) == 41
    assert future.done()


# ------------------------------------------------------------ engine tier


def _tiny_config(**overrides):
    from sav_tpu.serve.engine import ServeConfig

    base = dict(
        model_name="vit_ti_patch16",
        num_classes=10,
        image_size=32,
        model_overrides={"num_layers": 1},
        buckets=[1, 2, 4],
        max_queue=128,
        deadline_ms=2000.0,
    )
    base.update(overrides)
    return ServeConfig(**base)


def _requests(n, image_size=32, seed=0):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(0, 256, (image_size, image_size, 3), dtype=np.uint8)
        for _ in range(n)
    ]


def test_engine_serves_correct_results_and_masks_padding(tmp_path):
    import jax
    import jax.numpy as jnp

    from sav_tpu.ops.preprocess import normalize_images
    from sav_tpu.serve.engine import ServeEngine

    # deadline 300ms: with 3 requests against bucket 4 the drain waits
    # out the slack for a 4th, then ships padded — the wait is the test's
    # only idle time, so keep the budget short.
    engine = ServeEngine(
        _tiny_config(buckets=[1, 4], deadline_ms=300.0, log_dir=str(tmp_path))
    )
    images = _requests(3)
    with engine:
        # 3 requests flood into bucket 4 (one padded row).
        futures = [engine.submit(img) for img in images]
        rows = [f.result(timeout=30.0) for f in futures]
    assert all(r.shape == (10,) for r in rows)
    assert all(np.isfinite(r).all() for r in rows)
    # Results match a direct (non-AOT) apply of the same model+params on
    # the same uint8 wire bytes (bf16 compute: loose-ish tolerance).
    x = normalize_images(
        jnp.asarray(np.stack(images)), engine.compute_dtype
    )
    expected = np.asarray(
        engine.model.apply(
            {"params": engine._params}, x, is_training=False
        ).astype(jnp.float32)
    )
    np.testing.assert_allclose(np.stack(rows), expected, rtol=0.05, atol=0.05)
    # The validity mask zeroes padded rows in the program itself. The
    # fresh init's head is zero-init (vacuous logits), so randomize it —
    # otherwise "masked to zero" is indistinguishable from "all zero".
    params = dict(engine._params)
    params["head"] = dict(params["head"])
    params["head"]["kernel"] = 0.02 * np.asarray(
        jax.random.normal(
            jax.random.PRNGKey(2), engine._params["head"]["kernel"].shape
        )
    )
    placed = engine._place_host_batch(
        np.stack(images + [np.zeros_like(images[0])]),
        np.array([1, 1, 0, 0], np.float32),
    )
    direct = engine._executables[4](params, engine._batch_stats, placed)
    out = np.asarray(direct["logits"])
    assert np.all(out[2:] == 0.0)
    assert np.any(out[:2] != 0.0)
    # The quality digest leaves (ISSUE 20) ride the same program and
    # the same validity mask: padded rows digest to zero.
    assert np.all(np.asarray(direct["margin"])[2:] == 0.0)
    assert np.all(np.asarray(direct["top1"])[2:] == 0)
    assert np.all(np.asarray(direct["entropy"])[2:] == 0.0)
    summary = engine.stop()
    assert summary["requests"] == 3
    assert summary["bucket_occupancy"]["4"]["batches"] == 1
    assert summary["padding_waste_frac"] == pytest.approx(0.25)
    # --- the finalized serving manifest, and its sentinel view ----------
    from sav_tpu.obs.manifest import normalize_run_record

    manifests = [f for f in os.listdir(tmp_path) if f.startswith("manifest")]
    assert len(manifests) == 1
    with open(os.path.join(tmp_path, manifests[0])) as f:
        data = json.load(f)
    assert data["kind"] == "serve"
    assert data["outcome"] == "ok"
    assert data["metrics"]["serve/requests"] == 3.0
    assert data["metrics"]["serve/p99_latency_ms"] > 0
    assert data["metrics"]["serve/throughput_rps"] > 0
    assert data["notes"]["serve_startup"]["buckets"] == [1, 4]
    assert "padding_waste_frac" in data["notes"]["serve_summary"]
    record = normalize_run_record(data, label="serve")
    assert record.ok
    assert record.metrics["p99_latency_ms"] == (
        data["metrics"]["serve/p99_latency_ms"]
    )
    assert record.metrics["serve_throughput"] == (
        data["metrics"]["serve/throughput_rps"]
    )
    assert "throughput" not in record.metrics  # img/s stays training-only


def test_engine_exit_on_exception_never_finalizes_ok(tmp_path):
    """A driver dying mid-serve must not ship an 'ok' serving record
    built from the few requests that happened to finish — finalize is
    first-wins, so if the context manager stamped 'ok' here, no later
    error finalize could correct it and the sentinel would score the
    broken run as a healthy p99 baseline."""
    from sav_tpu.serve.engine import ServeEngine

    engine = ServeEngine(
        _tiny_config(buckets=[1], log_dir=str(tmp_path))
    )
    with pytest.raises(RuntimeError, match="driver died"):
        with engine:
            engine.submit(_requests(1)[0]).result(timeout=30.0)
            raise RuntimeError("driver died mid-serve")
    manifests = [f for f in os.listdir(tmp_path) if f.startswith("manifest")]
    with open(os.path.join(tmp_path, manifests[0])) as f:
        data = json.load(f)
    assert data["outcome"] == "error"
    assert "driver died" in data["error"]
    # The partial measurements still ride along for the post-mortem —
    # but under a non-ok outcome the sentinel never scores them.
    assert data["metrics"]["serve/requests"] == 1.0


def test_engine_overlap_place_of_next_batch_during_execution():
    """The acceptance ordering proof (tests/test_feeder.py technique):
    with the device loop still 'executing' batch N (execute_hook holds
    it), the feeder worker must already have ISSUED the placement of
    batch N+1 — a serial loop would not touch it until N completed."""
    from sav_tpu.serve.engine import ServeEngine

    place_times = []
    executing = threading.Event()
    release = threading.Event()

    def place_hook(formed):
        place_times.append((time.monotonic(), len(formed.requests)))

    def execute_hook(formed):
        if not executing.is_set():
            executing.set()
            release.wait(timeout=10.0)  # hold batch 0 'on device'

    engine = ServeEngine(
        _tiny_config(buckets=[4]), place_hook=place_hook,
        execute_hook=execute_hook,
    )
    images = _requests(8)
    with engine:
        futures = [engine.submit(img) for img in images]
        assert executing.wait(timeout=10.0)
        # Batch 0 is executing; wait for the worker to place batch 1.
        deadline = time.monotonic() + 10.0
        while len(place_times) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        n_placed_during_execution = len(place_times)
        release.set()
        for f in futures:
            f.result(timeout=30.0)
    assert n_placed_during_execution >= 2, (
        "placement of batch N+1 was not issued while batch N executed"
    )


def test_engine_dynamic_batching_beats_batch_size_1():
    """The throughput half of the acceptance criterion: under the same
    open-loop flood, the deadline-aware bucketed ladder strictly beats
    the ladder-[1] baseline — and no admitted request overran its
    deadline by more than one bucket's measured step time."""
    from sav_tpu.serve.engine import ServeEngine

    n = 48
    results = {}
    for label, buckets in (("batched", [1, 8]), ("bs1", [1])):
        # Deadline sized so the admission projection admits the whole
        # flood even against the bs1 arm's 48-batch backlog (the
        # shedding path has its own deterministic test above).
        engine = ServeEngine(
            _tiny_config(buckets=buckets, max_queue=256, deadline_ms=20000.0)
        )
        with engine:
            futures = [engine.submit(img) for img in _requests(n)]
            for f in futures:
                f.result(timeout=60.0)
        summary = engine.stop()
        assert summary["requests"] == n
        # One bucket's step time is the pinned overrun bound; the EMA
        # estimate tracks the actual, so allow scheduler slop on top.
        max_step_ms = max(engine._step_est.values()) * 1e3
        assert summary["deadline_overrun_max_ms"] <= max_step_ms + 250.0
        results[label] = summary["throughput_rps"]
    assert results["batched"] > results["bs1"], results


def test_engine_admission_validation_and_lifecycle():
    """One engine, three contracts. Admission shed, deterministically:
    hold the feeder worker inside the FIRST batch's placement
    (place_hook blocks on its thread) so the drain stops pulling; with
    max_queue=1 the next submit is admitted and the one after that must
    reject — and the ledger counts it. Plus the submit validation
    errors and the not-started/stopped lifecycle errors."""
    from sav_tpu.serve.engine import ServeEngine

    placing = threading.Event()
    release = threading.Event()

    def place_hook(formed):
        placing.set()
        release.wait(timeout=10.0)

    engine = ServeEngine(
        _tiny_config(max_queue=1, buckets=[1]), place_hook=place_hook
    )
    images = _requests(4)
    with pytest.raises(ServeClosedError, match="start"):
        engine.submit(images[0])
    with engine:
        with pytest.raises(ValueError, match="uint8"):
            engine.submit(np.zeros((32, 32, 3), np.float32))
        with pytest.raises(ValueError, match="32, 32, 3"):
            engine.submit(np.zeros((16, 16, 3), np.uint8))
        with pytest.raises(ValueError, match="deadline_s"):
            engine.submit(images[0], deadline_ms=0.0)
        futures = [engine.submit(images[0])]
        # The drain grabs request 0 (ladder [1] ships singles outright)
        # and the worker blocks inside its placement.
        assert placing.wait(timeout=10.0)
        deadline = time.monotonic() + 10.0
        while engine._batcher.stats()["queued"] and time.monotonic() < deadline:
            time.sleep(0.01)
        futures.append(engine.submit(images[1]))  # fills the queue (1)
        with pytest.raises(QueueFullError):
            engine.submit(images[2])
        release.set()
        for f in futures:
            f.result(timeout=30.0)
    assert engine.stop()["rejected"] == 1
    with pytest.raises(ServeClosedError):
        engine.submit(images[0])


def test_engine_rejects_buckets_that_do_not_shard(devices):
    import jax

    from sav_tpu.parallel.mesh import create_mesh
    from sav_tpu.serve.engine import ServeEngine

    mesh = create_mesh({"data": 8}, devices=jax.devices())
    with pytest.raises(ValueError, match="do not divide the mesh"):
        ServeEngine(_tiny_config(buckets=[1, 2, 8]), mesh=mesh)


# -------------------------------------------- params-only restore + serve


def _tiny_train_config(tmpdir, **overrides):
    from sav_tpu.train.config import TrainConfig

    base = dict(
        model_name="vit_ti_patch16", num_classes=10, image_size=32,
        model_overrides={"num_layers": 1}, global_batch_size=8,
        num_train_images=64, num_epochs=1, checkpoint_dir=str(tmpdir),
        fleet=False,
    )
    base.update(overrides)
    return TrainConfig(**base)


@pytest.mark.parametrize(
    "layout",
    ["fused", "per_leaf", "per_leaf_ema"],
)
def test_restore_params_only_accepts_every_opt_layout(tmp_path, layout):
    """The satellite contract: params-only restore never touches
    opt_state, so flat-buffer, per-leaf, and EMA-carrying checkpoints
    all restore WITHOUT an optimizer rebuild — and without requesting a
    single opt_state leaf from orbax."""
    import jax

    from sav_tpu.train.checkpoint import Checkpointer
    from sav_tpu.train.trainer import Trainer

    cfg = _tiny_train_config(
        tmp_path,
        fused_optimizer=(layout == "fused"),
        ema_decay=0.99 if layout == "per_leaf_ema" else None,
    )
    trainer = Trainer(cfg)
    state = trainer.init_state()
    trainer.checkpointer.save(0, state)
    trainer.checkpointer.wait()
    reader = Checkpointer(str(tmp_path), read_only=True)
    try:
        probed = reader.opt_layout()
        assert probed.get("fused") is (layout == "fused")
        assert probed.get("ema") is (layout == "per_leaf_ema")
        template = {
            "params": state.params,
            "batch_stats": state.batch_stats,
            "step": state.step,
        }
        restored = reader.restore_params_only(template)
    finally:
        reader.close()
    assert sorted(restored.keys()) == ["batch_stats", "params", "step"]
    assert jax.tree.all(
        jax.tree.map(
            lambda a, b: np.array_equal(np.asarray(a), np.asarray(b)),
            restored["params"], state.params,
        )
    )


def test_restore_params_only_empty_dir_returns_none(tmp_path):
    from sav_tpu.train.checkpoint import Checkpointer

    ckpt = Checkpointer(str(tmp_path))
    try:
        assert ckpt.restore_params_only({"params": {}}) is None
    finally:
        ckpt.close()


def test_engine_serves_training_checkpoint_params_only(tmp_path):
    """End to end: a training checkpoint (full TrainState incl. Adam
    moments) serves through the engine's params-only restore, and the
    served logits match the checkpointed weights."""
    import jax
    import jax.numpy as jnp

    from sav_tpu.ops.preprocess import normalize_images
    from sav_tpu.serve.engine import ServeEngine
    from sav_tpu.train.trainer import Trainer

    trainer = Trainer(_tiny_train_config(tmp_path))
    state = trainer.init_state()
    trainer.checkpointer.save(0, state)
    trainer.checkpointer.wait()
    engine = ServeEngine(_tiny_config(checkpoint_dir=str(tmp_path)))
    assert engine.startup_report["params_source"].startswith("checkpoint:")
    image = _requests(1)[0]
    with engine:
        row = engine.submit(image).result(timeout=30.0)
    x = normalize_images(jnp.asarray(image[None]), engine.compute_dtype)
    expected = np.asarray(
        trainer.model.apply(
            {"params": state.params}, x, is_training=False
        ).astype(jnp.float32)
    )[0]
    np.testing.assert_allclose(row, expected, rtol=0.05, atol=0.05)


# ------------------------------------------------- manifest + sentinel


def test_sentinel_scores_serve_fixtures_both_directions(capsys):
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import regression_sentinel as sentinel
    finally:
        sys.path.pop(0)
    assert sentinel.main([os.path.join(FIXTURES, "serve_clean")]) == 0
    clean_out = capsys.readouterr().out
    assert "ok      p99_latency_ms" in clean_out
    assert "ok      serve_throughput" in clean_out
    assert sentinel.main(
        ["--json", os.path.join(FIXTURES, "serve_regressed")]
    ) == 1
    report = json.loads(capsys.readouterr().out)
    flagged = {v["metric"] for v in report["verdicts"] if v["regressed"]}
    assert flagged == {"p99_latency_ms", "serve_throughput"}


def test_sentinel_skips_records_without_serving_metrics():
    """The attention_core_frac presence contract, for serving: training
    records are skipped (not zero-filled) for the serve metrics, and a
    training candidate after serving history is not scorable."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        from regression_sentinel import judge_metric
    finally:
        sys.path.pop(0)
    from sav_tpu.obs.manifest import normalize_run_record

    def serve_line(p99, rps, i):
        return normalize_run_record(
            {"outcome": "ok", "p99_latency_ms": p99, "serve_throughput": rps},
            label=f"s{i}", index=i,
        )

    def train_line(i):
        return normalize_run_record(
            {"value": 1800.0, "unit": "img/s/chip"}, label=f"t{i}", index=i,
        )

    history = [train_line(0), serve_line(21.0, 400.0, 1),
               serve_line(22.0, 410.0, 2), serve_line(21.5, 395.0, 3),
               serve_line(21.2, 402.0, 4)]
    verdict = judge_metric(
        history, "p99_latency_ms", k=3.5, rel_floor=0.05, min_history=2
    )
    assert verdict is not None and not verdict.regressed
    # Training-only history: nothing to score, never zero-filled.
    assert judge_metric(
        [train_line(i) for i in range(5)], "p99_latency_ms",
        k=3.5, rel_floor=0.05, min_history=2,
    ) is None
    # Newest record is a training bench: scoring would re-judge a stale
    # serving record as "the candidate" — not scorable.
    assert judge_metric(
        history + [train_line(5)], "p99_latency_ms",
        k=3.5, rel_floor=0.05, min_history=2,
    ) is None


# ---------------------------------------------------- zoo serve check


def test_zoo_serve_check_all_seven_families_on_cpu(capsys):
    """The acceptance criterion: every model family AOT-lowers +
    compiles + runs the serving program (smallest bucket) on CPU."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import zoo_tpu_check
    finally:
        sys.path.pop(0)
    argv = sys.argv
    sys.argv = ["zoo_tpu_check.py", "--serve", "--smoke"]
    try:
        with pytest.raises(SystemExit) as exit_info:
            zoo_tpu_check.main()
    finally:
        sys.argv = argv
    assert exit_info.value.code == 0
    out = capsys.readouterr().out
    assert out.count("OK  serve") == 7
    assert "ALL SERVABLE" in out
    families = ["vit_ti_patch16", "botnet_t3", "tnt_s_patch16", "ceit_t",
                "cait_xxs_24", "cvt-13", "mixer_s_patch16"]
    for family in families:
        assert f"OK  serve {family}" in out


# --------------------------------- serve_bench + warm compile cache proof


def _run_serve_bench(tmp_path, tag, cache_dir):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    manifest = str(tmp_path / f"manifest-{tag}.json")
    proc = subprocess.run(
        [
            sys.executable, os.path.join(ROOT, "tools", "serve_bench.py"),
            "--model", "vit_ti_patch16", "--num-classes", "10",
            "--image-size", "32",
            "--model-overrides", '{"num_layers": 1}',
            "--buckets", "1,2", "--requests", "12", "--deadline-ms", "2000",
            "--backend-wait", "0",
            "--compilation-cache-dir", str(cache_dir),
            "--manifest", manifest,
        ],
        capture_output=True, text=True, timeout=420, cwd=ROOT, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    return line, manifest


def test_serve_bench_line_and_warm_cache_restart(tmp_path):
    """Two REAL serve_bench processes sharing a persistent compile
    cache. The first (cold) start compiles every bucket from scratch;
    the second (warm) start compiles ZERO from scratch — every
    executable is a cache hit, which is what makes an engine restart
    milliseconds of compile instead of minutes. Also pins the
    serve_bench JSON line contract and its finalized manifest."""
    cache_dir = tmp_path / "xla_cache"
    cold, cold_manifest = _run_serve_bench(tmp_path, "cold", cache_dir)
    warm, warm_manifest = _run_serve_bench(tmp_path, "warm", cache_dir)
    # --- the parseable-line acceptance contract -------------------------
    for line in (cold, warm):
        assert line["outcome"] == "ok"
        assert line["requests"] == 12
        for key in ("p50_latency_ms", "p95_latency_ms", "p99_latency_ms",
                    "serve_throughput"):
            assert isinstance(line[key], (int, float)) and line[key] > 0
        assert line["padding_waste_frac"] >= 0.0
        assert line["bucket_occupancy"]  # per-bucket batches + fill
        assert line["queue_depth_avg"] >= 0.0
        assert line["deadline_overruns"] == 0
    # --- warm-restart proof: cache-hit counts asserted ------------------
    assert cold["startup"]["compiled_from_scratch"] == 2
    assert cold["startup"]["cache_hits"] == 0
    assert warm["startup"]["compiled_from_scratch"] == 0
    assert warm["startup"]["cache_hits"] == 2
    # --- r11 telemetry rides the line: SLO + the telemetry block --------
    for line in (cold, warm):
        assert line["slo_hit_frac"] == 1.0  # every request met its budget
        assert line["burn_rate"] == 0.0
        assert line["telemetry"]["exemplars"] == 0
    # --- backed by a finalized manifest the sentinel can score ----------
    with open(warm_manifest) as f:
        manifest = json.load(f)
    assert manifest["kind"] == "serve"
    assert manifest["outcome"] == "ok"
    assert manifest["metrics"]["serve/p99_latency_ms"] == (
        warm["p99_latency_ms"]
    )
    assert manifest["metrics"]["serve/compiled_from_scratch"] == 0.0
    assert manifest["metrics"]["serve/slo_hit_frac"] == 1.0
    assert manifest["notes"]["serve_telemetry"]["slo"]["target"] == 0.99


# -------------------------------------------------- preprocess parity


def test_preprocess_request_validation():
    from sav_tpu.serve.preprocess import preprocess_request

    with pytest.raises(ValueError, match=r"\[H, W, 3\]"):
        preprocess_request(np.zeros((32, 32), np.uint8), 32)
    with pytest.raises(ValueError, match="uint8"):
        preprocess_request(np.zeros((64, 64, 3), np.float32), 32)
    out = preprocess_request(
        np.random.default_rng(0).integers(0, 256, (90, 70, 3), np.uint8), 48
    )
    assert out.shape == (48, 48, 3)
    assert out.dtype == np.uint8


def test_preprocess_request_matches_training_eval_loader():
    """Parity against the training loader's eval path: the SAME decoded
    pixels through pipeline.py's crop_resize (TF) and through the
    numpy request path agree within one uint8 level (TF's bicubic
    quantizes the sample fraction through a 1024-bin table; the
    residual is float-order noise at the truncating cast)."""
    tf = pytest.importorskip("tensorflow")
    from sav_tpu.data.pipeline import _eval_preprocess
    from sav_tpu.serve.preprocess import preprocess_request

    rng = np.random.default_rng(7)
    for (h, w, size) in [(300, 451, 224), (97, 131, 48), (64, 64, 48)]:
        raw = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
        jpeg = tf.io.encode_jpeg(raw, quality=100).numpy()
        decoded = tf.io.decode_jpeg(jpeg, channels=3).numpy()
        tf_out = _eval_preprocess(jpeg, size, "crop_resize").numpy()
        np_out = preprocess_request(decoded, size)
        diff = np.abs(tf_out.astype(int) - np_out.astype(int))
        assert diff.max() <= 1, (h, w, size, diff.max())
        assert diff.mean() < 0.1


def test_uint8_wire_normalize_matches_training_host_path():
    """The wire stays uint8 end to end: device-side normalization of the
    uint8 request equals the training host pipeline's normalize of the
    float image — bit-for-bit in f32."""
    import jax.numpy as jnp

    from sav_tpu.data.constants import MEAN_RGB, STDDEV_RGB
    from sav_tpu.ops.preprocess import normalize_images

    wire = np.random.default_rng(3).integers(
        0, 256, (2, 32, 32, 3), dtype=np.uint8
    )
    device_side = np.asarray(normalize_images(jnp.asarray(wire), jnp.float32))
    host_side = (
        wire.astype(np.float32) - np.asarray(MEAN_RGB, np.float32)
    ) / np.asarray(STDDEV_RGB, np.float32)
    np.testing.assert_array_equal(device_side, host_side)
