"""Config-reachable sequence parallelism (VERDICT r3 item 5).

Covers the model seam :mod:`sav_tpu.parallel.seq_parallel` (pad-and-mask
routing into ring/Ulysses), the ``AttentionBlock(seq_parallel=...)`` wiring,
and the TrainConfig path — numerics pinned against the unsharded dense core
on the 8-device CPU mesh, including CLS-odd sequence lengths.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sav_tpu.models import create_model
from sav_tpu.ops.attention import xla_attention
from sav_tpu.parallel import create_mesh, sequence_parallel_attention
from sav_tpu.train import TrainConfig, Trainer


def _qkv(b=2, l=17, h=4, d=8, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, l, h, d), dtype) for k in ks)


def _dense_talking_heads(q, k, v, w_pre, w_post, scale=None):
    """Dense reference for the ring talking-heads path (the math of
    models.layers.attention.talking_heads_attention, without the modules)."""
    scale = scale or q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k,
                   preferred_element_type=jnp.float32)
    s = jnp.einsum("hi,bhqk->biqk", w_pre.astype(jnp.float32), s)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.einsum("hi,bhqk->biqk", w_post.astype(jnp.float32), p)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


@pytest.mark.parametrize("length", [16, 17])  # divisible and CLS-odd (pad)
def test_ring_talking_heads_matches_dense(devices, length):
    """The head-pair-accumulator ring equals the dense pre/post-mix core,
    including the pad-and-mask path."""
    mesh = create_mesh({"data": 4, "seq": 2})
    q, k, v = _qkv(l=length)
    wk = jax.random.split(jax.random.PRNGKey(7), 2)
    w_pre = jax.random.normal(wk[0], (4, 4), jnp.float32)
    w_post = jax.random.normal(wk[1], (4, 4), jnp.float32)
    want = np.asarray(_dense_talking_heads(q, k, v, w_pre, w_post), np.float32)
    got = np.asarray(
        sequence_parallel_attention(
            q, k, v, mesh=mesh, method="ring", talking_heads=(w_pre, w_post)
        ),
        np.float32,
    )
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_ring_talking_heads_grads_match_dense(devices):
    """Gradients through the ring-TH path — q/k/v AND the mixing matrices
    (the CaiT trunk trains through this seam)."""
    mesh = create_mesh({"data": 4, "seq": 2})
    q, k, v = _qkv(l=17)
    wk = jax.random.split(jax.random.PRNGKey(8), 2)
    w_pre = jax.random.normal(wk[0], (4, 4), jnp.float32)
    w_post = jax.random.normal(wk[1], (4, 4), jnp.float32)

    def dense_loss(q, k, v, wp, wq):
        return jnp.mean(_dense_talking_heads(q, k, v, wp, wq) ** 2)

    def sp_loss(q, k, v, wp, wq):
        return jnp.mean(
            sequence_parallel_attention(
                q, k, v, mesh=mesh, method="ring", talking_heads=(wp, wq)
            ) ** 2
        )

    want = jax.grad(dense_loss, argnums=(0, 1, 2, 3, 4))(q, k, v, w_pre, w_post)
    got = jax.grad(sp_loss, argnums=(0, 1, 2, 3, 4))(q, k, v, w_pre, w_post)
    for g, w in zip(got, want):
        np.testing.assert_allclose(
            np.asarray(g, np.float32), np.asarray(w, np.float32),
            atol=5e-5, rtol=5e-5,
        )


def test_talking_heads_rejects_ulysses(devices):
    mesh = create_mesh({"data": 4, "seq": 2})
    q, k, v = _qkv()
    w = jnp.eye(4)
    with pytest.raises(ValueError, match="ring-only"):
        sequence_parallel_attention(
            q, k, v, mesh=mesh, method="ulysses", talking_heads=(w, w)
        )


@pytest.mark.parametrize("method", ["ring", "ulysses"])
@pytest.mark.parametrize("length", [16, 17])  # divisible and CLS-odd (pad)
@pytest.mark.slow
def test_wrapper_matches_dense(devices, method, length):
    mesh = create_mesh({"data": 4, "seq": 2})
    q, k, v = _qkv(l=length)
    want = np.asarray(xla_attention(q, k, v), np.float32)
    got = np.asarray(
        sequence_parallel_attention(q, k, v, mesh=mesh, method=method),
        np.float32,
    )
    np.testing.assert_allclose(got, want, atol=2e-6, rtol=2e-6)


@pytest.mark.slow
def test_wrapper_grads_match_dense(devices):
    mesh = create_mesh({"data": 4, "seq": 2})
    q, k, v = _qkv(l=17)

    def dense_loss(q, k, v):
        return jnp.mean(xla_attention(q, k, v) ** 2)

    def sp_loss(q, k, v):
        return jnp.mean(
            sequence_parallel_attention(q, k, v, mesh=mesh, method="ring") ** 2
        )

    want = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    got = jax.grad(sp_loss, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(
            np.asarray(g, np.float32), np.asarray(w, np.float32),
            atol=5e-6, rtol=5e-6,
        )


def test_ulysses_rejects_indivisible_heads(devices):
    mesh = create_mesh({"data": 2, "seq": 4})
    q, k, v = _qkv(h=6)  # 6 % 4 != 0
    with pytest.raises(ValueError, match="divisible"):
        sequence_parallel_attention(q, k, v, mesh=mesh, method="ulysses")


@pytest.mark.slow
@pytest.mark.parametrize("name,method,kwargs", [
    # ViT: every block SP-routed; 32² p8 → 17 tokens exercises pad-and-mask
    # (the acceptance test VERDICT r3 item 5 names). Both methods.
    ("vit_ti_patch16", "ring",
     dict(num_layers=2, embed_dim=64, num_heads=4, patch_shape=(8, 8))),
    ("vit_ti_patch16", "ulysses",
     dict(num_layers=2, embed_dim=64, num_heads=4, patch_shape=(8, 8))),
    # TNT shards its outer patch-token stream only.
    ("tnt_s_patch16", "ring",
     dict(num_layers=2, embed_dim=64, inner_ch=12, num_heads=4,
          inner_num_heads=2, patch_shape=(8, 8))),
    # CeiT shards its trunk; the LCA head stays unsharded.
    ("ceit_t", "ring", dict(num_layers=2, embed_dim=64, num_heads=4)),
    # CaiT shards its talking-heads SA trunk (ring-only, head-pair
    # accumulators); the class-attention head stays unsharded.
    ("cait_xxs_24", "ring",
     dict(num_layers=2, num_layers_token_only=1, embed_dim=64, num_heads=4,
          patch_shape=(8, 8))),
])
def test_sp_model_forward_matches_unsharded(devices, name, method, kwargs):
    """A 2-way-SP forward equals the plain forward on the same params for
    every SP-capable family."""
    mesh = create_mesh({"data": 4, "seq": 2})
    dense = create_model(name, num_classes=10, **kwargs)
    sp = create_model(
        name, num_classes=10, seq_parallel=method, seq_mesh=mesh, **kwargs
    )
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 32, 32, 3), jnp.float32)
    variables = dense.init({"params": jax.random.PRNGKey(1)}, x, is_training=False)
    # Zero-init head makes fresh logits vacuously equal — randomize it.
    head = variables["params"]["head"]["kernel"]
    variables["params"]["head"]["kernel"] = jax.random.normal(
        jax.random.PRNGKey(2), head.shape, head.dtype
    )
    want = np.asarray(dense.apply(variables, x, is_training=False), np.float32)
    got = np.asarray(
        jax.jit(lambda v, x: sp.apply(v, x, is_training=False))(variables, x),
        np.float32,
    )
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_sp_model_requires_mesh(devices):
    with pytest.raises(ValueError, match="seq_mesh"):
        m = create_model(
            "vit_ti_patch16", num_classes=10, num_layers=1, embed_dim=32,
            num_heads=2, patch_shape=(8, 8), seq_parallel="ring",
        )
        x = jnp.zeros((1, 16, 16, 3))
        m.init({"params": jax.random.PRNGKey(0)}, x, is_training=False)


def test_sp_rejects_attention_free_models(devices):
    with pytest.raises(ValueError, match="sequence parallelism"):
        create_model(
            "mixer_s_patch32", num_classes=10, seq_parallel="ring",
            seq_mesh=create_mesh({"data": 4, "seq": 2}),
        )


@pytest.mark.slow
def test_trainer_sp_train_step(devices):
    """TrainConfig.sequence_parallel drives a full train step on a
    (data × seq) mesh — the framework-level capability, not the bare op."""
    config = TrainConfig(
        model_name="vit_ti_patch16",
        num_classes=10,
        image_size=32,
        compute_dtype="float32",
        global_batch_size=8,
        num_train_images=32,
        num_epochs=2,
        warmup_epochs=1,
        base_lr=1e-3,
        transpose_images=False,
        mesh_axes={"data": 4, "seq": 2},
        sequence_parallel="ring",
        model_overrides=dict(num_layers=2, embed_dim=64, num_heads=4),
        seed=0,
    )
    trainer = Trainer(config)
    assert trainer.model.seq_parallel == "ring"
    batch = {
        "images": np.random.default_rng(0)
        .normal(size=(8, 32, 32, 3))
        .astype(np.float32),
        "labels": (np.arange(8) % 10).astype(np.int32),
    }
    state = trainer.init_state(0)
    state, metrics = trainer.train_step(state, batch, jax.random.PRNGKey(0))
    assert np.isfinite(float(jax.device_get(metrics["loss"])))
    em = trainer.eval_step(state, batch)
    assert np.isfinite(float(jax.device_get(em["loss_sum"])))


@pytest.mark.slow
def test_trainer_sp_composes_with_grad_accum(devices):
    """SP attention inside the microbatched grad-accum step: the shard_map
    runs under lax.scan's body — a distinct trace path from the plain
    step."""
    config = TrainConfig(
        model_name="vit_ti_patch16",
        num_classes=10,
        image_size=32,
        compute_dtype="float32",
        global_batch_size=8,
        num_train_images=32,
        num_epochs=2,
        warmup_epochs=1,
        base_lr=1e-3,
        grad_accum_steps=2,
        transpose_images=False,
        mesh_axes={"data": 4, "seq": 2},
        sequence_parallel="ring",
        model_overrides=dict(num_layers=2, embed_dim=64, num_heads=4),
        seed=0,
    )
    trainer = Trainer(config)
    batch = {
        "images": np.random.default_rng(0)
        .normal(size=(8, 32, 32, 3))
        .astype(np.float32),
        "labels": (np.arange(8) % 10).astype(np.int32),
    }
    state = trainer.init_state(0)
    state, metrics = trainer.train_step(state, batch, jax.random.PRNGKey(0))
    assert np.isfinite(float(jax.device_get(metrics["loss"])))


def test_trainer_sp_requires_seq_axis(devices):
    config = TrainConfig(
        model_name="vit_ti_patch16",
        num_classes=10,
        image_size=32,
        global_batch_size=8,
        num_train_images=32,
        sequence_parallel="ring",
        transpose_images=False,
    )
    with pytest.raises(ValueError, match="'seq' mesh axis"):
        Trainer(config)


# ------------------------------------------------- replication observability


def test_replication_fallback_notifies_listeners(devices):
    """ISSUE 4 satellite: the batch-replication fallback routes through
    the observability hook — a registered listener (what Trainer.fit
    installs) receives the machine-readable event at trace time."""
    from sav_tpu.parallel import seq_parallel as sp

    mesh = create_mesh({"data": 4, "seq": 2})
    events = []
    unsubscribe = sp.on_batch_replication(events.append)
    try:
        q, k, v = _qkv(b=2, l=16)  # batch 2 does not divide data product 4
        sequence_parallel_attention(q, k, v, mesh=mesh, method="ring")
    finally:
        unsubscribe()
    assert events and events[0] == {"batch": 2, "data_axis_product": 4}
    # After unsubscribe the hook no longer reaches the listener.
    before = len(events)
    q, k, v = _qkv(b=2, l=16, seed=1)
    sequence_parallel_attention(q, k, v, mesh=mesh, method="ring")
    assert len(events) == before


def test_replication_warning_fires_once_per_shape_without_listeners():
    """Without listeners the module warns once per (batch, group) shape
    per process — not per call (the old per-trace UserWarning spam)."""
    import warnings

    from sav_tpu.parallel import seq_parallel as sp

    key = (313, 757)  # synthetic shape no other test uses
    sp._replication_warned.discard(key)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        sp._replication_fallback(*key)
        sp._replication_fallback(*key)
    assert len(caught) == 1
    assert "replicating the batch" in str(caught[0].message)


def test_replication_listener_exceptions_are_swallowed():
    import warnings

    from sav_tpu.parallel import seq_parallel as sp

    def bad_listener(info):
        raise RuntimeError("observer crash")

    unsubscribe = sp.on_batch_replication(bad_listener)
    try:
        with warnings.catch_warnings():
            # A crashed listener counts as unhandled, so the module falls
            # back to its own (expected) warning — not the test's concern.
            warnings.simplefilter("ignore")
            sp._replication_fallback(311, 751)  # must not raise
    finally:
        unsubscribe()


def test_fit_records_replication_fallback_once(devices, tmp_path):
    """Trainer integration: a degraded-parallelism fit warns ONCE, marks
    the span trace, sets the ledger gauge, and notes the manifest. The
    trigger is the realistic one — grad accumulation shrinks the
    micro-batch (4/2 = 2) below the 4-way data-axis product while the
    global batch still places cleanly."""
    import json as _json
    import warnings

    from sav_tpu.obs.manifest import RunManifest

    config = TrainConfig(
        model_name="vit_ti_patch16",
        num_classes=10,
        image_size=32,
        compute_dtype="float32",
        global_batch_size=4,
        grad_accum_steps=2,  # micro-batch 2 does not divide data axis 4
        num_train_images=8,
        num_epochs=1,
        warmup_epochs=1,
        lr_scaling_divisor=4,
        transpose_images=False,
        log_every_steps=2,
        log_dir=str(tmp_path),
        trace_spans=True,
        mesh_axes={"data": 4, "seq": 2},
        sequence_parallel="ring",
        model_overrides=dict(num_layers=1, embed_dim=64, num_heads=4),
        seed=0,
    )
    trainer = Trainer(config)
    manifest = RunManifest(str(tmp_path / "manifest.json"), kind="train")
    manifest.begin()
    rng = np.random.default_rng(0)

    def batches(n):
        for _ in range(n):
            yield {
                "images": rng.normal(size=(4, 32, 32, 3)).astype(np.float32),
                "labels": (np.arange(4) % 10).astype(np.int32),
            }

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        trainer.fit(batches(2), num_steps=2, manifest=manifest)
    fit_warnings = [
        w for w in caught
        if "batch-replication fallback" in str(w.message)
    ]
    assert len(fit_warnings) == 1  # once per fit, not per call/trace
    doc = RunManifest.load(manifest.path)
    assert doc["notes"]["seq_replication_fallback"] == {
        "batch": 2, "data_axis_product": 4,  # the micro-batch, not global
    }
    assert trainer.last_goodput["gauges"]["seq/replicated_batch"] == 2.0
    with open(tmp_path / "spans.trace.json") as f:
        names = {e["name"] for e in _json.load(f)["traceEvents"]}
    assert "seq_replication_fallback" in names
