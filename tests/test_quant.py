"""Int8 quantized matmuls (ISSUE 17, docs/quantization.md).

Op tier: per-channel symmetric quantization round-trips within half a
step, all-zero channels never divide by zero, stochastic rounding is
unbiased, and the STE dot's forward/backward track the float dot.

Module tier: QuantDenseGeneral's QAT arm initializes byte-identically
to the flax layer it replaces (quant checkpoints stay byte-compatible
with the bf16 arm), and the QAT forward is BIT-identical to the serving
forward after ``quantize_params`` — what trains is what serves.

Training tier: a CPU fit with ``quant="int8"`` moves the loss through
the STE + stochastic-rounding step; the pipeline arm refuses to compose.

Serving tier: the quant engine's logits track a float engine on the
same trained weights (top-1 agreement), the startup report carries the
HBM-density proof, the full-depth ratio clears the ≤0.6 gate (pure
eval_shape math — kernels dominate at depth), the manifest/bench-line
metrics land under the isolated ``quant_*`` sentinel names, and the
heartbeat dtype stamp survives to ``fleet/proc_0.jsonl``.
"""

import importlib.util
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn

from sav_tpu.ops.quant import (
    QuantDenseGeneral,
    int8_serve_dot,
    int8_ste_dot,
    quantize_channelwise,
    quantize_params,
    quantize_stochastic,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", f"{name}.py")
    )
    module = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = module
    spec.loader.exec_module(module)
    return module


# --------------------------------------------------------------- op tier


def test_quantize_channelwise_round_trip_and_zero_channels():
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((6, 8)), jnp.float32)
    a = a.at[:, 3].set(0.0)  # one all-zero output channel
    q, scale = quantize_channelwise(a, contract_axes=(0,))
    assert q.dtype == jnp.int8
    assert scale.shape == (1, 8)
    # Symmetric restricted range: -128 never appears.
    assert int(jnp.min(q)) >= -127 and int(jnp.max(q)) <= 127
    # Round-to-nearest: every element within half a quantization step.
    err = jnp.abs(q.astype(jnp.float32) * scale - a)
    assert float(jnp.max(err / scale)) <= 0.5 + 1e-6
    # The zero channel: scale 1.0 (not 0/0), q exactly 0.
    assert float(scale[0, 3]) == 1.0
    assert int(jnp.abs(q[:, 3]).sum()) == 0
    # Per-channel, not per-tensor: a huge outlier in channel 0 must not
    # crush channel 1's resolution.
    b = jnp.asarray([[1000.0, 0.5], [500.0, -0.25]], jnp.float32)
    _, sb = quantize_channelwise(b, contract_axes=(0,))
    assert float(sb[0, 1]) == pytest.approx(0.5 / 127.0)


def test_quantize_stochastic_is_unbiased():
    # amax 1.0 -> scale 1/127; 0.35/scale = 44.45 sits BETWEEN int8
    # steps: round-to-nearest always picks 44, stochastic rounding must
    # average to the true value (floor(44.45 + u) is 45 w.p. 0.45).
    a = jnp.asarray([[1.0], [0.35]], jnp.float32)
    keys = jax.random.split(jax.random.PRNGKey(17), 2048)

    def deq(key):
        q, s = quantize_stochastic(a, (0,), key)
        return (q.astype(jnp.float32) * s)[1, 0]

    vals = jax.vmap(deq)(keys)
    # E[q*s] = a (AQT unbiasedness); the empirical mean over 2048 draws
    # sits within a few standard errors of the true value.
    assert float(vals.mean()) == pytest.approx(0.35, rel=0.02)
    # And it genuinely rounds both ways (not a constant).
    assert float(vals.std()) > 0.0


def test_int8_ste_dot_tracks_float_forward_and_backward():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((4, 16)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
    key = jax.random.key_data(jax.random.PRNGKey(3))

    out = int8_ste_dot(x, w, key, 1)
    ref = x @ w
    # int8 resolution on unit-normal data: ~1% relative error envelope.
    assert float(jnp.max(jnp.abs(out - ref))) < 0.05 * float(
        jnp.max(jnp.abs(ref))
    )

    def loss(x, w):
        return jnp.sum(jnp.sin(int8_ste_dot(x, w, key, 1)))

    def loss_ref(x, w):
        return jnp.sum(jnp.sin(x @ w))

    gx, gw = jax.grad(loss, argnums=(0, 1))(x, w)
    rx, rw = jax.grad(loss_ref, argnums=(0, 1))(x, w)
    assert gx.shape == x.shape and gw.shape == w.shape
    # STE gradients are quantized estimates of the float gradients —
    # same direction, few-percent magnitude error.
    for g, r in ((gx, rx), (gw, rw)):
        cos = jnp.sum(g * r) / (
            jnp.linalg.norm(g) * jnp.linalg.norm(r) + 1e-12
        )
        assert float(cos) > 0.99


def test_int8_ste_dot_multi_axis_contraction():
    # The DenseGeneral shape: x [B, L, D] against w [D, H, Dh].
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((2, 5, 12)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((12, 3, 4)), jnp.float32)
    key = jax.random.key_data(jax.random.PRNGKey(4))
    out = int8_ste_dot(x, w, key, 1)
    ref = jnp.einsum("bld,dhk->blhk", x, w)
    assert out.shape == (2, 5, 3, 4)
    assert float(jnp.max(jnp.abs(out - ref))) < 0.05 * float(
        jnp.max(jnp.abs(ref))
    )
    # And two contracted axes (the folded [H, Dh] -> D output proj).
    x2 = jnp.asarray(rng.standard_normal((2, 5, 3, 4)), jnp.float32)
    w2 = jnp.asarray(rng.standard_normal((3, 4, 12)), jnp.float32)
    out2 = int8_ste_dot(x2, w2, key, 2)
    ref2 = jnp.einsum("blhk,hkd->bld", x2, w2)
    assert float(jnp.max(jnp.abs(out2 - ref2))) < 0.05 * float(
        jnp.max(jnp.abs(ref2))
    )


# ----------------------------------------------------------- module tier


def test_quant_dense_init_is_byte_identical_to_flax():
    """The QAT arm declares the SAME float params as the layer it
    replaces: identical tree paths, shapes, and init bytes — a quant
    checkpoint restores into the bf16 arm and vice versa."""
    x = jnp.zeros((2, 7, 16), jnp.float32)
    rng = jax.random.PRNGKey(0)
    ref = nn.DenseGeneral(features=(4, 8), axis=-1).init(rng, x)["params"]
    got = QuantDenseGeneral(features=(4, 8), mode="int8").init(
        {"params": rng}, x
    )["params"]
    assert jax.tree.structure(ref) == jax.tree.structure(got)
    for r, g in zip(jax.tree.leaves(ref), jax.tree.leaves(got)):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g))
    # Scalar-features twin of nn.Dense too.
    ref_d = nn.Dense(features=8).init(rng, x)["params"]
    got_d = QuantDenseGeneral(features=8, mode="int8").init(
        {"params": rng}, x
    )["params"]
    np.testing.assert_array_equal(
        np.asarray(ref_d["kernel"]), np.asarray(got_d["kernel"])
    )


def test_quant_dense_rejects_non_trailing_axis():
    x = jnp.zeros((2, 7, 16), jnp.float32)
    with pytest.raises(ValueError, match="trailing axes only"):
        QuantDenseGeneral(features=4, axis=1).init(
            {"params": jax.random.PRNGKey(0)}, x
        )


def test_qat_forward_is_bit_identical_to_serve_forward():
    """The parity gate: mode="int8" (training forward, round-to-nearest
    weights quantized on the fly) and mode="int8_serve" (pre-quantized
    kernels via quantize_params) must produce BIT-identical outputs —
    what the QAT arm trained is exactly what the serving arm runs."""
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((3, 16)), jnp.float32)
    key = jax.random.PRNGKey(7)
    qat = QuantDenseGeneral(features=(2, 4), mode="int8")
    float_params = qat.init({"params": key}, x)["params"]
    serve = QuantDenseGeneral(features=(2, 4), mode="int8_serve")
    template = jax.eval_shape(
        lambda: serve.init({"params": key}, x)
    )["params"]
    served_params = quantize_params(float_params, template)
    assert served_params["kernel"].dtype == jnp.int8
    assert served_params["scale"].shape == template["scale"].shape
    out_qat = qat.apply({"params": float_params}, x)
    out_serve = serve.apply({"params": served_params}, x)
    np.testing.assert_array_equal(np.asarray(out_qat), np.asarray(out_serve))


def test_quantize_params_casts_non_kernel_leaves_to_template_dtype():
    params = {
        "proj": {
            "kernel": jnp.ones((4, 2), jnp.float32) * 0.5,
            "bias": jnp.ones((2,), jnp.float32),
        },
        "norm": {"scale": jnp.ones((4,), jnp.float32)},
    }
    template = {
        "proj": {
            "kernel": jax.ShapeDtypeStruct((4, 2), jnp.int8),
            "scale": jax.ShapeDtypeStruct((2,), jnp.float32),
            "bias": jax.ShapeDtypeStruct((2,), jnp.bfloat16),
        },
        "norm": {"scale": jax.ShapeDtypeStruct((4,), jnp.bfloat16)},
    }
    out = quantize_params(params, template)
    assert out["proj"]["kernel"].dtype == jnp.int8
    assert int(out["proj"]["kernel"][0, 0]) == 127  # 0.5/(0.5/127)
    assert out["proj"]["scale"].shape == (2,)
    assert out["proj"]["bias"].dtype == jnp.bfloat16
    # norm/scale is NOT a quantized pair (no int8 kernel sibling): cast
    # only, never quantized.
    assert out["norm"]["scale"].dtype == jnp.bfloat16


def test_int8_serve_dot_matches_manual_dequant():
    rng = np.random.default_rng(6)
    x = jnp.asarray(rng.standard_normal((3, 8)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((8, 5)), jnp.float32)
    qw, sw = quantize_channelwise(w, (0,))
    out = int8_serve_dot(x, qw, sw.reshape(5), 1)
    qx, sx = quantize_channelwise(x, (1,))
    ref = (
        (qx.astype(jnp.int32) @ qw.astype(jnp.int32)).astype(jnp.float32)
        * sx
        * sw
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


# --------------------------------------------------------- training tier


def test_trainer_quant_fit_moves_the_loss(tmp_path, devices):
    """The QAT arm end-to-end on CPU: --quant int8 threads the "quant"
    rng stream through the STE step and the loss moves under synthetic
    data — the whole fwd/bwd graph runs through int8 contractions."""
    from sav_tpu.data import synthetic_data_iterator
    from sav_tpu.train import TrainConfig, Trainer

    config = TrainConfig(
        model_name="vit_ti_patch16", num_classes=10, image_size=32,
        compute_dtype="float32", global_batch_size=8, num_train_images=64,
        num_epochs=1, warmup_epochs=1, lr_scaling_divisor=8,
        transpose_images=False, log_every_steps=2, log_dir=str(tmp_path),
        model_overrides=dict(num_layers=2, embed_dim=64, num_heads=4),
        quant="int8", seed=0,
    )
    trainer = Trainer(config)
    assert getattr(trainer.model, "quant", None) == "int8"
    data = synthetic_data_iterator(
        batch_size=8, image_size=32, num_classes=10
    )
    _, history = trainer.fit(data, num_steps=8)
    losses = [float(m["loss"]) for m in history if "loss" in m]
    assert losses and all(np.isfinite(losses))
    # Synthetic labels are learnable: 8 STE steps must make progress.
    assert losses[-1] < losses[0]


def test_quant_refuses_pipeline_parallel():
    from sav_tpu.train import TrainConfig, Trainer

    config = TrainConfig(
        model_name="vit_ti_patch16", num_classes=10, image_size=32,
        global_batch_size=8, num_train_images=64, num_epochs=1,
        model_overrides=dict(num_layers=2, embed_dim=64, num_heads=4),
        pipeline_parallel=2, quant="int8", seed=0,
    )
    with pytest.raises(ValueError, match="does not compose"):
        Trainer(config)


def test_trainer_rejects_mismatched_external_model_quant():
    from sav_tpu.models import create_model
    from sav_tpu.train import TrainConfig, Trainer

    config = TrainConfig(
        model_name="vit_ti_patch16", num_classes=10, image_size=32,
        global_batch_size=8, num_train_images=64, num_epochs=1,
        quant="int8", seed=0,
    )
    model = create_model(
        "vit_ti_patch16", num_classes=10, dtype=jnp.float32,
        num_layers=2, embed_dim=64, num_heads=4,
    )
    with pytest.raises(ValueError, match="externally"):
        Trainer(config, model=model)


# ---------------------------------------------------------- serving tier


def _serve_config(**overrides):
    from sav_tpu.serve.engine import ServeConfig

    base = dict(
        model_name="vit_ti_patch16",
        num_classes=10,
        image_size=32,
        model_overrides={"num_layers": 1},
        buckets=[1, 2],
        max_queue=128,
        deadline_ms=2000.0,
    )
    base.update(overrides)
    return ServeConfig(**base)


def _noisy_params(config):
    """A float param tree with nonzero weights everywhere — fresh inits
    zero most projections, which would make the parity check vacuous."""
    from sav_tpu.models import create_model

    model = create_model(
        config.model_name, num_classes=config.num_classes,
        dtype=jnp.float32, **(config.model_overrides or {}),
    )
    s = config.image_size
    params = model.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((1, s, s, 3), jnp.float32), is_training=False,
    )["params"]
    leaves, treedef = jax.tree.flatten(params)
    keys = jax.random.split(jax.random.PRNGKey(42), len(leaves))
    noisy = [
        p + jax.random.normal(k, p.shape, p.dtype) * 0.02
        for p, k in zip(leaves, keys)
    ]
    return jax.tree.unflatten(treedef, noisy)


def test_quant_engine_parity_report_and_heartbeat_stamp(tmp_path, devices):
    """One engine pair on the same trained weights: the int8 arm must
    (a) agree with the float arm on top-1 within an int8-resolution
    logit envelope, (b) carry the HBM-density proof + int8 dtype in the
    startup report, (c) stamp serve/quant_weights into stop() metrics,
    and (d) leave an int8 dtype-stamped heartbeat in fleet/proc_0.jsonl
    (what serve_status/fleet_status render)."""
    from sav_tpu.serve.engine import ServeEngine

    params = _noisy_params(_serve_config())
    rng = np.random.default_rng(9)
    images = [
        rng.integers(0, 256, (32, 32, 3), dtype=np.uint8) for _ in range(4)
    ]

    float_engine = ServeEngine(_serve_config(), params=params)
    with float_engine:
        float_rows = [
            float_engine.submit(img).result(timeout=60.0) for img in images
        ]
    float_engine.stop()

    quant_engine = ServeEngine(
        _serve_config(quant_weights=True, log_dir=str(tmp_path)),
        params=params,
    )
    report = quant_engine.startup_report
    with quant_engine:
        quant_rows = [
            quant_engine.submit(img).result(timeout=60.0) for img in images
        ]

    # (a) numerics: same top-1, logits within the int8 envelope.
    for f, q in zip(float_rows, quant_rows):
        f, q = np.asarray(f), np.asarray(q)
        assert int(f.argmax()) == int(q.argmax())
        scale = max(float(np.abs(f).max()), 1e-6)
        assert float(np.abs(f - q).max()) <= 0.1 * scale

    # (b) the startup report: dtype stamp + the HBM-density proof.
    assert report["dtype"] == "int8"
    quant = report["quant"]
    assert quant["weights_dtype"] == "int8"
    assert quant["param_bytes_serving"] < quant["param_bytes_bf16_equiv"]
    assert 0.0 < quant["param_bytes_ratio"] < 1.0
    assert set(report["bucket_hbm_bytes"]) == {"1", "2"}

    # (c) the finalized manifest: the flat serve/quant_weights marker
    # (what _manifest_metrics keys the quant_* remap on) plus the
    # notes.quant arm stamp.
    from sav_tpu.obs.manifest import RunManifest

    manifests = [
        os.path.join(str(tmp_path), f)
        for f in os.listdir(str(tmp_path))
        if f.startswith("manifest-serve-")
    ]
    assert len(manifests) == 1
    doc = RunManifest.load(manifests[0])
    assert doc["outcome"] == "ok"
    assert doc["metrics"]["serve/quant_weights"] == 1.0
    assert doc["notes"]["quant"]["weights"] == "int8"

    # (d) the fleet heartbeat dtype stamp (telemetry close() emits a
    # final beat, so even a short-lived engine leaves one).
    beats_path = os.path.join(str(tmp_path), "fleet", "proc_0.jsonl")
    with open(beats_path) as f:
        beats = [json.loads(line) for line in f if line.strip()]
    assert any(b.get("dtype") == "int8" for b in beats)


def test_quant_engine_refuses_external_model():
    from sav_tpu.models import create_model
    from sav_tpu.serve.engine import ServeEngine

    model = create_model(
        "vit_ti_patch16", num_classes=10, dtype=jnp.float32, num_layers=1,
    )
    with pytest.raises(ValueError, match="quant_weights"):
        ServeEngine(_serve_config(quant_weights=True), model=model)


def test_full_depth_hbm_ratio_clears_the_gate():
    """The ≤0.6 acceptance gate, as pure eval_shape math (no training,
    no compile): at real depth the int8 kernels dominate the param
    bytes and the serving tree weighs ≤0.6× its bf16 equivalent. The
    shallow smoke models do NOT clear this (conv-embed tables dominate
    at depth 1-2) — depth is what the gate speaks to, which is why
    tools/battery/r17.steps proves it on the full-size model."""
    from sav_tpu.models import create_model

    kwargs = dict(
        num_classes=1000, dtype=jnp.float32, num_layers=6,
    )
    x = jnp.zeros((1, 64, 64, 3), jnp.float32)
    rng = {"params": jax.random.PRNGKey(0)}

    float_tree = jax.eval_shape(
        lambda: create_model("vit_ti_patch16", **kwargs).init(
            rng, x, is_training=False
        )
    )["params"]
    serve_tree = jax.eval_shape(
        lambda: create_model(
            "vit_ti_patch16", quant="int8_serve", **kwargs
        ).init(rng, x, is_training=False)
    )["params"]

    bf16_equiv = sum(int(l.size) * 2 for l in jax.tree.leaves(float_tree))
    serving = sum(
        int(l.size) * jnp.dtype(l.dtype).itemsize
        for l in jax.tree.leaves(serve_tree)
    )
    ratio = serving / bf16_equiv
    assert ratio <= 0.6, f"HBM density gate failed: {ratio:.4f}"
    # The trees differ ONLY by the kernel/scale pairs: same top-level
    # structure, so SpecLayout rules keyed on names still apply.
    assert set(float_tree) == set(serve_tree)


# ----------------------------------------- sentinel + harness isolation


def test_manifest_metrics_isolate_quant_records():
    from sav_tpu.obs.manifest import _bench_line_metrics, _manifest_metrics

    line = {
        "p99_latency_ms": 26.0, "serve_throughput": 330.0,
        "slo_hit_frac": 0.99,
    }
    plain = _bench_line_metrics(dict(line))
    assert plain["p99_latency_ms"] == 26.0
    assert "quant_p99_latency_ms" not in plain
    quant = _bench_line_metrics(dict(line, quant="int8"))
    assert quant["quant_p99_latency_ms"] == 26.0
    assert quant["quant_serve_throughput"] == 330.0
    assert quant["quant_slo_hit_frac"] == 0.99
    assert "p99_latency_ms" not in quant

    metrics = {
        "serve/p99_latency_ms": 26.0, "serve/throughput_rps": 330.0,
        "serve/slo_hit_frac": 0.99,
    }
    assert _manifest_metrics(dict(metrics))["p99_latency_ms"] == 26.0
    remapped = _manifest_metrics(dict(metrics, **{"serve/quant_weights": 1.0}))
    assert remapped["quant_p99_latency_ms"] == 26.0
    assert remapped["quant_serve_throughput"] == 330.0
    assert "serve_throughput" not in remapped


def test_serve_bench_quant_does_not_compose_with_replicas(capsys):
    serve_bench = _load_tool("serve_bench")
    with pytest.raises(SystemExit) as exit_info:
        serve_bench.main(["--quant-weights", "--replicas", "2"])
    assert exit_info.value.code == 2
    assert "single-engine A/B arm" in capsys.readouterr().err


def test_zoo_quant_serve_check_all_seven_families_on_cpu(capsys):
    """Every family's int8 serving program builds and runs finite on
    CPU under the smoke shrink (the full-size on-chip sweep is
    tools/battery/r17.steps zoo_int8)."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import zoo_tpu_check
    finally:
        sys.path.pop(0)
    argv = sys.argv
    sys.argv = ["zoo_tpu_check.py", "--serve", "--smoke", "--quant-weights"]
    try:
        with pytest.raises(SystemExit) as exit_info:
            zoo_tpu_check.main()
    finally:
        sys.argv = argv
    out = capsys.readouterr().out
    assert exit_info.value.code == 0
    assert out.count("OK  serve:int8") == 7
    assert "ALL SERVABLE" in out
