"""Pallas flash attention vs XLA reference numerics (BASELINE.json north star:
'every models/*_test.py cross-checks Pallas vs. XLA numerics')."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sav_tpu.ops import flash_attention, xla_attention, relative_logits_2d
from sav_tpu.ops.attention import dot_product_attention, xla_attention_fast
from sav_tpu.ops.relative import rel_to_abs




def _qkv(b=2, lq=197, lk=None, h=4, d=64, dtype=jnp.float32, seed=0):
    lk = lk or lq
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, lq, h, d), dtype)
    k = jax.random.normal(ks[1], (b, lk, h, d), dtype)
    v = jax.random.normal(ks[2], (b, lk, h, d), dtype)
    return q, k, v


@pytest.mark.parametrize(
    "lq,lk,d",
    [
        (197, 197, 64),  # ViT-B/16 @ 224
        (128, 128, 128),  # aligned
        (50, 50, 32),  # ViT @ 32x32-ish, tiny head dim
        (1, 197, 64),  # class attention: single query row
        (196, 49, 64),  # CvT: downsampled K/V
        (785, 785, 40),  # TNT-B outer-ish, odd head dim
    ],
)
@pytest.mark.slow
def test_flash_matches_xla(lq, lk, d):
    q, k, v = _qkv(lq=lq, lk=lk, d=d)
    ref = xla_attention(q, k, v)
    out = flash_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_flash_with_bias_matches_xla():
    q, k, v = _qkv(lq=64, lk=64, d=32)
    bias = jax.random.normal(jax.random.PRNGKey(9), (2, 4, 64, 64))
    ref = xla_attention(q, k, v, bias)
    out = flash_attention(q, k, v, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_flash_with_shared_bias():
    q, k, v = _qkv(lq=33, lk=33, d=16)
    bias = jax.random.normal(jax.random.PRNGKey(9), (1, 1, 33, 33))
    ref = xla_attention(q, k, v, bias)
    out = flash_attention(q, k, v, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_flash_gradients_match_xla():
    q, k, v = _qkv(lq=50, lk=50, d=32)
    bias = jax.random.normal(jax.random.PRNGKey(9), (1, 4, 50, 50))

    def loss_f(fn):
        return lambda q, k, v, b: jnp.sum(jnp.square(fn(q, k, v, b)))

    gf = jax.grad(loss_f(flash_attention), argnums=(0, 1, 2, 3))(q, k, v, bias)
    gx = jax.grad(loss_f(xla_attention), argnums=(0, 1, 2, 3))(q, k, v, bias)
    for a, b in zip(gf, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-4)


@pytest.mark.parametrize(
    "lq,lk,d,blk",
    [
        (197, 197, 64, None),  # DeiT-S/16 @ 224 — the flagship backward shape
        (128, 128, 128, None),  # aligned
        (50, 50, 32, None),  # unaligned: padded q rows + kv cols in both kernels
        (1, 197, 64, None),  # class attention: single query row
        (196, 49, 64, None),  # CvT: downsampled K/V
        # Explicit 128 blocks: with the default 256 these lengths would be
        # single-block, silently skipping the cross-block accumulation
        # protocol (ki==0 init / last-ki write) this case exists to cover.
        (320, 256, 40, 128),  # multi-block q and kv, odd head dim
    ],
)
@pytest.mark.slow
def test_flash_blocked_backward_matches_xla(lq, lk, d, blk):
    """No-bias gradients run the blocked Pallas backward kernels."""
    q, k, v = _qkv(lq=lq, lk=lk, d=d)
    kw = {} if blk is None else {"block_q": blk, "block_kv": blk}

    def loss_f(fn):
        return lambda q, k, v: jnp.sum(jnp.square(fn(q, k, v, **kw)))

    gf = jax.grad(loss_f(flash_attention), argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss_f(lambda q, k, v, **_: xla_attention(q, k, v)),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gx):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=5e-4
        )


@pytest.mark.slow
def test_flash_blocked_backward_bf16_finite_and_close():
    q, k, v = _qkv(lq=197, lk=197, d=64, dtype=jnp.bfloat16)

    def loss(fn, q, k, v):
        return jnp.sum(jnp.square(fn(q, k, v).astype(jnp.float32)))

    gf = jax.grad(lambda *a: loss(flash_attention, *a), argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(lambda *a: loss(xla_attention, *a), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gx):
        a, b = np.asarray(a, np.float32), np.asarray(b, np.float32)
        assert np.isfinite(a).all()
        # bf16 tolerance: both paths quantize differently.
        np.testing.assert_allclose(a, b, atol=0.15, rtol=0.15)


@pytest.mark.slow
def test_flash_bf16():
    q, k, v = _qkv(lq=197, lk=197, d=64, dtype=jnp.bfloat16)
    ref = xla_attention(q, k, v)
    out = flash_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2, rtol=3e-2
    )


@pytest.mark.slow
def test_flash_softmax_stability():
    """Large logit magnitudes must not overflow the online softmax."""
    q, k, v = _qkv(lq=64, lk=64, d=32)
    out = flash_attention(100.0 * q, 100.0 * k, v)
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.slow
def test_dispatch_backends_agree():
    q, k, v = _qkv(lq=60, lk=60, d=16)
    out_x = dot_product_attention(q, k, v, backend="xla")
    out_p = dot_product_attention(q, k, v, backend="pallas")
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_x), atol=2e-5, rtol=2e-5)


def test_dispatch_rejects_bad_backend():
    q, k, v = _qkv(lq=8, lk=8, d=8)
    with pytest.raises(ValueError, match="unknown attention backend"):
        dot_product_attention(q, k, v, backend="cuda")


def test_rel_to_abs_indexing():
    length = 9
    x = jax.random.normal(jax.random.PRNGKey(0), (2, length, 2 * length - 1))
    y = np.asarray(rel_to_abs(x))
    xn = np.asarray(x)
    for i in range(length):
        for j in range(length):
            np.testing.assert_allclose(y[0, i, j], xn[0, i, j - i + length - 1], rtol=1e-6)


def test_relative_logits_2d_offsets():
    """Entry [x,y,X,Y] must equal q[x,y]·rel_h[X-x+H-1] + q[x,y]·rel_w[Y-y+W-1]."""
    h_, w_, d = 3, 4, 8
    q = jax.random.normal(jax.random.PRNGKey(0), (1, 1, h_, w_, d))
    rel_h = jax.random.normal(jax.random.PRNGKey(1), (2 * h_ - 1, d))
    rel_w = jax.random.normal(jax.random.PRNGKey(2), (2 * w_ - 1, d))
    out = np.asarray(relative_logits_2d(q, rel_h, rel_w))
    qn, rh, rw = map(np.asarray, (q, rel_h, rel_w))
    for x in range(h_):
        for y in range(w_):
            for xx in range(h_):
                for yy in range(w_):
                    expected = qn[0, 0, x, y] @ rh[xx - x + h_ - 1] + qn[0, 0, x, y] @ rw[
                        yy - y + w_ - 1
                    ]
                    np.testing.assert_allclose(
                        out[0, 0, x, y, xx, yy], expected, rtol=1e-4
                    )


# ------------------------------------------------- talking-heads (CaiT)


@pytest.mark.parametrize("lq,lk,h,d", [(196, 196, 4, 48), (50, 50, 2, 32)])
@pytest.mark.slow
def test_talking_heads_fused_matches_xla(lq, lk, h, d):
    from sav_tpu.ops.talking_heads import (
        _th_dense_reference,
        flash_talking_heads_attention,
    )

    q, k, v = _qkv(lq=lq, lk=lk, h=h, d=d)
    ks = jax.random.split(jax.random.PRNGKey(5), 2)
    w_pre = jax.nn.initializers.orthogonal()(ks[0], (h, h))
    w_post = jax.nn.initializers.orthogonal()(ks[1], (h, h))
    ref = _th_dense_reference(q, k, v, w_pre, w_post, d ** -0.5)
    out = flash_talking_heads_attention(q, k, v, w_pre, w_post)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=5e-5, rtol=5e-5)


@pytest.mark.slow
def test_talking_heads_fused_gradients_match_dense():
    from sav_tpu.ops.talking_heads import (
        _th_dense_reference,
        flash_talking_heads_attention,
    )

    q, k, v = _qkv(lq=40, lk=40, h=2, d=16)
    ks = jax.random.split(jax.random.PRNGKey(6), 2)
    w_pre = jax.nn.initializers.orthogonal()(ks[0], (2, 2))
    w_post = jax.nn.initializers.orthogonal()(ks[1], (2, 2))

    def loss(fn):
        return lambda *a: jnp.sum(jnp.square(fn(*a)))

    gf = jax.grad(loss(lambda *a: flash_talking_heads_attention(*a)), argnums=(0, 1, 2, 3, 4))(
        q, k, v, w_pre, w_post
    )
    gx = jax.grad(loss(lambda *a: _th_dense_reference(*a, 16 ** -0.5)), argnums=(0, 1, 2, 3, 4))(
        q, k, v, w_pre, w_post
    )
    for a, b in zip(gf, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-4)


@pytest.mark.slow
def test_talking_heads_blocked_backward_multi_qblock():
    """block_q < q_len drives the backward's dk/dv/dW accumulation across
    sequential q-block grid cells (and the zero-padded final block)."""
    from sav_tpu.ops.talking_heads import (
        _th_dense_reference,
        flash_talking_heads_attention,
        fused_bwd_eligible,
    )

    assert fused_bwd_eligible(heads=3, q_len=40, kv_len=40, dim=16, block_q=16)
    q, k, v = _qkv(lq=40, lk=40, h=3, d=16)
    ks = jax.random.split(jax.random.PRNGKey(7), 2)
    w_pre = jax.nn.initializers.orthogonal()(ks[0], (3, 3))
    w_post = jax.nn.initializers.orthogonal()(ks[1], (3, 3))

    def loss(fn):
        return lambda *a: jnp.sum(jnp.square(fn(*a)))

    gf = jax.grad(
        loss(lambda *a: flash_talking_heads_attention(*a, block_q=16)),
        argnums=(0, 1, 2, 3, 4),
    )(q, k, v, w_pre, w_post)
    gx = jax.grad(
        loss(lambda *a: _th_dense_reference(*a, 16 ** -0.5)),
        argnums=(0, 1, 2, 3, 4),
    )(q, k, v, w_pre, w_post)
    for a, b in zip(gf, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-4)


def test_talking_heads_fused_rejects_over_budget_shapes():
    from sav_tpu.ops.talking_heads import (
        flash_talking_heads_attention,
        fused_eligible,
    )

    # Many heads × long kv blows the VMEM working set (the CaiT-M-at-high-res
    # class of shapes) — must raise, and the auto gate must say ineligible.
    assert not fused_eligible(heads=16, kv_len=2026, dim=64)
    assert fused_eligible(heads=4, kv_len=196, dim=48)  # CaiT-XXS24 trunk
    q, k, v = _qkv(lq=8, lk=2026, h=16, d=64)
    w = jnp.eye(16)
    with pytest.raises(ValueError, match="VMEM"):
        flash_talking_heads_attention(q, k, v, w, w)


def test_talking_heads_block_kernel_accessor():
    """TalkingHeadsBlock(None) returns the kernel with the same param tree."""
    from sav_tpu.models.layers.attention import TalkingHeadsBlock

    block = TalkingHeadsBlock(num_heads=4)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 8, 8))
    v1 = block.init(jax.random.PRNGKey(1), x)
    v2 = block.init(jax.random.PRNGKey(1), None)
    assert jax.tree.structure(v1) == jax.tree.structure(v2)
    kernel = block.apply(v1, None)
    assert kernel.shape == (4, 4)
    ref = jnp.einsum("hi,bhqk->biqk", kernel, x)
    np.testing.assert_allclose(np.asarray(block.apply(v1, x)), np.asarray(ref), rtol=1e-6)


@pytest.mark.parametrize(
    "lq,lk,d,with_bias",
    [
        (197, 197, 64, False),  # DeiT-S flagship shape
        (1, 197, 64, False),  # class attention
        (196, 49, 64, False),  # CvT downsampled K/V
        (50, 50, 32, True),  # bias gradient path
    ],
)
@pytest.mark.slow
def test_fast_vjp_matches_autodiff_f32(lq, lk, d, with_bias):
    """xla_attention_fast: hand-written VJP vs autodiff of the reference
    path. In f32 the residual-storage dtype matches, so gradients agree to
    matmul-reassociation tolerance."""
    from sav_tpu.ops.attention import xla_attention_fast

    q, k, v = _qkv(lq=lq, lk=lk, d=d)
    bias = (
        jax.random.normal(jax.random.PRNGKey(9), (1, 4, lq, lk))
        if with_bias
        else None
    )
    args = (q, k, v) if bias is None else (q, k, v, bias)
    argnums = tuple(range(len(args)))

    def loss_f(fn):
        return lambda *a: jnp.sum(jnp.square(fn(*a)))

    out_fast = xla_attention_fast(*args)
    out_ref = xla_attention(*args)
    np.testing.assert_allclose(
        np.asarray(out_fast), np.asarray(out_ref), atol=2e-5, rtol=2e-5
    )
    gf = jax.grad(loss_f(xla_attention_fast), argnums=argnums)(*args)
    gx = jax.grad(loss_f(xla_attention), argnums=argnums)(*args)
    for a, b in zip(gf, gx):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=5e-4
        )


@pytest.mark.slow
def test_fast_vjp_bf16_close_to_f32_chain():
    """bf16 inputs: fast-VJP gradients stay within bf16 quantization of the
    all-f32 gradient chain (the correctness bound claimed in the docstring)."""
    from sav_tpu.ops.attention import xla_attention_fast

    q, k, v = _qkv(lq=197, lk=197, d=64, dtype=jnp.bfloat16)
    q32, k32, v32 = (x.astype(jnp.float32) for x in (q, k, v))

    def loss(fn, *a):
        return jnp.sum(jnp.square(fn(*a).astype(jnp.float32)))

    gf = jax.grad(lambda *a: loss(xla_attention_fast, *a), argnums=(0, 1, 2))(
        q, k, v
    )
    g32 = jax.grad(lambda *a: loss(xla_attention, *a), argnums=(0, 1, 2))(
        q32, k32, v32
    )
    for a, b in zip(gf, g32):
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        assert np.all(np.isfinite(a))
        denom = np.maximum(np.abs(b), 1e-3)
        assert np.median(np.abs(a - b) / denom) < 2e-2


@pytest.mark.slow
def test_dot_product_attention_xla_matches_reference():
    """Dispatcher's XLA branch runs the plain-autodiff reference path
    (measured faster than the hand VJP on v5e — PERF.md §5); the fast path
    stays an explicit opt-in and must agree with it numerically."""
    q, k, v = _qkv(lq=64, lk=64, d=32)
    out = dot_product_attention(q, k, v, backend="xla")
    ref = xla_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)
    fast = xla_attention_fast(q, k, v)
    np.testing.assert_allclose(np.asarray(fast), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_logits_dtype_default_knob():
    """`set_default_logits_dtype` switches the XLA softmax dtype process-wide
    (TrainConfig.attention_logits_dtype plumbs to it); bf16 logits must stay
    within bf16 quantization of the f32 reference."""
    from sav_tpu.ops import attention as att

    q, k, v = _qkv(lq=32, lk=32, d=16, dtype=jnp.bfloat16)
    ref = np.asarray(att.xla_attention(q, k, v), np.float32)
    att.set_default_logits_dtype("bfloat16")
    try:
        lo = np.asarray(att.xla_attention(q, k, v), np.float32)
    finally:
        att.set_default_logits_dtype("float32")
    assert np.all(np.isfinite(lo))
    denom = np.maximum(np.abs(ref), 1e-2)
    assert np.median(np.abs(lo - ref) / denom) < 3e-2
    # explicit argument still overrides the default
    hi = np.asarray(att.xla_attention(q, k, v, logits_dtype=jnp.float32), np.float32)
    np.testing.assert_allclose(hi, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_fast_vjp_bf16_bias_cotangent_dtype():
    """bf16 bias (the BoTNet training configuration): dbias must come back
    in the primal dtype or custom_vjp rejects the cotangent at trace time."""
    from sav_tpu.ops.attention import xla_attention_fast

    q, k, v = _qkv(lq=50, lk=50, d=32, dtype=jnp.bfloat16)
    bias = jax.random.normal(
        jax.random.PRNGKey(9), (1, 4, 50, 50), jnp.bfloat16
    )
    g = jax.grad(
        lambda b: jnp.sum(jnp.square(xla_attention_fast(q, k, v, b).astype(jnp.float32)))
    )(bias)
    assert g.dtype == jnp.bfloat16
    assert np.all(np.isfinite(np.asarray(g, np.float32)))


@pytest.mark.parametrize("bias_shape", [(4, 24, 24), (24, 24), (1, 24)])
@pytest.mark.slow
def test_fast_vjp_low_rank_bias_matches_autodiff(bias_shape):
    """Bias with rank < logits rank broadcasts from the right; the hand
    VJP must reduce accordingly (left-aligned pairing is wrong/crashes)."""
    from sav_tpu.ops.attention import xla_attention_fast

    q, k, v = _qkv(lq=24, lk=24, d=16)
    bias = jax.random.normal(jax.random.PRNGKey(3), bias_shape)

    def loss_f(fn):
        return lambda q, k, v, b: jnp.sum(jnp.square(fn(q, k, v, b)))

    gf = jax.grad(loss_f(xla_attention_fast), argnums=(0, 1, 2, 3))(q, k, v, bias)
    gx = jax.grad(loss_f(xla_attention), argnums=(0, 1, 2, 3))(q, k, v, bias)
    for a, b in zip(gf, gx):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=5e-4
        )
