"""savlint self-run: the repo must lint clean, and stay that way (ISSUE 3).

This is the tier-1 enforcement point: ``lint_paths`` over ``sav_tpu/``,
``tools/``, ``train.py``, ``bench.py`` must report zero non-baselined,
non-pragma'd findings — a new host sync in the hot loop, an un-donated
step jit, or a re-inlined ``device_put`` fails CI here with the rule ID
and line. The planted-violation tests prove the gate actually bites
(a green self-run over a linter that matches nothing would be
indistinguishable from a clean repo), and the CLI tests pin the exit
codes external CI keys on (0 clean / 1 findings / 2 usage error).
"""

import json
import os
import subprocess
import sys
import textwrap
import time

from sav_tpu.analysis.lint import (
    DEFAULT_BASELINE,
    lint_paths,
    load_baseline,
    repo_root,
)

ROOT = repo_root()
SELF_PATHS = [
    os.path.join(ROOT, p) for p in ("sav_tpu", "tools", "train.py", "bench.py")
]

_SELF_LINT: dict = {}


def _self_lint():
    """The ONE shared full-surface lint this suite asserts against.

    Half the tests here examine different properties of the same
    repo-wide run; re-linting (and re-running the whole-program
    concurrency pass) per test was the suite's own wall-time hotspot.
    The result is read-only; the first call times itself for the
    wall-time budget test below.
    """
    if not _SELF_LINT:
        t0 = time.perf_counter()
        _SELF_LINT["result"] = lint_paths(
            SELF_PATHS, root=ROOT, baseline=DEFAULT_BASELINE
        )
        _SELF_LINT["elapsed_s"] = time.perf_counter() - t0
    return _SELF_LINT["result"]


def test_repo_lints_clean():
    """Zero unsuppressed findings over the whole linted surface."""
    result = _self_lint()
    assert result.findings == [], "\n".join(
        f.format() for f in result.findings
    )
    assert result.files > 80  # the walk actually covered the tree


def test_repo_suppressions_are_all_justified():
    """Every pragma carries a justification (SAV100 enforces the text);
    every baseline entry carries one too — no silent exemptions."""
    result = _self_lint()
    assert all(f.rule != "SAV100" for f in result.findings)
    if os.path.exists(DEFAULT_BASELINE):
        for e in load_baseline(DEFAULT_BASELINE):
            assert e.get("justification", "").strip(), e
            assert not e["justification"].startswith("TODO"), e


def test_trainer_hot_loop_suppressions_are_the_known_set():
    """The trainer's allowlisted syncs stay an explicit, enumerated set:
    a NEW intentional sync must extend this list consciously, not ride
    in on an existing pragma."""
    trainer = os.path.join(ROOT, "sav_tpu", "train", "trainer.py")
    result = lint_paths([trainer], root=ROOT)
    assert result.findings == []
    suppressed = sorted((f.rule, f.line) for f in result.suppressed)
    rules = [r for r, _ in suppressed]
    # 9 intentional SAV101 syncs (profiler edges, run-ahead caps, log
    # sync, boundary reads, and the flight recorder's periodic pre-step
    # snapshot — the ONE sync recording adds, at its configured cadence)
    # + the serial-fallback SAV106 + 4 SAV113 profiling sites (the armed
    # static window's open/close edges, its crash-path close, and the
    # OOM memdump in fit's finally — the sanctioned windows/incident
    # path the rule's docstring names). The recorder's per-step path
    # itself must stay sync-free: that is SAV111's beat, with zero
    # suppressions — and the fleet heartbeat/autoprof path likewise
    # (SAV112, zero suppressions: heartbeating adds NO device syncs).
    assert rules.count("SAV101") == 9
    assert rules.count("SAV106") == 1
    assert rules.count("SAV111") == 0
    assert rules.count("SAV112") == 0
    assert rules.count("SAV113") == 4
    # + the ONE sanctioned unbounded wait (SAV123): fit's final
    # checkpointer.wait() — the watchdog is deliberately stopped first
    # so the flush can take as long as the relay needs.
    assert rules.count("SAV123") == 1
    assert len(suppressed) == 15


def test_serve_hot_loop_suppressions_are_the_known_set():
    """SAV115's one sanctioned serve-path 'sync' stays exactly the
    documented site: ``ServeEngine.submit``'s ``np.asarray`` validation
    of the submitted HOST image (no device value in reach). The batcher
    itself — the drain the rule exists to keep sync-free — carries
    zero suppressions."""
    result = lint_paths([os.path.join(ROOT, "sav_tpu", "serve")], root=ROOT)
    assert result.findings == []
    sav115 = [f for f in result.suppressed if f.rule == "SAV115"]
    assert [os.path.basename(f.path) for f in sav115] == ["engine.py"]
    # SAV116 (serve-telemetry hot path): zero suppressions anywhere —
    # span stamping, window observation, and heartbeating add NO device
    # syncs, with no sanctioned exceptions.
    assert [f for f in result.suppressed if f.rule == "SAV116"] == []
    batcher = lint_paths(
        [os.path.join(ROOT, "sav_tpu", "serve", "batcher.py")], root=ROOT
    )
    assert batcher.findings == []
    assert batcher.suppressed == []
    telemetry = lint_paths(
        [os.path.join(ROOT, "sav_tpu", "serve", "telemetry.py")], root=ROOT
    )
    assert telemetry.findings == []
    assert telemetry.suppressed == []


def test_router_hot_path_suppressions_are_zero():
    """SAV118 (router-hot-path-sync): the fleet router's admit/route/
    drain surface carries ZERO suppressions — every request in the
    fleet passes through it, so a single sanctioned sync would tax the
    whole fleet. The router and pool modules themselves lint fully
    clean (they are stdlib-only: no device value is even reachable)."""
    result = lint_paths([os.path.join(ROOT, "sav_tpu", "serve")], root=ROOT)
    assert [f for f in result.findings if f.rule == "SAV118"] == []
    assert [f for f in result.suppressed if f.rule == "SAV118"] == []
    # SAV119 (router-trace-hot-path-sync, ISSUE 16): the tracing
    # surface the router grew (_dispatch/_route_with_waits/
    # _observe_completion/router_beat) carries ZERO suppressions too —
    # observing a request must not slow it, with no sanctioned
    # exceptions.
    assert [f for f in result.findings if f.rule == "SAV119"] == []
    assert [f for f in result.suppressed if f.rule == "SAV119"] == []
    # SAV125 (alert-eval-in-hot-path, ISSUE 19): the metrics pipeline
    # stays at heartbeat cadence with ZERO suppressions — across the
    # serving stack AND the pipeline's own modules (sav_tpu/obs):
    # alert evaluation lives in serve_beat(), rollup advances on the
    # router's heartbeat thread, never in a request path.
    obs = lint_paths([os.path.join(ROOT, "sav_tpu", "obs")], root=ROOT)
    for res in (result, obs):
        assert [f for f in res.findings if f.rule == "SAV125"] == []
        assert [f for f in res.suppressed if f.rule == "SAV125"] == []
    for module in ("router.py", "fleet.py"):
        one = lint_paths(
            [os.path.join(ROOT, "sav_tpu", "serve", module)], root=ROOT
        )
        assert one.findings == []
        assert one.suppressed == []


def test_quality_eval_suppressions_are_zero():
    """SAV126 (quality-eval-in-hot-path, ISSUE 20): prediction-quality
    telemetry holds its zero-sync/zero-per-request-eval contract with
    ZERO suppressions — the digests ride the device loop's one result
    fetch, probes run on the probe thread, shadow scoring on the shadow
    worker, snapshots at heartbeat cadence. The quality modules
    themselves lint fully clean (the obs side is stdlib-only; the serve
    side never touches a device value outside the traced digest fn)."""
    result = _self_lint()
    assert [f for f in result.findings if f.rule == "SAV126"] == []
    assert [f for f in result.suppressed if f.rule == "SAV126"] == []
    for path in (
        os.path.join(ROOT, "sav_tpu", "obs", "quality.py"),
        os.path.join(ROOT, "sav_tpu", "serve", "quality.py"),
    ):
        one = lint_paths([path], root=ROOT)
        assert one.findings == []
        assert one.suppressed == []


def test_adhoc_partition_spec_suppressions_are_zero():
    """SAV117 (adhoc-partition-spec): every PartitionSpec/NamedSharding
    outside sav_tpu/parallel/ derives from the SpecLayout — the rule
    carries ZERO suppressions over the whole linted surface, so the one
    source of layout truth cannot erode one pragma at a time
    (docs/parallelism.md)."""
    result = _self_lint()
    assert [f for f in result.findings if f.rule == "SAV117"] == []
    assert [f for f in result.suppressed if f.rule == "SAV117"] == []


def test_unscaled_int8_cast_suppressions_are_zero():
    """SAV120 (unscaled-int8-cast): every int8 tensor in the model/op/
    serve stack is born in sav_tpu/ops/quant.py next to its per-channel
    scale — the rule carries ZERO suppressions over the whole linted
    surface, so scale-less int8 can never creep in one pragma at a time
    (docs/quantization.md)."""
    result = _self_lint()
    assert [f for f in result.findings if f.rule == "SAV120"] == []
    assert [f for f in result.suppressed if f.rule == "SAV120"] == []


def test_library_exit_suppressions_are_the_two_contracts():
    """SAV114's sanctioned library exits stay exactly the documented
    pair (docs/elasticity.md exit-code table): the watchdog's os._exit
    capability and the backend probe's SystemExit(3). A third bare exit
    in sav_tpu/ must extend this consciously, not ride in on a pragma."""
    paths = [
        os.path.join(ROOT, "sav_tpu", "obs", "watchdog.py"),
        os.path.join(ROOT, "sav_tpu", "utils", "backend_probe.py"),
    ]
    result = lint_paths(paths, root=ROOT)
    assert result.findings == []
    sav114 = [f for f in result.suppressed if f.rule == "SAV114"]
    assert sorted(os.path.basename(f.path) for f in sav114) == [
        "backend_probe.py", "watchdog.py",
    ]
    # The supervisor itself — the layer most tempted to exit — never
    # does: it RETURNS exit codes (train.py owns the process exit).
    sup = lint_paths(
        [os.path.join(ROOT, "sav_tpu", "train", "supervisor.py")], root=ROOT
    )
    assert sup.findings == []
    assert [f for f in sup.suppressed if f.rule == "SAV114"] == []


def test_concurrency_suppressions_are_the_three_sanctioned_waits():
    """SAV121–SAV124 (ISSUE 18): the repo's locking discipline holds
    with ZERO suppressions for unguarded state (121), lock-order cycles
    (122), and thread leaks (124). SAV123's sanctioned unbounded waits
    stay exactly the documented three: the supervisor's child wait (the
    child's watchdog owns that liveness), fit's final checkpoint flush
    (watchdog stopped first, truncation would corrupt the save), and
    the recorder's crash-path incident dump (a truncated snapshot is a
    non-replayable bundle). A fourth must extend this list consciously."""
    result = _self_lint()
    for rule in ("SAV121", "SAV122", "SAV124"):
        assert [f for f in result.findings if f.rule == rule] == []
        assert [f for f in result.suppressed if f.rule == rule] == []
    sav123 = sorted(
        os.path.basename(f.path)
        for f in result.suppressed
        if f.rule == "SAV123"
    )
    assert sav123 == ["recorder.py", "supervisor.py", "trainer.py"]


def test_repo_lint_wall_time_stays_bounded():
    """The shared-parse restructure (each file parsed once, one
    ``ast.walk`` cached per module, the whole-program pass memoized
    across the four concurrency rules) keeps the full self-run cheap.
    The budget is deliberately loose — 4x headroom over the ~2s
    observed on a cold CI core — but a quadratic regression (a rule
    re-walking per rule, the project pass re-running per rule) blows
    through it immediately. Measured on the suite's one shared run —
    the measurement itself must not double the suite's cost."""
    result = _self_lint()
    elapsed = _SELF_LINT["elapsed_s"]
    assert result.files > 80
    assert elapsed < 8.0, f"repo lint took {elapsed:.2f}s (budget 8s)"


# ------------------------------------------------- the gate actually bites


def test_planted_host_sync_in_step_impl_fails_with_rule_and_line(tmp_path):
    src = tmp_path / "scratch_trainer.py"
    src.write_text(
        textwrap.dedent(
            """\
            import jax


            def _train_step_impl(state, batch, rng):
                loss = jax.device_get(batch["x"])
                return state, loss
            """
        )
    )
    result = lint_paths([str(src)], root=str(tmp_path))
    assert [(f.rule, f.line) for f in result.findings] == [("SAV101", 5)]


def test_planted_undonated_jit_fails_with_rule_and_line(tmp_path):
    src = tmp_path / "scratch_jit.py"
    src.write_text(
        textwrap.dedent(
            """\
            import jax


            def step(state, batch):
                return state


            run = jax.jit(step)
            """
        )
    )
    result = lint_paths([str(src)], root=str(tmp_path))
    assert [(f.rule, f.line) for f in result.findings] == [("SAV102", 8)]


# ------------------------------------------------------------ CLI contract


def _savlint(*args):
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "savlint.py"), *args],
        capture_output=True,
        text=True,
        cwd=ROOT,
    )


def test_cli_self_run_exits_zero():
    proc = _savlint()
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 findings" in proc.stderr


def test_cli_findings_exit_one_with_json(tmp_path):
    src = tmp_path / "bad.py"
    src.write_text(
        "import jax\n\n\ndef make(seed):\n"
        "    return jax.random.PRNGKey(seed + 1)\n"
    )
    proc = _savlint("--json", "--root", str(tmp_path), str(src))
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert [(f["rule"], f["line"]) for f in payload["findings"]] == [
        ("SAV110", 5)
    ]
    assert payload["files"] == 1


def test_cli_usage_errors_exit_two(tmp_path):
    assert _savlint("/no/such/path.py").returncode == 2
    assert _savlint("--select", "SAV999").returncode == 2
    # An explicitly named baseline that does not exist is a typo, not
    # "run without it and resurface every grandfathered finding".
    assert _savlint("--baseline", "/no/such/baseline.json").returncode == 2
    # A filtered snapshot would delete the unselected rules' entries.
    assert _savlint("--write-baseline", "--select", "SAV101").returncode == 2
    # Baseline I/O failures are usage errors (2), never "findings" (1).
    proc = _savlint(
        "--write-baseline", "--baseline",
        str(tmp_path / "no" / "dir" / "b.json"),
    )
    assert proc.returncode == 2
    assert "cannot write baseline" in proc.stderr


def test_cli_list_rules():
    proc = _savlint("--list-rules")
    assert proc.returncode == 0
    for rule_id in ("SAV100", "SAV101", "SAV106", "SAV110"):
        assert rule_id in proc.stdout
