"""Memory telemetry (sav_tpu/obs/memory.py): hbm_stats degrades to {}
on backends without memory_stats; RetraceCounter sees new jit traces."""

import jax
import jax.numpy as jnp

from sav_tpu.obs.memory import RetraceCounter, hbm_stats


def test_hbm_stats_never_raises_on_cpu():
    stats = hbm_stats()
    assert isinstance(stats, dict)
    # CPU backends either report nothing ({}) or real byte counts.
    for v in stats.values():
        assert v >= 0


def test_hbm_stats_aggregates_fake_devices():
    class Dev:
        def __init__(self, in_use, peak, limit=0):
            self._s = {
                "bytes_in_use": in_use, "peak_bytes_in_use": peak,
                **({"bytes_limit": limit} if limit else {}),
            }

        def memory_stats(self):
            return self._s

    stats = hbm_stats([Dev(100, 150, 1000), Dev(200, 120, 1000)])
    assert stats["hbm_bytes_in_use"] == 300
    assert stats["hbm_peak_bytes"] == 150  # max, not sum: the OOM number
    assert stats["hbm_bytes_limit"] == 2000


def test_hbm_stats_skips_raising_devices():
    class Bad:
        def memory_stats(self):
            raise RuntimeError("relay refused")

    assert hbm_stats([Bad()]) == {}


def test_retrace_counter_counts_new_traces():
    @jax.jit
    def f(x):
        return x * 2

    f(jnp.ones((2,)))  # first trace
    counter = RetraceCounter(f)
    if not counter.active:  # running jax lacks _cache_size(): degrade path
        assert counter.delta() == 0
        return
    assert counter.delta() == 0  # same shape -> cache hit
    f(jnp.ones((2,)))
    assert counter.delta() == 0
    f(jnp.ones((3,)))  # new shape -> retrace
    assert counter.delta() == 1
    f(jnp.ones((4, 4)))
    f(jnp.ones((5, 5)))
    assert counter.delta() == 2
    assert counter.delta() == 0  # diffing, not cumulative


def test_retrace_counter_degrades_without_cache_size():
    counter = RetraceCounter(lambda x: x)  # plain function: no _cache_size
    assert not counter.active
    assert counter.delta() == 0
