"""Flight recorder + incident dumps + deterministic replay (ISSUE 5).

The tier-1 acceptance criteria live here: a seeded run with a planted
NaN (scaled-up lr on synthetic data) produces an incident bundle,
``tools/replay_step.py`` reproduces the recorded step metrics
**bit-exactly** on CPU and names the first nonfinite layer group, a
healthy run of equal length produces zero incidents with recorder
overhead under 2% of step time (asserted via the goodput ledger), and
the crash paths — eval nonfinite, watchdog hang, uncaught exception —
all dump bundles before the process can lose them. Unit coverage pins
the ring/batch retention bounds and the spike/nonfinite gates.
"""

import importlib.util
import json
import io
import os
import sys

import numpy as np
import pytest

from sav_tpu.obs.recorder import FlightRecorder, load_bundle_batch
from sav_tpu.data import synthetic_data_iterator
from sav_tpu.train import TrainConfig, Trainer

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_replay():
    path = os.path.join(ROOT, "tools", "replay_step.py")
    spec = importlib.util.spec_from_file_location("replay_step", path)
    module = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = module
    spec.loader.exec_module(module)
    return module


replay_step = _load_replay()


def _config(tmp_path, **overrides):
    base = dict(
        model_name="vit_ti_patch16",
        num_classes=10,
        image_size=32,
        compute_dtype="float32",
        global_batch_size=8,
        num_train_images=8 * 32,
        num_epochs=1,
        warmup_epochs=0,
        lr_scaling_divisor=8,
        base_lr=1e-3,
        clip_grad_norm=None,
        transpose_images=False,
        log_every_steps=1,
        log_dir=str(tmp_path),
        diagnostics=True,
        # The recorder suite measures the RECORDER's steady-state cost
        # (the <2% overhead guard): keep the fleet heartbeat writer out
        # of these fits so the guard isolates the contract under test —
        # the fleet path carries its own <1% guard (tests/test_fleet.py).
        fleet=False,
        record=True,
        record_depth=8,
        record_batches=4,
        seed=0,
        # The model is rebuilt from this config by tools/replay_step.py,
        # so the architecture must live in model_overrides, not in an
        # externally constructed model.
        model_overrides={"num_layers": 1, "embed_dim": 32, "num_heads": 2},
    )
    base.update(overrides)
    return TrainConfig(**base)


def _batch(step):
    rng = np.random.default_rng(step)
    return {
        "images": rng.standard_normal((2, 4, 4, 3)).astype(np.float32),
        "labels": rng.integers(0, 10, (2,), dtype=np.int32),
    }


# ----------------------------------------------------------- ring bounds


def test_ring_eviction_and_batch_retention_bounds(tmp_path):
    rec = FlightRecorder(str(tmp_path), depth=8, keep_batches=3, seed=0)
    for step in range(1, 21):
        rec.observe_batch(_batch(step))
        rec.on_step(step)
    entries = list(rec._ring)
    assert [e.step for e in entries] == list(range(13, 21))  # depth bound
    held = [e.step for e in entries if e.batch is not None]
    assert held == [18, 19, 20]  # only the newest keep_batches hold data
    assert not rec._pending  # every observed batch was consumed
    assert rec.last_step == 20
    assert rec.stats()["steps"] == 20.0


def test_recorder_rejects_unreplayable_knobs(tmp_path):
    with pytest.raises(ValueError):
        FlightRecorder(str(tmp_path), depth=4, keep_batches=8)
    with pytest.raises(ValueError):
        # A snapshot cadence beyond the batch window could never replay.
        FlightRecorder(
            str(tmp_path), depth=8, keep_batches=2, snapshot_every=4
        )


def test_batch_fingerprint_rides_content_not_identity(tmp_path):
    rec = FlightRecorder(str(tmp_path), depth=4, keep_batches=1, seed=0)
    a, b = _batch(1), _batch(1)
    same = _batch(2)
    from sav_tpu.obs.recorder import batch_fingerprint

    assert batch_fingerprint(a)["hash"] == batch_fingerprint(b)["hash"]
    assert batch_fingerprint(a)["hash"] != batch_fingerprint(same)["hash"]
    assert batch_fingerprint(a)["shapes"]["images"] == [2, 4, 4, 3]
    del rec


# -------------------------------------------------------- incident gates


def test_spike_gate_flags_upward_spikes_only(tmp_path):
    rec = FlightRecorder(
        str(tmp_path), depth=4, keep_batches=1, spike_sigma=6.0, seed=0
    )
    # Healthy noisy window: never triggers while the gate warms up or on
    # jitter within the MAD envelope.
    for i, loss in enumerate(
        [2.30, 2.28, 2.31, 2.27, 2.29, 2.30, 2.26, 2.28, 2.29, 2.27]
    ):
        assert rec.note_metrics(i + 1, {"loss": loss}) is None
    # A collapse (downward) is progress, not an incident.
    assert rec.note_metrics(11, {"loss": 0.5}) is None
    # An upward spike beyond the robust envelope triggers.
    assert rec.note_metrics(12, {"loss": 10.0}) == "loss_spike"


def test_nonfinite_gate_fires_once_per_episode(tmp_path):
    rec = FlightRecorder(str(tmp_path), depth=4, keep_batches=1, seed=0)
    assert rec.note_metrics(1, {"loss": float("nan")}) == "nonfinite"
    # NaN persists in the state: later windows are the same episode.
    assert rec.note_metrics(2, {"loss": float("nan")}) is None
    assert rec.note_metrics(3, {"loss": 2.0}) is None  # episode ends
    assert rec.note_metrics(4, {"loss": float("inf")}) == "nonfinite"
    # Host-only keys never drive detection (hbm stats, throughput...).
    assert rec.note_metrics(
        5, {"loss": 2.0, "images_per_sec": float("nan")}
    ) is None


def test_sparse_step_bundle_is_not_replayable(tmp_path):
    """bench.py --record records at *window* granularity (entries at
    steps 10, 20, ... with snapshots between): the gap steps hold no
    batches, so the bundle must come out replayable: false — a snapshot
    that merely overlaps the kept window is not a replay recipe."""
    rec = FlightRecorder(
        str(tmp_path), depth=4, keep_batches=4, snapshot_every=1, seed=0
    )
    for window in (1, 2, 3):
        rec.snapshot((window - 1) * 10, {"w": np.zeros(2, np.float32)})
        rec.observe_batch(_batch(window))
        rec.on_step(window * 10)
    path = rec.dump_incident("nonfinite", 30)
    assert path is not None
    with open(os.path.join(path, "incident.json")) as f:
        doc = json.load(f)
    assert doc["replayable"] is False
    assert doc["snapshot_step"] == 20  # nearest context still recorded


def test_dump_budget_and_dedup(tmp_path):
    rec = FlightRecorder(
        str(tmp_path), depth=4, keep_batches=1, max_incidents=2, seed=0
    )
    rec.on_step(1)
    assert rec.dump_incident("nonfinite", 1) is not None
    assert rec.dump_incident("nonfinite", 1) is None  # same step+trigger
    assert rec.dump_incident("exception", 1) is not None  # distinct trigger
    assert rec.dump_incident("nonfinite", 2) is None  # budget spent


# ------------------------------------------- planted NaN -> replay (e2e)


def _fit(config, *, steps, data_seed=3):
    trainer = Trainer(config)
    data = synthetic_data_iterator(
        batch_size=config.global_batch_size, image_size=config.image_size,
        num_classes=config.num_classes, seed=data_seed,
    )
    state, history = trainer.fit(data, num_steps=steps, log_fn=None)
    return trainer, state, history


def test_planted_nan_bundle_replays_bitexact_and_names_group(
    tmp_path, devices
):
    """The acceptance pipeline end-to-end: scaled-up lr NaNs the run, the
    recorder dumps a replayable bundle, and replay_step reproduces the
    recorded metrics bit-exactly and names the first nonfinite layer
    group (cross-checked against the recorded in-jit diagnostics)."""
    config = _config(tmp_path, base_lr=1e12)
    _, _, history = _fit(config, steps=8)
    losses = [m["loss"] for m in history if "loss" in m]
    assert any(not np.isfinite(v) for v in losses), "NaN never planted"

    incidents_dir = os.path.join(str(tmp_path), "incidents")
    bundles = sorted(os.listdir(incidents_dir))
    assert len(bundles) == 1  # one bundle per nonfinite episode
    bundle = os.path.join(incidents_dir, bundles[0])
    with open(os.path.join(bundle, "incident.json")) as f:
        doc = json.load(f)
    assert doc["trigger"] == "nonfinite"
    assert doc["replayable"] is True
    assert doc["snapshot_step"] is not None
    assert doc["batch_steps"], "no batches kept"
    # Bundle layout: a batch npz per kept step + the state checkpoint.
    for s in doc["batch_steps"]:
        assert os.path.exists(os.path.join(bundle, f"batch_{s:08d}.npz"))
    assert os.path.isdir(os.path.join(bundle, "state"))
    # The ring index carries fingerprints and the logged metrics.
    ring = {e["step"]: e for e in doc["ring"]}
    bad_step = doc["step"]
    assert ring[bad_step]["metrics"] is not None
    assert ring[bad_step]["batch"]["hash"]
    # Recorded batches round-trip through the npz + dtype sidecar.
    first = doc["batch_steps"][0]
    loaded = load_bundle_batch(
        bundle, first, ring[first]["batch"]["dtypes"]
    )
    assert loaded["images"].shape == tuple(
        ring[first]["batch"]["shapes"]["images"]
    )

    # --- replay: bit-exact + provenance ---
    rc = replay_step.main([bundle, "--json"])
    assert rc == 0
    with open(os.path.join(bundle, "replay_verdict.json")) as f:
        verdict = json.load(f)
    assert verdict["metrics_match"] is True, verdict["mismatches"]
    assert verdict["steps_compared"] >= 1
    assert verdict["first_bad_step"] == bad_step
    # Independent cross-check: the groups the replay names must be
    # exactly the groups whose RECORDED in-jit grad norms went nonfinite.
    recorded_bad = sorted(
        k[len("grad_norm/"):]
        for k, v in ring[bad_step]["metrics"].items()
        if k.startswith("grad_norm/") and not np.isfinite(v)
    )
    assert sorted(verdict["bad_groups"]) == recorded_bad
    assert verdict["first_bad_group"] in recorded_bad
    # Escalation rung 2: checkify names the first failing primitive.
    assert verdict["checkify"] is not None
    assert "nan" in verdict["checkify"]["first_error"].lower()
    # Rung 3 is skipped honestly when the run was already f32.
    assert verdict["f32"] == {"ran": False, "reason": "already float32"}

    # run_report renders the incident alongside the other sections.
    out = io.StringIO()
    spec = importlib.util.spec_from_file_location(
        "run_report", os.path.join(ROOT, "tools", "run_report.py")
    )
    report = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = report
    spec.loader.exec_module(report)
    report.report_incidents(str(tmp_path), out)
    text = out.getvalue()
    assert "trigger=nonfinite" in text
    assert "bit-exact" in text
    assert verdict["first_bad_group"] in text


def test_healthy_run_zero_incidents_and_overhead_bound(tmp_path, devices):
    """Spike-gate no-false-positive on a healthy seeded run of the same
    length, and the steady-state cost contract: the recorder's
    training-thread bookkeeping stays under 2% of step time (its hashing
    runs on the feeder thread, reported separately — like feeder/h2d_s)."""
    config = _config(tmp_path, base_lr=1e-3, log_every_steps=2)
    trainer, _, history = _fit(config, steps=8)
    assert not os.path.exists(os.path.join(str(tmp_path), "incidents"))
    gauges = trainer.last_goodput["gauges"]
    assert gauges["recorder/incidents"] == 0.0
    assert gauges["recorder/steps"] == 8.0
    step_s = trainer.last_goodput["buckets_s"]["step"]
    assert step_s > 0
    assert gauges["recorder/overhead_s"] < 0.02 * step_s, (
        f"recorder overhead {gauges['recorder/overhead_s']:.6f}s is not "
        f"<2% of step time {step_s:.6f}s"
    )
    # Hashing happened (on the feeder thread) and is visible as a gauge.
    assert gauges["recorder/hash_s"] > 0.0


# ----------------------------------------------------------- crash paths


def test_eval_nonfinite_dumps_bundle_and_debug_nans_raises(
    tmp_path, devices
):
    """Satellite: cfg.debug_nans + the recorder wired through evaluate()
    — a nonfinite eval loss produces the same incident bundle."""
    import jax
    import jax.numpy as jnp

    config = _config(tmp_path, debug_nans=True)
    trainer = Trainer(config)
    state = trainer.init_state()
    poisoned = state.replace(
        params=jax.tree.map(lambda x: x * jnp.float32("nan"), state.params)
    )

    def eval_iter():
        for step in range(2):
            yield _eval_batch(step)

    def _eval_batch(step):
        rng = np.random.default_rng(step)
        return {
            "images": rng.standard_normal((8, 32, 32, 3)).astype(
                np.float32
            ),
            "labels": rng.integers(0, 10, (8,), dtype=np.int32),
        }

    with pytest.raises(FloatingPointError, match="eval"):
        trainer.evaluate(poisoned, eval_iter())
    bundles = os.listdir(os.path.join(str(tmp_path), "incidents"))
    assert len(bundles) == 1
    with open(
        os.path.join(str(tmp_path), "incidents", bundles[0],
                     "incident.json")
    ) as f:
        doc = json.load(f)
    assert doc["trigger"] == "eval_nonfinite"
    assert "eval_loss" in doc["extra"]["bad_keys"]


def test_midfit_eval_nonfinite_dumps_exactly_one_bundle(tmp_path, devices):
    """A nonfinite mid-fit eval under debug_nans dumps 'eval_nonfinite'
    and then raises — fit()'s finally must recognize the failure already
    dumped and not burn a second budget slot on a copy."""
    config = _config(
        tmp_path, debug_nans=True, num_train_images=8 * 2,
        eval_every_epochs=1,
    )
    trainer = Trainer(config)
    data = synthetic_data_iterator(
        batch_size=8, image_size=32, num_classes=10, seed=3
    )

    def nan_eval_iter():
        batch = next(
            synthetic_data_iterator(
                batch_size=8, image_size=32, num_classes=10, seed=5
            )
        )
        batch = dict(batch)
        batch["images"] = np.full_like(batch["images"], np.nan)
        yield batch

    with pytest.raises(FloatingPointError):
        trainer.fit(
            data, num_steps=4, eval_iter_fn=nan_eval_iter, log_fn=None
        )
    incidents_dir = os.path.join(str(tmp_path), "incidents")
    bundles = sorted(os.listdir(incidents_dir))
    assert len(bundles) == 1, bundles
    with open(
        os.path.join(incidents_dir, bundles[0], "incident.json")
    ) as f:
        doc = json.load(f)
    assert doc["trigger"] == "eval_nonfinite"


def test_exception_in_fit_dumps_incident_bundle(tmp_path, devices):
    """An uncaught exception mid-fit still dumps whatever context the
    ring holds (the finally path), classified as trigger 'exception'."""
    config = _config(tmp_path)
    trainer = Trainer(config)

    def dying_iter():
        data = synthetic_data_iterator(
            batch_size=8, image_size=32, num_classes=10, seed=3
        )
        for i in range(3):
            yield next(data)
        raise RuntimeError("input pipeline died mid-run")

    with pytest.raises(RuntimeError, match="input pipeline died"):
        trainer.fit(dying_iter(), num_steps=16, log_fn=None)
    incidents_dir = os.path.join(str(tmp_path), "incidents")
    bundles = sorted(os.listdir(incidents_dir))
    assert len(bundles) == 1
    with open(
        os.path.join(incidents_dir, bundles[0], "incident.json")
    ) as f:
        doc = json.load(f)
    assert doc["trigger"] == "exception"
    assert "input pipeline died" in doc["error"]
    assert doc["ring"], "ring context lost on the crash path"


def test_watchdog_fire_dumps_bundle_before_exit(tmp_path):
    """Satellite order proof (like the hang-finalize one): when the
    watchdog fires, the recorder bundle is on disk and the manifest's
    finalize notes point at it BEFORE os._exit can discard anything."""
    from sav_tpu.obs.manifest import RunManifest
    from sav_tpu.obs.watchdog import WATCHDOG_EXIT_CODE, HangWatchdog

    recorder = FlightRecorder(
        str(tmp_path), depth=4, keep_batches=2, seed=0
    )
    recorder.observe_batch(_batch(1))
    recorder.on_step(1)
    manifest = RunManifest(str(tmp_path / "manifest.json"), kind="train")
    manifest.begin()
    observed = {}

    def exit_fn(code):
        # Order proof: everything must already be durable at exit time.
        observed["code"] = code
        observed["doc"] = RunManifest.load(manifest.path)
        incidents = os.path.join(str(tmp_path), "incidents")
        observed["bundles"] = sorted(os.listdir(incidents))

    watchdog = HangWatchdog(
        0.2, manifest=manifest, recorder=recorder, tag="rec-watchdog",
        exit_fn=exit_fn, stream=io.StringIO(), poll_s=0.05,
    )
    watchdog.start()
    try:
        assert watchdog.fired.wait(timeout=5.0), "watchdog never fired"
    finally:
        watchdog.stop()
    assert observed["code"] == WATCHDOG_EXIT_CODE
    assert observed["bundles"], "no incident bundle at exit time"
    doc = observed["doc"]
    assert doc["outcome"] == "hang"
    bundle = os.path.join(
        str(tmp_path), "incidents", observed["bundles"][0]
    )
    assert doc["notes"]["incident"] == bundle
    with open(os.path.join(bundle, "incident.json")) as f:
        incident = json.load(f)
    assert incident["trigger"] == "hang"


# ------------------------------------------------------- replay plumbing


def test_replay_rejects_unreplayable_and_missing_bundles(tmp_path):
    assert replay_step.main([str(tmp_path / "nope")]) == 2
    bundle = tmp_path / "incidents" / "step_00000001"
    bundle.mkdir(parents=True)
    (bundle / "incident.json").write_text(json.dumps({
        "schema": 1, "step": 1, "trigger": "eval_nonfinite",
        "ring": [], "batch_steps": [], "snapshot_step": None,
        "replayable": False, "config": {}, "rng": {"seed": 0},
    }))
    assert replay_step.main([str(bundle)]) == 2
