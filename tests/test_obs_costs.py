"""Cost model (ISSUE 4): peak resolution, the analytic per-group FLOPs
walk, the XLA-upgrade path, and the end-to-end trainer integration — a
CPU fit() reports goodput/mfu + per-group attribution gauges and lands
them in the run manifest."""

import jax
import jax.numpy as jnp
import pytest

from sav_tpu.obs.costs import (
    CPU_FAKE_PEAK_FLOPS,
    TRAIN_STEP_MULTIPLIER,
    analytic_train_step_cost,
    infer_num_tokens,
    publish_cost_gauges,
    publish_mfu_gauges,
    resolve_peak_flops,
    train_step_cost,
)
from sav_tpu.obs.goodput import GoodputLedger


@pytest.fixture(scope="module")
def vit_params():
    from sav_tpu.models import create_model

    model = create_model(
        "vit_ti_patch16", num_classes=10, dtype=jnp.float32,
        num_layers=2, embed_dim=64, num_heads=4,
    )
    variables = model.init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((2, 32, 32, 3)), is_training=False,
    )
    return model, variables["params"]


# --------------------------------------------------------- peak resolution


def test_peak_resolution_order():
    # Override beats everything; CPU falls through the device table to
    # the deterministic fake — labeled, so it can never masquerade as a
    # hardware number.
    assert resolve_peak_flops(5e12) == (5e12, "override")
    peak, source = resolve_peak_flops()
    assert source == "cpu-fake"
    assert peak == CPU_FAKE_PEAK_FLOPS


def test_device_table_matches_on_kind():
    class FakeDevice:
        platform = "tpu"
        device_kind = "TPU v5 lite"

    peak, source = resolve_peak_flops(None, devices=[FakeDevice()])
    assert (peak, source) == (197e12, "device-table")

    class Unknown:
        platform = "tpu"
        device_kind = "TPU v99"

    assert resolve_peak_flops(None, devices=[Unknown()]) == (None, "unknown")


def test_dot_dtype_axis_scales_peak_and_tags_source():
    """ISSUE 17: the int8 arm's roofline denominator is 2x the bf16
    table entry (the MXU's native int8 path) and the source string is
    tagged ':int8' so a doubled peak can never masquerade as the bf16
    one. bf16/f32 are the identity (untagged); explicit overrides are
    taken verbatim — the operator's number is never scaled."""
    from sav_tpu.obs.costs import dot_dtype_bytes

    class FakeDevice:
        platform = "tpu"
        device_kind = "TPU v5 lite"

    devices = [FakeDevice()]
    assert resolve_peak_flops(None, devices=devices, dot_dtype="int8") == (
        2 * 197e12, "device-table:int8",
    )
    assert resolve_peak_flops(None, devices=devices, dot_dtype="bf16") == (
        197e12, "device-table",
    )
    # CPU fake doubles too, still labeled fake (+ the dtype tag).
    assert resolve_peak_flops(None, dot_dtype="int8") == (
        2 * CPU_FAKE_PEAK_FLOPS, "cpu-fake:int8",
    )
    assert resolve_peak_flops(5e12, dot_dtype="int8") == (5e12, "override")
    # The activation-traffic side of the axis.
    assert dot_dtype_bytes("int8") == 1
    assert dot_dtype_bytes("bf16") == 2
    assert dot_dtype_bytes("f32") == 4
    assert dot_dtype_bytes(None) == 2  # the historical bf16 default


# ----------------------------------------------------------- analytic walk


def test_token_inference_prefers_pos_embed_table(vit_params):
    _, params = vit_params
    # 32px / 16px patches = 2x2 grid + CLS = 5, stated by the pos_embed
    # table directly.
    assert infer_num_tokens(params, 32) == 5


def test_analytic_cost_attribution_sums_to_one(vit_params):
    _, params = vit_params
    cost = analytic_train_step_cost(
        params, batch_size=16, image_size=32, n_devices=1
    )
    assert cost.source == "analytic"
    assert cost.flops > 0
    assert sum(cost.attribution.values()) == pytest.approx(1.0)
    assert sum(cost.groups.values()) == pytest.approx(1.0)
    # Every named component of a ViT shows up, QK/AV included (the
    # parameter-free einsums a parameter-bytes count would miss).
    for comp in (
        "patch_embed", "attention_proj", "attention_qkav", "ffn", "head",
    ):
        assert cost.attribution.get(comp, 0.0) > 0.0, comp
    # Group naming matches diagnostics' grad_norm/<group> vocabulary.
    assert "Encoder_0" in cost.groups and "head" in cost.groups


def test_analytic_cost_scales_linearly_with_batch_and_devices(vit_params):
    _, params = vit_params
    one = analytic_train_step_cost(params, batch_size=8, image_size=32)
    two = analytic_train_step_cost(params, batch_size=16, image_size=32)
    assert two.flops == pytest.approx(2 * one.flops)
    sharded = analytic_train_step_cost(
        params, batch_size=16, image_size=32, n_devices=8
    )
    assert sharded.flops == pytest.approx(two.flops / 8)


def test_training_multiplier_applies(vit_params):
    _, params = vit_params
    train = analytic_train_step_cost(params, batch_size=8, image_size=32)
    infer = analytic_train_step_cost(
        params, batch_size=8, image_size=32, training=False
    )
    assert train.flops == pytest.approx(TRAIN_STEP_MULTIPLIER * infer.flops)


def test_analytic_total_tracks_xla_cost_analysis(vit_params):
    """The fallback must be in the right ballpark of XLA's exact count on
    a real fwd+bwd graph (within 2x either way — it is an estimate, but a
    wrong-order-of-magnitude one would poison every MFU it feeds)."""
    model, params = vit_params

    def loss_fn(p, x):
        return (model.apply({"params": p}, x, is_training=False) ** 2).mean()

    compiled = jax.jit(jax.value_and_grad(loss_fn)).lower(
        params, jnp.zeros((16, 32, 32, 3))
    ).compile()
    cost = train_step_cost(
        params, batch_size=16, image_size=32, compiled=compiled
    )
    assert cost.source == "xla-cost-analysis"
    analytic = analytic_train_step_cost(params, batch_size=16, image_size=32)
    assert cost.flops == pytest.approx(analytic.flops, rel=1.0)
    # Attribution stays analytic even when the total is XLA's.
    assert cost.attribution == analytic.attribution


def test_gauges_vocabulary(vit_params):
    _, params = vit_params
    ledger = GoodputLedger()
    cost = analytic_train_step_cost(params, batch_size=8, image_size=32)
    publish_cost_gauges(
        ledger, cost, peak_flops=CPU_FAKE_PEAK_FLOPS, peak_source="cpu-fake"
    )
    mfu = publish_mfu_gauges(
        ledger, step_flops=cost.flops, peak_flops=CPU_FAKE_PEAK_FLOPS,
        steps=10, step_seconds=2.0,
    )
    flat = ledger.flat_metrics()
    assert flat["goodput/mfu"] == pytest.approx(mfu, abs=1e-6)  # 6dp rounding
    assert flat["goodput/flops_per_s"] == pytest.approx(cost.flops * 5)
    assert flat["goodput/peak_flops_is_fake"] == 1.0
    assert flat["goodput/flops/ffn_frac"] > 0
    # Unreportable cases return None and publish no mfu gauge.
    empty = GoodputLedger()
    assert publish_mfu_gauges(
        empty, step_flops=0.0, peak_flops=1e12, steps=5, step_seconds=1.0
    ) is None
    assert "goodput/mfu" not in empty.flat_metrics()


# ----------------------------------------------------- trainer integration


def test_fit_reports_mfu_and_attribution_in_goodput_and_manifest(
    tmp_path, devices
):
    """ISSUE 4 acceptance: a CPU fit() produces goodput/mfu, per-group
    FLOPs attribution, and a manifest carrying both."""
    from sav_tpu.data import fake_data_iterator
    from sav_tpu.models import create_model
    from sav_tpu.obs.manifest import RunManifest
    from sav_tpu.train import TrainConfig, Trainer

    config = TrainConfig(
        model_name="vit_ti_patch16", num_classes=10, image_size=32,
        compute_dtype="float32", global_batch_size=8, num_train_images=32,
        num_epochs=1, warmup_epochs=1, lr_scaling_divisor=8,
        transpose_images=False, log_every_steps=2, log_dir=str(tmp_path),
        seed=0,
    )
    model = create_model(
        config.model_name, num_classes=10, dtype=jnp.float32,
        num_layers=2, embed_dim=64, num_heads=4,
    )
    trainer = Trainer(config, model=model)
    manifest = RunManifest(str(tmp_path / "manifest.json"), kind="train")
    manifest.begin()
    data = fake_data_iterator(batch_size=8, image_size=32, num_classes=10)
    _, history = trainer.fit(data, num_steps=4, manifest=manifest)
    manifest.finalize("ok", exit_code=0)

    gauges = trainer.last_goodput["gauges"]
    assert 0.0 < gauges["mfu"] < 1.0
    assert gauges["peak_flops_is_fake"] == 1.0
    assert gauges["flops/ffn_frac"] > 0
    # Per-window mfu rides the logged step metrics too.
    assert any("mfu" in m for m in history if "loss" in m)

    doc = RunManifest.load(manifest.path)
    assert doc["outcome"] == "ok"
    assert 0.0 < doc["metrics"]["goodput/mfu"] < 1.0
    attrib = [k for k in doc["metrics"] if k.startswith("goodput/flops/")]
    assert len(attrib) >= 5
    note = doc["notes"]["cost_model"]
    assert note["source"] == "analytic"  # CPU keeps the jit path (no AOT)
    assert note["peak_flops_source"] == "cpu-fake"
    assert doc["notes"]["backend"]["platform"] == "cpu"


def test_fit_crash_path_still_lands_cost_metrics_in_manifest(
    tmp_path, devices
):
    """A mid-run exception must leave a manifest that says where the
    FLOPs were going — fit()'s finally publishes before unwinding."""
    from sav_tpu.models import create_model
    from sav_tpu.obs.manifest import RunManifest, classify_exception
    from sav_tpu.train import TrainConfig, Trainer

    config = TrainConfig(
        model_name="vit_ti_patch16", num_classes=10, image_size=32,
        compute_dtype="float32", global_batch_size=8, num_train_images=32,
        num_epochs=1, warmup_epochs=1, lr_scaling_divisor=8,
        transpose_images=False, log_every_steps=2, log_dir=str(tmp_path),
        async_feed=False, seed=0,
    )
    model = create_model(
        config.model_name, num_classes=10, dtype=jnp.float32,
        num_layers=2, embed_dim=64, num_heads=4,
    )
    trainer = Trainer(config, model=model)
    manifest = RunManifest(str(tmp_path / "manifest.json"), kind="train")
    manifest.begin()

    def poisoned():
        import numpy as np

        rng = np.random.default_rng(0)
        yield {
            "images": rng.standard_normal((8, 32, 32, 3)).astype("float32"),
            "labels": rng.integers(0, 10, (8,), "int32"),
        }
        raise RuntimeError("data source died")

    with pytest.raises(RuntimeError, match="data source died"):
        try:
            trainer.fit(poisoned(), num_steps=4, manifest=manifest)
        except BaseException as e:
            manifest.finalize(classify_exception(e), error=repr(e))
            raise
    doc = RunManifest.load(manifest.path)
    assert doc["outcome"] == "error"
    assert doc["metrics"]["goodput/flops/ffn_frac"] > 0
    assert doc["notes"]["cost_model"]["source"] == "analytic"
