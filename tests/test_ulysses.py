"""Ulysses (all-to-all) sequence parallelism vs dense XLA attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sav_tpu.ops import xla_attention
from sav_tpu.parallel import create_mesh
from sav_tpu.parallel.ulysses import ulysses_attention



# Entire module is the expensive tier: mesh/kernel-heavy numerics sweeps.
pytestmark = pytest.mark.slow

def _qkv(b=2, l=256, h=8, d=32, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return tuple(jax.random.normal(k, (b, l, h, d), dtype) for k in ks)


def test_ulysses_matches_dense(devices):
    mesh = create_mesh({"seq": 8})
    q, k, v = _qkv()
    ref = xla_attention(q, k, v)
    out = ulysses_attention(q, k, v, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ulysses_with_batch_axis(devices):
    mesh = create_mesh({"data": 2, "seq": 4})
    q, k, v = _qkv(b=4, l=128)
    ref = xla_attention(q, k, v)
    out = ulysses_attention(q, k, v, mesh=mesh, batch_axis="data")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ulysses_gradients_match(devices):
    mesh = create_mesh({"seq": 4}, devices=jax.devices()[:4])
    q, k, v = _qkv(l=64)

    def loss_ulysses(q, k, v):
        return jnp.sum(jnp.square(ulysses_attention(q, k, v, mesh=mesh)))

    def loss_dense(q, k, v):
        return jnp.sum(jnp.square(xla_attention(q, k, v)))

    gu = jax.grad(loss_ulysses, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gu, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-4)


def test_ulysses_sharded_inputs_stay_sharded(devices):
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = create_mesh({"seq": 8})
    q, k, v = _qkv(b=1, l=1024, h=8, d=64)
    sh = NamedSharding(mesh, P(None, "seq", None, None))
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
    out = jax.jit(lambda q, k, v: ulysses_attention(q, k, v, mesh=mesh))(qs, ks, vs)
    assert out.sharding.spec == P(None, "seq", None, None)
    ref = xla_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=5e-5, rtol=5e-5)


def test_ulysses_rejects_indivisible_heads(devices):
    mesh = create_mesh({"seq": 8})
    q, k, v = _qkv(h=4)
    with pytest.raises(ValueError, match="head count"):
        ulysses_attention(q, k, v, mesh=mesh)


def test_ulysses_rejects_indivisible_length(devices):
    mesh = create_mesh({"seq": 8})
    q, k, v = _qkv(l=100)
    with pytest.raises(ValueError, match="not divisible"):
        ulysses_attention(q, k, v, mesh=mesh)


def test_ulysses_bf16(devices):
    mesh = create_mesh({"seq": 4}, devices=jax.devices()[:4])
    q, k, v = _qkv(l=128, dtype=jnp.bfloat16)
    ref = xla_attention(q, k, v)
    out = ulysses_attention(q, k, v, mesh=mesh)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2, rtol=3e-2
    )


def test_ulysses_flash_backend_matches_dense(devices):
    import numpy as np
    from sav_tpu.parallel import create_mesh

    mesh = create_mesh({"seq": 4}, devices=jax.devices()[:4])
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (2, 128, 4, 32)) for kk in ks)
    ref = xla_attention(q, k, v)
    out = ulysses_attention(q, k, v, mesh=mesh, backend="pallas")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def loss(fn):
        return lambda q, k, v: jnp.sum(jnp.square(fn(q, k, v)))

    gu = jax.grad(
        loss(lambda q, k, v: ulysses_attention(q, k, v, mesh=mesh, backend="pallas")),
        argnums=(0, 1, 2),
    )(q, k, v)
    gd = jax.grad(loss(xla_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gu, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=5e-4)
