"""SavRecord native dataset format: roundtrip, sharding, epoch iteration."""

import subprocess
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module", autouse=True)
def built_lib():
    try:
        subprocess.run(
            ["make", "-C", str(REPO / "native")], check=True, capture_output=True
        )
    except (subprocess.CalledProcessError, FileNotFoundError):
        pass  # fallback reader still covers the format


from sav_tpu.data.records import (  # noqa: E402
    SavRecDataset,
    host_shard_indices,
    savrec_epoch_iterator,
    write_savrec,
)


@pytest.fixture()
def recfile(tmp_path):
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (37, 8, 8, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, (37,), dtype=np.int32)
    path = str(tmp_path / "data.savrec")
    write_savrec(path, images, labels)
    return path, images, labels


def test_roundtrip(recfile):
    path, images, labels = recfile
    ds = SavRecDataset(path)
    assert len(ds) == 37 and ds.image_shape == (8, 8, 3)
    batch = ds.read_batch(np.asarray([0, 5, 36, 5]))
    np.testing.assert_array_equal(batch["images"], images[[0, 5, 36, 5]])
    np.testing.assert_array_equal(batch["labels"], labels[[0, 5, 36, 5]])
    ds.close()


def test_native_and_fallback_agree(recfile, monkeypatch):
    path, images, labels = recfile
    ds_native = SavRecDataset(path)
    # Force the fallback by pretending the library is absent.
    from sav_tpu.data import native_loader as nl

    monkeypatch.setattr(nl, "_load", lambda: None)
    ds_py = SavRecDataset(path)
    assert not ds_py.native
    idx = np.asarray([3, 1, 4, 1, 5, 9, 2, 6])
    a, b = ds_native.read_batch(idx), ds_py.read_batch(idx)
    np.testing.assert_array_equal(a["images"], b["images"])
    np.testing.assert_array_equal(a["labels"], b["labels"])
    ds_native.close()


def test_out_of_range_raises(recfile):
    path, _, _ = recfile
    ds = SavRecDataset(path)
    with pytest.raises(IndexError):
        ds.read_batch(np.asarray([0, 37]))
    with pytest.raises(IndexError):
        ds.read_batch(np.asarray([-1]))
    ds.close()


def test_rejects_garbage(tmp_path):
    bad = tmp_path / "bad.savrec"
    bad.write_bytes(b"not a savrec file at all, definitely not" * 4)
    with pytest.raises(ValueError, match="SavRecord"):
        SavRecDataset(str(bad))


def test_host_sharding_partitions():
    shards = [host_shard_indices(103, h, 4) for h in range(4)]
    allidx = np.concatenate(shards)
    assert len(allidx) == 103
    np.testing.assert_array_equal(np.sort(allidx), np.arange(103))
    # Matches the reference's np.array_split semantics: sizes differ by ≤1.
    sizes = [len(s) for s in shards]
    assert max(sizes) - min(sizes) <= 1


def test_epoch_iterator_determinism_and_coverage(recfile):
    path, _, labels = recfile
    ds = SavRecDataset(path)

    def epoch_order(start_epoch):
        it = savrec_epoch_iterator(
            ds, batch_size=4, seed=7, num_epochs=1, start_epoch=start_epoch,
            drop_remainder=False,
        )
        return np.concatenate([b["labels"] for b in it])

    a, b = epoch_order(0), epoch_order(0)
    np.testing.assert_array_equal(a, b)  # same (seed, epoch) → same order
    c = epoch_order(1)
    assert not np.array_equal(a, c)  # next epoch reshuffles
    # Full coverage without remainder dropping.
    np.testing.assert_array_equal(np.sort(a), np.sort(labels))
    ds.close()


def test_epoch_iterator_host_disjoint(recfile):
    path, _, _ = recfile
    ds = SavRecDataset(path)
    seen = []
    for host in range(2):
        it = savrec_epoch_iterator(
            ds, batch_size=4, shuffle=False, num_epochs=1,
            host_id=host, host_count=2, drop_remainder=True,
        )
        seen.append(np.concatenate([b["images"].reshape(len(b["labels"]), -1)
                                    for b in it]))
    # No record appears on both hosts (images are random → compare bytes).
    a = {row.tobytes() for row in seen[0]}
    b = {row.tobytes() for row in seen[1]}
    assert not (a & b)
    ds.close()


def test_savrec_train_iterator_end_to_end(recfile, devices):
    """SavRecord → native normalize/flip → Trainer.train_step runs."""
    import jax

    from sav_tpu.data.records import savrec_train_iterator
    from sav_tpu.models import create_model
    from sav_tpu.train import TrainConfig, Trainer

    path, _, _ = recfile
    ds = SavRecDataset(path)
    it = savrec_train_iterator(
        ds, batch_size=8, seed=0, drop_remainder=True, num_epochs=None
    )
    batch = next(it)
    assert batch["images"].dtype == np.float32
    assert batch["images"].shape == (8, 8, 8, 3)

    config = TrainConfig(
        model_name="vit_ti_patch16", num_classes=10, image_size=8,
        compute_dtype="float32", global_batch_size=8, num_train_images=32,
        num_epochs=2, warmup_epochs=1, transpose_images=False, seed=0,
    )
    model = create_model("vit_ti_patch16", num_classes=10, num_layers=2,
                         embed_dim=32, num_heads=2, patch_shape=(4, 4))
    trainer = Trainer(config, model=model)
    state = trainer.init_state()
    state, metrics = trainer.train_step(state, batch, jax.random.PRNGKey(0))
    assert np.isfinite(float(jax.device_get(metrics["loss"])))
    ds.close()


def test_small_shard_raises_instead_of_spinning(recfile):
    path, _, _ = recfile
    ds = SavRecDataset(path)
    with pytest.raises(ValueError, match="no batch"):
        next(savrec_epoch_iterator(ds, batch_size=64, host_id=0, host_count=1))
    ds.close()


def test_corrupt_num_records_rejected(tmp_path, recfile):
    """Huge num_records must fail open, not segfault on read (overflow guard)."""
    path, _, _ = recfile
    data = bytearray(open(path, "rb").read())
    import struct as _s
    _s.pack_into("<Q", data, 0x10, 1 << 61)
    bad = tmp_path / "corrupt.savrec"
    bad.write_bytes(data)
    with pytest.raises(ValueError, match="SavRecord"):
        SavRecDataset(str(bad))


def test_header_only_file_rejected(tmp_path, recfile):
    """A valid 0x28-byte header with no offsets table must fail open.

    Regression test for the native truncation guard: with zero u64 slots
    after the header, ``avail - 1`` underflowed and the guard passed, so
    ``offsets[num_records]`` read far past the mapping (ADVICE round 1).
    """
    path, _, _ = recfile
    header = open(path, "rb").read()[:0x28]
    for n in (0, 1, 7):
        data = bytearray(header)
        import struct as _s
        _s.pack_into("<Q", data, 0x10, n)
        bad = tmp_path / f"header_only_{n}.savrec"
        bad.write_bytes(data)
        with pytest.raises(ValueError, match="SavRecord"):
            SavRecDataset(str(bad))


def test_corrupt_offsets_rejected(tmp_path, recfile):
    path, _, _ = recfile
    data = bytearray(open(path, "rb").read())
    import struct as _s
    _s.pack_into("<Q", data, 0x28 + 8 * 3, 1 << 40)  # offsets[3] wild
    bad = tmp_path / "corrupt2.savrec"
    bad.write_bytes(data)
    with pytest.raises(ValueError, match="SavRecord"):
        SavRecDataset(str(bad))


def test_short_file_raises_valueerror_in_fallback(tmp_path, monkeypatch):
    from sav_tpu.data import native_loader as nl

    monkeypatch.setattr(nl, "_load", lambda: None)
    short = tmp_path / "short.savrec"
    short.write_bytes(b"xy")
    with pytest.raises(ValueError, match="SavRecord"):
        SavRecDataset(str(short))


def test_train_iterator_resume_replays_epoch(recfile):
    """start_epoch=e replays epoch e bit-exactly (shuffle AND flips)."""
    from sav_tpu.data.records import savrec_train_iterator

    path, _, _ = recfile
    ds = SavRecDataset(path)

    def epoch_batches(start, count):
        it = savrec_train_iterator(
            ds, batch_size=8, seed=3, start_epoch=start, num_epochs=count,
            normalize=False,
        )
        return [b["images"] for b in it]

    continuous = epoch_batches(0, 2)
    resumed = epoch_batches(1, 1)
    per_epoch = len(continuous) // 2
    for a, b in zip(continuous[per_epoch:], resumed):
        np.testing.assert_array_equal(a, b)
    ds.close()


def test_tfrecords_to_savrec_converter(tmp_path):
    """tools/tfrecords_to_savrec.py: ImageNet-layout TFRecords (JPEG bytes +
    label) convert to a SavRecord the native loader reads back, labels
    intact and pixels within JPEG+resize tolerance of the source."""
    tf = pytest.importorskip("tensorflow")
    import sys

    rng = np.random.default_rng(3)
    n, size = 5, 16
    # Smooth gradients, not noise: JPEG mangles white noise even at q100,
    # which would test the codec rather than the converter.
    ramp = np.linspace(0, 255, size)
    base = ramp[None, :, None] * 0.5 + ramp[None, None, :] * 0.5  # [1,H,W]
    phase = rng.uniform(0, 100, (n, 1, 1, 3))
    images = np.clip(base[..., None] * 0.8 + phase, 0, 255).astype(np.uint8)
    labels = rng.integers(0, 10, (n,), dtype=np.int64)
    tfr = str(tmp_path / "train-00000")
    with tf.io.TFRecordWriter(tfr) as w:
        for img, lab in zip(images, labels):
            ex = tf.train.Example(
                features=tf.train.Features(
                    feature={
                        "image/encoded": tf.train.Feature(
                            bytes_list=tf.train.BytesList(
                                value=[tf.io.encode_jpeg(img, quality=100).numpy()]
                            )
                        ),
                        "image/class/label": tf.train.Feature(
                            int64_list=tf.train.Int64List(value=[int(lab)])
                        ),
                    }
                )
            )
            w.write(ex.SerializeToString())

    out = str(tmp_path / "train.savrec")
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "tfrecords_to_savrec.py"),
         "--tfrecords", tfr, "--out", out, "--image-size", str(size)],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, f"converter failed:\n{proc.stderr}"
    ds = SavRecDataset(out)
    assert len(ds) == n
    batch = ds.read_batch(np.arange(n))
    np.testing.assert_array_equal(batch["labels"], labels.astype(np.int32))
    # Same size in/out -> resize is ~identity; only JPEG quantization remains.
    err = np.abs(batch["images"].astype(np.int32) - images.astype(np.int32))
    assert np.median(err) <= 12, f"median pixel error {np.median(err)}"


def test_fallback_validates_corruption_too(tmp_path, recfile, monkeypatch):
    from sav_tpu.data import native_loader as nl

    monkeypatch.setattr(nl, "_load", lambda: None)
    path, _, _ = recfile
    import struct as _s
    for offset, value in ((0x10, 1 << 61), (0x28 + 8 * 3, 1 << 40)):
        data = bytearray(open(path, "rb").read())
        _s.pack_into("<Q", data, offset, value)
        bad = tmp_path / f"fb_{offset}.savrec"
        bad.write_bytes(data)
        with pytest.raises(ValueError, match="SavRecord"):
            SavRecDataset(str(bad))
