"""DepthwiseConv2D (shifted-FMA depthwise) must be a drop-in for the
grouped ``nn.Conv`` it replaced: identical param tree, identical numerics,
identical SAME/stride geometry — the TPU compiler pathology it avoids is
documented in layers/depthwise.py."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import flax.linen as nn

from sav_tpu.models.layers.depthwise import DepthwiseConv2D


@pytest.mark.parametrize(
    "h,w,c,k,s",
    [
        (28, 28, 64, 3, 1),  # CvT stage grid, q projection
        (28, 28, 64, 3, 2),  # CvT k/v projection (strided)
        (14, 14, 192, 5, 1),  # LeFF 5x5
        (9, 11, 32, 3, 2),  # odd sizes: SAME pad asymmetry
    ],
)
def test_matches_grouped_conv(h, w, c, k, s):
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((2, h, w, c)), jnp.float32
    )
    ref = nn.Conv(
        features=c, kernel_size=(k, k), strides=(s, s), padding="SAME",
        feature_group_count=c, use_bias=False,
    )
    ours = DepthwiseConv2D(features=c, kernel_size=(k, k), stride=s)
    vref = ref.init(jax.random.PRNGKey(1), x)
    yref = ref.apply(vref, x)
    # Same param tree by construction: reuse the conv's kernel verbatim.
    yours = ours.apply({"params": {"kernel": vref["params"]["kernel"]}}, x)
    assert yref.shape == yours.shape
    np.testing.assert_allclose(np.asarray(yours), np.asarray(yref), atol=1e-4)


def test_param_layout_matches_grouped_conv():
    x = jnp.zeros((1, 8, 8, 16), jnp.float32)
    conv = nn.Conv(
        features=16, kernel_size=(3, 3), padding="SAME",
        feature_group_count=16, use_bias=False,
    ).init(jax.random.PRNGKey(0), x)
    ours = DepthwiseConv2D(features=16).init(jax.random.PRNGKey(0), x)
    assert (
        jax.tree_util.tree_structure(conv) == jax.tree_util.tree_structure(ours)
    )
    assert conv["params"]["kernel"].shape == ours["params"]["kernel"].shape


def test_gradients_flow():
    x = jnp.asarray(
        np.random.default_rng(1).standard_normal((2, 8, 8, 4)), jnp.float32
    )
    mod = DepthwiseConv2D(features=4)
    v = mod.init(jax.random.PRNGKey(0), x)
    g = jax.grad(lambda p: jnp.sum(mod.apply({"params": p}, x) ** 2))(v["params"])
    assert np.isfinite(np.asarray(g["kernel"])).all()
    assert float(jnp.max(jnp.abs(g["kernel"]))) > 0
