"""Goodput ledger (sav_tpu/obs/goodput.py): bucket accounting, the
buckets-sum-to-wall invariant, and per-window stall anomaly detection —
all on an injected fake clock so the tests are deterministic."""

import pytest

from sav_tpu.obs.goodput import BUCKETS, GoodputLedger


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


@pytest.fixture
def clock():
    return FakeClock()


def test_unknown_bucket_rejected(clock):
    ledger = GoodputLedger(clock=clock)
    with pytest.raises(KeyError):
        ledger.account("naps", 1.0)


def test_measure_accounts_wall_time(clock):
    ledger = GoodputLedger(clock=clock)
    with ledger.measure("input_wait"):
        clock.advance(2.5)
    assert ledger.summary()["buckets_s"]["input_wait"] == pytest.approx(2.5)


def test_buckets_sum_to_wall_time(clock):
    ledger = GoodputLedger(clock=clock)
    with ledger.measure("compile"):
        clock.advance(30.0)
    for _ in range(10):
        with ledger.measure("input_wait"):
            clock.advance(0.5)
        clock.advance(1.0)  # unaccounted loop overhead
        clock.advance(4.0)  # the window's step time...
        ledger.note_window(10, 4.0)  # ...attributed at the log boundary
    with ledger.measure("eval"):
        clock.advance(8.0)
    with ledger.measure("checkpoint"):
        clock.advance(3.0)
    s = ledger.summary()
    total = sum(s["buckets_s"].values())
    assert total == pytest.approx(s["wall_s"], rel=0.05)
    # 10 windows x 1.0s advanced outside any bucket -> the residual.
    assert s["buckets_s"]["other"] == pytest.approx(10.0, rel=1e-6)
    assert s["steps"] == 100


def test_fractions_and_goodput_fraction(clock):
    ledger = GoodputLedger(clock=clock)
    with ledger.measure("step"):
        clock.advance(75.0)
    with ledger.measure("compile"):
        clock.advance(25.0)
    s = ledger.summary()
    assert s["goodput_fraction"] == pytest.approx(0.75)
    assert s["fractions"]["compile"] == pytest.approx(0.25)
    assert set(s["buckets_s"]) == set(BUCKETS)


def test_stall_window_is_flagged_and_split(clock):
    ledger = GoodputLedger(clock=clock, stall_factor=5.0)
    for i in range(5):
        assert not ledger.note_window(10, 1.0, step=(i + 1) * 10)
    # 10x the 0.1 s/step median: anomalous. Expected share -> step,
    # excess -> stall.
    assert ledger.note_window(10, 10.0, step=60)
    s = ledger.summary()
    assert s["num_anomalies"] == 1
    (anomaly,) = s["anomalies"]
    assert anomaly["step"] == 60
    assert anomaly["slowdown"] == pytest.approx(10.0)
    assert s["buckets_s"]["stall"] == pytest.approx(9.0)
    assert s["buckets_s"]["step"] == pytest.approx(5 * 1.0 + 1.0)


def test_stalled_window_does_not_poison_median(clock):
    ledger = GoodputLedger(clock=clock, stall_factor=5.0)
    for _ in range(4):
        ledger.note_window(10, 1.0)
    ledger.note_window(10, 100.0)  # massive stall
    # Back to normal: must NOT be flagged as anomalously *fast* or slow —
    # the stalled window stayed out of the rolling median.
    assert not ledger.note_window(10, 1.0)
    assert ledger.summary()["median_step_s"] == pytest.approx(0.1)


def test_first_window_never_anomalous(clock):
    ledger = GoodputLedger(clock=clock)
    assert not ledger.note_window(10, 1000.0)
    assert ledger.summary()["num_anomalies"] == 0


def test_flat_metrics_are_scalar_floats(clock):
    ledger = GoodputLedger(clock=clock)
    with ledger.measure("step"):
        clock.advance(1.0)
    flat = ledger.flat_metrics()
    assert flat["goodput/step_s"] == pytest.approx(1.0)
    for key, value in flat.items():
        assert key.startswith("goodput/")
        assert isinstance(value, float) or isinstance(value, int)


def test_zero_step_window_ignored(clock):
    ledger = GoodputLedger(clock=clock)
    assert not ledger.note_window(0, 5.0)
    assert ledger.summary()["steps"] == 0
