"""Span tracer (sav_tpu/obs/spans.py): Chrome-trace-event JSON
well-formedness and the disabled-tracer no-op contract."""

import json
import threading

from sav_tpu.obs.spans import SpanTracer


def test_disabled_tracer_is_noop(tmp_path):
    tracer = SpanTracer(None)
    with tracer.span("anything"):
        pass
    tracer.instant("marker")
    assert tracer.write() is None
    assert not tracer.enabled


def test_trace_file_is_perfetto_loadable_json(tmp_path):
    path = str(tmp_path / "spans.trace.json")
    tracer = SpanTracer(path)
    with tracer.span("batch_fetch", step=1):
        pass
    with tracer.span("step_dispatch", step=1):
        with tracer.span("inner"):
            pass
    tracer.instant("stall_anomaly", step=1)
    assert tracer.write() == path

    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    complete = [e for e in events if e.get("ph") == "X"]
    assert {e["name"] for e in complete} == {
        "batch_fetch", "step_dispatch", "inner"
    }
    for e in complete:
        # The Trace Event Format's required complete-event fields.
        assert set(e) >= {"name", "ph", "ts", "dur", "pid", "tid"}
        assert e["dur"] >= 0
        assert e["ts"] >= 0
    instants = [e for e in events if e.get("ph") == "i"]
    assert instants and instants[0]["name"] == "stall_anomaly"
    assert instants[0]["args"] == {"step": 1}


def test_nested_span_ordering(tmp_path):
    path = str(tmp_path / "t.json")
    tracer = SpanTracer(path)
    with tracer.span("outer"):
        with tracer.span("inner"):
            pass
    tracer.write()
    with open(path) as f:
        events = {
            e["name"]: e for e in json.load(f)["traceEvents"]
            if e.get("ph") == "X"
        }
    outer, inner = events["outer"], events["inner"]
    assert outer["ts"] <= inner["ts"]
    assert outer["ts"] + outer["dur"] >= inner["ts"] + inner["dur"]


def test_span_records_on_exception(tmp_path):
    path = str(tmp_path / "t.json")
    tracer = SpanTracer(path)
    try:
        with tracer.span("failing"):
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    tracer.write()
    with open(path) as f:
        names = {
            e["name"] for e in json.load(f)["traceEvents"]
            if e.get("ph") == "X"
        }
    assert "failing" in names


def test_write_is_idempotent_after_midrun_exception(tmp_path):
    """Crash-path contract (ISSUE 4): a loop that flushes periodically and
    then dies mid-run leaves a valid, loadable Chrome trace — and a later
    flush (e.g. from an exception handler) is safe and wins."""
    path = str(tmp_path / "t.json")
    tracer = SpanTracer(path)
    with tracer.span("step_dispatch", step=1):
        pass
    assert tracer.write() == path  # periodic flush mid-run
    with open(path) as f:
        first = json.load(f)["traceEvents"]
    try:
        with tracer.span("step_dispatch", step=2):
            raise RuntimeError("mid-run crash")
    except RuntimeError:
        pass
    # Second write after the exception: still valid JSON, strictly more
    # events (the crashed span was recorded by the context manager), and
    # repeatable.
    assert tracer.write() == path
    assert tracer.write() == path
    with open(path) as f:
        doc = json.load(f)
    events = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert len(events) > len([e for e in first if e.get("ph") == "X"])
    steps = {e.get("args", {}).get("step") for e in events}
    assert {1, 2} <= steps
    for e in events:
        assert set(e) >= {"name", "ph", "ts", "dur", "pid", "tid"}


def test_concurrent_spans_are_thread_safe(tmp_path):
    path = str(tmp_path / "t.json")
    tracer = SpanTracer(path)

    def worker():
        for _ in range(50):
            with tracer.span("w"):
                pass

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    tracer.write()
    with open(path) as f:
        events = [
            e for e in json.load(f)["traceEvents"] if e.get("ph") == "X"
        ]
    assert len(events) == 200


def test_write_creates_parent_dirs(tmp_path):
    path = str(tmp_path / "deep" / "nested" / "spans.trace.json")
    tracer = SpanTracer(path)
    with tracer.span("s"):
        pass
    assert tracer.write() == path
    with open(path) as f:
        json.load(f)
