"""Regression sentinel (ISSUE 4): the tier-1 smoke over the checked-in
fixture histories pins the CI exit-code contract — 0 on a clean history,
1 on the planted throughput/MFU regression, 0 when the only deltas are
infra failures, 2 on usage/IO errors — plus unit coverage of the
median+MAD math and the record normalization it stands on."""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

from sav_tpu.obs.manifest import load_run_history, normalize_run_record

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(__file__), "sentinel_fixtures")
SENTINEL = os.path.join(ROOT, "tools", "regression_sentinel.py")


def _load_sentinel():
    spec = importlib.util.spec_from_file_location("regression_sentinel", SENTINEL)
    module = importlib.util.module_from_spec(spec)
    # Registered BEFORE exec: dataclasses resolves the module's postponed
    # annotations through sys.modules.
    sys.modules[spec.name] = module
    spec.loader.exec_module(module)
    return module


sentinel = _load_sentinel()


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, SENTINEL, *args],
        capture_output=True, text=True, cwd=ROOT,
    )


# ------------------------------------------------------ exit-code contract


def test_clean_history_exits_zero():
    proc = _run_cli(os.path.join(FIXTURES, "clean"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "REGRESS" not in proc.stdout


def test_planted_regression_exits_one_and_names_the_metrics():
    proc = _run_cli(os.path.join(FIXTURES, "regressed"))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    # The planted drop hits throughput AND mfu; input_wait stays clean.
    assert "REGRESS throughput" in proc.stdout
    assert "REGRESS mfu" in proc.stdout
    assert "REGRESS input_wait_frac" not in proc.stdout


def test_infra_failures_only_exits_zero_but_lists_them():
    """The BENCH_r05 lesson: a down relay is not a regression. Records
    with rc != 0 / parsed: null are reported, never scored."""
    proc = _run_cli(os.path.join(FIXTURES, "infra_only"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "2 infra failures" in proc.stdout
    assert "backend_unreachable" in proc.stdout
    assert "REGRESS" not in proc.stdout


def test_nonfinite_outcome_is_listed_but_never_scored():
    """ISSUE 5: a diverged (NaN) run's throughput is not a measurement.
    The nonfinite fixture's latest record carries outcome: nonfinite (a
    bench that planted an incident bundle); the sentinel must list it as
    an infra-style failure and score only the healthy history — exit 0."""
    proc = _run_cli(os.path.join(FIXTURES, "nonfinite"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "1 infra failures" in proc.stdout
    assert "nonfinite" in proc.stdout
    assert "REGRESS" not in proc.stdout
    # And the record normalizes with empty metrics (never averaged).
    path = os.path.join(FIXTURES, "nonfinite", "BENCH_r04.json")
    with open(path) as f:
        record = normalize_run_record(json.load(f), label="r04")
    assert record.outcome == "nonfinite"
    assert not record.ok
    assert record.metrics == {}


def test_usage_and_io_errors_exit_two(tmp_path):
    assert _run_cli().returncode == 2  # no inputs
    assert _run_cli("/no/such/file.json").returncode == 2
    assert _run_cli("--metric", "nope", os.path.join(FIXTURES, "clean")
                    ).returncode == 2
    torn = tmp_path / "BENCH_torn.json"
    torn.write_text('{"rc": 0, "parsed"')  # torn tail of a crashed write
    assert _run_cli(str(torn)).returncode == 2


def test_json_report_is_machine_readable():
    proc = _run_cli("--json", os.path.join(FIXTURES, "regressed"))
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["regressed"] is True
    regressed = {v["metric"] for v in payload["verdicts"] if v["regressed"]}
    assert regressed == {"throughput", "mfu"}


def test_quant_history_scores_under_quant_names_and_stays_isolated():
    """ISSUE 17: serve_bench --quant-weights lines carry quant="int8"
    and score under the quant_* metric names — an int8-only history.
    The float serve line planted at the head of both fixtures must
    neither flag nor be flagged: the plain serve metrics are simply
    unscorable there (one measurement), proving the histories never
    mix."""
    proc = _run_cli(os.path.join(FIXTURES, "quant_clean"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ok      quant_p99_latency_ms" in proc.stdout
    assert "ok      quant_serve_throughput" in proc.stdout
    assert "REGRESS" not in proc.stdout
    proc = _run_cli("--json", os.path.join(FIXTURES, "quant_regressed"))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    flagged = {v["metric"] for v in payload["verdicts"] if v["regressed"]}
    assert flagged == {
        "quant_p99_latency_ms", "quant_serve_throughput",
        "quant_slo_hit_frac",
    }
    # The bf16 metrics were never scored at all — the float record is
    # lone history, not baseline, on both fixtures.
    scored = {v["metric"] for v in payload["verdicts"]}
    assert "p99_latency_ms" not in scored
    assert "serve_throughput" not in scored


# --------------------------------------------------------- detection math


def test_mad_threshold_adapts_to_series_noise():
    noisy = [100.0, 120.0, 80.0, 110.0, 90.0]
    quiet = [100.0, 100.5, 99.5, 100.2, 99.8]
    _, _, t_noisy = sentinel.robust_threshold(noisy, k=3.5, rel_floor=0.0)
    _, _, t_quiet = sentinel.robust_threshold(quiet, k=3.5, rel_floor=0.0)
    assert t_noisy > t_quiet > 0


def test_rel_floor_prevents_zero_variance_flagging():
    flat = [100.0] * 5
    _, mad, threshold = sentinel.robust_threshold(flat, k=3.5, rel_floor=0.05)
    assert mad == 0.0
    assert threshold == pytest.approx(5.0)  # 5% of the median, not zero


def test_zero_median_fraction_baseline_does_not_flag_jitter():
    """A perfectly-overlapped history records input_wait_frac 0.0 (the
    ledger rounds fractions to 4 decimals); the relative floor is inert at
    median 0, so the absolute floor must absorb sub-point jitter."""
    def rec(wait_frac):
        return normalize_run_record({
            "value": 1000.0, "unit": "img/s/chip",
            "goodput": {"fractions": {"input_wait": wait_frac}},
        })

    records = [rec(0.0), rec(0.0), rec(0.0), rec(0.0002)]
    verdict = sentinel.judge_metric(
        records, "input_wait_frac", k=3.5, rel_floor=0.05, min_history=2
    )
    assert verdict is not None and not verdict.regressed
    # A real input-side regression (5% of wall blocked) still flags.
    bad = sentinel.judge_metric(
        records[:3] + [rec(0.05)], "input_wait_frac", k=3.5,
        rel_floor=0.05, min_history=2,
    )
    assert bad.regressed


def test_min_history_below_one_is_a_usage_error():
    proc = _run_cli(
        "--min-history", "0", os.path.join(FIXTURES, "clean")
    )
    assert proc.returncode == 2
    assert "min-history" in proc.stderr


def test_judge_metric_directionality():
    def rec(value, ok=True):
        return normalize_run_record(
            {"value": value, "unit": "img/s/chip",
             "goodput": {"fractions": {"input_wait": value / 1e4}}},
        )

    stable = [rec(1000.0), rec(1010.0), rec(990.0)]
    # Higher-is-better: a drop flags, a rise does not.
    drop = sentinel.judge_metric(
        stable + [rec(500.0)], "throughput", k=3.5, rel_floor=0.05,
        min_history=2,
    )
    rise = sentinel.judge_metric(
        stable + [rec(1500.0)], "throughput", k=3.5, rel_floor=0.05,
        min_history=2,
    )
    assert drop.regressed and not rise.regressed
    # Lower-is-better (input_wait_frac): the same records' rising wait flags.
    wait = sentinel.judge_metric(
        stable + [rec(1500.0)], "input_wait_frac", k=3.5, rel_floor=0.05,
        min_history=2,
    )
    assert wait.regressed


def test_attention_core_frac_gates_on_where_time_went():
    """ISSUE 8: traced benches carry the measured attention-core time
    share (bench --trace via obs/traceview.py); a rise flags even when
    throughput noise hides it, and untraced histories are simply not
    scored for it."""
    def rec(frac):
        return normalize_run_record({
            "value": 1000.0, "unit": "img/s/chip",
            "attention_core_frac": frac,
        })

    stable = [rec(0.30), rec(0.31), rec(0.29)]
    rise = sentinel.judge_metric(
        stable + [rec(0.55)], "attention_core_frac", k=3.5,
        rel_floor=0.05, min_history=2,
    )
    assert rise is not None and rise.regressed
    drop = sentinel.judge_metric(
        stable + [rec(0.20)], "attention_core_frac", k=3.5,
        rel_floor=0.05, min_history=2,
    )
    assert drop is not None and not drop.regressed
    # Records without the metric (untraced benches) never enter the
    # series — a mixed history with too few traced runs is unscorable,
    # not wrong.
    untraced = [
        normalize_run_record({"value": 1000.0, "unit": "img/s/chip"})
        for _ in range(4)
    ]
    assert sentinel.judge_metric(
        untraced + [rec(0.9)], "attention_core_frac", k=3.5,
        rel_floor=0.05, min_history=2,
    ) is None
    # And when the NEWEST measurement is untraced, the metric is not
    # scorable either: re-judging an older traced record as 'the
    # candidate' would re-flag a stale value on every later untraced
    # bench (the r8 battery runs traced benches before the headline).
    assert sentinel.judge_metric(
        stable + [rec(0.55)] + untraced[:1], "attention_core_frac",
        k=3.5, rel_floor=0.05, min_history=2,
    ) is None
    assert "attention_core_frac" in sentinel.METRICS


def test_insufficient_history_is_not_scored():
    records = [
        normalize_run_record({"value": 100.0, "unit": "img/s/chip"}),
        normalize_run_record({"value": 10.0, "unit": "img/s/chip"}),
    ]
    assert sentinel.judge_metric(
        records, "throughput", k=3.5, rel_floor=0.05, min_history=2
    ) is None


# ----------------------------------------------------- record normalization


def test_history_orders_by_wrapper_n_not_filename(tmp_path):
    # Filename order disagrees with the run order: 'a.json' is run 9.
    (tmp_path / "a.json").write_text(json.dumps(
        {"n": 9, "rc": 0, "tail": "", "parsed": {"value": 5.0, "unit": "x"}}
    ))
    (tmp_path / "b.json").write_text(json.dumps(
        {"n": 1, "rc": 0, "tail": "", "parsed": {"value": 100.0, "unit": "x"}}
    ))
    records = load_run_history([str(tmp_path / "a.json"), str(tmp_path / "b.json")])
    assert [r.metrics["throughput"] for r in records] == [100.0, 5.0]


def test_real_bench_history_loads_and_separates_infra():
    paths = [
        os.path.join(ROOT, f"BENCH_r0{i}.json") for i in range(1, 6)
    ]
    records = load_run_history(paths)
    outcomes = [r.outcome for r in records]
    assert outcomes[:2] == ["ok", "ok"]
    assert "backend_unreachable" in outcomes  # r04/r05's rc=3 probe abort
    assert all(not r.ok for r in records[2:])
