"""Fused 2-D relative-position flash kernel vs the dense XLA path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sav_tpu.ops import xla_attention
from sav_tpu.ops.flash_attention import (
    compact_to_absolute,
    expand_relative_bias,
    flash_botnet_attention,
)
from sav_tpu.ops.relative import relative_logits_2d



# Slow tier: interpret-mode kernel numerics — the authoritative gate
# is the on-chip zoo sweep (tools/zoo_tpu_check.py, real Mosaic).
pytestmark = pytest.mark.slow

def _inputs(b=2, height=7, width=9, heads=3, d=16, dtype=jnp.float32, seed=0):
    l = height * width
    ks = jax.random.split(jax.random.PRNGKey(seed), 5)
    q, k, v = (jax.random.normal(kk, (b, l, heads, d), dtype) for kk in ks[:3])
    rel_h = jax.random.normal(ks[3], (2 * height - 1, d), dtype) * 0.3
    rel_w = jax.random.normal(ks[4], (2 * width - 1, d), dtype) * 0.3
    return q, k, v, rel_h, rel_w


def _dense_reference(q, k, v, rel_h, rel_w, height, width):
    b, l, heads, d = q.shape
    scale = d**-0.5
    q_grid = jnp.transpose(
        q.reshape(b, height, width, heads, d), (0, 3, 1, 2, 4)
    ) * scale
    bias = relative_logits_2d(q_grid, rel_h, rel_w).reshape(b, heads, l, l)
    return xla_attention(q, k, v, bias=bias, scale=scale)


def test_expand_matches_relative_logits_2d():
    q, _, _, rel_h, rel_w = _inputs()
    b, l, heads, d = q.shape
    height, width = 7, 9
    scale = d**-0.5
    qs = q * scale
    cw = jnp.einsum("blhd,rd->bhlr", qs, rel_w)
    ch = jnp.einsum("blhd,rd->bhlr", qs, rel_h)
    got = expand_relative_bias(*compact_to_absolute(cw, ch, height, width),
                               height, width)
    q_grid = jnp.transpose(
        qs.reshape(b, height, width, heads, d), (0, 3, 1, 2, 4)
    )
    want = relative_logits_2d(q_grid, rel_h, rel_w).reshape(b, heads, l, l)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


def test_fused_matches_dense():
    q, k, v, rel_h, rel_w = _inputs()
    ref = _dense_reference(q, k, v, rel_h, rel_w, 7, 9)
    out = flash_botnet_attention(q, k, v, rel_h, rel_w, 7, 9)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_fused_matches_dense_14x14():
    """BoTNet's real final-stage grid (L=196 → padded blocks exercise masking)."""
    q, k, v, rel_h, rel_w = _inputs(b=1, height=14, width=14, heads=2, d=32)
    ref = _dense_reference(q, k, v, rel_h, rel_w, 14, 14)
    out = flash_botnet_attention(q, k, v, rel_h, rel_w, 14, 14)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_fused_small_blocks():
    q, k, v, rel_h, rel_w = _inputs(height=6, width=5)
    ref = _dense_reference(q, k, v, rel_h, rel_w, 6, 5)
    out = flash_botnet_attention(q, k, v, rel_h, rel_w, 6, 5, block_q=16, block_kv=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_fused_gradients_match():
    q, k, v, rel_h, rel_w = _inputs(b=1, height=5, width=6, heads=2, d=8)

    def loss_fused(q, k, v, rel_h, rel_w):
        return jnp.sum(
            jnp.square(flash_botnet_attention(q, k, v, rel_h, rel_w, 5, 6))
        )

    def loss_dense(q, k, v, rel_h, rel_w):
        return jnp.sum(jnp.square(_dense_reference(q, k, v, rel_h, rel_w, 5, 6)))

    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3, 4))(q, k, v, rel_h, rel_w)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2, 3, 4))(q, k, v, rel_h, rel_w)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-4)


def test_fused_bf16():
    q, k, v, rel_h, rel_w = _inputs(dtype=jnp.bfloat16)
    ref = _dense_reference(q, k, v, rel_h, rel_w, 7, 9)
    out = flash_botnet_attention(q, k, v, rel_h, rel_w, 7, 9)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2, rtol=3e-2
    )


def test_fused_rejects_bad_grid():
    q, k, v, rel_h, rel_w = _inputs()
    with pytest.raises(ValueError, match="height"):
        flash_botnet_attention(q, k, v, rel_h, rel_w, 7, 10)


def test_botmhsa_backends_agree():
    """The module's fused (pallas) and dense (xla) paths match."""
    from sav_tpu.models.layers import BoTMHSA

    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 32))
    outs = {}
    for backend in ("xla", "pallas"):
        block = BoTMHSA(num_heads=4, backend=backend)
        variables = block.init({"params": jax.random.PRNGKey(1)}, x)
        outs[backend] = np.asarray(block.apply(variables, x))
    np.testing.assert_allclose(outs["xla"], outs["pallas"], atol=2e-5, rtol=2e-5)


def test_fused_asymmetric_padded_axes():
    """Grid with one axis past 128: sel matrices must use their own padded
    dims (regression for a rw/rh padding mix-up)."""
    q, k, v, rel_h, rel_w = _inputs(b=1, height=2, width=130, heads=1, d=8)
    ref = _dense_reference(q, k, v, rel_h, rel_w, 2, 130)
    out = flash_botnet_attention(q, k, v, rel_h, rel_w, 2, 130)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_botmhsa_rejects_unknown_backend():
    from sav_tpu.models.layers import BoTMHSA

    x = jnp.zeros((1, 4, 4, 16))
    block = BoTMHSA(num_heads=2, backend="pallsa")
    with pytest.raises(ValueError, match="unknown attention backend"):
        block.init({"params": jax.random.PRNGKey(0)}, x)


def test_fused_blocked_backward_multiblock_padded():
    """Gradients through the blocked Pallas backward with several kv/q
    blocks and padded rows/cols (L=196, blocks of 64 → 196↛256 masking,
    cross-block d_rw/d_rh accumulation)."""
    q, k, v, rel_h, rel_w = _inputs(b=1, height=14, width=14, heads=2, d=16)

    def loss_fused(q, k, v, rel_h, rel_w):
        return jnp.sum(jnp.square(flash_botnet_attention(
            q, k, v, rel_h, rel_w, 14, 14, block_q=64, block_kv=64
        )))

    def loss_dense(q, k, v, rel_h, rel_w):
        return jnp.sum(
            jnp.square(_dense_reference(q, k, v, rel_h, rel_w, 14, 14))
        )

    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3, 4))(q, k, v, rel_h, rel_w)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2, 3, 4))(q, k, v, rel_h, rel_w)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=5e-4
        )


def test_fused_blocked_backward_bf16_finite():
    q, k, v, rel_h, rel_w = _inputs(
        b=1, height=7, width=9, heads=2, d=16, dtype=jnp.bfloat16
    )

    def loss(q, k, v, rel_h, rel_w):
        return jnp.sum(jnp.square(
            flash_botnet_attention(q, k, v, rel_h, rel_w, 7, 9).astype(
                jnp.float32
            )
        ))

    grads = jax.grad(loss, argnums=(0, 1, 2, 3, 4))(q, k, v, rel_h, rel_w)
    for g, primal in zip(grads, (q, k, v, rel_h, rel_w)):
        assert g.dtype == primal.dtype
        assert np.all(np.isfinite(np.asarray(g, np.float32)))
