"""tools/mesh_tune.py end-to-end on the CPU mesh: candidates
enumerated + ranked, infeasible configs recorded (never fatal), top-K
measured with the Trap-pinned scan loop, a preset emitted — and the
preset consumed by the trainer, closing the ISSUE-13 loop on CPU before
the on-chip battery round (tools/battery/r13.steps) proves it at chip
step times."""

import importlib.util
import json
import os
import sys

import jax
import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", f"{name}.py")
    )
    module = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = module
    spec.loader.exec_module(module)
    return module


@pytest.fixture(scope="module")
def mesh_tune():
    return _load_tool("mesh_tune")


# ------------------------------------------------------------- enumeration


def test_enumerate_layouts_covers_the_arms(mesh_tune):
    layouts = mesh_tune.enumerate_layouts(8, ["dp", "tp", "2d", "fsdp"])
    names = {l.name for l in layouts}
    assert "dp" in names
    assert {"tp2", "tp4", "tp8"} <= names
    assert {"2d2x2", "2d2x4", "2d4x2"} <= names
    assert {"fsdp2", "fsdp4", "fsdp8"} <= names
    # Every candidate states a fully explicit mesh over exactly 8 devices.
    for layout in layouts:
        sizes = list(layout.axis_dict().values())
        assert -1 not in sizes
        assert int(np.prod(sizes)) == 8


def test_check_feasible_divisibility(mesh_tune):
    from sav_tpu.parallel.layout import layout_from_mesh_axes

    params = {
        "to_qkv": {
            "kernel": jax.ShapeDtypeStruct((64, 3, 4, 16), jax.numpy.float32)
        }
    }
    tp8 = layout_from_mesh_axes({"data": 1, "model": 8}, name="tp8")
    reason = mesh_tune.check_feasible(
        tp8, params, global_batch=8, grad_accum=1
    )
    assert reason is not None and "not divisible" in reason
    tp4 = layout_from_mesh_axes({"data": 2, "model": 4}, name="tp4")
    assert (
        mesh_tune.check_feasible(tp4, params, global_batch=8, grad_accum=1)
        is None
    )
    # Microbatch must divide the batch-axis product (6/2 = 3 over data=2).
    assert "microbatch" in mesh_tune.check_feasible(
        tp4, params, global_batch=6, grad_accum=2
    )


def test_predict_step_time_dot_dtype_axis(mesh_tune):
    """ISSUE 17 (docs/quantization.md): --dot-dtype int8 prices the
    quantized arm — the caller resolves a 2x peak (halving the compute
    term, passed doubled here exactly as run() does) and int8
    activations halve the TP collective-traffic term relative to the
    bf16 default, so the int8 prediction must be strictly faster on a
    TP layout."""
    import types

    from sav_tpu.parallel.layout import layout_from_mesh_axes

    params = {
        "to_qkv": {
            "kernel": jax.ShapeDtypeStruct((64, 3, 4, 16), jax.numpy.float32)
        },
        "pos_embedding": {
            "pos_embedding": jax.ShapeDtypeStruct(
                (1, 65, 64), jax.numpy.float32
            )
        },
    }
    cost = types.SimpleNamespace(flops=1e12, num_tokens=65)
    # Pure TP (data=1): no dp gradient AllReduce term, so ALL collective
    # traffic is activation-sized and the dtype ratio is exact.
    tp4 = layout_from_mesh_axes({"data": 1, "model": 4}, name="tp4")
    kwargs = dict(
        global_batch=32, grad_accum=1, num_layers=2,
        ici_bytes_per_s=1e9,
    )
    bf16 = mesh_tune.predict_step_time(
        tp4, cost, params, peak_flops=1e12, dot_dtype=None, **kwargs
    )
    int8 = mesh_tune.predict_step_time(
        tp4, cost, params, peak_flops=2e12, dot_dtype="int8", **kwargs
    )
    assert int8["total_s"] < bf16["total_s"]
    assert int8["compute_s"] == pytest.approx(bf16["compute_s"] / 2)
    assert int8["comm_s"] == pytest.approx(bf16["comm_s"] / 2)
    assert "tp_block_allreduce" in int8["comm_terms"]
    # f32 doubles the activation bytes instead (collectives get slower).
    f32 = mesh_tune.predict_step_time(
        tp4, cost, params, peak_flops=1e12, dot_dtype="f32", **kwargs
    )
    assert f32["total_s"] > bf16["total_s"]


# -------------------------------------------------------------------- e2e


@pytest.fixture(scope="module")
def sweep(mesh_tune, tmp_path_factory):
    """One tiny sweep shared by the e2e assertions (compiles are the
    cost; ~2 candidates measured)."""
    tmp = tmp_path_factory.mktemp("mesh_tune")
    out = str(tmp / "preset.json")
    report_path = str(tmp / "report.json")
    import argparse

    ns = argparse.Namespace(
        model="vit_ti_patch16",
        num_classes=10,
        image_size=32,
        model_overrides='{"num_layers": 2, "embed_dim": 64, "num_heads": 4}',
        global_batch=32,
        devices=8,
        arms="dp,tp,2d,fsdp",
        grad_accum="1,2",
        top_k=2,
        iters=2,
        rounds=2,
        peak_flops=None,
        dot_dtype=None,
        ici_gbps=None,
        trace=str(tmp / "trace"),
        out=out,
        report=report_path,
    )
    lines = []
    report = mesh_tune.run(ns, log=lines.append)
    return {
        "report": report,
        "out": out,
        "report_path": report_path,
        "lines": lines,
    }


def test_sweep_ranks_and_records_infeasible(sweep):
    report = sweep["report"]
    cands = report["candidates"]
    assert len(cands) >= 10
    # tp8 cannot shard 4 heads — recorded with the reason, not dropped.
    tp8 = [c for c in cands if c["name"] == "tp8"]
    assert tp8 and all(not c["feasible"] for c in tp8)
    assert all("not divisible" in c["reason"] for c in tp8)
    # Every feasible candidate carries the prediction breakdown.
    for c in cands:
        if c["feasible"]:
            assert set(c["predicted"]) >= {"compute_s", "comm_s", "total_s"}
    # Ranking provenance: peak + ICI sources are labeled (cpu-fake here).
    assert report["peak_source"] == "cpu-fake"
    assert report["ici_source"] == "cpu-fake"


def test_sweep_measures_topk_and_emits_winner(sweep):
    report = sweep["report"]
    measured = [
        c for c in report["candidates"]
        if c.get("measured_ms_per_step") is not None
    ]
    assert len(measured) == 2  # top_k
    winner = report["winner"]
    assert winner is not None
    # Candidates at different accums compare per OPTIMIZER step.
    assert winner["measured_ms_per_opt_step"] == min(
        c["measured_ms_per_opt_step"] for c in measured
    )
    # The report file is valid JSON with the same shape.
    with open(sweep["report_path"]) as f:
        on_disk = json.load(f)
    assert on_disk["kind"] == "mesh-tune-report"
    assert on_disk["winner"]["name"] == winner["name"]


def test_sweep_trace_check_is_honest(sweep):
    """The cross-check either compares (and lists disagreements) or says
    it could not — an unindexed capture is never a clean bill."""
    check = sweep["report"]["trace_check"]
    assert check is not None
    if check["available"]:
        assert "vs_predicted" in check
        assert isinstance(check["disagrees"], list)
    else:
        assert check["reason"]


def test_emitted_preset_drives_the_trainer(sweep):
    """The winner preset rides TrainConfig.layout_preset end-to-end:
    mesh from the preset, one finite train step, provenance stamped."""
    from sav_tpu.data import synthetic_data_iterator
    from sav_tpu.parallel.layout import load_layout_preset
    from sav_tpu.train import TrainConfig, Trainer

    layout, doc = load_layout_preset(sweep["out"])
    assert doc["provenance"]["tool"] == "tools/mesh_tune.py"
    assert "measured_ms_per_step" in doc["provenance"]
    config = TrainConfig(
        model_name="vit_ti_patch16",
        num_classes=10,
        image_size=32,
        compute_dtype="float32",
        global_batch_size=32,
        num_train_images=64,
        num_epochs=1,
        warmup_epochs=1,
        transpose_images=False,
        layout_preset=sweep["out"],
        grad_accum_steps=doc.get("grad_accum_steps", 1),
        model_overrides=dict(num_layers=2, embed_dim=64, num_heads=4),
        seed=0,
    )
    trainer = Trainer(config)
    assert trainer.layout.name == sweep["report"]["winner"]["name"]
    assert trainer.layout.source == f"preset:{sweep['out']}"
    state = trainer.init_state()
    batch = next(
        synthetic_data_iterator(batch_size=32, image_size=32, num_classes=10)
    )
    state, metrics = trainer.train_step(state, batch, jax.random.PRNGKey(0))
    assert np.isfinite(float(jax.device_get(metrics["loss"])))
