"""Anomaly-triggered profiling (ISSUE 7): bounded, budgeted captures.

Unit coverage drives the state machine with injected start/stop fns
(window bounds, budget + cooldown denials, the robust step-time spike
gate, failure containment); the e2e test runs a real fit() on CPU with
an induced goodput stall anomaly and asserts exactly one bounded
jax.profiler capture whose path lands in the run manifest — the ISSUE 7
acceptance criterion.
"""

import json
import os

import numpy as np
import pytest

from sav_tpu.obs.autoprof import TRIGGERS, AutoProfiler
from sav_tpu.train import TrainConfig, Trainer


class SpyProfiler:
    def __init__(self, fail_start=False):
        self.started = []
        self.stopped = 0
        self.fail_start = fail_start

    def start(self, path):
        if self.fail_start:
            raise RuntimeError("trace already active")
        self.started.append(path)

    def stop(self):
        self.stopped += 1


def _prof(tmp_path, spy, **kwargs):
    return AutoProfiler(
        str(tmp_path), start_fn=spy.start, stop_fn=spy.stop, **kwargs
    )


def test_capture_window_is_bounded_and_recorded(tmp_path):
    spy = SpyProfiler()
    prof = _prof(tmp_path, spy, trace_steps=3)
    assert prof.request("stall_anomaly", 10)
    for step in range(10, 20):
        prof.on_step(step)
    assert len(spy.started) == 1 and spy.stopped == 1
    assert len(prof.captures) == 1
    cap = prof.captures[0]
    # Armed at 10, started at the next on_step (10), stopped 3 steps on.
    assert cap["trigger"] == "stall_anomaly"
    assert cap["trigger_step"] == 10
    assert cap["start_step"] == 10 and cap["end_step"] == 13
    assert "proc0_step00000010_stall_anomaly" in cap["path"]
    assert os.path.isdir(cap["path"])
    assert prof.stats()["captures"] == 1.0
    # The per-process sidecar: non-zero processes run with a DISABLED
    # run manifest, so the capture record must exist independently.
    sidecar = os.path.join(str(tmp_path), "autoprof",
                           "proc0_captures.jsonl")
    records = [json.loads(ln) for ln in open(sidecar)]
    assert [r["path"] for r in records] == [cap["path"]]


def test_budget_and_cooldown_deny_further_captures(tmp_path):
    spy = SpyProfiler()
    prof = _prof(
        tmp_path, spy, trace_steps=1, max_captures=2, cooldown_steps=50
    )
    assert prof.request("manual", 1)
    prof.on_step(1)
    prof.on_step(2)  # capture 1 done at step 2
    # Inside the cooldown window: denied.
    assert not prof.request("manual", 10)
    # Past the cooldown: granted; then the budget is spent.
    assert prof.request("manual", 60)
    prof.on_step(60)
    prof.on_step(61)
    assert not prof.request("manual", 200)
    assert prof.stats() == {
        "captures": 2.0, "denied": 2.0, "errors": 0.0,
    }
    # A request while armed/active is denied too (no nesting).
    prof2 = _prof(tmp_path, SpyProfiler(), trace_steps=4)
    assert prof2.request("manual", 1)
    assert not prof2.request("manual", 1)


def test_unknown_trigger_and_bad_knobs_raise(tmp_path):
    spy = SpyProfiler()
    prof = _prof(tmp_path, spy)
    with pytest.raises(ValueError, match="unknown trigger"):
        prof.request("nope", 1)
    assert "stall_anomaly" in TRIGGERS
    with pytest.raises(ValueError):
        AutoProfiler(str(tmp_path), trace_steps=0)
    with pytest.raises(ValueError):
        AutoProfiler(str(tmp_path), max_captures=0)


def test_step_time_spike_gate_is_robust(tmp_path):
    spy = SpyProfiler()
    prof = _prof(
        tmp_path, spy, spike_sigma=4.0, spike_min_history=8,
    )
    # Healthy history: no trigger, gate unarmed until min_history.
    for step in range(1, 9):
        assert prof.note_window(step, 0.1 + 0.001 * (step % 3)) is None
    # A 10x window: the robust gate fires and arms a capture.
    assert prof.note_window(9, 1.0) == "step_time_spike"
    # The spike did NOT enter the history (cannot poison the baseline):
    # after the capture resolves, a second equal spike still fires.
    prof.on_step(10)
    prof.on_step(10 + prof.trace_steps)
    prof2 = _prof(tmp_path, SpyProfiler(), cooldown_steps=0)
    for step in range(1, 9):
        prof2.note_window(step, 0.1)
    assert prof2.note_window(9, 1.0) == "step_time_spike"
    prof2.on_step(9)
    prof2.on_step(9 + prof2.trace_steps)
    assert prof2.note_window(20, 1.0) == "step_time_spike"


def test_start_failure_is_contained_and_rearmable(tmp_path):
    spy = SpyProfiler(fail_start=True)
    prof = _prof(tmp_path, spy, trace_steps=1)
    assert prof.request("manual", 1)
    prof.on_step(1)  # start fails (e.g. static profile window active)
    assert prof.captures == []
    assert prof.stats()["errors"] == 1.0
    assert not prof.active
    # Disarmed, not wedged: a later trigger can try again.
    spy.fail_start = False
    assert prof.request("manual", 5)
    prof.on_step(5)
    prof.on_step(6)
    assert len(prof.captures) == 1


def test_finalize_stops_inflight_capture(tmp_path):
    spy = SpyProfiler()
    prof = _prof(tmp_path, spy, trace_steps=100)
    prof.request("watchdog_soft", 3)
    prof.on_step(3)
    assert prof.active
    prof.finalize(7)  # fit()'s finally: crash mid-window
    assert not prof.active
    assert spy.stopped == 1
    assert prof.captures[0]["end_step"] == 7


# ---------------------------------------------------------------- fit e2e


def test_induced_stall_anomaly_arms_one_bounded_capture(
    tmp_path, devices, monkeypatch
):
    """ISSUE 7 acceptance: an induced goodput stall anomaly arms exactly
    one bounded profiler capture whose path appears in the run manifest.
    The anomaly is induced by flagging one logging window through the
    ledger's real note_window seam — fit()'s wiring (ledger flag →
    autoprof.request → bounded jax.profiler window → manifest stamp)
    runs for real, on the real CPU profiler."""
    from sav_tpu.obs.goodput import GoodputLedger
    from sav_tpu.obs.manifest import RunManifest

    real_note = GoodputLedger.note_window

    def induced(self, num_steps, seconds, step=None):
        flagged = real_note(self, num_steps, seconds, step=step)
        return True if step == 4 else flagged

    monkeypatch.setattr(GoodputLedger, "note_window", induced)
    config = TrainConfig(
        model_name="vit_ti_patch16",
        num_classes=10,
        image_size=32,
        compute_dtype="float32",
        global_batch_size=8,
        num_train_images=8 * 32,
        num_epochs=1,
        warmup_epochs=0,
        base_lr=1e-3,
        transpose_images=False,
        log_every_steps=2,
        log_dir=str(tmp_path),
        autoprof=True,
        autoprof_steps=2,
        autoprof_max=2,
        seed=0,
        model_overrides={"num_layers": 1, "embed_dim": 32, "num_heads": 2},
    )
    trainer = Trainer(config)
    manifest = RunManifest(
        os.path.join(str(tmp_path), "manifest.json"), kind="train"
    )
    manifest.begin()

    def batches(n=10):
        rng = np.random.default_rng(0)
        for _ in range(n):
            yield {
                "images": rng.standard_normal((8, 32, 32, 3)).astype(
                    np.float32
                ),
                "labels": rng.integers(0, 10, (8,), dtype=np.int32),
            }

    trainer.fit(batches(), num_steps=10, manifest=manifest)
    doc = RunManifest.load(manifest.path)
    captures = doc["notes"]["autoprof"]
    assert len(captures) == 1, captures
    cap = captures[0]
    assert cap["trigger"] == "stall_anomaly"
    assert cap["trigger_step"] == 4
    # Bounded: the window spans exactly autoprof_steps steps, starting
    # at the first boundary after the trigger.
    assert cap["end_step"] - cap["start_step"] == 2
    assert os.path.isdir(cap["path"])
    assert str(tmp_path) in cap["path"] and "autoprof" in cap["path"]
    # The real jax.profiler wrote a trace under the capture dir.
    contents = [
        os.path.join(dirpath, f)
        for dirpath, _, files in os.walk(cap["path"]) for f in files
    ]
    assert contents, f"no trace files under {cap['path']}"
    gauges = trainer.last_goodput["gauges"]
    assert gauges["autoprof/captures"] == 1.0
    assert gauges["autoprof/errors"] == 0.0
    # Post-capture trace intelligence (ISSUE 8): the capture was
    # machine-read on the spot — the summary rides the manifest record
    # AND the per-process sidecar, with the measured attribution keyed
    # exactly like the cost model's predicted one.
    from sav_tpu.obs.costs import COMP_ATTN_QKAV

    sidecar = os.path.join(str(tmp_path), "autoprof",
                           "proc0_captures.jsonl")
    records = [json.loads(ln) for ln in open(sidecar)]
    for record in (cap, records[-1]):
        summary = record["summary"]
        assert summary["per_step_ms"] > 0
        assert summary["device_selector"] == "cpu-hlo-op"
        assert summary["indexed_frac"] > 0.5  # the HLO op index resolved
        measured = summary["components_frac"]
        doc2 = RunManifest.load(manifest.path)
        predicted = doc2["notes"]["cost_model"]["attribution"]
        assert set(predicted).issubset(set(measured))
        assert summary["attention_core_frac"] == pytest.approx(
            measured[COMP_ATTN_QKAV], abs=1e-3
        )
        assert "disagrees" in summary
    # The capture dir carries the offline tools' inputs: the op index
    # and the full summary (tools/trace_report.py reads both).
    assert os.path.exists(os.path.join(cap["path"], "op_index.json"))
    with open(os.path.join(cap["path"], "trace_summary.json")) as f:
        full = json.load(f)
    assert full["vs_predicted"]["rows"]
    assert full["steps"] == 2  # the bounded window's own step count
    # ISSUE 8 acceptance: the capture round-trips through the offline
    # CLI (auto-discovering trace, op index, and the manifest's
    # predicted attribution) into a per-layer-group measured table
    # whose groups are the same keys obs/costs.py predicts.
    import importlib.util
    import sys as _sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "trace_report", os.path.join(root, "tools", "trace_report.py")
    )
    trace_report = importlib.util.module_from_spec(spec)
    _sys.modules[spec.name] = trace_report
    spec.loader.exec_module(trace_report)
    import contextlib
    import io

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = trace_report.main([str(tmp_path), "--json"])
    assert rc == 0
    cli = json.loads(buf.getvalue())
    predicted_groups = set(doc2["notes"]["cost_model"]["groups"])
    measured_groups = set(cli["groups_frac"])
    # Every measured group is a predicted group (or the honest 'other'
    # bucket for top-level loss/optimizer primitives).
    assert measured_groups - {"other"} <= predicted_groups
    assert measured_groups & predicted_groups, cli["groups_frac"]
    assert cli["vs_predicted"]["rows"]


def test_analysis_failure_is_contained(tmp_path):
    """A broken op_index_fn (or unparseable trace) counts as an error
    gauge; the capture record still lands without its summary."""

    def boom():
        raise RuntimeError("no HLO for you")

    spy = SpyProfiler()
    prof = AutoProfiler(
        str(tmp_path), start_fn=spy.start, stop_fn=spy.stop,
        trace_steps=1, op_index_fn=boom,
    )
    # Plant a trace file so analysis actually runs into the bad index fn.
    assert prof.request("manual", 1)
    prof.on_step(1)
    os.makedirs(os.path.join(prof._active["path"]), exist_ok=True)
    import gzip

    with gzip.open(
        os.path.join(prof._active["path"], "x.trace.json.gz"), "wt"
    ) as f:
        f.write('{"traceEvents": []}')
    prof.on_step(2)
    assert len(prof.captures) == 1
    assert "summary" not in prof.captures[0]
    assert prof.stats()["errors"] == 1.0
