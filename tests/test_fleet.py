"""Fleet telemetry (ISSUE 7): heartbeats, skew, stragglers, dead hosts.

Tier-1 acceptance criteria live here and in tests/test_two_process.py:
per-process heartbeat streams appear at the trainer's log boundary with
zero extra device syncs and <1% step overhead (the goodput-ledger guard,
same pattern as the recorder's), the aggregator's leave-one-out
median+MAD ranking names an injected-delay process as the straggler, a
silent process raises dead-host suspicion, the merged fleet manifest is
written atomically by the fit, and the report tools degrade gracefully
on runs with no ``fleet/`` dir.
"""

import importlib.util
import io
import json
import os
import sys

import numpy as np
import pytest

from sav_tpu.obs.fleet import (
    HeartbeatWriter,
    aggregate_fleet,
    fleet_dir,
    heartbeat_path,
    read_heartbeats,
    write_fleet_manifest,
    write_probe_timeline,
)
from sav_tpu.obs.goodput import GoodputLedger
from sav_tpu.train import TrainConfig, Trainer

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", f"{name}.py")
    )
    module = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = module
    spec.loader.exec_module(module)
    return module


# ------------------------------------------------------------ writer unit


class FakeClock:
    def __init__(self, t0=1000.0):
        self.t = t0

    def __call__(self):
        return self.t


def _ledger_with(step_s=1.0, input_wait_s=0.0):
    ledger = GoodputLedger()
    ledger.account("step", step_s)
    ledger.account("input_wait", input_wait_s)
    ledger.steps = 4
    return ledger


def test_heartbeat_writer_appends_schema_records(tmp_path):
    clock = FakeClock()
    writer = HeartbeatWriter(
        str(tmp_path), process_index=3, process_count=8, clock=clock
    )
    writer.beat(
        10,
        ledger=_ledger_with(step_s=2.0, input_wait_s=0.5),
        metrics={"loss": 1.25, "images_per_sec": 100.0, "retraces": 0.0},
    )
    clock.t += 5.0
    writer.beat(20, ledger=_ledger_with(), incident="incidents/step_20")
    writer.fleet_event("watchdog_soft", silent_s=12.0)
    writer.close(outcome="ok")
    path = heartbeat_path(str(tmp_path), 3)
    assert path.endswith(os.path.join("fleet", "proc_3.jsonl"))
    records = [json.loads(ln) for ln in open(path) if ln.strip()]
    kinds = [r["kind"] for r in records]
    assert kinds == ["hb", "hb", "event", "final"]
    # schema_version (ISSUE 19): every record kind carries the writer's
    # generation stamp next to the frozen line-shape schema.
    from sav_tpu.obs.fleet import FLEET_SCHEMA_VERSION

    assert FLEET_SCHEMA_VERSION == 2
    assert [r["schema_version"] for r in records] == [2, 2, 2, 2]
    assert all(r["schema"] == 1 for r in records)
    hb = records[0]
    assert hb["proc"] == 3 and hb["procs"] == 8 and hb["step"] == 10
    assert hb["b"]["step"] == 2.0 and hb["b"]["input_wait"] == 0.5
    assert hb["loss"] == 1.25 and hb["retraces"] == 0
    assert records[1]["incident"] == "incidents/step_20"
    assert records[2]["event"] == "watchdog_soft"
    assert records[3]["outcome"] == "ok"
    stats = writer.stats()
    assert stats["beats"] == 2.0 and stats["events"] == 1.0
    # Idempotent close; post-close beats are dropped, not errors.
    writer.close()
    writer.beat(30, ledger=_ledger_with())
    assert len(read_heartbeats(str(tmp_path))[3]) == 4


def test_read_heartbeats_skips_torn_tail(tmp_path):
    writer = HeartbeatWriter(str(tmp_path), process_index=0)
    writer.beat(1, ledger=_ledger_with())
    writer.close()
    with open(heartbeat_path(str(tmp_path), 0), "a") as f:
        f.write('{"kind": "hb", "step"')  # a killed writer's torn line
    records = read_heartbeats(str(tmp_path))[0]
    assert [r["kind"] for r in records] == ["hb", "final"]


def test_readers_tolerate_future_schema_versions(tmp_path):
    """Forward compat (ISSUE 19): a NEWER writer's records — higher
    schema_version, unknown keys, even unknown kinds — pass through the
    readers untouched; old readers filter on ``kind`` and must never
    crash or drop on a version bump."""
    path = heartbeat_path(str(tmp_path), 0)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(json.dumps({
            "schema": 1, "schema_version": 99, "kind": "hb", "proc": 0,
            "t": 1.0, "step": 5, "from_the_future": {"x": 1},
        }) + "\n")
        f.write(json.dumps({
            "schema": 1, "schema_version": 99, "kind": "hologram",
            "proc": 0, "t": 2.0,
        }) + "\n")
        f.write(json.dumps({
            "schema": 1, "schema_version": 99, "kind": "final",
            "proc": 0, "t": 3.0, "outcome": "ok",
        }) + "\n")
    records = read_heartbeats(str(tmp_path))[0]
    assert [r["kind"] for r in records] == ["hb", "hologram", "final"]
    assert records[0]["from_the_future"] == {"x": 1}
    # Aggregation sees through the unknown records too.
    summary = aggregate_fleet(str(tmp_path))
    proc = summary["processes"]["0"]
    assert proc["outcome"] == "ok"


# ------------------------------------------------------- aggregation unit


def _write_stream(tmp_path, proc, entries, final=None):
    path = heartbeat_path(str(tmp_path), proc)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "a") as f:
        for e in entries:
            record = {"schema": 1, "kind": "hb", "proc": proc}
            record.update(e)
            f.write(json.dumps(record) + "\n")
        if final is not None:
            f.write(json.dumps({
                "schema": 1, "kind": "final", "proc": proc,
                "outcome": final,
                "t": entries[-1]["t"] if entries else 0.0,
            }) + "\n")


def _stream(proc, *, t0=0.0, per_step=1.0, steps=10, stall_frac=0.0):
    """Synthetic heartbeat trail: one beat per step, constant rate, the
    host-stall buckets accruing ``stall_frac`` of each interval."""
    entries = []
    wall = 0.0
    b = {"step": 0.0, "input_wait": 0.0, "h2d": 0.0, "stall": 0.0,
         "eval": 0.0, "checkpoint": 0.0, "compile": 0.0}
    for i in range(1, steps + 1):
        wall += per_step
        b = dict(b)
        b["input_wait"] += per_step * stall_frac
        b["step"] += per_step * (1 - stall_frac)
        entries.append({
            "step": i, "t": round(t0 + wall, 3), "b": b,
            "wall_s": round(wall, 3), "anomalies": 0,
        })
    return entries


def test_straggler_ranking_names_injected_slow_process(tmp_path):
    """Four processes, one 3x slower: the leave-one-out median+MAD
    ranking flags exactly it, by raw step time."""
    for proc in range(3):
        _write_stream(tmp_path, proc, _stream(proc, per_step=1.0),
                      final="ok")
    _write_stream(tmp_path, 3, _stream(3, per_step=3.0), final="ok")
    summary = aggregate_fleet(str(tmp_path))
    ranking = summary["straggler"]["ranking"]
    assert summary["straggler"]["straggler"] == 3
    assert ranking[0]["proc"] == 3 and ranking[0]["flagged"]
    assert not any(e["flagged"] for e in ranking[1:])
    assert summary["processes"]["3"]["median_step_s"] == pytest.approx(3.0)


def test_straggler_by_host_stall_share_in_lockstep_fleet(tmp_path):
    """The collective-run signature (docs/fleet.md): every process shows
    the SAME wall per-step (lockstep), but the straggler's time sits in
    input_wait while the victims' sits in step — attribution must name
    the process that stalled BEFORE the all-reduce, not report a
    symmetric slowdown."""
    for proc in range(3):
        _write_stream(
            tmp_path, proc,
            _stream(proc, per_step=2.0, stall_frac=0.02), final="ok",
        )
    _write_stream(
        tmp_path, 3, _stream(3, per_step=2.0, stall_frac=0.7), final="ok"
    )
    summary = aggregate_fleet(str(tmp_path))
    assert summary["straggler"]["straggler"] == 3
    top = summary["straggler"]["ranking"][0]
    assert top["proc"] == 3
    assert top["host_stall"]["flagged"]
    # Step time alone could not have separated them (lockstep).
    assert not top["step_time"]["flagged"]


def test_missing_heartbeat_raises_dead_host_suspicion(tmp_path):
    """'Process 1 stopped heartbeating at step 4' — the MULTICHIP/bench
    post-mortem this layer exists for."""
    _write_stream(tmp_path, 0, _stream(0, per_step=1.0, steps=12),
                  final="ok")
    _write_stream(tmp_path, 1, _stream(1, per_step=1.0, steps=4))
    summary = aggregate_fleet(str(tmp_path))
    suspects = summary["suspects"]
    assert [s["proc"] for s in suspects] == [1]
    assert suspects[0]["last_step"] == 4
    assert suspects[0]["silent_s"] == pytest.approx(8.0)
    assert summary["step_skew"]["skew"] == 8
    assert summary["step_skew"]["laggard"] == 1
    # A process WITH a final record is finished, not dead.
    assert "0" in summary["processes"]
    assert summary["processes"]["0"]["final"]


def test_aggregate_empty_dir_and_single_process(tmp_path):
    assert aggregate_fleet(str(tmp_path))["processes"] == {}
    _write_stream(tmp_path, 0, _stream(0), final="ok")
    summary = aggregate_fleet(str(tmp_path))
    # One process: nobody to compare against — no straggler, no crash.
    assert summary["straggler"]["straggler"] is None
    assert summary["suspects"] == []


def test_fleet_manifest_written_atomically(tmp_path):
    _write_stream(tmp_path, 0, _stream(0), final="ok")
    summary = aggregate_fleet(str(tmp_path))
    path = write_fleet_manifest(str(tmp_path), summary)
    assert path == os.path.join(fleet_dir(str(tmp_path)), "fleet.json")
    with open(path) as f:
        assert json.load(f)["schema"] == 1
    assert not [
        n for n in os.listdir(fleet_dir(str(tmp_path))) if ".tmp." in n
    ]


def test_probe_timeline_rides_the_fleet_layout(tmp_path):
    probe_log = [
        {"attempt": 1, "elapsed_s": 90.0, "platform": None},
        {"attempt": 2, "elapsed_s": 210.0, "platform": None},
    ]
    path = write_probe_timeline(
        str(tmp_path), probe_log, deadline_s=600.0, tag="bench"
    )
    assert path == os.path.join(
        fleet_dir(str(tmp_path)), "backend_probe.jsonl"
    )
    records = [json.loads(ln) for ln in open(path)]
    assert [r["kind"] for r in records] == [
        "probe", "probe", "probe_giveup"
    ]
    assert records[-1]["attempts"] == 2
    assert records[0]["attempt"] == 1


# ---------------------------------------------------------------- fit e2e


def _fit_config(tmp_path, **overrides):
    base = dict(
        model_name="vit_ti_patch16",
        num_classes=10,
        image_size=32,
        compute_dtype="float32",
        global_batch_size=8,
        num_train_images=8 * 32,
        num_epochs=1,
        warmup_epochs=0,
        base_lr=1e-3,
        transpose_images=False,
        log_every_steps=2,
        log_dir=str(tmp_path),
        fleet=True,
        seed=0,
        model_overrides={"num_layers": 1, "embed_dim": 32, "num_heads": 2},
    )
    base.update(overrides)
    return TrainConfig(**base)


def _batches(n):
    rng = np.random.default_rng(0)
    for _ in range(n):
        yield {
            "images": rng.standard_normal((8, 32, 32, 3)).astype(np.float32),
            "labels": rng.integers(0, 10, (8,), dtype=np.int32),
        }


def test_fit_heartbeats_on_log_boundary_with_overhead_guard(
    tmp_path, devices
):
    """The tier-1 sync/overhead contract: heartbeats appear at every log
    boundary of a real fit, the merged fleet manifest lands next to
    them, and the whole fleet path costs <1% of step time on the
    training thread (goodput-ledger guard — the recorder's pattern;
    SAV112 is the static half of the same contract)."""
    config = _fit_config(tmp_path, log_every_steps=4)
    trainer = Trainer(config)
    from sav_tpu.obs.manifest import RunManifest

    manifest = RunManifest(
        os.path.join(str(tmp_path), "manifest.json"), kind="train"
    )
    manifest.begin()
    state, history = trainer.fit(
        _batches(16), num_steps=16, manifest=manifest
    )
    records = read_heartbeats(str(tmp_path))[0]
    beats = [r for r in records if r["kind"] == "hb"]
    # 16 steps at log_every=4 -> 4 log boundaries, then one final record.
    assert [b["step"] for b in beats] == [4, 8, 12, 16]
    assert records[-1]["kind"] == "final"
    assert records[-1]["outcome"] == "ok"
    for b in beats:
        assert b["b"]["step"] > 0  # ledger buckets ride every beat
        assert "loss" in b
    # Merged fleet manifest written by the fit itself (process 0).
    with open(os.path.join(fleet_dir(str(tmp_path)), "fleet.json")) as f:
        merged = json.load(f)
    assert merged["processes"]["0"]["heartbeats"] == 4
    assert merged["processes"]["0"]["final"]
    # ... and cross-linked from the run manifest.
    doc = RunManifest.load(manifest.path)
    assert doc["notes"]["fleet"]["processes"]["0"]["last_step"] == 16
    # Overhead: the fleet path (writes included) stays under 1% of step.
    gauges = trainer.last_goodput["gauges"]
    assert gauges["fleet/beats"] == 4.0
    step_s = trainer.last_goodput["buckets_s"]["step"]
    assert step_s > 0
    assert gauges["fleet/write_s"] < 0.01 * step_s, (
        f"fleet heartbeat overhead {gauges['fleet/write_s']:.6f}s is not "
        f"<1% of step time {step_s:.6f}s"
    )


def test_fit_without_fleet_or_log_dir_writes_nothing(tmp_path, devices):
    config = _fit_config(tmp_path, fleet=False)
    Trainer(config).fit(_batches(4), num_steps=4)
    assert not os.path.isdir(fleet_dir(str(tmp_path)))


def test_identity_override_gates_shared_writers(
    tmp_path, devices, monkeypatch
):
    """SAV_FLEET_PROC != 0 makes a worker a NON-writer for the shared
    files (goodput.json, the merged fleet manifest) while still
    heartbeating into its own stream — independent workers sharing a
    log dir must not clobber each other (docs/fleet.md)."""
    monkeypatch.setenv("SAV_FLEET_PROC", "1")
    monkeypatch.setenv("SAV_FLEET_PROCS", "2")
    config = _fit_config(tmp_path, log_every_steps=2)
    Trainer(config).fit(_batches(4), num_steps=4)
    records = read_heartbeats(str(tmp_path))
    assert list(records) == [1]  # its own stream, as proc 1
    assert records[1][0]["procs"] == 2
    # Shared artifacts belong to fleet process 0 — not written here.
    assert not os.path.exists(os.path.join(str(tmp_path), "goodput.json"))
    assert not os.path.exists(
        os.path.join(fleet_dir(str(tmp_path)), "fleet.json")
    )


def test_crashed_fit_stream_has_error_final(tmp_path, devices):
    config = _fit_config(tmp_path)
    trainer = Trainer(config)

    def exploding():
        yield from _batches(3)
        raise RuntimeError("iterator died")

    with pytest.raises(RuntimeError):
        trainer.fit(exploding(), num_steps=8)
    records = read_heartbeats(str(tmp_path))[0]
    assert records[-1]["kind"] == "final"
    assert records[-1]["outcome"] == "error"


# ------------------------------------------------------------- the tools


def test_fleet_status_cli_json_and_text(tmp_path, capsys):
    _write_stream(tmp_path, 0, _stream(0, per_step=1.0), final="ok")
    _write_stream(tmp_path, 1, _stream(1, per_step=4.0), final="ok")
    fleet_status = _load_tool("fleet_status")
    assert fleet_status.main(["--json", str(tmp_path)]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["straggler"]["straggler"] == 1
    assert fleet_status.main([str(tmp_path)]) == 0
    text = capsys.readouterr().out
    assert "STRAGGLER" in text and "proc 1" in text
    assert fleet_status.main([str(tmp_path / "nope")]) == 2


def test_run_report_fleet_renders_and_degrades_gracefully(tmp_path):
    run_report = _load_tool("run_report")
    # No fleet dir: --fleet degrades to a note, exit 0 (the r7 battery
    # renders old runs too).
    out = io.StringIO()
    empty = tmp_path / "empty"
    empty.mkdir()
    assert run_report.main([str(empty), "--fleet"]) == 0
    run_report.report_fleet(str(empty), out)
    assert "no fleet directory" in out.getvalue()
    # With streams: processes + straggler rendered.
    _write_stream(tmp_path, 0, _stream(0, per_step=1.0), final="ok")
    _write_stream(tmp_path, 1, _stream(1, per_step=4.0))
    out = io.StringIO()
    run_report.report_fleet(str(tmp_path), out)
    text = out.getvalue()
    assert "2 process(es)" in text
    assert "STRAGGLER: proc 1" in text
    assert "no final record" in text
    # Probe-only dir (backend never came up): rendered, not crashed.
    probe_dir = tmp_path / "probe_only"
    probe_dir.mkdir()
    write_probe_timeline(
        str(probe_dir),
        [{"attempt": 1, "elapsed_s": 90.0, "platform": None}],
        deadline_s=600.0, tag="bench",
    )
    out = io.StringIO()
    run_report.report_fleet(str(probe_dir), out)
    assert "backend never came up" in out.getvalue()
