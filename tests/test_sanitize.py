"""Runtime sanitizers (ISSUE 3): StepSanitizer unit + Trainer integration.

Unit tier: the retrace arm catches both retrace seeds (shape drift,
static-arg drift) the moment they happen; the transfer arm rejects
implicit host→device transfers while armed and unwinds cleanly on
close. Integration tier: ``TrainConfig.sanitize=True`` is silent on a
healthy run (the acceptance criterion for ``train.py --sanitize``),
composes with the feeder, the serial fallback, and diagnostics — and a
seeded retrace mid-fit fails loudly with the step number in the error.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from sav_tpu.analysis.sanitize import RetraceSanitizerError, StepSanitizer
from sav_tpu.obs.memory import RetraceCounter


# -------------------------------------------------------------- unit tier


def test_retrace_on_shape_drift_caught_at_the_offending_step():
    f = jax.jit(lambda x: x * 2)
    san = StepSanitizer(f, transfer_guard=None)
    f(jnp.ones(4))
    san.arm()  # warmup trace forgiven
    f(jnp.ones(4))
    san.check(2)  # cache hit: silent
    f(jnp.ones(5))  # shape drift: new trace
    with pytest.raises(RetraceSanitizerError, match="step 3"):
        san.check(3)
    san.close()


def test_retrace_on_static_scalar_drift():
    g = jax.jit(lambda x, n: x[:n], static_argnums=1)
    san = StepSanitizer(g, transfer_guard=None)
    x = jnp.ones(8)
    g(x, 4)
    san.arm()
    g(x, 4)
    san.check(2)
    g(x, 5)  # distinct static value: one program per value
    with pytest.raises(RetraceSanitizerError, match="re-traced 1x"):
        san.check(3)
    san.close()


def test_transfer_guard_blocks_implicit_h2d_until_close():
    f = jax.jit(lambda x: x + 1)
    placed = jnp.ones(4)
    san = StepSanitizer(f)
    f(placed)
    san.arm()
    f(placed)  # device-resident arg: fine
    # Explicit placement stays legal — the feeder/serial-fallback contract.
    f(jax.device_put(np.ones(4)))
    with pytest.raises(Exception, match="[Dd]isallow"):
        f(np.ones(4))  # implicit host->device upload
    san.close()
    f(np.ones(4))  # guard unwound


def test_sanitizer_is_idempotent_and_safe_unarmed():
    f = jax.jit(lambda x: x)
    san = StepSanitizer(f)
    san.check(1)  # before arm: no-op
    san.close()  # before arm: no-op
    san.arm()
    san.arm()  # double-arm: no double guard entry
    san.close()
    san.close()
    assert san.active  # counter works on this jax


def test_sanitizer_counter_is_independent_of_a_diagnostics_counter():
    """The trainer runs diagnostics' RetraceCounter and the sanitizer's
    side by side on one jitted fn; each holds its own watermark, so
    neither steals the other's delta."""
    f = jax.jit(lambda x: x)
    a, b = RetraceCounter(f), RetraceCounter(f)
    f(jnp.ones(3))
    assert a.delta() == 1
    assert b.delta() == 1  # a's read did not consume b's view
    f(jnp.ones(4))
    assert b.delta() == 1
    assert a.delta() == 1


# ------------------------------------------------------- integration tier


def _trainer(**config_overrides):
    from sav_tpu.models import create_model
    from sav_tpu.train import TrainConfig, Trainer

    base = dict(
        model_name="vit_ti_patch16",
        num_classes=10,
        image_size=32,
        compute_dtype="float32",
        global_batch_size=16,
        num_train_images=16 * 4,
        num_epochs=2,
        warmup_epochs=1,
        lr_scaling_divisor=16,
        transpose_images=False,
        log_every_steps=2,
        sanitize=True,
        seed=0,
    )
    base.update(config_overrides)
    config = TrainConfig(**base)
    model = create_model(
        config.model_name, num_classes=config.num_classes,
        dtype=jnp.float32, num_layers=2, embed_dim=64, num_heads=4,
    )
    return Trainer(config, model=model)


def _batches(n, batch_size=16, seed=0):
    rng = np.random.default_rng(seed)
    return [
        {
            "images": rng.standard_normal(
                (batch_size, 32, 32, 3)
            ).astype(np.float32),
            "labels": rng.integers(0, 10, (batch_size,), np.int32),
        }
        for _ in range(n)
    ]


def test_fit_with_sanitize_completes_silently(devices):
    """The acceptance path behind `train.py --sanitize`: a healthy run
    (async feeder on) finishes with guards armed and nothing fired."""
    trainer = _trainer()
    state, history = trainer.fit(iter(_batches(4)), num_steps=4)
    assert int(jax.device_get(state.step)) == 4
    assert trainer.last_goodput["gauges"]["feeder/batches"] == 4.0


def test_fit_with_sanitize_serial_fallback(devices):
    """async_feed=False places batches inline but EXPLICITLY — the
    transfer guard must accept the sanctioned serial path too."""
    trainer = _trainer(async_feed=False)
    state, _ = trainer.fit(iter(_batches(3)), num_steps=3)
    assert int(jax.device_get(state.step)) == 3


def test_fit_with_sanitize_and_diagnostics_coexist(devices):
    """Two RetraceCounters on one step fn (diagnostics' + the
    sanitizer's) must not steal each other's deltas."""
    trainer = _trainer(diagnostics=True)
    state, history = trainer.fit(iter(_batches(4)), num_steps=4)
    assert int(jax.device_get(state.step)) == 4
    logged = [h for h in history if "retraces" in h]
    assert logged and all(h["retraces"] == 0.0 for h in logged)


def test_fit_seeded_retrace_fails_loudly(devices):
    """A batch whose shape drifts mid-run re-traces the step; with
    sanitize on that is a hard error naming the step, not a silently
    slower run."""
    batches = _batches(2) + _batches(1, batch_size=8)
    trainer = _trainer()
    with pytest.raises(RetraceSanitizerError, match="step 3"):
        trainer.fit(iter(batches), num_steps=3)


def test_fit_without_sanitize_tolerates_the_same_drift(devices):
    """Control: the drift above is only fatal when asked for — default
    runs keep the old permissive behavior (retrace telemetry reports,
    nothing raises)."""
    batches = _batches(2) + _batches(1, batch_size=8)
    trainer = _trainer(sanitize=False)
    state, _ = trainer.fit(iter(batches), num_steps=3)
    assert int(jax.device_get(state.step)) == 3
