"""End-to-end telemetry through Trainer.fit() on the virtual CPU mesh:
in-jit diagnostics ride the step metrics, the span trace is
Perfetto-loadable JSON, the goodput ledger's buckets sum to wall time
within 5%, and an armed watchdog does not false-fire on a healthy run
(ISSUE 1 acceptance criteria)."""

import json
import os
import time

import jax.numpy as jnp
import pytest

from sav_tpu.data import fake_data_iterator
from sav_tpu.train import TrainConfig, Trainer


def _obs_trainer(tmp_path, **config_overrides):
    from sav_tpu.models import create_model

    base = dict(
        model_name="vit_ti_patch16",
        num_classes=10,
        image_size=32,
        compute_dtype="float32",
        global_batch_size=8,
        num_train_images=8 * 4,
        num_epochs=1,
        warmup_epochs=1,
        lr_scaling_divisor=8,
        transpose_images=False,
        log_every_steps=2,
        log_dir=str(tmp_path),
        diagnostics=True,
        trace_spans=True,
        seed=0,
    )
    base.update(config_overrides)
    config = TrainConfig(**base)
    model = create_model(
        config.model_name,
        num_classes=config.num_classes,
        dtype=jnp.float32,
        num_layers=2,
        embed_dim=64,
        num_heads=4,
    )
    return Trainer(config, model=model)


def test_fit_emits_diagnostics_spans_and_goodput(tmp_path, devices):
    # async_feed=False pins the *serial* loop's telemetry contract
    # (batch_fetch/shard_batch spans, h2d bucket on the training thread);
    # feeder-mode telemetry is covered in tests/test_feeder.py.
    trainer = _obs_trainer(tmp_path, watchdog_secs=300.0, async_feed=False)
    data = fake_data_iterator(batch_size=8, image_size=32, num_classes=10)
    t0 = time.perf_counter()
    state, history = trainer.fit(data, num_steps=4, log_fn=None)
    wall = time.perf_counter() - t0

    # --- in-jit diagnostics ride the logged step metrics ---
    train_records = [m for m in history if "loss" in m]
    assert train_records, "no training metrics logged"
    m = train_records[-1]
    for key in (
        "grad_norm", "param_norm", "update_norm", "update_to_param_ratio",
    ):
        assert key in m and m[key] >= 0.0, key
    assert m["nonfinite_grads"] == 0.0
    assert m["nonfinite_params"] == 0.0
    group_keys = [k for k in m if k.startswith("grad_norm/")]
    assert group_keys, "per-layer-group grad norms missing"
    assert "retraces" in m

    # --- span trace: Perfetto-loadable, covers the loop's phases ---
    span_path = os.path.join(str(tmp_path), "spans.trace.json")
    assert os.path.exists(span_path)
    with open(span_path) as f:
        events = json.load(f)["traceEvents"]
    names = {e["name"] for e in events if e.get("ph") == "X"}
    assert {"batch_fetch", "shard_batch", "step_dispatch", "log_sync"} <= names

    # --- goodput ledger: buckets sum to wall time within 5% ---
    goodput_path = os.path.join(str(tmp_path), "goodput.json")
    assert os.path.exists(goodput_path)
    with open(goodput_path) as f:
        summary = json.load(f)
    bucket_sum = sum(summary["buckets_s"].values())
    assert bucket_sum == pytest.approx(summary["wall_s"], rel=0.05)
    # The ledger's wall clock must agree with the caller's stopwatch.
    assert summary["wall_s"] <= wall * 1.05
    assert summary["steps"] == 4
    assert summary["buckets_s"]["compile"] > 0.0  # first jit dispatch
    # Serial loop books placement separately from fetch (ISSUE 2): the
    # shard_batch device_put lands in h2d, not input_wait.
    assert summary["buckets_s"]["h2d"] > 0.0
    assert summary["num_anomalies"] == 0

    # --- goodput record also lands in the returned history ---
    goodput_records = [m for m in history if "goodput/wall_s" in m]
    assert goodput_records
    assert trainer.last_goodput is not None

    # --- an armed watchdog did not false-fire on this healthy run ---
    # (fit() would have os._exit'd the test process if it had.)
    assert int(history[-1]["step"]) == 4


def test_fit_without_obs_flags_keeps_legacy_metrics(tmp_path, devices):
    trainer = _obs_trainer(
        tmp_path, diagnostics=False, trace_spans=False, log_dir=None,
        checkpoint_dir=None,
    )
    data = fake_data_iterator(batch_size=8, image_size=32, num_classes=10)
    _, history = trainer.fit(data, num_steps=2, log_fn=None)
    train_records = [m for m in history if "loss" in m]
    assert train_records
    assert "param_norm" not in train_records[-1]
    assert not os.path.exists(os.path.join(str(tmp_path), "spans.trace.json"))
    # The goodput ledger itself is always on (zero-cost); only files are
    # gated on a sink dir.
    assert trainer.last_goodput is not None
    assert not os.path.exists(os.path.join(str(tmp_path), "goodput.json"))
