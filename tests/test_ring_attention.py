"""Ring attention (sequence parallel) vs dense XLA attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sav_tpu.ops import xla_attention
from sav_tpu.parallel import create_mesh
from sav_tpu.parallel.ring_attention import ring_attention



# Entire module is the expensive tier: mesh/kernel-heavy numerics sweeps.
pytestmark = pytest.mark.slow

def _qkv(b=2, l=256, h=4, d=32, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return tuple(
        jax.random.normal(k, (b, l, h, d), dtype) for k in ks
    )


def test_ring_matches_dense(devices):
    mesh = create_mesh({"seq": 8})
    q, k, v = _qkv()
    ref = xla_attention(q, k, v)
    out = ring_attention(q, k, v, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_with_batch_axis(devices):
    mesh = create_mesh({"data": 2, "seq": 4})
    q, k, v = _qkv(b=4, l=128)
    ref = xla_attention(q, k, v)
    out = ring_attention(q, k, v, mesh=mesh, batch_axis="data")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_gradients_match(devices):
    mesh = create_mesh({"seq": 4}, devices=jax.devices()[:4])
    q, k, v = _qkv(l=64)

    def loss_ring(q, k, v):
        return jnp.sum(jnp.square(ring_attention(q, k, v, mesh=mesh)))

    def loss_dense(q, k, v):
        return jnp.sum(jnp.square(xla_attention(q, k, v)))

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-4)


def test_ring_long_sequence_sharded_inputs(devices):
    """Inputs already sharded over seq stay sharded; L scales with the ring."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = create_mesh({"seq": 8})
    q, k, v = _qkv(b=1, l=4096, h=2, d=64)
    sh = NamedSharding(mesh, P(None, "seq", None, None))
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh=mesh))(qs, ks, vs)
    assert out.sharding.spec == P(None, "seq", None, None)
    ref = xla_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=5e-5, rtol=5e-5
    )


def test_ring_rejects_indivisible_length(devices):
    mesh = create_mesh({"seq": 8})
    q, k, v = _qkv(l=100)
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention(q, k, v, mesh=mesh)


def test_ring_bf16(devices):
    mesh = create_mesh({"seq": 4}, devices=jax.devices()[:4])
    q, k, v = _qkv(l=128, dtype=jnp.bfloat16)
    ref = xla_attention(q, k, v)
    out = ring_attention(q, k, v, mesh=mesh)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2, rtol=3e-2
    )


def test_ring_flash_matches_dense(devices):
    """backend='pallas': fused-kernel ring steps + lse merge == dense."""
    mesh = create_mesh({"seq": 8})
    q, k, v = _qkv()
    ref = xla_attention(q, k, v)
    out = ring_attention(q, k, v, mesh=mesh, backend="pallas")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_flash_gradients_match(devices):
    """The re-streamed blocked backward (global-lse normalization, dk/dv
    carried around the ring) matches dense autodiff."""
    mesh = create_mesh({"seq": 4}, devices=jax.devices()[:4])
    q, k, v = _qkv(l=64)

    def loss_ring(q, k, v):
        return jnp.sum(
            jnp.square(ring_attention(q, k, v, mesh=mesh, backend="pallas"))
        )

    def loss_dense(q, k, v):
        return jnp.sum(jnp.square(xla_attention(q, k, v)))

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=5e-4)


def test_ring_flash_unaligned_local_blocks(devices):
    """Local shard length not a multiple of the kernel block (L_loc=48 with
    block 32): padding masks inside the per-step kernels must hold."""
    mesh = create_mesh({"seq": 4}, devices=jax.devices()[:4])
    q, k, v = _qkv(l=192)

    def loss_ring(q, k, v):
        return jnp.sum(jnp.square(ring_attention(
            q, k, v, mesh=mesh, backend="pallas", block_q=32, block_kv=32
        )))

    out = ring_attention(
        q, k, v, mesh=mesh, backend="pallas", block_q=32, block_kv=32
    )
    ref = xla_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)
    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(
        lambda q, k, v: jnp.sum(jnp.square(xla_attention(q, k, v))),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=5e-4)


def test_ring_flash_rejects_unknown_backend(devices):
    mesh = create_mesh({"seq": 8})
    q, k, v = _qkv()
    with pytest.raises(ValueError, match="backend"):
        ring_attention(q, k, v, mesh=mesh, backend="cuda")

