"""Pipeline parallelism: pipelined forward/backward vs sequential reference."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sav_tpu.parallel import create_mesh
from sav_tpu.parallel.pipelining import (
    pipeline,
    stack_stage_params,
    stage_param_shardings,
)


def _stage_fn(params, x):
    # One MLP "stage": x @ w + b, gelu.
    return jax.nn.gelu(x @ params["w"] + params["b"])


def _make_stage_params(rng, num_stages, dim):
    trees = []
    for i in range(num_stages):
        k = jax.random.fold_in(rng, i)
        kw, kb = jax.random.split(k)
        trees.append(
            {
                "w": jax.random.normal(kw, (dim, dim), jnp.float32) / np.sqrt(dim),
                "b": jax.random.normal(kb, (dim,), jnp.float32) * 0.01,
            }
        )
    return trees


def _sequential(trees, x):
    for p in trees:
        x = _stage_fn(p, x)
    return x


@pytest.mark.parametrize("num_microbatches", [4, 8])
def test_pipeline_matches_sequential(devices, num_microbatches):
    num_stages, dim, batch = 4, 16, 32
    mesh = create_mesh({"pipe": num_stages}, devices=devices[:num_stages])
    trees = _make_stage_params(jax.random.PRNGKey(0), num_stages, dim)
    stacked = stack_stage_params(trees)
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, dim), jnp.float32)

    out = pipeline(
        _stage_fn, stacked, x, mesh=mesh, num_microbatches=num_microbatches
    )
    ref = _sequential(trees, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_pipeline_under_jit_with_sharded_params(devices):
    num_stages, dim, batch = 4, 8, 16
    mesh = create_mesh({"pipe": num_stages}, devices=devices[:num_stages])
    trees = _make_stage_params(jax.random.PRNGKey(2), num_stages, dim)
    stacked = stack_stage_params(trees)
    stacked = jax.tree.map(
        jax.device_put, stacked, stage_param_shardings(stacked, mesh)
    )
    x = jax.random.normal(jax.random.PRNGKey(3), (batch, dim), jnp.float32)

    fn = jax.jit(
        functools.partial(pipeline, _stage_fn, mesh=mesh, num_microbatches=4)
    )
    out = fn(stacked, x)
    ref = _sequential(trees, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_pipeline_gradients_match_sequential(devices):
    num_stages, dim, batch = 4, 8, 16
    mesh = create_mesh({"pipe": num_stages}, devices=devices[:num_stages])
    trees = _make_stage_params(jax.random.PRNGKey(4), num_stages, dim)
    stacked = stack_stage_params(trees)
    x = jax.random.normal(jax.random.PRNGKey(5), (batch, dim), jnp.float32)

    def loss_pipe(stacked, x):
        return jnp.mean(
            pipeline(_stage_fn, stacked, x, mesh=mesh, num_microbatches=4) ** 2
        )

    def loss_seq(stacked, x):
        trees_ = [jax.tree.map(lambda p: p[i], stacked) for i in range(num_stages)]
        return jnp.mean(_sequential(trees_, x) ** 2)

    g_pipe = jax.grad(loss_pipe)(stacked, x)
    g_seq = jax.grad(loss_seq)(stacked, x)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        g_pipe,
        g_seq,
    )


@pytest.mark.slow
def test_pipeline_composes_with_data_parallel(devices):
    # 2-way DP × 4-stage PP on the 8-device mesh.
    num_stages, dim, batch = 4, 8, 16
    mesh = create_mesh({"data": 2, "pipe": num_stages}, devices=devices)
    trees = _make_stage_params(jax.random.PRNGKey(6), num_stages, dim)
    stacked = stack_stage_params(trees)
    x = jax.random.normal(jax.random.PRNGKey(7), (batch, dim), jnp.float32)

    out = pipeline(
        _stage_fn,
        stacked,
        x,
        mesh=mesh,
        num_microbatches=4,
        batch_axis="data",
    )
    ref = _sequential(trees, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    # Backward through the DP×PP composition (the data-axis psum transpose
    # is a distinct path from the pure-PP gradient test above).
    def loss_pipe(stacked, x):
        return jnp.mean(
            pipeline(
                _stage_fn, stacked, x, mesh=mesh,
                num_microbatches=4, batch_axis="data",
            )
            ** 2
        )

    def loss_seq(stacked, x):
        trees_ = [jax.tree.map(lambda p: p[i], stacked) for i in range(num_stages)]
        return jnp.mean(_sequential(trees_, x) ** 2)

    g_pipe = jax.grad(loss_pipe)(stacked, x)
    g_seq = jax.grad(loss_seq)(stacked, x)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        g_pipe,
        g_seq,
    )


def _encoder_setup(num_stages=4, batch=16, length=17, dim=64, seed=10):
    """Real model-zoo stages: one ViT EncoderBlock (pre-LN MHSA + FF) per
    pipeline stage, per-stage params from independent inits (VERDICT r3
    item 6 — the toy gelu stage proved the schedule, not the model)."""
    from sav_tpu.models.vit import EncoderBlock
    from sav_tpu.parallel.pipelining import module_stage_fn

    block = EncoderBlock(num_heads=4, dtype=jnp.float32)
    x = jax.random.normal(
        jax.random.PRNGKey(seed), (batch, length, dim), jnp.float32
    )
    trees = [
        block.init(
            {"params": jax.random.fold_in(jax.random.PRNGKey(seed + 1), i)},
            x[:1],
            False,
        )["params"]
        for i in range(num_stages)
    ]
    stage_fn = module_stage_fn(block, is_training=False)
    return stage_fn, trees, x


@pytest.mark.slow
def test_pipeline_encoder_blocks_match_sequential(devices):
    num_stages = 4
    mesh = create_mesh({"pipe": num_stages}, devices=devices[:num_stages])
    stage_fn, trees, x = _encoder_setup(num_stages)
    stacked = stack_stage_params(trees)

    out = pipeline(stage_fn, stacked, x, mesh=mesh, num_microbatches=4)
    ref = x
    for p in trees:
        ref = stage_fn(p, ref)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


@pytest.mark.slow
def test_pipeline_encoder_blocks_grads_match_sequential(devices):
    """End-to-end differentiation through pipelined transformer stages —
    loss AND parameter grads (every stage's attention/FF kernels) against
    the unpipelined stack."""
    num_stages = 4
    mesh = create_mesh({"pipe": num_stages}, devices=devices[:num_stages])
    stage_fn, trees, x = _encoder_setup(num_stages, batch=8)
    stacked = stack_stage_params(trees)

    def loss_pipe(stacked, x):
        return jnp.mean(
            pipeline(stage_fn, stacked, x, mesh=mesh, num_microbatches=4) ** 2
        )

    def loss_seq(stacked, x):
        h = x
        for i in range(num_stages):
            h = stage_fn(jax.tree.map(lambda p: p[i], stacked), h)
        return jnp.mean(h**2)

    lp, g_pipe = jax.value_and_grad(loss_pipe)(stacked, x)
    ls, g_seq = jax.value_and_grad(loss_seq)(stacked, x)
    np.testing.assert_allclose(float(lp), float(ls), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5
        ),
        g_pipe,
        g_seq,
    )


def test_pipeline_rejects_stage_mesh_mismatch(devices):
    mesh = create_mesh({"pipe": 2}, devices=devices[:2])
    trees = _make_stage_params(jax.random.PRNGKey(8), 4, 8)
    stacked = stack_stage_params(trees)
    x = jnp.zeros((8, 8), jnp.float32)
    with pytest.raises(ValueError, match="stages"):
        pipeline(_stage_fn, stacked, x, mesh=mesh, num_microbatches=2)
