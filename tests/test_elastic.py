"""Elastic-training layer (ISSUE 9): supervised restarts, step-granular
checkpoints, rewind-and-skip, torn-save defenses, and the chaos-soak
goodput proof.

Three tiers: stdlib-fast units on the supervisor's pure helpers; trainer
integration on the 8-device CPU mesh (cadence saves, opt-layout
auto-detection, torn-newest fallback); and REAL-child e2e — a SIGKILLed
``train.py`` resumed step-exact (recorder batch-hash match, bit-equal
re-logged loss windows), and a tier-1-scaled ``tools/chaos_soak.py`` run
(2 injected SIGKILLs + 1 planted NaN) whose manifest chain must verify:
≥99% goodput accounting, step-exact resumes, the NaN batch skipped
exactly once, and a loss curve bit-continued against an uninterrupted
reference.
"""

import json
import os
import signal
import subprocess
import sys
import time
from io import StringIO

import numpy as np
import pytest

from sav_tpu.data.synthetic import synth_batch, synth_resumable_iterator
from sav_tpu.obs.recorder import batch_fingerprint
from sav_tpu.train.supervisor import (
    Supervisor,
    chaos_wrap,
    classify_exit,
    latest_checkpoint_step,
    load_chain,
    newest_incident,
    parse_skip_steps,
    resume_schedule_position,
    skip_step_batches,
    strip_supervisor_flags,
    verify_chain,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAIN_PY = os.path.join(ROOT, "train.py")


# ------------------------------------------------------------ pure helpers


def test_strip_supervisor_flags_both_spellings():
    argv = [
        "--supervise", "-m", "x", "--max-restarts", "3",
        "--restart-backoff=2.5", "--steps", "5", "--max-restarts=9",
    ]
    assert strip_supervisor_flags(argv) == ["-m", "x", "--steps", "5"]
    # train.py --supervise strips the user's --skip-steps too (it seeds
    # the supervisor's cumulative ledger instead — two --skip-steps on
    # the child would collapse to click's last-value-wins).
    argv = ["--skip-steps", "5,9", "--steps", "5", "--skip-steps=7"]
    assert strip_supervisor_flags(
        argv, extra_value_flags=("--skip-steps",)
    ) == ["--steps", "5"]


def test_resume_schedule_position():
    assert resume_schedule_position(4, {5}) == 4
    assert resume_schedule_position(5, {5}) == 6
    assert resume_schedule_position(10, {5}) == 11
    assert resume_schedule_position(10, {5, 6}) == 12
    assert resume_schedule_position(5, {5, 6}) == 7
    assert resume_schedule_position(0, set()) == 0


def test_skip_shift_survives_later_restart():
    """THE rewind-and-skip resume contract: once position p was dropped,
    step s >= p consumes a later original batch — a restart resuming
    past the skip must rebuild the stream from the SHIFTED position (and
    one resuming before it must re-arm the skip), reproducing the
    uninterrupted skip-applied schedule exactly."""
    import itertools

    def stream(start_pos):  # original-schedule positions as the batches
        return iter(range(start_pos + 1, 100))

    skips = {5}
    full = list(itertools.islice(skip_step_batches(stream(0), skips), 20))
    assert full[:6] == [1, 2, 3, 4, 6, 7]  # position 5 dropped, shifted

    for r in (10, 3, 5):  # resume after / before / exactly at the skip
        start_pos = resume_schedule_position(r, skips)
        remaining = {p for p in skips if p > start_pos}
        resumed = list(itertools.islice(
            skip_step_batches(
                stream(start_pos), remaining, start_step=start_pos
            ),
            20 - r,
        ))
        assert resumed == full[r:], f"resume at step {r} desynced"


def test_supervisor_passes_cumulative_skips(tmp_path):
    """The skip set rides EVERY attempt's argv (initial user skips
    included), not just the one after the incident — the schedule shift
    must survive later restarts."""
    out = tmp_path / "argv.json"
    child = _fake_child(
        "import sys, json\n"
        "json.dump(sys.argv[1:], open(sys.argv[1], 'w'))\n",
        str(out),
    )
    sup = Supervisor(
        child, log_dir=str(tmp_path), checkpoint_dir=None,
        skip_steps={9, 5},
    )
    assert sup.run() == 0
    assert json.loads(out.read_text())[-2:] == ["--skip-steps", "5,9"]
    attempts = load_chain(str(tmp_path))["notes"]["chain"]["attempts"]
    assert attempts[0]["skip_steps"] == [5, 9]


def test_parse_skip_steps():
    assert parse_skip_steps(None) == set()
    assert parse_skip_steps("") == set()
    assert parse_skip_steps("3, 5,3") == {3, 5}
    with pytest.raises(ValueError):
        parse_skip_steps("3,x")
    with pytest.raises(ValueError):
        parse_skip_steps("0")


def test_skip_step_batches_semantics():
    """Positions are uninterrupted-schedule steps: consecutive skips drop
    consecutive ORIGINAL batches (no off-by-one re-anchoring), each at
    most once, and on_skip sees the dropped batch."""
    batches = [{"i": i} for i in range(1, 7)]
    dropped = []
    out = list(skip_step_batches(
        iter(batches), {2, 3}, on_skip=lambda pos, b: dropped.append((pos, b["i"]))
    ))
    assert [b["i"] for b in out] == [1, 4, 5, 6]
    assert dropped == [(2, 2), (3, 3)]
    # Resumed stream: start_step anchors the counter.
    out = list(skip_step_batches(
        iter([{"i": 11}, {"i": 12}, {"i": 13}]), {12}, start_step=10
    ))
    assert [b["i"] for b in out] == [11, 13]
    # Skip of the final batch: the stream just ends.
    out = list(skip_step_batches(iter([{"i": 1}]), {1}))
    assert out == []


def test_chaos_wrap_noop_without_env():
    it = iter([{"images": np.ones(3)}])
    assert chaos_wrap(it, start_step=0, env={}) is it


def test_chaos_wrap_nan_and_hang_once(tmp_path):
    def stream():
        while True:
            yield {"images": np.ones((2, 2), np.float32)}

    env = {"SAV_CHAOS_NAN_STEP": "2"}
    it = chaos_wrap(stream(), start_step=0, env=env)
    first, second, third = next(it), next(it), next(it)
    assert not np.isnan(first["images"]).any()
    assert np.isnan(second["images"]).all()
    assert not np.isnan(third["images"]).any()
    # Resumed stream re-injects at the same schedule position (the skip
    # wrapper outside is what cures it).
    it = chaos_wrap(stream(), start_step=1, env=env)
    assert np.isnan(next(it)["images"]).all()
    # Hang: once-per-chain via the marker dir, and measured in wall time.
    env = {
        "SAV_CHAOS_HANG_STEP": "1",
        "SAV_CHAOS_HANG_SECS": "0.2",
        "SAV_CHAOS_ONCE_DIR": str(tmp_path),
    }
    t0 = time.perf_counter()
    next(chaos_wrap(stream(), start_step=0, env=env))
    assert time.perf_counter() - t0 >= 0.2
    t0 = time.perf_counter()
    next(chaos_wrap(stream(), start_step=0, env=env))  # marker: no hang
    assert time.perf_counter() - t0 < 0.1


def test_synth_batch_is_counter_based():
    """The batch is a pure function of (seed, position) — resumable by
    construction, and an external verifier recomputes any position."""
    a = synth_batch(seed=7, position=5, batch_size=4)
    b = synth_batch(seed=7, position=5, batch_size=4)
    assert batch_fingerprint(a)["hash"] == batch_fingerprint(b)["hash"]
    c = synth_batch(seed=7, position=6, batch_size=4)
    assert batch_fingerprint(a)["hash"] != batch_fingerprint(c)["hash"]
    # A resumed iterator IS the uninterrupted schedule from that point.
    resumed = next(synth_resumable_iterator(seed=7, start_step=4, batch_size=4))
    assert batch_fingerprint(resumed)["hash"] == batch_fingerprint(a)["hash"]


def test_latest_checkpoint_step(tmp_path):
    assert latest_checkpoint_step(None) is None
    assert latest_checkpoint_step(str(tmp_path / "missing")) is None
    for name in ("4", "12", "7.orbax-checkpoint-tmp-123", "notastep"):
        (tmp_path / name).mkdir()
    assert latest_checkpoint_step(str(tmp_path)) == 12


def test_classify_exit():
    assert classify_exit(0, None) == "ok"
    assert classify_exit(3, None) == "backend_unreachable"
    assert classify_exit(4, None) == "hang"
    assert classify_exit(2, None) == "usage_error"
    assert classify_exit(-9, None) == "killed:SIGKILL"
    assert classify_exit(1, "nonfinite") == "nonfinite"
    # A SIGKILLed child's manifest is stranded at 'running' — meaningless;
    # the signal is the fact.
    assert classify_exit(-9, "running") == "killed:SIGKILL"
    assert classify_exit(1, None) == "crash:rc=1"


def test_newest_incident(tmp_path):
    assert newest_incident(str(tmp_path)) is None
    root = tmp_path / "incidents"
    for step, t in ((5, 1.0), (9, 2.0)):
        d = root / f"step_{step:08d}"
        d.mkdir(parents=True)
        (d / "incident.json").write_text(json.dumps(
            {"step": step, "trigger": "nonfinite", "created_unix": t}
        ))
    (root / "memdump_00000012").mkdir()  # no step context: skipped
    doc = newest_incident(str(tmp_path))
    assert doc["step"] == 9 and doc["path"].endswith("step_00000009")


# -------------------------------------------------- supervisor (fake kids)


def _fake_child(script: str, *args) -> list:
    return [sys.executable, "-c", script, *args]


def _run_supervisor(tmp_path, child, **kwargs):
    sleeps = []
    sup = Supervisor(
        child,
        log_dir=str(tmp_path),
        checkpoint_dir=str(tmp_path / "ckpt"),
        sleep=sleeps.append,
        **kwargs,
    )
    rc = sup.run()
    return sup, rc, sleeps


def test_supervisor_restarts_until_success(tmp_path):
    """Exit-3 children restart with exponential backoff; the chain ends
    ok, every restart carries a reason, and the goodput metrics ride the
    supervisor manifest (a plain RunManifest the sentinel can read)."""
    counter = tmp_path / "n"
    counter.write_text("2")
    child = _fake_child(
        # The 0.5s sleep makes attempt wall time dominate supervisor
        # bookkeeping so the accounting check is stable.
        "import sys, time\n"
        "time.sleep(0.5)\n"
        "p = sys.argv[1]\n"
        "n = int(open(p).read())\n"
        "open(p, 'w').write(str(n - 1))\n"
        "sys.exit(3 if n > 0 else 0)\n",
        str(counter),
    )
    sup, rc, sleeps = _run_supervisor(
        tmp_path, child, max_restarts=5, backoff_base_s=0.5
    )
    assert rc == 0
    assert sleeps == [0.5, 1.0]  # deterministic exponential backoff
    doc = load_chain(str(tmp_path))
    assert doc["outcome"] == "ok" and doc["kind"] == "supervisor"
    chain = doc["notes"]["chain"]
    attempts = chain["attempts"]
    assert [a["restart_reason"] for a in attempts] == [
        "backend_unreachable", "backend_unreachable", None,
    ]
    assert attempts[-1]["exit_code"] == 0
    metrics = doc["metrics"]
    for key in ("goodput_frac", "accounted_frac", "goodput/lost_s",
                "goodput/backoff_s"):
        assert isinstance(metrics[key], (int, float)), key
    # Structural verification; the accounting bound is slightly relaxed
    # here because ~10ms of fixed supervisor bookkeeping is a visible
    # share of 0.5s fake-child attempts — the ≥99% production criterion
    # is asserted by the chaos soak e2e, whose attempts run for seconds.
    assert verify_chain(doc, min_accounted=0.95) == []
    # The sentinel reads it natively: goodput_frac surfaces as a metric.
    from sav_tpu.obs.manifest import normalize_run_record

    rec = normalize_run_record(doc, label="supervisor.json")
    assert rec.ok and "goodput_frac" in rec.metrics


def test_supervisor_usage_error_is_terminal(tmp_path):
    sup, rc, sleeps = _run_supervisor(
        tmp_path, _fake_child("import sys; sys.exit(2)"), max_restarts=5
    )
    assert rc == 2 and sleeps == []
    doc = load_chain(str(tmp_path))
    assert doc["outcome"] == "error"
    assert len(doc["notes"]["chain"]["attempts"]) == 1


def test_supervisor_budget_exhaustion(tmp_path):
    sup, rc, sleeps = _run_supervisor(
        tmp_path, _fake_child("import sys; sys.exit(7)"),
        max_restarts=2, backoff_base_s=0.1,
    )
    assert rc == 7 and len(sleeps) == 2
    doc = load_chain(str(tmp_path))
    assert doc["outcome"] == "error"
    assert "budget exhausted" in doc["error"]
    assert len(doc["notes"]["chain"]["attempts"]) == 3
    assert verify_chain(doc)  # a failed chain must NOT verify


def test_supervisor_classifies_signal_kills(tmp_path):
    counter = tmp_path / "n"
    counter.write_text("1")
    child = _fake_child(
        "import os, sys, signal\n"
        "p = sys.argv[1]\n"
        "n = int(open(p).read())\n"
        "open(p, 'w').write(str(n - 1))\n"
        "if n > 0:\n"
        "    os.kill(os.getpid(), signal.SIGKILL)\n",
        str(counter),
    )
    sup, rc, _ = _run_supervisor(
        tmp_path, child, max_restarts=2, backoff_base_s=0.05
    )
    assert rc == 0
    attempts = load_chain(str(tmp_path))["notes"]["chain"]["attempts"]
    assert attempts[0]["restart_reason"] == "killed:SIGKILL"
    assert attempts[0]["exit_code"] == -9


def test_decide_skip_ignores_stale_incident(tmp_path):
    """A leftover incident bundle from an earlier run sharing the log
    dir must not arm a rewind-and-skip: skipping its (good) batch would
    shift the schedule while the real bad batch replays forever."""
    sup = Supervisor(
        ["true"], log_dir=str(tmp_path), checkpoint_dir=None
    )
    d = tmp_path / "incidents" / "step_00000025"
    d.mkdir(parents=True)
    stale_t = time.time() - 3600.0
    (d / "incident.json").write_text(json.dumps(
        {"step": 25, "trigger": "nonfinite", "created_unix": stale_t}
    ))
    # Attempt started NOW: the hour-old bundle is stale — no skip.
    assert sup._decide_skip("nonfinite", time.time() - 5.0) == []
    assert sup.skipped_steps == set()
    # A bundle created during the attempt IS the decision source.
    (d / "incident.json").write_text(json.dumps(
        {"step": 25, "trigger": "nonfinite", "created_unix": time.time()}
    ))
    assert sup._decide_skip("nonfinite", time.time() - 5.0) == [25]
    assert sup.skipped_steps == {25}
    # ...and once per chain: a second nonfinite at the same step does
    # not re-arm it.
    assert sup._decide_skip("nonfinite", time.time() - 5.0) == []


def test_verify_chain_flags_low_accounting():
    doc = {
        "outcome": "ok",
        "metrics": {"goodput_frac": 0.5, "accounted_frac": 0.5},
        "notes": {"chain": {"attempts": [
            {"attempt": 1, "restart_reason": "hang", "exit_code": 4},
            {"attempt": 2, "restart_reason": None, "exit_code": 0},
        ]}},
    }
    problems = verify_chain(doc, min_accounted=0.99)
    assert any("accounting" in p for p in problems)
    doc["metrics"]["accounted_frac"] = 0.995
    assert verify_chain(doc, min_accounted=0.99) == []
    assert verify_chain(doc, expect_attempts=3)  # wrong attempt count


# ------------------------------------------------- trainer-level integration


def _smoke_config(tmp_path, **overrides):
    from sav_tpu.train import TrainConfig

    base = dict(
        model_name="vit_ti_patch16",
        num_classes=10,
        image_size=32,
        compute_dtype="float32",
        global_batch_size=8,
        num_train_images=8 * 1000,  # long epoch: cadence saves, not epoch
        num_epochs=1,
        warmup_epochs=0,
        base_lr=1e-3,
        lr_scaling_divisor=8,
        transpose_images=False,
        log_every_steps=2,
        checkpoint_dir=str(tmp_path / "ckpt"),
        seed=0,
    )
    base.update(overrides)
    return TrainConfig(**base)


def _trainer(config):
    import jax.numpy as jnp

    from sav_tpu.models import create_model
    from sav_tpu.train import Trainer

    model = create_model(
        config.model_name, num_classes=config.num_classes,
        dtype=jnp.float32, num_layers=2, embed_dim=64, num_heads=4,
    )
    return Trainer(config, model=model)


def _synth_iter(start_step=0):
    return synth_resumable_iterator(
        seed=0, start_step=start_step, batch_size=8, image_size=32,
        num_classes=10,
    )


def test_step_cadence_layout_probe_and_torn_fallback(tmp_path, devices):
    """One fit, three elasticity contracts: (a) checkpoint_every_steps
    counts steps SINCE THE LAST SAVE, quantized up to the next log
    boundary (N=3 with log_every=2 saves at 4 and 8 — a step-number
    modulo would misalign to lcm(3,2)=6 and save at 6 only) + writes the
    resume stamp; (b) a fresh auto-mode trainer probes the saved
    PER-LEAF opt-state layout and rebuilds to match (no
    --no-fused-optimizer hand-holding); (c) when the newest step is
    torn, restore falls back to the previous committed one."""
    import jax

    cfg = _smoke_config(
        tmp_path, checkpoint_every_steps=3, fused_optimizer=False
    )
    tr = _trainer(cfg)
    state, _ = tr.fit(_synth_iter(), num_steps=10)
    assert tr.checkpointer.all_steps() == [4, 8, 10]
    stamp = json.load(open(tmp_path / "ckpt" / "resume.json"))
    assert stamp["step"] == 10 and stamp["step_in_epoch"] == 10
    assert stamp["feeder_position"] == 10 and "fold_in" in str(stamp["rng"])
    assert tr.checkpointer.opt_layout() == {"fused": False, "ema": False}
    tr.checkpointer.close()

    # (b) auto mode would pick fused=True on this pure-data mesh; the
    # probe must flip it to the checkpoint's per-leaf layout.
    cfg2 = _smoke_config(
        tmp_path, checkpoint_every_steps=3, fused_optimizer=None
    )
    tr2 = _trainer(cfg2)
    assert tr2.fused_optimizer is True
    st = tr2.restore_or_init()
    assert int(jax.device_get(st.step)) == 10
    assert tr2.fused_optimizer is False
    # The rebuilt optimizer actually steps.
    rng = jax.random.fold_in(jax.random.PRNGKey(0), 1)
    st2, m = tr2.train_step(st, next(_synth_iter(10)), rng)
    assert np.isfinite(float(jax.device_get(m["loss"])))
    tr2.checkpointer.close()

    # (c) torn newest: gut step 10's payload; restore falls back to 8.
    import shutil

    step_dir = tmp_path / "ckpt" / "10"
    for child in step_dir.iterdir():
        shutil.rmtree(child) if child.is_dir() else child.unlink()
    cfg3 = _smoke_config(
        tmp_path, checkpoint_every_steps=3, fused_optimizer=False
    )
    tr3 = _trainer(cfg3)
    st3 = tr3.restore_or_init()
    assert int(jax.device_get(st3.step)) == 8
    tr3.checkpointer.close()


def test_secs_cadence_dedupe_and_crash_drain(tmp_path, devices):
    """checkpoint_every_secs=0 saves at every log boundary without
    double-saving a step the epoch/step cadence already took, and
    fit()'s finally drains in-flight saves (bounded wait) on the crash
    path too."""
    from sav_tpu.train.checkpoint import Checkpointer

    calls = {"save": [], "wait": 0}

    class SpyCheckpointer(Checkpointer):
        def save(self, step, state):
            calls["save"].append(step)
            super().save(step, state)

        def wait(self, timeout_s=None):
            calls["wait"] += 1
            return super().wait(timeout_s=timeout_s)

    cfg = _smoke_config(tmp_path, checkpoint_every_secs=0.0)
    from sav_tpu.train import Trainer  # noqa: F401  (import surface)

    tr = _trainer(cfg)
    tr.checkpointer = SpyCheckpointer(cfg.checkpoint_dir)
    tr.fit(_synth_iter(), num_steps=6)
    # Log boundary every 2 steps → saves at 2, 4, 6; the final-step save
    # is deduped (6 was already saved by the cadence), no step repeats.
    assert calls["save"] == [2, 4, 6]
    assert calls["wait"] >= 1
    calls["save"].clear()
    calls["wait"] = 0

    # Crash path: the iterator explodes mid-run; the finally must still
    # drain the checkpointer so the step-2 save commits.
    def exploding():
        it = _synth_iter(6)
        for i, batch in enumerate(it):
            if i == 3:
                raise RuntimeError("boom")
            yield batch

    cfg2 = _smoke_config(tmp_path, checkpoint_every_secs=0.0)
    tr2 = _trainer(cfg2)
    tr2.checkpointer = SpyCheckpointer(cfg2.checkpoint_dir)
    with pytest.raises(RuntimeError, match="boom"):
        tr2.fit(exploding(), num_steps=20)
    assert calls["wait"] >= 1
    assert set(calls["save"]) <= {8}  # only log-boundary saves happened
    tr2.checkpointer.close()


def test_checkpointer_bounded_wait_times_out():
    from sav_tpu.train.checkpoint import Checkpointer

    ckpt = Checkpointer.__new__(Checkpointer)  # no orbax manager needed

    class _StuckMgr:
        def wait_until_finished(self):
            time.sleep(10.0)

    ckpt._mgr = _StuckMgr()
    t0 = time.perf_counter()
    assert ckpt.wait(timeout_s=0.2) is False
    assert time.perf_counter() - t0 < 2.0


def test_detect_opt_layout_paths():
    from sav_tpu.train.checkpoint import detect_opt_layout

    per_leaf = [("opt_state", "1", "mu", "dense", "kernel"),
                ("opt_state", "1", "nu", "dense", "kernel")]
    flat = [("opt_state", "1", "0", "mu"), ("opt_state", "1", "0", "nu")]
    ema = flat + [("opt_state", "3", "ema", "dense", "kernel")]
    assert detect_opt_layout(per_leaf) == {"fused": False, "ema": False}
    assert detect_opt_layout(flat) == {"fused": True, "ema": False}
    assert detect_opt_layout(ema) == {"fused": True, "ema": True}
    assert detect_opt_layout([("opt_state", "0")])["fused"] is None


def test_watchdog_drains_checkpointer_before_exit():
    """The exit-4 path waits (bounded) for in-flight async saves before
    os._exit abandons them — and a wedged checkpointer cannot stall the
    guaranteed-exit contract."""
    from sav_tpu.obs.watchdog import HangWatchdog

    events = []

    class _Ckpt:
        def wait(self, timeout_s=None):
            events.append(("wait", timeout_s))
            return True

    wd = HangWatchdog(
        0.2, poll_s=0.05, checkpointer=_Ckpt(), stream=StringIO(),
        exit_fn=lambda code: events.append(("exit", code)),
    )
    wd.start()
    assert wd.fired.wait(5.0)
    wd.stop()
    assert events[0][0] == "wait" and events[0][1] is not None
    assert events[-1] == ("exit", 4)


# ----------------------------------------------------------- real-child e2e


def _child_cmd(tmp_path, steps=20, extra=()):
    return [
        sys.executable, TRAIN_PY,
        "--preset", "elastic_smoke", "--synth-data", "--platform", "cpu",
        "--steps", str(steps), "--seed", "0",
        "-c", str(tmp_path / "ckpt"), "--log-dir", str(tmp_path),
        "--checkpoint-every-steps", "4",
        *extra,
    ]


def _wait_for(predicate, timeout_s, what):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        if predicate():
            return
        time.sleep(0.2)
    raise AssertionError(f"timed out waiting for {what}")


def _heartbeat_step(log_dir, pid):
    from sav_tpu.train.supervisor import read_attempt_heartbeats

    beats = read_attempt_heartbeats(str(log_dir), pid)
    return beats[-1]["step"] if beats else None


def _metrics_lines(log_dir):
    out = []
    with open(os.path.join(str(log_dir), "metrics.jsonl")) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    pass
    return out


def test_sigkill_resume_is_step_exact(tmp_path):
    """Kill a real training child mid-epoch; the rerun must resume from
    the committed checkpoint with the SAME rng recipe and the SAME next
    batch (recorder blake2b fingerprint vs the recomputed uninterrupted
    schedule), and the re-logged overlap windows must reproduce the
    killed run's losses bit-for-bit."""
    child = subprocess.Popen(
        _child_cmd(tmp_path), stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        _wait_for(
            lambda: (latest_checkpoint_step(str(tmp_path / "ckpt")) or 0) >= 4
            and (_heartbeat_step(tmp_path, child.pid) or 0) >= 10,
            timeout_s=180,
            what="a committed checkpoint and step >= 10",
        )
        os.kill(child.pid, signal.SIGKILL)
    finally:
        child.wait()
    assert child.returncode == -9
    resumed_from = latest_checkpoint_step(str(tmp_path / "ckpt"))
    assert resumed_from and resumed_from >= 4
    killed_losses = {
        int(r["step"]): r["loss"] for r in _metrics_lines(tmp_path)
        if "loss" in r
    }
    assert killed_losses, "the killed run logged no windows"

    rerun = subprocess.run(
        _child_cmd(tmp_path), capture_output=True, text=True, timeout=300
    )
    assert rerun.returncode == 0, rerun.stderr[-2000:]
    manifest = json.load(open(tmp_path / "manifest.json"))
    assert manifest["outcome"] == "ok"
    resume = manifest["notes"]["resume"]
    # Step-exact: resumed from a committed checkpoint, not epoch 0...
    assert resume["from_step"] >= resumed_from > 0
    assert "fold_in" in resume["rng"]  # same rng: derivation is (seed, step)
    # ...and the first batch is the uninterrupted schedule's, bit-for-bit.
    expected = batch_fingerprint(synth_batch(
        seed=0, position=resume["from_step"] + 1, batch_size=8,
        image_size=32, num_classes=10,
    ))["hash"]
    assert resume["next_batch_hash"] == expected

    # Loss continues: windows logged by BOTH runs (between the resume
    # point and the kill) must agree exactly — same state, same batches,
    # same rng. metrics.jsonl appends, so later lines are the rerun's.
    all_lines = _metrics_lines(tmp_path)
    rerun_losses = {}
    for r in all_lines:
        if "loss" in r:
            rerun_losses[int(r["step"])] = r["loss"]  # last occurrence wins
    overlap = [
        s for s in killed_losses
        if s > resume["from_step"] and s in rerun_losses
    ]
    assert overlap, "no overlap windows — kill/checkpoint cadence broken"
    for s in overlap:
        assert rerun_losses[s] == killed_losses[s], (
            f"loss at step {s} not bit-continued"
        )
    assert max(rerun_losses) == 20  # ran to completion


def test_chaos_soak_smoke_two_kills_one_nan(tmp_path):
    """The acceptance-criteria soak, CPU-scaled: 2 injected SIGKILLs + 1
    planted NaN in one supervised run. The harness itself verifies the
    chain (≥99% accounting, step-exact resume hashes, NaN skipped
    exactly once, loss bit-continued vs an uninterrupted reference);
    this test asserts the verification PASSED and the render tools read
    the chain."""
    proc = subprocess.run(
        [
            sys.executable, os.path.join(ROOT, "tools", "chaos_soak.py"),
            "--log-dir", str(tmp_path),
            "--steps", "24",
            "--kill-at-steps", "6,14",
            "--nan-at-step", "18",
            "--checkpoint-every-steps", "4",
            "--backoff", "0.2",
            "--json",
        ],
        capture_output=True, text=True, timeout=420,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr[-2000:]
    summary = json.loads(proc.stdout)
    assert summary["verified"], summary["problems"]
    assert summary["attempts"] == 4  # 1 + 2 kills + 1 nonfinite restart
    assert summary["restart_reasons"].count("killed:SIGKILL") == 2
    assert summary["restart_reasons"].count("nonfinite") == 1
    assert summary["skipped_steps"] == [18]
    assert summary["accounted_frac"] >= 0.99
    assert 0.0 < summary["goodput_frac"] < 1.0
    assert summary["resume_hash_checks"] >= 2
    assert summary["loss_continuity"]["max_abs_diff"] == 0.0
    assert summary["loss_continuity"]["final_step"] == 24

    # The chain renders through run_report (--chain auto-detects) and
    # fleet_status folds the supervisor headline into the fleet view.
    report = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "run_report.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=60,
    )
    assert report.returncode == 0
    assert "Supervisor chain: 4 attempt(s), outcome=ok" in report.stdout
    assert "rewind-and-skip decided here: step(s) [18]" in report.stdout
    assert "skip set armed: step(s) [18]" in report.stdout
    assert "killed:SIGKILL" in report.stdout
    fleet = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "fleet_status.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=60,
    )
    assert fleet.returncode == 0
    assert "Supervisor chain: 4 attempt(s)" in fleet.stdout

    # Single-attempt degradation: the reference run inside the soak dir
    # was never supervised — run_report must degrade gracefully there.
    ref = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "run_report.py"),
         str(tmp_path / "reference"), "--chain"],
        capture_output=True, text=True, timeout=60,
    )
    assert ref.returncode == 0
    assert "no supervisor chain" in ref.stdout


# ------------------------------------------------------- CLI + sentinel


def test_supervise_requires_checkpoint_dir():
    proc = subprocess.run(
        [sys.executable, TRAIN_PY, "--supervise", "--synth-data",
         "--platform", "cpu", "--steps", "2"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 2
    assert "needs -c" in proc.stderr


def test_sentinel_gates_goodput_frac(tmp_path):
    """regression_sentinel scores the supervisor chain's goodput_frac
    (higher-better): a collapse past the MAD gate regresses; healthy
    history stays clean; unsupervised records are skipped, not
    zero-filled."""
    from sav_tpu.obs.manifest import MANIFEST_SCHEMA

    def write(name, gf):
        doc = {
            "schema": MANIFEST_SCHEMA, "kind": "supervisor",
            "outcome": "ok", "metrics": {"goodput_frac": gf},
            "notes": {}, "error": None,
        }
        (tmp_path / name).write_text(json.dumps(doc))

    sentinel = os.path.join(ROOT, "tools", "regression_sentinel.py")
    write("r1.json", 0.991)
    write("r2.json", 0.993)
    write("r3.json", 0.992)
    clean = subprocess.run(
        [sys.executable, sentinel, "--metric", "goodput_frac", "--",
         str(tmp_path / "r1.json"), str(tmp_path / "r2.json"),
         str(tmp_path / "r3.json")],
        capture_output=True, text=True, timeout=60,
    )
    assert clean.returncode == 0, clean.stdout + clean.stderr
    write("r4.json", 0.62)  # preemptions started eating real wall time
    flagged = subprocess.run(
        [sys.executable, sentinel, "--metric", "goodput_frac", "--json",
         "--", str(tmp_path / "r1.json"), str(tmp_path / "r2.json"),
         str(tmp_path / "r3.json"), str(tmp_path / "r4.json")],
        capture_output=True, text=True, timeout=60,
    )
    assert flagged.returncode == 1
    payload = json.loads(flagged.stdout)
    verdicts = {v["metric"]: v for v in payload["verdicts"]}
    assert verdicts["goodput_frac"]["regressed"]
