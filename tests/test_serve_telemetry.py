"""Serve telemetry (sav_tpu/serve/telemetry.py) — ISSUE 11.

Unit tier (stdlib-only, no jax): span stamping under a fake clock
(every request's stamps monotone and lifecycle-ordered), the
sliding-window sketch against the exact percentile, the live window's
graceful empty-state, the ledger's windowed rebase (final summary
bit-identical with the window on or off), SLO burn-window arithmetic
pins, the chrome-trace export round-tripped through ``obs/traceview``,
serve heartbeat schema + offline aggregation, and the structural
zero-sync proof that the batcher/telemetry import surface never pulls
in jax.

Engine tier (tiny ViT on CPU): complete 8-stage span timelines on real
requests, the live-stats view before the first completed batch (no
IndexError — the bugfix satellite), the induced-latency-spike e2e
(slow-request exemplar naming the stage that ate the latency + exactly
one bounded anomaly capture), the telemetry-on/off throughput A/B
(within 2%), and the ``serve_status`` / ``run_report --serve`` /
sentinel ``slo_hit_frac`` surfaces.
"""

import gzip
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from sav_tpu.obs import traceview
from sav_tpu.serve.batcher import DynamicBatcher
from sav_tpu.serve.bucketing import BucketLadder
from sav_tpu.serve.latency import LatencyLedger, percentile
from sav_tpu.serve.telemetry import (
    INTERVALS,
    STAGES,
    LiveWindow,
    RequestTrace,
    ServeTelemetry,
    SlidingWindow,
    SLOTracker,
    SpanRing,
    aggregate_serve,
    dominant_stage,
    export_chrome_trace,
    find_exemplars,
    intervals,
    stamp,
    trace_record,
    write_request_trace,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(__file__), "sentinel_fixtures")


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ------------------------------------------------------------- span tier


def test_stamp_intervals_and_dominant_stage():
    clock = FakeClock()
    trace = RequestTrace(7, 0.1, clock())
    walk = [
        ("admit", 0.001), ("batch_formed", 0.004), ("placed", 0.005),
        ("dispatched", 0.006), ("executed", 0.030), ("depadded", 0.0305),
        ("completed", 0.031),
    ]
    for stage, t in walk:
        stamp(trace, stage, t)
    stages = intervals(trace.stamps)
    assert stages["admission"] == pytest.approx(0.001)
    assert stages["queue"] == pytest.approx(0.003)
    assert stages["device"] == pytest.approx(0.024)
    assert dominant_stage(stages) == "device"
    # Every lifecycle interval is derivable from a full walk.
    assert set(stages) == {name for name, _, _ in INTERVALS}
    # stamp() on an untraced request is a no-op, never an error.
    stamp(None, "admit", 1.0)
    rec = trace_record(
        trace, latency_s=0.031, overrun_s=-0.069, bucket=4, batch_n=3
    )
    assert rec["rid"] == 7
    assert rec["hit"] is True
    assert rec["dominant_stage"] == "device"
    assert rec["stages_ms"]["device"] == pytest.approx(24.0)


def test_batcher_stamps_spans_under_fake_clock():
    """The drain's span contract, deterministically: submit -> admit ->
    batch_formed stamps appear in lifecycle order, monotone in the fake
    clock, and batch_formed carries the SAME instant for every request
    in the batch (one clock read per formed batch)."""
    clock = FakeClock()
    telemetry = ServeTelemetry(clock=clock)
    batcher = DynamicBatcher(
        BucketLadder([1, 2]), step_time_fn=lambda b: 0.0,
        default_deadline_s=1.0, clock=clock,
    )
    traces = []
    for _ in range(2):
        trace = telemetry.begin_trace(1.0)
        traces.append(trace)
        batcher.submit("x", trace=trace)
        clock.advance(0.01)
    formed = batcher.next_batch()
    assert len(formed.requests) == 2
    for trace in traces:
        names = [s for s, _ in trace.stamps]
        assert names == ["submit", "admit", "batch_formed"]
        times = [t for _, t in trace.stamps]
        assert times == sorted(times)
    formed_ts = {t for trace in traces for s, t in trace.stamps
                 if s == "batch_formed"}
    assert len(formed_ts) == 1
    assert formed_ts == {formed.formed_t}
    batcher.close()


def test_span_ring_bounded():
    ring = SpanRing(3)
    for i in range(10):
        ring.append({"rid": i})
    assert len(ring) == 3
    assert ring.appended == 10
    assert [r["rid"] for r in ring.records()] == [7, 8, 9]
    with pytest.raises(ValueError):
        SpanRing(0)


def test_chrome_export_roundtrips_through_traceview(tmp_path):
    """The golden request-trace round trip: a deterministic ring ->
    chrome events -> *.trace.json.gz -> traceview.load_trace +
    request_spans, with stage durations pinned — request timelines read
    through the same machinery as device profiles."""
    clock = FakeClock(10.0)
    trace = RequestTrace(3, 0.05, clock())
    for stage, t in [
        ("admit", 10.001), ("batch_formed", 10.002), ("placed", 10.003),
        ("dispatched", 10.004), ("executed", 10.024),
        ("depadded", 10.0245), ("completed", 10.025),
    ]:
        stamp(trace, stage, t)
    rec = trace_record(
        trace, latency_s=0.025, overrun_s=-0.025, bucket=2, batch_n=2
    )
    doc = export_chrome_trace([rec])
    names = [e.get("name") for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert names == [name for name, _, _ in INTERVALS]
    path = str(tmp_path / "serve_traces" / "requests.trace.json.gz")
    assert write_request_trace(path, [rec]) == path
    # traceview's discovery + loader find and parse it like any capture.
    assert traceview.find_traces(str(tmp_path)) == [path]
    events = traceview.load_trace(path)
    spans = traceview.request_spans(events)
    assert set(spans) == {3}
    view = spans[3]
    assert view["bucket"] == 2
    assert view["dominant_stage"] == "device"
    stages = {name: dur for name, _, dur in view["stages"]}
    assert stages["device"] == pytest.approx(20.0, abs=0.01)
    assert stages["queue"] == pytest.approx(1.0, abs=0.01)
    assert view["total_ms"] == pytest.approx(25.0, abs=0.1)
    # A device-profile trace has no request plane: empty, not an error.
    assert traceview.request_spans(
        [{"ph": "X", "name": "fusion.1", "ts": 0, "dur": 5}]
    ) == {}


def test_trace_report_renders_request_timelines(tmp_path):
    clock = FakeClock(0.0)
    trace = RequestTrace(1, 0.01, clock())
    for stage, t in [
        ("admit", 0.001), ("batch_formed", 0.002), ("placed", 0.003),
        ("dispatched", 0.004), ("executed", 0.030), ("depadded", 0.031),
        ("completed", 0.032),
    ]:
        stamp(trace, stage, t)
    rec = trace_record(
        trace, latency_s=0.032, overrun_s=0.022, bucket=1, batch_n=1
    )
    write_request_trace(
        str(tmp_path / "requests.trace.json.gz"), [rec]
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "trace_report.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=120, cwd=ROOT,
    )
    assert proc.returncode == 0, proc.stderr
    assert "serve request timelines: 1 request(s)" in proc.stdout
    assert "OVERRAN deadline by 22.0 ms — device dominated" in proc.stdout


# ----------------------------------------------------------- window tier


def test_sliding_window_matches_exact_percentile_under_cap():
    clock = FakeClock()
    window = SlidingWindow(10.0, max_samples=1024, clock=clock)
    rng = np.random.default_rng(0)
    values = [float(v) for v in rng.uniform(1.0, 50.0, 200)]
    for v in values:
        window.observe(v)
        clock.advance(0.01)
    # Everything fits in both the time window and the cap: EXACT.
    for q in (50.0, 95.0, 99.0):
        assert window.percentile(q) == percentile(sorted(values), q)


def test_sliding_window_time_eviction_and_cap_tolerance():
    clock = FakeClock()
    window = SlidingWindow(1.0, max_samples=64, clock=clock)
    for v in (100.0, 200.0):
        window.observe(v)
    clock.advance(2.0)  # both now stale
    assert window.percentile(99.0) is None
    assert window.count() == 0
    # Over the cap: percentiles are exact over the newest max_samples —
    # the bounded-staleness approximation, pinned against the exact
    # tail.
    values = [float(i) for i in range(200)]
    for v in values:
        window.observe(v)
    retained = values[-64:]
    assert window.count() == 64
    assert window.percentile(50.0) == percentile(retained, 50.0)
    with pytest.raises(ValueError):
        SlidingWindow(0.0)
    with pytest.raises(ValueError):
        SlidingWindow(1.0, max_samples=0)


def test_live_window_graceful_before_first_batch_then_exact():
    """The bugfix satellite's unit half: a live snapshot before any
    completed batch is all Nones/zeros — never an IndexError."""
    clock = FakeClock()
    window = LiveWindow(30.0, clock=clock)
    empty = window.snapshot()
    assert empty["requests"] == 0
    assert empty["p50_ms"] is None
    assert empty["p99_ms"] is None
    assert empty["occupancy"] is None
    assert empty["throughput_rps"] == 0.0
    window.observe_window(
        latencies_s=[0.010, 0.020, 0.030], overruns_s=[-0.1, -0.1, 0.002],
        bucket=4, queue_depth=5, step_s=0.008,
    )
    clock.advance(1.0)
    window.observe_shed(2)
    snap = window.snapshot()
    assert snap["requests"] == 3
    assert snap["batches"] == 1
    assert snap["p50_ms"] == 20.0
    assert snap["queue_depth_max"] == 5
    assert snap["occupancy"] == 0.75
    assert snap["padding_waste_frac"] == 0.25
    assert snap["overruns"] == 1
    assert snap["shed"] == 2
    # Time passes beyond the window: everything ages out gracefully.
    clock.advance(60.0)
    aged = window.snapshot()
    assert aged["requests"] == 0 and aged["p99_ms"] is None


def test_ledger_windowed_rebase_final_summary_bit_identical():
    """The acceptance pin: the ledger's FINAL numbers are bit-identical
    with the live window attached or not — same observation stream,
    byte-equal summary()/flat_metrics()."""
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    plain = LatencyLedger(clock=clock)
    windowed = LatencyLedger(
        clock=clock, window=LiveWindow(30.0, clock=clock)
    )
    for ledger in (plain, windowed):
        ledger.start()
    stream = [
        dict(bucket=4, latencies_s=[0.01, 0.02, 0.03],
             overruns_s=[-0.05, -0.04, 0.002], queue_depth=5, step_s=0.008),
        dict(bucket=1, latencies_s=[0.04], overruns_s=[-0.1],
             queue_depth=0, step_s=0.004),
    ]
    for i, obs in enumerate(stream):
        t[0] = float(i + 1)
        for ledger in (plain, windowed):
            ledger.observe_batch(**obs)
    for ledger in (plain, windowed):
        ledger.observe_rejected(2)
    assert plain.summary() == windowed.summary()
    assert plain.flat_metrics() == windowed.flat_metrics()
    assert json.dumps(plain.summary(), sort_keys=True) == json.dumps(
        windowed.summary(), sort_keys=True
    )
    # Only the windowed one has a live view; the plain one says so.
    assert plain.live() is None
    assert windowed.live()["requests"] == 4


# -------------------------------------------------------------- SLO tier


def test_slo_burn_window_arithmetic_pins():
    clock = FakeClock()
    slo = SLOTracker(
        target=0.9, fast_window_s=10.0, slow_window_s=100.0,
        burn_threshold=2.0, clock=clock,
    )
    # Empty: no burn, no alert, hit_frac None (not 1.0, not 0.0).
    state = slo.state()
    assert state["burn_fast"] is None and state["burn_rate"] is None
    assert state["hit_frac"] is None and state["burning"] is False
    # 9 hits + 1 miss: miss_frac 0.1 == the 0.1 budget -> burn 1.0.
    for i in range(10):
        slo.observe_request(i != 0)
        clock.advance(0.1)
    state = slo.state()
    assert state["hit_frac"] == pytest.approx(0.9)
    assert state["burn_fast"] == pytest.approx(1.0)
    assert state["burn_slow"] == pytest.approx(1.0)
    assert state["burning"] is False  # burn 1.0 <= threshold 2.0
    # A miss storm: 5 misses in a row -> fast window burns hot.
    for _ in range(5):
        slo.observe_request(False)
        clock.advance(0.1)
    state = slo.state()
    assert state["burn_fast"] == pytest.approx((6 / 15) / 0.1)
    assert state["burning"] is True  # both windows past the threshold
    # The fast window forgets; the slow window remembers: after 20s of
    # clean traffic the fast burn is back to 0 but the slow one still
    # carries the storm — the two-window AND stops alerting (recovered),
    # while burn_rate (slow) still reports the budget spend.
    for _ in range(200):
        slo.observe_request(True)
        clock.advance(0.1)
    state = slo.state()
    assert state["burn_fast"] == 0.0
    assert state["burn_slow"] > 0.0
    assert state["burning"] is False
    assert state["burn_rate"] == state["burn_slow"]
    assert state["requests"] == 215 and state["misses"] == 6


def test_slo_validation():
    with pytest.raises(ValueError, match="target"):
        SLOTracker(target=1.0)
    with pytest.raises(ValueError, match="shorter"):
        SLOTracker(fast_window_s=60.0, slow_window_s=60.0)


def test_shed_requests_count_as_slo_misses():
    clock = FakeClock()
    telemetry = ServeTelemetry(clock=clock)
    telemetry.observe_shed(3)
    state = telemetry.slo.state()
    assert state["requests"] == 3 and state["misses"] == 3
    assert telemetry.stats()["shed"] == 3.0


# -------------------------------------------------- heartbeats + offline


def _write_serve_beats(log_dir, proc, payloads):
    from sav_tpu.obs.fleet import HeartbeatWriter

    writer = HeartbeatWriter(str(log_dir), process_index=proc,
                             process_count=2)
    for payload in payloads:
        writer.serve_beat(payload)
    writer.close("ok")


def _beat(requests, p99, queue, rps, *, burning=False, shed=0):
    return {
        "up_s": 12.0,
        "requests": requests,
        "batches": requests,
        "shed": shed,
        "queued": queue,
        "inflight": 1,
        "w": {
            "window_s": 30.0, "requests": requests, "p50_ms": p99 / 2,
            "p95_ms": p99 * 0.9, "p99_ms": p99, "throughput_rps": rps,
            "queue_depth_last": queue, "queue_depth_avg": queue,
            "queue_depth_max": queue, "occupancy": 0.9,
            "padding_waste_frac": 0.1, "overruns": 0, "shed": shed,
        },
        "slo": {
            "target": 0.99, "hit_frac": 0.97 if burning else 0.999,
            "burn_fast": 5.0 if burning else 0.1,
            "burn_slow": 3.0 if burning else 0.1,
            "burn_rate": 3.0 if burning else 0.1,
            "burning": burning,
        },
        "exemplars": 1 if burning else 0,
    }


def test_serve_heartbeat_schema_and_aggregation(tmp_path):
    """kind=serve lines ride the PR-7 fleet substrate and aggregate to
    the per-replica router view: p99 / queue / occupancy / SLO burn per
    replica plus fleet totals."""
    _write_serve_beats(
        tmp_path, 0, [_beat(40, 20.0, 2, 100.0), _beat(80, 21.0, 3, 110.0)]
    )
    _write_serve_beats(
        tmp_path, 1,
        [_beat(35, 30.0, 9, 90.0), _beat(70, 45.0, 12, 80.0, burning=True,
                                         shed=5)],
    )
    # The raw lines carry the schema contract.
    with open(tmp_path / "fleet" / "proc_0.jsonl") as f:
        first = json.loads(f.readline())
    assert first["kind"] == "serve"
    assert first["proc"] == 0 and first["procs"] == 2
    assert first["w"]["p99_ms"] == 20.0
    assert first["slo"]["target"] == 0.99
    assert "t" in first and "host" in first and "pid" in first
    summary = aggregate_serve(str(tmp_path))
    replicas = summary["replicas"]
    assert set(replicas) == {"0", "1"}
    assert replicas["0"]["p99_ms"] == 21.0
    assert replicas["0"]["queue_depth"] == 3
    assert replicas["0"]["occupancy"] == 0.9
    assert replicas["0"]["burning"] is False
    assert replicas["1"]["burning"] is True
    assert replicas["1"]["shed"] == 5
    assert replicas["1"]["median_p99_ms"] == pytest.approx(37.5)
    fleet = summary["fleet"]
    assert fleet["replicas"] == 2
    assert fleet["throughput_rps"] == pytest.approx(190.0)
    assert fleet["worst_p99_ms"] == 45.0
    assert fleet["burning"] == [1]
    assert len(summary["timeline"]) == 4
    # Training-heartbeat-only dirs aggregate to no replicas.
    assert aggregate_serve(str(tmp_path / "nothing"))["replicas"] == {}


def test_capacity_stamp_and_alert_rules_ride_the_beat(
    tmp_path, monkeypatch
):
    """ISSUE 19: a replica with a measured step publishes
    ``capacity_rps`` (max_batch / step_s_avg) in every beat, evaluates
    the armed alert rules at beat cadence (built-in SLO rule + the
    SAV_ALERT_RULES env seam), stamps active rule names on the line,
    and resolves open episodes at close."""
    from sav_tpu.obs.alerts import episodes, read_alerts
    from sav_tpu.obs.fleet import HeartbeatWriter

    rules_path = tmp_path / "rules.json"
    rules_path.write_text(json.dumps({"rules": [{
        "name": "hot-p99", "severity": "warn",
        "when": [{"metric": "w.p99_ms", "op": ">", "value": 40.0}],
    }]}))
    monkeypatch.setenv("SAV_ALERT_RULES", str(rules_path))
    clock = FakeClock(100.0)
    writer = HeartbeatWriter(str(tmp_path), process_index=0, clock=clock)
    telemetry = ServeTelemetry(
        str(tmp_path), clock=clock, wall_clock=clock, writer=writer,
        max_batch=8, heartbeat_secs=0.0,
    )
    # Armed rule set: the built-in SLO burn rule, the quality set
    # (ISSUE 20 — armed alongside, never inside default_rules), then
    # the env rule.
    from sav_tpu.obs.alerts import quality_rules

    assert [r.name for r in telemetry.alerts.rules] == (
        ["slo-burn"] + [r.name for r in quality_rules()] + ["hot-p99"]
    )
    # A measured 20 ms step at max_batch 8 -> 400 rows/s capacity.
    telemetry.window.observe_window(
        latencies_s=[0.08], overruns_s=[], bucket=8, queue_depth=1,
        step_s=0.02,
    )
    telemetry.serve_beat()
    with open(tmp_path / "fleet" / "proc_0.jsonl") as f:
        beat = json.loads(f.readline())
    assert beat["capacity_rps"] == pytest.approx(400.0)
    # 80 ms latency > 40 ms rule threshold: firing, stamped on the line.
    assert beat["alerts"] == ["hot-p99"]
    # Close resolves the open episode (the emitter outlives no episode).
    summary = telemetry.close("ok")
    events = read_alerts(str(tmp_path))
    assert [(e["rule"], e["event"]) for e in events] == [
        ("hot-p99", "firing"), ("hot-p99", "resolved"),
    ]
    assert episodes(events)["hot-p99"]["active"] is False
    assert summary["alerts"]["episodes"] == {"hot-p99": 1}
    # No writer -> no engine armed; nothing to evaluate, nothing breaks.
    bare = ServeTelemetry(clock=FakeClock())
    assert bare.alerts is None


def test_capacity_absent_without_measured_step(tmp_path):
    """Skip-not-zero-fill: no measured step (or no max_batch) means NO
    capacity_rps key — the fold must never read an unmeasured replica
    as zero capacity."""
    from sav_tpu.obs.fleet import HeartbeatWriter

    clock = FakeClock(10.0)
    writer = HeartbeatWriter(str(tmp_path), process_index=0, clock=clock)
    telemetry = ServeTelemetry(
        str(tmp_path), clock=clock, wall_clock=clock, writer=writer,
        max_batch=8,
    )
    telemetry.serve_beat()  # window empty: step_s_avg is None
    with open(tmp_path / "fleet" / "proc_0.jsonl") as f:
        beat = json.loads(f.readline())
    assert "capacity_rps" not in beat
    telemetry.close("ok")


def test_fleet_status_renders_serve_replicas(tmp_path):
    _write_serve_beats(tmp_path, 0, [_beat(40, 20.0, 2, 100.0)])
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "fleet_status.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=120, cwd=ROOT,
    )
    assert proc.returncode == 0, proc.stderr
    assert "Serve replicas: 1" in proc.stdout
    assert "replica 0: p99 20.0 ms" in proc.stdout
    as_json = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "fleet_status.py"),
         "--json", str(tmp_path)],
        capture_output=True, text=True, timeout=120, cwd=ROOT,
    )
    doc = json.loads(as_json.stdout)
    assert doc["serve"]["replicas"]["0"]["p99_ms"] == 20.0


def test_serve_status_cli_live_dir_and_exit_codes(tmp_path):
    """The mid-run observability acceptance: serve_status on a LIVE log
    dir (heartbeats flowing, manifest still 'running') reports windowed
    p99/queue/occupancy from artifacts alone; exit 2 on a bad dir."""
    _write_serve_beats(
        tmp_path, 0, [_beat(40, 20.0, 2, 100.0), _beat(80, 22.5, 4, 105.0)]
    )
    # A live (unfinalized) manifest — the process is still serving.
    with open(tmp_path / "manifest-serve-live.json", "w") as f:
        json.dump({"schema": 1, "kind": "serve", "outcome": "running",
                   "notes": {}, "metrics": {}}, f)
    # One slow-request exemplar bundle.
    os.makedirs(tmp_path / "serve_traces")
    with open(tmp_path / "serve_traces" / "slow_0000_req9.json", "w") as f:
        json.dump({
            "schema": 1, "kind": "slow_exemplar", "rid": 9,
            "latency_ms": 180.0, "deadline_ms": 100.0, "overrun_ms": 80.0,
            "dominant_stage": "queue",
            "stages_ms": {"queue": 150.0, "device": 25.0},
        }, f)
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "serve_status.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=120, cwd=ROOT,
    )
    assert proc.returncode == 0, proc.stderr
    assert "p99 22.5 ms" in proc.stdout
    assert "queue 4" in proc.stdout
    assert "occupancy 90%" in proc.stdout
    assert "outcome=running" in proc.stdout and "live" in proc.stdout
    assert "queue dominated" in proc.stdout
    as_json = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "serve_status.py"),
         "--json", str(tmp_path)],
        capture_output=True, text=True, timeout=120, cwd=ROOT,
    )
    doc = json.loads(as_json.stdout)
    assert doc["replicas"]["0"]["p99_ms"] == 22.5
    assert len(doc["exemplars"]) == 1
    bad = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "serve_status.py"),
         str(tmp_path / "no_such_dir")],
        capture_output=True, text=True, timeout=120, cwd=ROOT,
    )
    assert bad.returncode == 2


def test_run_report_serve_section_and_pre_telemetry_degrade(tmp_path):
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import run_report
    finally:
        sys.path.pop(0)
    import io

    # r11-era dir: finalized serve manifest + heartbeats + an exemplar.
    live = tmp_path / "r11"
    os.makedirs(live)
    _write_serve_beats(live, 0, [_beat(40, 20.0, 2, 100.0)])
    with open(live / "manifest-serve-x.json", "w") as f:
        json.dump({
            "schema": 1, "kind": "serve", "outcome": "ok", "notes": {},
            "metrics": {"serve/p99_latency_ms": 21.0,
                        "serve/throughput_rps": 100.0,
                        "serve/slo_hit_frac": 0.999,
                        "serve/burn_rate": 0.1},
        }, f)
    out = io.StringIO()
    run_report.report_serve(str(live), out)
    text = out.getvalue()
    assert "outcome=ok" in text
    assert "p99 21.0 ms" in text and "SLO hit 99.90%" in text
    assert "serve replica 0" in text
    # PR-10-era dir: manifest only — graceful "(no serve telemetry" note.
    old = tmp_path / "r10"
    os.makedirs(old)
    with open(old / "manifest-serve-old.json", "w") as f:
        json.dump({
            "schema": 1, "kind": "serve", "outcome": "ok", "notes": {},
            "metrics": {"serve/p99_latency_ms": 30.0,
                        "serve/throughput_rps": 90.0},
        }, f)
    out = io.StringIO()
    run_report.report_serve(str(old), out)
    text = out.getvalue()
    assert "p99 30.0 ms" in text
    assert "(no serve telemetry" in text
    # And the main() auto-detection renders the section for a serve dir.
    rc = run_report.main([str(live)])
    assert rc == 0


# ----------------------------------------------------- sentinel surface


def test_sentinel_scores_slo_fixtures_both_directions(capsys):
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import regression_sentinel as sentinel
    finally:
        sys.path.pop(0)
    assert sentinel.main([os.path.join(FIXTURES, "slo_clean")]) == 0
    assert "ok      slo_hit_frac" in capsys.readouterr().out
    assert sentinel.main(
        ["--json", os.path.join(FIXTURES, "slo_regressed")]
    ) == 1
    report = json.loads(capsys.readouterr().out)
    flagged = {v["metric"] for v in report["verdicts"] if v["regressed"]}
    assert flagged == {"slo_hit_frac"}


def test_sentinel_skips_records_lacking_slo_hit_frac():
    """The attention_core_frac presence contract for slo_hit_frac:
    PR-10-era serve records (no SLO tracker) are skipped, never
    zero-filled, and a pre-telemetry candidate after r11 history is not
    scorable."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        from regression_sentinel import judge_metric
    finally:
        sys.path.pop(0)
    from sav_tpu.obs.manifest import normalize_run_record

    def r11(slo, i):
        return normalize_run_record(
            {"outcome": "ok", "p99_latency_ms": 21.0,
             "serve_throughput": 400.0, "slo_hit_frac": slo},
            label=f"s{i}", index=i,
        )

    def r10(i):
        return normalize_run_record(
            {"outcome": "ok", "p99_latency_ms": 21.0,
             "serve_throughput": 400.0},
            label=f"old{i}", index=i,
        )

    history = [r10(0), r11(0.995, 1), r11(0.992, 2), r11(0.994, 3),
               r11(0.993, 4)]
    verdict = judge_metric(
        history, "slo_hit_frac", k=3.5, rel_floor=0.05, min_history=2
    )
    assert verdict is not None and not verdict.regressed
    assert judge_metric(
        [r10(i) for i in range(5)], "slo_hit_frac",
        k=3.5, rel_floor=0.05, min_history=2,
    ) is None
    assert judge_metric(
        history + [r10(5)], "slo_hit_frac",
        k=3.5, rel_floor=0.05, min_history=2,
    ) is None
    # Manifest shape: serve/slo_hit_frac surfaces as the metric name.
    rec = normalize_run_record({
        "schema": 1, "outcome": "ok",
        "metrics": {"serve/slo_hit_frac": 0.99},
    })
    assert rec.metrics["slo_hit_frac"] == 0.99


# ----------------------------------------------- structural no-sync proof


def test_batcher_drain_telemetry_is_structurally_sync_free():
    """The thread-guard twin of savlint SAV116, proved structurally: the
    batcher + telemetry import surface (everything the drain and the
    span/window/heartbeat paths execute) never imports jax — a device
    sync is unreachable from the drain by construction."""
    code = (
        "import sys\n"
        "import sav_tpu.serve.batcher, sav_tpu.serve.telemetry\n"
        "import sav_tpu.serve.latency\n"
        "assert 'jax' not in sys.modules, 'drain surface imported jax'\n"
        "assert 'numpy' not in sys.modules\n"
        "print('CLEAN')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=120, cwd=ROOT,
    )
    assert proc.returncode == 0, proc.stderr
    assert "CLEAN" in proc.stdout


# ------------------------------------------------------------ engine tier


def _tiny_config(**overrides):
    from sav_tpu.serve.engine import ServeConfig

    base = dict(
        model_name="vit_ti_patch16",
        num_classes=10,
        image_size=32,
        model_overrides={"num_layers": 1},
        buckets=[1, 2, 4],
        max_queue=128,
        deadline_ms=2000.0,
    )
    base.update(overrides)
    return ServeConfig(**base)


def _requests(n, image_size=32, seed=0):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(0, 256, (image_size, image_size, 3), dtype=np.uint8)
        for _ in range(n)
    ]


def test_engine_spans_complete_monotone_and_manifest_slo(tmp_path):
    """Real requests carry the full 8-stage walk, monotone; the ring
    holds them; the manifest carries slo_hit_frac end to end."""
    from sav_tpu.serve.engine import ServeEngine

    engine = ServeEngine(
        _tiny_config(buckets=[1, 4], deadline_ms=500.0,
                     log_dir=str(tmp_path), heartbeat_secs=0.2)
    )
    with engine:
        futures = [engine.submit(img) for img in _requests(8)]
        for f in futures:
            f.result(timeout=30.0)
        time.sleep(0.5)  # let at least one heartbeat fire
    engine.stop()
    telemetry = engine._telemetry
    records = telemetry.ring.records()
    assert len(records) == 8
    for rec in records:
        names = [s for s, _ in rec["stamps"]]
        assert names == list(STAGES), names
        times = [t for _, t in rec["stamps"]]
        assert times == sorted(times), "stamps not monotone"
        assert rec["dominant_stage"] in {
            name for name, _, _ in INTERVALS
        }
    assert telemetry.stats()["heartbeats"] >= 2  # cadence + final beat
    # Heartbeats landed in the fleet stream with the serve schema.
    beats = aggregate_serve(str(tmp_path))
    assert beats["replicas"]["0"]["requests"] == 8
    # slo_hit_frac flowed engine -> manifest -> normalize_run_record.
    from sav_tpu.obs.manifest import normalize_run_record

    manifests = [f for f in os.listdir(tmp_path) if f.startswith("manifest")]
    with open(os.path.join(tmp_path, manifests[0])) as f:
        data = json.load(f)
    assert data["metrics"]["serve/slo_hit_frac"] == 1.0
    assert data["metrics"]["serve/burn_rate"] == 0.0
    assert data["notes"]["serve_telemetry"]["slo"]["target"] == 0.99
    record = normalize_run_record(data, label="serve")
    assert record.metrics["slo_hit_frac"] == 1.0
    # The span ring's chrome export is on disk (replica-namespaced like
    # proc_<i>.jsonl) and traceview-readable.
    import glob as _glob

    ring_paths = _glob.glob(os.path.join(
        str(tmp_path), "serve_traces", "requests_proc*.trace.json.gz"
    ))
    assert len(ring_paths) == 1
    spans = traceview.request_spans(traceview.load_trace(ring_paths[0]))
    assert len(spans) == 8


def test_engine_live_stats_graceful_before_first_batch(tmp_path):
    """The bugfix satellite, engine half: live percentiles before the
    first completed batch are None (no IndexError), and a zero-request
    run finalizes an honest manifest WITHOUT slo_hit_frac (skip, not
    zero-fill)."""
    from sav_tpu.serve.engine import ServeEngine

    engine = ServeEngine(
        _tiny_config(buckets=[1], log_dir=str(tmp_path),
                     heartbeat_secs=0.1)
    )
    with engine:
        time.sleep(0.25)  # heartbeats fire on an idle engine
        stats = engine.stats()
        assert stats["live"]["p99_ms"] is None
        assert stats["live"]["requests"] == 0
        assert stats["slo"]["hit_frac"] is None
        assert stats["slo"]["burning"] is False
    summary = engine.stop()
    assert summary["requests"] == 0
    manifests = [f for f in os.listdir(tmp_path) if f.startswith("manifest")]
    with open(os.path.join(tmp_path, manifests[0])) as f:
        data = json.load(f)
    assert data["outcome"] == "ok"
    assert "serve/slo_hit_frac" not in data["metrics"]
    assert "serve/p99_latency_ms" not in data["metrics"]
    assert data["metrics"]["serve/requests"] == 0.0


def test_induced_spike_exemplar_names_stage_and_one_bounded_capture(
    tmp_path,
):
    """The acceptance e2e: an induced device-side latency spike yields
    >= 1 slow-request exemplar whose span timeline names the stage that
    ate the time (device, not queue), plus EXACTLY ONE bounded anomaly
    capture (armed/active/cooldown gating — PR-7's budget machinery)."""
    from sav_tpu.obs.autoprof import AutoProfiler
    from sav_tpu.serve.engine import ServeEngine

    starts, stops = [], []
    autoprof = AutoProfiler(
        str(tmp_path), trace_steps=2, max_captures=2,
        cooldown_steps=10_000,
        start_fn=lambda p: starts.append(p), stop_fn=lambda: stops.append(1),
        analyze=False,
    )
    seen = {"n": 0}

    def execute_hook(formed):
        seen["n"] += 1
        if seen["n"] == 30:
            time.sleep(0.8)  # one slow "device" batch

    engine = ServeEngine(
        _tiny_config(buckets=[1], deadline_ms=5000.0, log_dir=str(tmp_path),
                     heartbeat_secs=0.2, slow_sigma=20.0),
        autoprof=autoprof, execute_hook=execute_hook,
    )
    image = _requests(1)[0]
    with engine:
        for _ in range(40):
            engine.submit(image).result(timeout=30.0)
    engine.stop()
    # Exactly one bounded capture, serve-triggered, 2 batches wide.
    assert len(autoprof.captures) == 1
    capture = autoprof.captures[0]
    assert capture["trigger"] == "serve_p99_spike"
    assert capture["end_step"] - capture["start_step"] == 2
    assert len(starts) == 1 and len(stops) == 1
    # >= 1 exemplar, full span detail, device named as the eater. (CPU
    # jitter can flag an extra request; the INDUCED spike must be among
    # the exemplars regardless.)
    exemplars = find_exemplars(str(tmp_path))
    assert len(exemplars) >= 1
    by_rid = {e["rid"]: e for e in exemplars}
    assert 30 in by_rid, sorted(by_rid)
    slow = by_rid[30]
    assert slow["dominant_stage"] == "device"
    assert slow["stages_ms"]["device"] > 500.0
    assert slow["stages_ms"]["device"] > 10 * slow["stages_ms"]["queue"]
    assert slow["gate"]["window_n"] >= 16
    # serve_status renders the whole post-mortem from artifacts.
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "serve_status.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=120, cwd=ROOT,
    )
    assert proc.returncode == 0, proc.stderr
    assert "Slow-request exemplars: " in proc.stdout
    assert "device dominated" in proc.stdout
    assert "Anomaly captures: 1" in proc.stdout
    assert "serve_p99_spike" in proc.stdout


def test_telemetry_overhead_within_two_percent(tmp_path):
    """The overhead acceptance: with tracing + heartbeats + windows ON,
    flood throughput stays within 2% of telemetry-off, and the
    telemetry layer's own accounting stays under 100us/request (~1% of
    a realistic 10ms serving latency).

    Methodology: a deep-enough model that device time dominates (the
    ratio real serving runs at — on a 0.5ms/request toy, scheduler and
    GC noise of either arm dwarfs any telemetry signal), interleaved
    paired floods through BOTH live engines; each adjacent (on, off)
    pair yields a ratio and the best pair judges — a one-off scheduler
    hiccup slows its own pair's arm, not the verdict. A real 2%+
    telemetry tax depresses EVERY pair and still fails. GC is paused
    during the floods: the 100us/request gauge is cumulative
    perf-counter accounting, and a gen-2 collection landing inside a
    timed section bills tens of ms of interpreter housekeeping to the
    telemetry layer (late in a full tier-1 run the heap makes that
    routine) — the contract is the layer's own cost, not Python's."""
    import gc

    from sav_tpu.serve.engine import ServeEngine

    n = 256

    def mk(telemetry, log_dir):
        return ServeEngine(_tiny_config(
            image_size=64, model_overrides={"num_layers": 4},
            buckets=[1, 8], max_queue=1024, deadline_ms=120000.0,
            telemetry=telemetry, log_dir=log_dir, heartbeat_secs=0.5,
        ))

    images = _requests(n, image_size=64)
    engines = {
        "on": mk(True, str(tmp_path / "on")),
        "off": mk(False, None),
    }
    rates = {"on": [], "off": []}
    for engine in engines.values():
        engine.start()
    gc.collect()
    gc.disable()
    try:
        for _ in range(3):
            for label, engine in engines.items():
                t0 = time.monotonic()
                futures = [engine.submit(img) for img in images]
                for f in futures:
                    f.result(timeout=120.0)
                rates[label].append(n / (time.monotonic() - t0))
        stats = engines["on"].stats()
        per_request = (
            stats["telemetry"]["overhead_s"]
            / max(stats["telemetry"]["requests"], 1.0)
        )
        assert per_request <= 100e-6, stats["telemetry"]
        assert stats["telemetry"]["heartbeats"] >= 1
    finally:
        gc.enable()
        for engine in engines.values():
            engine.stop()
    ratios = [on / off for on, off in zip(rates["on"], rates["off"])]
    assert max(ratios) >= 0.98, (rates, ratios)


def test_serve_bench_zero_requests_honest_line(tmp_path):
    """The bugfix satellite, CLI half: serve_bench against an instantly
    drained (zero-request) engine emits an honest JSON line — requests
    0, null percentiles, no slo_hit_frac key — not a traceback."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    manifest = str(tmp_path / "manifest-zero.json")
    proc = subprocess.run(
        [
            sys.executable, os.path.join(ROOT, "tools", "serve_bench.py"),
            "--model", "vit_ti_patch16", "--num-classes", "10",
            "--image-size", "32",
            "--model-overrides", '{"num_layers": 1}',
            "--buckets", "1", "--requests", "0",
            "--heartbeat-secs", "0.2",
            "--backend-wait", "0", "--manifest", manifest,
        ],
        capture_output=True, text=True, timeout=420, cwd=ROOT, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["outcome"] == "ok"
    assert line["requests"] == 0
    assert line["p99_latency_ms"] is None
    assert line["serve_throughput"] == 0.0
    assert "slo_hit_frac" not in line
    assert line["telemetry"]["heartbeats"] >= 1
    with open(manifest) as f:
        data = json.load(f)
    assert data["outcome"] == "ok"
    assert "serve/slo_hit_frac" not in data["metrics"]


def test_begin_trace_adopts_propagated_fleet_id():
    """ISSUE 16 propagation: begin_trace ADOPTS a router-minted trace
    id (the wire header's ``r<pid>-<seq>``) instead of minting a
    replica-local one — the adoption is what joins the replica's spans
    to the router's in the offline fleet merge. Replica-local serving
    (no id to adopt) mints from the local counter exactly as before,
    and adoption does not consume local ids."""
    clock = FakeClock()
    telemetry = ServeTelemetry(clock=clock)
    local = telemetry.begin_trace(0.5)
    assert local.rid == 1
    adopted = telemetry.begin_trace(0.25, rid="r4242-7")
    assert adopted.rid == "r4242-7"
    assert adopted.deadline_s == 0.25
    assert adopted.stamps[0][0] == "submit"
    # The local counter did not advance for the adopted id.
    assert telemetry.begin_trace(0.5).rid == 2
