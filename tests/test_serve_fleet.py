"""Serve fleet (ISSUE 15): replica pool, wait-aware router, chaos proof.

Four tiers:

- **Structural**: the router/pool import surface never pulls in jax
  (routing cannot sync a device value by construction — the batcher's
  proof, fleet-wide).
- **Fake-clock router units**: the projected-wait arithmetic pinned to
  the batcher's, min-wait routing, admission shed, transport failover
  + reroute, heartbeat-silence down/recovery, straggler drain/resume,
  and close semantics — no processes, no wall clocks.
- **Artifact units**: serve heartbeat streams -> ``aggregate_serve``
  dead-replica suspicion + ``router_views`` (the router consumes the
  SAME flag the offline tools render), the fleet sentinel metrics both
  directions, and the supervisor's serve-mode chain.
- **REAL process tier**: two replica processes behind the router via
  ``serve_bench --replicas`` — the straggler smoke (injected +latency
  on rank 1 -> load shifts to rank 0; fleet identity via the
  ``SAV_FLEET_PROC`` override, the two_process_smoke technique) and
  the CHAOS PROOF (SIGKILL a replica mid-flood: exact accounting —
  nothing silently lost — bounded fleet p99, warm supervisor restart
  with ``compiled_from_scratch == 0``, router fold-back).
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from sav_tpu.serve.batcher import (  # noqa: E402
    DeadlineInfeasibleError,
    QueueFullError,
    ServeClosedError,
)
from sav_tpu.serve.router import (  # noqa: E402
    ReplicaShedError,
    ReplicaTransportError,
    Router,
    RouterShedError,
    projected_wait_s,
)

# --------------------------------------------------- structural no-jax


def test_router_fleet_surface_is_structurally_jax_free():
    """The router/pool import surface (everything admission, routing,
    spawning, and the wire client execute) never imports jax or numpy
    — the fleet-wide twin of the batcher's structural no-sync proof,
    and the supervisor-parent contract (the pool's parent must not be
    hangable by backend import)."""
    code = (
        "import sys\n"
        "import sav_tpu.serve.router, sav_tpu.serve.fleet\n"
        "import sav_tpu.serve.telemetry\n"
        "import sav_tpu.obs.rollup, sav_tpu.obs.alerts\n"
        "import tools.fleet_console\n"
        "assert 'jax' not in sys.modules, 'fleet surface imported jax'\n"
        "assert 'numpy' not in sys.modules\n"
        "print('CLEAN')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=120, cwd=ROOT,
    )
    assert proc.returncode == 0, proc.stderr
    assert "CLEAN" in proc.stdout


# ------------------------------------------------- projection math pins


def test_projected_wait_math_pinned():
    """The router's wait projection is the batcher's admission
    projection verbatim: ``inflight + ceil((queued + fresh) /
    max_batch)`` batches (the ``+ max_batch`` counting the request's
    own batch), each one estimated step."""
    # Idle replica: the request's own batch only.
    assert projected_wait_s(
        queued=0, inflight=0, fresh_outstanding=0, max_batch=8,
        est_step_s=0.05,
    ) == pytest.approx(0.05)
    # Batcher parity: 2 in flight + (20 queued + 4 fresh + 8)//8 = 5
    # queue batches -> 6 total (hand-computed against batcher.submit).
    assert projected_wait_s(
        queued=20, inflight=2, fresh_outstanding=4, max_batch=8,
        est_step_s=0.05,
    ) == pytest.approx(0.3)
    # An exactly-full queue ships (queued = max_batch -> 2 batches).
    assert projected_wait_s(
        queued=8, inflight=0, fresh_outstanding=0, max_batch=8,
        est_step_s=0.1,
    ) == pytest.approx(0.2)
    # Degenerate inputs clamp rather than explode.
    assert projected_wait_s(
        queued=0, inflight=0, fresh_outstanding=0, max_batch=8,
        est_step_s=-1.0,
    ) == 0.0
    assert projected_wait_s(
        queued=-5, inflight=-1, fresh_outstanding=0, max_batch=0,
        est_step_s=1.0,
    ) == pytest.approx(1.0)


# ------------------------------------------------ fake-clock router units


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def sleep(self, s):
        self.t += float(s)


class FakeTransport:
    """Per-rank scripted behavior: a result dict, an exception instance,
    or a callable. Records every send."""

    def __init__(self, behavior):
        self.behavior = dict(behavior)
        self.sends = []

    def send(self, rank, payload, meta, timeout_s):
        self.sends.append(rank)
        b = self.behavior[rank]
        if callable(b):
            b = b()
        if isinstance(b, BaseException):
            raise b
        return b


def _view(**kw):
    base = {
        "queued": 0, "inflight": 0, "est_step_s": 0.01, "p99_ms": 10.0,
        "last_beat_unix": 100.0, "beats": 5, "final": False,
        "suspect": False, "pid": 1000,
    }
    base.update(kw)
    return base


def make_router(views, transport, clock=None, wall=None, **kw):
    clock = clock or FakeClock()
    wall = wall or FakeClock(100.0)
    defaults = dict(
        views_fn=lambda: views,
        max_batch=2,
        default_step_s=0.01,
        default_deadline_s=1.0,
        refresh_secs=0.0,  # every admit refreshes (deterministic)
        workers=0,         # synchronous dispatch: admit blocks
        clock=clock,
        wall_clock=wall,
        sleep=clock.sleep,
    )
    defaults.update(kw)
    return Router(transport, **defaults), clock, wall


def test_route_picks_min_projected_wait_and_skips_unroutable():
    views = {
        0: _view(queued=8, est_step_s=0.1),   # 1 + (8+2)//2 = 6 -> 0.6
        1: _view(queued=0, est_step_s=0.1),   # 0 + 1 -> 0.1
        2: _view(queued=0, est_step_s=0.01),  # 0.01 — best
    }
    router, clock, _ = make_router(
        views, FakeTransport({0: {"ok": True}, 1: {"ok": True},
                              2: {"ok": True}})
    )
    assert router.route() == 2
    assert router.drain(2)
    assert router.route() == 1   # draining excluded, next-best wins
    views[1]["suspect"] = True
    router.refresh()
    assert router.route() == 0   # suspect down; only rank 0 remains
    router.close()


def test_admission_sheds_when_best_wait_blows_deadline():
    views = {
        0: _view(queued=20, inflight=2, est_step_s=0.2),
        1: _view(queued=40, inflight=1, est_step_s=0.2),
    }
    router, clock, _ = make_router(
        views, FakeTransport({0: {"ok": True}, 1: {"ok": True}})
    )
    # Best is rank 0: (2 + (20+2)//2) * 0.2 = 2.6s > the 1s default.
    with pytest.raises(DeadlineInfeasibleError):
        router.admit(b"x")
    assert router.stats()["shed_admit"] == 1
    # A deadline that fits is admitted and served.
    future = router.admit(b"x", deadline_s=10.0)
    assert future.result(timeout=0) == {"ok": True}
    router.close()
    assert router.summary()["shed"] == 1


def test_failover_marks_down_reroutes_and_recovers():
    views = {
        0: _view(est_step_s=0.001),
        1: _view(est_step_s=0.1),
    }
    transport = FakeTransport({
        0: ReplicaTransportError("connection reset"),
        1: {"ok": True, "pred": 7},
    })
    router, clock, wall = make_router(views, transport)
    # Rank 0 wins the projection, dies on send, gets marked down; the
    # request REROUTES to rank 1 and completes — never silently lost.
    future = router.admit(b"x")
    assert future.result(timeout=0)["pred"] == 7
    stats = router.stats()
    assert transport.sends == [0, 1]
    assert stats["transport_failures"] == 1
    assert stats["rerouted"] == 1
    assert stats["replicas"]["0"]["state"] == "down"
    assert "transport" in stats["replicas"]["0"]["down_reason"]
    assert stats["completed"] == 1
    # Recovery: a heartbeat NEWER than the down mark folds it back in.
    views[0]["last_beat_unix"] = wall() + 5.0
    transport.behavior[0] = {"ok": True, "pred": 0}
    router.refresh()
    assert router.stats()["replicas"]["0"]["state"] == "active"
    assert router.route() == 0
    router.close()


def test_all_replicas_down_sheds_at_deadline_never_hangs():
    views = {0: _view(), 1: _view()}
    transport = FakeTransport({
        0: ReplicaTransportError("dead"),
        1: ReplicaTransportError("dead"),
    })
    router, clock, _ = make_router(views, transport)
    future = router.admit(b"x", deadline_s=0.25)
    with pytest.raises(RouterShedError):
        future.result(timeout=0)
    stats = router.stats()
    assert stats["shed_deadline"] == 1
    assert stats["replicas"]["0"]["state"] == "down"
    assert stats["replicas"]["1"]["state"] == "down"
    # The fake clock advanced past the deadline via the poll sleeps —
    # the dispatch loop polls for recovery, it never busy-hangs.
    assert clock() >= 0.25
    router.close()


def test_straggler_loo_drains_and_resumes():
    views = {
        0: _view(p99_ms=10.0),
        1: _view(p99_ms=10.5),
        2: _view(p99_ms=200.0),  # the straggler
    }
    router, clock, _ = make_router(
        views,
        FakeTransport({0: {"ok": True}, 1: {"ok": True}, 2: {"ok": True}}),
    )
    router.refresh()
    stats = router.stats()["replicas"]
    assert stats["2"]["state"] == "draining"
    assert stats["0"]["state"] == stats["1"]["state"] == "active"
    assert router.route() in (0, 1)
    # Recovery: its window p99 returns to the pack -> resumed.
    views[2]["p99_ms"] = 11.0
    router.refresh()
    assert router.stats()["replicas"]["2"]["state"] == "active"
    router.close()


def test_never_drains_the_last_active_replica():
    views = {0: _view(p99_ms=500.0), 1: _view(p99_ms=10.0)}
    router, clock, _ = make_router(
        views, FakeTransport({0: {"ok": True}, 1: {"ok": True}})
    )
    views[1]["suspect"] = True  # rank 1 dies...
    router.refresh()
    stats = router.stats()["replicas"]
    # ...so rank 0, however slow its p99, must NOT also be drained.
    assert stats["1"]["state"] == "down"
    assert stats["0"]["state"] == "active"
    assert router.drain(0) is False
    assert router.route() == 0
    router.close()


def test_replica_shed_retries_until_deadline_then_sheds_honestly():
    views = {0: _view()}
    calls = {"n": 0}

    def shed_then_ok():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ReplicaShedError("replica queue full")
        return {"ok": True}

    transport = FakeTransport({0: shed_then_ok})
    router, clock, _ = make_router(views, transport)
    future = router.admit(b"x", deadline_s=5.0)
    assert future.result(timeout=0) == {"ok": True}
    assert calls["n"] == 3  # retried through the replica-side rejects
    router.close()


def test_close_fails_queued_and_stops_admission():
    views = {0: _view()}
    release = threading.Event()

    class BlockingTransport:
        def __init__(self):
            self.sent = 0

        def send(self, rank, payload, meta, timeout_s):
            self.sent += 1
            release.wait(10.0)
            return {"ok": True}

    transport = BlockingTransport()
    router = Router(
        transport, views_fn=lambda: views, workers=1, max_batch=2,
        refresh_secs=3600.0,
    )
    first = router.admit(b"a", deadline_s=30.0)
    deadline = time.monotonic() + 5.0
    while transport.sent == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert transport.sent == 1  # the single worker holds request A
    second = router.admit(b"b", deadline_s=30.0)
    # Release the held send shortly after close() starts draining, so
    # close's worker join returns promptly.
    threading.Timer(0.3, release.set).start()
    router.close()
    # B never shipped: failed loudly. A was already sent: completes.
    with pytest.raises(ServeClosedError):
        second.result(timeout=5.0)
    assert first.result(timeout=5.0) == {"ok": True}
    with pytest.raises(ServeClosedError):
        router.admit(b"c")


def test_router_rejects_past_max_inflight():
    views = {0: _view()}
    release = threading.Event()

    class HoldingTransport:
        def send(self, rank, payload, meta, timeout_s):
            release.wait(10.0)
            return {"ok": True}

    router = Router(
        HoldingTransport(), views_fn=lambda: views, workers=1,
        max_inflight=2, refresh_secs=3600.0,
    )
    router.admit(b"a", deadline_s=30.0)
    router.admit(b"b", deadline_s=30.0)
    with pytest.raises(QueueFullError):
        router.admit(b"c", deadline_s=30.0)
    assert router.stats()["rejected"] == 1
    release.set()
    router.close()


# ------------------------------------ distributed tracing units (ISSUE 16)


class StampingTransport:
    """A stamp-aware fake transport (the TcpTransport seam): stamps
    connect/sent at scripted clock instants, so the router's
    transport_send / replica_wait intervals have pinned durations."""

    supports_stamps = True

    def __init__(self, clock, *, connect_s=0.002, exchange_s=0.010,
                 result=None):
        self.clock = clock
        self.connect_s = connect_s
        self.exchange_s = exchange_s
        self.result = result if result is not None else {"ok": True}
        self.metas = []

    def send(self, rank, payload, meta, timeout_s, stamp_fn=None):
        self.metas.append(dict(meta))
        if stamp_fn is not None:
            stamp_fn("connect")
        self.clock.sleep(self.connect_s)
        if stamp_fn is not None:
            stamp_fn("sent")
        self.clock.sleep(self.exchange_s)
        r = self.result
        if callable(r):
            r = r()
        if isinstance(r, BaseException):
            raise r
        return r


def test_router_traces_full_walk_ring_and_export(tmp_path):
    """Tentpole part 1: every admitted request is one end-to-end trace.
    The router mints a globally unique ``r<pid>-<seq>`` id, propagates
    it on the wire header (``meta["trace"]``), stamps the full
    lifecycle through the transport's connect/sent seam, and exports
    the span ring as a chrome trace at close — in the router interval
    vocabulary, with rank/outcome join keys for the offline merge."""
    from sav_tpu.obs.traceview import _span_bounds, load_trace

    views = {0: _view()}
    clock = FakeClock()
    transport = StampingTransport(clock)
    router, _, _ = make_router(
        views, transport, clock=clock, log_dir=str(tmp_path)
    )
    first = router.admit(b"x", deadline_s=1.0)
    second = router.admit(b"y", deadline_s=1.0)
    assert first.result(timeout=0) == {"ok": True}
    assert second.result(timeout=0) == {"ok": True}
    rids = [m["trace"] for m in transport.metas]
    assert rids == [f"r{os.getpid()}-0", f"r{os.getpid()}-1"]
    assert len(set(rids)) == 2  # globally unique: pid + private seq
    summ = router.summary()
    assert summ["traces"] == {"ring": 2, "appended": 2}
    assert summ["router_overhead_ms"] >= 0.0
    router.close()
    path = os.path.join(
        str(tmp_path), "serve_traces", "requests_router.trace.json.gz"
    )
    assert os.path.exists(path)
    bounds = _span_bounds(load_trace(path))
    assert set(bounds) == set(rids)
    at = bounds[rids[0]]["at"]
    for name in ("admission", "router_queue", "route", "transport_send",
                 "replica_wait", "deliver"):
        assert name in at, f"missing {name} interval in the export"
    # transport_send spans the socket's connect->sent instants (2 ms);
    # the exchange itself is the opaque replica_wait (10 ms) the
    # offline merge decomposes.
    send = at["transport_send"]
    assert send[1] - send[0] == pytest.approx(2000.0)
    wait = at["replica_wait"]
    assert wait[1] - wait[0] == pytest.approx(10000.0)
    assert bounds[rids[0]]["args"]["rank"] == 0
    assert bounds[rids[0]]["args"]["outcome"] == "completed"


def test_reroute_records_attempt_sub_spans_and_candidate_waits():
    """A rerouted request's trace carries one sub-span per attempt
    (failed rank first, serving rank second) plus the candidate wait
    table the routing decision saw — the Tail-at-Scale WHY."""
    views = {0: _view(est_step_s=0.001), 1: _view(est_step_s=0.1)}
    transport = FakeTransport({
        0: ReplicaTransportError("connection reset"),
        1: {"ok": True},
    })
    router, clock, _ = make_router(views, transport)
    assert router.admit(b"x").result(timeout=0) == {"ok": True}
    rec = router._ring.records()[0]
    assert rec["outcome"] == "completed"
    assert [a["rank"] for a in rec["attempts"]] == [0, 1]
    assert [a["outcome"] for a in rec["attempts"]] == [
        "transport_error", "ok",
    ]
    assert set(rec["candidate_waits_ms"]) == {0, 1}
    assert rec["candidate_waits_ms"][0] < rec["candidate_waits_ms"][1]
    assert rec["dominant_stage"] is not None
    router.close()


def test_shed_trace_ends_with_honest_terminal_stamp():
    """A shed request's trace ends with the honest ``shed`` stamp —
    never a fake ``completed`` — and folds into the ring with its real
    outcome (the merged fleet view must show where load was refused)."""
    views = {0: _view()}
    transport = FakeTransport({0: ReplicaTransportError("dead")})
    router, clock, _ = make_router(views, transport)
    future = router.admit(b"x", deadline_s=0.25)
    with pytest.raises(RouterShedError):
        future.result(timeout=0)
    rec = router._ring.records()[0]
    assert rec["outcome"] == "shed"
    assert rec["stamps"][-1][0] == "shed"
    assert rec["hit"] is False
    assert rec["rank"] is None
    router.close()


def test_live_and_summary_agree_mid_run():
    """The ISSUE-16 bugfix pin: the throughput/percentiles serve_status
    reads MID-RUN (``live()``) are the same numbers ``summary()``
    reports at close — previously throughput existed only in the
    close-time summary, so a mid-run status could not be compared to
    the post-run record."""
    views = {0: _view()}
    clock = FakeClock()
    transport = StampingTransport(clock, connect_s=0.0, exchange_s=0.01)
    router, _, _ = make_router(views, transport, clock=clock)
    for _ in range(5):
        router.admit(b"x", deadline_s=5.0).result(timeout=0)
        clock.sleep(0.09)  # spaced load; last gap is BEFORE the reads
    clock.t = router._last_complete_t  # read at the last completion
    live = router.live()
    summ = router.summary()
    assert live["completed"] == summ["completed"] == 5
    assert live["throughput_rps"] == summ["throughput_rps"]
    assert live["w"] == summ["window"]
    # The windowed view divides by the EFFECTIVE span (run younger than
    # the window), so the windowed rate agrees with the span rate too.
    assert live["w"]["throughput_rps"] == summ["throughput_rps"]
    assert live["w"]["p99_ms"] == summ["latency_ms"]["p99"] == 10.0
    # Stage shares: the whole windowed latency sat in replica_wait.
    assert live["w"]["stage_shares"] == {"replica_wait": 1.0}
    assert live["router_overhead_ms"] == summ["router_overhead_ms"]
    router.close()


def test_router_heartbeats_on_fleet_substrate(tmp_path):
    """The router is a first-class fleet citizen: ``kind=router`` beats
    on the PR-7 heartbeat substrate (``fleet/router.jsonl``), carrying
    the live windowed view + the trace-overhead meter; close() appends
    a final beat so the last written state is the closing state."""
    from sav_tpu.obs.fleet import read_router_beats

    views = {0: _view()}
    clock = FakeClock()
    transport = StampingTransport(clock, connect_s=0.0, exchange_s=0.01)
    router, _, _ = make_router(
        views, transport, clock=clock, log_dir=str(tmp_path)
    )
    router.admit(b"x", deadline_s=5.0).result(timeout=0)
    assert router.router_beat() is True
    beats = read_router_beats(str(tmp_path))
    assert len(beats) == 1
    beat = beats[0]
    assert beat["kind"] == "router"
    assert beat["completed"] == 1
    assert beat["w"]["requests"] == 1
    assert beat["w"]["p99_ms"] == 10.0
    assert "router_overhead_ms" in beat
    assert os.path.exists(
        os.path.join(str(tmp_path), "fleet", "router.jsonl")
    )
    router.close()
    final = read_router_beats(str(tmp_path))
    assert len(final) == 2  # close() appended the closing beat
    assert final[-1]["completed"] == 1


def test_plain_transport_degrades_to_contiguous_stamps():
    """A transport WITHOUT the stamp seam still produces a contiguous
    walk: connect/sent collapse to the pre-send instant, so the whole
    exchange lands in replica_wait and no interval is missing."""
    from sav_tpu.serve.telemetry import ROUTER_INTERVALS, intervals

    views = {0: _view()}
    router, clock, _ = make_router(
        views, FakeTransport({0: {"ok": True}})
    )
    router.admit(b"x").result(timeout=0)
    rec = router._ring.records()[0]
    stages = intervals(rec["stamps"], ROUTER_INTERVALS)
    assert set(stages) >= {"transport_send", "replica_wait", "deliver"}
    assert stages["transport_send"] == 0.0
    router.close()


def test_tcp_transport_declares_the_stamp_seam():
    """The production TcpTransport is the stamp-aware side of the seam:
    the capability flag the router keys on, and the send/_exchange
    signatures that accept the stamp callback."""
    import inspect

    from sav_tpu.serve.fleet import TcpTransport

    assert TcpTransport.supports_stamps is True
    assert "stamp_fn" in inspect.signature(TcpTransport.send).parameters
    assert "stamp_fn" in inspect.signature(
        TcpTransport._exchange
    ).parameters


# --------------------------------- heartbeat artifacts -> suspicion/views


def _write_serve_stream(log_dir, proc, times, *, pid=1000, final=False,
                        step_s=0.01, queued=0, inflight=0, p99=12.0,
                        capacity=None, rps=50.0):
    os.makedirs(os.path.join(log_dir, "fleet"), exist_ok=True)
    path = os.path.join(log_dir, "fleet", f"proc_{proc}.jsonl")
    with open(path, "a") as f:
        for t in times:
            record = {
                "schema": 1, "kind": "serve", "proc": proc, "procs": 2,
                "t": t, "pid": pid, "queued": queued, "inflight": inflight,
                "requests": 10, "shed": 0,
                "w": {"p99_ms": p99, "step_s_avg": step_s,
                      "queue_depth_last": queued, "throughput_rps": rps},
                "slo": {"hit_frac": 1.0, "burn_rate": 0.0,
                        "burning": False},
            }
            if capacity is not None:
                record["capacity_rps"] = capacity
            f.write(json.dumps(record) + "\n")
        if final:
            f.write(json.dumps({
                "schema": 1, "kind": "final", "proc": proc,
                "outcome": "ok", "t": times[-1] + 0.1,
            }) + "\n")


def test_aggregate_serve_flags_silent_replica_and_router_consumes_it(
    tmp_path,
):
    """Satellite: a SIGKILLed serve replica no longer just vanishes —
    aggregate_serve lists it under ``suspects`` (silent > 3x the fleet
    median beat interval, no final record), its view carries
    ``suspect: true``, and ``router_views`` hands the router the SAME
    flag (one detection body, obs.fleet.silence_suspects)."""
    from sav_tpu.serve.telemetry import aggregate_serve, router_views

    log_dir = str(tmp_path)
    _write_serve_stream(log_dir, 0, [float(t) for t in range(11)])
    _write_serve_stream(
        log_dir, 1, [0.0, 1.0, 2.0, 3.0], pid=2000, queued=3, inflight=1,
        step_s=0.2, p99=80.0,
    )
    summary = aggregate_serve(log_dir, now=10.0)
    assert [s["proc"] for s in summary["suspects"]] == [1]
    assert summary["suspects"][0]["silent_s"] == pytest.approx(7.0)
    assert summary["replicas"]["1"]["suspect"] is True
    assert summary["replicas"]["0"]["suspect"] is False
    assert summary["fleet"]["suspects"] == [1]
    views = router_views(log_dir, now=10.0)
    assert views[1]["suspect"] is True
    assert views[1]["queued"] == 3
    assert views[1]["inflight"] == 1
    assert views[1]["est_step_s"] == pytest.approx(0.2)
    assert views[1]["p99_ms"] == pytest.approx(80.0)
    assert views[1]["pid"] == 2000
    assert views[0]["suspect"] is False
    # Offline default ('now' = newest beat anywhere): same flag.
    assert [s["proc"] for s in aggregate_serve(log_dir)["suspects"]] == [1]


def test_final_record_is_a_close_not_a_death(tmp_path):
    from sav_tpu.serve.telemetry import aggregate_serve

    log_dir = str(tmp_path)
    _write_serve_stream(log_dir, 0, [float(t) for t in range(11)])
    _write_serve_stream(log_dir, 1, [0.0, 1.0, 2.0], final=True)
    summary = aggregate_serve(log_dir, now=10.0)
    assert summary["suspects"] == []
    assert summary["replicas"]["1"]["final"] is True


def test_stale_final_does_not_close_a_restarted_replica(tmp_path):
    """Regression: the heartbeat streams are append-only across
    restarts, so a ``final`` from a PREVIOUS generation (a graceful
    stop before a pool restart over the same log dir) followed by
    fresh beats must NOT mark the replica closed — that would make the
    router permanently down every replica of a reused log dir and shed
    100% of the second run. Only a final at least as new as the newest
    beat counts."""
    from sav_tpu.serve.telemetry import aggregate_serve, router_views

    log_dir = str(tmp_path)
    _write_serve_stream(log_dir, 0, [float(t) for t in range(11)])
    # Generation 1: beats, then an orderly final. Generation 2 (pool
    # restart, new pid): fresh beats APPENDED after the final.
    _write_serve_stream(log_dir, 1, [0.0, 1.0, 2.0], pid=2000, final=True)
    _write_serve_stream(
        log_dir, 1, [8.0, 9.0, 10.0], pid=3000,
    )
    summary = aggregate_serve(log_dir, now=10.0)
    assert summary["replicas"]["1"]["final"] is False
    assert summary["replicas"]["1"]["pid"] == 3000
    assert summary["suspects"] == []
    views = router_views(log_dir, now=10.0)
    assert views[1]["final"] is False
    assert views[1]["suspect"] is False


def test_aggregate_serve_folds_capacity_and_headroom(tmp_path):
    """ISSUE 19: with capacity stamps on the beats and a rolled
    throughput series, aggregate_serve folds fleet capacity, the
    Theil–Sen load projection, and headroom_frac; without stamps the
    fold stays silent (skip-not-zero-fill)."""
    from sav_tpu.obs.rollup import Roller
    from sav_tpu.serve.telemetry import aggregate_serve

    log_dir = str(tmp_path)
    # 40 beats at 1 Hz per replica, throughput climbing 1 rps/s each.
    for proc in (0, 1):
        _write_serve_stream(
            log_dir, proc,
            [float(t) for t in range(40)],
            capacity=400.0, rps=100.0,
        )
    roller = Roller(log_dir)
    roller.roll_once()
    roller.flush()
    summary = aggregate_serve(log_dir, now=40.0)
    fleet = summary["fleet"]
    assert summary["replicas"]["0"]["capacity_rps"] == 400.0
    assert fleet["capacity_rps"] == 800.0
    # Flat 100 rps per replica -> flat 200 rps projection, headroom
    # (800 - 200) / 800 = 0.75.
    assert fleet["projected_rps"] == pytest.approx(200.0, rel=0.01)
    assert fleet["headroom_frac"] == pytest.approx(0.75, abs=0.01)
    assert fleet["load_rps"] == pytest.approx(200.0, rel=0.01)
    # Un-rolled dir: the beat-timeline fallback still projects.
    bare = str(tmp_path / "bare")
    for proc in (0, 1):
        _write_serve_stream(
            bare, proc, [float(t) for t in range(11)], capacity=150.0,
        )
    fleet2 = aggregate_serve(bare, now=11.0)["fleet"]
    assert fleet2["capacity_rps"] == 300.0
    assert isinstance(fleet2["headroom_frac"], float)
    # No capacity stamps anywhere -> NO capacity/headroom keys.
    plain = str(tmp_path / "plain")
    _write_serve_stream(plain, 0, [0.0, 1.0, 2.0])
    fleet3 = aggregate_serve(plain, now=3.0)["fleet"]
    assert "capacity_rps" not in fleet3
    assert "headroom_frac" not in fleet3


def test_read_heartbeats_tail_bound_reads_recent_lines_only(tmp_path):
    """The router's live view is tail-bounded: a refresh parses only
    each stream's trailing bytes (constant cost however long the run),
    dropping the partial first line of the mid-file seek — while the
    offline default still reads everything."""
    from sav_tpu.obs.fleet import read_heartbeats

    log_dir = str(tmp_path)
    _write_serve_stream(
        log_dir, 0, [float(t) for t in range(200)], p99=12.0
    )
    full = read_heartbeats(log_dir)[0]
    assert len(full) == 200
    tail = read_heartbeats(log_dir, tail_bytes=4096)[0]
    assert 0 < len(tail) < 200
    # The tail is the NEWEST suffix, whole lines only.
    assert [r["t"] for r in tail] == [r["t"] for r in full[-len(tail):]]
    # And the live router view built on it still carries the headline.
    from sav_tpu.serve.telemetry import router_views

    views = router_views(log_dir, now=199.0, tail_bytes=4096)
    assert views[0]["p99_ms"] == pytest.approx(12.0)
    assert views[0]["suspect"] is False


# ------------------------------------------------ fleet sentinel metrics


FIXDIR = os.path.join(os.path.dirname(__file__), "sentinel_fixtures")


def _sentinel(argv):
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import regression_sentinel
    finally:
        sys.path.pop(0)
    return regression_sentinel.main(argv)


def test_sentinel_scores_fleet_fixtures_both_directions(capsys):
    assert _sentinel([os.path.join(FIXDIR, "fleet_clean")]) == 0
    capsys.readouterr()
    assert _sentinel([os.path.join(FIXDIR, "fleet_regressed")]) == 1
    out = capsys.readouterr().out
    assert "fleet_p99_latency_ms" in out
    assert "fleet_throughput" in out


def test_sentinel_scores_headroom_both_directions(capsys):
    """fleet_headroom_frac (ISSUE 19): the capacity/headroom fold is
    sentinel-gated in BOTH directions — a hovering ~0.40 history stays
    clean, and a collapse to 0.10 flags even though latency and
    throughput stay flat (saturation risk surfaces before the tail
    moves; that is the whole point of the fold)."""
    assert _sentinel([os.path.join(FIXDIR, "headroom_clean")]) == 0
    out = capsys.readouterr().out
    assert "fleet_headroom_frac" in out
    assert _sentinel([os.path.join(FIXDIR, "headroom_regressed")]) == 1
    out = capsys.readouterr().out
    assert "REGRESS fleet_headroom_frac" in out
    assert "REGRESS fleet_p99_latency_ms" not in out  # tail stayed flat
    # Skip-not-zero-fill: records without capacity stamps (pre-19
    # fleet lines, manifests without the fold) never contribute.
    from sav_tpu.obs.manifest import MANIFEST_SCHEMA, normalize_run_record

    rec = normalize_run_record(
        {"outcome": "ok", "fleet_p99_latency_ms": 35.0,
         "fleet_throughput": 700.0, "fleet_headroom_frac": 0.4},
        label="new", index=0,
    )
    assert rec.metrics["fleet_headroom_frac"] == 0.4
    mrec = normalize_run_record(
        {"schema": MANIFEST_SCHEMA, "outcome": "ok",
         "kind": "serve_fleet", "metrics": {"fleet/headroom_frac": 0.37}},
        label="m", index=1,
    )
    assert mrec.metrics["fleet_headroom_frac"] == 0.37
    old = normalize_run_record(
        {"outcome": "ok", "fleet_p99_latency_ms": 35.0,
         "fleet_throughput": 700.0},
        label="old", index=2,
    )
    assert "fleet_headroom_frac" not in old.metrics


def test_sentinel_scores_router_overhead_both_directions(capsys):
    """router_overhead_ms (ISSUE 16): the router's self-accounted
    tracing cost is sentinel-gated — flat history stays ok, a jump past
    the 0.05 ms absolute floor flags (observability taxing the routing
    hot path IS a regression), while the surrounding fleet metrics stay
    clean in both fixture directions."""
    assert _sentinel([os.path.join(FIXDIR, "router_clean")]) == 0
    out = capsys.readouterr().out
    assert "router_overhead_ms" in out
    assert _sentinel([os.path.join(FIXDIR, "router_regressed")]) == 1
    out = capsys.readouterr().out
    assert "REGRESS router_overhead_ms" in out
    assert "REGRESS fleet" not in out  # only the overhead series moved


def test_router_overhead_skip_not_zero_fill():
    """Records lacking router_overhead_ms (pre-16 fleet records, plain
    serve records, training records) are SKIPPED, never zero-filled —
    the attention_core_frac presence contract — and the metric reads
    from both record shapes (bench line + serve_fleet manifest)."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        from regression_sentinel import judge_metric
    finally:
        sys.path.pop(0)
    from sav_tpu.obs.manifest import MANIFEST_SCHEMA, normalize_run_record

    traced = {
        "outcome": "ok", "fleet_p99_latency_ms": 35.0,
        "fleet_throughput": 700.0, "router_overhead_ms": 0.02,
    }
    rec = normalize_run_record(traced, label="traced", index=0)
    assert rec.metrics["router_overhead_ms"] == 0.02
    manifest = {
        "schema": MANIFEST_SCHEMA, "outcome": "ok", "kind": "serve_fleet",
        "metrics": {"fleet/router_overhead_ms": 0.03},
    }
    mrec = normalize_run_record(manifest, label="m", index=1)
    assert mrec.metrics["router_overhead_ms"] == 0.03
    # A pre-16 fleet record lacks it entirely — never zero-filled.
    untraced = normalize_run_record(
        {"outcome": "ok", "fleet_p99_latency_ms": 35.0,
         "fleet_throughput": 700.0},
        label="old", index=2,
    )
    assert "router_overhead_ms" not in untraced.metrics
    # Newest record lacking it -> unscorable, not re-judged stale.
    records = [
        normalize_run_record(dict(traced), label=f"t{i}", index=i)
        for i in range(3)
    ] + [untraced]
    assert judge_metric(
        records, "router_overhead_ms", k=3.5, rel_floor=0.05,
        min_history=2,
    ) is None


def test_fleet_metrics_skip_not_zero_fill():
    """A training record after fleet records must not zero-fill the
    fleet metrics (unscorable, the attention_core_frac contract), and
    fleet metrics read from both record shapes (line + manifest)."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        from regression_sentinel import judge_metric
    finally:
        sys.path.pop(0)
    from sav_tpu.obs.manifest import MANIFEST_SCHEMA, normalize_run_record

    fleet_line = {
        "outcome": "ok", "fleet_p99_latency_ms": 35.0,
        "fleet_throughput": 700.0,
    }
    rec = normalize_run_record(fleet_line, label="fleet", index=0)
    assert rec.metrics["fleet_p99_latency_ms"] == 35.0
    assert rec.metrics["fleet_throughput"] == 700.0
    assert "fleet" in rec.detail and "p99" in rec.detail
    manifest = {
        "schema": MANIFEST_SCHEMA, "outcome": "ok", "kind": "serve_fleet",
        "metrics": {"fleet/p99_latency_ms": 30.0,
                    "fleet/throughput_rps": 650.0},
    }
    mrec = normalize_run_record(manifest, label="m", index=1)
    assert mrec.metrics["fleet_p99_latency_ms"] == 30.0
    assert mrec.metrics["fleet_throughput"] == 650.0
    # Training record lacks them entirely — never zero-filled.
    train = normalize_run_record(
        {"outcome": "ok", "value": 100.0, "unit": "img/s"},
        label="train", index=2,
    )
    assert "fleet_p99_latency_ms" not in train.metrics
    # Newest record lacking the metric -> unscorable, not re-judged.
    records = [
        normalize_run_record(dict(fleet_line), label=f"f{i}", index=i)
        for i in range(3)
    ] + [train]
    assert judge_metric(
        records, "fleet_p99_latency_ms", k=3.5, rel_floor=0.05,
        min_history=2,
    ) is None


# ------------------------------------------- supervisor serve-mode chain


def test_supervisor_serve_mode_stop_and_restart(tmp_path):
    """Serve-mode chain semantics: a SIGKILLed serve child restarts
    (the PR-9 contract), and a REQUESTED stop ends the chain with
    outcome ok and zero lost wall — a terminating server is a
    completed serve, not a crash."""
    from sav_tpu.train.supervisor import Supervisor

    log_dir = str(tmp_path / "chain")
    os.makedirs(log_dir)
    manifest_src = str(tmp_path / "manifest-serve-r0.json")
    child = [sys.executable, "-c",
             "import time, json, sys; "
             f"open({manifest_src!r}, 'w').write(json.dumps("
             "{'schema': 1, 'outcome': 'running'})); "
             "time.sleep(600)"]
    sup = Supervisor(
        child, log_dir=log_dir, checkpoint_dir=None, max_restarts=2,
        backoff_base_s=0.05, backoff_max_s=0.1, capture=True,
        serve=True, manifest_src=manifest_src,
    )
    rc_holder = {}
    thread = threading.Thread(target=lambda: rc_holder.update(
        rc=sup.run()))
    thread.start()
    # Attempt 1: SIGKILL -> restart (serve chains restart on kill).
    deadline = time.monotonic() + 30.0
    while sup.child is None and time.monotonic() < deadline:
        time.sleep(0.02)
    assert sup.child is not None
    first_pid = sup.child.pid
    # Let the child register its manifest before the kill, so the
    # preservation path has something to copy aside.
    while not os.path.exists(manifest_src) and time.monotonic() < deadline:
        time.sleep(0.02)
    assert os.path.exists(manifest_src)
    os.kill(first_pid, 9)
    while (
        (sup.child is None or sup.child.pid == first_pid)
        and time.monotonic() < deadline
    ):
        time.sleep(0.02)
    assert sup.child.pid != first_pid, "supervisor did not restart"
    # Requested stop: chain ends ok even though the child dies by
    # signal.
    sup.request_stop()
    sup.child.terminate()
    thread.join(30.0)
    assert not thread.is_alive()
    assert rc_holder["rc"] == 0
    with open(os.path.join(log_dir, "supervisor.json")) as f:
        doc = json.load(f)
    assert doc["outcome"] == "ok"
    assert doc["notes"]["stop_requested"] is True
    attempts = doc["notes"]["chain"]["attempts"]
    assert len(attempts) == 2
    assert attempts[0]["restart_reason"] == "killed:SIGKILL"
    assert attempts[1]["stopped"] is True
    assert attempts[1]["restart_reason"] is None
    assert attempts[1]["lost_s"] == 0.0
    # The per-attempt manifest preservation followed manifest_src.
    assert os.path.exists(
        os.path.join(log_dir, "attempts", "attempt_001.manifest.json")
    )


def test_replica_flag_vocabulary_consistent_across_tools():
    """serve_fleet.add_model_args and serve_bench's parser declare the
    engine/model flag set independently, with replica_argv forwarding
    between them — pin the vocabulary so it cannot drift: every flag
    replica_argv emits is declared by add_model_args, is spelled in
    serve_bench's parser too (so `serve_bench --replicas` can set it),
    and round-trips through the replica-mode parser with its values
    intact."""
    import argparse

    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import serve_fleet
    finally:
        sys.path.pop(0)

    fleet_parser = argparse.ArgumentParser()
    serve_fleet.add_model_args(fleet_parser)
    fleet_flags = {
        a.option_strings[0]
        for a in fleet_parser._actions
        if a.option_strings
    }
    forwarded = {
        "--model", "--num-classes", "--image-size", "--backend",
        "--max-batch", "--max-queue", "--deadline-ms",
        "--heartbeat-secs", "--slo-target", "--model-overrides",
        "--buckets", "--checkpoint", "--layout-preset",
        "--compilation-cache-dir", "--attn-tune-cache",
        "--probe-every",
    }
    missing = forwarded - fleet_flags
    assert not missing, (
        f"replica_argv forwards {sorted(missing)} but add_model_args "
        "does not declare them"
    )
    with open(os.path.join(ROOT, "tools", "serve_bench.py")) as f:
        bench_src = f.read()
    for flag in sorted(forwarded):
        assert f'"{flag}"' in bench_src, (
            f"serve_bench's parser lost {flag} — fleet mode could no "
            "longer forward it to the replicas"
        )
    # Round trip: replica_argv's emitted argv parses cleanly back
    # through the replica-mode parser with the same values.
    ns = argparse.Namespace(
        model="vit_ti_patch16", num_classes=10, image_size=32,
        backend="auto", max_batch=2, max_queue=64, deadline_ms=500.0,
        heartbeat_secs=0.5, slo_target=0.99,
        model_overrides='{"num_layers": 1}', buckets="1,2",
        checkpoint=None, layout_preset=None,
        compilation_cache_dir="/tmp/cache", attn_tune_cache=None,
        probe_every=5.0,
    )
    argv = serve_fleet.replica_argv(ns, 1, "/tmp/logs")[2:]
    fleet_parser.add_argument("--replica-rank", type=int)
    fleet_parser.add_argument("--log-dir")
    fleet_parser.add_argument("--manifest")
    parsed = fleet_parser.parse_args(argv)
    assert parsed.model == "vit_ti_patch16"
    assert parsed.replica_rank == 1
    assert parsed.max_batch == 2
    assert parsed.deadline_ms == 500.0
    assert parsed.buckets == "1,2"
    assert parsed.model_overrides == '{"num_layers": 1}'
    assert parsed.compilation_cache_dir == "/tmp/cache"
    assert parsed.probe_every == 5.0
    assert parsed.manifest.endswith("manifest-serve-r1.json")


def test_pool_wait_ready_fails_fast_on_dead_chain(tmp_path):
    """A replica that crashes on startup exhausts its restart budget in
    seconds; wait_ready must surface that immediately (RuntimeError
    naming the rank) instead of sitting out the full startup timeout."""
    from sav_tpu.serve.fleet import ReplicaPool

    pool = ReplicaPool(
        replicas=1,
        child_argv_fn=lambda r: [
            sys.executable, "-c", "import sys; sys.exit(2)"
        ],
        log_dir=str(tmp_path),
        max_restarts=1,
        backoff_base_s=0.05,
    )
    pool.start()
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="supervisor chain ended"):
        pool.wait_ready(timeout_s=120.0)
    assert time.monotonic() - t0 < 30.0  # failed fast, not at timeout
    pool.stop()


def test_pool_endpoint_registry_roundtrip(tmp_path):
    from sav_tpu.serve.fleet import (
        pid_alive,
        read_endpoint,
        read_endpoints,
        write_endpoint,
    )

    log_dir = str(tmp_path)
    path = write_endpoint(
        log_dir, 1, host="127.0.0.1", port=4242,
        startup={"compiled_from_scratch": 0}, platform="cpu",
    )
    assert path and os.path.exists(path)
    doc = read_endpoint(log_dir, 1)
    assert doc["port"] == 4242
    assert doc["pid"] == os.getpid()
    assert doc["startup"]["compiled_from_scratch"] == 0
    assert read_endpoints(log_dir) == {1: doc}
    assert pid_alive(os.getpid())
    reaped = subprocess.Popen([sys.executable, "-c", "pass"])
    reaped.wait()
    assert not pid_alive(reaped.pid)  # fully reaped child
    assert not pid_alive(None)
    assert read_endpoint(log_dir, 7) is None


# --------------------------------------------- REAL two-process fleet tier


BENCH_TIMEOUT = 420


@pytest.fixture(scope="module")
def fleet_cache_dir(tmp_path_factory):
    """One persistent compile cache shared by every fleet bench in this
    module: the first replica startup compiles the (tiny, identical)
    executables from scratch, everything after warm-starts — which is
    also what makes the chaos test's ``compiled_from_scratch == 0``
    restart proof representative."""
    return str(tmp_path_factory.mktemp("fleet_xla_cache"))


def _run_fleet_bench(tmp_path, tag, cache_dir, extra, lockwatch=False,
                     env_extra=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    if env_extra:
        env.update(env_extra)
    if lockwatch:
        # Arm the runtime lock sanitizer (ISSUE 18): the router/
        # transport/telemetry locks are tracked and the observed
        # acquisition graph lands in log_dir/lockwatch.json. Only the
        # chaos proof runs armed — tracked locks add ~40µs to the
        # per-request dispatch path, which would pollute the smoke
        # test's strict ≤100µs tracing-overhead measurement.
        env["SAV_LOCKWATCH"] = "1"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    log_dir = str(tmp_path / tag)
    manifest = os.path.join(log_dir, f"manifest-fleet-{tag}.json")
    argv = [
        sys.executable, os.path.join(ROOT, "tools", "serve_bench.py"),
        "--model", "vit_ti_patch16", "--num-classes", "10",
        "--image-size", "32", "--model-overrides", '{"num_layers": 1}',
        # Bucket-1 ladder: every request ships immediately (no trickle
        # wait for a bucket to fill), so fleet latency measures routing
        # + service, not the batcher's deadline slack — the dynamic-
        # batching policy itself is test_serve.py's beat.
        "--buckets", "1", "--max-batch", "1",
        "--backend-wait", "0",
        "--heartbeat-secs", "0.3", "--router-refresh-secs", "0.2",
        "--compilation-cache-dir", cache_dir,
        "--manifest", manifest, "--log-dir", log_dir,
        "--replica-startup-timeout", "240",
    ] + extra
    proc = subprocess.run(
        argv, capture_output=True, text=True, timeout=BENCH_TIMEOUT,
        cwd=ROOT, env=env,
    )
    assert proc.returncode == 0, (
        f"serve_bench --replicas failed:\n{proc.stdout[-3000:]}\n"
        f"{proc.stderr[-3000:]}"
    )
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    return line, log_dir, manifest


@pytest.mark.usefixtures("fleet_cache_dir")
def test_fleet_smoke_two_replicas_router_shifts_load(
    tmp_path, fleet_cache_dir, monkeypatch
):
    """The tier-1 fleet serve smoke: TWO real replica processes (fleet
    identity via the SAV_FLEET_PROC override the pool sets — the
    two_process_smoke technique), one router, +0.35s injected per-batch
    latency on rank 1. The router must shift load toward rank 0 while
    rank 1 still serves (draining/straggler pressure, not exclusion),
    and the accounting must balance exactly.

    ISSUE 19 rides the same run: an operator latency rule (via the
    SAV_ALERT_RULES env seam) must produce EXACTLY ONE firing->resolved
    episode on the straggler, the capacity/headroom fold must land in
    the bench line and manifest metrics, and the ops console must
    render from rollups alone (zero raw-stream re-parses)."""
    rules_path = str(tmp_path / "alert_rules.json")
    with open(rules_path, "w") as f:
        json.dump({"rules": [{
            # The +0.35 s injected batch delay puts rank 1's windowed
            # p99 well over 250 ms; rank 0 stays in the tens of ms.
            "name": "slow-replica-p99", "severity": "warn",
            "when": [
                {"metric": "w.p99_ms", "op": ">", "value": 250.0},
            ],
            # Fire on the first hot beat; resolve only via the orderly
            # close (the injected delay never recovers in-run), so the
            # run yields exactly one episode.
            "for_s": 0, "resolve_s": 3600,
        }]}, f)
    line, log_dir, manifest = _run_fleet_bench(
        tmp_path, "smoke", fleet_cache_dir,
        [
            "--replicas", "2", "--requests", "48", "--rate", "0",
            "--deadline-ms", "4000", "--inject-delay", "1:0.35",
            "--probe-requests", "0", "--drain-timeout", "120",
        ],
        env_extra={"SAV_ALERT_RULES": rules_path},
    )
    assert line["outcome"] == "ok"
    assert line["replicas"] == 2
    acct = line["accounting"]
    assert acct["offered"] == 48
    assert acct["lost"] == 0, f"requests silently lost: {acct}"
    assert acct["errors"] == 0
    assert acct["completed"] + acct["shed"] + acct["closed"] == 48
    assert acct["completed"] >= 40  # the fleet actually served
    routed = {
        rank: v["routed"]
        for rank, v in line["router"]["replicas"].items()
    }
    assert routed["0"] > routed["1"], (
        f"router did not shift load away from the slow replica: {routed}"
    )
    assert line["router"]["replicas"]["0"]["completed"] > 0
    # Both replicas heartbeated into the shared dir under their own
    # identity; serve_status renders the fleet offline.
    status = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "serve_status.py"),
         "--json", log_dir],
        capture_output=True, text=True, timeout=120,
    )
    assert status.returncode == 0, status.stderr
    summary = json.loads(status.stdout)
    assert set(summary["replicas"]) == {"0", "1"}
    assert summary["router"]["completed"] == acct["completed"]
    # The fleet line is sentinel-scoreable.
    from sav_tpu.obs.manifest import normalize_run_record

    rec = normalize_run_record(line, label="smoke", index=0)
    assert rec.ok
    assert rec.metrics["fleet_p99_latency_ms"] > 0
    assert rec.metrics["fleet_throughput"] > 0
    with open(manifest) as f:
        mdoc = json.load(f)
    assert mdoc["kind"] == "serve_fleet"
    assert mdoc["outcome"] == "ok"
    assert mdoc["metrics"]["fleet/p99_latency_ms"] == (
        line["fleet_p99_latency_ms"]
    )
    # ---------------- distributed tracing acceptance (ISSUE 16) ----------
    # ONE merged chrome trace for the whole fleet run: the router's span
    # ring + both replicas' exports joined offline into contiguous
    # router->replica->router chains.
    from sav_tpu.obs.traceview import fleet_request_spans, load_trace

    traces = line["serve_traces"]
    assert traces["router"] and os.path.exists(traces["router"])
    assert len(traces["replicas"]) == 2
    assert traces["merged"] and traces["merged"].endswith(
        "fleet.trace.json.gz"
    )
    # The per-request stamp cost stays bounded (<= 100 us/request, the
    # acceptance contract), measured by the router's own meter.
    assert line["router_overhead_ms"] is not None
    assert line["router_overhead_ms"] <= 0.1, (
        f"router tracing overhead {line['router_overhead_ms']}ms/request "
        "blew the 100us contract"
    )
    merged = fleet_request_spans(log_dir)
    assert merged["requests"], "the merge joined no requests"
    full = {
        rid: e for rid, e in merged["requests"].items()
        if not e["router_only"]
    }
    assert full, "no request merged across processes (all router-only)"
    for rid, e in merged["requests"].items():
        stages = e["stages"]
        assert stages, f"{rid} merged with an empty chain"
        # Contiguous: each stage starts where the previous ended.
        for prev, cur in zip(stages, stages[1:]):
            assert cur[1] == pytest.approx(
                prev[1] + prev[2], abs=2e-3
            ), f"{rid} chain is not contiguous at {cur[0]}"
    # Per-request stage sums match the client-observed latency within
    # the stamped skew bound (plus the sub-ms pre-admit sliver and
    # rounding).
    for rid, e in full.items():
        client_ms = e["deadline_ms"] + e["overrun_ms"]
        skew = e["skew_ms"] or 0.0
        assert abs(client_ms - e["total_ms"]) <= skew + 10.0, (
            f"{rid}: merged chain {e['total_ms']}ms vs client "
            f"{client_ms}ms exceeds the {skew}ms skew bound"
        )
    # Every replica the merge used states its clock skew honestly.
    assert merged["replicas"], "no per-replica clock offset estimated"
    for proc, est in merged["replicas"].items():
        assert est["pairs"] >= 1
        assert est["skew_ms"] >= 0.0
    # The induced straggler (rank 1, +0.35 s per batch) shows up in the
    # fleet exemplars with the blame on the REPLICA side of the chain —
    # the cross-process attribution this PR exists for.
    exemplar_paths = sorted(
        p for p in os.listdir(os.path.join(log_dir, "serve_traces"))
        if p.startswith("slow_fleet_")
    )
    assert exemplar_paths, "no fleet exemplars written"
    exemplars = []
    for name in exemplar_paths:
        with open(os.path.join(log_dir, "serve_traces", name)) as f:
            exemplars.append(json.load(f))
    assert line["serve_traces"]["fleet_exemplars"] == len(exemplars)
    straggled = [
        e for e in exemplars
        if not e["router_only"]
        and e["dominant_stage"] in ("replica_queue", "device")
    ]
    assert straggled, (
        "no exemplar blamed the straggler's replica-side stages: "
        f"{[(e['rid'], e['dominant_stage']) for e in exemplars]}"
    )
    # The merged artifact is ONE trace every existing consumer reads.
    events = load_trace(traces["merged"])
    fleet_names = {
        e["args"]["name"] for e in events if e.get("ph") == "M"
    }
    assert fleet_names == {"Fleet Requests"}
    # The router heartbeated as a fleet citizen (kind=router stream),
    # and serve_status surfaced both the beats and the live window.
    from sav_tpu.obs.fleet import read_router_beats

    beats = read_router_beats(log_dir)
    assert beats, "router wrote no kind=router heartbeats"
    assert beats[-1]["completed"] == acct["completed"]
    assert summary["router_beats"] >= 1
    assert summary["router_live"]["completed"] == acct["completed"]
    # The manifest points at every trace artifact (run_report's hook).
    assert mdoc["notes"]["serve_traces"]["merged"] == traces["merged"]
    # -------------- fleet metrics pipeline acceptance (ISSUE 19) ---------
    # The straggler rule produced EXACTLY ONE firing->resolved episode,
    # fired by the slow replica, resolved at its orderly close.
    from sav_tpu.obs.alerts import episodes, read_alerts

    events = [
        e for e in read_alerts(log_dir) if e["rule"] == "slow-replica-p99"
    ]
    assert [(e["event"], e["proc"]) for e in events] == [
        ("firing", 1), ("resolved", 1),
    ], f"expected one firing->resolved episode on rank 1: {events}"
    eps = episodes(read_alerts(log_dir))["slow-replica-p99"]
    assert eps["fired"] == 1 and eps["resolved"] == 1
    assert eps["active"] is False
    # The episode is on the bench line and in the manifest notes.
    assert line["alerts"]["slow-replica-p99"]["fired"] == 1
    assert mdoc["notes"]["alerts"]["slow-replica-p99"]["fired"] == 1
    # Capacity/headroom fold: replicas stamped measured capacity_rps,
    # the fold summed it and projected load over the rollup series.
    assert line["fleet_capacity_rps"] > 0
    assert isinstance(line["fleet_headroom_frac"], float)
    assert -1.0 <= line["fleet_headroom_frac"] <= 1.0
    assert mdoc["metrics"]["fleet/headroom_frac"] == (
        line["fleet_headroom_frac"]
    )
    assert mdoc["notes"]["fleet"]["capacity_rps"] == (
        line["fleet_capacity_rps"]
    )
    assert rec.metrics["fleet_headroom_frac"] == (
        line["fleet_headroom_frac"]
    )
    # The router's heartbeat thread rolled IN-RUN (cursor + tiers exist
    # independent of the bench's post-run flush).
    assert os.path.exists(
        os.path.join(log_dir, "fleet", "rollup.cursor.json")
    )
    assert os.path.exists(
        os.path.join(log_dir, "fleet", "rollup_10.jsonl")
    )
    # The ops console renders from rollups + alerts ALONE: with the raw
    # heartbeat readers booby-trapped, gather() still renders and only
    # the instrumented rollup reader moved.
    import io

    from sav_tpu.obs import fleet as fleet_mod
    from sav_tpu.obs import rollup as rollup_mod

    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import fleet_console
    finally:
        sys.path.pop(0)

    def _boom(*a, **k):
        raise AssertionError(
            "console re-parsed a raw heartbeat stream"
        )

    monkeypatch.setattr(fleet_mod, "read_heartbeats", _boom)
    monkeypatch.setattr(fleet_mod, "read_router_beats", _boom)
    reads_before = rollup_mod.READS["read_rollup"]
    snapshot = fleet_console.gather(log_dir)
    rendered = io.StringIO()
    fleet_console.render(snapshot, rendered)
    assert rollup_mod.READS["read_rollup"] > reads_before
    assert snapshot["capacity_rps"] > 0
    assert isinstance(snapshot["headroom_frac"], float)
    assert set(snapshot["replicas"]) == {"0", "1"}
    assert snapshot["alerts"]["slow-replica-p99"]["fired"] == 1
    text = rendered.getvalue()
    assert "capacity" in text and "headroom" in text
    # And the user-facing CLI agrees (fresh process, --once --json).
    console = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "fleet_console.py"),
         "--once", "--json", log_dir],
        capture_output=True, text=True, timeout=60,
    )
    assert console.returncode == 0, console.stderr
    doc = json.loads(console.stdout)
    assert doc["headroom_frac"] == snapshot["headroom_frac"]
    assert subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "fleet_console.py"),
         "--once", str(tmp_path / "not_a_run")],
        capture_output=True, text=True, timeout=60,
    ).returncode == 2


def test_fleet_chaos_sigkill_mid_flood_bounded_p99_warm_restart(
    tmp_path, fleet_cache_dir
):
    """THE chaos proof (acceptance criterion): two real replicas under
    flood, SIGKILL rank 1 mid-load. Every accepted request completes or
    is honestly shed (none silently lost), fleet p99 stays bounded (no
    cliff — the tail never absorbs the restart outage, and it stays
    within a generous multiple of the single-replica baseline), the
    supervisor restarts the victim WARM (``compiled_from_scratch ==
    0``), and the router folds it back in (the post-restart probe burst
    lands requests on it)."""
    # Single-replica baseline first (also covers --replicas 1 and warms
    # the shared cache for the chaos replicas).
    base_line, _, _ = _run_fleet_bench(
        tmp_path, "baseline", fleet_cache_dir,
        [
            "--replicas", "1", "--requests", "24", "--rate", "0",
            "--deadline-ms", "4000", "--probe-requests", "0",
            "--drain-timeout", "120",
        ],
    )
    assert base_line["accounting"]["lost"] == 0
    p99_base = base_line["fleet_p99_latency_ms"]
    assert p99_base and p99_base > 0

    line, log_dir, manifest = _run_fleet_bench(
        tmp_path, "chaos", fleet_cache_dir,
        [
            "--replicas", "2", "--requests", "48", "--rate", "0",
            "--deadline-ms", "6000",
            "--chaos-kill-rank", "1", "--chaos-kill-at-frac", "0.4",
            "--chaos-recovery-timeout", "180",
            "--probe-requests", "12",
            "--max-restarts", "2", "--restart-backoff", "0.3",
            "--drain-timeout", "180",
        ],
        lockwatch=True,
    )
    assert line["outcome"] == "ok"
    # 1. Exact accounting: nothing silently lost, no errors. A stuck
    # future would surface as a drain TimeoutError -> errors, so
    # lost == 0 AND errors == 0 is the none-silently-dropped proof
    # even when overload sheds part of the load honestly.
    acct = line["accounting"]
    assert acct["offered"] == 48
    assert acct["lost"] == 0, f"requests silently lost: {acct}"
    assert acct["errors"] == 0
    assert acct["completed"] + acct["shed"] + acct["closed"] == 48
    assert acct["completed"] >= 32  # the fleet kept serving through it
    # 2. The kill really happened mid-load and the supervisor absorbed
    # it: exactly one restart, reason SIGKILL, warm from the cache.
    chaos = line["chaos"]
    assert chaos["killed_pid"]
    assert line["restarts"] == 1
    assert chaos["outage_s"] > 0.5  # a real multi-second process death
    restart = chaos["restart_startup"]
    assert restart["compiled_from_scratch"] == 0, (
        f"victim restart was not warm: {restart}"
    )
    assert line["startup_warm"]["1"] == 0
    # 3. Bounded fleet p99 — no cliff. A cliff is the tail absorbing
    # the restart: requests parked on the dead replica completing only
    # after the multi-second outage, i.e. p99 far PAST the deadline
    # contract. Bounded = within the admitted-request contract
    # (deadline + bounded completion slack) AND within a generous
    # multiple of the single-replica flood baseline (CPU CI noise
    # allowed for; the cliff alternative is orders of magnitude).
    p99 = line["fleet_p99_latency_ms"]
    assert p99 and p99 > 0
    assert p99 <= 6000.0 + 2000.0, (
        f"fleet p99 {p99}ms blew past the deadline contract — the tail "
        "absorbed the restart outage"
    )
    assert p99 <= max(25.0 * p99_base, 6000.0), (
        f"fleet p99 {p99}ms cliffed vs single-replica baseline "
        f"{p99_base}ms"
    )
    # 4. Rerouting did the absorbing: the victim's in-flight work came
    # back as transport failures and was rerouted, not dropped.
    assert line["transport_failures"] >= 1
    assert line["rerouted"] >= 1
    # 5. The router folded the restarted victim back in: the probe
    # burst landed requests on it.
    probe = line["probe_routed"]
    assert probe["1"] > 0, f"router never resumed routing to victim: {probe}"
    # 6. One sentinel-scoreable fleet line + finalized manifest.
    from sav_tpu.obs.manifest import normalize_run_record

    rec = normalize_run_record(line, label="chaos", index=0)
    assert rec.ok
    assert rec.metrics["fleet_p99_latency_ms"] == p99
    with open(manifest) as f:
        mdoc = json.load(f)
    assert mdoc["outcome"] == "ok"
    assert mdoc["metrics"]["fleet/restarts"] == 1.0
    assert mdoc["notes"]["fleet"]["chaos"]["rank"] == 1
    # The supervisor chain for the victim recorded the kill.
    with open(os.path.join(
        log_dir, "replicas", "rank_1", "supervisor.json"
    )) as f:
        chain = json.load(f)
    attempts = chain["notes"]["chain"]["attempts"]
    assert attempts[0]["restart_reason"] == "killed:SIGKILL"
    assert chain["outcome"] == "ok"  # requested stop at bench teardown
    # 7. Lock sanitizer acceptance (ISSUE 18): the whole chaos run —
    # flood, kill, reroute storm, warm restart, probe burst — executed
    # under lockwatch and observed ZERO lock-order inversions, and
    # every observed acquisition is one the static SAV122 graph
    # predicts (exit 0 from lockgraph's --observed cross-check; a
    # cycle or a linter blind spot would exit 1).
    lockwatch_path = os.path.join(log_dir, "lockwatch.json")
    with open(lockwatch_path) as f:
        lw = json.load(f)
    assert lw["cycles"] == [], (
        f"lock-order inversion observed during chaos: {lw['cycles']}"
    )
    assert "Router._lock" in lw["locks"]  # sanitizer was actually armed
    crosscheck = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "lockgraph.py"),
         "--observed", lockwatch_path],
        capture_output=True, text=True, timeout=120, cwd=ROOT,
    )
    assert crosscheck.returncode == 0, (
        f"observed lock graph inconsistent with static SAV122 graph:\n"
        f"{crosscheck.stdout}\n{crosscheck.stderr}"
    )
