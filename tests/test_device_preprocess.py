"""Device-side preprocessing (sav_tpu.ops.preprocess +
TrainConfig.device_preprocess): host ships post-augment uint8, the jitted
steps normalize and mix on device. Tests pin the host-parity contract the
module docstring promises."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from sav_tpu.ops import preprocess as pp



# Entire module is the expensive tier: mesh/kernel-heavy numerics sweeps.
pytestmark = pytest.mark.slow

def _uint8_images(n=8, size=32, seed=0):
    rng = np.random.default_rng(seed)
    images = rng.integers(0, 256, (n, size, size, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, (n,), dtype=np.int32)
    return images, labels


# ------------------------------------------------------------- normalize


def test_normalize_matches_host_normalize():
    tf = pytest.importorskip("tensorflow")
    from sav_tpu.data.pipeline import _normalize

    images, _ = _uint8_images()
    host = _normalize(tf.cast(tf.constant(images), tf.float32)).numpy()
    dev = np.asarray(pp.normalize_images(jnp.asarray(images), jnp.float32))
    np.testing.assert_allclose(dev, host, atol=1e-5)


def test_normalize_uint8_and_float_inputs_identical():
    images, _ = _uint8_images()
    a = pp.normalize_images(jnp.asarray(images), jnp.float32)
    b = pp.normalize_images(jnp.asarray(images, jnp.float32), jnp.float32)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------------------------ mixes


def test_mixup_is_convex_roll_combination():
    images, labels = _uint8_images()
    x = jnp.asarray(images)
    mixed, mix_labels, ratio = pp.mixup(jax.random.PRNGKey(0), x, jnp.asarray(labels))
    r = np.asarray(ratio)
    assert ((0.0 <= r) & (r <= 1.0)).all()
    expect = (
        r[:, None, None, None] * images.astype(np.float32)
        + (1.0 - r[:, None, None, None]) * np.roll(images, 1, 0).astype(np.float32)
    )
    np.testing.assert_allclose(np.asarray(mixed), expect, atol=1e-3)
    np.testing.assert_array_equal(np.asarray(mix_labels), np.roll(labels, 1, 0))


def test_cutmix_box_and_ratio_consistent():
    images, labels = _uint8_images(n=16, size=64)
    x = jnp.asarray(images)
    mixed, mix_labels, ratio = pp.cutmix(jax.random.PRNGKey(3), x, jnp.asarray(labels))
    mixed = np.asarray(mixed)
    rolled = np.roll(images, 1, 0).astype(np.float32)
    own = images.astype(np.float32)
    for i in range(len(images)):
        from_own = np.isclose(mixed[i], own[i]).all(-1)
        from_partner = np.isclose(mixed[i], rolled[i]).all(-1)
        # Every pixel comes from exactly one source (ignoring the rare
        # pixel where both sources agree), and the kept-area fraction is
        # the label ratio.
        assert (from_own | from_partner).all()
        assert abs(from_own.mean() - float(ratio[i])) < 0.02


def test_combined_policy_splits_halves():
    images, labels = _uint8_images(n=8, size=32)
    x = jnp.asarray(images)
    mixed, mix_labels, ratio = pp.mixup_and_cutmix(
        jax.random.PRNGKey(1), x, jnp.asarray(labels)
    )
    assert mixed.shape == x.shape
    # Halves roll within themselves, like the host combined policy.
    np.testing.assert_array_equal(np.asarray(mix_labels[:4]), np.roll(labels[:4], 1, 0))
    np.testing.assert_array_equal(np.asarray(mix_labels[4:]), np.roll(labels[4:], 1, 0))


def test_apply_mixes_none_spec_passthrough():
    images, labels = _uint8_images()
    out, ml, r = pp.apply_mixes(
        jax.random.PRNGKey(0), jnp.asarray(images), jnp.asarray(labels), None
    )
    assert ml is None and r is None
    np.testing.assert_array_equal(np.asarray(out), images.astype(np.float32))


# ------------------------------------------------------- pipeline contract


def test_load_device_preprocess_emits_uint8_without_mix_keys():
    tf = pytest.importorskip("tensorflow")
    from sav_tpu.data import Split, load

    images, labels = _uint8_images(n=32, size=48)
    it = load(
        Split.TRAIN,
        source=(images, labels),
        is_training=True,
        batch_dims=[8],
        image_size=32,
        augment_name="cutmix_mixup",
        device_preprocess=True,
        seed=0,
        process_index=0,
        process_count=1,
    )
    batch = next(it)
    assert batch["images"].dtype == np.uint8
    assert "mix_labels" not in batch and "ratio" not in batch


def test_load_device_preprocess_rejects_augment_after_mix():
    tf = pytest.importorskip("tensorflow")
    from sav_tpu.data import Split, load

    images, labels = _uint8_images(n=32, size=48)
    with pytest.raises(ValueError, match="device_preprocess"):
        next(
            load(
                Split.TRAIN,
                source=(images, labels),
                is_training=True,
                batch_dims=[8],
                image_size=32,
                augment_name="cutmix_mixup_randaugment_405",
                augment_before_mix=False,
                device_preprocess=True,
                seed=0,
                process_index=0,
                process_count=1,
            )
        )


# ----------------------------------------------------------- trainer path


def test_trainer_device_preprocess_end_to_end(devices):
    from sav_tpu.train import TrainConfig, Trainer
    from sav_tpu.models import create_model

    config = TrainConfig(
        model_name="vit_ti_patch16",
        num_classes=10,
        image_size=32,
        compute_dtype="float32",
        global_batch_size=16,
        num_train_images=64,
        num_epochs=2,
        warmup_epochs=1,
        transpose_images=False,
        augment="cutmix_mixup",
        device_preprocess=True,
        seed=0,
    )
    model = create_model(
        "vit_ti_patch16", num_classes=10, num_layers=2, embed_dim=64,
        num_heads=4, dtype=jnp.float32,
    )
    trainer = Trainer(config, model=model)
    images, labels = _uint8_images(n=16, size=32)
    batch = {"images": images, "labels": labels}
    state = trainer.init_state(0)
    state, metrics = trainer.train_step(state, batch, jax.random.PRNGKey(0))
    assert np.isfinite(float(metrics["loss"]))
    eval_metrics = trainer.eval_step(state, batch)
    assert np.isfinite(float(jax.device_get(eval_metrics["loss_sum"])))


def test_trainer_device_preprocess_replayable(devices):
    """Same (state.step, rng) → identical mix draws → identical loss."""
    from sav_tpu.train import TrainConfig, Trainer
    from sav_tpu.models import create_model

    config = TrainConfig(
        model_name="vit_ti_patch16",
        num_classes=10,
        image_size=32,
        compute_dtype="float32",
        global_batch_size=16,
        num_train_images=64,
        num_epochs=2,
        warmup_epochs=1,
        transpose_images=False,
        augment="mixup",
        device_preprocess=True,
        seed=0,
    )
    model = create_model(
        "vit_ti_patch16", num_classes=10, num_layers=2, embed_dim=64,
        num_heads=4, dtype=jnp.float32,
    )
    trainer = Trainer(config, model=model)
    images, labels = _uint8_images(n=16, size=32)
    batch = {"images": images, "labels": labels}
    l1 = float(
        trainer.train_step(trainer.init_state(0), batch, jax.random.PRNGKey(7))[1][
            "loss"
        ]
    )
    l2 = float(
        trainer.train_step(trainer.init_state(0), batch, jax.random.PRNGKey(7))[1][
            "loss"
        ]
    )
    assert l1 == l2


def test_savrec_raw_path_rejects_transpose(tmp_path):
    """The HWCN transpose is fused into the C++ normalize; the raw uint8
    (device-preprocess) path must reject transpose rather than silently
    yield NHWC to a trainer expecting HWCN."""
    from sav_tpu.data.records import (
        SavRecDataset,
        savrec_train_iterator,
        write_savrec,
    )

    images, labels = _uint8_images(n=8, size=16)
    path = str(tmp_path / "t.savrec")
    write_savrec(path, images, labels.astype(np.int32))
    with pytest.raises(ValueError, match="transpose"):
        next(
            savrec_train_iterator(
                SavRecDataset(path),
                batch_size=4,
                seed=0,
                normalize=False,
                transpose=True,
            )
        )


def test_mode_mismatch_fails_loudly(devices):
    """device_preprocess wiring mistakes must not train silently wrong
    (ADVICE r3): uint8 into a float-path trainer and floats into a
    device-preprocess trainer both raise at trace time."""
    from sav_tpu.train import TrainConfig, Trainer

    def smoke_config(**kw):
        return TrainConfig(
            model_name="vit_ti_patch16",
            num_classes=10,
            image_size=32,
            compute_dtype="float32",
            global_batch_size=8,
            num_train_images=32,
            num_epochs=2,
            warmup_epochs=1,
            transpose_images=False,
            augment="cutmix_mixup",
            model_overrides=dict(num_layers=1, embed_dim=32, num_heads=2),
            seed=0,
            **kw,
        )

    rng = jax.random.PRNGKey(0)
    u8 = {
        "images": np.zeros((8, 32, 32, 3), np.uint8),
        "labels": np.zeros((8,), np.int32),
    }
    f32 = {
        "images": np.zeros((8, 32, 32, 3), np.float32),
        "labels": np.zeros((8,), np.int32),
    }
    plain = Trainer(smoke_config())
    with pytest.raises(ValueError, match="uint8"):
        plain.train_step(plain.init_state(0), u8, rng)
    devpp = Trainer(smoke_config(device_preprocess=True))
    with pytest.raises(ValueError, match="uint8"):
        devpp.train_step(devpp.init_state(0), f32, rng)
