"""Trainer integration tests on the 8-device virtual CPU mesh — the
train-step coverage tier the reference lacked (SURVEY.md §4)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from sav_tpu.data import fake_data_iterator, synthetic_data_iterator
from sav_tpu.parallel import create_mesh
from sav_tpu.train import Checkpointer, TrainConfig, Trainer


def _smoke_config(**overrides):
    base = dict(
        model_name="vit_ti_patch16",
        num_classes=10,
        image_size=32,
        compute_dtype="float32",
        global_batch_size=16,
        num_train_images=16 * 4,  # 4 steps/epoch
        num_epochs=2,
        warmup_epochs=1,
        base_lr=1e-3,
        lr_scaling_divisor=16,
        transpose_images=False,
        log_every_steps=2,
        eval_every_epochs=1,
        seed=0,
    )
    base.update(overrides)
    return TrainConfig(**base)


def _small_model_overrides():
    return dict(num_layers=2, embed_dim=64, num_heads=4)


def _trainer(config=None, **model_overrides):
    from sav_tpu.models import create_model

    config = config or _smoke_config()
    model = create_model(
        config.model_name,
        num_classes=config.num_classes,
        dtype=jnp.float32,
        **(_small_model_overrides() | model_overrides),
    )
    return Trainer(config, model=model)


@pytest.mark.slow
def test_loss_decreases_on_learnable_data(devices):
    trainer = _trainer()
    state = trainer.init_state()
    data = synthetic_data_iterator(
        batch_size=16, image_size=32, num_classes=10, seed=0
    )
    rng = jax.random.PRNGKey(0)
    losses = []
    for _, batch in zip(range(30), data):
        state, metrics = trainer.train_step(state, batch, rng)
        losses.append(float(metrics["loss"]))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) - 0.1, losses
    assert int(jax.device_get(state.step)) == 30


def test_state_is_sharded_on_mesh(devices):
    trainer = _trainer()
    state = trainer.init_state()
    leaf = jax.tree.leaves(state.params)[0]
    assert len(leaf.sharding.device_set) == 8  # replicated over the full mesh


@pytest.mark.slow
def test_fit_loop_with_eval_and_transpose(devices):
    cfg = _smoke_config(transpose_images=True)
    trainer = _trainer(cfg)
    train_iter = synthetic_data_iterator(
        batch_size=16, image_size=32, num_classes=10, transpose=True
    )
    eval_fn = lambda: synthetic_data_iterator(
        batch_size=16, image_size=32, num_classes=10, transpose=True, num_batches=2
    )
    state, history = trainer.fit(
        train_iter, num_steps=8, eval_iter_fn=eval_fn
    )
    assert int(jax.device_get(state.step)) == 8
    assert any("eval_loss" in h for h in history)
    assert any("images_per_sec" in h for h in history)


@pytest.mark.slow
def test_batch_stats_model_trains(devices):
    """BatchNorm models thread batch_stats through the same trainer
    (collapses the reference's base.py/base_with_state.py split)."""
    from sav_tpu.models import create_model

    cfg = _smoke_config(model_name="botnet_t3", image_size=64)
    model = create_model(
        "botnet_t3", num_classes=10, dtype=jnp.float32, stage_sizes=(1, 1, 1, 1)
    )
    trainer = Trainer(cfg, model=model)
    state = trainer.init_state()
    assert state.batch_stats  # BN present
    before = jax.device_get(jax.tree.leaves(state.batch_stats)[0]).copy()
    data = synthetic_data_iterator(batch_size=16, image_size=64, num_classes=10)
    rng = jax.random.PRNGKey(0)
    for _, batch in zip(range(2), data):
        state, metrics = trainer.train_step(state, batch, rng)
    after = jax.device_get(jax.tree.leaves(state.batch_stats)[0])
    assert not np.allclose(before, after)  # running stats updated
    assert np.isfinite(float(metrics["loss"]))


@pytest.mark.slow
def test_train_many_steps_matches_loop(devices):
    """K scan-fused steps == K separate steps (same math, one dispatch)."""
    it = synthetic_data_iterator(batch_size=16, image_size=32, num_classes=10, seed=5)
    batches = [next(it) for _ in range(4)]
    rng = jax.random.PRNGKey(0)

    t1 = _trainer()
    s1 = t1.init_state()
    losses_loop = []
    for b in batches:
        s1, m = t1.train_step(s1, b, rng)
        losses_loop.append(float(m["loss"]))

    t2 = _trainer()
    s2 = t2.init_state()
    stacked = {k: np.stack([b[k] for b in batches]) for k in batches[0]}
    s2, metrics = t2.train_many_steps(s2, stacked, rng)
    losses_scan = [float(x) for x in np.asarray(jax.device_get(metrics["loss"]))]
    np.testing.assert_allclose(losses_scan, losses_loop, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        jax.device_get(jax.tree.leaves(s1.params)[0]),
        jax.device_get(jax.tree.leaves(s2.params)[0]),
        rtol=1e-5, atol=1e-6,
    )


def test_mixed_labels_loss(devices):
    trainer = _trainer()
    state = trainer.init_state()
    batch = next(synthetic_data_iterator(batch_size=16, image_size=32, num_classes=10))
    batch["mix_labels"] = np.roll(batch["labels"], 1)
    batch["ratio"] = np.full((16,), 0.7, np.float32)
    state, metrics = trainer.train_step(state, batch, jax.random.PRNGKey(0))
    assert np.isfinite(float(metrics["loss"]))


def test_fake_data_shapes():
    it = fake_data_iterator(batch_size=4, image_size=16, transpose=True)
    batch = next(it)
    assert batch["images"].shape == (16, 16, 3, 4)
    it = fake_data_iterator(batch_size=4, image_size=16)
    assert next(it)["images"].shape == (4, 16, 16, 3)


@pytest.mark.slow
def test_checkpoint_save_restore(tmp_path, devices):
    cfg = _smoke_config(checkpoint_dir=str(tmp_path / "ckpt"))
    trainer = _trainer(cfg)
    state = trainer.init_state()
    data = synthetic_data_iterator(batch_size=16, image_size=32, num_classes=10)
    rng = jax.random.PRNGKey(0)
    for _, batch in zip(range(3), data):
        state, _ = trainer.train_step(state, batch, rng)
    trainer.checkpointer.save(3, state)
    trainer.checkpointer.wait()

    # Fresh trainer restores the latest step into the right structure.
    trainer2 = _trainer(cfg)
    restored = trainer2.restore_or_init()
    assert int(jax.device_get(restored.step)) == 3
    a = jax.device_get(jax.tree.leaves(state.params)[0])
    b = jax.device_get(jax.tree.leaves(restored.params)[0])
    np.testing.assert_allclose(a, b)


@pytest.mark.slow
def test_fit_final_step_on_checkpoint_boundary(tmp_path, devices):
    """Final step landing exactly on an epoch-checkpoint boundary must not
    double-save (orbax raises StepAlreadyExistsError)."""
    cfg = _smoke_config(
        checkpoint_dir=str(tmp_path / "ckpt"), checkpoint_every_epochs=2
    )
    trainer = _trainer(cfg)
    train_iter = synthetic_data_iterator(batch_size=16, image_size=32, num_classes=10)
    state, _ = trainer.fit(train_iter, num_steps=8)  # 4 steps/epoch → epoch 2
    assert trainer.checkpointer.latest_step() == 8


def test_weight_decay_mask():
    from sav_tpu.train import weight_decay_mask

    params = {
        "block": {"kernel": jnp.zeros((4, 4)), "bias": jnp.zeros((4,))},
        "pos_embed": jnp.zeros((1, 5, 4)),
        "cls": jnp.zeros((1, 1, 4)),
    }
    mask = weight_decay_mask(params)
    assert mask["block"]["kernel"] is True
    assert mask["block"]["bias"] is False
    assert mask["pos_embed"] is False
    assert mask["cls"] is False


def test_schedule_shape():
    from sav_tpu.train import warmup_cosine_schedule

    sched = warmup_cosine_schedule(
        1e-3, steps_per_epoch=10, warmup_epochs=2, num_epochs=10, end_lr=1e-5
    )
    assert float(sched(0)) == 0.0
    assert abs(float(sched(20)) - 1e-3) < 1e-9  # peak at end of warmup
    assert float(sched(100)) <= 1e-4  # decayed


@pytest.mark.slow
def test_grad_accum_matches_full_batch(devices):
    """K micro-batches, averaged grads → same update as one full batch
    (deterministic model: no dropout/BN, rates are 0 by default)."""
    import dataclasses

    from sav_tpu.data import synthetic_data_iterator
    from sav_tpu.models import create_model
    from sav_tpu.train import TrainConfig, Trainer

    base = TrainConfig(
        model_name="vit_ti_patch16", num_classes=10, image_size=16,
        compute_dtype="float32", global_batch_size=16, num_train_images=64,
        num_epochs=2, warmup_epochs=1, transpose_images=False,
        label_smoothing=0.0, base_lr=0.01, seed=0,
    )
    model = create_model("vit_ti_patch16", num_classes=10, num_layers=2,
                         embed_dim=32, num_heads=2, patch_shape=(4, 4))
    batch = next(synthetic_data_iterator(batch_size=16, image_size=16,
                                         num_classes=10, seed=5))
    rng = jax.random.PRNGKey(0)
    results = {}
    for accum in (1, 4):
        cfg = dataclasses.replace(base, grad_accum_steps=accum)
        trainer = Trainer(cfg, model=model)
        state = trainer.init_state()
        state, metrics = trainer.train_step(state, batch, rng)
        results[accum] = (
            jax.device_get(state.params["head"]["kernel"]),
            float(jax.device_get(metrics["loss"])),
        )
    np.testing.assert_allclose(results[1][1], results[4][1], rtol=1e-5)
    np.testing.assert_allclose(results[1][0], results[4][0], rtol=1e-4, atol=1e-6)


def test_grad_accum_rejects_indivisible(devices):
    import dataclasses

    from sav_tpu.data import synthetic_data_iterator
    from sav_tpu.models import create_model
    from sav_tpu.train import TrainConfig, Trainer

    cfg = TrainConfig(
        model_name="vit_ti_patch16", num_classes=10, image_size=16,
        compute_dtype="float32", global_batch_size=16, num_train_images=64,
        num_epochs=2, warmup_epochs=1, transpose_images=False,
        grad_accum_steps=3, seed=0,
    )
    model = create_model("vit_ti_patch16", num_classes=10, num_layers=1,
                         embed_dim=32, num_heads=2, patch_shape=(4, 4))
    trainer = Trainer(cfg, model=model)
    state = trainer.init_state()
    batch = next(synthetic_data_iterator(batch_size=16, image_size=16, num_classes=10))
    with pytest.raises(ValueError, match="not divisible"):
        trainer.train_step(state, batch, jax.random.PRNGKey(0))


@pytest.mark.slow
def test_eval_pads_non_divisible_final_batch(devices):
    """50 eval examples in batches of 16 leave a remainder of 2 — not
    divisible by the 8-way data axis. evaluate() must pad + mask instead of
    crashing, and count exactly 50 examples."""
    trainer = _trainer()
    state = trainer.init_state()

    def eval_iter():
        rng = np.random.default_rng(0)
        remaining = 50
        while remaining > 0:
            n = min(16, remaining)
            yield {
                "images": rng.standard_normal((n, 32, 32, 3)).astype(np.float32),
                "labels": rng.integers(0, 10, (n,), dtype=np.int32),
            }
            remaining -= n

    metrics = trainer.evaluate(state, eval_iter())
    assert metrics["eval_count"] == 50.0
    assert 0.0 <= metrics["eval_top_1_acc"] <= 1.0


@pytest.mark.slow
def test_eval_tiny_set_smaller_than_mesh(devices):
    """A 3-example eval set on an 8-way data axis must still work."""
    trainer = _trainer()
    state = trainer.init_state()
    rng = np.random.default_rng(1)
    batch = {
        "images": rng.standard_normal((3, 32, 32, 3)).astype(np.float32),
        "labels": rng.integers(0, 10, (3,), dtype=np.int32),
    }
    metrics = trainer.evaluate(state, iter([batch]))
    assert metrics["eval_count"] == 3.0


@pytest.mark.slow
def test_fused_optimizer_matches_per_leaf():
    """optax.flatten'd Adam (fused_optimizer=True) is numerically identical
    to the per-leaf chain — flatten is a reshape, not an approximation."""
    import jax
    import jax.numpy as jnp

    from sav_tpu.train import make_optimizer
    from sav_tpu.train.optimizer import warmup_cosine_schedule

    sched = warmup_cosine_schedule(
        1e-3, steps_per_epoch=10, warmup_epochs=1, num_epochs=10
    )
    params = {
        "encoder": {"kernel": jnp.ones((8, 16)) * 0.3, "bias": jnp.zeros((16,))},
        "pos_embed": {"embedding": jnp.ones((1, 4, 8)) * 0.1},
    }
    grads = jax.tree.map(lambda x: x * 0.05 + 0.01, params)
    tx_f = make_optimizer(sched, fused=True)
    tx_p = make_optimizer(sched, fused=False)
    sf, sp = tx_f.init(params), tx_p.init(params)
    pf, pp = params, params
    for _ in range(3):
        uf, sf = tx_f.update(grads, sf, pf)
        up, sp = tx_p.update(grads, sp, pp)
        import optax

        pf = optax.apply_updates(pf, uf)
        pp = optax.apply_updates(pp, up)
    jax.tree.map(
        lambda a, b: __import__("numpy").testing.assert_allclose(
            a, b, atol=1e-7, rtol=1e-6
        ),
        pf,
        pp,
    )


def _smoke_batch():
    return {
        "images": np.zeros((16, 32, 32, 3), np.float32),
        "labels": np.arange(16) % 10,
    }


@pytest.mark.slow
def test_logits_dtype_isolated_between_trainers(devices):
    """The softmax dtype is a model *attribute*, so trainers with different
    settings coexist structurally — no process state tracks whose step ran
    last, and nothing a second trainer does can retroactively change what a
    first trainer's lazy traces bake in."""
    from sav_tpu.ops import attention as att

    # Trainer-built models (model_overrides, not an external model) so the
    # config's logits dtype threads through create_model.
    tr_f32 = Trainer(_smoke_config(model_overrides=_small_model_overrides()))
    tr_bf16 = Trainer(
        _smoke_config(
            attention_logits_dtype="bfloat16",
            model_overrides=_small_model_overrides(),
        )
    )
    assert tr_f32.model.logits_dtype is None  # None = inherit compute (f32)
    assert tr_bf16.model.logits_dtype == "bfloat16"
    # Steps of both trainers interleave; the deprecated process fallback
    # never moves because no model path consults or sets it.
    batch = _smoke_batch()
    state = tr_f32.init_state(0)
    state, _ = tr_f32.train_step(state, batch, jax.random.PRNGKey(0))
    state_b = tr_bf16.init_state(0)
    tr_bf16.train_step(state_b, batch, jax.random.PRNGKey(0))
    tr_f32.eval_step(state, batch)
    assert att._DEFAULT_LOGITS_DTYPE == jnp.float32


def test_logits_dtype_ignores_process_global(devices):
    """No jitted model path reads the deprecated process-wide default: a
    block whose attributes say f32 softmax must produce bit-identical
    outputs whatever ``set_default_logits_dtype`` was left at (VERDICT r3
    weak #7 — the hazard class this threading deletes)."""
    from sav_tpu.models.layers.attention import SelfAttentionBlock
    from sav_tpu.ops import attention as att

    x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 32), jnp.bfloat16)
    block = SelfAttentionBlock(
        num_heads=4, dtype=jnp.bfloat16, logits_dtype=jnp.float32
    )
    variables = block.init({"params": jax.random.PRNGKey(1)}, x, is_training=False)
    # Un-jitted applies: each run re-executes the dtype resolution, so a
    # regression to reading the global CANNOT hide behind the jit cache
    # (a second jitted call with identical avals would reuse the first
    # trace and compare equal no matter what the global says).
    clean = np.asarray(block.apply(variables, x, is_training=False), np.float32)
    try:
        att.set_default_logits_dtype("bfloat16")  # poison the fallback
        poisoned = np.asarray(
            block.apply(variables, x, is_training=False), np.float32
        )
        # The control: the raw op with logits_dtype=None DOES see the
        # poison — proving the poison is live and the equality below is a
        # property of the block's explicit resolution, not a vacuous pass.
        q = jax.random.normal(jax.random.PRNGKey(2), (2, 16, 4, 8), jnp.bfloat16)
        raw_poisoned = np.asarray(att.xla_attention(q, q, q), np.float32)
        att.set_default_logits_dtype("float32")
        raw_clean = np.asarray(att.xla_attention(q, q, q), np.float32)
        assert not np.array_equal(raw_poisoned, raw_clean)
    finally:
        att.set_default_logits_dtype("float32")
    np.testing.assert_array_equal(poisoned, clean)


def test_logits_dtype_external_model_mismatch_raises(devices):
    """An external model carries its own logits_dtype; a config that says
    otherwise must fail loudly (the old process-global pinning DID apply
    the config to external models — silence would be a regression)."""
    from sav_tpu.models import create_model

    cfg = _smoke_config(
        compute_dtype="bfloat16", attention_logits_dtype="float32"
    )
    model = create_model(
        cfg.model_name, num_classes=10, dtype=jnp.bfloat16,
        **_small_model_overrides(),
    )
    with pytest.raises(ValueError, match="attention_logits_dtype"):
        Trainer(cfg, model=model)
    # Matching attribute: accepted.
    ok = create_model(
        cfg.model_name, num_classes=10, dtype=jnp.bfloat16,
        logits_dtype="float32", **_small_model_overrides(),
    )
    Trainer(cfg, model=ok)


@pytest.mark.slow
def test_logits_dtype_inherits_compute_dtype(devices):
    """attention_logits_dtype=None resolves to the compute dtype — the
    reference's semantics (its logits einsum runs in the model dtype), so
    a bf16-compute trainer softmaxes in bf16 and an f32 one in f32;
    'float32' still forces f32 softmax under bf16 compute. Resolution is
    structural (block attribute), verified by numerics: bf16 vs f32
    softmax differ on the same params/inputs."""
    from sav_tpu.models.layers.attention import SelfAttentionBlock

    tr_forced = Trainer(
        _smoke_config(
            compute_dtype="bfloat16",
            attention_logits_dtype="float32",
            model_overrides=_small_model_overrides(),
        )
    )
    assert tr_forced.model.logits_dtype == "float32"
    tr_inherit = Trainer(
        _smoke_config(
            compute_dtype="bfloat16",
            model_overrides=_small_model_overrides(),
        )
    )
    assert tr_inherit.model.logits_dtype is None

    # Block-level: None inherits the block dtype (bf16 here), and that is
    # a real numerical difference from forcing f32 softmax.
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 32), jnp.bfloat16)
    inherit = SelfAttentionBlock(num_heads=4, dtype=jnp.bfloat16)
    forced = SelfAttentionBlock(
        num_heads=4, dtype=jnp.bfloat16, logits_dtype=jnp.float32
    )
    variables = inherit.init({"params": jax.random.PRNGKey(1)}, x, is_training=False)
    out_bf16 = np.asarray(
        inherit.apply(variables, x, is_training=False), np.float32
    )
    out_f32 = np.asarray(
        forced.apply(variables, x, is_training=False), np.float32
    )
    assert not np.array_equal(out_bf16, out_f32)


@pytest.mark.slow
def test_warm_start_cross_resolution(tmp_path, devices):
    """--init-from semantics: params transfer, pos_embed resampled to the
    new token count (224->384-style finetune), step/optimizer fresh."""
    overrides = dict(
        num_layers=1, embed_dim=32, num_heads=2, patch_shape=(8, 8)
    )
    cfg32 = _smoke_config(
        checkpoint_dir=str(tmp_path / "pre"), model_overrides=overrides
    )
    pre = Trainer(cfg32)
    state = pre.init_state(0)
    batch = _smoke_batch()
    state, _ = pre.train_step(state, batch, jax.random.PRNGKey(0))
    pre.checkpointer.save(1, state)
    pre.checkpointer.wait()

    cfg48 = _smoke_config(
        image_size=48, model_overrides=overrides, ema_decay=0.999
    )
    fine = Trainer(cfg48)
    warm = fine.warm_start_from(str(tmp_path / "pre"))
    assert int(jax.device_get(warm.step)) == 0  # fresh step + optimizer
    # pos_embed resampled: 32/8 -> 17 tokens, 48/8 -> 37 tokens.
    pe = warm.params["Encoder_0"]["AddAbsPosEmbed_0"]["pos_embed"]
    assert pe.shape[1] == 37
    # Non-positional leaves transfer exactly.
    np.testing.assert_array_equal(
        jax.device_get(warm.params["head"]["kernel"]),
        jax.device_get(state.params["head"]["kernel"]),
    )
    # The parameter EMA is reseeded from the TRANSFERRED weights, not the
    # random init tx.init saw (eval-on-EMA would otherwise start from
    # garbage on short finetunes).
    from sav_tpu.train.optimizer import ema_params

    ema = ema_params(warm.opt_state)
    np.testing.assert_array_equal(
        jax.device_get(ema["head"]["kernel"]),
        jax.device_get(state.params["head"]["kernel"]),
    )
    # ...and as a distinct buffer: the donated train step would otherwise
    # donate the aliased params/EMA buffer twice (runtime crash).
    batch48 = {
        "images": np.zeros((16, 48, 48, 3), np.float32),
        "labels": np.arange(16) % 10,
    }
    warm, metrics = fine.train_step(warm, batch48, jax.random.PRNGKey(1))
    assert np.isfinite(float(jax.device_get(metrics["loss"])))


def test_ema_tracks_post_step_params(devices):
    """track_params_ema sits last in the chain, so after one step
    ema == decay·p0 + (1−decay)·p1 exactly; eval runs on the EMA tree."""
    from sav_tpu.train.optimizer import ema_params

    decay = 0.5
    cfg = _smoke_config(
        ema_decay=decay, model_overrides=_small_model_overrides()
    )
    trainer = Trainer(cfg)
    state0 = trainer.init_state(0)
    p0 = jax.device_get(jax.tree.leaves(state0.params)[0])
    ema0 = jax.device_get(jax.tree.leaves(ema_params(state0.opt_state))[0])
    np.testing.assert_array_equal(ema0, p0)  # init: ema == params

    batch = _smoke_batch()
    state1, _ = trainer.train_step(state0, batch, jax.random.PRNGKey(0))
    p1 = jax.device_get(jax.tree.leaves(state1.params)[0])
    ema1 = jax.device_get(jax.tree.leaves(ema_params(state1.opt_state))[0])
    np.testing.assert_allclose(
        ema1, decay * p0 + (1 - decay) * p1, rtol=1e-6, atol=1e-7
    )


@pytest.mark.slow
def test_eval_uses_ema_params(devices):
    """With decay=1.0 the EMA never moves off the init — eval metrics must
    match a fresh model's even after training steps moved the live params."""
    overrides = _small_model_overrides()
    frozen = Trainer(_smoke_config(ema_decay=1.0, model_overrides=overrides))
    live = Trainer(_smoke_config(model_overrides=overrides))
    batch = _smoke_batch()
    rng = jax.random.PRNGKey(0)

    fs = frozen.init_state(0)
    ls = live.init_state(0)
    baseline = float(jax.device_get(frozen.eval_step(fs, batch)["loss_sum"]))
    for i in range(3):
        fs, _ = frozen.train_step(fs, batch, rng)
        ls, _ = live.train_step(ls, batch, rng)
    after_frozen = float(jax.device_get(frozen.eval_step(fs, batch)["loss_sum"]))
    after_live = float(jax.device_get(live.eval_step(ls, batch)["loss_sum"]))
    # decay=1.0: eval-on-EMA pinned to the init weights...
    np.testing.assert_allclose(after_frozen, baseline, rtol=1e-5)
    # ...while the same steps moved the live trainer's eval.
    assert abs(after_live - baseline) > 1e-3
