"""Known-bad fixture for SAV119: device syncs in the fleet router's
TRACING surface — a blocking wait in the dispatch loop's stamp path, a
device_get building the candidate-wait table, a float() pulling a
device latency through __float__ in the span-ring fold, and an .item()
in the heartbeat snapshot."""
import jax


class Router:
    def _dispatch(self, job, metrics):
        metrics["step"].block_until_ready()
        self.stamps.append(("sent", self.clock()))

    def _route_with_waits(self):
        waits = jax.device_get(self.projections)
        return 0, dict(enumerate(waits))

    def _observe_completion(self, job, metrics):
        latency = float(metrics["latency"])
        self.ring.append({"latency_ms": latency * 1e3})

    def router_beat(self, metrics):
        depth = metrics["queue_depth"].item()
        return self.writer.serve_beat({"queue": depth}, kind="router")
