"""Known-clean for SAV124: every thread is a daemon or gets joined."""
import threading


def start_daemon(fn):
    t = threading.Thread(target=fn, daemon=True)  # daemon kwarg
    t.start()
    return t


def start_marked(fn):
    t = threading.Thread(target=fn)
    t.daemon = True  # attribute spelling
    t.start()
    return t


def run_bounded(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join(timeout=5.0)  # reaped on the only exit path
    return t
