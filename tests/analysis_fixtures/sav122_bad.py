"""Known-bad: inconsistent lock acquisition order across methods (SAV122).

Also the RUNTIME half's planted inversion: tests import this module and
drive ``write()`` + ``scan()`` under lockwatch, which must observe the
same meta->data->meta cycle the static rule reports.
"""
import threading


class Ledger:
    def __init__(self):
        self._meta = threading.Lock()
        self._data = threading.Lock()
        self.entries = {}
        self.revision = 0

    def write(self, key, value):
        with self._meta:
            with self._data:  # line 19: meta -> data ...
                self.entries[key] = value
                self.revision += 1

    def scan(self):
        with self._data:
            with self._meta:  # ... data -> meta: the inversion
                return dict(self.entries), self.revision
