"""Known-bad: Python loop counter passed into jitted calls (SAV104)."""
import jax

step = jax.jit(lambda state, n: state)


def run(state):
    for i in range(100):
        state = step(state, i)  # line 9: loop var straight into jit
    for j, batch in enumerate(load()):
        state = step(state, j * 2)  # line 11: BinOp of the counter
    return state


def load():
    return []
