"""Known-clean fixture for SAV112: the nearest legitimate idioms — the
heartbeat carries values the trainer already synced at its log boundary
(host floats by contract), the profiler's gate is host math, and the
event path is pure bookkeeping."""
import json


class HeartbeatWriter:
    def beat(self, step, ledger, metrics):
        # The metrics dict is host-side by contract (the trainer's
        # log-boundary device_get produced it); extracting named host
        # floats is not a sync.
        record = {"step": step, "wall_s": ledger.wall_s}
        loss = metrics.get("loss")
        if isinstance(loss, (int, float)):
            record["loss"] = float(loss)
        self.file.write(json.dumps(record) + "\n")
        self.file.flush()

    def fleet_event(self, event, silent_s):
        self.file.write(json.dumps({"event": event, "silent_s": silent_s}))


class AutoProfiler:
    def note_window(self, step, per_step_s):
        # Robust spike gate over host wall-clock floats.
        history = sorted(self.history)
        if history and per_step_s > 4.0 * history[len(history) // 2]:
            return self.request("step_time_spike", step)
        self.history.append(per_step_s)

    def request(self, trigger, step):
        if len(self.captures) >= self.max_captures:
            self.denied += 1
            return False
        self.armed = {"trigger": trigger, "step": step}
        return True
