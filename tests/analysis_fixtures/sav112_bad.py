"""Known-bad fixture for SAV112: device syncs in the fleet heartbeat /
anomaly-profiler hot path — sync calls inside beat()/fleet_event(), a
float() pulling a device metric scalar through __float__ in beat(), and
a pipeline drain inside the profiler's note_window() gate."""
import jax


class HeartbeatWriter:
    def beat(self, step, metrics):
        snapshot = jax.device_get(metrics)
        self.last_loss = float(metrics["loss"])
        self.records.append(snapshot)

    def fleet_event(self, event, state):
        state.params.block_until_ready()
        self.events.append(event)


class AutoProfiler:
    def note_window(self, step, per_step_s, metrics):
        self.history.append(metrics["loss"].item())

    def request(self, trigger, step, metrics):
        self.last = float(metrics)
