"""Known-clean for SAV104: counters on device or in the data."""
import jax
import jax.numpy as jnp

step = jax.jit(lambda state, batch: state)


def run(state, batches):
    for batch in batches:  # data loop var is the normal pattern
        state = step(state, batch)
    for i in range(10):
        state = step(state, jnp.float32(i))  # wrapped: arrives as array
    return state
