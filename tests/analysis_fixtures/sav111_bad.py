"""Known-bad fixture for SAV111: host syncs on step metrics in the
recorded hot loop — float() on a bare metrics name in fit(), and sync
calls inside the recorder's per-step functions (outside SAV101's scope)."""
import jax


def fit(model, batches):
    metrics = None
    for batch in batches:
        state, metrics = model.step(batch)
        loss = float(metrics)
    return loss


class Recorder:
    def on_step(self, step, metrics):
        self.ring.append(jax.device_get(metrics))

    def note_metrics(self, step, metrics):
        self.window.append(metrics["loss"].item())
        self.norm = float(metrics["grad_norm"])
