"""Known-bad fixture for SAV126: prediction-quality evaluation dragged
onto the request path — a windowed digest fold in the batcher's dequeue
loop, a quality snapshot in the router's admission check, a shadow
score in the dispatch worker, a resolved quality-module call in a
telemetry stamp, and a device sync inside the quality fold itself."""
import jax

from sav_tpu.obs import quality


class Batcher:
    def next_batch(self):
        b = self._form()
        self.quality.observe_digests(b.top1, b.margin, b.entropy)
        return b


class Router:
    def admit(self, payload):
        if self.quality_tracker.snapshot().get("churn"):
            raise RuntimeError("shedding")
        return self._enqueue(payload)

    def _dispatch(self, job):
        self.shadow_scorer.score_shadow("bf16", "bf16", job.pred, job.pred)
        self._send(job)


class Telemetry:
    def observe_completed(self, latency_ms):
        ceiling = quality.envelope_rel("bf16", "int8")
        self.window.note(latency_ms)
        return ceiling


class Tracker:
    def observe_digests(self, top1, margin, entropy):
        top1 = jax.device_get(top1)
        self._rows.extend(top1)
