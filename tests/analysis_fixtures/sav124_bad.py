"""Known-bad: non-daemon threads started and never reaped (SAV124)."""
import threading


def start_logger(fn):
    t = threading.Thread(target=fn)  # line 6: daemon unset, never joined
    t.start()
    return t


def fire_and_forget(fn):
    threading.Thread(target=fn).start()  # line 12: unbound, unreapable
