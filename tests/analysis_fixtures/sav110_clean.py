"""Known-clean for SAV110: sibling streams derived with fold_in."""
import jax


def make_streams(seed):
    run_key = jax.random.PRNGKey(seed)
    train_rng = jax.random.fold_in(run_key, 1)
    eval_rng = jax.random.fold_in(run_key, 2)
    return train_rng, eval_rng
