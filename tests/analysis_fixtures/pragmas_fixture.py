"""Pragma mechanics: suppression, mandatory justification, unknown ids."""
import jax


def fit(self, train_iter):
    state = self.state
    for batch in train_iter:
        state, metrics = self.step(state, batch)
        loss = jax.device_get(metrics)  # savlint: disable=SAV101 -- fixture: justified suppression
        bad = jax.device_get(metrics)  # savlint: disable=SAV101
        other = jax.device_get(metrics)  # savlint: disable=SAV999 -- unknown rule id
    return state, loss, bad, other
