"""Known-clean for SAV105: timing on the host, around the dispatch."""
import time

import jax


@jax.jit
def step(x, batch):
    return x + batch


def run(state, batches):
    t0 = time.perf_counter()  # host-side timing around the call: fine
    for batch in batches:
        state = step(state, batch)
    return state, time.perf_counter() - t0
