"""File-scope pragma: every SAV110 below is suppressed at once."""
# savlint: disable-file=SAV110 -- fixture: sweeping a legacy file wholesale
import jax


def streams(seed):
    a = jax.random.PRNGKey(seed + 1)
    b = jax.random.PRNGKey(seed + 2)
    return a, b
