"""Known-clean fixture for SAV119: the nearest legitimate idioms — the
dispatch loop stamps monotonic clock reads, the wait table is host
arithmetic over parsed heartbeat lines, the span-ring fold appends
plain floats, and the heartbeat snapshot is counter reads (the router
module is stdlib-only; no device value is in reach)."""
import time


class Router:
    def _dispatch(self, job):
        # Stamps are monotonic clock reads — the cheapest host op.
        self.stamps.append(("route_selected", time.monotonic()))
        self.stamps.append(("sent", time.monotonic()))

    def _route_with_waits(self):
        # Host comparison of host floats — nothing to sync.
        waits = {r: self._projected_wait(r) for r in self.replicas}
        return min(waits, key=waits.get), waits

    def _observe_completion(self, job, latency_s):
        self.ring.append({
            "rid": job.rid,
            "latency_ms": latency_s * 1e3,
        })
        self.window.observe(latency_s * 1e3)

    def router_beat(self):
        return self.writer.serve_beat(
            {"completed": self.completed, "shed": self.shed},
            kind="router",
        )
