"""Known-bad fixture for SAV125: the metrics pipeline dragged onto the
request latency path — alert-rule evaluation in the batcher's dequeue
loop and the router's admission check, a rollup advance in the dispatch
worker, and a resolved module call into the alert engine from the
per-batch telemetry stamp."""
from sav_tpu.obs import alerts


class Batcher:
    def next_batch(self):
        batch = self._form()
        self.alerts.observe({"w": {"queue_depth": len(batch)}})
        return batch


class Router:
    def admit(self, payload):
        if self.alert_rule.evaluate({"w": {"inflight": self.inflight}}):
            raise RuntimeError("shedding")
        return self._enqueue(payload)

    def _dispatch(self, job):
        self.roller.roll_once()
        self._send(job)


class Telemetry:
    def observe_completed(self, latency_ms):
        events = alerts.AlertEngine(self.rules).observe(
            {"w": {"p99_ms": latency_ms}}
        )
        self.window.note(latency_ms)
        return events
