"""Known-clean for SAV106: placement lives on the feeder thread."""


class Trainer:
    def fit(self, train_iter):
        feeder = self.make_feeder(train_iter, self.shard_batch)  # reference, not call
        state = self.state
        for placed in feeder:
            state, _ = self.step(state, placed)
        return state

    def evaluate(self, eval_iter):
        def place(batch):
            # Closure handed to the feeder: runs on the feeder thread,
            # exempt by design.
            return self.shard_batch(batch)

        sums = [self.eval_step(b) for b in self.make_feeder(eval_iter, place)]
        return sums

    def train_step(self, state, batch):
        # The shard-inline convenience wrapper is not fit()'s hot loop.
        return self.step(state, self.shard_batch(batch))
