"""Known-clean fixture for SAV118: the nearest legitimate idioms — the
admission projection is host arithmetic over parsed heartbeat lines,
the replica choice compares host floats, completion bookkeeping is
counter updates, and the view refresh folds JSON the replicas already
wrote (the router module is stdlib-only; no device value is in reach)."""
import json
import time


class Router:
    def admit(self, payload, deadline_s):
        # Projection over host-side heartbeat numbers only.
        wait = min(self._projected_wait(r) for r in self.replicas)
        if wait > deadline_s:
            raise RuntimeError("shed")
        self.jobs.append((payload, time.monotonic()))

    def route(self):
        # Host comparison of host floats — nothing to sync.
        return min(self.replicas, key=self._projected_wait)

    def note_result(self, rank, ok):
        self.outstanding[rank] -= 1
        self.completed += 1 if ok else 0

    def _refresh_views(self, path):
        with open(path) as f:
            for line in f:
                self.views.update(json.loads(line))
