"""Known-bad: state-carrying jits without donation (SAV102)."""
from functools import partial

import jax


def train_step_impl(state, batch, rng):
    return state, {}


class Trainer:
    def __init__(self):
        self._train_step = jax.jit(train_step_impl)  # line 13: no donation


@jax.jit  # line 16: bare decorator cannot donate
def update(state, grads):
    return state


@partial(jax.jit)  # line 21: partial form, donation forgotten
def apply_updates(state, updates):
    return state
