"""Known-clean for SAV122: ranked nesting, RLock re-entry, release-then-call."""
import threading


class Ledger:
    def __init__(self):
        self._meta = threading.Lock()
        self._data = threading.Lock()
        self._state = threading.RLock()
        self.entries = {}
        self.revision = 0

    def write(self, key, value):
        with self._meta:
            with self._data:  # every path ranks meta before data
                self.entries[key] = value
                self.revision += 1

    def scan(self):
        with self._meta:
            with self._data:  # same order: a DAG, not a cycle
                return dict(self.entries), self.revision

    def mutate(self):
        with self._state:
            self._helper()  # RLock re-entry via a call: not a cycle

    def _helper(self):
        with self._state:
            return self.revision

    def rebuild(self):
        with self._data:
            snapshot = dict(self.entries)
        # Lock released BEFORE calling into other-lock territory.
        self.audit(snapshot)

    def audit(self, snapshot):
        with self._meta:
            return len(snapshot)
