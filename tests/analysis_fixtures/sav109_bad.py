"""Known-bad: jax.jit wrapped inside the loop (SAV109)."""
import jax


def sweep(shapes, x):
    results = []
    for shape in shapes:
        fn = jax.jit(lambda v: v.reshape(shape))  # line 8: jit per iteration
        results.append(fn(x))
    return results
