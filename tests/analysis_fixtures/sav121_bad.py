"""Known-bad: lock-guarded attribute touched lock-free on a thread path (SAV121)."""
import threading


class Telemetry:
    def __init__(self):
        self._lock = threading.Lock()
        self._completed = 0
        self._window = []
        self._thread = threading.Thread(target=self._beat, daemon=True)

    def observe(self, ms):
        with self._lock:
            self._completed += 1
            self._window.append(ms)

    def _emit(self):
        return {"n": self._completed}  # line 18: guarded attr, no lock, reachable

    def _beat(self):
        while True:
            self._emit()
            self._window.clear()  # line 23: guarded attr mutated lock-free
