"""Known-bad fixture for SAV116: device syncs in the serve-telemetry
span/window/heartbeat path — a pipeline drain inside a span stamp, a
device_get in the window observation, a float() pulling a device metric
through __float__ in the batch-completion path, and a blocking read in
the heartbeat emitter."""
import jax


def stamp(trace, stage, t):
    t.block_until_ready()
    trace.stamps.append((stage, t))


class LiveWindow:
    def observe_window(self, latencies_s):
        host = jax.device_get(latencies_s)
        self.samples.extend(host)


class ServeTelemetry:
    def observe_completed(self, formed, metrics):
        self.last_loss = float(metrics["loss"])
        self.batches += 1

    def serve_beat(self, metrics):
        record = {"p99": metrics["p99"].item()}
        self.writer.append(record)
