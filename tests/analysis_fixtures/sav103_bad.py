"""Known-bad: the same PRNG key consumed twice (SAV103)."""
import jax


def sample(key, shape):
    noise = jax.random.normal(key, shape)
    mask = jax.random.bernoulli(key, 0.5, shape)  # line 7: key reused
    return noise, mask


def augment(rng, images, labels):
    k = jax.random.fold_in(rng, 7)  # deriving is fine
    perm = jax.random.permutation(k, labels.shape[0])
    ratio = jax.random.uniform(k)  # line 14: k reused
    return perm, ratio
