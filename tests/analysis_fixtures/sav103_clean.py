"""Known-clean for SAV103: split/fold_in per consumer, reassignment resets."""
import jax


def sample(key, shape):
    k_noise, k_mask = jax.random.split(key)
    noise = jax.random.normal(k_noise, shape)
    mask = jax.random.bernoulli(k_mask, 0.5, shape)
    return noise, mask


def loop_body(rng, xs):
    for i, x in enumerate(xs):
        step_key = jax.random.fold_in(rng, i)  # derive per step: fine
        yield jax.random.normal(step_key, x.shape)


def reassigned(key, shape):
    a = jax.random.normal(key, shape)
    key = jax.random.fold_in(key, 1)  # reassignment resets the count
    b = jax.random.normal(key, shape)
    return a, b
