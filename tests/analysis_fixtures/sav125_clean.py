"""Known-clean fixture for SAV125: the pipeline at its sanctioned
cadence — rules evaluate once per beat in serve_beat(), the rollup
ladder advances on the router's heartbeat thread, and the hot paths
only touch their own windows/counters (a .observe() on a non-alert
window is the SlidingWindow idiom, not rule evaluation)."""


class Telemetry:
    def serve_beat(self):
        # Sanctioned home: once per heartbeat interval, not per request.
        record = {"w": self.window.snapshot()}
        self.alerts.observe(record)
        return self.writer.serve_beat(record)

    def observe_completed(self, latency_ms):
        # Hot path touches its own window — .observe() on a non-alert
        # chain is the latency fold, not rule evaluation.
        self.window.observe(latency_ms)


class Router:
    def _hb_loop(self):
        while not self._closed.wait(self.heartbeat_secs):
            self.router_beat()
            self._roll_tick()

    def _roll_tick(self):
        # Sanctioned home: the ladder advances at heartbeat cadence.
        self.roller.roll_once()

    def _dispatch(self, job):
        self._send(job)
        self.stamps.append(("sent", job.rid))
