"""Known-clean fixture for SAV115: the nearest legitimate idioms — the
admission path does host bookkeeping only, the drain forms batches from
host wall clocks, and placement ISSUES the device_put without waiting on
it (the device loop's post-execution fetch owns the one per-batch sync,
outside this rule's scope)."""
import time

import jax


class DynamicBatcher:
    def submit(self, payload, deadline_s):
        # Host-side admission: wall clocks and queue bookkeeping.
        record = {"payload": payload, "enqueue_t": time.monotonic(),
                  "deadline_s": float(deadline_s)}
        self.queue.append(record)
        return record

    def next_batch(self):
        batch = [self.queue.pop()]
        dispatch_by = batch[0]["enqueue_t"] + batch[0]["deadline_s"]
        while self.queue and time.monotonic() < dispatch_by:
            batch.append(self.queue.pop())
        return batch


class ServeEngine:
    def _formed_batches(self):
        while True:
            formed = self.batcher.next_batch()
            if formed is None:
                return
            yield formed

    def _place_formed(self, formed):
        # Issue the transfer; never wait on it here — the overlap with
        # batch N's execution is the point.
        return jax.device_put(formed.images)
