"""Known-clean for SAV121: the legitimate neighbors of the lockset rule."""
import queue
import threading
import time


class Telemetry:
    def __init__(self):
        self._lock = threading.Lock()
        self._clock = time.monotonic  # immutable after __init__: no lock needed
        self._completed = 0
        self._inbox = queue.Queue()  # self-synchronizing: exempt
        self._thread = threading.Thread(target=self._beat, daemon=True)

    def observe(self):
        with self._lock:
            self._completed += 1

    def _snapshot_locked(self):
        # Called ONLY with the lock held: inherits the guard.
        return {"n": self._completed}

    def _beat(self):
        while True:
            t0 = self._clock()  # read-only after init: fine lock-free
            with self._lock:
                snap = self._snapshot_locked()
            self._inbox.put((t0, snap))  # Queue synchronizes itself
