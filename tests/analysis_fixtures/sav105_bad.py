"""Known-bad: wall-clock reads inside jit-traced code (SAV105)."""
import time
from datetime import datetime

import jax


@jax.jit
def timed_step(x, batch):
    t0 = time.time()  # line 10: frozen at trace time
    x = x + batch
    elapsed = time.perf_counter() - t0  # line 12: same
    stamp = datetime.now()  # line 13: same
    return x, elapsed, stamp


def step_impl(x):
    return x, time.monotonic()  # line 18: jitted via jax.jit below


wrapped = jax.jit(step_impl)
