"""Known-clean fixture for SAV126: the quality layer at its sanctioned
homes — the per-batch digest fold on ALREADY-FETCHED host digests (they
rode the device loop's one result fetch), snapshots at heartbeat
cadence, an O(1) bounded handoff (not an evaluation) on the dispatch
path, scoring on the shadow worker thread, and a probe run that blocks
on request futures from its own low-cadence thread."""


class Engine:
    def _complete(self, formed, host):
        # Sanctioned per-batch fold: host["top1"] etc. are host-side
        # already — quality adds no sync to the device loop's fetch.
        n = len(formed.requests)
        self._quality.observe_digests(
            host["top1"][:n].tolist(),
            host["margin"][:n].tolist(),
            host["entropy"][:n].tolist(),
        )


class Telemetry:
    def serve_beat(self):
        # Sanctioned cadence: one snapshot per heartbeat, not per
        # request.
        record = {"quality": self._quality_fn()}
        return self.writer.serve_beat(record)


class Router:
    def _dispatch(self, job):
        self._send(job)
        if job.shadow:
            # O(1) bounded queue put — the scoring itself runs on the
            # shadow worker thread, never on a dispatch worker.
            self._shadow_enqueue(job)

    def _shadow_worker(self):
        while not self._closed:
            job = self._shadow_queue.get(timeout=0.25)
            self._shadow_scorer.score_shadow(
                "bf16", "bf16", job.pred, job.shadow_pred
            )


class Probe:
    def observe_probe(self):
        # The probe thread may block on request FUTURES — it is off the
        # hot path by construction; what it must not do is device-sync.
        rows = [f.result(timeout=30.0) for f in self.futures]
        return self.ledger.record(fingerprint=self.fp(rows))
