"""Known-bad: inline device placement in fit()/evaluate() (SAV106)."""
import jax


class Trainer:
    def fit(self, train_iter):
        state = self.state
        for batch in train_iter:
            placed = jax.device_put(batch)  # line 9: inline placement
            sharded = self.shard_batch(batch)  # line 10: same via helper
            state, _ = self.step(state, placed or sharded)
        return state

    def evaluate(self, eval_iter):
        sums = []
        for batch in eval_iter:
            placed = self.shard_batch(batch)  # line 17: eval is hot too
            sums.append(self.eval_step(placed))
        return sums
