"""Known-bad: unlocked multi-writer state across threads (SAV107)."""
import threading


class Pipeline:
    def __init__(self):
        self.count = 0
        self.status = "idle"
        self._thread = threading.Thread(target=self._worker, daemon=True)

    def _worker(self):
        while True:
            self.count += 1  # line 13: worker writes...
            self.status = "running"  # line 14: worker writes...

    def reset(self):
        self.count = 0  # line 17: ...and so does another thread
        self.status = "idle"  # line 18: ...unlocked both sides
