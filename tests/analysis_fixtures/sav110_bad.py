"""Known-bad: seed arithmetic instead of fold_in (SAV110)."""
import jax


def make_streams(seed):
    train_rng = jax.random.PRNGKey(seed + 1)  # line 6: seed arithmetic
    eval_rng = jax.random.PRNGKey(2 * seed)  # line 7: seed arithmetic
    return train_rng, eval_rng
