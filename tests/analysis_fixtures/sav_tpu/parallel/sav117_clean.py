"""Known-clean fixture for SAV117: spec construction INSIDE
sav_tpu/parallel/ (this file's fixture-relative path) is the layout
subsystem's job — plus the consumer idioms that are legal anywhere:
deriving shardings from the layout/mesh helpers without ever naming
PartitionSpec."""
from jax.sharding import NamedSharding, PartitionSpec as P

from sav_tpu.parallel import batch_sharding, batch_sharding_at, replicated


def role_spec(heads_axis):
    # The layout module states specs — that is its purpose.
    return P(None, None, heads_axis, None)


def param_sharding(mesh, heads_axis):
    return NamedSharding(mesh, role_spec(heads_axis))


def place_batch(mesh, trainer_layout, batch):
    # Consumer idiom: helpers, not constructors (legal outside too).
    import jax

    sh = batch_sharding(mesh)
    transposed = batch_sharding_at(mesh, 3)
    rep = replicated(mesh)
    del transposed, rep
    return jax.device_put(batch, sh)
