"""Known-bad: unbounded blocking calls in a bounded-contract module (SAV123)."""
import queue
import threading


class Drain:
    def __init__(self):
        self._jobs = queue.Queue()
        self._gate = threading.Lock()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        job = self._jobs.get()  # line 13: blocks forever on an empty queue
        self._gate.acquire()  # line 14: blocks forever on a held lock
        return job

    def stop(self):
        self._thread.join()  # line 18: blocks forever on a wedged worker
        return self._jobs.get(timeout=None)  # line 19: spelled-out forever
