"""Known-clean for SAV123: bounded blocking, plus the zero-arg lookalikes."""
import queue
import threading

_POLL_S = 0.5


class Drain:
    def __init__(self):
        self._jobs = queue.Queue()
        self._gate = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while not self._stop.is_set():
            try:
                job = self._jobs.get(timeout=_POLL_S)  # bounded: re-checks stop
            except queue.Empty:
                continue
            if self._gate.acquire(timeout=_POLL_S):  # bounded, expiry handled
                try:
                    self._handle(job)
                finally:
                    self._gate.release()

    def _handle(self, job):
        del job

    def stop(self, config):
        self._stop.set()
        self._thread.join(timeout=5 * _POLL_S)  # bounded join
        # Zero-arg-needs-an-argument forms are NOT blocking calls:
        label = ",".join(sorted(config))  # str.join takes an iterable
        return config.get("mode"), label  # dict.get takes a key
