"""Known-bad fixture for SAV114: bare process exits in library code —
a sys.exit on a validation failure, an os._exit from a monitor thread,
a raise SystemExit masquerading as error handling, and the os._exit
capability handed around as a callback default."""
import os
import sys


def validate_config(config):
    if config is None:
        sys.exit(2)
    return config


def monitor(deadline, exit_fn=os._exit):
    if deadline <= 0:
        os._exit(4)
    return exit_fn


def load_or_die(path):
    if not os.path.exists(path):
        raise SystemExit(1)
    return open(path)
