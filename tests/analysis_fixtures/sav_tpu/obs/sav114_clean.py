"""Known-clean fixture for SAV114: the legitimate idioms — library code
raising typed exceptions for the CLI to map to exit codes, an injectable
exit_fn that defaults to a test-friendly callable, and the pragma'd
sanctioned contract."""
import os


class BackendUnreachableError(RuntimeError):
    """Typed error the CLI maps to its exit-3 contract."""


def validate_config(config):
    if config is None:
        raise ValueError("config must not be None")
    return config


def require_backend(platform):
    if platform is None:
        # Raise; train.py/bench.py own the process exit code.
        raise BackendUnreachableError("backend unreachable")
    return platform


class Watchdog:
    def __init__(self, exit_fn=None):
        # The one sanctioned hard-exit contract, pragma'd with the why.
        self._exit_fn = exit_fn if exit_fn is not None else os._exit  # savlint: disable=SAV114 -- sanctioned watchdog contract: a wedged main thread cannot be unwound
