"""Known-bad: unscaled int8 casts outside the quant module (SAV120)."""
import jax.numpy as jnp
import numpy as np


def compress_activations(x):
    q = x.astype(jnp.int8)  # line 7: bare cast, no scale
    q2 = x.astype("int8")  # line 8: string-dtype cast
    buf = np.asarray(x, np.int8)  # line 9: positional int8 ctor
    arr = jnp.array(x, dtype=jnp.int8)  # line 10: dtype= kwarg ctor
    return q, q2, buf, arr
