"""Known-clean: int8 born with a scale, or non-int8 casts (SAV120)."""
import jax.numpy as jnp

from sav_tpu.ops.quant import quantize_channelwise


def project(x, w):
    x = x.astype(jnp.bfloat16)  # dtype cast, not int8
    q, scale = quantize_channelwise(x, 1)  # int8 WITH per-channel scale
    widths = jnp.asarray([8, 16], dtype=jnp.int32)  # int32, not int8
    return q, scale, w, widths
