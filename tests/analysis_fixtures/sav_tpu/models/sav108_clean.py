"""Known-clean for SAV108: explicit dtype, positional dtype, int arange."""
import jax.numpy as jnp


def position_table(length, dim, dtype):
    table = jnp.zeros((length, dim), dtype=dtype)
    mask = jnp.ones((length,), jnp.int32)  # positional dtype
    idx = jnp.arange(length)  # int arange defaults to int: fine
    ramp = jnp.linspace(0.0, 1.0, length, dtype=dtype)
    return table, mask, idx, ramp
