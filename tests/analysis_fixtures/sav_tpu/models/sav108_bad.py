"""Known-bad: dtype-less f32 constructors in a bf16 path (SAV108)."""
import jax.numpy as jnp


def position_table(length, dim):
    table = jnp.zeros((length, dim))  # line 6: f32 default
    ramp = jnp.linspace(0.0, 1.0, length)  # line 7: f32 default
    steps = jnp.arange(0.0, 1.0, 0.1)  # line 8: float arange
    return table + ramp[:, None] + steps.sum()
