"""Known-clean for SAV102: donation present, or exempt by role."""
from functools import partial

import jax


def train_step_impl(state, batch, rng):
    return state, {}


def eval_step_impl(state, batch):
    # eval reuses state across batches — donating it would crash.
    return {}


def init_fn(rng):
    return rng


class Trainer:
    def __init__(self):
        self._train_step = jax.jit(train_step_impl, donate_argnums=(0,))
        self._eval_step = jax.jit(eval_step_impl)
        self._init = jax.jit(init_fn)


@partial(jax.jit, donate_argnums=(0,))
def update(state, grads):
    return state
