"""Known-clean fixture for SAV113: the nearest legitimate idioms —
profiling through the armed windows' own machinery (autoprof drives
start/stop from its bounded state machine, outside the hot functions)
and forensics on the incident path of a non-hot helper."""
import jax

from sav_tpu.obs.memdump import dump_memory_incident


class AutoProfiler:
    def on_step(self, step):
        # The capture state machine is NOT a hot function: the bounded
        # window is the sanctioned home of start/stop.
        if self.armed is not None:
            jax.profiler.start_trace(self.path)
            self.active = {"stop_step": step + self.trace_steps}
            self.armed = None
        elif self.active and step >= self.active["stop_step"]:
            jax.profiler.stop_trace()
            self.active = None


def handle_oom(log_dir, state, exc):
    # Incident-path forensics in a dedicated handler — the run is
    # already dead; this is not the hot loop.
    return dump_memory_incident(log_dir, state=state, error=repr(exc))


class Trainer:
    def fit(self, batches):
        for step, batch in enumerate(batches):
            if self.autoprof is not None:
                self.autoprof.on_step(step)
            state, metrics = self.step(batch)
