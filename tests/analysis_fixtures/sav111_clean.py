"""Known-clean fixture for SAV111: the nearest legitimate idioms — the
recorder's per-step path is host bookkeeping only, and detection runs on
metrics the trainer already synced at its log boundary (float() over the
host values of that dict is fine; the dict is host-side by contract)."""


class Recorder:
    def observe_batch(self, batch):
        # Host-side fingerprinting — hashes bytes, never syncs.
        self.pending.append((batch["images"].tobytes(), batch))

    def on_step(self, step):
        self.ring.append(step)
        if len(self.ring) > self.depth:
            self.ring.popleft()

    def note_metrics(self, step, metrics):
        # The trainer device_get this dict at its log boundary already;
        # iterating host floats is not a sync.
        for key, value in metrics.items():
            self.window.append((key, float(value)))
