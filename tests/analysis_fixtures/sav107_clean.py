"""Known-clean for SAV107: locked writes, or a single writer."""
import threading


class LockedPipeline:
    def __init__(self):
        self.count = 0
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._worker, daemon=True)

    def _worker(self):
        while True:
            with self._lock:
                self.count += 1

    def reset(self):
        with self._lock:
            self.count = 0


class SingleWriter:
    def __init__(self):
        self.fetched = 0
        self.consumed = 0
        self._thread = threading.Thread(target=self._worker, daemon=True)

    def _worker(self):
        self.fetched += 1  # only the worker writes this: fine

    def take(self):
        self.consumed += 1  # only the consumer writes this: fine
        return self.fetched  # cross-thread *reads* are not flagged
