"""Known-bad fixture for SAV115: device syncs in the serving batcher's
admission/drain path — a per-request result read inside next_batch(),
a pipeline drain in submit(), a float() pulling a device metric through
__float__ in the drain iterator, and a sync in the placement stage."""
import jax


class DynamicBatcher:
    def submit(self, payload, metrics):
        payload.block_until_ready()
        self.last_loss = float(metrics["loss"])
        self.queue.append(payload)

    def next_batch(self):
        batch = self.queue.pop()
        return jax.device_get(batch)


class ServeEngine:
    def _formed_batches(self, metrics):
        while True:
            yield float(metrics)

    def _place_formed(self, formed):
        placed = jax.device_put(formed.images)
        placed.block_until_ready()
        return placed
