"""Known-clean fixture for SAV116: the nearest legitimate idioms — span
stamps are host-clock list appends, window observation folds host floats
the device loop already fetched with its one sanctioned sync, and the
heartbeat emitter writes one JSON line from window snapshots."""
import json
import time


def stamp(trace, stage, t):
    # Host-clock append only: the whole cost of tracing a stage.
    if trace is not None:
        trace.stamps.append((stage, t))


class LiveWindow:
    def observe_window(self, latencies_s):
        # latencies_s are host floats (computed from wall clocks after
        # the device loop's post-execution fetch) — plain bookkeeping.
        now = time.monotonic()
        for v in latencies_s:
            self.samples.append((now, v))


class ServeTelemetry:
    def observe_completed(self, formed, latencies_s):
        self.batches += 1
        self.completed += len(latencies_s)

    def serve_beat(self):
        record = {"t": time.time(), "w": self.window.snapshot()}
        self.writer.write(json.dumps(record) + "\n")
