"""Known-bad: host syncs inside hot-path functions (SAV101)."""
import jax
import numpy as np


def fit(self, train_iter):
    state = self.state
    for batch in train_iter:
        state, metrics = self.step(state, batch)
        loss = jax.device_get(metrics["loss"])  # line 10: device_get
        jax.block_until_ready(state)  # line 11: block_until_ready fn
        acc = metrics["acc"].item()  # line 12: .item() method
        arr = np.asarray(metrics["grads"])  # line 13: np.asarray
        lr = float(metrics["lr"])  # line 14: float(subscript)
        state.params.block_until_ready()  # line 15: method sync
    return state, loss, acc, arr, lr


def evaluate(self, eval_iter):
    sums = [self.eval_step(b) for b in eval_iter]
    return [s.item() for s in sums]  # line 21: .item() in evaluate
