"""Known-bad fixture for SAV113: jax.profiler / memory-forensics calls
inside the training hot path — an ad-hoc per-step trace window in fit(),
a live-buffer walk in evaluate(), and a memdump inside the jitted step's
dispatch wrapper."""
import jax

from sav_tpu.obs.memdump import dump_memory_incident, live_buffer_ranking


class Trainer:
    def fit(self, batches):
        for step, batch in enumerate(batches):
            jax.profiler.start_trace("/tmp/every_step")
            state, metrics = self.step(batch)
            jax.profiler.stop_trace()
            if step % 10 == 0:
                jax.profiler.save_device_memory_profile("/tmp/mem.pprof")

    def evaluate(self, batches):
        for batch in batches:
            self.sums.append(self.eval(batch))
            ranking = live_buffer_ranking(self.state)
            self.rankings.append(ranking)

    def train_step_placed(self, state, placed, rng):
        dump_memory_incident(self.log_dir, state=state)
        return self._train_step(state, placed, rng)
