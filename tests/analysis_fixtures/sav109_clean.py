"""Known-clean for SAV109: jit once outside, call many inside."""
import jax


@jax.jit
def fn(v):
    return v * 2


def sweep(xs):
    return [fn(x) for x in xs]


def make_runner():
    for _ in range(1):
        pass

    def run(x):  # a def in a function is fine; the jit is outside loops
        return jax.jit(lambda v: v)(x)

    return run
