"""Known-clean for SAV101: syncs outside the hot path don't fire."""
import jax


def fit(self, train_iter):
    state = self.state
    for batch in train_iter:
        state, metrics = self.step(state, batch)
        self.history.append(metrics)  # stays on device
    return state


def summarize(history):
    # Not a hot function: a post-run sync is fine.
    return [float(jax.device_get(m["loss"])) for m in history]


def report(metrics):
    # float() of a bare name is not flagged (too ambiguous statically).
    v = metrics
    return float(v)
