"""Known-bad fixture for SAV117: ad-hoc PartitionSpec/NamedSharding
construction outside sav_tpu/parallel/ — an inline param spec, a batch
placement built from scratch, and the fully-qualified module spelling.
Each forks the SpecLayout source of truth."""
from jax.sharding import NamedSharding, PartitionSpec as P


def shard_my_params(mesh, params):
    spec = P(None, "model")
    return NamedSharding(mesh, spec)


def place_batch(mesh, batch):
    import jax
    import jax.sharding as jsh

    sharding = jsh.NamedSharding(mesh, jsh.PartitionSpec("data"))
    return jax.device_put(batch, sharding)
