"""Known-bad fixture for SAV118: device syncs in the fleet router's
admit/route/drain path — a blocking wait inside admission, a device_get
in the replica choice, a float() pulling a device metric through
__float__ in the completion bookkeeping, and a sync inside the
heartbeat-view refresh."""
import jax


class Router:
    def admit(self, payload, metrics):
        metrics["queue"].block_until_ready()
        self.jobs.append(payload)

    def route(self):
        waits = jax.device_get(self.projections)
        return min(range(len(waits)), key=waits.__getitem__)

    def note_result(self, rank, metrics):
        self.last_latency = float(metrics["latency"])
        self.completed += 1

    def _refresh_views(self, metrics):
        depth = metrics["queue_depth"].item()
        self.views[0] = depth
