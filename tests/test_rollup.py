"""Heartbeat rollups (ISSUE 19): incremental aggregation, cursor crash
recovery, O(new bytes) re-rolls, retention, and the projection helpers.

Everything here is file-only (synthetic streams, no processes, no jax)
— the tier-1 budget is tight and the rollup contract is byte-level, so
byte-level tests are the honest ones."""

import json
import os

import pytest

from sav_tpu.obs.rollup import (
    RESOLUTIONS,
    Roller,
    cursor_path,
    finest_rollup,
    metrics_from,
    project_load,
    read_rollup,
    robust_slope,
    rollup_path,
    series,
)


def _write_stream(log_dir, name, records, mode="w"):
    fleet = os.path.join(log_dir, "fleet")
    os.makedirs(fleet, exist_ok=True)
    path = os.path.join(fleet, name)
    with open(path, mode) as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    return path


def _serve_rec(proc, t, rps, p99=12.0, cap=400.0):
    return {
        "schema": 1, "schema_version": 2, "kind": "serve", "proc": proc,
        "t": t, "w": {"p99_ms": p99, "throughput_rps": rps,
                      "step_s_avg": 0.01},
        "capacity_rps": cap,
    }


def _beats(proc, t0, n, rps=100.0):
    return [_serve_rec(proc, t0 + i, rps + i) for i in range(n)]


# --------------------------------------------------------------- folding


def test_metrics_from_shapes():
    rec = _serve_rec(0, 1000.0, 100.0)
    rec["w"]["queue_depth_last"] = 3
    rec["slo"] = {"burn_rate": 1.5}
    m = metrics_from(rec)
    assert m["throughput_rps"] == 100.0
    assert m["queue_depth"] == 3.0  # renamed from queue_depth_last
    assert m["capacity_rps"] == 400.0
    assert m["burn_rate"] == 1.5
    router = {"kind": "router", "t": 1000.0, "throughput_rps": 50.0,
              "router_overhead_ms": 0.4, "w": {"p99_ms": 9.0}}
    rm = metrics_from(router)
    assert rm["router_throughput_rps"] == 50.0
    assert rm["router_p99_ms"] == 9.0
    assert rm["router_overhead_ms"] == 0.4  # no router_router_ double
    # Unknown kinds roll nothing (forward compat).
    assert metrics_from({"kind": "mystery", "x": 1.0}) == {}


def test_roll_and_read_basic(tmp_path):
    d = str(tmp_path)
    _write_stream(d, "proc_0.jsonl", _beats(0, 1000.0, 25))
    roller = Roller(d)
    roller.roll_once()
    lines = read_rollup(d, 10)
    # 25 beats at 1 Hz from t=1000: buckets 1000/1010 closed by the
    # watermark (1020s tail still pending), one line per metric.
    buckets = sorted({ln["bucket"] for ln in lines})
    assert buckets == [1000, 1010]
    tp = {ln["bucket"]: ln for ln in lines
          if ln["metric"] == "throughput_rps"}
    assert tp[1000]["n"] == 10
    assert tp[1000]["min"] == 100.0 and tp[1000]["max"] == 109.0
    assert tp[1000]["mean"] == pytest.approx(104.5)
    # flush() force-closes the pending tail.
    roller.flush()
    lines = read_rollup(d, 10)
    assert sorted({ln["bucket"] for ln in lines}) == [1000, 1010, 1020]
    # Coarser tiers fold the same samples.
    assert {ln["bucket"] for ln in read_rollup(d, 600)} == {600}


def test_per_stream_watermark_does_not_close_lagging_replica(tmp_path):
    """A fast replica's clock must not close a lagging replica's
    buckets: watermarks are per-stream."""
    d = str(tmp_path)
    _write_stream(d, "proc_0.jsonl", _beats(0, 1000.0, 25))
    _write_stream(d, "proc_1.jsonl", _beats(1, 1000.0, 5))  # lags
    roller = Roller(d)
    roller.roll_once()
    by_proc = {}
    for ln in read_rollup(d, 10):
        if ln["metric"] == "throughput_rps":
            by_proc.setdefault(ln["proc"], []).append(ln["bucket"])
    assert sorted(by_proc[0]) == [1000, 1010]
    # proc 1 never passed t=1010 — its 1000 bucket is still pending.
    assert 1 not in by_proc
    # Its beats arrive late; the next roll closes them with full counts.
    _write_stream(d, "proc_1.jsonl", _beats(1, 1005.0, 20), mode="a")
    roller.roll_once()
    p1 = {ln["bucket"]: ln["n"] for ln in read_rollup(d, 10)
          if ln["proc"] == 1 and ln["metric"] == "throughput_rps"}
    # 5 early + 5 late beats land in [1000, 1010) — full count, closed.
    assert p1[1000] == 10 and p1[1010] == 10


def test_incremental_roll_is_o_new_bytes(tmp_path):
    """The warm-cursor guarantee: re-rolling a 10k-line dir reads only
    the appended bytes (the bytes_read gauge IS the contract)."""
    d = str(tmp_path)
    _write_stream(d, "proc_0.jsonl", _beats(0, 1000.0, 10_000))
    roller = Roller(d)
    roller.roll_once()
    cold = roller.bytes_read
    assert cold > 100_000  # the full backlog
    appended = _beats(0, 11_000.0, 3)
    _write_stream(d, "proc_0.jsonl", appended, mode="a")
    warm = Roller(d)
    warm.roll_once()
    budget = sum(len(json.dumps(r)) + 1 for r in appended)
    assert warm.bytes_read <= budget + 16
    # And a no-op roll reads nothing at all.
    idle = Roller(d)
    idle.roll_once()
    assert idle.bytes_read == 0


# ------------------------------------------------------- crash recovery


def test_torn_tail_not_consumed_then_glued(tmp_path):
    d = str(tmp_path)
    path = _write_stream(d, "proc_0.jsonl", _beats(0, 1000.0, 12))
    with open(path, "a") as f:
        f.write('{"kind": "serve", "proc": 0, "t": 1012.0, "w": {"thr')
    roller = Roller(d)
    roller.roll_once()
    cursor = json.load(open(cursor_path(d)))
    offset = cursor["streams"]["proc_0.jsonl"]["offset"]
    # Consumed exactly through the last newline — the torn tail waits.
    assert offset == sum(
        len(json.dumps(r)) + 1 for r in _beats(0, 1000.0, 12)
    )
    # A restarted writer glues a fresh record onto the torn line; the
    # glued garbage line is skipped, the following record rolls fine.
    with open(path, "a") as f:
        f.write('oughput": 1}}\n')
        f.write(json.dumps(_serve_rec(0, 1020.0, 300.0)) + "\n")
    roller.roll_once()
    roller.flush()
    tp = [ln for ln in read_rollup(d, 10)
          if ln["metric"] == "throughput_rps"]
    assert {ln["bucket"] for ln in tp} == {1000, 1010, 1020}
    b1020 = next(ln for ln in tp if ln["bucket"] == 1020)
    assert b1020["n"] == 1 and b1020["mean"] == 300.0


def test_missing_cursor_rebuilds_without_double_count(tmp_path):
    d = str(tmp_path)
    _write_stream(d, "proc_0.jsonl", _beats(0, 1000.0, 25))
    Roller(d).roll_once()
    before = read_rollup(d, 10)
    os.remove(cursor_path(d))
    _write_stream(d, "proc_0.jsonl", _beats(0, 1030.0, 5), mode="a")
    roller = Roller(d)
    roller.roll_once()
    after = read_rollup(d, 10)
    # Rebuild re-read everything exactly once: the old buckets carry
    # the same counts, no metric doubled.
    tp = {ln["bucket"]: ln["n"] for ln in after
          if ln["metric"] == "throughput_rps"}
    assert tp[1000] == 10 and tp[1010] == 10
    assert len(after) >= len(before)


@pytest.mark.parametrize("garbage", ['{"v": 99}', '{"trunc', ""])
def test_torn_or_foreign_cursor_rebuilds(tmp_path, garbage):
    d = str(tmp_path)
    _write_stream(d, "proc_0.jsonl", _beats(0, 1000.0, 25))
    Roller(d).roll_once()
    with open(cursor_path(d), "w") as f:
        f.write(garbage)
    roller = Roller(d)
    roller.roll_once()
    tp = {ln["bucket"]: ln["n"] for ln in read_rollup(d, 10)
          if ln["metric"] == "throughput_rps"}
    assert tp == {1000: 10, 1010: 10}


def test_stale_cursor_after_stream_truncation_rebuilds(tmp_path):
    d = str(tmp_path)
    path = _write_stream(d, "proc_0.jsonl", _beats(0, 1000.0, 25))
    Roller(d).roll_once()
    # The stream shrinks under the cursor (rotated/recreated file).
    _write_stream(d, "proc_0.jsonl", _beats(0, 2000.0, 12))
    assert os.path.getsize(path) < json.load(
        open(cursor_path(d))
    )["streams"]["proc_0.jsonl"]["offset"]
    roller = Roller(d)
    roller.roll_once()
    tp = {ln["bucket"]: ln["n"] for ln in read_rollup(d, 10)
          if ln["metric"] == "throughput_rps"}
    # Only the new stream's contents — the pre-truncation buckets are
    # gone from the rebuilt tiers, not merged into a franken-history.
    assert tp == {2000: 10}


def test_crash_between_append_and_cursor_is_idempotent(tmp_path):
    """SIGKILL after the rollup append, before the cursor write: the
    next roll re-appends the same buckets and the reader dedups by
    (bucket, proc, metric) keeping the newest line."""
    d = str(tmp_path)
    _write_stream(d, "proc_0.jsonl", _beats(0, 1000.0, 25))
    roller = Roller(d)
    saved = []
    orig = roller._save_cursor
    roller._save_cursor = lambda doc: saved.append(doc)  # crash: no write
    roller.roll_once()
    assert saved and not os.path.exists(cursor_path(d))
    raw_lines = sum(
        1 for _ in open(rollup_path(d, 10))
    )
    # Replay from byte 0 (no cursor): the file carries duplicates...
    replay = Roller(d)
    replay._save_cursor = orig.__func__.__get__(replay)  # normal save
    replay.roll_once()
    assert sum(1 for _ in open(rollup_path(d, 10))) >= raw_lines
    # ...but the reader sees each (bucket, proc, metric) exactly once.
    tp = [ln for ln in read_rollup(d, 10)
          if ln["metric"] == "throughput_rps"]
    assert [(ln["bucket"], ln["n"]) for ln in tp] == [(1000, 10), (1010, 10)]


def test_retention_compacts_tier(tmp_path):
    d = str(tmp_path)
    roller = Roller(d, resolutions=(10,), retention_buckets=4)
    _write_stream(d, "proc_0.jsonl", _beats(0, 1000.0, 400))
    roller.roll_once()
    lines = read_rollup(d, 10)
    buckets = sorted({ln["bucket"] for ln in lines})
    # Budget is per-series buckets: only the newest survive compaction.
    assert len(buckets) <= 2 * 4  # _COMPACT_SLACK bounded
    assert max(buckets) == 1380  # newest closed bucket retained
    assert min(buckets) >= 1380 - (2 * 4 + 1) * 10


# ------------------------------------------------------------ projection


def test_series_and_projection(tmp_path):
    d = str(tmp_path)
    # 40 beats -> four FULL 10s buckets after flush (a partial tail
    # bucket would skew the Theil-Sen slope below 1 rps/s per stream).
    _write_stream(d, "proc_0.jsonl", _beats(0, 1000.0, 40, rps=100.0))
    _write_stream(d, "proc_1.jsonl", _beats(1, 1000.0, 40, rps=200.0))
    roller = Roller(d)
    roller.roll_once()
    roller.flush()
    res, lines = finest_rollup(d)
    assert res == 10
    pts = series(lines, "throughput_rps")  # summed across procs
    assert pts[0][1] == pytest.approx(104.5 + 204.5)
    one = series(lines, "throughput_rps", proc=1)
    assert one[0][1] == pytest.approx(204.5)
    slope = robust_slope(pts)
    assert slope == pytest.approx(2.0)  # both replicas climb 1 rps/s
    proj = project_load(pts, horizon_s=30.0)
    assert proj["projected_rps"] == pytest.approx(
        proj["now_rps"] + 2.0 * 30.0
    )
    # Degenerate inputs answer None, not garbage.
    assert robust_slope(pts[:1]) is None
    assert project_load([], horizon_s=30.0) is None
    # A falling projection floors at zero (no negative load).
    falling = [(t, 100.0 - 10.0 * i) for i, t in enumerate(range(0, 60, 10))]
    assert project_load(falling, horizon_s=600.0)["projected_rps"] == 0.0


def test_empty_dir_answers_empty(tmp_path):
    d = str(tmp_path)
    assert read_rollup(d, 10) == []
    assert finest_rollup(d) == (None, [])
    stats = Roller(d).roll_once()
    assert stats["bytes_read"] == 0
