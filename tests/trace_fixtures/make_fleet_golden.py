#!/usr/bin/env python
"""Regenerate the fleet-merge golden fixture (ISSUE 16, checked in).

A hand-pinned two-replica fleet log under ``fleet_golden/serve_traces/``
exercising every path of ``traceview.fleet_request_spans``:

  requests_router.trace.json.gz — the router's span-ring export: four
    requests in the ``ROUTER_INTERVALS`` vocabulary, args carrying
    rank/outcome (the join keys) plus deadline/overrun.
  requests_proc0.trace.json.gz — replica 0's export with a DELIBERATE
    +5 s clock skew (its stamps read 5 s ahead of the router's): two
    complete replica walks for requests A and B. The merge must recover
    the offset from the handshake pairs — hand-worked below — and emit
    contiguous router→replica→router chains.
  requests_proc1.trace.json.gz — replica 1's export is TORN: request C's
    record lacks the device span, so C must degrade to the router-only
    chain (never dropped). Request D (shed before the exchange) has no
    replica record at all and keeps its raw router spans.

Hand-worked offset (replica 0; replica clock + offset = router clock,
true offset −5 s): request A bounds the offset to [−5.0010, −4.9970] s,
request B to [−5.0005, −4.9970] s; the intersection's midpoint is
−4.99875 s (−4998.75 ms) with half-width 1.75 ms — the skew bound the
merge stamps into its output. The merged chains those numbers produce
are pinned in ``tests/test_traceview.py`` — change either side
consciously.

Deterministic output (gzip mtime pinned to 0):
``python tests/trace_fixtures/make_fleet_golden.py``.
"""

import gzip
import json
import os

from sav_tpu.serve.telemetry import (
    INTERVALS,
    ROUTER_INTERVALS,
    export_chrome_trace,
)

HERE = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(HERE, "fleet_golden", "serve_traces")

# Replica 0's clock reads 5 s AHEAD of the router's.
SKEW_0 = 5.0


def _rec(rid, stamps, *, rank, outcome, deadline_ms, overrun_ms):
    return {
        "rid": rid,
        "stamps": stamps,
        "rank": rank,
        "outcome": outcome,
        "deadline_ms": deadline_ms,
        "overrun_ms": overrun_ms,
    }


def router_records():
    return [
        _rec("rA", [
            ("submit", 10.0000), ("admit", 10.0002),
            ("route_selected", 10.0010), ("connect", 10.0015),
            ("sent", 10.0020), ("reply", 10.0220),
            ("completed", 10.0225),
        ], rank=0, outcome="completed", deadline_ms=100.0,
            overrun_ms=-77.5),
        _rec("rB", [
            ("submit", 11.0000), ("admit", 11.0002),
            ("route_selected", 11.0008), ("connect", 11.0012),
            ("sent", 11.0015), ("reply", 11.0215),
            ("completed", 11.0220),
        ], rank=0, outcome="completed", deadline_ms=100.0,
            overrun_ms=-78.0),
        _rec("rC", [
            ("submit", 12.0000), ("admit", 12.0003),
            ("route_selected", 12.0010), ("connect", 12.0014),
            ("sent", 12.0018), ("reply", 12.0318),
            ("completed", 12.0322),
        ], rank=1, outcome="completed", deadline_ms=30.0,
            overrun_ms=2.2),
        # Shed on the dispatch path before any exchange: admission is
        # the only closed interval; the honest terminal stamp ends no
        # interval. The merge must keep this request (router-only).
        _rec("rD", [
            ("submit", 13.0000), ("admit", 13.0002), ("shed", 13.5000),
        ], rank=None, outcome="shed", deadline_ms=400.0,
            overrun_ms=100.0),
    ]


def replica0_records():
    def shift(stamps):
        return [(name, t + SKEW_0) for name, t in stamps]

    return [
        {"rid": "rA", "stamps": shift([
            ("submit", 10.0030), ("admit", 10.0032),
            ("batch_formed", 10.0060), ("placed", 10.0062),
            ("dispatched", 10.0070), ("executed", 10.0170),
            ("depadded", 10.0180), ("completed", 10.0190),
        ])},
        {"rid": "rB", "stamps": shift([
            ("submit", 11.0020), ("admit", 11.0022),
            ("batch_formed", 11.0040), ("placed", 11.0042),
            ("dispatched", 11.0050), ("executed", 11.0150),
            ("depadded", 11.0180), ("completed", 11.0185),
        ])},
    ]


def replica1_records():
    # Torn: request C's record ends at admission — no device span, so
    # _replica_boundaries returns None and C degrades to router-only.
    return [
        {"rid": "rC", "stamps": [
            ("submit", 12.5000), ("admit", 12.5002),
        ]},
    ]


def write(name, doc):
    os.makedirs(OUT, exist_ok=True)
    path = os.path.join(OUT, name)
    with open(path, "wb") as raw:
        with gzip.GzipFile(fileobj=raw, mode="wb", mtime=0) as f:
            f.write(json.dumps(doc, sort_keys=True).encode())
    print(f"wrote {path}")


def main():
    write("requests_router.trace.json.gz", export_chrome_trace(
        router_records(), ROUTER_INTERVALS,
        process_name="Fleet Router", extra_args=("rank", "outcome"),
    ))
    write("requests_proc0.trace.json.gz", export_chrome_trace(
        replica0_records(), INTERVALS, process_name="Replica 0",
    ))
    write("requests_proc1.trace.json.gz", export_chrome_trace(
        replica1_records(), INTERVALS, process_name="Replica 1",
    ))


if __name__ == "__main__":
    main()
