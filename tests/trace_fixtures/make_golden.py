#!/usr/bin/env python
"""Regenerate the golden trace fixtures (checked-in .trace.json.gz).

Two hand-pinned chrome-trace files exercising both device-plane
selectors of ``sav_tpu/obs/traceview.py``:

  golden_tpu.trace.json.gz — a TPU device process plane (op events named
    by HLO instruction, no args) plus a host plane whose nested
    ``PjitFunction`` markers pin the top-level step segmentation.
  golden_cpu.trace.json.gz — the same ops as a CPU-backend trace: no
    device process, ops tagged with ``hlo_op``/``hlo_module`` args on
    XLA execution threads (what autoprof's tier-1 e2e captures).

``golden_op_index.json`` maps the ops to HLO metadata scopes; the
expected per-component/per-group totals are pinned in
``tests/test_traceview.py`` — change either side consciously.

Deterministic output (gzip mtime pinned to 0) so regeneration diffs are
meaningful: ``python tests/trace_fixtures/make_golden.py``.
"""

import gzip
import json
import os

HERE = os.path.dirname(os.path.abspath(__file__))

# (op name, duration us, metadata scope or None)
OPS = [
    ("dot.1", 2000.0,
     "jit(step)/jit(main)/jvp(Model)/Encoder_0/block_0/"
     "SelfAttentionBlock_0/to_qkv/dot_general"),
    ("fusion.2", 3000.0,
     "jit(step)/jit(main)/jvp(Model)/Encoder_0/block_0/"
     "SelfAttentionBlock_0/SelfAttentionBlock_0/softmax"),
    ("dot.3", 1000.0,
     "jit(step)/jit(main)/transpose(jvp(Model))/Encoder_0/block_0/"
     "FFBlock_0/fc1/dot_general"),
    ("convolution.4", 2000.0,
     "jit(step)/jit(main)/jvp(Model)/PatchEmbedBlock_0/proj/"
     "conv_general_dilated"),
    ("dot.5", 500.0, "jit(step)/jit(main)/jvp(Model)/head/dot_general"),
    ("fusion.6", 1500.0, "jit(step)/jit(main)/add"),
    ("copy.7", 1000.0, None),  # deliberately NOT in the op index
]


def _host_plane(pid):
    """Host plane: 2 top-level step markers, each emitted twice (the
    profiler's re-entrant TraceMe) — pins the top-level dedupe."""
    events = [
        {"ph": "M", "pid": pid, "name": "process_name",
         "args": {"name": "python"}},
    ]
    for ts in (0.0, 20000.0):
        for _ in range(2):  # nested duplicate, same span
            events.append({
                "ph": "X", "pid": pid, "tid": 1, "ts": ts, "dur": 11000.0,
                "name": "PjitFunction(_train_step_impl)",
            })
    return events


def make_tpu():
    pid_dev, pid_host = 7, 99
    events = [
        {"ph": "M", "pid": pid_dev, "name": "process_name",
         "args": {"name": "/device:TPU:0 (pid 7)"}},
        # The xprof export's per-device thread layout: the per-op rows
        # plus AGGREGATE rows ("XLA Modules", "Steps") whose events
        # span whole steps ON THE SAME PID — the parser must count the
        # op rows only, or every op is double/triple-booked and
        # idle_frac pins at 0.
        {"ph": "M", "pid": pid_dev, "tid": 2, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "pid": pid_dev, "tid": 5, "name": "thread_name",
         "args": {"name": "XLA Modules"}},
        {"ph": "M", "pid": pid_dev, "tid": 6, "name": "thread_name",
         "args": {"name": "Steps"}},
    ]
    ts = 0.0
    for name, dur, _ in OPS:
        events.append({
            "ph": "X", "pid": pid_dev, "tid": 2, "ts": ts, "dur": dur,
            "name": name,
        })
        ts += dur
    # Aggregate rows spanning the whole window — excluded from totals.
    events.append({
        "ph": "X", "pid": pid_dev, "tid": 5, "ts": 0.0, "dur": ts,
        "name": "jit_step",
    })
    events.append({
        "ph": "X", "pid": pid_dev, "tid": 6, "ts": 0.0, "dur": ts,
        "name": "1",
    })
    events += _host_plane(pid_host)
    return {"traceEvents": events}


def make_cpu():
    pid = 701
    events = [
        {"ph": "M", "pid": pid, "name": "process_name",
         "args": {"name": "/host:CPU"}},
    ]
    ts = 0.0
    for name, dur, _ in OPS:
        events.append({
            "ph": "X", "pid": pid, "tid": 3, "ts": ts, "dur": dur,
            "name": name,
            "args": {"hlo_module": "jit_step", "hlo_op": name},
        })
        ts += dur
    events += _host_plane(pid)
    return {"traceEvents": events}


def write(name, doc):
    path = os.path.join(HERE, name)
    with open(path, "wb") as raw:
        with gzip.GzipFile(fileobj=raw, mode="wb", mtime=0) as f:
            f.write(json.dumps(doc, sort_keys=True).encode())
    print(f"wrote {path}")


def main():
    write("golden_tpu.trace.json.gz", make_tpu())
    write("golden_cpu.trace.json.gz", make_cpu())
    index = {name: scope for name, _, scope in OPS if scope is not None}
    with open(os.path.join(HERE, "golden_op_index.json"), "w") as f:
        json.dump(index, f, indent=2, sort_keys=True)
    print("wrote golden_op_index.json")


if __name__ == "__main__":
    main()
