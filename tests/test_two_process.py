"""Multi-process distributed bring-up smoke (VERDICT r3 item 8).

Wraps ``tools/two_process_smoke.py``: two OS processes, one
``jax.distributed.initialize`` rendezvous, one global DP mesh, six train
steps — the parent asserts both ranks' losses agree bit-for-bit and
decrease. Skips (rather than fails) when the sandbox forbids the local
TCP rendezvous the coordinator needs.
"""

import os
import subprocess
import sys

import pytest


@pytest.mark.slow
def test_two_process_dp_smoke():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "two_process_smoke.py")],
        capture_output=True,
        text=True,
        timeout=900,
    )
    out = proc.stdout + proc.stderr
    # Skip ONLY on rendezvous-setup failures (sandbox forbids the local TCP
    # coordinator) — narrow patterns so a genuine mid-run distributed
    # regression (which also surfaces barrier/UNAVAILABLE text) still FAILS.
    setup_errors = (
        "Address already in use",
        "Permission denied",
        "Failed to connect to coordinator",
        "Cannot assign requested address",
    )
    if proc.returncode != 0 and any(e in out for e in setup_errors):
        pytest.skip(f"multi-process rendezvous unsupported here: {out[-400:]}")
    assert proc.returncode == 0, out[-2000:]
    assert "AGREE" in out
