"""Multi-process distributed bring-up smoke (VERDICT r3 item 8 + r5 tp/sp/pp/ep/fsdp).

Wraps ``tools/two_process_smoke.py``: two OS processes, one
``jax.distributed.initialize`` rendezvous, one global mesh, six train
steps per mode — dp (gradient AllReduce crosses processes), tp/sp/pp/ep/fsdp
(the model / seq / pipe / expert / fsdp axis itself spans the process
boundary; losses must match a single-process run of the same mesh shape —
bit-identical for tp/sp/pp/ep, last-ulp tolerance for fsdp's 4-way
gradient reduction — proving placement changes the transport, not the
numerics). Each mode
runs as its own test case with its own timeout. Skips (rather than
fails) when the sandbox forbids the local TCP rendezvous the coordinator
needs.
"""

import os
import subprocess
import sys

import pytest

# Skip ONLY on rendezvous-setup failures (sandbox forbids the local TCP
# coordinator) — narrow patterns so a genuine mid-run distributed
# regression (which also surfaces barrier/UNAVAILABLE text) still FAILS.
SETUP_ERRORS = (
    "Address already in use",
    "Permission denied",
    "Failed to connect to coordinator",
    "Cannot assign requested address",
)


def _run_smoke(mode, timeout):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(repo, "tools", "two_process_smoke.py"),
            "--mode", mode,
        ],
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    out = proc.stdout + proc.stderr
    # The rendezvous skip applies only to modes that USE the TCP
    # coordinator: fleet mode runs independent workers, so a 'Permission
    # denied' there is a failure of the artifact layout under test, not
    # an environment capability gap.
    if (
        proc.returncode != 0
        and mode != "fleet"
        and any(e in out for e in SETUP_ERRORS)
    ):
        pytest.skip(f"multi-process rendezvous unsupported here: {out[-400:]}")
    assert proc.returncode == 0, out[-2000:]
    assert "AGREE" in out
    return out


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["dp", "tp", "sp", "pp", "ep", "fsdp"])
def test_two_process_smoke(mode):
    # Per-mode budget: 2 workers (600s communicate each, overlapping)
    # plus the tp/sp/pp single-process reference (900s) on a contended
    # 1-core host.
    _run_smoke(mode, timeout=1800)


def test_two_process_fleet_heartbeats_and_straggler():
    """ISSUE 7 acceptance, under REAL multi-process (tier-1, no slow
    marker): a two-process CPU fit produces per-process heartbeat
    streams, exactly one merged fleet manifest, and a straggler ranking
    (recomputed offline through tools/fleet_status.py) that names the
    injected-delay rank. The smoke script carries the assertions; this
    wrapper pins its AGREE contract."""
    out = _run_smoke("fleet", timeout=900)
    assert "straggler" in out
