"""Multi-process distributed bring-up smoke (VERDICT r3 item 8 + r5 tp/sp/pp/ep/fsdp).

Wraps ``tools/two_process_smoke.py``: two OS processes, one
``jax.distributed.initialize`` rendezvous, one global mesh, six train
steps per mode — dp (gradient AllReduce crosses processes), tp/sp/pp/ep/fsdp
(the model / seq / pipe / expert / fsdp axis itself spans the process
boundary; losses must match a single-process run of the same mesh shape —
bit-identical for tp/sp/pp/ep, last-ulp tolerance for fsdp's 4-way
gradient reduction — proving placement changes the transport, not the
numerics). Each mode
runs as its own test case with its own timeout. Skips (rather than
fails) when the sandbox forbids the local TCP rendezvous the coordinator
needs.
"""

import os
import subprocess
import sys

import pytest


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["dp", "tp", "sp", "pp", "ep", "fsdp"])
def test_two_process_smoke(mode):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(repo, "tools", "two_process_smoke.py"),
            "--mode", mode,
        ],
        capture_output=True,
        text=True,
        # Per-mode budget: 2 workers (600s communicate each, overlapping)
        # plus the tp/sp/pp single-process reference (900s) on a contended
        # 1-core host.
        timeout=1800,
    )
    out = proc.stdout + proc.stderr
    # Skip ONLY on rendezvous-setup failures (sandbox forbids the local TCP
    # coordinator) — narrow patterns so a genuine mid-run distributed
    # regression (which also surfaces barrier/UNAVAILABLE text) still FAILS.
    setup_errors = (
        "Address already in use",
        "Permission denied",
        "Failed to connect to coordinator",
        "Cannot assign requested address",
    )
    if proc.returncode != 0 and any(e in out for e in setup_errors):
        pytest.skip(f"multi-process rendezvous unsupported here: {out[-400:]}")
    assert proc.returncode == 0, out[-2000:]
    assert "AGREE" in out
