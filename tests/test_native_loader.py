"""Native C++ loader core vs numpy reference."""

import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module", autouse=True)
def built_lib():
    lib = REPO / "native" / "libsavtpu_loader.so"
    # Always run make — it is incremental, and a stale .so from before a
    # source change would silently miss new symbols.
    try:
        subprocess.run(
            ["make", "-C", str(REPO / "native")], check=True, capture_output=True
        )
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        if not lib.exists():
            pytest.skip(f"native build unavailable: {e}")
    return lib


def test_native_is_loaded(built_lib):
    from sav_tpu.data import native_loader as nl

    assert nl.native_available()


def test_normalize_matches_numpy():
    from sav_tpu.data import native_loader as nl
    from sav_tpu.data.pipeline import MEAN_RGB, STDDEV_RGB

    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (8, 16, 16, 3), dtype=np.uint8)
    ref = (images.astype(np.float32) - np.asarray(MEAN_RGB, np.float32)) / np.asarray(
        STDDEV_RGB, np.float32
    )
    out = nl.normalize_batch(images, MEAN_RGB, STDDEV_RGB)
    np.testing.assert_allclose(out, ref, rtol=1e-6)

    out_t = nl.normalize_batch(images, MEAN_RGB, STDDEV_RGB, transpose=True)
    np.testing.assert_allclose(out_t, np.transpose(ref, (1, 2, 3, 0)), rtol=1e-6)


def test_bf16_cast_matches_ml_dtypes():
    import ml_dtypes

    from sav_tpu.data import native_loader as nl

    rng = np.random.default_rng(1)
    x = rng.standard_normal((1000,)).astype(np.float32) * 100
    x = np.concatenate([x, [0.0, -0.0, 1e-38, 3.4e38, -3.4e38]]).astype(np.float32)
    out = nl.f32_to_bf16(x)
    ref = x.astype(ml_dtypes.bfloat16)
    np.testing.assert_array_equal(
        out.view(np.uint16), ref.view(np.uint16)
    )


def test_bf16_cast_preserves_nan():
    from sav_tpu.data import native_loader as nl

    x = np.array([np.nan, np.inf, -np.inf, 1.5], np.float32)
    out = nl.f32_to_bf16(x).astype(np.float32)
    assert np.isnan(out[0]) and np.isinf(out[1]) and np.isinf(out[2])


def test_u8_passthrough_matches_numpy(built_lib):
    """ctypes-level check of the uint8 wire-format passthrough: the C++
    flip+assemble must be byte-identical to the numpy reference, through
    the real .so (ISSUE 2 satellite)."""
    from sav_tpu.data import native_loader as nl

    assert nl.native_available()
    assert hasattr(nl._load(), "sav_u8_passthrough_batch")
    rng = np.random.default_rng(7)
    images = rng.integers(0, 256, (9, 12, 10, 3), dtype=np.uint8)
    flip = rng.random(9) < 0.5
    assert flip.any() and not flip.all()  # both branches exercised
    ref = np.where(flip[:, None, None, None], images[:, :, ::-1], images)
    out = nl.passthrough_batch_u8(images, flip=flip)
    assert out.dtype == np.uint8 and out.shape == images.shape
    np.testing.assert_array_equal(out, ref)
    # No-flip mode is a pure copy into a fresh buffer.
    out2 = nl.passthrough_batch_u8(images)
    assert out2 is not images
    np.testing.assert_array_equal(out2, images)
    # Non-contiguous input (a strided view) still round-trips correctly —
    # passed as-is, so the function's own contiguity handling is what is
    # under test here.
    view = images[:, ::2]
    assert not view.flags["C_CONTIGUOUS"]
    np.testing.assert_array_equal(nl.passthrough_batch_u8(view), view)


def test_u8_passthrough_fallback_matches_native(monkeypatch):
    """The numpy fallback (no .so) and the native kernel agree bitwise."""
    from sav_tpu.data import native_loader as nl

    rng = np.random.default_rng(8)
    images = rng.integers(0, 256, (5, 6, 7, 3), dtype=np.uint8)
    flip = np.array([1, 0, 1, 1, 0], np.uint8)
    native = nl.passthrough_batch_u8(images, flip=flip)
    monkeypatch.setattr(nl, "_load", lambda: None)
    fallback = nl.passthrough_batch_u8(images, flip=flip)
    np.testing.assert_array_equal(native, fallback)
    # Fallback no-flip mode must also hand back a fresh buffer (never an
    # alias of a possibly-reused source pool), like the native path.
    out = nl.passthrough_batch_u8(images)
    assert out is not images and out.flags["C_CONTIGUOUS"]
    np.testing.assert_array_equal(out, images)


def test_savrec_uint8_wire_path_uses_passthrough(tmp_path):
    """savrec_train_iterator(normalize=False) — the uint8-on-the-wire mode
    — yields uint8 NHWC batches whose flips match the (seed, epoch)
    deterministic draw of the normalized path."""
    from sav_tpu.data.records import (
        SavRecDataset, savrec_train_iterator, write_savrec,
    )

    rng = np.random.default_rng(9)
    images = rng.integers(0, 256, (16, 8, 8, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, (16,), dtype=np.int32)
    path = str(tmp_path / "t.savrec")
    write_savrec(path, images, labels)
    ds = SavRecDataset(path)
    raw = next(savrec_train_iterator(
        ds, batch_size=8, seed=3, normalize=False, bfloat16=False,
    ))
    assert raw["images"].dtype == np.uint8
    # Same seed, normalized arm: the uint8 batch normalizes to exactly it.
    norm = next(savrec_train_iterator(
        ds, batch_size=8, seed=3, normalize=True, bfloat16=False,
        mean=(0, 0, 0), stddev=(1, 1, 1),
    ))
    np.testing.assert_allclose(
        raw["images"].astype(np.float32), norm["images"], rtol=1e-6
    )


def test_gather_batch_rejects_out_of_range():
    from sav_tpu.data import native_loader as nl

    pool = np.zeros((4, 2, 2, 3), np.uint8)
    with pytest.raises(IndexError):
        nl.gather_batch(pool, np.array([0, 4], np.int32))
    with pytest.raises(IndexError):
        nl.gather_batch(pool, np.array([-1], np.int32))


def test_normalize_scalar_mean_broadcast():
    from sav_tpu.data import native_loader as nl

    images = np.full((2, 4, 4, 3), 100, np.uint8)
    out = nl.normalize_batch(images, 50.0, 2.0)
    np.testing.assert_allclose(out, 25.0)


def test_prefetch_exhausted_keeps_raising():
    from sav_tpu.data.native_loader import PrefetchLoader

    it = PrefetchLoader(iter([{"a": 1}]), depth=1)
    assert next(it) == {"a": 1}
    for _ in range(3):  # must raise StopIteration every time, never block
        with pytest.raises(StopIteration):
            next(it)


def test_gather_batch():
    from sav_tpu.data import native_loader as nl

    rng = np.random.default_rng(2)
    pool = rng.integers(0, 256, (32, 8, 8, 3), dtype=np.uint8)
    idx = rng.integers(0, 32, (16,), dtype=np.int32)
    np.testing.assert_array_equal(nl.gather_batch(pool, idx), pool[idx])


def test_transpose_hwcn():
    from sav_tpu.data import native_loader as nl

    rng = np.random.default_rng(3)
    x = rng.standard_normal((4, 6, 5, 3)).astype(np.float32)
    np.testing.assert_array_equal(
        nl.transpose_nhwc_to_hwcn(x), np.transpose(x, (1, 2, 3, 0))
    )


def test_prefetch_loader_order_and_exhaustion():
    from sav_tpu.data.native_loader import PrefetchLoader

    items = [{"i": np.array([k])} for k in range(20)]
    out = list(PrefetchLoader(iter(items), depth=3))
    assert [int(b["i"][0]) for b in out] == list(range(20))


def test_prefetch_loader_propagates_errors():
    from sav_tpu.data.native_loader import PrefetchLoader

    def gen():
        yield {"a": 1}
        raise RuntimeError("boom")

    it = PrefetchLoader(gen(), depth=2)
    assert next(it) == {"a": 1}
    with pytest.raises(RuntimeError, match="boom"):
        next(it)


def test_prefetch_with_native_transform():
    from sav_tpu.data import native_loader as nl

    rng = np.random.default_rng(4)
    batches = [
        {"images": rng.integers(0, 256, (4, 8, 8, 3), dtype=np.uint8)}
        for _ in range(5)
    ]

    def transform(b):
        return {"images": nl.normalize_batch(b["images"], (0, 0, 0), (1, 1, 1))}

    out = list(nl.PrefetchLoader(iter(batches), transform=transform))
    assert len(out) == 5
    np.testing.assert_allclose(
        out[0]["images"], batches[0]["images"].astype(np.float32), rtol=1e-6
    )
