"""Native C++ loader core vs numpy reference."""

import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module", autouse=True)
def built_lib():
    lib = REPO / "native" / "libsavtpu_loader.so"
    # Always run make — it is incremental, and a stale .so from before a
    # source change would silently miss new symbols.
    try:
        subprocess.run(
            ["make", "-C", str(REPO / "native")], check=True, capture_output=True
        )
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        if not lib.exists():
            pytest.skip(f"native build unavailable: {e}")
    return lib


def test_native_is_loaded(built_lib):
    from sav_tpu.data import native_loader as nl

    assert nl.native_available()


def test_normalize_matches_numpy():
    from sav_tpu.data import native_loader as nl
    from sav_tpu.data.pipeline import MEAN_RGB, STDDEV_RGB

    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (8, 16, 16, 3), dtype=np.uint8)
    ref = (images.astype(np.float32) - np.asarray(MEAN_RGB, np.float32)) / np.asarray(
        STDDEV_RGB, np.float32
    )
    out = nl.normalize_batch(images, MEAN_RGB, STDDEV_RGB)
    np.testing.assert_allclose(out, ref, rtol=1e-6)

    out_t = nl.normalize_batch(images, MEAN_RGB, STDDEV_RGB, transpose=True)
    np.testing.assert_allclose(out_t, np.transpose(ref, (1, 2, 3, 0)), rtol=1e-6)


def test_bf16_cast_matches_ml_dtypes():
    import ml_dtypes

    from sav_tpu.data import native_loader as nl

    rng = np.random.default_rng(1)
    x = rng.standard_normal((1000,)).astype(np.float32) * 100
    x = np.concatenate([x, [0.0, -0.0, 1e-38, 3.4e38, -3.4e38]]).astype(np.float32)
    out = nl.f32_to_bf16(x)
    ref = x.astype(ml_dtypes.bfloat16)
    np.testing.assert_array_equal(
        out.view(np.uint16), ref.view(np.uint16)
    )


def test_bf16_cast_preserves_nan():
    from sav_tpu.data import native_loader as nl

    x = np.array([np.nan, np.inf, -np.inf, 1.5], np.float32)
    out = nl.f32_to_bf16(x).astype(np.float32)
    assert np.isnan(out[0]) and np.isinf(out[1]) and np.isinf(out[2])


def test_gather_batch_rejects_out_of_range():
    from sav_tpu.data import native_loader as nl

    pool = np.zeros((4, 2, 2, 3), np.uint8)
    with pytest.raises(IndexError):
        nl.gather_batch(pool, np.array([0, 4], np.int32))
    with pytest.raises(IndexError):
        nl.gather_batch(pool, np.array([-1], np.int32))


def test_normalize_scalar_mean_broadcast():
    from sav_tpu.data import native_loader as nl

    images = np.full((2, 4, 4, 3), 100, np.uint8)
    out = nl.normalize_batch(images, 50.0, 2.0)
    np.testing.assert_allclose(out, 25.0)


def test_prefetch_exhausted_keeps_raising():
    from sav_tpu.data.native_loader import PrefetchLoader

    it = PrefetchLoader(iter([{"a": 1}]), depth=1)
    assert next(it) == {"a": 1}
    for _ in range(3):  # must raise StopIteration every time, never block
        with pytest.raises(StopIteration):
            next(it)


def test_gather_batch():
    from sav_tpu.data import native_loader as nl

    rng = np.random.default_rng(2)
    pool = rng.integers(0, 256, (32, 8, 8, 3), dtype=np.uint8)
    idx = rng.integers(0, 32, (16,), dtype=np.int32)
    np.testing.assert_array_equal(nl.gather_batch(pool, idx), pool[idx])


def test_transpose_hwcn():
    from sav_tpu.data import native_loader as nl

    rng = np.random.default_rng(3)
    x = rng.standard_normal((4, 6, 5, 3)).astype(np.float32)
    np.testing.assert_array_equal(
        nl.transpose_nhwc_to_hwcn(x), np.transpose(x, (1, 2, 3, 0))
    )


def test_prefetch_loader_order_and_exhaustion():
    from sav_tpu.data.native_loader import PrefetchLoader

    items = [{"i": np.array([k])} for k in range(20)]
    out = list(PrefetchLoader(iter(items), depth=3))
    assert [int(b["i"][0]) for b in out] == list(range(20))


def test_prefetch_loader_propagates_errors():
    from sav_tpu.data.native_loader import PrefetchLoader

    def gen():
        yield {"a": 1}
        raise RuntimeError("boom")

    it = PrefetchLoader(gen(), depth=2)
    assert next(it) == {"a": 1}
    with pytest.raises(RuntimeError, match="boom"):
        next(it)


def test_prefetch_with_native_transform():
    from sav_tpu.data import native_loader as nl

    rng = np.random.default_rng(4)
    batches = [
        {"images": rng.integers(0, 256, (4, 8, 8, 3), dtype=np.uint8)}
        for _ in range(5)
    ]

    def transform(b):
        return {"images": nl.normalize_batch(b["images"], (0, 0, 0), (1, 1, 1))}

    out = list(nl.PrefetchLoader(iter(batches), transform=transform))
    assert len(out) == 5
    np.testing.assert_allclose(
        out[0]["images"], batches[0]["images"].astype(np.float32), rtol=1e-6
    )
