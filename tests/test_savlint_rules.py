"""savlint rule semantics (ISSUE 3): every rule against its fixture pair.

Each rule has a known-bad fixture (exact rule IDs *and line numbers*
asserted — a rule that fires on the wrong line sends a human to the
wrong code) and a known-clean fixture holding the nearest legitimate
idioms (exactly zero findings — false positives are what kill linters).
Plus the suppression machinery itself: line pragmas, file pragmas, the
mandatory justification (SAV100), and the baseline.
"""

import json
import os

import pytest

from sav_tpu.analysis.lint import (
    Finding,
    lint_file,
    lint_paths,
    load_baseline,
    write_baseline,
)
from sav_tpu.analysis.rules import ALL_RULES, rule_catalog

FIXTURES = os.path.join(os.path.dirname(__file__), "analysis_fixtures")


def fixture_findings(name, suppressed=False):
    path = os.path.join(FIXTURES, name)
    found = lint_file(path, root=FIXTURES)
    if suppressed:
        return found
    return [f for f in found if f.suppressed_by is None]


BAD_EXPECTATIONS = {
    "sav101_bad.py": [
        ("SAV101", 10),  # jax.device_get
        ("SAV101", 11),  # jax.block_until_ready
        ("SAV101", 12),  # .item()
        ("SAV101", 13),  # np.asarray
        ("SAV101", 14),  # float(subscript)
        ("SAV101", 15),  # .block_until_ready()
        ("SAV101", 21),  # .item() in evaluate()
    ],
    "sav102_bad.py": [
        ("SAV102", 13),  # jax.jit(train_step_impl) without donation
        ("SAV102", 16),  # bare @jax.jit on a state-carrying fn
        ("SAV102", 21),  # @partial(jax.jit) with donation forgotten
    ],
    "sav103_bad.py": [
        ("SAV103", 7),  # key consumed by normal then bernoulli
        ("SAV103", 14),  # derived key consumed twice
    ],
    "sav104_bad.py": [
        ("SAV104", 9),  # range() counter straight into a jitted call
        ("SAV104", 11),  # BinOp of an enumerate() counter
    ],
    "sav105_bad.py": [
        ("SAV105", 10),  # time.time() under @jax.jit
        ("SAV105", 12),  # time.perf_counter()
        ("SAV105", 13),  # datetime.now()
        ("SAV105", 18),  # fn registered jitted via jax.jit(step_impl)
    ],
    "sav106_bad.py": [
        ("SAV106", 9),  # jax.device_put in fit()
        ("SAV106", 10),  # shard_batch in fit()
        ("SAV106", 17),  # shard_batch in evaluate()
    ],
    "sav107_bad.py": [
        ("SAV107", 13),  # worker += on shared attr
        ("SAV107", 14),  # worker assign on shared attr
        ("SAV107", 17),  # consumer assign on the same attrs
        ("SAV107", 18),
    ],
    "sav_tpu/models/sav108_bad.py": [
        ("SAV108", 6),  # dtype-less zeros
        ("SAV108", 7),  # dtype-less linspace
        ("SAV108", 8),  # float arange
    ],
    "sav109_bad.py": [
        ("SAV109", 8),  # jax.jit per loop iteration
    ],
    "sav110_bad.py": [
        ("SAV110", 6),  # PRNGKey(seed + 1)
        ("SAV110", 7),  # PRNGKey(2 * seed)
    ],
    "sav111_bad.py": [
        ("SAV111", 11),  # float(metrics) on a bare name in fit()
        ("SAV111", 17),  # jax.device_get in the recorder's on_step()
        ("SAV111", 20),  # metrics[...].item() in note_metrics()
        ("SAV111", 21),  # float(metrics[...]) in note_metrics()
    ],
    "sav112_bad.py": [
        ("SAV112", 10),  # jax.device_get in the heartbeat's beat()
        ("SAV112", 11),  # float(metrics[...]) in beat()
        ("SAV112", 15),  # .block_until_ready() in fleet_event()
        ("SAV112", 21),  # metrics[...].item() in autoprof note_window()
        ("SAV112", 24),  # float(metrics) on a bare name in request()
    ],
    "sav113_bad.py": [
        ("SAV113", 13),  # ad-hoc jax.profiler.start_trace in fit()
        ("SAV113", 15),  # jax.profiler.stop_trace in fit()
        ("SAV113", 17),  # per-N-steps device-memory pprof in fit()
        ("SAV113", 22),  # live_buffer_ranking in evaluate()
        ("SAV113", 26),  # memdump inside train_step_placed()
    ],
    "sav_tpu/obs/sav114_bad.py": [
        ("SAV114", 11),  # sys.exit on a validation failure
        ("SAV114", 15),  # os._exit handed around as a callback default
        ("SAV114", 17),  # os._exit from a monitor path
        ("SAV114", 23),  # raise SystemExit as error handling
    ],
    "sav115_bad.py": [
        ("SAV115", 10),  # .block_until_ready() in the batcher's submit()
        ("SAV115", 11),  # float(metrics[...]) in submit()
        ("SAV115", 16),  # jax.device_get in next_batch() — per-request sync
        ("SAV115", 22),  # float(metrics) on a bare name in _formed_batches()
        ("SAV115", 26),  # .block_until_ready() in the placement stage
    ],
    "sav116_bad.py": [
        ("SAV116", 10),  # .block_until_ready() inside a span stamp
        ("SAV116", 16),  # jax.device_get in the window observation
        ("SAV116", 22),  # float(metrics[...]) in observe_completed()
        ("SAV116", 26),  # metrics[...].item() in the heartbeat emitter
    ],
    "sav117_bad.py": [
        ("SAV117", 9),   # inline PartitionSpec for a param
        ("SAV117", 10),  # inline NamedSharding
        ("SAV117", 17),  # jsh.NamedSharding(...) — qualified spelling
        ("SAV117", 17),  # ...wrapping a jsh.PartitionSpec(...) call
    ],
    "sav118_bad.py": [
        ("SAV118", 11),  # .block_until_ready() in the router's admit()
        ("SAV118", 15),  # jax.device_get in route()
        ("SAV118", 19),  # float(metrics[...]) in note_result()
        ("SAV118", 23),  # metrics[...].item() in _refresh_views()
    ],
    "sav119_bad.py": [
        ("SAV119", 11),  # .block_until_ready() in _dispatch's stamp path
        ("SAV119", 15),  # jax.device_get in _route_with_waits()
        ("SAV119", 19),  # float(metrics[...]) in _observe_completion()
        ("SAV119", 23),  # metrics[...].item() in router_beat()
    ],
    "sav_tpu/models/sav120_bad.py": [
        ("SAV120", 7),  # x.astype(jnp.int8) — bare cast, no scale
        ("SAV120", 8),  # x.astype("int8") — string-dtype spelling
        ("SAV120", 9),  # np.asarray(x, np.int8) — positional dtype
        ("SAV120", 10),  # jnp.array(x, dtype=jnp.int8) — kwarg dtype
    ],
    "sav121_bad.py": [
        ("SAV121", 18),  # guarded attr read lock-free in a reachable helper
        ("SAV121", 23),  # guarded attr mutated lock-free in the thread target
    ],
    "sav122_bad.py": [
        ("SAV122", 19),  # meta->data here, data->meta in scan(): a cycle
    ],
    "sav_tpu/serve/sav123_bad.py": [
        ("SAV123", 13),  # Queue.get() with no timeout
        ("SAV123", 14),  # Lock.acquire() with no timeout
        ("SAV123", 18),  # Thread.join() with no timeout
        ("SAV123", 19),  # timeout=None — forever, spelled out
    ],
    "sav124_bad.py": [
        ("SAV124", 6),  # bound thread: daemon unset, never joined
        ("SAV124", 12),  # unbound fire-and-forget thread
    ],
    "sav125_bad.py": [
        ("SAV125", 12),  # .observe() on an alert engine in next_batch()
        ("SAV125", 18),  # .evaluate() on an alert rule in admit()
        ("SAV125", 23),  # .roll_once() on the roller in _dispatch()
        ("SAV125", 29),  # resolved sav_tpu.obs.alerts call in a stamp
    ],
    "sav126_bad.py": [
        ("SAV126", 14),  # .observe_digests() on a quality fold in next_batch()
        ("SAV126", 20),  # .snapshot() on a quality tracker in admit()
        ("SAV126", 25),  # .score_shadow() on the scorer in _dispatch()
        ("SAV126", 31),  # resolved sav_tpu.obs.quality call in a stamp
        ("SAV126", 38),  # jax.device_get inside the quality fold itself
    ],
}

CLEAN_FIXTURES = [
    "sav101_clean.py",
    "sav102_clean.py",
    "sav103_clean.py",
    "sav104_clean.py",
    "sav105_clean.py",
    "sav106_clean.py",
    "sav107_clean.py",
    "sav_tpu/models/sav108_clean.py",
    "sav109_clean.py",
    "sav110_clean.py",
    "sav111_clean.py",
    "sav112_clean.py",
    "sav113_clean.py",
    "sav_tpu/obs/sav114_clean.py",
    "sav115_clean.py",
    "sav116_clean.py",
    "sav_tpu/parallel/sav117_clean.py",
    "sav118_clean.py",
    "sav119_clean.py",
    "sav_tpu/models/sav120_clean.py",
    "sav121_clean.py",
    "sav122_clean.py",
    "sav_tpu/serve/sav123_clean.py",
    "sav124_clean.py",
    "sav125_clean.py",
    "sav126_clean.py",
]


@pytest.mark.parametrize("name", sorted(BAD_EXPECTATIONS))
def test_known_bad_fixture_exact_rules_and_lines(name):
    got = [(f.rule, f.line) for f in fixture_findings(name)]
    assert got == BAD_EXPECTATIONS[name]


@pytest.mark.parametrize("name", CLEAN_FIXTURES)
def test_known_clean_fixture_has_zero_findings(name):
    assert fixture_findings(name) == []


def test_every_rule_has_a_fixture_pair():
    """A rule without fixtures is a rule whose regressions are invisible."""
    covered = {rule for findings in BAD_EXPECTATIONS.values()
               for rule, _ in findings}
    assert covered == {r.id for r in ALL_RULES}


def test_severity_and_hint_attached():
    by_id = {r.id: r for r in ALL_RULES}
    for f in fixture_findings("sav101_bad.py") + fixture_findings(
        "sav102_bad.py"
    ):
        assert f.severity == by_id[f.rule].severity
        assert f.hint  # every finding tells the reader how to fix it
        assert f.code  # and shows the offending line


# ----------------------------------------------------------- suppression


def test_line_pragma_suppresses_and_requires_justification():
    found = fixture_findings("pragmas_fixture.py", suppressed=True)
    by = {(f.rule, f.line): f for f in found}
    # Justified pragma: SAV101 suppressed, no SAV100.
    assert by[("SAV101", 9)].suppressed_by == "pragma"
    # Unjustified pragma: suppression still applies (the author's intent
    # is clear) but pragma hygiene makes the missing reason a finding.
    assert by[("SAV101", 10)].suppressed_by == "pragma"
    assert by[("SAV100", 10)].suppressed_by is None
    # Unknown rule id: no suppression of the real finding + hygiene error.
    assert by[("SAV101", 11)].suppressed_by is None
    assert by[("SAV100", 11)].suppressed_by is None


def test_file_pragma_suppresses_whole_file():
    found = fixture_findings("pragmas_file_fixture.py", suppressed=True)
    assert [(f.rule, f.suppressed_by) for f in found] == [
        ("SAV110", "pragma"),
        ("SAV110", "pragma"),
    ]


def test_baseline_roundtrip_suppresses_exactly_counted_findings(tmp_path):
    baseline = str(tmp_path / "baseline.json")
    bad = os.path.join(FIXTURES, "sav110_bad.py")
    first = lint_paths([bad], root=FIXTURES)
    assert len(first.findings) == 2
    n = write_baseline(baseline, first.findings)
    assert n == 2  # distinct source lines -> distinct entries
    entries = load_baseline(baseline)
    assert all(e["justification"].startswith("TODO") for e in entries)
    again = lint_paths([bad], root=FIXTURES, baseline=baseline)
    assert again.findings == []
    assert [f.suppressed_by for f in again.suppressed] == ["baseline"] * 2


def test_baseline_count_does_not_absorb_new_duplicates(tmp_path):
    """count=1 in the baseline grandfathers ONE occurrence; a copy-pasted
    second violation on an identical line still fails."""
    baseline = str(tmp_path / "baseline.json")
    src = tmp_path / "dup.py"
    src.write_text(
        "import jax\n\n\ndef make(seed):\n"
        "    a = jax.random.PRNGKey(seed + 1)\n"
        "    return a\n"
    )
    res = lint_paths([str(src)], root=str(tmp_path))
    write_baseline(baseline, res.findings)
    src.write_text(
        "import jax\n\n\ndef make(seed):\n"
        "    a = jax.random.PRNGKey(seed + 1)\n"
        "    b = jax.random.PRNGKey(seed + 1)\n"
        "    return a, b\n"
    )
    res2 = lint_paths([str(src)], root=str(tmp_path), baseline=baseline)
    assert [(f.rule, f.line) for f in res2.findings] == [("SAV110", 6)]


def test_rewrite_preserves_existing_entries_and_justifications(tmp_path):
    """--write-baseline must not orphan earlier grandfathered findings:
    re-snapshotting (un-baselined, as the CLI does) keeps surviving
    entries AND their hand-edited justifications; entries whose
    violation was fixed fall out."""
    baseline = str(tmp_path / "baseline.json")
    src = tmp_path / "mix.py"
    src.write_text(
        "import jax\n\n\ndef make(seed):\n"
        "    a = jax.random.PRNGKey(seed + 1)\n"
        "    b = jax.random.PRNGKey(seed + 2)\n"
        "    return a, b\n"
    )
    write_baseline(baseline, lint_paths([str(src)], root=str(tmp_path)).findings)
    entries = load_baseline(baseline)
    entries[0]["justification"] = "legacy stream, migrating in PR 9"
    with open(baseline, "w") as f:
        json.dump({"version": 1, "entries": entries}, f)
    # One violation fixed, the justified one still present.
    src.write_text(
        "import jax\n\n\ndef make(seed):\n"
        "    a = jax.random.PRNGKey(seed + 1)\n"
        "    b = jax.random.fold_in(jax.random.PRNGKey(seed), 2)\n"
        "    return a, b\n"
    )
    unbaselined = lint_paths([str(src)], root=str(tmp_path))
    write_baseline(baseline, unbaselined.findings)
    rewritten = load_baseline(baseline)
    assert len(rewritten) == 1
    assert rewritten[0]["code"] == "a = jax.random.PRNGKey(seed + 1)"
    assert rewritten[0]["justification"] == "legacy stream, migrating in PR 9"
    assert lint_paths(
        [str(src)], root=str(tmp_path), baseline=baseline
    ).findings == []


def test_pragma_text_inside_strings_is_inert(tmp_path):
    """Only real # comments arm suppression: quoting the syntax in a
    docstring (as this repo's own modules do) must not suppress."""
    src = tmp_path / "documented.py"
    src.write_text(
        '"""Docs quote the syntax: # savlint: disable-file=SAV110 -- example."""\n'
        "import jax\n\n\ndef make(seed):\n"
        "    return jax.random.PRNGKey(seed + 1)\n"
    )
    res = lint_paths([str(src)], root=str(tmp_path))
    assert [(f.rule, f.line) for f in res.findings] == [("SAV110", 6)]


# ---------------------------------------------------------------- plumbing


def test_select_and_ignore_filter_rules():
    bad = os.path.join(FIXTURES, "sav101_bad.py")
    only = lint_paths([bad], root=FIXTURES, select=["SAV101"])
    assert {f.rule for f in only.findings} == {"SAV101"}
    none = lint_paths([bad], root=FIXTURES, ignore=["SAV101"])
    assert none.findings == []


def test_syntax_error_is_a_finding_not_a_crash(tmp_path):
    src = tmp_path / "broken.py"
    src.write_text("def f(:\n")
    res = lint_paths([str(src)], root=str(tmp_path))
    assert [f.rule for f in res.findings] == ["SAV001"]
    assert res.findings[0].severity == "error"


def test_rule_catalog_is_complete():
    cat = {r["id"]: r for r in rule_catalog()}
    assert set(cat) == {r.id for r in ALL_RULES} | {"SAV100"}
    for r in cat.values():
        assert r["summary"] and r["hint"] and r["severity"] in (
            "error", "warning",
        )


def test_finding_json_shape():
    f = fixture_findings("sav110_bad.py")[0]
    d = json.loads(json.dumps(f.to_dict()))
    assert d["rule"] == "SAV110" and d["line"] == 6 and d["path"].endswith(
        "sav110_bad.py"
    )
    assert isinstance(f, Finding)
