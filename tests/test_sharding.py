"""Multi-chip sharding tests on the 8-device virtual CPU mesh: DP, DP×TP,
and their numerical equivalence — the distributed coverage tier the
reference never had (SURVEY.md §4 'No distributed tests')."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from sav_tpu.data import synthetic_data_iterator
from sav_tpu.models import create_model
from sav_tpu.parallel import (
    MODEL_AXIS,
    create_mesh,
    param_path_specs,
)
from sav_tpu.train import TrainConfig, Trainer


def _config(**kw):
    base = dict(
        model_name="vit_ti_patch16",
        num_classes=10,
        image_size=32,
        compute_dtype="float32",
        global_batch_size=16,
        num_train_images=64,
        num_epochs=2,
        warmup_epochs=1,
        transpose_images=False,
        seed=0,
    )
    base.update(kw)
    return TrainConfig(**base)


def _model():
    return create_model(
        "vit_ti_patch16", num_classes=10, dtype=jnp.float32,
        num_layers=2, embed_dim=64, num_heads=4,
    )


def test_mesh_shapes(devices):
    mesh = create_mesh()
    assert mesh.axis_names == ("data",) and mesh.devices.size == 8
    mesh = create_mesh({"data": -1, "model": 2})
    assert mesh.shape == {"data": 4, "model": 2}
    with pytest.raises(ValueError):
        create_mesh({"data": 3, "model": 2})


def test_tp_param_specs():
    model = _model()
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 32, 32, 3)), is_training=False
    )
    specs = param_path_specs(variables["params"])
    block = specs["Encoder_0"]["block_0"]["SelfAttentionBlock_0"]
    assert block["to_qkv"]["kernel"] == P(None, None, MODEL_AXIS, None)
    assert block["to_out"]["kernel"] == P(MODEL_AXIS, None, None)
    ff = specs["Encoder_0"]["block_0"]["FFBlock_0"]
    assert ff["fc1"]["kernel"] == P(None, MODEL_AXIS)
    assert ff["fc2"]["kernel"] == P(MODEL_AXIS, None)
    # Norms/bias/pos tables replicated.
    assert specs["Encoder_0"]["AddAbsPosEmbed_0"]["pos_embed"] == P()


@pytest.mark.slow
def test_dp_and_tp_meshes_agree(devices):
    """Same seed, same data → DP-only and DP×TP runs produce the same loss
    trajectory (the partitioner only changes layouts, not math)."""
    losses = {}
    for name, axes in {"dp": None, "dp_tp": {"data": 4, "model": 2}}.items():
        cfg = _config(mesh_axes=axes)
        trainer = Trainer(cfg, mesh=create_mesh(axes), model=_model())
        state = trainer.init_state()
        data = synthetic_data_iterator(
            batch_size=16, image_size=32, num_classes=10, seed=3
        )
        rng = jax.random.PRNGKey(0)
        run = []
        for _, batch in zip(range(5), data):
            state, metrics = trainer.train_step(state, batch, rng)
            run.append(float(metrics["loss"]))
        losses[name] = run
    np.testing.assert_allclose(losses["dp"], losses["dp_tp"], rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_tp_state_actually_sharded(devices):
    mesh = create_mesh({"data": 4, "model": 2})
    cfg = _config(mesh_axes={"data": 4, "model": 2})
    trainer = Trainer(cfg, mesh=mesh, model=_model())
    state = trainer.init_state()
    qkern = state.params["Encoder_0"]["block_0"]["SelfAttentionBlock_0"]["to_qkv"]["kernel"]
    # heads axis split in 2 → each shard holds half the heads.
    assert qkern.sharding.spec == P(None, None, MODEL_AXIS, None)
    shard_shape = qkern.sharding.shard_shape(qkern.shape)
    assert shard_shape[2] == qkern.shape[2] // 2
    # Optimizer state mirrors pick up the same sharding via path suffixes.
    def has_model_axis(spec):
        return any(
            e == MODEL_AXIS or (isinstance(e, tuple) and MODEL_AXIS in e)
            for e in spec
            if e is not None
        )

    flat = jax.tree_util.tree_flatten_with_path(state.opt_state)[0]
    tp_sharded = [
        leaf for path, leaf in flat
        if hasattr(leaf, "sharding") and leaf.ndim >= 2
        and has_model_axis(leaf.sharding.spec)
    ]
    assert tp_sharded, "adam mu/nu should be TP-sharded like their params"


class TestFSDP:
    """ZeRO-3-style parameter sharding over the 'fsdp' mesh axis."""

    def test_add_fsdp_axis_specs(self):
        from sav_tpu.parallel import FSDP_AXIS, add_fsdp_axis

        # Large 2-D kernel: largest free dim sharded.
        spec = add_fsdp_axis(P(), (512, 2048), 4, min_elements=2**16)
        assert spec == P(None, FSDP_AXIS)
        # TP already took the hidden dim → fsdp lands on the other one.
        spec = add_fsdp_axis(P(None, MODEL_AXIS), (512, 2048), 4, min_elements=0)
        assert spec == P(FSDP_AXIS, MODEL_AXIS)
        # Small tensors stay replicated.
        assert add_fsdp_axis(P(), (64,), 4, min_elements=2**16) == P()
        # Indivisible dims stay replicated.
        assert add_fsdp_axis(P(), (3, 5), 4, min_elements=0) == P()

    def test_params_actually_sharded(self, devices):
        from sav_tpu.parallel import FSDP_AXIS

        mesh = create_mesh({"data": 2, "fsdp": 4})
        cfg = _config(mesh_axes={"data": 2, "fsdp": 4}, global_batch_size=16)
        # Wide enough that kernels cross the 2**16-element FSDP threshold.
        model = create_model(
            "vit_ti_patch16", num_classes=10, dtype=jnp.float32,
            num_layers=2, embed_dim=128, num_heads=4,
        )
        trainer = Trainer(cfg, mesh=mesh, model=model)
        state = trainer.init_state()

        def fsdp_sharded(leaf):
            spec = getattr(getattr(leaf, "sharding", None), "spec", ())
            return any(
                e == FSDP_AXIS or (isinstance(e, tuple) and FSDP_AXIS in e)
                for e in spec if e is not None
            )

        big = [
            l for l in jax.tree.leaves(state.params)
            if np.prod(l.shape) >= 2**16
        ]
        assert big and all(fsdp_sharded(l) for l in big)
        # Optimizer mirrors shard the same way.
        big_opt = [
            l for l in jax.tree.leaves(state.opt_state)
            if hasattr(l, "shape") and np.prod(l.shape) >= 2**16
        ]
        assert big_opt and all(fsdp_sharded(l) for l in big_opt)

    def test_fsdp_matches_dp_numerics(self, devices):
        losses = {}
        for name, axes in {"dp": None, "fsdp": {"data": 2, "fsdp": 4}}.items():
            cfg = _config(mesh_axes=axes)
            trainer = Trainer(cfg, mesh=create_mesh(axes), model=_model())
            state = trainer.init_state()
            data = synthetic_data_iterator(
                batch_size=16, image_size=32, num_classes=10, seed=3
            )
            rng = jax.random.PRNGKey(0)
            run = []
            for _, batch in zip(range(5), data):
                state, metrics = trainer.train_step(state, batch, rng)
                run.append(float(metrics["loss"]))
            losses[name] = run
        np.testing.assert_allclose(losses["dp"], losses["fsdp"], rtol=2e-4, atol=2e-5)
