"""Position-embedding resolution transfer."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sav_tpu.models import create_model
from sav_tpu.models.surgery import adapt_pos_embeds, resize_pos_embed_table


def test_resize_identity():
    t = jax.random.normal(jax.random.PRNGKey(0), (1, 197, 16))
    assert resize_pos_embed_table(t, 197) is t


def test_resize_cls_preserved():
    t = jax.random.normal(jax.random.PRNGKey(0), (1, 1 + 14 * 14, 8))
    out = resize_pos_embed_table(t, 1 + 24 * 24)
    assert out.shape == (1, 1 + 24 * 24, 8)
    np.testing.assert_array_equal(np.asarray(out[:, 0]), np.asarray(t[:, 0]))


def test_resize_no_cls():
    t = jax.random.normal(jax.random.PRNGKey(0), (1, 49, 8))
    out = resize_pos_embed_table(t, 196)
    assert out.shape == (1, 196, 8)


def test_resize_roundtrip_close():
    """Up then down returns near the original (low-frequency tables)."""
    g = jnp.linspace(0, 1, 14)
    smooth = (g[:, None] + g[None, :]).reshape(1, 196, 1)
    smooth = jnp.broadcast_to(smooth, (1, 196, 4))
    up = resize_pos_embed_table(smooth, 576)
    back = resize_pos_embed_table(up, 196)
    np.testing.assert_allclose(np.asarray(back), np.asarray(smooth), atol=5e-2)


def test_resize_rejects_non_square():
    t = jnp.zeros((1, 12, 8))
    with pytest.raises(ValueError, match="neither"):
        resize_pos_embed_table(t, 16)


def test_vit_finetune_at_higher_resolution():
    """224-pretrained ViT params transfer to 384 input and run."""
    model = create_model("vit_s_patch16", num_classes=10, num_layers=2,
                         embed_dim=64, num_heads=4)
    x224 = jnp.ones((1, 224, 224, 3))
    x384 = jnp.ones((1, 384, 384, 3))
    p224 = model.init({"params": jax.random.PRNGKey(0)}, x224,
                      is_training=False)["params"]
    p384_tpl = jax.eval_shape(
        lambda: model.init({"params": jax.random.PRNGKey(0)}, x384,
                           is_training=False)["params"]
    )
    p384 = adapt_pos_embeds(p224, p384_tpl)
    table = p384["Encoder_0"]["AddAbsPosEmbed_0"]["pos_embed"]
    assert table.shape == (1, 1 + 24 * 24, 64)
    logits = model.apply({"params": p384}, x384, is_training=False)
    assert logits.shape == (1, 10)
    # Non-pos-embed leaves are untouched.
    np.testing.assert_array_equal(
        np.asarray(p384["head"]["kernel"]), np.asarray(p224["head"]["kernel"])
    )
