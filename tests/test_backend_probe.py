"""Bounded backend probing (sav_tpu/utils/backend_probe.py).

A down/wedged relay hangs in-process backend init, so train.py/bench.py
gate on a subprocess probe. These tests pin the decision logic; the
subprocess probe itself is exercised for real by every on-chip run.
"""

import sav_tpu.utils.backend_probe as bp


def _clear(monkeypatch):
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.delenv("PALLAS_AXON_POOL_IPS", raising=False)


def test_accelerator_not_expected_when_env_empty(monkeypatch):
    _clear(monkeypatch)
    assert not bp.accelerator_expected()


def test_accelerator_not_expected_when_cpu_pinned(monkeypatch):
    _clear(monkeypatch)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert not bp.accelerator_expected()


def test_accelerator_expected_with_relay_trigger(monkeypatch):
    _clear(monkeypatch)
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1")
    assert bp.accelerator_expected()


def test_accelerator_expected_with_tpu_platform(monkeypatch):
    _clear(monkeypatch)
    monkeypatch.setenv("JAX_PLATFORMS", "axon,cpu")
    assert bp.accelerator_expected()


def test_wait_short_circuits_cpu_only(monkeypatch):
    _clear(monkeypatch)
    # No subprocess spawned: returns immediately without burning the deadline.
    monkeypatch.setattr(
        bp, "probe_backend", lambda **kw: (_ for _ in ()).throw(AssertionError)
    )
    assert bp.wait_for_backend(deadline_s=0.01) == "cpu"


def test_wait_gives_up_at_deadline(monkeypatch):
    _clear(monkeypatch)
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1")
    monkeypatch.setattr(bp, "probe_backend", lambda timeout_s: None)
    assert bp.wait_for_backend(deadline_s=0.05, poll_s=0.01) is None


def test_wait_returns_platform_on_success(monkeypatch):
    _clear(monkeypatch)
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1")
    monkeypatch.setattr(bp, "probe_backend", lambda timeout_s: "axon")
    assert bp.wait_for_backend(deadline_s=5.0) == "axon"


def test_require_backend_or_exit_abort_contract(monkeypatch):
    """Exit code 3 is the contract wrapper scripts key on; pin it."""
    import pytest

    _clear(monkeypatch)
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1")
    monkeypatch.setattr(bp, "probe_backend", lambda timeout_s: None)
    with pytest.raises(SystemExit) as exc:
        bp.require_backend_or_exit(0.05, tag="test")
    assert exc.value.code == 3
    monkeypatch.setattr(bp, "probe_backend", lambda timeout_s: "axon")
    assert bp.require_backend_or_exit(5.0, tag="test") == "axon"


def test_cpu_platform_counts_as_unreachable_when_accel_expected(monkeypatch):
    _clear(monkeypatch)
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1")
    calls = []

    def fake_run(cmd, **kw):
        calls.append(cmd)

        class P:
            returncode = 0
            stdout = "cpu\n16384.0\n"

        return P()

    monkeypatch.setattr(bp.subprocess, "run", fake_run)
    assert bp.probe_backend(timeout_s=5.0) is None
    assert calls
