"""Bounded backend probing (sav_tpu/utils/backend_probe.py).

A down/wedged relay hangs in-process backend init, so train.py/bench.py
gate on a subprocess probe. These tests pin the decision logic; the
subprocess probe itself is exercised for real by every on-chip run.
"""

import sav_tpu.utils.backend_probe as bp


def _clear(monkeypatch):
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.delenv("PALLAS_AXON_POOL_IPS", raising=False)


def test_accelerator_not_expected_when_env_empty(monkeypatch):
    _clear(monkeypatch)
    assert not bp.accelerator_expected()


def test_accelerator_not_expected_when_cpu_pinned(monkeypatch):
    _clear(monkeypatch)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert not bp.accelerator_expected()


def test_accelerator_expected_with_relay_trigger(monkeypatch):
    _clear(monkeypatch)
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1")
    assert bp.accelerator_expected()


def test_accelerator_expected_with_tpu_platform(monkeypatch):
    _clear(monkeypatch)
    monkeypatch.setenv("JAX_PLATFORMS", "axon,cpu")
    assert bp.accelerator_expected()


def test_wait_short_circuits_cpu_only(monkeypatch):
    _clear(monkeypatch)
    # No subprocess spawned: returns immediately without burning the deadline.
    monkeypatch.setattr(
        bp, "probe_backend", lambda **kw: (_ for _ in ()).throw(AssertionError)
    )
    assert bp.wait_for_backend(deadline_s=0.01) == "cpu"


def test_wait_gives_up_at_deadline(monkeypatch):
    _clear(monkeypatch)
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1")
    monkeypatch.setattr(bp, "probe_backend", lambda timeout_s: None)
    assert bp.wait_for_backend(deadline_s=0.05, poll_s=0.01) is None


def test_wait_returns_platform_on_success(monkeypatch):
    _clear(monkeypatch)
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1")
    monkeypatch.setattr(bp, "probe_backend", lambda timeout_s: "axon")
    assert bp.wait_for_backend(deadline_s=5.0) == "axon"


def test_require_backend_or_exit_abort_contract(monkeypatch):
    """Exit code 3 is the contract wrapper scripts key on; pin it."""
    import pytest

    _clear(monkeypatch)
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1")
    monkeypatch.setattr(bp, "probe_backend", lambda timeout_s: None)
    with pytest.raises(SystemExit) as exc:
        bp.require_backend_or_exit(0.05, tag="test")
    assert exc.value.code == 3
    monkeypatch.setattr(bp, "probe_backend", lambda timeout_s: "axon")
    assert bp.require_backend_or_exit(5.0, tag="test") == "axon"


def _fake_probe_run(monkeypatch, stdout, returncode=0):
    calls = []

    def fake_run(cmd, **kw):
        calls.append(cmd)

        class P:
            pass

        P.returncode = returncode
        P.stdout = stdout
        return P()

    monkeypatch.setattr(bp.subprocess, "run", fake_run)
    return calls


def test_cpu_platform_counts_as_unreachable_when_accel_expected(monkeypatch):
    _clear(monkeypatch)
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1")
    calls = _fake_probe_run(monkeypatch, "PROBE_PLATFORM=cpu\n16384.0\n")
    assert bp.probe_backend(timeout_s=5.0) is None
    assert calls


def test_probe_parses_sentinel_not_first_token(monkeypatch):
    """A plugin banner on stdout must not be misread as a platform."""
    _clear(monkeypatch)
    _fake_probe_run(
        monkeypatch,
        "axon-plugin: dialing relay pool...\n"
        "PROBE_PLATFORM=axon\n16384.0\n",
    )
    assert bp.probe_backend(timeout_s=5.0) == "axon"


def test_probe_without_sentinel_is_unreachable(monkeypatch):
    """Stdout that is only banners (no sentinel) is not a working probe —
    the old first-token parse would have reported 'warning:' as a
    reachable platform."""
    _clear(monkeypatch)
    _fake_probe_run(monkeypatch, "warning: something chatty\naxon\n")
    assert bp.probe_backend(timeout_s=5.0) is None


def test_wait_spends_full_deadline(monkeypatch):
    """The wait only gives up when ~1s of budget remains: with a 10s
    deadline and 4s poll, the old `remaining <= poll_s` bail-out stopped
    after ~one sleep; now probes keep coming until the budget is gone."""
    _clear(monkeypatch)
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1")
    clock = {"t": 0.0}
    probes = []

    monkeypatch.setattr(bp.time, "monotonic", lambda: clock["t"])
    monkeypatch.setattr(
        bp.time, "sleep", lambda s: clock.__setitem__("t", clock["t"] + s)
    )

    def probe(timeout_s):
        probes.append((clock["t"], timeout_s))
        clock["t"] += min(timeout_s, 0.5)  # each probe fails fast
        return None

    monkeypatch.setattr(bp, "probe_backend", probe)
    assert bp.wait_for_backend(deadline_s=10.0, poll_s=4.0) is None
    # Probes at ~0, ~4.5, ~9: the third lands inside the final poll window
    # the old logic abandoned.
    assert len(probes) >= 3
    assert probes[-1][0] > 10.0 - 4.0  # a probe ran inside the last poll_s
    assert clock["t"] >= 9.0  # (almost) the whole deadline was spent
    # And every probe timeout stayed within the remaining budget.
    for start, timeout_s in probes:
        assert timeout_s <= max(10.0 - start, 1.0) + 1e-9
