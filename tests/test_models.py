"""Model zoo forward-shape tests.

Mirrors the reference's per-model test pattern (models/*_test.py: init full
paper configs, assert logits ``(2, 1000)``), but at reduced depth/size so the
whole zoo runs quickly on CPU, plus explicit RNG streams for every stochastic
path (the reference leaned on Flax's params-rng fallback — SURVEY.md §4).
Full paper-sized configs are exercised via the registry names in
``test_registry_configs``.
"""

import chex
import flax
import jax
import jax.numpy as jnp
import pytest

from sav_tpu import models




def _rngs():
    return {
        "params": jax.random.PRNGKey(0),
        "dropout": jax.random.PRNGKey(1),
        "stochastic_depth": jax.random.PRNGKey(2),
    }


def _run(model, image_size=32, channels=3, batch=2, is_training=True):
    x = jnp.ones((batch, image_size, image_size, channels), jnp.float32)
    variables = model.init(_rngs(), x, is_training=False)
    out = model.apply(
        variables,
        x,
        is_training=is_training,
        rngs={k: v for k, v in _rngs().items() if k != "params"},
        mutable=["batch_stats"] if "batch_stats" in variables else False,
    )
    logits = out[0] if isinstance(out, tuple) else out
    return logits, variables


@pytest.mark.slow
def test_vit():
    model = models.ViT(
        num_classes=10, embed_dim=64, num_layers=2, num_heads=4, patch_shape=(8, 8)
    )
    logits, _ = _run(model)
    chex.assert_shape(logits, (2, 10))


@pytest.mark.slow
def test_mixer():
    model = models.MLPMixer(
        num_classes=10, embed_dim=64, num_layers=2, tokens_hidden_ch=32,
        channels_hidden_ch=128, patch_shape=(8, 8),
    )
    logits, _ = _run(model)
    chex.assert_shape(logits, (2, 10))


@pytest.mark.slow
def test_cait():
    model = models.CaiT(
        num_classes=10, embed_dim=64, num_layers=2, num_layers_token_only=2,
        num_heads=4, patch_shape=(8, 8), stoch_depth_rate=0.1,
    )
    logits, _ = _run(model)
    chex.assert_shape(logits, (2, 10))


@pytest.mark.slow
def test_tnt():
    model = models.TNT(
        num_classes=10, embed_dim=64, inner_ch=24, num_layers=2, num_heads=4,
        inner_num_heads=4, patch_shape=(16, 16),
    )
    logits, _ = _run(model)
    chex.assert_shape(logits, (2, 10))


@pytest.mark.slow
def test_ceit():
    model = models.CeiT(
        num_classes=10, embed_dim=64, num_layers=2, num_heads=4, patch_shape=(4, 4)
    )
    logits, variables = _run(model)
    chex.assert_shape(logits, (2, 10))
    assert "batch_stats" in variables  # LeFF + stem BatchNorm


@pytest.mark.slow
def test_cvt():
    model = models.CvT(
        num_classes=10, embed_dims=(32, 64, 128), num_layers=(1, 1, 2),
        num_heads=(1, 2, 4),
    )
    logits, variables = _run(model)
    chex.assert_shape(logits, (2, 10))
    assert "batch_stats" in variables  # conv projection BatchNorm


@pytest.mark.slow
def test_botnet():
    model = models.BoTNet(num_classes=10, stage_sizes=(1, 1, 1, 1))
    logits, variables = _run(model, image_size=64)
    chex.assert_shape(logits, (2, 10))
    assert "batch_stats" in variables


@pytest.mark.slow
def test_botnet_eval_mode():
    model = models.BoTNet(num_classes=10, stage_sizes=(1, 1, 1, 1))
    x = jnp.ones((2, 64, 64, 3), jnp.float32)
    variables = model.init(_rngs(), x, is_training=False)
    logits = model.apply(variables, x, is_training=False)
    chex.assert_shape(logits, (2, 10))


@pytest.mark.parametrize("name", models.model_names())
def test_registry_configs(name):
    """Every named config instantiates; tiny ones also run a forward pass."""
    model = models.create_model(name, num_classes=1000)
    assert model is not None
    small = {"vit_ti_patch16", "vit_s_patch32", "mixer_s_patch32"}
    if name in small:
        logits, _ = _run(model, image_size=64, is_training=False)
        chex.assert_shape(logits, (2, 1000))


def test_registry_backend_injection_skips_attention_free_models():
    # MLP-Mixer has no attention → no backend field; must not crash.
    model = models.create_model("mixer_s_patch32", backend="pallas")
    assert model is not None
    vit = models.create_model("vit_ti_patch16", backend="pallas")
    assert vit.backend == "pallas"


def test_registry_unknown_name():
    with pytest.raises(ValueError, match="unknown model"):
        models.create_model("nope")


@pytest.mark.slow
def test_bf16_dtype():
    model = models.create_model(
        "vit_ti_patch16", num_classes=10, dtype=jnp.bfloat16
    )
    x = jnp.ones((2, 32, 32, 3), jnp.bfloat16)
    variables = model.init(_rngs(), x, is_training=False)
    # Params stay fp32; compute runs bf16.
    leaf = jax.tree.leaves(variables["params"])[0]
    assert leaf.dtype == jnp.float32
    logits = model.apply(variables, x, is_training=False)
    chex.assert_shape(logits, (2, 10))


def _randomize_head(variables):
    """Fresh-model logits are vacuously zero (zero-init classifier);
    randomize the head so backend comparisons have teeth."""
    variables = flax.core.unfreeze(variables)
    params = dict(variables["params"])
    params["head"] = {
        "kernel": jax.random.normal(
            jax.random.PRNGKey(2), params["head"]["kernel"].shape
        ) * 0.05,
        "bias": jnp.zeros_like(params["head"]["bias"]),
    }
    variables["params"] = params
    return variables


def _small_config(kind):
    """Small instance of each attention model family, backend-injectable."""
    if kind == "cait":
        return lambda backend: models.CaiT(
            num_classes=10, embed_dim=32, num_layers=2, num_heads=2,
            num_layers_token_only=1, patch_shape=(8, 8), backend=backend,
        )
    if kind == "vit":
        return lambda backend: models.ViT(
            num_classes=10, embed_dim=32, num_layers=2, num_heads=2,
            patch_shape=(8, 8), backend=backend,
        )
    if kind == "tnt":
        return lambda backend: models.TNT(
            num_classes=10, embed_dim=32, inner_ch=24, num_layers=2,
            num_heads=2, inner_num_heads=2, patch_shape=(16, 16),
            backend=backend,
        )
    if kind == "ceit":
        return lambda backend: models.CeiT(
            num_classes=10, embed_dim=32, num_layers=2, num_heads=2,
            patch_shape=(4, 4), backend=backend,
        )
    if kind == "cvt":
        return lambda backend: models.CvT(
            num_classes=10, embed_dims=(16, 32, 64), num_layers=(1, 1, 1),
            num_heads=(1, 2, 4), backend=backend,
        )
    if kind == "botnet":
        return lambda backend: models.BoTNet(
            num_classes=10, stage_sizes=(1, 1, 1, 1), backend=backend,
        )
    raise ValueError(kind)


@pytest.mark.parametrize("kind", ["vit", "cait", "tnt", "ceit", "cvt", "botnet"])
@pytest.mark.slow
def test_model_pallas_backend_matches_xla(kind):
    """Every attention model family cross-checks Pallas vs XLA logits
    (BASELINE.json north-star test requirement; CaiT via the fused
    talking-heads kernel, VERDICT r2 item 7)."""
    import numpy as np

    size = 64 if kind == "botnet" else 32
    x = jax.random.normal(jax.random.PRNGKey(0), (2, size, size, 3))
    outs = {}
    for backend in ("xla", "pallas"):
        model = _small_config(kind)(backend)
        variables = _randomize_head(
            model.init({"params": jax.random.PRNGKey(1)}, x, is_training=False)
        )
        outs[backend] = np.asarray(
            model.apply(variables, x, is_training=False)
        )
    assert np.all(np.isfinite(outs["pallas"]))
    np.testing.assert_allclose(outs["pallas"], outs["xla"], atol=1e-4, rtol=5e-3)


@pytest.mark.slow
def test_cait_pallas_backward_runs_and_matches():
    import numpy as np

    x = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 32, 3))
    grads = {}
    for backend in ("xla", "pallas"):
        model = models.CaiT(
            num_classes=10, embed_dim=32, num_layers=2, num_heads=2,
            num_layers_token_only=1, patch_shape=(8, 8), backend=backend,
        )
        variables = model.init(
            {"params": jax.random.PRNGKey(1)}, x, is_training=False
        )

        def loss(params):
            out = model.apply({"params": params}, x, is_training=False)
            return jnp.sum(out.astype(jnp.float32) ** 2)

        grads[backend] = jax.grad(loss)(variables["params"])
    flat_p, _ = jax.tree.flatten(grads["pallas"])
    flat_x, _ = jax.tree.flatten(grads["xla"])
    for a, b in zip(flat_p, flat_x):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=5e-3
        )


@pytest.mark.slow
def test_vit_remat_matches_no_remat():
    """remat=True must be numerically identical fwd and bwd (it only changes
    what the backward rematerializes) while keeping the same param tree."""
    import numpy as np

    x = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 32, 3))
    outs, grads = {}, {}
    for remat in (False, True):
        model = models.ViT(
            num_classes=10, embed_dim=32, num_layers=2, num_heads=2,
            patch_shape=(8, 8), remat=remat,
        )
        variables = _randomize_head(
            model.init({"params": jax.random.PRNGKey(1)}, x, is_training=False)
        )

        def loss(params):
            out = model.apply({"params": params}, x, is_training=False)
            return jnp.sum(out.astype(jnp.float32) ** 2)

        outs[remat] = np.asarray(
            model.apply(variables, x, is_training=False)
        )
        grads[remat] = jax.grad(loss)(variables["params"])
    np.testing.assert_allclose(outs[True], outs[False], atol=1e-6, rtol=1e-6)
    flat_t, tree_t = jax.tree.flatten(grads[True])
    flat_f, tree_f = jax.tree.flatten(grads[False])
    assert tree_t == tree_f
    for a, b in zip(flat_t, flat_f):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)
