"""The three-way ``auto`` attention dispatch and its tuning cache
(ISSUE 6): test-pinned thresholds on both sides of the dense-logits HBM
budget and the single-block VMEM band, evidence-gated fused promotion via
the attn_tune cache, the 4-D input error path, and the trace-time
dispatch log bench.py stamps into its JSON line / run manifest."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sav_tpu.ops import attention as att
from sav_tpu.ops import attn_tuning
from sav_tpu.ops.attention import (
    _AUTO_PALLAS_LOGITS_BYTES,
    dot_product_attention,
    resolve_attention_backend,
)
from sav_tpu.ops.fused_attention import fused_eligible


@pytest.fixture(autouse=True)
def _isolate_cache(tmp_path):
    """Each test sees an EMPTY tune cache unless it installs one — the
    checked-in default table must not leak measured entries into the
    threshold assertions."""
    empty = tmp_path / "empty_cache.json"
    empty.write_text(json.dumps({"version": 1, "entries": {}}))
    attn_tuning.set_cache_path(str(empty))
    yield
    attn_tuning.set_cache_path(None)


def _install_cache(tmp_path, entries, infeasible=None):
    path = tmp_path / "cache.json"
    attn_tuning.write_cache(str(path), entries, infeasible)
    attn_tuning.set_cache_path(str(path))
    return str(path)


# ------------------------------------------------ threshold boundaries


def test_auto_dense_logits_budget_both_sides():
    """The pallas band boundary: 3 copies × 4 bytes × B·H·Lq·Lk against
    the 2 GiB budget, pinned one shape on each side."""
    # B=8, H=6, L=4096: 3*4*8*6*4096^2 = 9.66e9 > 2 GiB -> pallas
    over = resolve_attention_backend(8, 4096, 4096, 6, 64, on_tpu=True)
    assert over.backend == "pallas" and over.source == "threshold"
    # B=8, H=6, L=1024: 3*4*8*6*1024^2 = 0.6 GiB <= 2 GiB -> not pallas
    under = resolve_attention_backend(8, 1024, 1024, 6, 64, on_tpu=True)
    assert under.backend == "xla"
    # The exact constant is load-bearing for both assertions above.
    assert _AUTO_PALLAS_LOGITS_BYTES == 2 << 30


def test_auto_short_band_defaults_to_xla_without_measured_win():
    """Evidence-gated promotion: an eligible short shape with NO measured
    cache entry stays on XLA (the PERF.md §5 winner), with the reason
    naming the gate."""
    assert fused_eligible(197, 197, 64)
    d = resolve_attention_backend(256, 197, 197, 6, 64, on_tpu=True)
    assert d.backend == "xla" and d.source == "default"
    assert "promotion" in d.reason


def test_auto_single_block_vmem_threshold_both_sides(tmp_path):
    """A fused cache entry only promotes INSIDE the single-block band:
    the same 'fused' verdict at an over-budget shape is ignored."""
    entries = {
        attn_tuning.shape_key("*", 197, 197, 6, 64): {
            "backend": "fused", "block_q": 256, "block_kv": None,
            "block_b": 4, "fwd_ms": 1.0, "fwd_bwd_ms": 3.0, "source": "t"},
        attn_tuning.shape_key("*", 2048, 2048, 6, 64): {
            "backend": "fused", "block_q": 256, "block_kv": None,
            "block_b": 1, "fwd_ms": 1.0, "fwd_bwd_ms": 3.0, "source": "t"},
    }
    _install_cache(tmp_path, entries)
    inside = resolve_attention_backend(256, 197, 197, 6, 64, on_tpu=True)
    assert inside.backend == "fused" and inside.source == "tuned"
    assert inside.block_config == {"block_q": 256, "block_b": 4}
    assert not fused_eligible(2048, 2048, 64)
    outside = resolve_attention_backend(4, 2048, 2048, 6, 64, on_tpu=True)
    assert outside.backend == "xla"  # entry ignored: over the VMEM band


def test_auto_off_tpu_and_dropout_stay_xla():
    d = resolve_attention_backend(256, 197, 197, 6, 64, on_tpu=False)
    assert d.backend == "xla" and "non-TPU" in d.reason
    d = resolve_attention_backend(
        256, 197, 197, 6, 64, on_tpu=True, kernels_ok=False
    )
    assert d.backend == "xla" and "ineligible" in d.reason


def test_tuned_pallas_entry_dispatches_below_threshold(tmp_path):
    """The autotuner sweeps all three backends — a measured pallas win in
    the sub-2-GiB band must dispatch (with its block config), not fall
    through to the XLA default."""
    _install_cache(tmp_path, {
        attn_tuning.shape_key("*", 785, 785, 6, 64): {
            "backend": "pallas", "block_q": 256, "block_kv": 256,
            "block_b": 2, "fwd_ms": 9.0, "fwd_bwd_ms": 12.0, "source": "t"},
    })
    # B=16 keeps dense logits (3·4·16·6·785² ≈ 0.7 GiB) under the 2 GiB
    # threshold — the entry, not the long-band rule, must pick pallas.
    d = resolve_attention_backend(16, 785, 785, 6, 64, on_tpu=True)
    assert d.backend == "pallas" and d.source == "tuned"
    assert d.block_config == {"block_q": 256, "block_kv": 256, "block_b": 2}


def test_tuned_xla_entry_reports_tuned_source(tmp_path):
    _install_cache(tmp_path, {
        attn_tuning.shape_key("*", 197, 197, 6, 64): {
            "backend": "xla", "block_q": None, "block_kv": None,
            "block_b": None, "fwd_ms": 2.25, "fwd_bwd_ms": 7.38,
            "source": "PERF"},
    })
    d = resolve_attention_backend(256, 197, 197, 6, 64, on_tpu=True)
    assert d.backend == "xla" and d.source == "tuned"


def test_checked_in_default_cache_is_loadable_and_consulted():
    """The shipped table (sav_tpu/ops/attn_tune_cache.json) parses and
    resolves the DeiT-S shape to the measured XLA win."""
    attn_tuning.set_cache_path(None)  # default resolution
    assert os.path.exists(attn_tuning.DEFAULT_CACHE_PATH)
    cache = attn_tuning.load_cache(attn_tuning.DEFAULT_CACHE_PATH)
    assert cache.get("version") == attn_tuning.CACHE_VERSION
    d = resolve_attention_backend(256, 197, 197, 6, 64, on_tpu=True)
    assert d.backend == "xla" and d.source == "tuned"
    # The recorded Mosaic infeasibilities (block_b 16/32) survive too.
    inf = cache.get("infeasible", {})
    assert any(
        rec.get("block_b") in (16, 32)
        for recs in inf.values()
        for rec in recs
    )


def test_lookup_batch_wildcard_and_exact_precedence(tmp_path):
    key_star = attn_tuning.shape_key("*", 197, 197, 6, 64)
    key_exact = attn_tuning.shape_key(256, 197, 197, 6, 64)
    _install_cache(tmp_path, {
        key_star: {"backend": "xla", "source": "star"},
        key_exact: {"backend": "fused", "block_q": 128, "source": "exact"},
    })
    assert attn_tuning.lookup(256, 197, 197, 6, 64)["source"] == "exact"
    assert attn_tuning.lookup(64, 197, 197, 6, 64)["source"] == "star"
    assert attn_tuning.lookup(64, 198, 198, 6, 64) is None


def test_broken_cache_degrades_to_static_rule(tmp_path):
    path = tmp_path / "broken.json"
    path.write_text("{not json")
    attn_tuning.set_cache_path(str(path))
    d = resolve_attention_backend(256, 197, 197, 6, 64, on_tpu=True)
    assert d.backend == "xla" and d.source == "default"


# ------------------------------------------------ dot_product_attention


def _qkv(b=2, l=60, h=2, d=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(kk, (b, l, h, d)) for kk in ks)


def test_dispatch_fused_backend_matches_xla():
    q, k, v = _qkv()
    out = dot_product_attention(q, k, v, backend="fused")
    ref = dot_product_attention(q, k, v, backend="xla")
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_dispatch_auto_picks_fused_from_cache(tmp_path, monkeypatch):
    """End to end: a measured fused entry + simulated TPU backend routes
    the real call through the fused kernel."""
    q, k, v = _qkv(l=50)
    _install_cache(tmp_path, {
        attn_tuning.shape_key("*", 50, 50, 2, 16, q.dtype): {
            "backend": "fused", "block_q": 64, "block_kv": None,
            "block_b": 1, "source": "t"},
    })
    monkeypatch.setattr(att, "_on_tpu", lambda: True)
    called = {}
    real = att._fused.fused_attention

    def spy(*a, **kw):
        called.update(kw)
        called["hit"] = True
        return real(*a, **kw, interpret=True)

    monkeypatch.setattr(att._fused, "fused_attention", spy)
    out = dot_product_attention(q, k, v, backend="auto")
    assert called.get("hit") and called.get("block_q") == 64
    ref = att.xla_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_dispatch_4d_error_paths():
    """The kernel backends demand 4-D [B, L, H, D]; dropout likewise
    forces the XLA path — both raise rather than silently degrade."""
    x3 = jnp.zeros((4, 8, 8))
    for backend in ("pallas", "fused"):
        with pytest.raises(ValueError, match="4-D"):
            dot_product_attention(x3, x3, x3, backend=backend)
    q, k, v = _qkv(l=16)
    with pytest.raises(ValueError, match="4-D"):
        dot_product_attention(
            q, k, v, backend="fused",
            dropout_rate=0.5, deterministic=False,
            dropout_rng=jax.random.PRNGKey(0),
        )
    # 5-D (an un-flattened TNT inner layout) is kernel-ineligible too.
    x5 = jnp.zeros((2, 3, 8, 2, 8))
    with pytest.raises(ValueError, match="4-D"):
        dot_product_attention(x5, x5, x5, backend="fused")


def test_dispatch_rejects_unknown_backend():
    q, k, v = _qkv(l=8)
    with pytest.raises(ValueError, match="unknown attention backend"):
        dot_product_attention(q, k, v, backend="cuda")


def test_dispatch_log_records_resolutions():
    att.clear_dispatch_log()
    q, k, v = _qkv(l=24)
    dot_product_attention(q, k, v, backend="xla")
    dot_product_attention(q, k, v, backend="fused")
    log = att.snapshot_dispatch_log()
    assert {e["backend"] for e in log} == {"xla", "fused"}
    for e in log:
        assert e["shape"] == [2, 24, 2, 16]
        assert e["kv_len"] == 24
        assert set(e) >= {"requested", "backend", "reason", "source"}
    # Idempotent per (shape, kv_len, requested): re-tracing adds no dupes.
    dot_product_attention(q, k, v, backend="xla")
    assert len(att.snapshot_dispatch_log()) == len(log)
    # Cross-attention with the same query shape but different kv_len is a
    # DISTINCT record (class-attention / CvT sites must not collapse).
    k2 = jnp.concatenate([k, k], axis=1)
    dot_product_attention(q, k2, k2, backend="xla")
    log2 = att.snapshot_dispatch_log()
    assert len(log2) == len(log) + 1
    assert {e["kv_len"] for e in log2} == {24, 48}
    att.clear_dispatch_log()
    assert att.snapshot_dispatch_log() == []


def test_attention_block_fused_backend():
    """Model plumbing: AttentionBlock(backend='fused') runs end to end and
    matches the XLA block bit-for-bit in structure (same params)."""
    from sav_tpu.models.layers.attention import SelfAttentionBlock

    x = jax.random.normal(jax.random.PRNGKey(0), (2, 50, 32))
    fused_block = SelfAttentionBlock(num_heads=2, backend="fused")
    xla_block = SelfAttentionBlock(num_heads=2, backend="xla")
    variables = fused_block.init(jax.random.PRNGKey(1), x, is_training=False)
    out_f = fused_block.apply(variables, x, is_training=False)
    out_x = xla_block.apply(variables, x, is_training=False)
    np.testing.assert_allclose(
        np.asarray(out_f), np.asarray(out_x), atol=2e-5, rtol=2e-5
    )
