"""The attention autotuner (tools/attn_tune.py) and the measurement
methodology it inherits (docs/benchmarking.md Traps 1–3), pinned in
tier-1 so the protocol cannot silently regress:

- a bad/infeasible kernel config must be RECORDED and skipped, never kill
  the sweep (the flash_sweep failure mode this tool replaced);
- the emitted cache must be the exact schema the dispatcher consumes;
- the timing loops must thread both the primal and the cotangent through
  the scan carry — asserted structurally on the jaxpr: every matmul in
  the scan body must be reachable from the carry, i.e. not hoistable;
- ab_step's full-step timing loop must thread the train state.
"""

import importlib.util
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sav_tpu.ops import attn_tuning

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", f"{name}.py")
    )
    module = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = module
    spec.loader.exec_module(module)
    return module


@pytest.fixture(scope="module")
def attn_tune():
    return _load_tool("attn_tune")


# ----------------------------------------------------- sweep machinery


def test_sweep_records_infeasible_and_continues(attn_tune, monkeypatch):
    """A config whose compile raises (the Mosaic VMEM failure mode) is
    recorded as infeasible with the error message; the rest of the sweep
    still measures and a winner is still picked."""
    fumod = sys.modules["sav_tpu.ops.fused_attention"]
    real = fumod.fused_attention

    def failing(q, k, v, *a, **kw):
        if kw.get("block_b") == 2:
            raise RuntimeError("Mosaic: VMEM over budget (simulated)")
        return real(q, k, v, *a, **kw)

    monkeypatch.setattr(attn_tune.fumod, "fused_attention", failing)
    results, infeasible = attn_tune.sweep_shape(
        (2, 50, 50, 2, 16),
        blocks=[(64, 64)], block_bs=[1, 2], backends=["xla", "fused"],
        iters=2, rounds=1, bwd=False, log=lambda *_: None,
    )
    assert [r["name"] for r in results] == ["xla", "fused bq=64 bb=1"]
    assert len(infeasible) == 1
    assert infeasible[0]["block_b"] == 2
    assert "VMEM over budget (simulated)" in infeasible[0]["error"]
    winner = attn_tune.pick_winner(results, bwd=False)
    assert winner is not None


def test_sweep_all_infeasible_records_instead_of_crashing(attn_tune, monkeypatch):
    """Every candidate failing must yield (no winner, all recorded) — not
    a ZeroDivisionError out of the empty timing rotation."""

    def always_fail(*a, **kw):
        raise RuntimeError("Mosaic: simulated reject")

    monkeypatch.setattr(attn_tune.fumod, "fused_attention", always_fail)
    results, infeasible = attn_tune.sweep_shape(
        (2, 50, 50, 2, 16),
        blocks=[(64, 64)], block_bs=[1], backends=["fused"],
        iters=2, rounds=1, bwd=False, log=lambda *_: None,
    )
    assert results == []
    assert len(infeasible) == 1
    assert attn_tune.pick_winner(results, bwd=False) is None


def test_sweep_pins_block_b_through_backward_trace(attn_tune, monkeypatch):
    """The swept block_b must still be pinned when the flash BACKWARD
    traces — jax.vjp's bwd rule fires after the forward call returns, so
    a pin scoped to the forward call alone would silently time every
    'bb=N' row with the default-block_b backward."""
    flmod = attn_tune.flmod
    observed = []
    real_bwd = flmod._flash_backward_pallas

    def spy(*a, **kw):
        # 999 divides none of (8, 4, 2): the unpinned picker returns 1,
        # the pinned one returns the swept value regardless of bh.
        observed.append(flmod._pick_block_b(999))
        return real_bwd(*a, **kw)

    monkeypatch.setattr(flmod, "_flash_backward_pallas", spy)
    attn_tune.sweep_shape(
        (2, 24, 24, 2, 16),
        blocks=[(16, 16)], block_bs=[4], backends=["pallas"],
        iters=2, rounds=1, bwd=True, log=lambda *_: None,
    )
    assert observed, "backward never traced"
    assert all(v == 4 for v in observed), observed


def test_sweep_precheck_skips_over_budget_without_compiling(attn_tune):
    """Configs the VMEM estimator rules out are recorded infeasible
    without paying a compile (block_b=8 at a deliberately fat shape)."""
    specs = list(attn_tune.variant_specs(
        8, 197, 197, 6, 64,
        blocks=[(256, 256)], block_bs=[8], backends=["fused"], itemsize=2,
    ))
    assert len(specs) == 1
    name, backend, cfg, build = specs[0]
    assert backend == "fused" and build is None  # estimator said no


def test_emitted_cache_is_dispatcher_consumable(attn_tune, tmp_path):
    """End to end on CPU: sweep → write_cache → attn_tuning.lookup →
    resolve_attention_backend consults the new entry (and the infeasible
    record survives the merge)."""
    out = str(tmp_path / "cache.json")
    rc = attn_tune.main([
        "--shapes", "2,50,2,16", "--blocks", "64,64", "--block-b", "1",
        "--backends", "xla,fused", "--iters", "2", "--rounds", "1",
        "--fwd-only", "--out", out,
    ])
    assert rc == 0
    cache = json.load(open(out))
    assert cache["version"] == attn_tuning.CACHE_VERSION
    key = attn_tuning.shape_key(2, 50, 50, 2, 16)
    star = attn_tuning.shape_key("*", 50, 50, 2, 16)
    assert key in cache["entries"] and star in cache["entries"]
    entry = cache["entries"][key]
    assert entry["backend"] in ("xla", "fused", "pallas")
    assert entry["fwd_ms"] > 0
    # Merge keeps prior entries and accumulates infeasible records.
    attn_tuning.write_cache(
        out,
        {"B9.Lq9.Lkv9.H9.D9.bfloat16": {"backend": "xla", "source": "x"}},
        {key: [{"backend": "pallas", "block_b": 16, "error": "VMEM"}]},
        merge=True,
    )
    merged = json.load(open(out))
    assert key in merged["entries"]  # survived the merge
    assert merged["infeasible"][key][0]["block_b"] == 16
    # The dispatcher consults it.
    attn_tuning.set_cache_path(out)
    try:
        assert attn_tuning.lookup(2, 50, 50, 2, 16) == entry
    finally:
        attn_tuning.set_cache_path(None)


def test_winner_prefers_fwd_bwd_metric(attn_tune):
    results = [
        {"name": "a", "backend": "xla", "config": None,
         "fwd_ms": 1.0, "fwd_bwd_ms": 9.0},
        {"name": "b", "backend": "fused",
         "config": {"block_q": 64, "block_kv": None, "block_b": 2},
         "fwd_ms": 2.0, "fwd_bwd_ms": 3.0},
    ]
    assert attn_tune.pick_winner(results, bwd=True)["name"] == "b"
    assert attn_tune.pick_winner(results, bwd=False)["name"] == "a"
    entry = attn_tune.winner_entry(attn_tune.pick_winner(results, bwd=True), "src")
    assert entry == {
        "backend": "fused", "block_q": 64, "block_kv": None, "block_b": 2,
        "fwd_ms": 2.0, "fwd_bwd_ms": 3.0, "source": "src",
    }


# --------------------------------------- methodology pins (Traps 1 & 2)


def _subjaxprs(eqn):
    for v in eqn.params.values():
        vals = v if isinstance(v, (tuple, list)) else (v,)
        for x in vals:
            if isinstance(x, jax.extend.core.ClosedJaxpr):
                yield x.jaxpr
            elif isinstance(x, jax.extend.core.Jaxpr):
                yield x


def _check_dots_carry_fed(jaxpr, seeds):
    """Walk a jaxpr with `seeds` (carry-derived invars) marked reachable;
    return (num_dots, num_carry_fed_dots), descending into sub-jaxprs with
    positional invar mapping where it lines up."""
    reachable = set(map(id, seeds))
    dots = fed = 0
    for eqn in jaxpr.eqns:
        ins_reach = [
            not hasattr(v, "val") and id(v) in reachable for v in eqn.invars
        ]
        if eqn.primitive.name in ("dot_general", "pjit") or list(
            _subjaxprs(eqn)
        ):
            if eqn.primitive.name == "dot_general":
                dots += 1
                fed += any(ins_reach)
            for sub in _subjaxprs(eqn):
                if len(sub.invars) == len(eqn.invars):
                    sub_seeds = [
                        sv for sv, r in zip(sub.invars, ins_reach) if r
                    ]
                elif any(ins_reach):
                    sub_seeds = list(sub.invars)  # conservative
                else:
                    sub_seeds = []
                d, f = _check_dots_carry_fed(sub, sub_seeds)
                dots += d
                fed += f
        elif eqn.primitive.name == "dot_general":
            dots += 1
            fed += any(ins_reach)
        if any(ins_reach):
            reachable.update(id(v) for v in eqn.outvars)
    return dots, fed


def _scan_carry_dot_stats(fn, *args):
    """For every scan in fn's jaxpr: (dots, carry-fed dots) inside the
    scan body, seeding reachability from the carry invars only."""
    jaxpr = jax.make_jaxpr(fn)(*args)

    stats = []

    def visit(jx):
        for eqn in jx.eqns:
            if eqn.primitive.name == "scan":
                body = eqn.params["jaxpr"].jaxpr
                nc, ncar = eqn.params["num_consts"], eqn.params["num_carry"]
                carry = body.invars[nc:nc + ncar]
                stats.append(_check_dots_carry_fed(body, carry))
            else:
                for sub in _subjaxprs(eqn):
                    visit(sub)

    visit(jaxpr.jaxpr)
    return stats


def _qkv(l=24, d=16):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return tuple(jax.random.normal(kk, (2, l, 2, d)) for kk in ks)


def test_timing_loop_threads_primal_through_carry(attn_tune):
    """Trap 1 pin: in the fwd timing loop's scan body, EVERY matmul is
    reachable from the carry — nothing is loop-invariant-hoistable."""
    from sav_tpu.ops.attention import xla_attention

    q, k, v = _qkv()
    loop = attn_tune.timing_loop(lambda q, k, v: xla_attention(q, k, v), 3)
    stats = _scan_carry_dot_stats(loop, q, k, v)
    assert stats, "timing loop lost its scan"
    for dots, fed in stats:
        assert dots > 0
        assert fed == dots, f"{dots - fed} hoistable matmuls in timing scan"


def test_grad_loop_threads_primal_and_cotangent(attn_tune):
    """Traps 1+2 pin: the fwd+bwd loop's backward matmuls (dP = g·Vᵀ and
    friends) must also be carry-fed — a trivial/loop-invariant cotangent
    would let the simplifier collapse them (docs/benchmarking.md)."""
    from sav_tpu.ops.attention import xla_attention

    q, k, v = _qkv()
    cot = jax.random.normal(jax.random.PRNGKey(1), q.shape)
    wrapped = attn_tune.grad_wrap(lambda q, k, v: xla_attention(q, k, v), cot)
    loop = attn_tune.timing_loop(wrapped, 3)
    stats = _scan_carry_dot_stats(loop, q, k, v)
    assert stats, "grad timing loop lost its scan"
    # The fwd+bwd body has strictly more matmuls than the fwd-only body
    # (the backward's transpose-dots), and every one is carry-fed.
    fwd_dots = _scan_carry_dot_stats(
        attn_tune.timing_loop(lambda q, k, v: xla_attention(q, k, v), 3),
        q, k, v,
    )[0][0]
    for dots, fed in stats:
        assert dots > fwd_dots, "backward matmuls missing from the loop"
        assert fed == dots, f"{dots - fed} hoistable matmuls in grad scan"


def test_methodology_pin_catches_hoistable_loop(attn_tune):
    """The pin itself must fail a Trap-1 regression: a loop that does NOT
    thread the primal (constant operands every iteration) shows
    non-carry-fed matmuls."""
    from sav_tpu.ops.attention import xla_attention

    q, k, v = _qkv()

    @jax.jit
    def bad_loop(q, k, v):
        def body(carry, _):
            out = xla_attention(q, k, v)  # loop-invariant: hoistable
            return carry + jnp.sum(out.astype(jnp.float32)) * 1e-30, None

        tot, _ = jax.lax.scan(body, jnp.float32(0), None, length=3)
        return tot

    stats = _scan_carry_dot_stats(bad_loop, q, k, v)
    assert stats
    dots, fed = stats[0]
    assert dots > 0 and fed < dots, (
        "reachability check failed to flag a hoistable timing loop"
    )


def test_ab_step_time_steps_threads_state():
    """ab_step's full-step timing loop must thread the train state through
    the python loop (call N receives call N-1's output) — re-stepping a
    constant state would let XLA serve every step from one result."""
    ab_step = _load_tool("ab_step")

    received = []

    class FakeTrainer:
        def init_state(self, seed=0):
            return jnp.float32(0)

        def shard_batch(self, b):
            return b

        def _train_step(self, state, batch, rng):
            received.append(float(state))
            return state + 1, {"loss": jnp.float32(0)}

    best, med = ab_step.time_steps(
        FakeTrainer(), batch={}, warmup=1, windows=2, steps=3
    )
    assert best >= 0 and med >= 0
    assert received == list(map(float, range(len(received)))), (
        "time_steps must thread state through consecutive steps"
    )
