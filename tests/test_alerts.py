"""Declarative alert rules (ISSUE 19): rule parsing, the
firing/resolved state machine with flap suppression, the events file,
and the bit-for-bit SLO-burn parity gate against PR 11's SLOTracker.

File-only and clock-injected — no processes, no sleeps."""

import json
import os

import pytest

from sav_tpu.obs.alerts import (
    AlertEngine,
    AlertRule,
    alerts_path,
    default_rules,
    episodes,
    load_rules,
    read_alerts,
    slo_burn_rule,
)


# ------------------------------------------------------------ rule shape


def test_rule_evaluate_and_shapes():
    rule = AlertRule(
        "hot-queue",
        when=[("w.queue_depth", ">", 10), ("w.p99_ms", ">=", 50.0)],
    )
    assert rule.evaluate(
        {"w": {"queue_depth": 11, "p99_ms": 50.0}}
    ) is True
    # AND-composed: one false conjunct kills the condition.
    assert rule.evaluate(
        {"w": {"queue_depth": 11, "p99_ms": 49.0}}
    ) is False
    # Missing metric / non-numeric / bool -> False, never a throw (the
    # SLOTracker None-window semantics, generalized).
    assert rule.evaluate({}) is False
    assert rule.evaluate({"w": {"queue_depth": "11", "p99_ms": 60}}) is False
    assert rule.evaluate({"w": {"queue_depth": True, "p99_ms": 60}}) is False
    # Round-trips through the JSON shape, shorthand included.
    doc = rule.to_dict()
    again = AlertRule.from_dict(doc)
    assert again.to_dict() == doc
    with pytest.raises(ValueError):
        AlertRule("bad-op", when=[("x", "~", 1)])


def test_load_rules_sources(tmp_path):
    doc = {"rules": [
        {"name": "lat", "severity": "warn", "for_s": 2,
         "when": [{"metric": "w.p99_ms", "op": ">", "value": 40}]},
    ]}
    path = os.path.join(str(tmp_path), "rules.json")
    with open(path, "w") as f:
        json.dump(doc, f)
    for source in (path, json.dumps(doc), json.dumps(doc["rules"])):
        rules = load_rules(source)
        assert [r.name for r in rules] == ["lat"]
        assert rules[0].for_s == 2.0
    # Errors are loud — a silently-dropped page rule is the worst bug.
    with pytest.raises(ValueError):
        load_rules(os.path.join(str(tmp_path), "missing.json"))
    with pytest.raises(ValueError):
        load_rules('{"rules": [{"severity": "warn"}]}')  # nameless


# ---------------------------------------------------------- state machine


def test_firing_resolved_episode(tmp_path):
    d = str(tmp_path)
    rule = AlertRule(
        "lat", when=[("w.p99_ms", ">", 40)], for_s=2.0, resolve_s=3.0,
    )
    eng = AlertEngine([rule], log_dir=d, proc=0)
    hot = {"w": {"p99_ms": 80.0}}
    cool = {"w": {"p99_ms": 10.0}}
    # Pending during for_s: no event until the condition HELD 2s.
    assert eng.observe(hot, now=100.0) == []
    assert eng.observe(hot, now=101.0) == []
    events = eng.observe(hot, now=102.0)
    assert [e["event"] for e in events] == ["firing"]
    # Once-per-episode dedupe: still firing, no repeat event.
    assert eng.observe(hot, now=103.0) == []
    # Cooling during resolve_s; a flap back suppresses the resolve.
    assert eng.observe(cool, now=104.0) == []
    assert eng.observe(hot, now=105.0) == []   # flap: same episode
    assert eng.observe(cool, now=106.0) == []
    events = eng.observe(cool, now=109.5)      # held cool 3s
    assert [e["event"] for e in events] == ["resolved"]
    # On-disk events mirror the returned ones, with provenance.
    on_disk = read_alerts(d)
    assert [(e["event"], e["rule"], e["proc"]) for e in on_disk] == [
        ("firing", "lat", 0), ("resolved", "lat", 0),
    ]
    eps = episodes(on_disk)
    assert eps["lat"]["fired"] == 1 and eps["lat"]["resolved"] == 1
    assert eps["lat"]["active"] is False
    # A fresh excursion is a NEW episode.
    eng.observe(hot, now=200.0)
    events = eng.observe(hot, now=202.5)
    assert [e["episode"] for e in events] == [2]


def test_zero_holds_transition_within_one_observe():
    rule = AlertRule("insta", when=[("x", ">", 0)], for_s=0, resolve_s=0)
    eng = AlertEngine([rule])
    assert [e["event"] for e in eng.observe({"x": 1}, now=1.0)] == ["firing"]
    assert eng.active() == ["insta"]
    assert [e["event"] for e in eng.observe({"x": 0}, now=2.0)] == ["resolved"]
    assert eng.active() == []


def test_finalize_resolves_open_episodes(tmp_path):
    d = str(tmp_path)
    rule = AlertRule("lat", when=[("x", ">", 0)], for_s=0, resolve_s=60.0)
    eng = AlertEngine([rule], log_dir=d)
    eng.observe({"x": 1}, now=10.0)
    events = eng.finalize(now=11.0)
    assert [e["event"] for e in events] == ["resolved"]
    eps = episodes(read_alerts(d))
    assert eps["lat"] == {
        "fired": 1, "resolved": 1, "active": False,
        "severity": "warn", "last_t": 11.0,
    }
    # Idempotent: a second finalize emits nothing.
    assert eng.finalize(now=12.0) == []


def test_engine_state_and_torn_events(tmp_path):
    d = str(tmp_path)
    eng = AlertEngine(
        [AlertRule("a", when=[("x", ">", 0)]),
         AlertRule("b", when=[("y", ">", 0)], severity="page")],
        log_dir=d,
    )
    eng.observe({"x": 1, "y": 0}, now=1.0)
    state = eng.state()
    assert state["active"] == ["a"]
    assert state["episodes"] == {"a": 1}
    assert state["emitted"] == 1 and state["dropped"] == 0
    assert state["rules"] == 2
    # Torn tail + foreign lines are skipped by the reader.
    with open(alerts_path(d), "a") as f:
        f.write('{"kind": "other"}\n')
        f.write('{"kind": "alert", "event": "fir')
    assert [e["rule"] for e in read_alerts(d)] == ["a"]


# ------------------------------------------------------- SLO parity gate


def test_slo_burn_rule_bit_for_bit_parity_with_slotracker():
    """The ISSUE 19 acceptance gate: the declarative slo-burn rule,
    replayed over a beat stream, is firing EXACTLY when SLOTracker says
    ``burning`` — byte-identical decisions at every beat, including the
    None-window edges (missing burn -> not firing)."""
    from sav_tpu.serve.telemetry import SLOTracker

    tracker = SLOTracker(
        target=0.99, fast_window_s=60.0, slow_window_s=600.0,
        burn_threshold=2.0, clock=lambda: 0.0,
    )
    rule = slo_burn_rule(2.0)
    eng = AlertEngine([rule])
    # A replayed outcome stream: healthy -> heavy misses -> recovery.
    # (8% misses burns at 8x the budget: over threshold in both
    # windows once the slow window accumulates.)
    phases = (
        [(0, 50)] * 30           # healthy
        + [(4, 50)] * 120        # sustained 8% miss burn
        + [(0, 50)] * 700        # recovery (slow window drains)
    )
    decisions = []
    for i, (misses, n) in enumerate(phases):
        now = float(i)
        tracker.observe_outcomes(misses, n, now=now)
        slo = tracker.state(now=now)
        beat = {"slo": slo}           # exactly what serve_beat stamps
        eng.observe(beat, now=now)
        decisions.append((slo["burning"], "slo-burn" in eng.active()))
    mismatches = [i for i, (a, b) in enumerate(decisions) if a != b]
    assert mismatches == []
    # And the stream actually exercised both sides of the edge.
    assert any(a for a, _ in decisions)
    assert decisions[0][0] is False and decisions[-1][0] is False


def test_default_rules_are_the_slo_rule():
    rules = default_rules(3.0)
    assert [r.name for r in rules] == ["slo-burn"]
    assert rules[0].severity == "page"
    assert list(rules[0].when) == [
        ("slo.burn_fast", ">", 3.0), ("slo.burn_slow", ">", 3.0),
    ]
