"""Rotary position embeddings: op properties and model wiring."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sav_tpu.models import create_model
from sav_tpu.ops.rotary import (
    apply_rotary_pos_emb,
    fixed_positional_embedding,
    rotate_every_two,
)


def test_rotate_every_two():
    x = jnp.asarray([[1.0, 2.0, 3.0, 4.0]])
    np.testing.assert_allclose(
        np.asarray(rotate_every_two(x)), [[-2.0, 1.0, -4.0, 3.0]]
    )


def test_rope_preserves_norm():
    """Rotation is orthogonal: per-pair vector norms are unchanged."""
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 4, 32))
    sincos = fixed_positional_embedding(16, 32)
    y = apply_rotary_pos_emb(x, sincos)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(x), axis=-1),
        np.linalg.norm(np.asarray(y), axis=-1),
        rtol=1e-5,
    )


def test_rope_relative_property():
    """q·k after RoPE depends only on the position *offset*: shifting both
    positions by the same amount leaves the dot product unchanged."""
    d = 32
    q = jax.random.normal(jax.random.PRNGKey(0), (d,))
    k = jax.random.normal(jax.random.PRNGKey(1), (d,))
    L = 24

    def dot_at(pos_q, pos_k):
        sincos = fixed_positional_embedding(L, d)
        qs = jnp.zeros((1, L, d)).at[0, pos_q].set(q)
        ks = jnp.zeros((1, L, d)).at[0, pos_k].set(k)
        qr = apply_rotary_pos_emb(qs, sincos)[0, pos_q]
        kr = apply_rotary_pos_emb(ks, sincos)[0, pos_k]
        return float(jnp.dot(qr, kr))

    np.testing.assert_allclose(dot_at(3, 7), dot_at(10, 14), rtol=1e-5)
    np.testing.assert_allclose(dot_at(0, 5), dot_at(12, 17), rtol=1e-5)
    assert abs(dot_at(3, 7) - dot_at(3, 12)) > 1e-4  # different offsets differ


def test_rope_odd_dim_rejected():
    with pytest.raises(ValueError, match="even"):
        fixed_positional_embedding(8, 33)


@pytest.mark.parametrize("mode", ["learned", "sincos", "rotary", "none"])
def test_vit_pos_embed_modes(mode):
    model = create_model(
        "vit_s_patch16_rope", num_classes=10, num_layers=2, embed_dim=64,
        num_heads=4, pos_embed=mode,
    )
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 32, 3))
    variables = model.init({"params": jax.random.PRNGKey(1)}, x, is_training=False)
    logits = model.apply(variables, x, is_training=False)
    assert logits.shape == (2, 10)
    has_table = "AddAbsPosEmbed_0" in variables["params"]["Encoder_0"]
    assert has_table == (mode == "learned")


def test_vit_rope_is_position_sensitive():
    """With RoPE (and no other position source), permuting patches must
    change pre-head features — attention is no longer permutation-equivariant."""
    model = create_model(
        "vit_s_patch16_rope", num_classes=10, num_layers=2, embed_dim=64,
        num_heads=4,
    )
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 32, 32, 3))
    # Swap the top and bottom halves of the image (patch rows permute).
    x_perm = jnp.concatenate([x[:, 16:], x[:, :16]], axis=1)
    variables = model.init({"params": jax.random.PRNGKey(1)}, x, is_training=False)
    p = variables["params"]
    p["head"]["kernel"] = jax.random.normal(
        jax.random.PRNGKey(2), p["head"]["kernel"].shape
    ) * 0.05
    out = model.apply({"params": p}, x, is_training=False)
    out_perm = model.apply({"params": p}, x_perm, is_training=False)
    assert float(jnp.max(jnp.abs(out - out_perm))) > 1e-4
