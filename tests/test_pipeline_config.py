"""Config-reachable pipeline parallelism (VERDICT r4 item 6).

The library op (sav_tpu/parallel/pipelining.py) is numerics-tested in
test_pipeline_parallel.py; this file covers the *framework* path: the
PipelinedViT model, its sharding rule, and the real Trainer/TrainConfig
route a user reaches via ``train.py --pp S``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sav_tpu.models.pipelined import PipelinedViT, create_pipelined_model
from sav_tpu.parallel import create_mesh
from sav_tpu.train import TrainConfig, Trainer


def _tiny_pipelined(mesh, **overrides):
    kwargs = dict(
        num_classes=10,
        embed_dim=32,
        num_layers=4,
        num_heads=2,
        patch_shape=(8, 8),
        num_stages=4,
        num_microbatches=4,
        pipe_mesh=mesh,
        dtype=jnp.float32,
    )
    kwargs.update(overrides)
    return PipelinedViT(**kwargs)


def test_pipelined_vit_matches_sequential(devices):
    """The GPipe schedule must be execution-only: same params, same logits
    and gradients as running the stages as a plain loop."""
    mesh = create_mesh({"data": 2, "pipe": 4}, devices=devices)
    model = _tiny_pipelined(mesh)
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 32, 32, 3), jnp.float32)
    variables = model.init({"params": jax.random.PRNGKey(1)}, x, is_training=False)

    # Head is zero-init -> logits vacuously equal; randomize it first.
    params = variables["params"]
    params = jax.tree_util.tree_map_with_path(
        lambda path, p: (
            jax.random.normal(jax.random.PRNGKey(2), p.shape, p.dtype) * 0.02
            if any(getattr(k, "key", None) == "head" for k in path)
            else p
        ),
        params,
    )
    variables = {"params": params}

    seq_model = model.clone(sequential=True)
    out_pipe = model.apply(variables, x, is_training=False)
    out_seq = seq_model.apply(variables, x, is_training=False)
    np.testing.assert_allclose(
        np.asarray(out_pipe), np.asarray(out_seq), rtol=2e-5, atol=2e-5
    )

    def loss(m, v):
        return jnp.mean(m.apply(v, x, is_training=True) ** 2)

    g_pipe = jax.grad(lambda v: loss(model, v))(variables)["params"]
    g_seq = jax.grad(lambda v: loss(seq_model, v))(variables)["params"]
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5
        ),
        g_pipe,
        g_seq,
    )


def test_pipelined_params_carry_stage_axis(devices):
    mesh = create_mesh({"data": 2, "pipe": 4}, devices=devices)
    model = _tiny_pipelined(mesh)
    x = jnp.zeros((4, 32, 32, 3), jnp.float32)
    variables = model.init({"params": jax.random.PRNGKey(0)}, x, is_training=False)
    stages = variables["params"]["pipe_stages"]
    for leaf in jax.tree.leaves(stages):
        assert leaf.shape[0] == 4, leaf.shape


def test_pp_sharding_rule_places_stage_axis_on_pipe(devices):
    from jax.sharding import PartitionSpec as P

    from sav_tpu.parallel.sharding import param_shardings

    mesh = create_mesh({"data": 2, "pipe": 4}, devices=devices)
    params = {
        "pipe_stages": {"layer_0": {"kernel": jnp.zeros((4, 8, 8))}},
        "head": {"kernel": jnp.zeros((8, 10))},
    }
    sh = param_shardings(params, mesh)
    assert sh["pipe_stages"]["layer_0"]["kernel"].spec == P("pipe")
    assert sh["head"]["kernel"].spec == P()


@pytest.mark.slow
def test_trainer_pp_config_reachable(devices):
    """The full train.py route: TrainConfig(pipeline_parallel=4) -> Trainer
    builds the pipelined model, init_state shards stages over 'pipe', and
    train/eval steps execute with finite metrics and advancing params."""
    cfg = TrainConfig(
        model_name="vit_ti_patch16",
        num_classes=10,
        image_size=32,
        compute_dtype="float32",
        model_overrides=dict(num_layers=4, embed_dim=32, num_heads=2,
                             patch_shape=(8, 8)),
        global_batch_size=16,
        num_train_images=64,
        num_epochs=2,
        warmup_epochs=1,
        base_lr=1e-3,
        lr_scaling_divisor=16,
        transpose_images=False,
        mesh_axes={"data": 2, "pipe": 4},
        pipeline_parallel=4,
        pipeline_microbatches=4,
        seed=0,
    )
    trainer = Trainer(cfg)
    assert isinstance(trainer.model, PipelinedViT)
    state = trainer.init_state()
    # Stage params materialized sharded over 'pipe'.
    leaf = jax.tree.leaves(state.params["pipe_stages"])[0]
    assert "pipe" in str(leaf.sharding.spec)

    batch = {
        "images": jnp.asarray(
            np.random.RandomState(0).rand(16, 32, 32, 3), jnp.float32
        ),
        "labels": jnp.asarray(np.arange(16) % 10, jnp.int32),
    }
    rng = jax.random.PRNGKey(0)
    # Step 1 only moves the zero-init head (no gradient reaches the trunk
    # through a zero head kernel); the trunk moves from step 2 on.
    before = jax.device_get(
        state.params["pipe_stages"]["layer_0"]["SelfAttentionBlock_0"]
    )
    for _ in range(3):
        state, metrics = trainer.train_step(
            state, trainer.shard_batch(batch), rng
        )
    after = jax.device_get(
        state.params["pipe_stages"]["layer_0"]["SelfAttentionBlock_0"]
    )
    assert np.isfinite(float(metrics["loss"]))
    changed = jax.tree.map(
        lambda a, b: not np.allclose(a, b), before, after
    )
    assert any(jax.tree.leaves(changed)), "stage params did not update"
    ev = trainer.eval_step(state, trainer.shard_batch(batch))
    assert np.isfinite(float(jax.device_get(ev["loss_sum"])))


def test_pipelined_remat_matches_plain(devices):
    """--remat --pp composes: rematerialized stage blocks are numerics-
    identical (checkpointing trades memory for recompute only)."""
    mesh = create_mesh({"data": 2, "pipe": 4}, devices=devices)
    model = _tiny_pipelined(mesh)
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 32, 32, 3), jnp.float32)
    variables = model.init({"params": jax.random.PRNGKey(1)}, x, is_training=False)
    rm = model.clone(remat=True)

    def loss(m, v):
        return jnp.mean(m.apply(v, x, is_training=True) ** 2)

    g_plain = jax.grad(lambda v: loss(model, v))(variables)["params"]
    g_remat = jax.grad(lambda v: loss(rm, v))(variables)["params"]
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6
        ),
        g_plain,
        g_remat,
    )


def test_create_pipelined_rejects_non_vit(devices):
    mesh = create_mesh({"data": 2, "pipe": 4}, devices=devices)
    with pytest.raises(ValueError, match="ViT-family only"):
        create_pipelined_model("botnet_t3", num_stages=4, mesh=mesh)


def test_create_pipelined_rejects_moe(devices):
    mesh = create_mesh({"data": 2, "pipe": 4}, devices=devices)
    with pytest.raises(ValueError, match="MoE"):
        create_pipelined_model(
            "vit_moe_s_patch16_e8", num_stages=4, mesh=mesh
        )


def test_create_pipelined_rejects_stage_dropout(devices):
    mesh = create_mesh({"data": 2, "pipe": 4}, devices=devices)
    with pytest.raises(ValueError, match="dropout"):
        create_pipelined_model(
            "vit_ti_patch16", num_stages=4, mesh=mesh, dropout_rate=0.1
        )


def test_pipeline_rejects_indivisible_microbatches(devices):
    mesh = create_mesh({"data": 2, "pipe": 4}, devices=devices)
    model = _tiny_pipelined(mesh, num_microbatches=3)
    x = jnp.zeros((8, 32, 32, 3), jnp.float32)  # per-shard 4, M=3
    variables = model.init({"params": jax.random.PRNGKey(0)}, x, is_training=False)
    with pytest.raises(ValueError, match="num_microbatches"):
        model.apply(variables, x, is_training=False)
