"""Metric writers (sav_tpu/utils/writers.py): jsonl round-trip,
MultiWriter composition, and the lazy-degrade no-op sinks."""

import json

import pytest

from sav_tpu.utils.writers import (
    JsonlWriter,
    LoggingWriter,
    MultiWriter,
    TensorBoardWriter,
    WandbWriter,
)


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_jsonl_writer_round_trip(tmp_path):
    w = JsonlWriter(str(tmp_path))
    w.write(10, {"loss": 1.5, "top_1_acc": 0.25})
    w.write(20, {"loss": 1.2})
    w.close()
    records = _read_jsonl(w.path)
    assert records == [
        {"step": 10, "loss": 1.5, "top_1_acc": 0.25},
        {"step": 20, "loss": 1.2},
    ]


def test_jsonl_writer_appends_across_instances(tmp_path):
    w1 = JsonlWriter(str(tmp_path))
    w1.write(1, {"a": 1.0})
    w1.close()
    w2 = JsonlWriter(str(tmp_path))
    w2.write(2, {"a": 2.0})
    w2.close()
    assert [r["step"] for r in _read_jsonl(w2.path)] == [1, 2]


def test_jsonl_writer_positional_step_wins_over_metrics_step(tmp_path):
    w = JsonlWriter(str(tmp_path))
    w.write(5, {"step": 999, "loss": 1.0})
    w.close()
    (rec,) = _read_jsonl(w.path)
    assert rec["step"] == 5 and isinstance(rec["step"], int)


def test_jsonl_writer_passes_through_non_scalar_payloads(tmp_path):
    w = JsonlWriter(str(tmp_path))
    w.write(1, {"loss": 0.5, "goodput": {"buckets_s": {"step": 1.0}}})
    w.close()
    (rec,) = _read_jsonl(w.path)
    assert rec["goodput"]["buckets_s"]["step"] == 1.0


def test_jsonl_writer_close_idempotent(tmp_path):
    w = JsonlWriter(str(tmp_path))
    w.close()
    w.close()  # must not raise


def test_jsonl_writer_custom_filename(tmp_path):
    w = JsonlWriter(str(tmp_path), filename="eval.jsonl")
    assert w.path.endswith("eval.jsonl")
    w.close()


def test_logging_writer_formats_floats(tmp_path):
    lines = []
    w = LoggingWriter(log_fn=lines.append)
    w.write(3, {"loss": 0.123456789, "count": 7})
    w.close()
    assert lines == ["step 3: loss=0.123457, count=7"]


def test_multi_writer_fans_out(tmp_path):
    lines = []
    jw = JsonlWriter(str(tmp_path))
    mw = MultiWriter([jw, LoggingWriter(log_fn=lines.append)])
    mw.write(1, {"loss": 2.0})
    mw.close()
    assert len(_read_jsonl(jw.path)) == 1
    assert len(lines) == 1


def test_multi_writer_closes_all_despite_failures(tmp_path):
    class Exploding:
        def write(self, step, metrics):
            pass

        def close(self):
            raise RuntimeError("network down")

    jw = JsonlWriter(str(tmp_path))
    mw = MultiWriter([Exploding(), jw])
    with pytest.raises(RuntimeError, match="network down"):
        mw.close()
    # The failure above must not have skipped the jsonl close.
    assert jw._f.closed


def test_wandb_writer_degrades_without_wandb(monkeypatch):
    import builtins

    real_import = builtins.__import__

    def block_wandb(name, *args, **kwargs):
        if name == "wandb":
            raise ImportError("no wandb in this image")
        return real_import(name, *args, **kwargs)

    monkeypatch.setattr(builtins, "__import__", block_wandb)
    w = WandbWriter(project="test")
    assert not w.active
    w.write(1, {"loss": 1.0})  # no-op, must not raise
    w.close()


def test_tensorboard_writer_degrades_without_tf(monkeypatch):
    import builtins

    real_import = builtins.__import__

    def block_tf(name, *args, **kwargs):
        if name.startswith("sav_tpu.data._tf"):
            raise ImportError("no tf in this image")
        return real_import(name, *args, **kwargs)

    monkeypatch.setattr(builtins, "__import__", block_tf)
    w = TensorBoardWriter(str("unused_dir"))
    assert not w.active
    w.write(1, {"loss": 1.0})
    w.close()
